"""Multi-host elastic-membership fence: host loss, DCN partition, and
autoscale as RECOVERY EVENTS, not outages (CLI twin of
tests/test_multihost_mesh.py; the single-host lineage sibling is
scripts/dist_chaos_check.py).

Four phases over an emulated 2-host x 4-device topology — the driver
plus worker processes, each reconstructing a 4-device virtual-CPU mesh
slice — all on CPU:

  1. differential : join + group-by run across the 2-host mesh,
                    BIT-EXACT against a single-process oracle with the
                    SAME mesh shape (identical shard_map programs =>
                    identical float reduction order), the driver's
                    per-stage dispatch count within the single-host
                    budget, and every ICI-vs-DCN seam decision recorded
                    with its exact reason. -> MULTICHIP_r07.json
  2. host_kill    : ``killHostAtStage`` SIGKILLs the output-owning
                    worker at the final exchange's reduce entry — every
                    map output registered, the worst moment. The lineage
                    ladder (fetch failure -> invalidate -> respawn
                    {slot}~{gen} -> re-run lost maps -> re-read) must
                    resolve it bit-exact with nonzero
                    workers_respawned / maps_rerun / stage_retries.
  3. dcn_partition: ``partitionDcnAtRequest`` fails a burst of
                    cross-host round trips past the transport reconnect
                    budget — the partition escalates to a fetch failure
                    and resolves through the SAME stage-retry ladder,
                    bit-exact, with the partition counted once.
  4. scale_up     : an open-loop submission burst under
                    ``service.maxConcurrent=1`` builds queue pressure;
                    the autoscaler answers with ``add_host`` — the same
                    elastic-membership seam recovery drives — and every
                    queued query still returns the oracle answer.

Phases 2-4 are the DIST record -> DIST_r02.json.

    python scripts/multihost_chaos_check.py [--rows 3000] [--fast]
        [--output-multichip MULTICHIP_r07.json]
        [--output-dist DIST_r02.json]

Prints one JSON report; exit code 0 = fence holds.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# dispatch telemetry must wrap jax.jit BEFORE the compute modules
# import (module-level @jit decorators capture the binding) — phase 1
# fences the driver-side per-stage dispatch budget
from spark_rapids_tpu.utils import dispatch as disp  # noqa: E402

disp.install()

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

JOIN_Q = ("SELECT s.k AS k, count(*) AS n, sum(s.v) AS sv, "
          "sum(d.w) AS sw FROM sales s JOIN dim d ON s.k = d.id "
          "GROUP BY s.k ORDER BY s.k")
GROUPBY_Q = ("SELECT k, count(*) AS n, sum(v) AS sv, min(v) AS mn, "
             "max(v) AS mx FROM sales GROUP BY k ORDER BY k")

#: single-process mesh sessions share the plan shape with the cluster
#: driver; the cluster run may not exceed this many extra driver-side
#: round trips (stub reads replace in-process child execution)
MESH_CONF = {
    "rapids.tpu.mesh.enabled": True,
    "rapids.tpu.mesh.devices": 4,
    "rapids.tpu.sql.shuffle.partitions": 4,
    "rapids.tpu.sql.autoBroadcastJoinThreshold": 0,
}

CLUSTER_CONF = dict(MESH_CONF, **{
    "rapids.tpu.cluster.enabled": True,
    "rapids.tpu.cluster.workers": 2,
    "rapids.tpu.cluster.executors": 1,
    "rapids.tpu.cluster.retryBackoffMs": 10,
})

DCN_SEAM_REASON = ("exchange: dcn: cluster exchange: map outputs "
                   "cross the host boundary over TCP")


def _views(s, n: int, seed: int = 7) -> None:
    """Multi-partition inputs so every shuffle actually shuffles (a
    single-partition source would broadcast the join away)."""
    rng = np.random.default_rng(seed)
    s.create_temp_view("sales", s.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 50, n).astype(np.int64),
        "v": rng.normal(size=n)}))
        .repartition(3, "k"))
    s.create_temp_view("dim", s.create_dataframe(pd.DataFrame({
        "id": np.arange(50, dtype=np.int64),
        "w": rng.normal(size=50)}))
        .repartition(2, "id"))


def _oracle(query: str, n: int):
    """Single-process oracle with the SAME mesh shape as the cluster
    sessions — the bit-exactness contract needs identical shard_map
    programs on both sides."""
    from spark_rapids_tpu.api import Session

    s = Session(dict(MESH_CONF))
    _views(s, n)
    return s.sql(query).collect()


def _frames_equal(got, want) -> str:
    got = got.reset_index(drop=True)[list(want.columns)]
    if len(got) != len(want):
        return f"row count {len(got)} != {len(want)}"
    for c in want.columns:
        a, b = got[c].to_numpy(), want[c].to_numpy()
        try:
            np.testing.assert_array_equal(a, b)  # bit-exact, order too
        except AssertionError as e:
            return f"column {c}: {str(e)[:200]}"
    return ""


def check_differential(rows: int) -> dict:
    """Phase 1: 2-host x 4-device differential + dispatch budget +
    seam-decision telemetry (the MULTICHIP record)."""
    import jax

    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.parallel import mesh as pmesh
    from spark_rapids_tpu.parallel import spmd
    from spark_rapids_tpu.runtime.cluster import shutdown_session_cluster

    rec: dict = {"n_devices": len(jax.devices()), "queries": {}}
    topo = pmesh.HostTopology(n_hosts=3, devices_per_host=4)
    rec["topology"] = topo.axis_layout()
    ok = True
    for name, query in (("join", JOIN_Q), ("groupby", GROUPBY_Q)):
        # single-host budget: warm run compiles, second run measures
        single = Session(dict(MESH_CONF))
        _views(single, rows)
        single.sql(query).collect()
        pre = disp.snapshot()
        pre_stage = disp.stage_snapshot()
        want = single.sql(query).collect()
        single_d = disp.delta(pre)
        single_stage = disp.stage_delta(pre_stage)

        cluster = Session(dict(CLUSTER_CONF))
        _views(cluster, rows)
        cluster.sql(query).collect()
        pre = disp.snapshot()
        pre_stage = disp.stage_snapshot()
        pre_seam = spmd.seam_snapshot()
        got = cluster.sql(query).collect()
        cluster_d = disp.delta(pre)
        cluster_stage = disp.stage_delta(pre_stage)
        seams = spmd.seam_delta(pre_seam)
        shutdown_session_cluster()

        mismatch = _frames_equal(got, want)
        ici = {k: v for k, v in seams.items() if ": ici: " in k}
        q = {
            "rows": len(want),
            "matches_same_mesh_oracle": not mismatch,
            "detail": mismatch,
            "single_host_dispatches": single_d["dispatch_count"],
            "cluster_driver_dispatches": cluster_d["dispatch_count"],
            "single_host_per_stage": single_stage,
            "cluster_driver_per_stage": cluster_stage,
            "seam_decisions": seams,
            "ok": (not mismatch
                   # the driver sheds work to the workers; its own
                   # dispatch bill must stay within the single-host
                   # budget for the same plan shape
                   and cluster_d["dispatch_count"]
                   <= single_d["dispatch_count"]
                   and seams.get(DCN_SEAM_REASON, 0) >= 1
                   and len(ici) >= 1),
        }
        rec["queries"][name] = q
        ok = ok and q["ok"]
    rec["ok"] = ok
    return rec


def check_host_kill(rows: int) -> dict:
    """Phase 2: SIGKILL the output-owning host at the final reduce
    entry; the lineage ladder must win, bit-exact."""
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.runtime import recovery
    from spark_rapids_tpu.runtime.cluster import (session_cluster,
                                                  shutdown_session_cluster)
    from spark_rapids_tpu.shuffle import fault_injection as FI

    want = _oracle(JOIN_Q, rows)
    s = Session(dict(CLUSTER_CONF))
    _views(s, rows)
    runtime = session_cluster(s.conf)
    # stage boundaries of this plan: map(sid0), reduce(sid0),
    # map(sid1), reduce(sid1) — ordinal 4 is the final reduce entry,
    # when every map output is registered
    FI.arm_from_conf(RapidsConf({
        cfg.SHUFFLE_FI_ENABLED.key: True,
        cfg.SHUFFLE_FI_KILL_HOST_AT_STAGE.key: 4,
    }))
    pre = recovery.snapshot()
    t0 = time.monotonic()
    try:
        got = s.sql(JOIN_Q).collect()
    finally:
        inj = FI.get_injector().stats()  # before disarm resets counts
        FI.get_injector().disarm()
    took = time.monotonic() - t0
    d = recovery.delta(pre)
    mismatch = _frames_equal(got, want)
    respawned = [w.executor_id for w in runtime.workers
                 if "~" in w.executor_id]
    shutdown_session_cluster()
    rec = {
        "recovery": d,
        "host_kills": inj["host_kills"],
        "respawned_worker_ids": respawned,
        "matches_same_mesh_oracle": not mismatch,
        "detail": mismatch,
        "time_sec": round(took, 2),
    }
    rec["ok"] = (not mismatch and inj["host_kills"] == 1 and
                 d["fetch_failures"] >= 1 and d["maps_rerun"] >= 1 and
                 d["workers_respawned"] >= 1 and
                 d["stage_retries"] >= 1 and
                 len(respawned) == d["workers_respawned"])
    return rec


def check_dcn_partition(rows: int) -> dict:
    """Phase 3: a DCN partition outlasting the transport reconnect
    budget escalates to a fetch failure and resolves through the same
    stage-retry ladder."""
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.runtime import recovery
    from spark_rapids_tpu.runtime.cluster import shutdown_session_cluster
    from spark_rapids_tpu.shuffle import fault_injection as FI

    want = _oracle(JOIN_Q, rows)
    s = Session(dict(CLUSTER_CONF))
    _views(s, rows)
    # consecutive=5 outlasts the default 3-reconnect transport budget:
    # the partition is not absorbed, it escalates to the ladder
    FI.arm_from_conf(RapidsConf({
        cfg.SHUFFLE_FI_ENABLED.key: True,
        cfg.SHUFFLE_FI_PARTITION_DCN_AT.key: 3,
        cfg.SHUFFLE_FI_CONSECUTIVE.key: 5,
    }))
    pre = recovery.snapshot()
    t0 = time.monotonic()
    try:
        got = s.sql(JOIN_Q).collect()
    finally:
        inj = FI.get_injector().stats()
        FI.get_injector().disarm()
    took = time.monotonic() - t0
    d = recovery.delta(pre)
    mismatch = _frames_equal(got, want)
    shutdown_session_cluster()
    rec = {
        "recovery": d,
        "dcn_partitions": inj["dcn_partitions"],
        "dcn_drops": inj["dcn_drops"],
        "matches_same_mesh_oracle": not mismatch,
        "detail": mismatch,
        "time_sec": round(took, 2),
    }
    rec["ok"] = (not mismatch and inj["dcn_partitions"] == 1 and
                 inj["dcn_drops"] >= 2 and
                 d["dcn_partitions"] == 1)
    return rec


def check_scale_up(rows: int) -> dict:
    """Phase 4: queue pressure under an open-loop submission burst;
    the autoscaler grows the cluster through the SAME add_host seam
    recovery uses, and every queued query still matches the oracle."""
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.runtime import recovery
    from spark_rapids_tpu.runtime.cluster import (session_cluster,
                                                  shutdown_session_cluster)

    want = _oracle(JOIN_Q, rows)
    s = Session(dict(CLUSTER_CONF, **{
        "rapids.tpu.cluster.workers": 1,
        "rapids.tpu.cluster.autoscale.enabled": True,
        "rapids.tpu.cluster.autoscale.queueDepthHigh": 1,
        "rapids.tpu.cluster.autoscale.maxWorkers": 3,
        "rapids.tpu.cluster.autoscale.cooldownSec": 0.0,
        "rapids.tpu.service.maxConcurrent": 1,
    }))
    _views(s, rows)
    # materialize the cluster BEFORE the burst: the autoscaler extends
    # live membership, it never creates it
    runtime = session_cluster(s.conf)
    n_before = len(runtime.live_worker_slots())
    pre = recovery.snapshot()
    t0 = time.monotonic()
    handles = [s.sql(JOIN_Q).collect_async(tenant=f"t{i}")
               for i in range(4)]
    frames = [h.result(timeout=600.0) for h in handles]
    took = time.monotonic() - t0
    stats = s.service.stats().to_dict()
    d = recovery.delta(pre)
    n_after = len(runtime.live_worker_slots())
    mismatches = [m for m in (_frames_equal(f, want) for f in frames)
                  if m]
    shutdown_session_cluster()
    s.service.shutdown()
    rec = {
        "recovery": d,
        "workers_before": n_before,
        "workers_after": n_after,
        "scale_ups": stats["counters"].get("scale_ups", 0),
        "autoscaler": stats["autoscaler"],
        "queries": len(frames),
        "all_match_same_mesh_oracle": not mismatches,
        "detail": mismatches[:1],
        "time_sec": round(took, 2),
    }
    rec["ok"] = (not mismatches and
                 d["hosts_added"] >= 1 and
                 rec["scale_ups"] >= 1 and
                 n_after > n_before)
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rows", type=int, default=3000)
    p.add_argument("--fast", action="store_true",
                   help="smaller inputs for the deterministic CI fence")
    p.add_argument("--output-multichip", default=None,
                   help="write the differential record here "
                        "(MULTICHIP_r07.json)")
    p.add_argument("--output-dist", default=None,
                   help="write the chaos/elasticity record here "
                        "(DIST_r02.json)")
    args = p.parse_args(argv)
    rows = 1000 if args.fast else args.rows

    multichip = check_differential(rows)
    dist = {
        "host_kill": check_host_kill(rows),
        "dcn_partition": check_dcn_partition(rows),
        "scale_up": check_scale_up(rows),
    }
    dist["ok"] = all(r["ok"] for r in dist.values()
                     if isinstance(r, dict))
    report = {"differential": multichip, **{k: v for k, v in
                                            dist.items() if k != "ok"},
              "ok": multichip["ok"] and dist["ok"]}
    if args.output_multichip:
        with open(args.output_multichip, "w") as f:
            f.write(json.dumps(multichip, indent=2, default=str))
    if args.output_dist:
        with open(args.output_dist, "w") as f:
            f.write(json.dumps(dist, indent=2, default=str))
    print(json.dumps(report, indent=2, default=str))
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
