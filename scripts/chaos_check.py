"""Chaos regression fence for the OOM-resilience subsystem (CLI twin of
tests/test_chaos.py, which runs the same scenarios under the `chaos`
pytest marker in tier-1).

Runs a q5lite/q26-class query suite three ways and asserts oracle
parity plus the counters that prove the machinery actually fired:

  1. tiny-budget : device budget = working set / 4, host tier halved —
                   must complete through the disk spill chain
                   (spilled_device/host bytes > 0),
  2. injected    : deterministic RESOURCE_EXHAUSTED at the aggregate +
                   join sites, bursts long enough to force splits —
                   must complete with retries >= 2 and splits >= 1,
  3. seeded-sweep: probabilistic injection over every guarded site,
                   bounded by --sweep-injections.

    python scripts/chaos_check.py [--rows 150000] [--seed 11]
                                  [--sweep-probability 1.0]
                                  [--sweep-injections 2]

Prints one JSON report; exit code 0 = fence holds.
"""
import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402


def _data(rows: int, seed: int):
    rng = np.random.default_rng(seed)
    n_dim = 64
    fact = pd.DataFrame({
        "k": rng.integers(0, n_dim, rows).astype(np.int64),
        "v": rng.random(rows),
        "w": rng.integers(0, 1000, rows).astype(np.int64)})
    dim = pd.DataFrame({
        "k": np.arange(n_dim, dtype=np.int64),
        "cat": (np.arange(n_dim, dtype=np.int64) % 7)})
    return fact, dim


def _q26_class(s, fact, dim):
    from spark_rapids_tpu.api import col, functions as F

    return (s.create_dataframe(fact)
            .join(s.create_dataframe(dim), on="k")
            .filter(col("v") > 0.2)
            .group_by("cat")
            .agg(F.sum(col("v")).alias("sv"),
                 F.count("*").alias("n"))
            .order_by("cat"))


def _sort_q(s, fact, dim):
    from spark_rapids_tpu.api import col

    return (s.create_dataframe(fact)
            .join(s.create_dataframe(dim), on="k")
            .filter(col("v") > 0.2)
            .order_by("w", "k", "cat", "v"))


def _agg_oracle(fact, dim):
    j = fact.merge(dim, on="k")
    j = j[j["v"] > 0.2]
    return (j.groupby("cat").agg(sv=("v", "sum"), n=("v", "size"))
            .reset_index().sort_values("cat").reset_index(drop=True))


def _sort_oracle(fact, dim):
    j = fact.merge(dim, on="k")
    return (j[j["v"] > 0.2]
            .sort_values(["w", "k", "cat", "v"], kind="stable")
            .reset_index(drop=True))


def _frames_equal(got, want, float_cols=("sv",)) -> str:
    got = got.reset_index(drop=True)[list(want.columns)]
    if len(got) != len(want):
        return f"row count {len(got)} != {len(want)}"
    for c in want.columns:
        a, b = got[c].to_numpy(), want[c].to_numpy()
        try:
            if c in float_cols:
                np.testing.assert_allclose(a.astype(float),
                                           b.astype(float), rtol=1e-9)
            else:
                np.testing.assert_array_equal(a, b)
        except AssertionError as e:
            return f"column {c}: {str(e)[:200]}"
    return ""


def check_tiny_budget(rows: int, seed: int) -> dict:
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.plan.optimizer import estimate_footprint_bytes

    fact, dim = _data(rows, seed)
    probe = Session()
    footprint = estimate_footprint_bytes(
        _sort_q(probe, fact, dim)._plan)
    staged = int(rows * 0.8) * (8 + 8 + 8 + 8 + 4)
    budget = min(footprint // 4, staged // 2)
    spill_dir = tempfile.mkdtemp(prefix="chaos-spill-")
    s = Session({
        cfg.DEVICE_BUDGET.key: budget,
        cfg.HOST_SPILL_STORAGE_SIZE.key: max(budget // 2, 1 << 16),
        cfg.SPILL_DIR.key: spill_dir,
    }, initialize_runtime=True)
    try:
        got = _sort_q(s, fact, dim).collect()
        cat = s.runtime.catalog
        cat.flush_spills()
        mismatch = _frames_equal(got, _sort_oracle(fact, dim),
                                 float_cols=("v",))
        rec = {
            "footprint_bytes": footprint,
            "device_budget": budget,
            "over_budget_factor": round(footprint / budget, 2),
            "spilled_device_bytes": cat.spilled_device_bytes,
            "spilled_host_bytes": cat.spilled_host_bytes,
            "matches_cpu": not mismatch,
            "detail": mismatch,
        }
        rec["ok"] = (not mismatch and footprint >= 4 * budget and
                     cat.spilled_device_bytes > 0 and
                     cat.spilled_host_bytes > 0)
        return rec
    finally:
        s.stop()


def check_injected(rows: int, seed: int) -> dict:
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.memory import fault_injection as FI
    from spark_rapids_tpu.memory import retry as R

    fact, dim = _data(min(rows, 40_000), seed + 1)
    s = Session()
    FI.arm_from_conf(RapidsConf({
        cfg.FAULT_INJECTION_ENABLED.key: True,
        cfg.FAULT_INJECTION_AT_CALL.key: 1,
        cfg.FAULT_INJECTION_SITES.key: "aggregate.update,join.probe",
        cfg.FAULT_INJECTION_CONSECUTIVE.key: 3,
        cfg.FAULT_INJECTION_MAX.key: 6,
    }))
    try:
        pre = R.snapshot()
        got = _q26_class(s, fact, dim).collect()
        d = R.delta(pre)
        mismatch = _frames_equal(got, _agg_oracle(fact, dim))
        rec = {"retry": d,
               "injector": FI.get_injector().stats(),
               "matches_cpu": not mismatch, "detail": mismatch}
        rec["ok"] = (not mismatch and d["oom_retries"] >= 2 and
                     d["oom_splits"] >= 1 and d["gave_ups"] == 0 and
                     rec["injector"]["injections"] > 0)
        return rec
    finally:
        FI.get_injector().disarm()
        s.stop()


def check_sweep(rows: int, seed: int, probability: float,
                max_injections: int) -> dict:
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.memory import fault_injection as FI

    fact, dim = _data(min(rows, 40_000), seed + 2)
    s = Session()
    FI.get_injector().arm(probability=probability, seed=seed,
                          consecutive=1,
                          max_injections=max_injections)
    try:
        got = _q26_class(s, fact, dim).collect()
        mismatch = _frames_equal(got, _agg_oracle(fact, dim))
        rec = {"injector": FI.get_injector().stats(),
               "matches_cpu": not mismatch, "detail": mismatch}
        rec["ok"] = not mismatch
        return rec
    finally:
        FI.get_injector().disarm()
        s.stop()


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rows", type=int, default=150_000,
                   help="fact-table rows for the tiny-budget sort "
                        "fence (must exceed the 65536-row sort budget "
                        "floor to exercise the out-of-core path)")
    p.add_argument("--seed", type=int, default=11)
    p.add_argument("--sweep-probability", type=float, default=1.0)
    p.add_argument("--sweep-injections", type=int, default=2,
                   help="total injections in the probabilistic sweep; "
                        "keep below the spill-rung count to stay away "
                        "from give-up on no-split sites")
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)

    report = {
        "tiny_budget": check_tiny_budget(args.rows, args.seed),
        "injected": check_injected(args.rows, args.seed),
        "seeded_sweep": check_sweep(args.rows, args.seed,
                                    args.sweep_probability,
                                    args.sweep_injections),
    }
    report["ok"] = all(r["ok"] for r in report.values()
                       if isinstance(r, dict))
    text = json.dumps(report, indent=2, default=str)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
