"""Smoke-run the kernel surface on the real TPU chip (run WITHOUT the test
conftest so the default platform applies). Exercises the ops the CPU test
mesh can't validate for TPU-compile legality (f64 emulation, x64 rewrites).

Usage: python scripts/tpu_smoke.py
"""
import time

import numpy as np

import spark_rapids_tpu  # noqa: F401  (x64 on)
import jax

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.ops import concat, filter as filt, groupby, hashing, \
    join, partition, sort
from spark_rapids_tpu.ops.groupby import AggSpec
from spark_rapids_tpu.ops.sortkeys import SortKeySpec


def main():
    print("devices:", jax.devices())
    rng = np.random.default_rng(0)
    n = 100_000
    keys = rng.integers(0, 1000, n)
    vals = rng.normal(size=n)
    vv = rng.random(n) > 0.1
    batch = ColumnarBatch([
        Column.from_numpy(keys.astype(np.int64)),
        Column.from_numpy(vals, validity=vv),
        StringColumn.from_strings(
            [f"s{i % 257}" for i in range(n)]),
    ], n)
    types = [dt.INT64, dt.FLOAT64, dt.STRING]

    t0 = time.time()
    out = sort.sort_batch(batch, [SortKeySpec.spark_default(1, False)], types)
    out.columns[0].data.block_until_ready()
    print(f"sort f64 desc: {time.time()-t0:.2f}s")

    t0 = time.time()
    g, _ = groupby.groupby_aggregate(
        batch, [0], [AggSpec("sum", 1), AggSpec("count", 1),
                     AggSpec("min", 1), AggSpec("max", 1)], types)
    print(f"groupby: {time.time()-t0:.2f}s groups={g.realized_num_rows()}")

    t0 = time.time()
    h = hashing.hash_columns(batch, [0, 1, 2], types)
    h.block_until_ready()
    print(f"hash 3 cols (incl f64+str): {time.time()-t0:.2f}s")

    t0 = time.time()
    p, counts = partition.hash_partition(batch, [0], types, 16)
    print(f"hash_partition: {time.time()-t0:.2f}s counts_sum={counts.sum()}")

    t0 = time.time()
    keep = batch.columns[1].data > 0
    f = filt.compact_batch(batch, keep, batch.columns[1].validity)
    print(f"filter: {time.time()-t0:.2f}s rows={f.realized_num_rows()}")

    small = ColumnarBatch([
        Column.from_numpy(rng.integers(0, 1000, 500).astype(np.int64)),
        Column.from_numpy(rng.normal(size=500)),
    ], 500)
    t0 = time.time()
    j, _ = join.equi_join(batch.select([0, 1]), small, [0], [0],
                          [dt.INT64, dt.FLOAT64], [dt.INT64, dt.FLOAT64],
                          "inner")
    print(f"join: {time.time()-t0:.2f}s rows={j.realized_num_rows()}")

    t0 = time.time()
    c = concat.concat_batches([f.select([0, 1]), j.select([0, 1])])
    print(f"concat: {time.time()-t0:.2f}s rows={c.realized_num_rows()}")

    # fused filter-into-groupby (live_mask path)
    t0 = time.time()
    cols = [(batch.columns[0].data, None),
            (batch.columns[1].data, batch.columns[1].validity)]
    (kd, kv), (ad, av), ng = groupby._groupby(
        cols, (dt.INT64, dt.FLOAT64), (0,),
        (AggSpec("sum", 1), AggSpec("count_star")),
        batch.num_rows_device(),
        live_mask=(batch.columns[1].data > 0))
    print(f"groupby live_mask: {time.time()-t0:.2f}s groups={int(ng)}")

    # bitwise/shift kernels (64-bit emulation edges)
    from spark_rapids_tpu.expressions import bitwise as bw
    from spark_rapids_tpu.expressions.base import BoundReference
    from spark_rapids_tpu.expressions.compiler import CompiledProjection

    t0 = time.time()
    r0 = BoundReference(0, dt.INT64)
    proj = CompiledProjection([bw.BitwiseNot(r0),
                               bw.ShiftRightUnsigned(
                                   r0, BoundReference(0, dt.INT64))])
    bb = proj(batch.select([0]))
    bb.columns[0].data.block_until_ready()
    print(f"bitwise/ushr: {time.time()-t0:.2f}s")

    # window: running + range frames on device
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.expressions.aggregates import Sum as AggSum
    from spark_rapids_tpu.plan import nodes as pn
    from spark_rapids_tpu.plan.overrides import apply_overrides

    t0 = time.time()
    wn = 20_000
    wplan = pn.WindowNode(
        [0], [SortKeySpec.spark_default(1)],
        [pn.WindowCall(AggSum(BoundReference(2, dt.FLOAT64)), "rs",
                       frame=pn.WindowFrame(None, 0)),
         pn.WindowCall(AggSum(BoundReference(2, dt.FLOAT64)), "rng",
                       frame=pn.WindowFrame(-5, 5, kind="range"))],
        pn.ScanNode(pn.InMemorySource({
            "p": rng.integers(0, 50, wn).astype(np.int64),
            "o": rng.integers(0, 1000, wn).astype(np.int64),
            "v": rng.normal(size=wn)})))
    wdf = collect(apply_overrides(wplan))
    print(f"window run+range: {time.time()-t0:.2f}s rows={len(wdf)}")
    print("TPU SMOKE OK")


if __name__ == "__main__":
    main()
