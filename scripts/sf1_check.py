"""sf >= 1 scale fence: dispatch budgets + CPU-oracle match on full
TPC queries at real scale (CLI twin of the slow-marked smoke in
tests/test_dispatch_budget.py).

PR 13 moved the engine past the CPU oracle at sf 1 (q1/q6-class
queries) by collapsing stage0 into one program per batch chain, a
single-pass group-by and an attributed result sync. This fence keeps
that state: a future PR that re-adds a dispatch (a host sync, an
un-fused launch, a chunked aggregate) or breaks oracle equality at
scale fails here, not in production telemetry.

Per-query WARM dispatch ceilings (measured on the single-device CPU
backend, sf 1; multi-batch queries launch one fused chain per scan
batch, so the ceilings scale with the sf-1 batch count and carry a
little headroom for batching jitter — the fence catches per-batch or
per-query regressions, which add whole multiples):

    python scripts/sf1_check.py [--queries tpch_q1,tpch_q6]
                                [--sf 1.0] [--data-dir DIR]
                                [--output SF1.json]

Prints one JSON report; exit code 0 = fence holds.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# telemetry must wrap jax.jit before any compute module import
from spark_rapids_tpu.utils import dispatch as disp  # noqa: E402

disp.install()

# warm dispatch ceilings at sf 1 (measured + 2 headroom each; see
# module docstring). A query absent here gets BUDGET_DEFAULT.
BUDGETS = {
    "tpch_q1": 16,    # measured 14: 3 chains + 5 groupby + 2 sync +
                      # 2 concat + sort-tail + result_sync
    "tpch_q6": 14,    # measured 12: 3 chains + 5 reduce + 2 concat +
                      # final project + result_sync
    "tpch_q12": 20,   # measured 18 (join + grouped agg over 3 scan
                      # batches; orders side adds its own chains)
    "tpch_q14": 25,   # measured 23 (two scan legs + join + global agg)
    "tpcxbb_q26": 12,  # measured 10 (build-inlined chain + 3 groupby +
                       # stage3 filter + sort-tail + result_sync)
}
BUDGET_DEFAULT = 24


def run_query(benchmark: str, sf: float, data_dir: str) -> dict:
    from spark_rapids_tpu.benchmarks.runner import (ALL_BENCHMARKS,
                                                    BenchmarkRunner)
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.plan.overrides import apply_overrides

    r = BenchmarkRunner(data_dir, sf)
    r.ensure_data(benchmark)
    # warm run traces + compiles; the fence pins the steady state
    plan = ALL_BENCHMARKS[benchmark](data_dir)
    collect(apply_overrides(plan, r.conf))
    pre = disp.snapshot()
    pre_stage = disp.stage_snapshot()
    plan = ALL_BENCHMARKS[benchmark](data_dir)
    t0 = time.perf_counter()
    df = collect(apply_overrides(plan, r.conf))
    wall = time.perf_counter() - t0
    d = disp.delta(pre)
    per_stage = disp.stage_delta(pre_stage)
    cmp_ = r.compare_results(benchmark, df)
    budget = BUDGETS.get(benchmark, BUDGET_DEFAULT)
    rec = {
        "benchmark": benchmark,
        "sf": sf,
        "wall_s": round(wall, 3),
        "dispatch_count": d["dispatch_count"],
        "dispatch_budget": budget,
        "per_stage": per_stage,
        "matches_cpu": cmp_["matches_cpu"],
        "cpu_oracle_s": round(cmp_["cpu_time_sec"], 3),
        "vs_cpu_oracle": round(cmp_["cpu_time_sec"] / wall, 3)
        if wall else None,
        "detail": cmp_.get("detail", ""),
    }
    rec["ok"] = bool(
        cmp_["matches_cpu"] and
        d["dispatch_count"] <= budget and
        "<unstaged>" not in per_stage)
    return rec


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--queries", default="tpch_q1,tpch_q6")
    p.add_argument("--sf", type=float, default=1.0)
    p.add_argument("--data-dir", default="/tmp/srt_bench_tpch")
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)

    records = [run_query(q, args.sf, args.data_dir)
               for q in args.queries.split(",")]
    ok = all(r["ok"] for r in records)
    report = {"fence": "sf1_check", "sf": args.sf, "ok": ok,
              "queries": records}
    text = json.dumps(report, indent=1)
    print(text)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
