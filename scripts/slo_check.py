"""Sustained-QPS SLO fence for the cross-tenant serving layer (CLI twin
of the fast smoke in tests/test_service.py / tests/test_batching.py).

ROADMAP item 4 fence: at N=64 concurrent q1/q6 instances the p99
queue+run latency must stay within 3x the SERIAL single-query time.
The criterion is RATIO-based (p99 / measured serial reference), never
an absolute seconds threshold, so it is meaningful on CPU CI, a local
TPU, or behind the remote tunnel alike.

Two measurements, one warmed service (shape-bucketed executables +
micro-batching enabled):

  1. open-loop : Poisson arrivals at a rate CALIBRATED from the
                 measured serial time (``--load-factor`` x the
                 interleaving capacity), the regime an SLO is defined
                 over — asserts the p99 ratio criterion and reports
                 shed rate vs offered QPS.
  2. burst     : all N submitted at once (closed loop) — reported for
                 context (queue depth dominates), not asserted.

Also asserts the sharing fence the batching layer exists for: across
the whole run, same-template queries must hit the shared program cache
(cross-tenant hit rate) rather than re-compiling per tenant.

    python scripts/slo_check.py [--queries 64] [--sf 0.01]
                                [--ratio 3.0] [--load-factor 0.5]
                                [--output SLO.json]

Prints one JSON report; exit code 0 = fence holds.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--queries", type=int, default=64)
    p.add_argument("--mix", default="tpch_q1,tpch_q6")
    p.add_argument("--tenants", type=int, default=8)
    p.add_argument("--sf", type=float, default=0.01)
    p.add_argument("--data-dir", default="/tmp/rapids_tpu_slo")
    p.add_argument("--ratio", type=float, default=3.0,
                   help="p99 total latency must be <= ratio x serial "
                        "single-query time at the calibrated rate")
    p.add_argument("--load-factor", type=float, default=0.35,
                   help="offered_qps = load_factor / serial_s — the "
                        "sustained operating point the SLO is "
                        "evaluated at, as a fraction of the device's "
                        "single-stream throughput (1/serial). "
                        "maxConcurrent interleaves queries on ONE "
                        "dispatch path, it does not multiply "
                        "throughput; coalescing is what buys headroom "
                        "above 1.0")
    p.add_argument("--min-hit-rate", type=float, default=0.875,
                   help="cross-tenant progcache hit-rate floor "
                        "(>= 7/8: N same-template queries, <= 1 "
                        "compile per stage bucket)")
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)

    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.benchmarks.runner import (ALL_BENCHMARKS,
                                                    BenchmarkRunner)
    from spark_rapids_tpu.benchmarks.service_bench import (
        _serial_single_query_s, run_service_bench)
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.expressions.compiler import _FUSED_CACHE_STATS
    from spark_rapids_tpu.service import QueryService
    from spark_rapids_tpu.service.batching import slo

    mix = args.mix.split(",")
    conf = RapidsConf({
        cfg.SERVICE_BATCHING_ENABLED.key: True,
        # wider than the dispatch-coalescing default: the fence runs
        # many tiny queries, so a longer hold harvests bigger groups
        cfg.SERVICE_BATCHING_WINDOW_MS.key: 10.0,
        cfg.SERVICE_WARMUP_ENABLED.key: False,  # warmed explicitly
    })
    runner = BenchmarkRunner(args.data_dir, args.sf, conf=conf)
    for name in dict.fromkeys(mix):
        runner.ensure_data(name)
    serial = _serial_single_query_s(runner, mix, args.data_dir)
    serial_s = serial["max_s"]

    service = QueryService(conf)
    for name in dict.fromkeys(mix):
        service.register_template(ALL_BENCHMARKS[name](args.data_dir),
                                  name)
    warmup_report = service.warmup()

    # the sharing fence window opens AFTER warmup: every tenant query
    # from here on should reuse, not compile
    hits0 = dict(_FUSED_CACHE_STATS)

    offered_qps = max(args.load_factor / max(serial_s, 1e-4), 0.5)

    def make_query(i):
        return ALL_BENCHMARKS[mix[i % len(mix)]](args.data_dir)

    open_loop = slo.run_open_loop(service, make_query, offered_qps,
                                  args.queries, tenants=args.tenants,
                                  seed=args.seed)
    stats_open = service.stats()
    service.shutdown()

    hits1 = dict(_FUSED_CACHE_STATS)
    d_hits = hits1["hits"] - hits0["hits"]
    d_misses = hits1["misses"] - hits0["misses"]
    hit_rate = d_hits / (d_hits + d_misses) if d_hits + d_misses \
        else 1.0

    # burst context: fresh service, all N at once (not asserted — a
    # burst's tail latency is queue depth by construction)
    burst = run_service_bench(args.data_dir, args.sf,
                              queries=args.queries, mix=mix,
                              tenants=args.tenants, conf=conf,
                              warmup=False)

    p99 = open_loop["latency_s"]["total"]["p99"]
    p99_ratio = p99 / max(serial_s, 1e-9)
    checks = {
        "slo_p99_within_ratio": {
            "p99_total_s": p99,
            "serial_s": serial_s,
            "p99_over_serial": round(p99_ratio, 3),
            "threshold": args.ratio,
            "at_offered_qps": round(offered_qps, 3),
            "ok": bool(p99_ratio <= args.ratio and
                       open_loop["failed"] == 0),
        },
        "cross_tenant_sharing": {
            "hits": d_hits, "misses": d_misses,
            "hit_rate": round(hit_rate, 4),
            "threshold": args.min_hit_rate,
            "ok": bool(hit_rate >= args.min_hit_rate),
        },
        "open_loop_completed": {
            "done": open_loop["done"], "shed": open_loop["shed"],
            "failed": open_loop["failed"],
            "ok": bool(open_loop["done"] + open_loop["shed"] ==
                       args.queries and open_loop["failed"] == 0),
        },
    }
    report = {
        "benchmark": "slo_check",
        "scale_factor": args.sf,
        "queries": args.queries,
        "mix": mix,
        "serial": serial,
        "warmup": warmup_report,
        "open_loop": open_loop,
        "burst": {
            "wall_time_sec": burst["wall_time_sec"],
            "total_p99_s": burst["total_time_sec"]["p99"],
            "batching": burst["service_stats"]["batching"],
        },
        "batching": stats_open.to_dict()["batching"],
        "checks": checks,
        "ok": all(c["ok"] for c in checks.values()),
    }
    text = json.dumps(report, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)
    if not report["ok"]:
        print("SLO FENCE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
