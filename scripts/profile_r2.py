"""Round-2 on-chip profiling: where does the q5lite step spend its time?

Times (one process, warmup + async pipelined iterations):
  1. current entry() step (9 sort words)
  2. main variadic sort alone, current lane layout
  3. packed single-i32-key sort carrying f64 val (3 words) — narrow-key
     prototype: pad/validity/key packed into one int32 lane
  4. argsort(~boundary) compaction sort (2 lanes)
  5. cumsum i64 vs i32, segmented f64 associative_scan
  6. candidate fully-packed groupby step end to end
  7. dispatch overhead: per-iter device_get vs pipelined async
"""
from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import spark_rapids_tpu  # noqa: F401  (x64 on)
import jax
import jax.numpy as jnp

N = 4_000_000
N_KEYS = 65_536
WARMUP = 2
ITERS = 5


def timeit(name, fn, *args, iters=ITERS, pipelined=True):
    for _ in range(WARMUP):
        out = fn(*args)
        jax.block_until_ready(out)
        _force(out)
    t0 = time.perf_counter()
    if pipelined:
        outs = [fn(*args) for _ in range(iters)]
        _force(outs[-1])
    else:
        for _ in range(iters):
            out = fn(*args)
            _force(out)
    dt = (time.perf_counter() - t0) / iters
    print(f"{name:45s} {dt*1e3:9.2f} ms", flush=True)
    return dt


def _force(out):
    leaves = jax.tree_util.tree_leaves(out)
    jax.device_get(leaves[-1].ravel()[0])


def main():
    rng = np.random.default_rng(7)
    keys = rng.integers(0, N_KEYS, N).astype(np.int64)
    key_valid = rng.random(N) > 0.02
    vals = rng.random(N)

    from spark_rapids_tpu.ops.buckets import bucket_capacity
    cap = bucket_capacity(N)
    kd = jnp.asarray(np.concatenate([keys, np.zeros(cap - N, np.int64)]))
    kv = jnp.asarray(np.concatenate([key_valid, np.zeros(cap - N, bool)]))
    vd = jnp.asarray(np.concatenate([vals, np.zeros(cap - N)]))
    nr = jnp.int32(N)
    print(f"capacity={cap}", flush=True)

    # --- 1. current step
    from __graft_entry__ import entry
    step, _ = entry()
    jstep = jax.jit(step)
    timeit("1a. current step (pipelined)", jstep, kd, kv, vd, nr)
    timeit("1b. current step (sync per iter)", jstep, kd, kv, vd, nr,
           pipelined=False)

    # --- 2. main sort, current lanes: keys [i32 pad, i32 vrank, i64 key]
    #     payloads [i64 key, f64 val, bool valid]
    @jax.jit
    def cur_sort(kd, kv, vd, nr):
        iota = jnp.arange(cap, dtype=jnp.int32)
        keep = (vd > 0.5) & kv
        pad = jnp.maximum((iota >= nr).astype(jnp.int32),
                          (~keep).astype(jnp.int32))
        vrank = kv.astype(jnp.int32)
        kz = jnp.where(kv, kd, 0)
        out = jax.lax.sort((pad, vrank, kz, kd, vd, kv), num_keys=3,
                           is_stable=True)
        return out[3], out[4], out[5]
    timeit("2. current-layout sort alone", cur_sort, kd, kv, vd, nr)

    # --- 3. packed i32-key sort + f64 payload
    @jax.jit
    def packed_sort(kd, kv, vd, nr):
        iota = jnp.arange(cap, dtype=jnp.int32)
        keep = (vd > 0.5) & kv & (iota < nr)
        packed = jnp.where(keep, kd.astype(jnp.int32) + 1,
                           jnp.int32(0x7FFFFFFF))
        out = jax.lax.sort((packed, vd), num_keys=1, is_stable=True)
        return out
    timeit("3. packed i32-key sort (+f64 payload)", packed_sort,
           kd, kv, vd, nr)

    @jax.jit
    def packed_sort_f32pair(kd, kv, vd, nr):
        # payload as two f32 lanes instead of one f64 (is f64 payload
        # more than 2 words on v5e?)
        iota = jnp.arange(cap, dtype=jnp.int32)
        keep = (vd > 0.5) & kv & (iota < nr)
        packed = jnp.where(keep, kd.astype(jnp.int32) + 1,
                           jnp.int32(0x7FFFFFFF))
        hi = vd.astype(jnp.float32)
        lo = (vd - hi.astype(jnp.float64)).astype(jnp.float32)
        out = jax.lax.sort((packed, hi, lo), num_keys=1, is_stable=True)
        return out
    timeit("3b. packed i32-key sort (+2xf32 payload)", packed_sort_f32pair,
           kd, kv, vd, nr)

    @jax.jit
    def packed_sort_i32payload(kd, kv, vd, nr):
        # carry row-id instead of value
        iota = jnp.arange(cap, dtype=jnp.int32)
        keep = (vd > 0.5) & kv & (iota < nr)
        packed = jnp.where(keep, kd.astype(jnp.int32) + 1,
                           jnp.int32(0x7FFFFFFF))
        out = jax.lax.sort((packed, iota), num_keys=1, is_stable=True)
        return out
    timeit("3c. packed i32-key sort (+i32 rowid)", packed_sort_i32payload,
           kd, kv, vd, nr)

    @jax.jit
    def rowid_gather(kd, kv, vd, nr):
        packed, rowid = packed_sort_i32payload(kd, kv, vd, nr)
        return packed, jnp.take(vd, rowid)
    timeit("3d. packed sort + permutation gather f64", rowid_gather,
           kd, kv, vd, nr)

    # --- 4. compaction argsort
    bnd = np.zeros(cap, dtype=bool)
    bnd[np.sort(rng.choice(cap, N_KEYS, replace=False))] = True
    bndd = jnp.asarray(bnd)

    @jax.jit
    def compaction(b):
        return jnp.argsort(~b, stable=True).astype(jnp.int32)
    timeit("4. argsort(~boundary) compaction", compaction, bndd)

    # --- 5. scans
    xi64 = jnp.asarray(rng.integers(0, 2, cap).astype(np.int64))
    xf64 = vd

    @jax.jit
    def cs64(x):
        return jnp.cumsum(x)
    timeit("5a. cumsum i64", cs64, xi64)
    timeit("5b. cumsum i32", cs64, xi64.astype(jnp.int32))
    timeit("5c. cumsum f64", cs64, xf64)
    timeit("5d. cumsum f32", cs64, xf64.astype(jnp.float32))

    @jax.jit
    def segscan(x, b):
        def combine(a, c):
            av, af = a
            cv, cf = c
            return jnp.where(cf, cv, av + cv), af | cf
        v, _ = jax.lax.associative_scan(combine, (x, b))
        return v
    timeit("5e. segmented assoc-scan f64", segscan, xf64, bndd)

    # --- 6. candidate packed groupby end-to-end
    @jax.jit
    def packed_step(kd, kv, vd, nr):
        iota = jnp.arange(cap, dtype=jnp.int32)
        keep = (vd > 0.5) & kv & (iota < nr)
        nlive = jnp.sum(keep).astype(jnp.int32)
        packed = jnp.where(keep, kd.astype(jnp.int32) + 1,
                           jnp.int32(0x7FFFFFFF))
        sp, sv = jax.lax.sort((packed, vd), num_keys=1, is_stable=True)
        live_sorted = iota < nlive
        boundary = jnp.concatenate(
            [jnp.ones(1, bool), sp[1:] != sp[:-1]]) & live_sorted
        ng = jnp.sum(boundary).astype(jnp.int32)
        first_idx = jnp.argsort(~boundary, stable=True).astype(jnp.int32)
        glive = iota < ng
        next_first = jnp.where(iota < ng - 1, jnp.roll(first_idx, -1),
                               nlive)
        seg_sizes = jnp.where(glive, next_first - first_idx, 0)
        last_idx = first_idx + jnp.maximum(seg_sizes, 1) - 1
        key_out = (jnp.take(sp, first_idx) - 1).astype(jnp.int64)
        # f64 sum via cumsum-diff (bench data has no inf)
        cs = jnp.cumsum(jnp.where(live_sorted, sv, 0.0))
        hi = jnp.take(cs, last_idx)
        lo = jnp.where(first_idx > 0,
                       jnp.take(cs, jnp.maximum(first_idx - 1, 0)), 0.0)
        s = hi - lo
        cnt = seg_sizes.astype(jnp.int64)
        return key_out, s, cnt, cnt, ng
    timeit("6. candidate packed step e2e", packed_step, kd, kv, vd, nr)

    # --- 6b. packed step with segscan sum (inf-safe)
    @jax.jit
    def packed_step_segscan(kd, kv, vd, nr):
        iota = jnp.arange(cap, dtype=jnp.int32)
        keep = (vd > 0.5) & kv & (iota < nr)
        nlive = jnp.sum(keep).astype(jnp.int32)
        packed = jnp.where(keep, kd.astype(jnp.int32) + 1,
                           jnp.int32(0x7FFFFFFF))
        sp, sv = jax.lax.sort((packed, vd), num_keys=1, is_stable=True)
        live_sorted = iota < nlive
        boundary = jnp.concatenate(
            [jnp.ones(1, bool), sp[1:] != sp[:-1]]) & live_sorted
        ng = jnp.sum(boundary).astype(jnp.int32)
        first_idx = jnp.argsort(~boundary, stable=True).astype(jnp.int32)
        glive = iota < ng
        next_first = jnp.where(iota < ng - 1, jnp.roll(first_idx, -1),
                               nlive)
        seg_sizes = jnp.where(glive, next_first - first_idx, 0)
        last_idx = first_idx + jnp.maximum(seg_sizes, 1) - 1
        key_out = (jnp.take(sp, first_idx) - 1).astype(jnp.int64)

        def combine(a, c):
            av, af = a
            cv, cf = c
            return jnp.where(cf, cv, av + cv), af | cf
        scan, _ = jax.lax.associative_scan(
            combine, (jnp.where(live_sorted, sv, 0.0), boundary))
        s = jnp.take(scan, last_idx)
        cnt = seg_sizes.astype(jnp.int64)
        return key_out, s, cnt, cnt, ng
    timeit("6b. packed step segscan-sum e2e", packed_step_segscan,
           kd, kv, vd, nr)

    # correctness cross-check of candidate vs current
    ref = jstep(kd, kv, vd, nr)
    got = packed_step(kd, kv, vd, nr)
    ngr = int(jax.device_get(ref[4]))
    ngg = int(jax.device_get(got[4]))
    assert ngr == ngg, (ngr, ngg)
    rs = np.asarray(jax.device_get(ref[1]))[:ngr].sum()
    gs = np.asarray(jax.device_get(got[1]))[:ngg].sum()
    assert abs(rs - gs) / abs(rs) < 1e-12, (rs, gs)
    print("candidate matches current step", flush=True)


if __name__ == "__main__":
    main()
