"""Distributed chaos fence: lineage fault recovery under injected
transport and process faults (CLI twin of tests/test_fault_recovery.py;
the OOM sibling is scripts/chaos_check.py).

Two phases over the multi-process cluster runtime
(``rapids.tpu.cluster.*``), both on CPU:

  1. survive : a join+groupby+order-by across 3 worker processes runs
               with the deterministic injector armed — a worker is
               SIGKILLed before its Nth task (its earlier registered
               outputs then fail reduce-side), one transport connection
               drops (absorbed by the reconnect/backoff budget, costing
               NO stage), and one chunk frame comes back truncated
               (escalating to a fetch failure + stage retry). The query
               must finish BIT-EXACT against the single-process oracle
               with nonzero fetch_failures / maps_rerun /
               workers_respawned / stage_retries recovery counters.
  2. exhaust : every remote chunk truncated, placement pinned off the
               reader's executor, ``maxStageRetries=1`` — recovery
               cannot win, and the run must fail CLEANLY: the original
               ``ShuffleFetchFailedError`` surfaces chained ``from`` its
               short-chunk ``TransportError``, after exactly the
               budgeted number of stage retries.

    python scripts/dist_chaos_check.py [--rows 400] [--fast]
                                       [--output DIST_r01.json]

Prints one JSON report; exit code 0 = fence holds.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

import numpy as np  # noqa: E402
import pandas as pd  # noqa: E402

QUERY = ("SELECT d.name AS name, sum(s.v) AS total, count(*) AS n "
         "FROM sales s JOIN dim d ON s.k = d.id "
         "GROUP BY d.name ORDER BY name")


def _views(s, n: int, seed: int = 7) -> None:
    """Multi-partition inputs so every shuffle actually shuffles (a
    single-partition source would broadcast the join away)."""
    rng = np.random.default_rng(seed)
    s.create_temp_view("sales", s.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 20, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64)}))
        .repartition(3, "k"))
    s.create_temp_view("dim", s.create_dataframe(pd.DataFrame({
        "id": np.arange(20, dtype=np.int64),
        "name": np.array([f"g{i % 5}" for i in range(20)],
                         dtype=object)}))
        .repartition(2, "id"))


def _oracle(n: int):
    from spark_rapids_tpu.api import Session

    s = Session()
    _views(s, n)
    return s.sql(QUERY).collect()


def _frames_equal(got, want) -> str:
    got = got.reset_index(drop=True)[list(want.columns)]
    if len(got) != len(want):
        return f"row count {len(got)} != {len(want)}"
    for c in want.columns:
        a, b = got[c].to_numpy(), want[c].to_numpy()
        try:
            np.testing.assert_array_equal(a, b)  # bit-exact, order too
        except AssertionError as e:
            return f"column {c}: {str(e)[:200]}"
    return ""


def _worker_round_robin():
    """Placement hook pinning map tasks to worker PROCESSES round-robin
    (skipping the in-process executor), so killed-worker recovery is
    guaranteed to have remote outputs to lose."""
    state = {"i": 0}

    def hook(sid, mid, targets):
        ws = [t for t in targets if t.startswith("exec-worker")]
        if not ws:
            return None
        state["i"] += 1
        return ws[state["i"] % len(ws)]

    return hook


def check_survive(rows: int) -> dict:
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.runtime import recovery
    from spark_rapids_tpu.runtime.cluster import (session_cluster,
                                                  shutdown_session_cluster)
    from spark_rapids_tpu.shuffle import fault_injection as FI

    want = _oracle(rows)
    s = Session({
        cfg.CLUSTER_ENABLED.key: True,
        cfg.CLUSTER_EXECUTORS.key: 1,
        cfg.CLUSTER_WORKERS.key: 3,
        cfg.SHUFFLE_PARTITIONS.key: 4,
        cfg.AUTO_BROADCAST_THRESHOLD.key: 0,
        cfg.CLUSTER_RETRY_BACKOFF_MS.key: 10,
    })
    _views(s, rows)
    runtime = session_cluster(s.conf)
    runtime.placement_hook = _worker_round_robin()
    # the 4th worker submission SIGKILLs its target — by then that
    # worker has registered real map output; the 2nd round trip drops
    # (reconnect absorbs it); the 6th data chunk arrives truncated
    # (escalates to a fetch failure + stage retry)
    FI.arm_from_conf(RapidsConf({
        cfg.SHUFFLE_FI_ENABLED.key: True,
        cfg.SHUFFLE_FI_KILL_BEFORE_TASK.key: 4,
        cfg.SHUFFLE_FI_DROP_AT.key: 2,
        cfg.SHUFFLE_FI_TRUNCATE_AT.key: 6,
    }))
    pre = recovery.snapshot()
    t0 = time.monotonic()
    try:
        got = s.sql(QUERY).collect()
    finally:
        inj = FI.get_injector().stats()  # before disarm resets counts
        FI.get_injector().disarm()
        runtime.placement_hook = None
    took = time.monotonic() - t0
    d = recovery.delta(pre)
    mismatch = _frames_equal(got, want)
    respawned = [w.executor_id for w in runtime.workers if "~" in
                 w.executor_id]
    shutdown_session_cluster()
    rec = {
        "recovery": d,
        "injector": inj,
        "respawned_worker_ids": respawned,
        "matches_single_process_oracle": not mismatch,
        "detail": mismatch,
        "time_sec": round(took, 2),
    }
    rec["ok"] = (not mismatch and
                 inj["kills"] == 1 and inj["drops"] == 1 and
                 inj["truncations"] == 1 and
                 d["fetch_failures"] >= 1 and d["maps_rerun"] >= 1 and
                 d["workers_respawned"] >= 1 and
                 d["stage_retries"] >= 1 and
                 len(respawned) == d["workers_respawned"])
    return rec


def check_exhaust(rows: int) -> dict:
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.runtime import recovery
    from spark_rapids_tpu.runtime.cluster import (session_cluster,
                                                  shutdown_session_cluster)
    from spark_rapids_tpu.shuffle import fault_injection as FI
    from spark_rapids_tpu.shuffle.iterator import ShuffleFetchFailedError
    from spark_rapids_tpu.shuffle.transport import TransportError

    s = Session({
        cfg.CLUSTER_ENABLED.key: True,
        cfg.CLUSTER_EXECUTORS.key: 3,
        cfg.CLUSTER_WORKERS.key: 0,
        cfg.SHUFFLE_PARTITIONS.key: 4,
        cfg.AUTO_BROADCAST_THRESHOLD.key: 0,
        cfg.CLUSTER_MAX_STAGE_RETRIES.key: 1,
        cfg.CLUSTER_RETRY_BACKOFF_MS.key: 0,
    })
    _views(s, rows)
    runtime = session_cluster(s.conf)
    # pin every map OFF the reader's executor so each read stays remote
    # — with every chunk truncated, recovery can never win
    runtime.placement_hook = \
        lambda sid, mid, targets: next(
            (t for t in targets if t != "exec-0"), None)
    FI.get_injector().arm(truncate_at_request=1,
                          consecutive=1 << 30)
    pre = recovery.snapshot()
    err = None
    try:
        s.sql(QUERY).collect()
    except ShuffleFetchFailedError as e:
        err = e
    finally:
        FI.get_injector().disarm()
        runtime.placement_hook = None
    d = recovery.delta(pre)
    shutdown_session_cluster()
    rec = {
        "recovery": d,
        "raised": type(err).__name__ if err else None,
        "cause": type(err.__cause__).__name__
        if err and err.__cause__ else None,
        "message": str(err)[:200] if err else None,
    }
    rec["ok"] = (err is not None and
                 isinstance(err.__cause__, TransportError) and
                 "short chunk" in str(err.__cause__) and
                 d["stage_retries"] == 1 and  # exactly the budget
                 d["fetch_failures"] >= 2)    # original + failed retry
    return rec


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--rows", type=int, default=400)
    p.add_argument("--fast", action="store_true",
                   help="smaller inputs for the deterministic CI fence")
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)
    rows = 200 if args.fast else args.rows

    report = {
        "survive": check_survive(rows),
        "exhaust": check_exhaust(rows),
    }
    report["ok"] = all(r["ok"] for r in report.values()
                       if isinstance(r, dict))
    text = json.dumps(report, indent=2, default=str)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)
    return 0 if report["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
