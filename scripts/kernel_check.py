#!/usr/bin/env python
"""Native-kernel fence: every Pallas kernel must agree bit-for-bit with
the jnp/host implementation it replaces (any backend — CPU CI runs the
kernels through the Pallas interpreter), and on a real TPU at least one
op must clear the 2x speedup that justifies the layer.

    python scripts/kernel_check.py            # exit 0 = fence holds
    python scripts/kernel_check.py --rows N   # smaller/larger probe
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=500_000)
    ap.add_argument("--iterations", type=int, default=3)
    args = ap.parse_args(argv)

    from spark_rapids_tpu.benchmarks import kernel_bench

    rec = kernel_bench.run(args.rows, args.iterations)
    failures = []
    for name, op in rec["ops"].items():
        if not op["equal"]:
            failures.append(f"{name}: kernel != jnp oracle")
    if rec["backend"] == "tpu" and rec["max_ratio"] < 2.0:
        failures.append(
            f"tpu: no op reached 2x vs jnp (max {rec['max_ratio']}x)")
    rec["ok"] = not failures
    rec["failures"] = failures
    print(json.dumps(rec))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
