"""Fail when the SPMD mesh path stops landing its programs in progcache.

Sibling of ``check_bench_cache.py``, for the sharded whole-stage
programs: the in-program shuffle (``parallel/shuffle.py``) funnels every
exchange through ONE module-level jit entry (``_run_shuffle_step``), so
its executable must persist through ``utils/progcache`` exactly like the
single-device bench kernel does — otherwise every fresh worker process
eats the shard_map program's cold compile per plan shape, which is the
regression this fence makes loud. Unlike the bench fence it needs no
tracked seed and no TPU box: it is a live two-process proof under
``JAX_PLATFORMS=cpu`` with 8 virtual devices.

**Probe 1 (land).** A subprocess points progcache at a throwaway
directory, runs a real 8-device ``shuffle_step`` over a ``data_mesh``,
and the parent asserts a ``jit__run_shuffle_step-*-cache`` entry
appeared — the mesh-path program key landed in progcache.

**Probe 2 (hit).** A SECOND subprocess replays the same program against
the same directory with actual compilation FORBIDDEN (the
``jax._src.compiler`` backend-compile chokepoint monkeypatched to
raise, the same trick as the bench fence's --device mode). Success proves the
persistent entry is keyed reproducibly across processes — a cold worker
starts hot. The parent also asserts no NEW main-program entry was
written: a second key for the identical program would mean the cache key
picked up process-local state.

Both probes run the package's own staging path
(``distributed_batch_from_host``) and check row conservation through the
``all_to_all``, so a probe that "passes" on a broken exchange cannot
happen. The probe env is forced to ``JAX_PLATFORMS=cpu`` with
``--xla_force_host_platform_device_count=8`` by the parent, so the
script works from any shell, TPU-attached or not.
"""
from __future__ import annotations

import argparse
import os
import shutil
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# basename marker of the whole-stage exchange program's cache entries
MAIN_PROGRAM = "_run_shuffle_step"
N_DEV = 8
N_ROWS = 1000


def _probe_env() -> dict:
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={N_DEV}"
        ).strip()
    return env


def _main_entries(cache_dir: str) -> list:
    if not os.path.isdir(cache_dir):
        return []
    return sorted(e for e in os.listdir(cache_dir)
                  if MAIN_PROGRAM in e and e.endswith("-cache"))


def probe(cache_dir: str, forbid_compile: bool) -> int:
    """Child-process body: run one real in-program exchange with
    progcache installed at ``cache_dir``. With ``forbid_compile`` the
    executable MUST come from the persistent cache."""
    from spark_rapids_tpu.utils import progcache

    import jax

    if not progcache.install(cache_dir):
        print("probe: progcache.install() refused the directory",
              file=sys.stderr)
        return 2

    import numpy as np

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.parallel.mesh import data_mesh
    from spark_rapids_tpu.parallel.shuffle import (
        distributed_batch_from_host, shuffle_step)

    mesh = data_mesh(N_DEV)
    dtypes = [dt.INT64, dt.FLOAT64]
    step = shuffle_step(mesh, dtypes, [0], N_DEV)
    rng = np.random.default_rng(0)
    arrs = [rng.integers(0, 50, N_ROWS).astype(np.int64),
            rng.random(N_ROWS)]
    datas, valids, counts, _cap = distributed_batch_from_host(
        mesh, arrs, dtypes)

    if forbid_compile:
        from jax._src import compiler

        def _forbid(*a, **k):
            raise RuntimeError(
                "backend_compile reached: the persistent entry did not "
                "serve the mesh program")

        # the actual-XLA-compile chokepoint under compile_or_get_cached
        # (this jax predates backend_compile_and_load)
        name = ("backend_compile_and_load"
                if hasattr(compiler, "backend_compile_and_load")
                else "backend_compile")
        orig = getattr(compiler, name)
        setattr(compiler, name, _forbid)
        try:
            out = step(datas, valids, counts)
            jax.block_until_ready(out[3])
        finally:
            setattr(compiler, name, orig)
    else:
        out = step(datas, valids, counts)
        jax.block_until_ready(out[3])

    total = int(np.asarray(jax.device_get(out[3])).sum())
    if total != N_ROWS:
        print(f"probe: exchange lost rows ({total} != {N_ROWS})",
              file=sys.stderr)
        return 2
    # the parent reads the platform-suffixed directory from here rather
    # than re-deriving the suffix (one definition: progcache's)
    print(f"probe-ok dir={progcache.installed_dir()}")
    return 0


def _run_probe(base_dir: str, forbid: bool):
    cmd = [sys.executable, os.path.abspath(__file__),
           "--_probe", base_dir]
    if forbid:
        cmd.append("--_forbid-compile")
    r = subprocess.run(cmd, env=_probe_env(), cwd=REPO,
                       capture_output=True, text=True, timeout=600)
    installed = None
    for line in r.stdout.splitlines():
        if line.startswith("probe-ok dir="):
            installed = line.split("=", 1)[1]
    return r, installed


def check() -> int:
    tmp = tempfile.mkdtemp(prefix="mesh_progcache_fence_")
    base = os.path.join(tmp, "cache")
    try:
        cold, installed = _run_probe(base, forbid=False)
        if cold.returncode != 0 or not installed:
            print("FAIL: cold mesh probe did not complete:\n"
                  + cold.stdout + cold.stderr)
            return 1
        entries = _main_entries(installed)
        if not entries:
            print("FAIL: the mesh whole-stage program left NO "
                  f"{MAIN_PROGRAM} entry in progcache ({installed}) — "
                  "every fresh worker will eat the shard_map program's "
                  "cold compile. Did parallel/shuffle.py stop funneling "
                  "exchanges through the module-level jit entry, or did "
                  "progcache.install() stop covering sharded programs?")
            return 1
        warm, _ = _run_probe(base, forbid=True)
        if warm.returncode != 0:
            print("FAIL: warm replay had to COMPILE the mesh program — "
                  "its progcache key is not reproducible across "
                  "processes (process-local state leaked into the "
                  "cache key?):\n" + warm.stdout + warm.stderr)
            return 1
        after = _main_entries(installed)
        if after != entries:
            print("FAIL: the warm replay minted a new program key "
                  f"({entries} -> {after}) — the mesh program's cache "
                  "key is unstable across processes")
            return 1
        print("OK: mesh-path program key lands in progcache and "
              f"serves a fresh process ({entries[0]})")
        return 0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--_probe", metavar="DIR", default=None,
                   help=argparse.SUPPRESS)
    p.add_argument("--_forbid-compile", action="store_true",
                   dest="_forbid_compile", help=argparse.SUPPRESS)
    args = p.parse_args()
    if args._probe:
        return probe(args._probe, args._forbid_compile)
    return check()


if __name__ == "__main__":
    sys.exit(main())
