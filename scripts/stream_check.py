"""Streaming fence: folds must cost O(batch), never O(total), and the
incremental answer must be bit-exact against the batch engine (CLI twin
of tests/test_streaming.py).

The claim the incremental engine makes is measured directly: a standing
aggregation folds >= 10 identical-size micro-batches while the table's
cumulative row count grows >= 10x, and the fence requires:

  1. **bit_exact**  : after every appended batch — including an
                      out-of-order LATE batch re-merged under the
                      watermark — the standing query's emitted frame
                      equals the batch engine run over the concatenated
                      input, bit for bit (the aggregates are integer
                      SUM/COUNT, which merge associatively: no float
                      reorder tolerance needed, none granted)
  2. **flat_folds** : per-fold wall clock stays flat as the table grows
                      (max measured fold <= 3x the median — a fold that
                      rescanned history would grow ~linearly and blow
                      far past that)
  3. **flat_dispatch**: per-fold device dispatch count is EXACTLY flat
                      after warmup — fixed key domain + fixed batch
                      size means identical compiled programs per fold,
                      so any extra launch means the fold did work
                      proportional to something other than the batch
  4. **late_data**  : the late batch actually exercised the late path
                      (late_rows_remerged > 0) and the final frame
                      still matches the oracle

    python scripts/stream_check.py [--batches 12] [--rows 20000]
                                   [--keys 64] [--output STREAM_r01.json]

Prints one JSON report; exit code 0 = fence holds.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: folds excluded from the flatness stats: fold 0 has no running-state
#: merge (3 launches, not 6) and folds 1-2 eat the update/merge
#: compiles for the steady-state shapes; fold 3 onward is steady state
WARMUP_FOLDS = 3


def _batch(rng, n, keys, t0):
    import numpy as np

    return {"k": rng.integers(0, keys, n).astype(np.int64),
            "v": rng.integers(0, 1000, n).astype(np.int64),
            "ev": (t0 + rng.integers(0, 1000, n)).astype(np.int64)}


def _canon(frame):
    return frame.sort_values("k").reset_index(drop=True)


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--batches", type=int, default=12,
                        help="micro-batches to fold (>= 10)")
    parser.add_argument("--rows", type=int, default=20_000,
                        help="rows per micro-batch")
    parser.add_argument("--keys", type=int, default=64,
                        help="group-by key domain")
    parser.add_argument("--max-wall-ratio", type=float, default=3.0,
                        help="max fold wall / median fold wall bound")
    parser.add_argument("--output", default="STREAM_r01.json")
    args = parser.parse_args(argv)

    from spark_rapids_tpu.utils import dispatch as disp

    disp.install()   # per-fold dispatch deltas need the interceptor

    import numpy as np
    import pandas as pd

    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import Schema

    rng = np.random.default_rng(42)
    s = Session()
    s.create_streaming_table(
        "events", Schema(["k", "v", "ev"],
                         [dt.INT64, dt.INT64, dt.INT64]))
    df = s.sql("SELECT k, SUM(v) AS sv, COUNT(v) AS c "
               "FROM events GROUP BY k")
    sq = s.service.register_standing(
        df, name="stream_check", event_time_col="ev",
        watermark_ms=500, late_policy="merge")

    folds = []
    frames = []
    mismatches = []
    total_batches = max(args.batches, 10)
    late_at = total_batches - 2   # one late batch, inside the run
    for i in range(total_batches):
        # the late batch reuses an old time range (below the
        # watermark); every other batch advances event time
        t0 = 0 if i == late_at else (i + 1) * 100_000
        b = _batch(rng, args.rows, args.keys, t0)
        frames.append(pd.DataFrame(b))
        s.append_batch("events", b)
        if sq.state != "EMITTING":
            print(f"fold {i} left state {sq.state}: {sq.error}",
                  file=sys.stderr)
            return 1
        # oracle at EVERY emit point: batch engine over the full table
        got = _canon(sq.results())
        want = _canon(
            pd.concat(frames, ignore_index=True).groupby("k").agg(
                sv=("v", "sum"), c=("v", "count")).reset_index())
        if not got.equals(want):
            mismatches.append(f"fold {i}: streamed frame != batch "
                              f"oracle")
        engine = _canon(df.to_pandas())
        if not got.equals(engine):
            mismatches.append(f"fold {i}: streamed frame != batch "
                              f"ENGINE frame")
        folds.append({
            "fold": i,
            "cumulative_rows": int(sq.rows_folded),
            "wall_s": round(sq.last_fold_wall_s, 6),
            "dispatches": sq.last_fold_dispatches,
            "late": i == late_at,
        })

    measured = folds[WARMUP_FOLDS:]
    walls = sorted(f["wall_s"] for f in measured)
    median_wall = walls[len(walls) // 2]
    max_wall = walls[-1]
    dispatch_counts = {f["dispatches"] for f in measured}
    # table growth across the run: fold 0 cost O(1 batch); the last
    # fold runs against a table >= 10x larger — same cost required
    growth = folds[-1]["cumulative_rows"] / folds[0]["cumulative_rows"]

    checks = {
        "bit_exact": {
            "emit_points_checked": total_batches,
            "mismatches": mismatches,
            "ok": bool(not mismatches),
        },
        "flat_folds": {
            "median_wall_s": round(median_wall, 6),
            "max_wall_s": round(max_wall, 6),
            "ratio": round(max_wall / max(median_wall, 1e-9), 3),
            "threshold": args.max_wall_ratio,
            "rows_growth": round(growth, 2),
            "ok": bool(max_wall <= args.max_wall_ratio *
                       max(median_wall, 1e-9) and growth >= 10.0),
        },
        "flat_dispatch": {
            "per_fold_dispatch_counts": sorted(dispatch_counts),
            "ok": bool(len(dispatch_counts) == 1),
        },
        "late_data": {
            "late_rows_remerged": int(sq.late_rows_remerged),
            "watermark": sq.watermark,
            "watermark_lag_ms": sq.watermark_lag_ms,
            "ok": bool(sq.late_rows_remerged > 0 and not mismatches),
        },
    }
    streaming_stats = s.service.stats().streaming
    streaming_stats.pop("standing", None)
    report = {
        "benchmark": "stream_check",
        "batches": total_batches,
        "rows_per_batch": args.rows,
        "keys": args.keys,
        "total_rows": folds[-1]["cumulative_rows"],
        "folds": folds,
        "streaming_stats": streaming_stats,
        "checks": checks,
        "ok": all(c["ok"] for c in checks.values()),
    }
    s.stop()
    text = json.dumps(report, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)
    if not report["ok"]:
        print("STREAM FENCE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
