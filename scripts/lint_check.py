"""tpulint CI gate: zero NEW findings relative to the checked-in state.

The analyzer (``spark_rapids_tpu/analysis/``) runs four passes —
host-sync (TPU1xx), recompile hazards (TPU2xx), lock order (TPU3xx),
robustness/config (TPU4xx) — over the package, filters through the
per-site allowlist (``analysis/allowlist.txt``, every entry carries a
mandatory written justification), and compares the survivors against
``scripts/lint_baseline.json``. The baseline is EMPTY and is meant to
stay empty: a new finding means fix the site or add a justified
allowlist entry in the same PR, never "append to the baseline".

Exit status:

- 0 — no findings beyond allowlist+baseline, no stale allowlist
  entries, no parse errors.
- 1 — new findings (each rendered with code, site, and message), or
  stale allowlist entries (a justification whose site was fixed must
  be deleted so the exemption can't silently migrate).
- 2 — allowlist parse error (missing justification, unknown code).

Modes:

    python scripts/lint_check.py                  # the gate
    python scripts/lint_check.py --json out.json  # + machine-readable dump
    python scripts/lint_check.py --write-baseline # refresh baseline file
    python scripts/lint_check.py --root DIR       # scan a seeded tree
    python scripts/lint_check.py --sync-map       # q26 plan-level sync map

``--sync-map`` builds the q26 physical plan and prints every
device->host synchronization point the stage-cut plan implies, one per
line as ``<stage>  <exec>  <kind>`` — the plan-level complement to the
per-site AST passes (acceptance: exactly a duplicate-flag fetch and the
result fetch). Runs the planner only; no data is executed.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

BASELINE_PATH = os.path.join(REPO, "scripts", "lint_baseline.json")


def _load_baseline(path):
    if not os.path.exists(path):
        return set()
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {(e["code"], e["path"], e["qualname"]) for e in data["findings"]}


def _sync_map(data_dir: str) -> int:
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    from spark_rapids_tpu.analysis import plan_sync
    from spark_rapids_tpu.benchmarks.runner import (ALL_BENCHMARKS,
                                                    BenchmarkRunner)
    from spark_rapids_tpu.plan.overrides import apply_overrides

    r = BenchmarkRunner(data_dir, 0.1)
    r.ensure_data("tpcxbb_q26")
    root = apply_overrides(ALL_BENCHMARKS["tpcxbb_q26"](data_dir),
                           r.conf)
    print(plan_sync.render(plan_sync.sync_map(root)))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", default=None,
                    help="tree to scan (default: this repo)")
    ap.add_argument("--json", dest="json_path", default=None,
                    help="write all raw findings + verdicts as JSON")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite scripts/lint_baseline.json from the "
                         "current post-allowlist findings")
    ap.add_argument("--allowlist", default=None,
                    help="allowlist path (default: analysis/allowlist.txt)")
    ap.add_argument("--sync-map", action="store_true",
                    help="print the q26 plan-level sync map and exit")
    ap.add_argument("--data-dir", default="/tmp/srt_dispatch_fence",
                    help="--sync-map dataset dir (reuses the dispatch-"
                         "fence tables; generated if missing)")
    args = ap.parse_args(argv)

    if args.sync_map:
        return _sync_map(args.data_dir)

    from spark_rapids_tpu import analysis
    from spark_rapids_tpu.analysis.allowlist import (Allowlist,
                                                     AllowlistError)

    try:
        allowlist = (Allowlist.load(args.allowlist) if args.allowlist
                     else Allowlist.load())
    except AllowlistError as e:
        print(f"lint_check: allowlist error: {e}", file=sys.stderr)
        return 2

    raw = analysis.run_all(args.root)
    survivors = allowlist.filter(raw)
    stale = allowlist.unused_entries(raw) if args.root is None else []

    if args.json_path:
        with open(args.json_path, "w", encoding="utf-8") as f:
            json.dump({
                "total": len(raw),
                "allowlisted": len(raw) - len(survivors),
                "new": [fi.to_json() for fi in survivors],
                "stale_allowlist_entries": [
                    {"code": c, "scope": s, "justification": j}
                    for c, s, j in stale],
                "findings": [fi.to_json() for fi in raw],
            }, f, indent=2)
            f.write("\n")

    if args.write_baseline:
        with open(BASELINE_PATH, "w", encoding="utf-8") as f:
            json.dump({"findings": [fi.to_json() for fi in survivors]},
                      f, indent=2)
            f.write("\n")
        print(f"lint_check: baseline written "
              f"({len(survivors)} entries) to {BASELINE_PATH}")
        return 0

    baseline = _load_baseline(BASELINE_PATH)
    new = [fi for fi in survivors
           if (fi.code, fi.path, fi.qualname) not in baseline]

    ok = True
    if new:
        ok = False
        print(f"lint_check: {len(new)} new finding(s) "
              f"(fix the site or add a justified allowlist entry):")
        for fi in new:
            print(f"  {fi.render()}")
    if stale:
        ok = False
        print(f"lint_check: {len(stale)} stale allowlist entr"
              f"{'y' if len(stale) == 1 else 'ies'} "
              f"(site fixed — delete the exemption):")
        for code, scope, _ in stale:
            print(f"  {code} {scope}")
    if ok:
        print(f"lint_check: OK — {len(raw)} finding(s), all "
              f"allowlisted with justifications, 0 new vs baseline")
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
