"""Streaming durability fence: kill -9 mid-fold must restart bit-exact
(CLI twin of tests/test_stream_durability.py, with a REAL process
death).

The parent orchestrates child processes of this same script against
one shared checkpoint dir:

  scenario "crash_recover":
    1. an ingest child arms ``crashAtFold=N`` and streams micro-batches
       into a durable standing query; the injector SIGKILLs the child
       at the Nth fold start — after that delta's WAL append is
       durable, before its fold lands in any checkpoint. The parent
       requires the child actually died by SIGKILL.
    2. a recover child starts fresh against the same dir: table
       re-creation replays the WAL, query re-registration restores the
       newest checkpoint, the catch-up drain folds exactly the WAL
       suffix past its cursor, and ingest continues to the full run.
  scenario "torn_fallback": same, but every checkpoint commit is torn
    (``tornCheckpointAt=1`` with a huge ``consecutive``) — recovery
    must reject them all on CRC and refold ENTIRELY from the WAL.

Fence requirements (both scenarios):

  1. **killed**      : the ingest child exited on SIGKILL (rc -9),
                       not a clean error
  2. **bit_exact**   : after recovery, at EVERY emit point, the
                       standing query's frame equals the pandas oracle
                       AND the batch engine over the replayed table
                       (integer SUM/COUNT — bit for bit, no tolerance)
  3. **exactly_once**: total folds across both processes == total
                       micro-batches (nothing double-folded, nothing
                       dropped), rows_folded == rows appended
  4. **flat_dispatch**: per-fold device dispatch count is flat after
                       post-restart warmup — recovery must not leave
                       folds doing work proportional to history
  5. **counters**    : wal_replays >= 1; recoveries >= 1 for
                       crash_recover; torn_rejected >= 1 with
                       recoveries == 0 for torn_fallback

    python scripts/stream_durability_check.py [--batches 12]
        [--rows 4000] [--keys 32] [--crash-at 6]
        [--output STREAM_r02.json]

Prints one JSON report; exit code 0 = fence holds.
"""
import argparse
import json
import os
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

QUERY_NAME = "durable_q"
AGG_SQL = ("SELECT k, SUM(v) AS sv, COUNT(v) AS c "
           "FROM events GROUP BY k")
#: post-restart folds excluded from dispatch flatness: the restored
#: process re-pays the update/merge compiles for the steady shapes
WARMUP_FOLDS = 3


def _batch(index, rows, keys):
    """Deterministic per-INDEX batch: both child processes and the
    oracle regenerate identical data from the index alone."""
    import numpy as np

    rng = np.random.default_rng(1000 + index)
    return {"k": rng.integers(0, keys, rows).astype(np.int64),
            "v": rng.integers(0, 1000, rows).astype(np.int64)}


def _canon(frame):
    return frame.sort_values("k").reset_index(drop=True)


def _session(ckpt_dir):
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import Schema

    s = Session({cfg.STREAMING_CHECKPOINT_DIR.key: ckpt_dir})
    s.create_streaming_table("events",
                             Schema(["k", "v"], [dt.INT64, dt.INT64]))
    return s


def phase_ingest(args):
    """Child 1: stream until the armed injector SIGKILLs us mid-fold.
    Reaching the end of the loop alive means the fault never fired —
    that is a fence FAILURE, reported via a clean nonzero exit."""
    from spark_rapids_tpu.shuffle.fault_injection import get_injector

    s = _session(args.dir)
    sq = s.service.register_standing(s.sql(AGG_SQL), name=QUERY_NAME)
    get_injector().arm(
        crash_at_fold=args.crash_at,
        torn_checkpoint_at=1 if args.torn else 0,
        consecutive=10 ** 6 if args.torn else 1)
    for i in range(args.batches):
        s.append_batch("events", _batch(i, args.rows, args.keys))
        if sq.terminal:
            print(f"ingest: query died at fold {i}: {sq.error}",
                  file=sys.stderr)
            return 1
    print("ingest: survived the full run — crash injection never "
          "fired", file=sys.stderr)
    return 1


def phase_recover(args):
    """Child 2: fresh process, same checkpoint dir — recover, finish
    the run, verify at every emit, report the facts as JSON for the
    parent to judge."""
    from spark_rapids_tpu.utils import dispatch as disp

    disp.install()   # per-fold dispatch deltas need the interceptor

    import pandas as pd

    from spark_rapids_tpu.service.streaming import stats as sstats

    pre = sstats.snapshot()
    s = _session(args.dir)
    replayed = s.streaming_table("events").num_appends
    df = s.sql(AGG_SQL)
    sq = s.service.register_standing(df, name=QUERY_NAME)
    restored_folds = sq.folds
    # catch-up already drained the WAL suffix past the checkpoint
    # cursor inside register_standing; continue the interrupted run
    folds = []
    mismatches = []
    frames = [pd.DataFrame(_batch(i, args.rows, args.keys))
              for i in range(replayed)]

    def _verify(tag):
        got = _canon(sq.results())
        want = _canon(pd.concat(frames, ignore_index=True)
                      .groupby("k").agg(sv=("v", "sum"),
                                        c=("v", "count")).reset_index())
        if not got.equals(want):
            mismatches.append(f"{tag}: streamed frame != pandas oracle")
        engine = _canon(df.to_pandas())
        if not got.equals(engine):
            mismatches.append(f"{tag}: streamed frame != batch ENGINE")

    _verify("post-recovery")
    for i in range(replayed, args.batches):
        b = _batch(i, args.rows, args.keys)
        frames.append(pd.DataFrame(b))
        s.append_batch("events", b)
        if sq.state != "EMITTING":
            mismatches.append(f"fold of batch {i} left state "
                              f"{sq.state}: {sq.error}")
            break
        folds.append({"batch": i,
                      "dispatches": sq.last_fold_dispatches,
                      "wall_s": round(sq.last_fold_wall_s, 6)})
        _verify(f"batch {i}")
    report = {
        "replayed_deltas": replayed,
        "restored_folds": restored_folds,
        "total_folds": sq.folds,
        "rows_folded": sq.rows_folded,
        "folds": folds,
        "mismatches": mismatches,
        "stats_delta": sstats.delta(pre),
    }
    s.stop()
    with open(args.report, "w") as f:
        json.dump(report, f, indent=2)
    return 0


def _run_scenario(args, name, torn):
    """One ingest-crash + recover cycle; returns (checks, detail)."""
    ckpt = tempfile.mkdtemp(prefix=f"stream_dur_{name}_")
    base = [sys.executable, os.path.abspath(__file__),
            "--batches", str(args.batches), "--rows", str(args.rows),
            "--keys", str(args.keys), "--crash-at", str(args.crash_at),
            "--dir", ckpt]
    if torn:
        base.append("--torn")
    ingest = subprocess.run(base + ["--phase", "ingest"], check=False)
    report_path = os.path.join(ckpt, "recover_report.json")
    recover = subprocess.run(
        base + ["--phase", "recover", "--report", report_path],
        check=False)
    rep = {}
    if recover.returncode == 0 and os.path.exists(report_path):
        with open(report_path) as f:
            rep = json.load(f)
    d = rep.get("stats_delta", {})
    measured = [f["dispatches"] for f in
                rep.get("folds", [])[WARMUP_FOLDS:]]
    total_rows = args.batches * args.rows
    checks = {
        "killed": {
            "ingest_rc": ingest.returncode,
            "ok": bool(ingest.returncode == -9),
        },
        "bit_exact": {
            "recover_rc": recover.returncode,
            "mismatches": rep.get("mismatches", ["no recover report"]),
            "ok": bool(recover.returncode == 0
                       and rep.get("mismatches") == []),
        },
        "exactly_once": {
            "total_folds": rep.get("total_folds"),
            "expected_folds": args.batches,
            "rows_folded": rep.get("rows_folded"),
            "expected_rows": total_rows,
            "ok": bool(rep.get("total_folds") == args.batches
                       and rep.get("rows_folded") == total_rows),
        },
        "flat_dispatch": {
            "per_fold_dispatch_counts": sorted(set(measured)),
            "ok": bool(measured and len(set(measured)) == 1),
        },
        "counters": {
            "wal_replays": d.get("wal_replays"),
            "recoveries": d.get("recoveries"),
            "torn_rejected": d.get("torn_rejected"),
            "ok": bool(d.get("wal_replays", 0) >= 1 and
                       (d.get("torn_rejected", 0) >= 1
                        and d.get("recoveries", 0) == 0 if torn
                        else d.get("recoveries", 0) >= 1)),
        },
    }
    detail = {"checkpoint_dir": ckpt, "recover_report": rep,
              "checks": checks,
              "ok": all(c["ok"] for c in checks.values())}
    return detail


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--batches", type=int, default=12)
    parser.add_argument("--rows", type=int, default=4000)
    parser.add_argument("--keys", type=int, default=32)
    parser.add_argument("--crash-at", type=int, default=6,
                        help="fold ordinal the injector SIGKILLs at")
    parser.add_argument("--output", default="STREAM_r02.json")
    # child-process plumbing
    parser.add_argument("--phase", choices=["ingest", "recover"])
    parser.add_argument("--dir", help="shared checkpoint dir (child)")
    parser.add_argument("--torn", action="store_true",
                        help="tear every checkpoint commit (child)")
    parser.add_argument("--report", help="child recover report path")
    args = parser.parse_args(argv)

    if args.phase == "ingest":
        return phase_ingest(args)
    if args.phase == "recover":
        return phase_recover(args)

    scenarios = {
        "crash_recover": _run_scenario(args, "crash", torn=False),
        "torn_fallback": _run_scenario(args, "torn", torn=True),
    }
    report = {
        "benchmark": "stream_durability_check",
        "batches": args.batches,
        "rows_per_batch": args.rows,
        "keys": args.keys,
        "crash_at_fold": args.crash_at,
        "scenarios": scenarios,
        "ok": all(sc["ok"] for sc in scenarios.values()),
    }
    text = json.dumps(report, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)
    if not report["ok"]:
        print("STREAM DURABILITY FENCE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
