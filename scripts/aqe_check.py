"""AQE skew fence: a deliberately skewed join must stay within 1.5x
the uniform-data wall clock, match the CPU oracle bit for bit, and
leave a nonzero replan-event trail — otherwise the adaptive layer has
silently stopped replanning (or started corrupting).

Two scenarios:

A. **Host-path skewed join** — tpch q12 (orders x lineitem on
   l_orderkey) twice: uniform data, then data with half of lineitem on
   one hot key (``--skew 0.5``). The skewed run must produce skew
   replan events (the fence lowers the skew cut so the detection
   triggers at the chosen sf) and hold the wall-clock ratio.
B. **In-program salting** — a direct exchange-layer check on the
   8-virtual-device CPU mesh: a hot hash partition is salted across
   devices before the ``all_to_all`` and per-partition content stays
   bit-equal to the host path.

    python scripts/aqe_check.py [--sf 1.0] [--skew 0.5]
                                [--query tpch_q12]
                                [--data-dir /tmp/srt_aqe]
                                [--output AQE.json]

Prints one JSON report; exit code 0 = fence holds.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")
# scenario B needs a multi-device mesh before jax initializes
os.environ.setdefault("XLA_FLAGS",
                      "--xla_force_host_platform_device_count=8")

# telemetry must wrap jax.jit before any compute module import
from spark_rapids_tpu.utils import dispatch as disp  # noqa: E402

disp.install()

#: wall-clock gate: skewed wall <= RATIO * uniform wall + SLACK_S
#: (the slack absorbs compile/IO jitter at small sf where both walls
#: are fractions of a second)
RATIO = 1.5
SLACK_S = 2.0


def _aqe_conf(sf: float):
    """Skew thresholds scaled so detection triggers at this sf: the
    hot partition at --skew 0.5 carries ~half the shuffled bytes, so a
    cut at ~1/8 of the uniform partition's natural size flags it and
    nothing else at factor 2."""
    from spark_rapids_tpu.config import RapidsConf

    cut = max(int(sf * 64 * 1024), 1024)
    return RapidsConf({
        "rapids.tpu.sql.adaptive.skewJoin."
        "skewedPartitionThresholdInBytes": cut,
        "rapids.tpu.sql.adaptive.skewJoin.skewedPartitionFactor": 2.0,
        # advisory at the cut: a partition past the skew cut is then
        # always alone in its coalesced group, i.e. splittable
        "rapids.tpu.sql.adaptive.advisoryPartitionSizeBytes": cut,
        # the skewed join is the scenario under test: keep the build
        # side off the (static or measured) broadcast shortcut
        "rapids.tpu.sql.autoBroadcastJoinThreshold": 0,
        # keep sf-1 scans multi-partition (the default 256 MiB reader
        # packing folds q12's 5-column lineitem scan into ONE split,
        # which erases the exchanges AQE replans over)
        "rapids.tpu.sql.reader.batchSizeBytes":
            max(int(sf * 32) << 20, 1 << 20),
    })


def run_join(query: str, sf: float, data_dir: str, skew: float) -> dict:
    from spark_rapids_tpu.benchmarks.runner import (ALL_BENCHMARKS,
                                                    BenchmarkRunner)
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.plan.overrides import apply_overrides

    r = BenchmarkRunner(data_dir, sf, conf=_aqe_conf(sf), skew=skew)
    r.ensure_data("tpch")
    # warm run traces + compiles; the fence times the steady state
    collect(apply_overrides(ALL_BENCHMARKS[query](data_dir), r.conf))
    pre = disp.replan_snapshot()
    t0 = time.perf_counter()
    df = collect(apply_overrides(ALL_BENCHMARKS[query](data_dir),
                                 r.conf))
    wall = time.perf_counter() - t0
    events = disp.replan_delta(pre)
    cmp_ = r.compare_results(query, df)
    return {
        "skew": skew,
        "wall_s": round(wall, 3),
        "replan_events": events,
        "matches_cpu": cmp_["matches_cpu"],
        "detail": cmp_.get("detail", ""),
    }


def check_salting() -> dict:
    """Scenario B: in-program salted exchange == host path, with a
    skew_salt replan event."""
    import numpy as np

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.execs.base import TpuExec
    from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.parallel.mesh import data_mesh
    from spark_rapids_tpu.parallel.spmd import SkewSpec

    rng = np.random.default_rng(7)
    parts = []
    for _ in range(4):
        keys = rng.integers(0, 40, 2000).astype(np.int64)
        keys[rng.random(2000) < 0.7] = 11  # hot key
        parts.append((keys, rng.random(2000)))

    class _Rows(TpuExec):
        def __init__(self):
            super().__init__([], Schema(["k", "v"],
                                        [dt.INT64, dt.FLOAT64]))

        @property
        def num_partitions(self):
            return len(parts)

        def execute(self, partition=0):
            keys, vals = parts[partition]
            yield ColumnarBatch(
                [Column.from_numpy(keys, dt.INT64),
                 Column.from_numpy(vals, dt.FLOAT64)], len(keys))

    def drain(ex):
        out = {}
        for p in range(ex.num_out_partitions):
            rows = []
            for b in ex.execute(p):
                pdf = b.to_pandas()
                rows += [(int(r.iloc[0]), float(r.iloc[1]))
                         for _, r in pdf.iterrows()]
            out[p] = sorted(rows)
        return out

    num_out = 5
    want = drain(ShuffleExchangeExec(("hash", [0]), num_out, _Rows()))
    pre = disp.replan_snapshot()
    prog = ShuffleExchangeExec(("hash", [0]), num_out, _Rows())
    prog.enable_in_program(data_mesh(8),
                           skew=SkewSpec(factor=2.0, threshold=1024,
                                         max_splits=8))
    got = drain(prog)
    events = disp.replan_delta(pre)
    salted = any(k.startswith("skew_salt") for k in events)
    equal = bool(prog.in_program) and all(
        got[p] == want[p] for p in range(num_out))
    return {"in_program": bool(prog.in_program),
            "content_equal": equal, "replan_events": events,
            "ok": salted and equal}


def main(argv=None):
    p = argparse.ArgumentParser()
    p.add_argument("--sf", type=float, default=1.0)
    p.add_argument("--skew", type=float, default=0.5)
    p.add_argument("--query", default="tpch_q12")
    p.add_argument("--data-dir", default="/tmp/srt_aqe")
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)

    uniform = run_join(args.query, args.sf, args.data_dir, 0.0)
    skewed = run_join(args.query, args.sf,
                      args.data_dir + f"_skew{args.skew}", args.skew)

    wall_ok = skewed["wall_s"] <= RATIO * uniform["wall_s"] + SLACK_S
    replanned = any(k.startswith(("skew_split", "skew_salt"))
                    for k in skewed["replan_events"])
    salt = check_salting()
    ok = bool(wall_ok and replanned and skewed["matches_cpu"] and
              uniform["matches_cpu"] and salt["ok"])
    report = {
        "fence": "aqe_check", "sf": args.sf, "query": args.query,
        "ok": ok,
        "wall_ratio": round(skewed["wall_s"] /
                            max(uniform["wall_s"], 1e-9), 3),
        "wall_ratio_limit": RATIO,
        "wall_ok": wall_ok,
        "skew_replanned": replanned,
        "uniform": uniform,
        "skewed": skewed,
        "salting": salt,
    }
    text = json.dumps(report, indent=1)
    print(text)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
