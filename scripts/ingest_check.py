"""Ingest fence: the async scan pipeline must be a pure performance
knob — oracle-equal answers with measured scan-compute overlap and
footer-stat pruning that actually cuts bytes (CLI twin of
tests/test_scan_pipeline.py, run at real scale).

Four checks over TPC-H at sf >= 10:

  1. **q1_oracle_overlap** : q1 through the pipelined scan matches the
                       CPU oracle AND the measured scan-compute overlap
                       fraction (decode busy time hidden behind the
                       consumer, from the io.scan telemetry block) is
                       >= 0.5 — the scan wall is paid concurrently with
                       compute, not in front of it
  2. **q6_pruning**    : q6's pushed-down shipdate range prunes row
                       groups by footer stats; bytes_read with
                       pruning.enabled=false must be >= 2x the pruned
                       run's (the datagen writes lineitem time-ordered,
                       so a 1-year predicate keeps a fraction of the
                       7-year span). Both runs match the oracle.
  3. **depth0_identity**: prefetch.depth=0 (the strict synchronous
                       read-then-upload path) and the default pipelined
                       depth produce byte-identical batches — same
                       boundaries, same buffer bytes
  4. **depth0_oracle** : q1 with depth=0 still matches the oracle (the
                       pipeline is not load-bearing for correctness)

    python scripts/ingest_check.py [--sf 10] [--data-dir DIR]
                                   [--output INGEST_r01.json]

Prints one JSON report; exit code 0 = fence holds.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")

# telemetry must wrap jax.jit before any compute module import
from spark_rapids_tpu.utils import dispatch as disp  # noqa: E402

disp.install()

MIN_OVERLAP = 0.5
MIN_PRUNE_RATIO = 2.0


def _run(benchmark: str, runner, conf, compare: bool = True) -> dict:
    """One cold-ish run: scan telemetry delta + oracle comparison."""
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.io import scanpipe
    from spark_rapids_tpu.benchmarks.runner import ALL_BENCHMARKS
    from spark_rapids_tpu.plan.overrides import apply_overrides

    scanpipe.clear_cache()
    pre = scanpipe.snapshot()
    plan = ALL_BENCHMARKS[benchmark](runner.data_dir)
    t0 = time.perf_counter()
    df = collect(apply_overrides(plan, conf), conf)
    wall = time.perf_counter() - t0
    scan = scanpipe.delta(pre)
    rec = {"benchmark": benchmark, "wall_s": round(wall, 3),
           "io_scan": scan}
    if compare:
        cmp_ = runner.compare_results(benchmark, df)
        rec["matches_cpu"] = cmp_["matches_cpu"]
        rec["cpu_oracle_s"] = round(cmp_["cpu_time_sec"], 3)
        rec["detail"] = cmp_.get("detail", "")
    return rec


def _depth0_identity(data_dir: str, conf) -> dict:
    """Batch-by-batch byte comparison of the synchronous (depth=0) and
    pipelined scans over the first lineitem split."""
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.io import ParquetSource, arrow_conv
    from spark_rapids_tpu.plan import nodes as pn
    from spark_rapids_tpu.plan.overrides import apply_overrides

    path = os.path.join(data_dir, "lineitem")
    cols = ["l_quantity", "l_extendedprice", "l_discount", "l_shipdate",
            "l_returnflag", "l_linestatus"]

    def batches(depth):
        c = conf.with_overrides({cfg.SCAN_PREFETCH_DEPTH.key: depth})
        src = ParquetSource(path, columns=cols, conf=c)
        exec_ = apply_overrides(pn.ScanNode(src), c)
        out = []
        for b in exec_.execute(0):   # first split is plenty of bytes
            if b.realized_num_rows():
                out.append(arrow_conv.batch_to_arrow(b, exec_.schema))
        return out

    sync_b, async_b = batches(0), batches(2)
    rows = sum(t.num_rows for t in sync_b)
    mismatch = None
    if len(sync_b) != len(async_b):
        mismatch = (f"batch count differs: depth0={len(sync_b)} "
                    f"depth2={len(async_b)}")
    else:
        for i, (a, b) in enumerate(zip(sync_b, async_b)):
            if a.num_rows != b.num_rows:
                mismatch = f"batch {i} rows {a.num_rows}!={b.num_rows}"
                break
            for name in a.column_names:
                ca = a.column(name).combine_chunks()
                cb = b.column(name).combine_chunks()
                for ba, bb in zip(ca.buffers(), cb.buffers()):
                    if (ba is None) != (bb is None) or (
                            ba is not None and
                            ba.to_pybytes() != bb.to_pybytes()):
                        mismatch = f"batch {i} column {name}: " \
                                   f"buffer bytes differ"
                        break
                if mismatch:
                    break
            if mismatch:
                break
    return {"batches": len(sync_b), "rows": int(rows),
            "mismatch": mismatch,
            "ok": bool(mismatch is None and sync_b)}


def main(argv=None):
    parser = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    parser.add_argument("--sf", type=float, default=10.0,
                        help="TPC-H scale factor (fence requires >= 10)")
    parser.add_argument("--data-dir", default="bench_data",
                        help="where TPC-H tables live / get generated")
    parser.add_argument("--output", default="INGEST_r01.json")
    args = parser.parse_args(argv)

    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.benchmarks.runner import BenchmarkRunner

    r = BenchmarkRunner(args.data_dir, args.sf)
    t0 = time.perf_counter()
    r.ensure_data("tpch")
    gen_s = time.perf_counter() - t0

    conf = r.conf

    # -- 1. q1: oracle + overlap through the default pipelined scan ----
    q1 = _run("tpch_q1", r, conf)
    overlap = (q1["io_scan"] or {}).get("overlap_fraction")
    q1_ok = bool(q1["matches_cpu"] and overlap is not None and
                 overlap >= MIN_OVERLAP)

    # -- 2. q6: pruned vs unpruned bytes-read differential -------------
    q6_pruned = _run("tpch_q6", r, conf)
    no_prune = conf.with_overrides(
        {cfg.SCAN_PRUNING_ENABLED.key: False})
    q6_full = _run("tpch_q6", r, no_prune)
    read_pruned = q6_pruned["io_scan"]["bytes_read"]
    read_full = q6_full["io_scan"]["bytes_read"]
    ratio = read_full / max(read_pruned, 1)
    q6_ok = bool(q6_pruned["matches_cpu"] and q6_full["matches_cpu"] and
                 q6_pruned["io_scan"]["chunks_pruned"] > 0 and
                 ratio >= MIN_PRUNE_RATIO)

    # -- 3. depth=0 byte-identical to the pipelined scan ---------------
    ident = _depth0_identity(args.data_dir, conf)

    # -- 4. q1 with depth=0: the synchronous path stays oracle-equal ---
    sync_conf = conf.with_overrides({cfg.SCAN_PREFETCH_DEPTH.key: 0})
    q1_sync = _run("tpch_q1", r, sync_conf)

    checks = {
        "q1_oracle_overlap": {
            "matches_cpu": q1["matches_cpu"],
            "overlap_fraction": overlap,
            "threshold": MIN_OVERLAP,
            "ok": q1_ok,
        },
        "q6_pruning": {
            "matches_cpu": bool(q6_pruned["matches_cpu"] and
                                q6_full["matches_cpu"]),
            "bytes_read_pruned": int(read_pruned),
            "bytes_read_unpruned": int(read_full),
            "reduction_ratio": round(ratio, 3),
            "chunks_pruned": q6_pruned["io_scan"]["chunks_pruned"],
            "threshold": MIN_PRUNE_RATIO,
            "ok": q6_ok,
        },
        "depth0_identity": ident,
        "depth0_oracle": {
            "matches_cpu": q1_sync["matches_cpu"],
            "ok": bool(q1_sync["matches_cpu"]),
        },
    }
    report = {
        "benchmark": "ingest_check",
        "sf": args.sf,
        "datagen_s": round(gen_s, 3),
        "runs": {"q1": q1, "q6_pruned": q6_pruned, "q6_unpruned": q6_full,
                 "q1_depth0": q1_sync},
        "checks": checks,
        "ok": all(c["ok"] for c in checks.values()),
    }
    text = json.dumps(report, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)
    if not report["ok"]:
        print("INGEST FENCE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
