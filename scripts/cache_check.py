"""Semantic-cache fence: repeated dashboards must get cheaper, never
wronger (CLI twin of tests/test_cache.py, the service/cache analogue of
scripts/slo_check.py).

The serving claim the cache makes is measured directly: an open-loop
mix of REPEATED query templates over unchanged data runs twice — once
with ``rapids.tpu.service.cache.enabled=false`` (control) and once with
the cache on, same Poisson arrivals, same seed. The fence requires:

  1. **latency**  : cache-on p99 total (queue+run) <= control p99 / 2
  2. **dispatch** : cache-on physical device dispatches <= control / 2
  3. **oracle**   : EVERY served frame — miss, hit, follower — matches
                    the CPU oracle for its template
  4. **staleness**: after a MID-RUN version bump (the backing parquet
                    is rewritten), the next submit returns the NEW
                    oracle, not the cached old frame

Criteria 1-2 are RATIOS against a control measured in the same process
on the same backend, so the fence is meaningful on CPU CI, a local TPU,
or the remote tunnel alike.

    python scripts/cache_check.py [--queries 24] [--sf 0.01]
                                  [--output SLO_r02.json]

Prints one JSON report; exit code 0 = fence holds.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def _run_phase(service, make_query, oracles, mix, offered_qps, n,
               tenants, seed, disp):
    """Open loop over repeated templates; unlike slo.run_open_loop this
    drains every FRAME and oracle-matches it (the stock harness only
    keeps latency stats)."""
    from spark_rapids_tpu.benchmarks.runner import _frames_match
    from spark_rapids_tpu.service.batching import slo

    gaps = slo.poisson_gaps(offered_qps, n, seed=seed)
    pre = disp.snapshot()
    handles = []
    shed = failed = 0
    t0 = time.perf_counter()
    next_at = t0
    for i, gap in enumerate(gaps):
        next_at += gap
        delay = next_at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        try:
            handles.append((i, service.submit(
                make_query(i), tenant=f"tenant{i % max(tenants, 1)}")))
        except Exception:
            shed += 1
    totals, mismatches = [], []
    for i, h in handles:
        try:
            frame = h.result(timeout=600)
        except Exception as e:
            failed += 1
            mismatches.append(f"q{i} failed: {e}")
            continue
        info = h.info()
        totals.append((info["queue_time_s"] or 0.0) +
                      (info["run_time_s"] or 0.0))
        ok, msg = _frames_match(oracles[mix[i % len(mix)]], frame)
        if not ok:
            mismatches.append(f"q{i} ({mix[i % len(mix)]}): {msg}")
    delta = disp.delta(pre)
    return {
        "done": len(totals), "shed": shed, "failed": failed,
        "wall_s": round(time.perf_counter() - t0, 4),
        "p50_s": round(slo.percentile(totals, 50), 4),
        "p99_s": round(slo.percentile(totals, 99), 4),
        "dispatch_count": delta["dispatch_count"],
        "oracle_mismatches": mismatches,
    }


def main(argv=None):
    p = argparse.ArgumentParser()
    # enough repeats that the per-template cold misses (2 here) fall
    # below the nearest-rank p99 of the cached phase
    p.add_argument("--queries", type=int, default=240)
    p.add_argument("--mix", default="tpch_q1,tpch_q6")
    p.add_argument("--tenants", type=int, default=4)
    p.add_argument("--sf", type=float, default=0.01)
    p.add_argument("--data-dir", default="/tmp/rapids_tpu_cache_check")
    p.add_argument("--min-speedup", type=float, default=2.0)
    p.add_argument("--seed", type=int, default=7)
    p.add_argument("--output", default=None)
    args = p.parse_args(argv)

    # telemetry wraps jax.jit; must precede every compute-module import
    from spark_rapids_tpu.utils import dispatch as disp

    disp.install()

    import pandas as pd

    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.benchmarks.runner import (ALL_BENCHMARKS,
                                                    BenchmarkRunner)
    from spark_rapids_tpu.benchmarks.runner import _frames_match
    from spark_rapids_tpu.cpu.engine import execute_cpu
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.plan.overrides import apply_overrides
    from spark_rapids_tpu.service import QueryService

    mix = args.mix.split(",")
    runner = BenchmarkRunner(args.data_dir, args.sf)
    for name in dict.fromkeys(mix):
        runner.ensure_data(name)

    def fresh_plan(name):
        return ALL_BENCHMARKS[name](args.data_dir)

    oracles = {name: execute_cpu(fresh_plan(name)).to_pandas()
               for name in dict.fromkeys(mix)}

    # warm the process-global compile caches so the control phase
    # measures steady-state recompute, not first-compile — inflating
    # the control would make the fence trivially (and dishonestly)
    # pass. The SECOND run's time (compiles already warm) sets the
    # offered rate.
    serial_s = 0.0
    for name in dict.fromkeys(mix):
        collect(apply_overrides(fresh_plan(name), runner.conf))
        t0 = time.perf_counter()
        collect(apply_overrides(fresh_plan(name), runner.conf))
        serial_s = max(serial_s, time.perf_counter() - t0)
    offered_qps = min(max(0.35 / max(serial_s, 1e-4), 0.5), 24.0)

    def make_query(i):
        return fresh_plan(mix[i % len(mix)])

    # -- phase A: control, cache off ----------------------------------
    svc_off = QueryService({cfg.SERVICE_CACHE_ENABLED.key: False})
    control = _run_phase(svc_off, make_query, oracles, mix,
                         offered_qps, args.queries, args.tenants,
                         args.seed, disp)
    svc_off.shutdown()

    # -- phase B: cache on, same arrivals -----------------------------
    svc = QueryService()
    cached = _run_phase(svc, make_query, oracles, mix, offered_qps,
                        args.queries, args.tenants, args.seed, disp)
    cache_stats = svc.stats().to_dict()["cache"]

    # -- phase C: mid-run version bump must not serve stale -----------
    # rewrite one lineitem part (both q1 and q6 read the table) with
    # fewer rows: a different answer is guaranteed, and the file's
    # (mtime_ns, size) snapshot version changes with it
    li = os.path.join(args.data_dir, "lineitem", "part-000.parquet")
    frame = pd.read_parquet(li)
    frame.iloc[:max(len(frame) - max(len(frame) // 10, 1), 1)] \
        .to_parquet(li)
    os.utime(li, ns=(time.time_ns(), time.time_ns()))
    bump_name = mix[0]
    new_oracle = execute_cpu(fresh_plan(bump_name)).to_pandas()
    stale_would_differ, _ = _frames_match(oracles[bump_name],
                                          new_oracle)
    got = svc.submit(fresh_plan(bump_name)).result(timeout=600)
    fresh_ok, fresh_msg = _frames_match(new_oracle, got)
    svc.shutdown()

    p99_ratio = control["p99_s"] / max(cached["p99_s"], 1e-6)
    disp_ratio = control["dispatch_count"] / \
        max(cached["dispatch_count"], 1)
    checks = {
        "p99_speedup": {
            "control_p99_s": control["p99_s"],
            "cached_p99_s": cached["p99_s"],
            "ratio": round(p99_ratio, 3),
            "threshold": args.min_speedup,
            "ok": bool(p99_ratio >= args.min_speedup),
        },
        "dispatch_drop": {
            "control_dispatches": control["dispatch_count"],
            "cached_dispatches": cached["dispatch_count"],
            "ratio": round(disp_ratio, 3),
            "threshold": args.min_speedup,
            "ok": bool(disp_ratio >= args.min_speedup),
        },
        "oracle_matched": {
            "control_mismatches": control["oracle_mismatches"],
            "cached_mismatches": cached["oracle_mismatches"],
            "ok": bool(not control["oracle_mismatches"] and
                       not cached["oracle_mismatches"] and
                       control["failed"] == 0 and
                       cached["failed"] == 0),
        },
        "version_bump_not_stale": {
            # guard the guard: the mutation must actually change the
            # answer, else "fresh" and "stale" are indistinguishable
            "mutation_changed_answer": bool(not stale_would_differ),
            "served_fresh": fresh_ok,
            "detail": None if fresh_ok else fresh_msg,
            "ok": bool(fresh_ok and not stale_would_differ),
        },
    }
    report = {
        "benchmark": "cache_check",
        "scale_factor": args.sf,
        "queries": args.queries,
        "mix": mix,
        "offered_qps": round(offered_qps, 3),
        "control": control,
        "cached": cached,
        "cache_stats": cache_stats,
        "checks": checks,
        "ok": all(c["ok"] for c in checks.values()),
    }
    text = json.dumps(report, indent=2)
    if args.output:
        with open(args.output, "w") as f:
            f.write(text)
    print(text)
    if not report["ok"]:
        print("CACHE FENCE FAILED", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
