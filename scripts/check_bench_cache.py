"""Fail when scripts/bench_cache/ no longer matches the bench kernel.

The driver bench (bench.py) absorbs the ~30-minute cold XLA compile of
the 4M-row fused pipeline by seeding .jax_cache from a tracked
executable (scripts/bench_cache/). Any edit to ops/groupby.py or the
entry pipeline changes the cache key and silently invalidates the seed —
the next driver bench then times out (r2's rc 124). This check makes
the staleness loud IN-ROUND, and (round-6) WITHOUT needing the TPU box:

**Key check (default, device-free).** The tracked
``scripts/bench_cache/PROGRAM_KEY.json`` records a fingerprint of the
bench program's *jaxpr* — the backend-independent trace whose change is
what invalidates the platform cache key (the XLA key hashes the lowered
module; a changed trace changes the module on every platform). CI under
``JAX_PLATFORMS=cpu`` re-traces and compares: a mismatch means "refresh
the seed". Conservative by construction: a fingerprint match with a
stale seed is impossible for program edits (the only false alarms are
trace-identical refactors of jax internals, which a --device run
settles). The fingerprint also records the jax version, since the same
program can print a different jaxpr across versions — a version
mismatch is reported as SKIP, not STALE.

**Device check (--device).** The original proof: trace against the
attached TPU backend and ask jax's compile path for the executable with
actual compilation FORBIDDEN — a persistent-cache hit proves the
tracked entry matches. Requires the axon-attached build box.

Refreshing the seed (on the TPU box):

    rm -rf .jax_cache && python bench.py   # one cold compile (~30 min)
    cp .jax_cache/jit_step-*-cache scripts/bench_cache/  # + git add
    python scripts/check_bench_cache.py --update-key     # + git add
"""
from __future__ import annotations

import argparse
import hashlib
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

KEY_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "bench_cache", "PROGRAM_KEY.json")


class _WouldCompile(Exception):
    pass


def program_fingerprint() -> dict:
    """Backend-independent fingerprint of the bench entry program: a
    hash of its jaxpr (abstract trace — no device, no compile)."""
    import jax

    from __graft_entry__ import entry

    step, args = entry()
    jaxpr = jax.make_jaxpr(step)(*args)
    digest = hashlib.sha256(str(jaxpr).encode()).hexdigest()
    return {"jaxpr_sha256": digest, "jax_version": jax.__version__,
            "x64": bool(jax.config.jax_enable_x64)}


def check_key() -> int:
    if not os.path.exists(KEY_PATH):
        print(f"SKIP: {os.path.relpath(KEY_PATH)} not tracked yet — "
              "run with --update-key after refreshing the seed "
              "(or --device on the TPU box)")
        return 0
    with open(KEY_PATH) as f:
        tracked = json.load(f)
    now = program_fingerprint()
    if tracked.get("jax_version") != now["jax_version"] or \
            tracked.get("x64") != now["x64"]:
        print(f"SKIP: environment changed (tracked jax "
              f"{tracked.get('jax_version')}/x64={tracked.get('x64')}, "
              f"running {now['jax_version']}/x64={now['x64']}) — jaxpr "
              "text is only comparable within one version; re-run "
              "--update-key from the seed-refresh environment")
        return 0
    if tracked.get("jaxpr_sha256") != now["jaxpr_sha256"]:
        print("STALE: the bench kernel's program changed since "
              "scripts/bench_cache/ was seeded — the next driver bench "
              "will eat a ~30-min cold compile. Refresh the seed (see "
              "module docstring).")
        return 1
    print("OK: bench kernel matches the tracked program key "
          f"({now['jaxpr_sha256'][:12]}...)")
    return 0


def update_key() -> int:
    fp = program_fingerprint()
    os.makedirs(os.path.dirname(KEY_PATH), exist_ok=True)
    with open(KEY_PATH, "w") as f:
        json.dump(fp, f, indent=2, sort_keys=True)
        f.write("\n")
    print(f"wrote {os.path.relpath(KEY_PATH)}: "
          f"{fp['jaxpr_sha256'][:12]}... (jax {fp['jax_version']})")
    return 0


def check_device() -> int:
    import bench

    bench.seed_compile_cache()

    import jax

    if jax.devices()[0].platform == "cpu":
        print("SKIP: no TPU backend attached (cache keys are "
              "platform-specific; run --device on the TPU box, or use "
              "the default key check)")
        return 0

    from __graft_entry__ import entry

    step, args = entry()
    lowered = jax.jit(step).lower(*args)

    from jax._src import compiler

    def _forbid(*a, **k):
        raise _WouldCompile()

    orig = compiler.backend_compile_and_load
    compiler.backend_compile_and_load = _forbid
    try:
        lowered.compile()
    except _WouldCompile:
        print("STALE: the bench kernel no longer matches "
              "scripts/bench_cache/ — the next driver bench will eat a "
              "~30-min cold compile. Refresh the seed (see module "
              "docstring).")
        return 1
    finally:
        compiler.backend_compile_and_load = orig
    print("OK: scripts/bench_cache/ matches the current bench kernel")
    return 0


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--device", action="store_true",
                   help="prove the tracked executable loads via the "
                        "persistent cache against the attached TPU "
                        "(the original, device-requiring check)")
    p.add_argument("--update-key", action="store_true",
                   help="record the current program fingerprint as the "
                        "tracked PROGRAM_KEY.json (run when refreshing "
                        "the seed)")
    args = p.parse_args()
    if args.update_key:
        return update_key()
    if args.device:
        return check_device()
    return check_key()


if __name__ == "__main__":
    sys.exit(main())
