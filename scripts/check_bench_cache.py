"""Fail when scripts/bench_cache/ no longer matches the bench kernel.

The driver bench (bench.py) absorbs the ~30-minute cold XLA compile of
the 4M-row fused pipeline by seeding .jax_cache from a tracked
executable (scripts/bench_cache/). Any edit to ops/groupby.py or the
entry pipeline changes the cache key and silently invalidates the seed —
the next driver bench then times out (r2's rc 124). This check makes the
staleness loud IN-ROUND: it traces the exact bench program against the
attached TPU backend, then asks jax's compile path for it with the
actual backend compile FORBIDDEN. A persistent-cache hit proves the
tracked entry still matches; a miss means "refresh the seed":

    rm -rf .jax_cache && python bench.py   # one cold compile (~30 min)
    cp .jax_cache/jit_step-*-cache scripts/bench_cache/  # + git add

Requires the TPU backend (the cache key includes the target platform),
so it runs on the axon-attached build box, not in CPU-only CI.
"""
from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


class _WouldCompile(Exception):
    pass


def main() -> int:
    import bench

    bench.seed_compile_cache()

    import jax

    if jax.devices()[0].platform == "cpu":
        print("SKIP: no TPU backend attached (cache keys are "
              "platform-specific; run this on the TPU box)")
        return 0

    from __graft_entry__ import entry

    step, args = entry()
    lowered = jax.jit(step).lower(*args)

    from jax._src import compiler

    def _forbid(*a, **k):
        raise _WouldCompile()

    orig = compiler.backend_compile_and_load
    compiler.backend_compile_and_load = _forbid
    try:
        lowered.compile()
    except _WouldCompile:
        print("STALE: the bench kernel no longer matches "
              "scripts/bench_cache/ — the next driver bench will eat a "
              "~30-min cold compile. Refresh the seed (see module "
              "docstring).")
        return 1
    finally:
        compiler.backend_compile_and_load = orig
    print("OK: scripts/bench_cache/ matches the current bench kernel")
    return 0


if __name__ == "__main__":
    sys.exit(main())
