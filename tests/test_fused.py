"""Cross-exec fusion (execs/fused.py): one program per pipeline segment.

Oracle strategy: every query runs twice — fusion on (default) and off —
and must produce identical frames; plan-shape assertions pin that the
fused execs actually replaced the per-op pipeline (the dispatch-count
reduction is structural: no FilterExec/BroadcastHashJoinExec remains in
a fused segment). Mirrors the reference's hash-join test matrix
(GpuHashJoin.scala:302-318 kinds) plus the duplicate-build fallback.
"""
import numpy as np
import pandas as pd
import pytest

from compare import assert_frames_equal
from spark_rapids_tpu.api import Session
from spark_rapids_tpu.execs.fused import (FusedAggregateExec,
                                          FusedChainExec, JoinStep)

pytestmark = pytest.mark.smoke


def _sessions():
    on = Session(conf={"rapids.tpu.sql.fusion.enabled": True})
    off = Session(conf={"rapids.tpu.sql.fusion.enabled": False})
    return on, off


def _tables(rng, n=800, nulls=True):
    k = rng.integers(0, 30, n).astype(np.int64)
    fact = pd.DataFrame({
        "k": k,
        "v": rng.normal(size=n),
        "g": rng.integers(0, 6, n).astype(np.int64)})
    if nulls:
        fact.loc[rng.integers(0, n, 40), "v"] = None
    dim = pd.DataFrame({
        "id": np.arange(30, dtype=np.int64),
        "name": np.array([f"cat{i % 5}" for i in range(30)],
                         dtype=object),
        "w": (np.arange(30) * 1.5)})
    if nulls:
        dim.loc[3, "name"] = None
    return fact, dim


def _register(s, fact, dim):
    s.create_temp_view("f", s.create_dataframe(fact))
    s.create_temp_view("d", s.create_dataframe(dim))


def _both(sql, fact, dim):
    on, off = _sessions()
    _register(on, fact, dim)
    _register(off, fact, dim)
    got = on.sql(sql).collect()
    want = off.sql(sql).collect()
    assert_frames_equal(got, want)
    return on, got


def find(node, cls, out=None):
    out = [] if out is None else out
    if isinstance(node, cls):
        out.append(node)
    for c in node.children:
        find(c, cls, out)
    return out


def test_join_agg_becomes_fused_aggregate():
    rng = np.random.default_rng(7)
    fact, dim = _tables(rng)
    sql = ("SELECT d.name AS name, count(*) AS n, sum(f.v) AS sv "
           "FROM f JOIN d ON f.k = d.id WHERE f.g < 4 "
           "GROUP BY d.name ORDER BY name")
    on, _got = _both(sql, fact, dim)
    ex = on.sql(sql)._exec()
    fused = find(ex, FusedAggregateExec)
    assert fused, ex.tree_string()
    # the probe + filter + input projection all live in ONE chain
    assert any(isinstance(st, JoinStep) for st in fused[0].chain.steps)
    from spark_rapids_tpu.execs.basic import FilterExec
    from spark_rapids_tpu.execs.joins import BroadcastHashJoinExec

    assert not find(ex, FilterExec)
    assert not find(ex, BroadcastHashJoinExec)


@pytest.mark.parametrize("kind,sql", [
    ("inner", "SELECT f.k AS k, f.v AS v, d.w AS w FROM f JOIN d "
              "ON f.k = d.id WHERE f.g = 1 ORDER BY k, v"),
    ("left", "SELECT f.k AS k, f.v AS v, d.name AS name FROM f "
             "LEFT JOIN d ON f.k = d.id WHERE f.g = 2 ORDER BY k, v"),
    ("semi", "SELECT f.k AS k, f.v AS v FROM f WHERE f.k IN "
             "(SELECT d.id FROM d WHERE d.w > 10) ORDER BY k, v"),
    ("anti", "SELECT f.k AS k, f.v AS v FROM f WHERE f.k NOT IN "
             "(SELECT d.id FROM d WHERE d.w <= 40) AND f.k IS NOT NULL "
             "ORDER BY k, v"),
])
def test_fused_join_kinds_match_unfused(kind, sql):
    rng = np.random.default_rng(11)
    fact, dim = _tables(rng)
    # out-of-range keys so left/anti have unmatched rows
    fact.loc[rng.integers(0, len(fact), 60), "k"] = 99
    _both(sql, fact, dim)


def test_duplicate_build_keys_fall_back_exactly():
    """A build side with duplicate join keys needs multi-match
    expansion — the chain must detect it (hash-duplicate flag) and run
    the preserved general kernel, bit-identical to fusion-off."""
    rng = np.random.default_rng(13)
    fact, dim = _tables(rng)
    dup = dim.copy()
    dup.loc[len(dup)] = {"id": 5, "name": "dupe", "w": 123.0}
    sql = ("SELECT f.k AS k, count(*) AS n, sum(d.w) AS sw "
           "FROM f JOIN d ON f.k = d.id GROUP BY f.k ORDER BY k")
    on, _ = _both(sql, fact, dup)
    ex = on.sql(sql)._exec()
    # the exec owning the join's build side (a post-aggregate tail
    # chain with no builds may sit above it since the sort absorption)
    fused = [f for f in find(ex, (FusedAggregateExec, FusedChainExec))
             if f.builds]
    assert fused
    # force prep, then confirm the fallback path was chosen
    list(fused[0].execute(0))
    assert fused[0]._preps_ok is False


def test_mixed_int_float_keys_coerce():
    """pandas None->NaN turns an int64 key column float; the join must
    compare bigint = double as double (Spark implicit cast) in both
    the fused probe and the general kernel."""
    rng = np.random.default_rng(17)
    fact, dim = _tables(rng)
    fact.loc[rng.integers(0, len(fact), 50), "k"] = None  # -> float64
    sql = ("SELECT d.name AS name, count(*) AS n FROM f JOIN d "
           "ON f.k = d.id GROUP BY d.name ORDER BY name")
    _both(sql, fact, dim)


def test_multi_join_chain_one_program():
    """Two stacked dimension joins + filter + aggregate fuse into a
    single chain (q5/q26's fact->dim->dim shape)."""
    rng = np.random.default_rng(19)
    fact, dim = _tables(rng, nulls=False)
    dim2 = pd.DataFrame({"id2": np.arange(6, dtype=np.int64),
                         "label": np.array(
                             [f"l{i%3}" for i in range(6)], dtype=object)})
    sql = ("SELECT d2.label AS label, d.name AS name, sum(f.v) AS sv "
           "FROM f JOIN d ON f.k = d.id JOIN d2 ON f.g = d2.id2 "
           "WHERE f.v > -1 GROUP BY d2.label, d.name "
           "ORDER BY label, name")
    on, off = _sessions()
    for s in (on, off):
        _register(s, fact, dim)
        s.create_temp_view("d2", s.create_dataframe(dim2))
    got = on.sql(sql).collect()
    want = off.sql(sql).collect()
    assert_frames_equal(got, want)
    ex = on.sql(sql)._exec()
    fused = find(ex, FusedAggregateExec)
    assert fused, ex.tree_string()
    joins = [st for st in fused[0].chain.steps
             if isinstance(st, JoinStep)]
    assert len(joins) == 2, fused[0].chain.steps


def test_standalone_chain_compacts_lazily():
    """A filter+join segment NOT ending at an aggregate becomes a
    FusedChainExec whose output row count is a device scalar."""
    rng = np.random.default_rng(23)
    fact, dim = _tables(rng, nulls=False)
    sql = ("SELECT f.k AS k, d.w AS w FROM f JOIN d ON f.k = d.id "
           "WHERE f.g = 3 ORDER BY k, w")
    on, _ = _both(sql, fact, dim)
    ex = on.sql(sql)._exec()
    assert find(ex, FusedChainExec), ex.tree_string()


def test_nan_and_negzero_key_semantics_in_fused_probe():
    """NaN == NaN and -0.0 == 0.0 must hold inside the fused program
    (the add-zero canonicalization folds away in larger XLA programs —
    this pins the select-based canonicalization)."""
    on, off = _sessions()
    probe = pd.DataFrame({"y": np.array([0.0, 1.5, 7.25],
                                        dtype=np.float64)})
    build = pd.DataFrame({"y2": np.array([-0.0, np.inf],
                                         dtype=np.float64)})
    for s in (on, off):
        s.create_temp_view("p", s.create_dataframe(probe))
        s.create_temp_view("b", s.create_dataframe(build))
    sql = ("SELECT p.y AS y FROM p WHERE p.y NOT IN "
           "(SELECT y2 FROM b) ORDER BY y")
    got = on.sql(sql).collect()
    want = off.sql(sql).collect()
    assert_frames_equal(got, want)
    assert got["y"].tolist() == [1.5, 7.25]  # 0.0 cancels against -0.0


def test_string_predicates_fuse_into_chain():
    """String-vs-literal predicates (=, IN, <, >=) ride INSIDE the
    chain program as per-batch code-range operands — no FilterExec, no
    eager dictionary pass — and match the unfused engine exactly,
    including nulls and literals absent from the dictionary."""
    rng = np.random.default_rng(31)
    n = 900
    fact = pd.DataFrame({
        "k": rng.integers(0, 20, n).astype(np.int64),
        "v": rng.normal(size=n),
        "c": rng.choice(["web", "store", "catalog", "zzz"], n),
        "m": rng.choice(["M", "S", "D"], n)})
    fact.loc[rng.integers(0, n, 60), "c"] = None
    dim = pd.DataFrame({"id": np.arange(20, dtype=np.int64),
                        "w": np.arange(20) * 2.0})
    sql = ("SELECT f.k AS k, count(*) AS n, "
           "sum(CASE WHEN f.c = 'web' THEN f.v ELSE 0.0 END) AS wv "
           "FROM f JOIN d ON f.k = d.id "
           "WHERE f.m IN ('M', 'S') AND f.c >= 'catalog' "
           "AND f.c < 'x' AND f.c <> 'nope' "
           "GROUP BY f.k ORDER BY k")
    on, got = _both(sql, fact, dim)
    ex = on.sql(sql)._exec()
    from spark_rapids_tpu.execs.basic import FilterExec

    assert not find(ex, FilterExec), ex.tree_string()
    fused = find(ex, FusedAggregateExec)
    assert fused, ex.tree_string()
    assert fused[0].chain.n_aux > 0  # string preds became aux operands


def test_string_pred_literal_absent_from_dictionary():
    """A literal that never occurs in a batch's dictionary must match
    nothing (equality) / split correctly (range) — searchsorted gives a
    lo==hi empty range, not a false positive."""
    fact = pd.DataFrame({"k": np.arange(50, dtype=np.int64),
                         "c": np.array(
                             ["aa", "bb", "cc", "dd", "ee"] * 10,
                             dtype=object)})
    dim = pd.DataFrame({"id": np.arange(50, dtype=np.int64),
                        "w": np.arange(50) * 1.0})
    sql = ("SELECT count(*) AS n FROM f JOIN d ON f.k = d.id "
           "WHERE f.c = 'bbb' OR f.c > 'dd'")
    _both(sql, fact, dim)


def test_multi_join_distinct_key_shapes_pair_preps_correctly():
    """Regression (TPC-DS q83/q93): a chain with joins whose build
    sides have DIFFERENT widths and key ordinals must pair each
    prepared build with its own key spec — the builds list is in
    extraction order while steps run in execution order."""
    rng = np.random.default_rng(83)
    n = 600
    fact = pd.DataFrame({
        "k": rng.integers(0, 25, n).astype(np.int64),
        "s": rng.integers(0, 40, n).astype(np.int64),
        "v": rng.normal(size=n)})
    wide = pd.DataFrame({
        "pad0": np.arange(25) * 7.0,
        "pad1": np.arange(25) * 3.0,
        "id": np.arange(25, dtype=np.int64),     # key at ordinal 2
        "w": np.arange(25) * 1.5})
    narrow = pd.DataFrame({"sid": rng.choice(40, 15, replace=False)
                           .astype(np.int64)})   # 1-col semi build
    on, off = _sessions()
    for s in (on, off):
        s.create_temp_view("f", s.create_dataframe(fact))
        s.create_temp_view("wide", s.create_dataframe(wide))
        s.create_temp_view("narrow", s.create_dataframe(narrow))
    sql = ("SELECT f.k AS k, sum(f.v) AS sv, count(*) AS n "
           "FROM f JOIN wide ON f.k = wide.id "
           "WHERE f.s IN (SELECT sid FROM narrow) "
           "GROUP BY f.k ORDER BY k")
    got = on.sql(sql).collect()
    want = off.sql(sql).collect()
    assert_frames_equal(got, want)
    ex = on.sql(sql)._exec()
    fused = find(ex, FusedAggregateExec)
    assert fused, ex.tree_string()
    widths = sorted(len(s.build_types) for s in fused[0].chain.steps
                    if isinstance(s, JoinStep))
    assert len(widths) == 2 and widths[0] != widths[1], widths


# --------------------------------------------------- dense probe tables

def test_dense_probe_selected_and_matches_hash_path():
    """Single integral build keys probe through the dense inverse table
    (PreparedBuild.table); results must equal both the hash-probe path
    (forced via the denseProbe.maxSpan=0 config knob) and fusion-off,
    including negative keys, out-of-range probes, and null values."""
    rng = np.random.default_rng(29)
    n = 600
    fact = pd.DataFrame({
        "k": rng.integers(-40, 60, n).astype(np.int64),  # out-of-range
        "v": rng.normal(size=n)})                        # probes incl.
    fact.loc[rng.integers(0, n, 25), "v"] = None
    dim = pd.DataFrame({
        "id": np.arange(-30, 25, dtype=np.int64),    # negative base
        "w": rng.normal(size=55)})
    sql = ("SELECT f.k AS k, f.v AS v, d.w AS w FROM f JOIN d "
           "ON f.k = d.id ORDER BY k, v")
    on, _ = _both(sql, fact, dim)
    ex = on.sql(sql)._exec()
    fused = find(ex, (FusedAggregateExec, FusedChainExec))
    assert fused
    list(fused[0].execute(0))
    assert fused[0]._preps is not None
    assert fused[0]._preps[0].table is not None      # dense mode chosen

    # force the hash path via the config knob and compare exactly
    on2 = Session(conf={"rapids.tpu.sql.fusion.enabled": True,
                        "rapids.tpu.sql.fusion.denseProbe.maxSpan": 0})
    off2 = Session(conf={"rapids.tpu.sql.fusion.enabled": False})
    _register(on2, fact, dim)
    _register(off2, fact, dim)
    got_hash = on2.sql(sql).collect()
    want = off2.sql(sql).collect()
    assert_frames_equal(want, got_hash)
    ex2 = on2.sql(sql)._exec()
    fused2 = find(ex2, (FusedAggregateExec, FusedChainExec))
    list(fused2[0].execute(0))
    assert fused2[0]._preps[0].table is None         # hash mode forced


def test_dense_probe_multi_key_stays_hash():
    """Composite join keys keep the hash+searchsorted probe."""
    rng = np.random.default_rng(31)
    n = 400
    fact = pd.DataFrame({
        "a": rng.integers(0, 8, n).astype(np.int64),
        "b": rng.integers(0, 7, n).astype(np.int64),
        "v": rng.normal(size=n)})
    dim = pd.DataFrame({
        "x": np.repeat(np.arange(8, dtype=np.int64), 7),
        "y": np.tile(np.arange(7, dtype=np.int64), 8),
        "w": rng.normal(size=56)})
    sql = ("SELECT f.a AS a, f.b AS b, f.v AS v, d.w AS w FROM f "
           "JOIN d ON f.a = d.x AND f.b = d.y ORDER BY a, b, v")
    on, _ = _both(sql, fact, dim)
    ex = on.sql(sql)._exec()
    fused = find(ex, (FusedAggregateExec, FusedChainExec))
    assert fused
    list(fused[0].execute(0))
    assert fused[0]._preps[0].table is None


def test_wide_agg_compacts_before_sort_path(monkeypatch):
    """A wide (chunk-forcing) aggregate over a fused filter compacts
    survivors first when the batch is large: the 2^23-capacity chunked
    groupby shape costs a multi-ten-minute remote compile (q26 @ sf 1).
    Forced here via a tiny threshold; results must match fusion-off."""
    from spark_rapids_tpu.execs.aggregate import HashAggregateExec

    monkeypatch.setattr(HashAggregateExec, "_COMPACT_WIDE_MIN_CAP", 256)
    rng = np.random.default_rng(41)
    n = 3000
    fact = pd.DataFrame({
        # high-cardinality float key: defeats the dense path so the
        # compaction branch (sort path) is the one under test
        "k": rng.normal(0, 1000, n).round(3),
        **{f"v{i}": rng.normal(size=n) for i in range(8)}})
    sql = ("SELECT k, " +
           ", ".join(f"sum(v{i}) AS s{i}" for i in range(8)) +
           " FROM f WHERE v0 > 0 GROUP BY k ORDER BY k LIMIT 50")
    on, off = _sessions()
    on.create_temp_view("f", on.create_dataframe(fact))
    off.create_temp_view("f", off.create_dataframe(fact))
    got = on.sql(sql).collect()
    want = off.sql(sql).collect()
    assert_frames_equal(got, want)


def test_in_program_build_knob_off_matches_on():
    """inProgramBuild on (default: builds fold into the chain's first
    launch) vs off (host _prep_build + batched flag sync) must be
    frame-identical, and the on-path must actually resolve the builds
    from the inline launch rather than falling back."""
    rng = np.random.default_rng(29)
    fact, dim = _tables(rng)
    sql = ("SELECT d.name AS name, count(*) AS n, sum(f.v) AS sv "
           "FROM f JOIN d ON f.k = d.id WHERE f.g < 4 "
           "GROUP BY d.name ORDER BY name")
    key = "rapids.tpu.sql.fusion.inProgramBuild.enabled"
    s_on = Session(conf={key: True})
    s_off = Session(conf={key: False})
    _register(s_on, fact, dim)
    _register(s_off, fact, dim)
    got = s_on.sql(sql).collect()
    want = s_off.sql(sql).collect()
    assert_frames_equal(got, want)
    # the inline launch resolved the builds (no fallback, no host prep)
    ex = s_on.sql(sql)._exec()
    fused = [f for f in find(ex, (FusedAggregateExec, FusedChainExec))
             if f.builds]
    assert fused
    list(fused[0].execute(0))
    assert fused[0]._preps_ok is True
    assert fused[0]._preps and fused[0]._preps[0].ok
    # knob-off exec goes through the host path and agrees too
    ex_off = s_off.sql(sql)._exec()
    host = [f for f in find(ex_off,
                            (FusedAggregateExec, FusedChainExec))
            if f.builds]
    if host:  # fusion still on; only the build inlining is disabled
        assert not host[0]._inline_enabled()


def test_in_program_build_dense_table_from_stats():
    """A dim table with host-known key stats gets its dense inverse
    table built INSIDE the inline launch — the prepared build carries
    table + dense_lo without any separate _prep_build dispatch."""
    rng = np.random.default_rng(31)
    fact, dim = _tables(rng)
    sql = ("SELECT f.k AS k, f.v AS v, d.w AS w FROM f JOIN d "
           "ON f.k = d.id WHERE f.g = 1 ORDER BY k, v")
    on, _ = _sessions()
    _register(on, fact, dim)
    ex = on.sql(sql)._exec()
    fused = [f for f in find(ex, (FusedAggregateExec, FusedChainExec))
             if f.builds]
    assert fused
    list(fused[0].execute(0))
    assert fused[0]._preps_ok is True
    # dim ids are 0..29 with upload stats: dense-eligible
    assert fused[0]._preps[0].table is not None
    assert fused[0]._preps[0].dense_lo == 0
