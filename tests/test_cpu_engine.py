"""CPU engine tests: the oracle must itself match hand-computed Spark
semantics before it can judge the TPU path (reference: vanilla Spark is
trusted implicitly; our pandas/numpy engine needs its own checks)."""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.cpu.engine import execute_cpu
from spark_rapids_tpu.expressions import (Add, Alias, Average, BoundReference,
                                          Cast, Count, Divide, GreaterThan,
                                          Literal, Max, Min, Multiply, Sum)
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn


def ref(i, t, nullable=True):
    return BoundReference(i, t, nullable)


def scan(data, validity=None):
    return pn.ScanNode(pn.InMemorySource(data, validity=validity))


def test_scan_project_filter():
    plan = scan({"a": np.array([1, 2, 3, 4], dtype=np.int64),
                 "b": np.array([10.0, 20.0, 30.0, 40.0])})
    plan = pn.FilterNode(GreaterThan(ref(0, dt.INT64), Literal(1)), plan)
    plan = pn.ProjectNode(
        [Alias(Add(ref(0, dt.INT64), Literal(100)), "x"),
         Alias(Multiply(ref(1, dt.FLOAT64), Literal(2.0)), "y")], plan)
    out = execute_cpu(plan)
    df = out.to_pandas()
    assert list(df["x"]) == [102, 103, 104]
    assert list(df["y"]) == [40.0, 60.0, 80.0]


def test_filter_null_is_dropped():
    plan = scan({"a": np.array([1, 2, 3], dtype=np.int64)},
                validity={"a": np.array([True, False, True])})
    plan = pn.FilterNode(GreaterThan(ref(0, dt.INT64), Literal(0)), plan)
    out = execute_cpu(plan)
    assert out.num_rows == 2


def test_groupby_agg():
    plan = scan({"k": np.array([1, 2, 1, 2, 1], dtype=np.int64),
                 "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0])})
    aggs = [pn.AggCall(Sum(ref(1, dt.FLOAT64)), "s"),
            pn.AggCall(Count(ref(1, dt.FLOAT64)), "c"),
            pn.AggCall(Average(ref(1, dt.FLOAT64)), "a")]
    plan = pn.AggregateNode([ref(0, dt.INT64)], aggs, plan,
                            grouping_names=["k"])
    df = execute_cpu(plan).to_pandas().sort_values("k").reset_index(
        drop=True)
    assert list(df["k"]) == [1, 2]
    assert list(df["s"]) == [9.0, 6.0]
    assert list(df["c"]) == [3, 2]
    assert list(df["a"]) == [3.0, 3.0]


def test_groupby_null_keys_group_together():
    plan = scan({"k": np.array([1, 1, 2], dtype=np.int64),
                 "v": np.array([5.0, 6.0, 7.0])},
                validity={"k": np.array([False, False, True])})
    aggs = [pn.AggCall(Sum(ref(1, dt.FLOAT64)), "s")]
    plan = pn.AggregateNode([ref(0, dt.INT64)], aggs, plan)
    df = execute_cpu(plan).to_pandas()
    assert len(df) == 2
    assert set(df["s"]) == {11.0, 7.0}


def test_partial_final_split_matches_complete():
    data = {"k": np.array([1, 2, 1, 3, 2, 1], dtype=np.int64),
            "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0, np.nan])}
    aggs = [pn.AggCall(Sum(ref(1, dt.FLOAT64)), "s"),
            pn.AggCall(Average(ref(1, dt.FLOAT64)), "a"),
            pn.AggCall(Count(), "n")]
    complete = pn.AggregateNode([ref(0, dt.INT64)], aggs, scan(data))
    partial = pn.AggregateNode([ref(0, dt.INT64)], aggs, scan(data),
                               mode="partial")
    final = pn.AggregateNode(
        [ref(0, dt.INT64)],
        aggs, partial, mode="final")
    a = execute_cpu(complete).to_pandas().sort_values("col0")
    b = execute_cpu(final).to_pandas().sort_values("col0")
    np.testing.assert_array_equal(a["s"].to_numpy(np.float64),
                                  b["s"].to_numpy(np.float64))
    np.testing.assert_array_equal(a["n"].to_numpy(), b["n"].to_numpy())


def test_global_agg_empty_input():
    plan = scan({"v": np.array([], dtype=np.float64)})
    aggs = [pn.AggCall(Count(), "n"), pn.AggCall(Sum(ref(0, dt.FLOAT64)),
                                                 "s")]
    plan = pn.AggregateNode([], aggs, plan)
    df = execute_cpu(plan).to_pandas()
    assert len(df) == 1
    assert df["n"][0] == 0
    assert df["s"][0] is None  # SUM over empty input is NULL, not NaN


def test_sort_nulls_and_nan():
    plan = scan({"v": np.array([3.0, np.nan, 1.0, 2.0])},
                validity={"v": np.array([True, True, True, False])})
    plan = pn.SortNode([SortKeySpec.spark_default(0, ascending=True)], plan)
    out = execute_cpu(plan)
    v = out.cols[0]
    # ASC NULLS FIRST, NaN greatest
    assert not v.valid_mask()[0]
    assert v.data[1] == 1.0
    assert v.data[2] == 3.0
    assert np.isnan(v.data[3])


def test_sort_desc():
    plan = scan({"v": np.array([3, 1, 2], dtype=np.int64)})
    plan = pn.SortNode([SortKeySpec.spark_default(0, ascending=False)],
                       plan)
    out = execute_cpu(plan)
    assert list(out.cols[0].data) == [3, 2, 1]


@pytest.mark.parametrize("kind,expected", [
    ("inner", {(1, 10.0, 1, "a"), (2, 20.0, 2, "b")}),
    ("left_semi", {(1, 10.0), (2, 20.0)}),
    ("left_anti", {(3, 30.0), (4, None)}),
])
def test_joins(kind, expected):
    left = scan({"k": np.array([1, 2, 3, 4], dtype=np.int64),
                 "v": np.array([10.0, 20.0, 30.0, 40.0])},
                validity={"k": np.array([True, True, True, False])})
    right = scan({"k2": np.array([1, 2, 5], dtype=np.int64),
                  "s": np.array(["a", "b", "c"], dtype=object)})
    plan = pn.JoinNode(kind, left, right, [0], [0])
    df = execute_cpu(plan).to_pandas()
    got = set()
    for _, row in df.iterrows():
        vals = tuple(None if row.isna()[c] else row[c] for c in df.columns)
        got.add(vals)
    if kind == "left_anti":
        # row 4's key is null -> never matches -> kept with its null key
        assert got == {(3, 30.0), (None, 40.0)}
    elif kind == "left_semi":
        assert got == {(1, 10.0), (2, 20.0)}
    else:
        assert got == expected


def test_left_join_pads_nulls():
    left = scan({"k": np.array([1, 9], dtype=np.int64)})
    right = scan({"k2": np.array([1], dtype=np.int64),
                  "w": np.array([100], dtype=np.int64)})
    plan = pn.JoinNode("left", left, right, [0], [0])
    df = execute_cpu(plan).to_pandas().sort_values("k")
    assert df["w"].tolist()[0] == 100
    assert df["w"].isna().tolist() == [False, True]


def test_join_condition():
    left = scan({"k": np.array([1, 1], dtype=np.int64),
                 "v": np.array([5, 50], dtype=np.int64)})
    right = scan({"k2": np.array([1], dtype=np.int64),
                  "w": np.array([10], dtype=np.int64)})
    cond = GreaterThan(ref(3, dt.INT64), ref(1, dt.INT64))  # w > v
    plan = pn.JoinNode("inner", left, right, [0], [0], condition=cond)
    df = execute_cpu(plan).to_pandas()
    assert len(df) == 1
    assert df["v"][0] == 5


def test_union_limit():
    a = scan({"x": np.array([1, 2], dtype=np.int64)})
    b = scan({"x": np.array([3, 4], dtype=np.int64)})
    plan = pn.LimitNode(3, pn.UnionNode([a, b]))
    df = execute_cpu(plan).to_pandas()
    assert df["x"].tolist() == [1, 2, 3]


def test_window_row_number_and_running_sum():
    plan = scan({"p": np.array([1, 1, 1, 2, 2], dtype=np.int64),
                 "o": np.array([3, 1, 2, 2, 1], dtype=np.int64),
                 "v": np.array([1.0, 2.0, 3.0, 4.0, 5.0])})
    calls = [pn.WindowCall("row_number", "rn"),
             pn.WindowCall(Sum(ref(2, dt.FLOAT64)), "rs",
                           frame=pn.WindowFrame(None, 0)),
             pn.WindowCall(("lag", ref(1, dt.INT64)), "lg")]
    plan = pn.WindowNode([0], [SortKeySpec.spark_default(1)], calls, plan)
    df = execute_cpu(plan).to_pandas()
    # partition 1 ordered by o: rows with o=1,2,3 -> v=2,3,1
    p1 = df[df["p"] == 1].sort_values("o")
    assert p1["rn"].tolist() == [1, 2, 3]
    assert p1["rs"].tolist() == [2.0, 5.0, 6.0]
    assert p1["lg"].isna().tolist() == [True, False, False]
    assert p1["lg"].tolist()[1:] == [1, 2]


def test_expand():
    plan = scan({"a": np.array([1, 2], dtype=np.int64)})
    projections = [[ref(0, dt.INT64), Literal(0)],
                   [ref(0, dt.INT64), Literal(1)]]
    plan = pn.ExpandNode(projections, plan, ["a", "tag"])
    df = execute_cpu(plan).to_pandas()
    assert len(df) == 4
    assert set(zip(df["a"], df["tag"])) == {(1, 0), (1, 1), (2, 0), (2, 1)}


def test_range():
    df = execute_cpu(pn.RangeNode(0, 10, 3)).to_pandas()
    assert df["id"].tolist() == [0, 3, 6, 9]


def test_min_max_nan_semantics():
    plan = scan({"k": np.array([1, 1, 2], dtype=np.int64),
                 "v": np.array([np.nan, 1.0, np.nan])})
    aggs = [pn.AggCall(Min(ref(1, dt.FLOAT64)), "lo"),
            pn.AggCall(Max(ref(1, dt.FLOAT64)), "hi")]
    plan = pn.AggregateNode([ref(0, dt.INT64)], aggs, plan,
                            grouping_names=["k"])
    df = execute_cpu(plan).to_pandas().sort_values("k").reset_index(
        drop=True)
    # Spark: NaN is greatest -> min avoids NaN, max picks it
    assert df["lo"][0] == 1.0
    assert np.isnan(df["hi"][0])
    assert np.isnan(df["lo"][1]) and np.isnan(df["hi"][1])


def test_divide_by_zero_null():
    plan = scan({"a": np.array([1.0, 2.0]),
                 "b": np.array([0.0, 2.0])})
    plan = pn.ProjectNode(
        [Alias(Divide(ref(0, dt.FLOAT64), ref(1, dt.FLOAT64)), "q")], plan)
    df = execute_cpu(plan).to_pandas()
    assert df["q"][0] is None  # Spark Divide: x/0 is NULL
    assert df["q"][1] == 1.0


def test_cast_string_roundtrip():
    plan = scan({"s": np.array(["12", "x", "7"], dtype=object)})
    plan = pn.ProjectNode(
        [Alias(Cast(ref(0, dt.STRING), dt.INT64), "i")], plan)
    out = execute_cpu(plan)
    c = out.cols[0]
    assert c.data[0] == 12 and c.data[2] == 7
    assert not c.valid_mask()[1]
