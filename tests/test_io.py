"""I/O layer tests: parquet/ORC/CSV scans, pruning, pushdown, writes.

Model: the reference's parquet_test.py / orc_test.py / csv_test.py
round-trips plus the Scala GpuParquetScan row-group filter behavior —
always CPU-engine-as-oracle (SURVEY.md §4).
"""
import datetime

import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.cpu.engine import execute_cpu
from spark_rapids_tpu.execs.base import collect
from spark_rapids_tpu.expressions.base import BoundReference, Literal
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.io import (CsvSource, OrcSource, ParquetSource,
                                 WriteFilesNode)
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.overrides import apply_overrides

from tests.compare import assert_cpu_and_tpu_equal, assert_frames_equal


def _mixed_table(n=1000, seed=3):
    rng = np.random.default_rng(seed)
    ints = rng.integers(-1000, 1000, n).astype(np.int64)
    floats = rng.random(n) * 100
    bools = rng.random(n) > 0.5
    strs = [None if rng.random() < 0.1 else f"s{int(v) % 50}"
            for v in ints]
    dates = [datetime.date(2020, 1, 1) + datetime.timedelta(days=int(d))
             for d in rng.integers(0, 365, n)]
    ts = [datetime.datetime(2021, 5, 1, tzinfo=datetime.timezone.utc)
          + datetime.timedelta(seconds=int(s))
          for s in rng.integers(0, 86400, n)]
    null_at = rng.random(n) < 0.08
    return pa.table({
        "i": pa.array(ints, mask=null_at),
        "f": pa.array(floats),
        "b": pa.array(bools),
        "s": pa.array(strs, type=pa.string()),
        "d": pa.array(dates, type=pa.date32()),
        "t": pa.array(ts, type=pa.timestamp("us", tz="UTC")),
    })


@pytest.fixture()
def pq_file(tmp_path):
    path = tmp_path / "data.parquet"
    pq.write_table(_mixed_table(), path, row_group_size=100)
    return str(path)


def test_parquet_scan_matches_cpu_oracle(pq_file):
    plan = pn.ScanNode(ParquetSource(pq_file))
    assert_cpu_and_tpu_equal(plan)


def test_parquet_schema_and_projection(pq_file):
    src = ParquetSource(pq_file, columns=["f", "i"])
    s = src.schema()
    assert s.names == ["f", "i"]
    assert s.types == [dt.FLOAT64, dt.INT64]
    plan = pn.ScanNode(src)
    assert_cpu_and_tpu_equal(plan)


def test_parquet_multifile_threadpool(tmp_path):
    for k in range(6):
        pq.write_table(_mixed_table(200, seed=k),
                       tmp_path / f"part-{k}.parquet")
    src = ParquetSource(str(tmp_path))
    # six tiny files PACK into one scan partition (Spark's
    # FilePartition packing under maxPartitionBytes)
    assert src.num_splits() == 1
    unpacked = ParquetSource(str(tmp_path))
    unpacked.pack_splits = False
    assert unpacked.num_splits() == 6
    plan = pn.ScanNode(src)
    exec_ = assert_cpu_and_tpu_equal(plan)
    assert exec_ is not None
    data, _ = src.read_host()  # threaded whole-read path
    assert len(data["i"]) == 1200


def test_parquet_rowgroup_pruning(tmp_path):
    # sorted key -> row-group stats are tight -> pruning must drop groups
    path = tmp_path / "sorted.parquet"
    n = 1000
    t = pa.table({"k": np.arange(n, dtype=np.int64),
                  "v": np.random.default_rng(0).random(n)})
    pq.write_table(t, path, row_group_size=100)
    src = ParquetSource(str(path), filters=[("k", ">=", 800)])
    data, valid = src.read_host()
    assert src.chunks_pruned == 8          # groups [0..799] dropped
    assert data["k"].min() >= 800
    # conservative: kept rows are a superset; exact filter still applies
    assert len(data["k"]) == 200


def test_filter_pushdown_prunes_and_matches(tmp_path):
    path = tmp_path / "sorted.parquet"
    n = 1000
    t = pa.table({"k": np.arange(n, dtype=np.int64),
                  "v": np.random.default_rng(1).random(n)})
    pq.write_table(t, path, row_group_size=100)
    src = ParquetSource(str(path))
    cond = P.And(
        P.GreaterThanOrEqual(BoundReference(0, dt.INT64),
                             Literal(900, dt.INT64)),
        P.LessThan(Literal(980, dt.INT64), BoundReference(0, dt.INT64)))
    plan = pn.FilterNode(cond, pn.ScanNode(src))
    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, RapidsConf(
        {"rapids.tpu.sql.test.enabled": True}))
    tpu_df = collect(exec_)
    assert_frames_equal(cpu_df, tpu_df)
    assert len(tpu_df) == 19  # k in (980, 999]
    # the rewritten scan pruned row groups below k=900
    scans = [e for e in _walk_execs(exec_)
             if type(e).__name__ == "ScanExec"]
    assert scans and scans[0].source.chunks_pruned >= 8


def _walk_execs(e):
    yield e
    for c in e.children:
        yield from _walk_execs(c)


def test_parquet_date_timestamp_pruning_stats(tmp_path):
    path = tmp_path / "dt.parquet"
    days = [datetime.date(2020, 1, 1) + datetime.timedelta(days=i)
            for i in range(100)]
    t = pa.table({"d": pa.array(days, type=pa.date32())})
    pq.write_table(t, path, row_group_size=10)
    cutoff = (datetime.date(2020, 3, 1)
              - datetime.date(1970, 1, 1)).days  # physical int32 days
    src = ParquetSource(str(path), filters=[("d", ">=", cutoff)])
    data, _ = src.read_host()
    assert src.chunks_pruned >= 5
    assert (data["d"] >= cutoff).all()


def test_parquet_scan_disabled_falls_back(pq_file):
    plan = pn.ScanNode(ParquetSource(pq_file))
    conf = RapidsConf(
        {"rapids.tpu.sql.format.parquet.read.enabled": False})
    exec_ = apply_overrides(plan, conf)
    assert type(exec_).__name__ == "CpuFallbackExec"
    # result still correct through the fallback
    cpu_df = execute_cpu(plan).to_pandas()
    assert_frames_equal(cpu_df, collect(exec_))


def test_orc_roundtrip_matches_oracle(tmp_path):
    from pyarrow import orc

    path = tmp_path / "data.orc"
    orc.write_table(_mixed_table(500), str(path))
    plan = pn.ScanNode(OrcSource(str(path)))
    assert_cpu_and_tpu_equal(plan)


def test_csv_scan_with_schema(tmp_path):
    path = tmp_path / "data.csv"
    df = pd.DataFrame({"a": [1, 2, 3, 4], "b": [1.5, 2.5, None, 4.0],
                       "s": ["x", "y", None, "w"]})
    df.to_csv(path, index=False)
    schema = Schema(["a", "b", "s"], [dt.INT64, dt.FLOAT64, dt.STRING])
    src = CsvSource(str(path), schema=schema)
    plan = pn.ScanNode(src)
    assert_cpu_and_tpu_equal(plan)


def test_csv_timestamp_gate(tmp_path):
    """CSV TIMESTAMP compat gate (the reference's csvTimestamps.enabled,
    RapidsConf.scala:482): off -> the scan is refused with a tagging
    reason; on -> only the configured formats parse (as UTC storage)."""
    path = tmp_path / "ts.csv"
    path.write_text("t,v\n2020-01-01T10:00:00,1\n"
                    "2020-01-02 11:30:00,2\n")
    schema = Schema(["t", "v"], [dt.TIMESTAMP, dt.INT64])

    # default (gate off): planner refuses the scan with a reason, and
    # the query still RUNS via the CPU fallback (permissive arrow
    # parsers — the Spark-CPU-semantics stand-in)
    from spark_rapids_tpu.plan.overrides import explain

    plan = pn.ScanNode(CsvSource(str(path), schema=schema))
    assert "csv.read.timestamps.enabled" in explain(plan, RapidsConf())
    fell_back = collect(apply_overrides(plan, RapidsConf()))
    assert len(fell_back) == 2

    # gate on: both default formats parse, values are UTC micros
    conf = RapidsConf({cfg.CSV_TIMESTAMPS_ENABLED.key: True})
    src = CsvSource(str(path), schema=schema, conf=conf)
    out = collect(apply_overrides(pn.ScanNode(src), conf))
    want = [int(pd.Timestamp(x).value) // 1000
            for x in ("2020-01-01 10:00:00", "2020-01-02 11:30:00")]
    assert out["t"].tolist() == want

    # a format outside the configured list fails loudly (FAILFAST),
    # never silently shifts
    bad = tmp_path / "bad.csv"
    bad.write_text("t,v\n01/02/2020 10:00,1\n")
    with pytest.raises(Exception, match="(?i)convert|invalid"):
        CsvSource(str(bad), schema=schema,
                  conf=conf).read_host_split(0)


def test_csv_inferred_schema(tmp_path):
    path = tmp_path / "inf.csv"
    pd.DataFrame({"x": [10, 20], "y": ["a", "b"]}).to_csv(path,
                                                          index=False)
    src = CsvSource(str(path))
    assert src.schema().names == ["x", "y"]
    assert_cpu_and_tpu_equal(pn.ScanNode(src))


@pytest.mark.parametrize("fmt", ["parquet", "orc"])
def test_write_roundtrip(tmp_path, fmt):
    pq.write_table(_mixed_table(300), tmp_path / "in.parquet")
    out_tpu = tmp_path / "out_tpu"
    scan = pn.ScanNode(ParquetSource(str(tmp_path / "in.parquet")))
    node = pn.PlanNode  # noqa  (clarity)
    write = WriteFilesNode(scan, str(out_tpu), format=fmt)
    exec_ = apply_overrides(write, RapidsConf(
        {"rapids.tpu.sql.test.enabled": True}))
    stats = collect(exec_)
    assert stats["num_rows"].astype(int).sum() == 300
    # read back what the TPU path wrote and compare against the input
    back = pn.ScanNode(ParquetSource(str(out_tpu)) if fmt == "parquet"
                       else OrcSource(str(out_tpu)))
    orig = pn.ScanNode(ParquetSource(str(tmp_path / "in.parquet")))
    assert_frames_equal(execute_cpu(orig).to_pandas(),
                        execute_cpu(back).to_pandas())


def test_write_partitioned_layout(tmp_path):
    import os

    src_path = tmp_path / "in.parquet"
    t = pa.table({"k": pa.array([0, 0, 1, 1, 2], type=pa.int64()),
                  "v": pa.array([1.0, 2.0, 3.0, 4.0, 5.0])})
    pq.write_table(t, src_path)
    out = tmp_path / "out_part"
    write = WriteFilesNode(pn.ScanNode(ParquetSource(str(src_path))),
                           str(out), format="parquet",
                           partition_by=["k"])
    stats = collect(apply_overrides(write, RapidsConf(
        {"rapids.tpu.sql.test.enabled": True})))
    dirs = sorted(d for d in os.listdir(out) if d.startswith("k="))
    assert dirs == ["k=0", "k=1", "k=2"]
    assert stats["num_rows"].astype(int).sum() == 5
    # partition column removed from the data files — check the file's
    # PHYSICAL schema: pq.read_table would re-infer `k` from the hive
    # path (pyarrow >= 15 turns on hive partitioning for single files)
    sub = pq.ParquetFile(
        os.path.join(out, "k=0", os.listdir(out / "k=0")[0]))
    assert sub.schema_arrow.names == ["v"]


def test_write_cpu_oracle_agrees(tmp_path):
    src_path = tmp_path / "in.parquet"
    pq.write_table(_mixed_table(200, seed=9), src_path)
    scan = pn.ScanNode(ParquetSource(str(src_path)))
    out_tpu = str(tmp_path / "w_tpu")
    out_cpu = str(tmp_path / "w_cpu")
    collect(apply_overrides(WriteFilesNode(scan, out_tpu),
                            RapidsConf(
                                {"rapids.tpu.sql.test.enabled": True})))
    execute_cpu(WriteFilesNode(scan, out_cpu))
    a = execute_cpu(pn.ScanNode(ParquetSource(out_tpu))).to_pandas()
    b = execute_cpu(pn.ScanNode(ParquetSource(out_cpu))).to_pandas()
    assert_frames_equal(a, b)


def test_write_disabled_falls_back(tmp_path, pq_file):
    write = WriteFilesNode(pn.ScanNode(ParquetSource(pq_file)),
                           str(tmp_path / "o"))
    conf = RapidsConf(
        {"rapids.tpu.sql.format.parquet.write.enabled": False})
    exec_ = apply_overrides(write, conf)
    assert type(exec_).__name__ == "CpuFallbackExec"
    stats = collect(exec_)
    assert stats["num_rows"].astype(int).sum() == 1000


def test_full_pipeline_on_files(tmp_path):
    """scan -> filter -> aggregate over parquet (the §3.3 hot path)."""
    from spark_rapids_tpu.expressions import aggregates as A

    path = tmp_path / "agg.parquet"
    pq.write_table(_mixed_table(2000, seed=11), path)
    scan = pn.ScanNode(ParquetSource(str(path)))
    cond = P.GreaterThan(BoundReference(1, dt.FLOAT64),
                         Literal(50.0, dt.FLOAT64))
    filt = pn.FilterNode(cond, scan)
    agg = pn.AggregateNode(
        [BoundReference(3, dt.STRING)],
        [pn.AggCall(A.Sum(BoundReference(1, dt.FLOAT64)), "sum_f"),
         pn.AggCall(A.Count(BoundReference(0, dt.INT64)), "cnt_i")],
        filt)
    assert_cpu_and_tpu_equal(agg, approx_float=1e-6)


def test_csv_delimiter_and_multifile(tmp_path):
    for k in range(3):
        with open(tmp_path / f"f{k}.csv", "w") as f:
            f.write("a|b\n")
            for i in range(5):
                f.write(f"{k * 10 + i}|x{i}\n")
    schema = Schema(["a", "b"], [dt.INT64, dt.STRING])
    src = CsvSource(str(tmp_path), schema=schema, delimiter="|")
    # tiny files pack into one partition; raw splits stay per-file
    assert src.num_splits() == 1
    unpacked = CsvSource(str(tmp_path), schema=schema, delimiter="|")
    unpacked.pack_splits = False
    assert unpacked.num_splits() == 3
    plan = pn.ScanNode(src)
    assert_cpu_and_tpu_equal(plan)


def test_orc_projection(tmp_path):
    from pyarrow import orc

    orc.write_table(_mixed_table(200), str(tmp_path / "d.orc"))
    src = OrcSource(str(tmp_path / "d.orc"), columns=["f", "b"])
    assert src.schema().names == ["f", "b"]
    assert_cpu_and_tpu_equal(pn.ScanNode(src))


def test_session_runtime_lifecycle(tmp_path):
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.memory import semaphore as sem
    from spark_rapids_tpu.memory.catalog import get_catalog

    s = Session({"rapids.tpu.memory.spillDir": str(tmp_path),
                 "rapids.tpu.sql.concurrentTpuTasks": 3},
                initialize_runtime=True)
    try:
        assert s.runtime is not None
        assert s.runtime.catalog is get_catalog()
        # conf actually reached the global wiring
        assert sem.get()._max == 3
        assert get_catalog()._spill_dir == str(tmp_path)
        # a second runtime-owning Session must be refused while this
        # one is alive (the runtime is process-global)
        import pytest as _pytest

        with _pytest.raises(RuntimeError, match="owns the runtime"):
            Session({}, initialize_runtime=True)
        assert s.create_dataframe({"x": [1, 2, 3]}).count() == 3
    finally:
        s.stop()
    assert s.runtime is None
    # after stop, a new owner may initialize
    s2 = Session({}, initialize_runtime=True)
    s2.stop()


def test_parquet_debug_dump(tmp_path, pq_file):
    import os

    dump = tmp_path / "dump"
    src = ParquetSource(pq_file, conf=RapidsConf(
        {"rapids.tpu.sql.parquet.debug.dumpPrefix": str(dump)}))
    src.read_host()
    assert os.listdir(dump) == ["data.parquet"]


def test_orc_stripe_statistics_pushdown(tmp_path):
    """Stripe-level min/max pruning (OrcFilters.scala:206 analogue, read
    from the ORC tail directly): a filter outside a stripe's range drops
    the stripe before any read; surviving stripes feed Column.stats."""
    import pyarrow as pa
    from pyarrow import orc

    from spark_rapids_tpu.io.orc_meta import stripe_statistics

    path = str(tmp_path / "t.orc")
    # 4 stripes with disjoint k ranges (tiny stripe size forces splits)
    ks = np.arange(0, 40_000, dtype=np.int64)
    vs = (ks % 97).astype(np.float64)
    orc.write_table(pa.table({"k": ks, "v": vs}), path,
                    stripe_size=64 << 10)
    f = orc.ORCFile(path)
    assert f.nstripes > 2, f.nstripes

    stats = stripe_statistics(path, ["k", "v"])
    assert stats is not None and len(stats) == f.nstripes
    lo0, hi0, _ = stats[0]["k"]
    assert lo0 == 0 and hi0 < 40_000

    # filter selecting only the LAST stripe's range
    lo_last = stats[-1]["k"][0]
    src = OrcSource(str(path), filters=[("k", ">=", int(lo_last))])
    src.splits()
    assert src.chunks_pruned >= f.nstripes - 1
    got = pn.ScanNode(src)
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.plan.overrides import apply_overrides

    df = collect(apply_overrides(got))
    assert sorted(df["k"].tolist()) == list(range(int(lo_last), 40_000))

    # surviving stripes feed Column.stats (packed-key groupby path)
    st = src.split_stats(0)
    assert st is not None and st["k"][0] >= int(lo_last)


def test_orc_stats_map_by_file_schema_under_projection(tmp_path):
    """Column projection must not shift which physical column a name's
    stats come from (r3 review: positional mapping attributed k's range
    to v and pruned stripes that DID match)."""
    import pyarrow as pa
    from pyarrow import orc

    path = str(tmp_path / "p.orc")
    ks = np.arange(0, 40_000, dtype=np.int64)
    vs = (ks % 97).astype(np.float64)
    orc.write_table(pa.table({"k": ks, "v": vs}), path,
                    stripe_size=64 << 10)
    src = OrcSource(str(path), columns=["v"],
                    filters=[("v", "<", 0.5)])
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.plan.overrides import apply_overrides

    # v spans 0..96 in EVERY stripe, so nothing may be pruned (the
    # positional-mapping bug attributed k's disjoint ranges to v and
    # pruned all but the first stripe); source filters prune chunks
    # only — row filtering is the Filter node's job
    df = collect(apply_overrides(pn.ScanNode(src)))
    assert src.chunks_pruned == 0
    assert len(df) == 40_000


def test_orc_debug_dump_and_row_estimate(tmp_path):
    import os

    from pyarrow import orc

    path = tmp_path / "data.orc"
    orc.write_table(_mixed_table(400), str(path))
    dump = tmp_path / "dump"
    src = OrcSource(str(path), conf=RapidsConf(
        {"rapids.tpu.sql.orc.debug.dumpPrefix": str(dump)}))
    assert src.estimated_row_count() == 400
    src.read_host()
    assert os.listdir(dump) == ["data.orc"]


def test_parquet_row_estimate(pq_file):
    src = ParquetSource(pq_file)
    est = src.estimated_row_count()
    assert est is not None and est > 0


# ----------------------------------------------- transfer packing round-trip

def test_transfer_packing_roundtrip_exact():
    """Packed uploads (narrow string codes, offset-narrowed ints,
    scaled-decimal f64, bit-packed validity) must decode on device to
    EXACTLY the full-width upload's values — bit-identical f64, same
    nulls. Mixed with a non-packable f64 column (NaN + irrational) that
    must fall back to raw."""
    import jax
    import numpy as np

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import Schema
    from spark_rapids_tpu.execs.interop import (_PACK_MIN_ROWS,
                                                host_to_batch)
    from spark_rapids_tpu.io.hoststrings import HostStrings

    rng = np.random.default_rng(3)
    n = _PACK_MIN_ROWS
    money = np.round(rng.uniform(0, 5000, n), 2)       # ~all distinct: raw
    qty = rng.integers(1, 51, n).astype(np.float64)    # 50 values: fdict
    raw_f = rng.normal(0, 1, n)                        # not packable
    raw_f[7] = np.nan
    ints = (rng.integers(0, 1200, n) + 2_450_000).astype(np.int64)
    iv = rng.random(n) > 0.3
    scodes = rng.integers(0, 3, n).astype(np.int32)
    sdict = np.asarray(["a", "bb", "ccc"], dtype=object)
    sv = rng.random(n) > 0.1
    schema = Schema(["m", "q", "r", "i", "s"],
                    [dt.FLOAT64, dt.FLOAT64, dt.FLOAT64, dt.INT64,
                     dt.STRING])
    data = {"m": money, "q": qty, "r": raw_f, "i": ints,
            "s": HostStrings(scodes, sdict)}
    validity = {"m": None, "q": None, "r": None, "i": iv, "s": sv}
    stats = {"i": (2_450_000, 2_451_199)}

    packed = host_to_batch(data, validity, schema, stats=stats,
                           pack=True)
    full = host_to_batch(data, validity, schema, stats=stats,
                         pack=False)
    for cp, cf, name in zip(packed.columns, full.columns, schema.names):
        dp = np.asarray(jax.device_get(cp.data))[:n]
        df_ = np.asarray(jax.device_get(cf.data))[:n]
        if name == "r":
            np.testing.assert_array_equal(
                dp.view(np.uint64), df_.view(np.uint64), err_msg=name)
        elif name in ("m", "q"):
            # bit-identical decode is the contract
            np.testing.assert_array_equal(
                dp.view(np.uint64), df_.view(np.uint64), err_msg=name)
        else:
            np.testing.assert_array_equal(dp, df_, err_msg=name)
        vp = None if cp.validity is None else \
            np.asarray(jax.device_get(cp.validity))[:n]
        vf = None if cf.validity is None else \
            np.asarray(jax.device_get(cf.validity))[:n]
        assert (vp is None) == (vf is None), name
        if vp is not None:
            np.testing.assert_array_equal(vp, vf, err_msg=name)
    # the narrow columns really were narrow on the wire: int span 1200
    # fits u16, money span <= 500000 fits u32, qty fits u8, codes u8
    from spark_rapids_tpu.execs import interop as it

    assert it._narrow_uint(1199) is np.uint16
    assert it._narrow_uint(50) is np.uint8
    assert it._pack_fdict(qty, None) is not None      # 50 distinct values
    assert it._pack_fdict(raw_f, None) is None        # ~all distinct
