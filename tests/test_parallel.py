"""Distributed shuffle + groupby over the virtual 8-device CPU mesh,
checked against a pandas oracle (the CPU-as-oracle methodology of
SURVEY.md §4 applied to the multi-chip path)."""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.ops.groupby import AggSpec
from spark_rapids_tpu.parallel import (
    DistributedGroupByStep,
    data_mesh,
    distributed_batch_from_host,
    gather_distributed_result,
)


def run_distributed_groupby(keys, vals, key_valid=None, n_dev=8,
                            aggs=None):
    mesh = data_mesh(n_dev)
    aggs = aggs or [AggSpec("sum", 1), AggSpec("count", 1),
                    AggSpec("count_star")]
    dtypes = [dt.INT64, dt.FLOAT64]
    datas, valids, counts, cap = distributed_batch_from_host(
        mesh, [keys, vals], dtypes, validities=[key_valid, None])
    step = DistributedGroupByStep(mesh, dtypes, [0], aggs)
    od, ov, ng = step(datas, valids, counts)
    return gather_distributed_result(od, ov, ng, step.output_dtypes(), n_dev)


def test_distributed_groupby_matches_pandas():
    rng = np.random.default_rng(42)
    n = 5000
    keys = rng.integers(0, 37, n).astype(np.int64)
    vals = rng.normal(size=n)
    out = run_distributed_groupby(keys, vals)
    df = out.to_pandas()
    got = df.sort_values(df.columns[0]).reset_index(drop=True)

    oracle = (pd.DataFrame({"k": keys, "v": vals})
              .groupby("k", as_index=False)
              .agg(s=("v", "sum"), c=("v", "count"), n=("v", "size"))
              .sort_values("k").reset_index(drop=True))
    assert len(got) == len(oracle)
    np.testing.assert_array_equal(got.iloc[:, 0].to_numpy(np.int64),
                                  oracle["k"].to_numpy())
    np.testing.assert_allclose(got.iloc[:, 1].to_numpy(np.float64),
                               oracle["s"].to_numpy(), rtol=1e-12)
    np.testing.assert_array_equal(got.iloc[:, 2].to_numpy(np.int64),
                                  oracle["c"].to_numpy())
    np.testing.assert_array_equal(got.iloc[:, 3].to_numpy(np.int64),
                                  oracle["n"].to_numpy())


def test_distributed_groupby_null_keys_group_together():
    rng = np.random.default_rng(7)
    n = 1000
    keys = rng.integers(0, 5, n).astype(np.int64)
    key_valid = rng.random(n) > 0.3
    vals = np.ones(n)
    out = run_distributed_groupby(keys, vals, key_valid=key_valid)
    df = out.to_pandas()
    # exactly one null group holding all null-key rows
    kcol, ccol = df.columns[0], df.columns[3]
    null_rows = df[df[kcol].isna()]
    assert len(null_rows) == 1
    assert int(null_rows[ccol].iloc[0]) == int((~key_valid).sum())
    assert int(df[ccol].sum()) == n


def test_distributed_groupby_skewed_single_key():
    # all rows one key: worst-case routing skew must still be exact
    n = 3000
    keys = np.full(n, 11, dtype=np.int64)
    vals = np.arange(n, dtype=np.float64)
    out = run_distributed_groupby(keys, vals)
    df = out.to_pandas()
    assert len(df) == 1
    assert df.iloc[0, 0] == 11
    assert df.iloc[0, 1] == vals.sum()


def test_distributed_groupby_empty_input():
    out = run_distributed_groupby(np.zeros(0, dtype=np.int64),
                                  np.zeros(0, dtype=np.float64))
    assert out.realized_num_rows() == 0


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_distributed_groupby_mesh_sizes(n_dev):
    rng = np.random.default_rng(n_dev)
    n = 800
    keys = rng.integers(0, 13, n).astype(np.int64)
    vals = rng.random(n)
    out = run_distributed_groupby(keys, vals, n_dev=n_dev)
    df = out.to_pandas()
    assert len(df) == len(np.unique(keys))
    np.testing.assert_allclose(sorted(df.iloc[:, 1]), sorted(
        pd.DataFrame({"k": keys, "v": vals}).groupby("k")["v"].sum()),
        rtol=1e-12)


def test_distributed_dim_join(n_virtual_devices):
    """Broadcast dim join on the mesh: fact row-sharded, dim replicated,
    per-chip binary-search probe; validated against pandas merge."""
    import jax
    import pandas as pd

    from spark_rapids_tpu.parallel import shuffle as psh
    from spark_rapids_tpu.parallel.join_step import (
        DistributedDimJoinStep, replicate_dim)
    from spark_rapids_tpu.parallel.mesh import data_mesh

    mesh = data_mesh(8)
    rng = np.random.default_rng(17)
    n = 4000
    fact_k = rng.integers(0, 64, n).astype(np.int64)
    fact_v = rng.random(n)
    fk_valid = rng.random(n) > 0.05
    dim_k = np.arange(0, 50, dtype=np.int64)  # unique keys, some misses
    dim_w = (dim_k * 10).astype(np.float64)

    datas, valids, counts, cap = psh.distributed_batch_from_host(
        mesh, [fact_k, fact_v], [dt.INT64, dt.FLOAT64],
        validities=[fk_valid, None])
    d_datas, d_valids = replicate_dim(mesh, [dim_k, dim_w],
                                      [dt.INT64, dt.FLOAT64])
    step = DistributedDimJoinStep(mesh, (dt.INT64, dt.FLOAT64),
                                  (dt.INT64, dt.FLOAT64),
                                  fact_key=0, dim_key=0)
    out_d, out_v, hit, cnts = step(datas, valids, counts,
                                   d_datas, d_valids)
    # collect matched rows host-side
    hit_h = np.asarray(jax.device_get(hit))
    k_h = np.asarray(jax.device_get(out_d[0]))
    v_h = np.asarray(jax.device_get(out_d[1]))
    w_h = np.asarray(jax.device_get(out_d[2]))
    got = pd.DataFrame({"k": k_h[hit_h], "v": v_h[hit_h],
                        "w": w_h[hit_h]}).sort_values(
        ["k", "v"]).reset_index(drop=True)
    exp = (pd.DataFrame({"k": fact_k[fk_valid], "v": fact_v[fk_valid]})
           .merge(pd.DataFrame({"k": dim_k, "w": dim_w}), on="k")
           .sort_values(["k", "v"]).reset_index(drop=True))
    assert len(got) == len(exp)
    np.testing.assert_array_equal(got["k"], exp["k"])
    np.testing.assert_allclose(got["v"], exp["v"])
    np.testing.assert_allclose(got["w"], exp["w"])
    assert int(np.asarray(jax.device_get(cnts)).sum()) == len(exp)
