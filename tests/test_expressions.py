"""Expression layer tests vs Spark SQL semantics (nulls, 3VL, div-by-zero,
java remainder, date math) — pandas/python is the oracle where applicable."""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, Scalar, StringColumn
from spark_rapids_tpu.expressions import (
    Abs, Add, Alias, And, BoundReference, CaseWhen, Cast, Coalesce,
    CompiledFilter, CompiledProjection, Divide, EqualNullSafe, EqualTo,
    GreaterThan, If, In, IntegralDivide, IsNaN, IsNotNull, IsNull,
    LessThan, Literal, Multiply, NaNvl, Not, Or, Remainder, Subtract,
)
from spark_rapids_tpu.expressions import datetime as dtexpr
from spark_rapids_tpu.expressions import math as mexpr
from spark_rapids_tpu.expressions import strings as sexpr
from spark_rapids_tpu.expressions.base import EvalContext, broadcast


def make_batch(*cols_spec):
    cols = []
    for spec in cols_spec:
        if isinstance(spec, tuple):
            vals, validity = spec
        else:
            vals, validity = spec, None
        if isinstance(vals, list) and any(
                isinstance(v, str) or v is None for v in vals):
            cols.append(StringColumn.from_strings(vals))
        else:
            cols.append(Column.from_numpy(np.asarray(vals),
                                          validity=validity))
    n = len(cols_spec[0][0] if isinstance(cols_spec[0], tuple)
            else cols_spec[0])
    return ColumnarBatch(cols, n)


def run_project(exprs, batch):
    return CompiledProjection(exprs)(batch)


def col_out(batch, i=0):
    n = batch.realized_num_rows()
    return batch.columns[i].to_numpy(n)


def ref(i, t, nullable=True):
    return BoundReference(i, t, nullable)


def test_fused_arithmetic_pipeline():
    b = make_batch(np.array([1.0, 2.0, 3.0]),
                   np.array([10.0, 20.0, 30.0]))
    e = Add(Multiply(ref(0, dt.FLOAT64), Literal(2.0)), ref(1, dt.FLOAT64))
    proj = CompiledProjection([e])
    assert proj.fused
    out = proj(b)
    vals, _ = col_out(out)
    np.testing.assert_allclose(vals, [12.0, 24.0, 36.0])


def test_null_propagation_binary():
    b = make_batch((np.array([1.0, 2.0]), np.array([True, False])))
    out = run_project([Add(ref(0, dt.FLOAT64), Literal(1.0))], b)
    vals, v = col_out(out)
    assert vals[0] == 2.0
    assert v is not None and not v[1]


def test_divide_by_zero_is_null():
    b = make_batch(np.array([4.0, 9.0]), np.array([2.0, 0.0]))
    out = run_project([Divide(ref(0, dt.FLOAT64), ref(1, dt.FLOAT64))], b)
    vals, v = col_out(out)
    assert vals[0] == 2.0
    assert v is not None and not v[1]


def test_integral_divide_truncates_toward_zero():
    b = make_batch(np.array([-7, 7, -7], dtype=np.int64),
                   np.array([2, 2, -2], dtype=np.int64))
    out = run_project([IntegralDivide(ref(0, dt.INT64), ref(1, dt.INT64))], b)
    vals, _ = col_out(out)
    np.testing.assert_array_equal(vals, [-3, 3, 3])  # java semantics


def test_remainder_java_sign():
    b = make_batch(np.array([-7, 7], dtype=np.int64),
                   np.array([3, -3], dtype=np.int64))
    out = run_project([Remainder(ref(0, dt.INT64), ref(1, dt.INT64))], b)
    vals, _ = col_out(out)
    np.testing.assert_array_equal(vals, [-1, 1])  # sign of dividend


def test_and_or_three_valued_logic():
    t = np.array([True, False, True, False])
    validity = np.array([True, True, False, False])
    b = make_batch((t, validity), np.array([True, True, True, True]))
    # false AND null = false; null AND true = null
    out = run_project([And(ref(0, dt.BOOLEAN), ref(1, dt.BOOLEAN))], b)
    vals, v = col_out(out)
    assert vals[0] and not vals[1]
    assert v is not None
    assert v[1]  # false AND true = false, valid
    assert not v[2] and not v[3]  # null AND true = null
    b2 = make_batch((t, validity),
                    np.array([False, False, False, False]))
    out2 = run_project([And(ref(0, dt.BOOLEAN), ref(1, dt.BOOLEAN))], b2)
    _, v2 = col_out(out2)
    assert v2 is None or v2.all()  # x AND false = false (never null)


def test_comparisons_and_filter():
    b = make_batch(np.array([1, 5, 3, 8], dtype=np.int64))
    f = CompiledFilter(GreaterThan(ref(0, dt.INT64), Literal(3)))
    assert f.fused
    out = f(b)
    vals, _ = col_out(out)
    np.testing.assert_array_equal(sorted(vals.tolist()), [5, 8])


def test_is_null_not_null():
    b = make_batch((np.array([1, 2], dtype=np.int64),
                    np.array([True, False])))
    out = run_project([IsNull(ref(0, dt.INT64)),
                       IsNotNull(ref(0, dt.INT64))], b)
    nv, _ = col_out(out, 0)
    nn, _ = col_out(out, 1)
    np.testing.assert_array_equal(nv, [False, True])
    np.testing.assert_array_equal(nn, [True, False])


def test_case_when_with_null_predicate():
    pred_data = np.array([True, False, True])
    pred_valid = np.array([True, True, False])
    b = make_batch((pred_data, pred_valid),
                   np.array([10, 20, 30], dtype=np.int64))
    e = CaseWhen([(ref(0, dt.BOOLEAN), ref(1, dt.INT64))],
                 Literal(-1, dt.INT64))
    out = run_project([e], b)
    vals, v = col_out(out)
    np.testing.assert_array_equal(vals, [10, -1, -1])  # null pred -> else


def test_coalesce():
    b = make_batch((np.array([1, 0], dtype=np.int64),
                    np.array([True, False])),
                   (np.array([5, 7], dtype=np.int64), None))
    out = run_project([Coalesce([ref(0, dt.INT64), ref(1, dt.INT64)])], b)
    vals, v = col_out(out)
    np.testing.assert_array_equal(vals, [1, 7])
    assert v is None or v.all()


def test_nanvl_null_left_stays_null():
    vals = np.array([np.nan, 2.0, 1.0])
    validity = np.array([True, True, False])
    b = make_batch((vals, validity))
    out = run_project([NaNvl(ref(0, dt.FLOAT64), Literal(9.0))], b)
    v, valid = col_out(out)
    assert v[0] == 9.0 and v[1] == 2.0
    assert valid is not None and not valid[2]  # NULL stays NULL


def test_in_with_null_list():
    b = make_batch(np.array([1, 2, 3], dtype=np.int64))
    out = run_project([In(ref(0, dt.INT64), [1, None])], b)
    vals, v = col_out(out)
    assert vals[0]
    assert v is not None and not v[1] and not v[2]  # no-match + null -> null


def test_cast_float_to_int_java_semantics():
    b = make_batch(np.array([1.9, -1.9, np.nan, 1e300]))
    out = run_project([Cast(ref(0, dt.FLOAT64), dt.INT32)], b)
    vals, _ = col_out(out)
    np.testing.assert_array_equal(
        vals, [1, -1, 0, np.iinfo(np.int32).max])


def test_cast_string_to_int_invalid_is_null():
    b = make_batch(["12", "x", " 7 ", "9223372036854775808"])
    out = run_project([Cast(ref(0, dt.STRING), dt.INT64)], b)
    vals, v = col_out(out)
    assert vals[0] == 12 and vals[2] == 7
    assert v is not None and not v[1] and not v[3]


def test_cast_int_to_string():
    b = make_batch(np.array([1, -5], dtype=np.int64))
    out = run_project([Cast(ref(0, dt.INT64), dt.STRING)], b)
    vals, _ = col_out(out)
    assert list(vals) == ["1", "-5"]


def test_date_extracts():
    # 2020-02-29 = 18321 days since epoch
    b = make_batch(np.array([18321, 0], dtype=np.int32))
    b.columns[0].dtype = dt.DATE
    out = run_project([dtexpr.Year(ref(0, dt.DATE)),
                       dtexpr.Month(ref(0, dt.DATE)),
                       dtexpr.DayOfMonth(ref(0, dt.DATE)),
                       dtexpr.DayOfWeek(ref(0, dt.DATE)),
                       dtexpr.LastDay(ref(0, dt.DATE))], b)
    assert col_out(out, 0)[0].tolist() == [2020, 1970]
    assert col_out(out, 1)[0].tolist() == [2, 1]
    assert col_out(out, 2)[0].tolist() == [29, 1]
    # 2020-02-29 was a Saturday (7); 1970-01-01 Thursday (5)
    assert col_out(out, 3)[0].tolist() == [7, 5]
    # last day of feb 2020 = 2020-02-29 = 18321
    assert col_out(out, 4)[0].tolist()[0] == 18321


def test_timestamp_fields():
    us = (13 * 3600 + 45 * 60 + 7) * 1_000_000
    b = make_batch(np.array([us], dtype=np.int64))
    b.columns[0].dtype = dt.TIMESTAMP
    out = run_project([dtexpr.Hour(ref(0, dt.TIMESTAMP)),
                       dtexpr.Minute(ref(0, dt.TIMESTAMP)),
                       dtexpr.Second(ref(0, dt.TIMESTAMP))], b)
    assert col_out(out, 0)[0][0] == 13
    assert col_out(out, 1)[0][0] == 45
    assert col_out(out, 2)[0][0] == 7


def test_string_upper_length_substring():
    b = make_batch(["hello", "World", None])
    out = run_project([sexpr.Upper(ref(0, dt.STRING)),
                       sexpr.Length(ref(0, dt.STRING)),
                       sexpr.Substring(ref(0, dt.STRING), 2, 3)], b)
    up, upv = col_out(out, 0)
    assert list(up) == ["HELLO", "WORLD", None]
    ln, lnv = col_out(out, 1)
    assert ln[0] == 5 and ln[1] == 5 and lnv is not None and not lnv[2]
    sub, _ = col_out(out, 2)
    assert list(sub)[:2] == ["ell", "orl"]


def test_string_predicates_and_like():
    b = make_batch(["apple pie", "banana", "apricot"])
    out = run_project([
        sexpr.StartsWith(ref(0, dt.STRING), "ap"),
        sexpr.Contains(ref(0, dt.STRING), "an"),
        sexpr.Like(ref(0, dt.STRING), "a%t"),
    ], b)
    assert col_out(out, 0)[0].tolist() == [True, False, True]
    assert col_out(out, 1)[0].tolist() == [False, True, False]
    assert col_out(out, 2)[0].tolist() == [False, False, True]


def test_string_comparison_with_scalar_between_codes():
    b = make_batch(["apple", "fig", "zebra"])
    # "cat" is not in the dictionary: between "apple" and "fig"
    out = run_project([LessThan(ref(0, dt.STRING), Literal("cat"))], b)
    vals, _ = col_out(out)
    assert vals.tolist() == [True, False, False]


def test_string_eq_null_scalar_is_null():
    b = make_batch(["None", "x"])
    out = run_project([EqualTo(ref(0, dt.STRING),
                               Literal(None, dt.STRING))], b)
    _, v = col_out(out)
    assert v is not None and not v.any()


def test_string_column_comparison():
    b = make_batch(["b", "a", "c"], ["b", "b", "a"])
    out = run_project([EqualTo(ref(0, dt.STRING), ref(1, dt.STRING)),
                       GreaterThan(ref(0, dt.STRING), ref(1, dt.STRING))], b)
    assert col_out(out, 0)[0].tolist() == [True, False, False]
    assert col_out(out, 1)[0].tolist() == [False, False, True]


def test_concat_strings():
    b = make_batch(["a", None], ["x", "y"])
    out = run_project([sexpr.ConcatStrings(
        [ref(0, dt.STRING), Literal("-"), ref(1, dt.STRING)])], b)
    vals, v = col_out(out)
    assert vals[0] == "a-x"
    assert v is not None and not v[1]


def test_equal_null_safe():
    a = np.array([1, 2, 0], dtype=np.int64)
    av = np.array([True, True, False])
    bvals = np.array([1, 0, 0], dtype=np.int64)
    bv = np.array([True, False, False])
    b = make_batch((a, av), (bvals, bv))
    out = run_project([EqualNullSafe(ref(0, dt.INT64), ref(1, dt.INT64))], b)
    vals, v = col_out(out)
    assert v is None or v.all()
    np.testing.assert_array_equal(vals, [True, False, True])


def test_math_floor_ceil():
    b = make_batch(np.array([1.5, -1.5]))
    out = run_project([mexpr.Floor(ref(0, dt.FLOAT64)),
                       mexpr.Ceil(ref(0, dt.FLOAT64))], b)
    np.testing.assert_array_equal(col_out(out, 0)[0], [1, -2])
    np.testing.assert_array_equal(col_out(out, 1)[0], [2, -1])


def test_if_with_strings():
    pred = np.array([True, False])
    b = make_batch(pred, ["yes", "yes2"], ["no", "no2"])
    e = If(ref(0, dt.BOOLEAN), ref(1, dt.STRING), ref(2, dt.STRING))
    out = run_project([e], b)
    vals, _ = col_out(out)
    assert list(vals) == ["yes", "no2"]


def test_substring_negative_pos_past_start():
    # Spark: substring('abc', -5, 2) = '' (start+len still left of string)
    b = make_batch(["abc", "abcdef"])
    out = run_project([sexpr.Substring(ref(0, dt.STRING), -5, 2),
                       sexpr.Substring(ref(0, dt.STRING), -2, 5)], b)
    v0, _ = col_out(out, 0)
    v1, _ = col_out(out, 1)
    assert list(v0) == ["", "bc"]
    assert list(v1) == ["bc", "ef"]


def test_string_scalar_scalar_comparison():
    from spark_rapids_tpu.expressions import predicates as pexpr
    from spark_rapids_tpu.expressions.base import Literal
    b = make_batch(["x"])
    out = run_project([
        pexpr.EqualTo(Literal("a", dt.STRING), Literal("a", dt.STRING)),
        pexpr.LessThan(Literal("a", dt.STRING), Literal("b", dt.STRING)),
        pexpr.EqualNullSafe(Literal("a", dt.STRING), Literal("b", dt.STRING)),
    ], b)
    assert col_out(out, 0)[0][0]
    assert col_out(out, 1)[0][0]
    assert not col_out(out, 2)[0][0]


def test_inverse_hyperbolic_and_cot():
    vals = np.array([0.3, 1.5, 2.0, -0.4])
    b = make_batch(vals)
    out = run_project(
        [mexpr.Asinh(ref(0, dt.FLOAT64)), mexpr.Acosh(ref(0, dt.FLOAT64)),
         mexpr.Atanh(ref(0, dt.FLOAT64)), mexpr.Cot(ref(0, dt.FLOAT64))],
        b)
    with np.errstate(all="ignore"):
        np.testing.assert_allclose(col_out(out, 0)[0], np.arcsinh(vals))
        np.testing.assert_allclose(col_out(out, 1)[0], np.arccosh(vals))
        np.testing.assert_allclose(col_out(out, 2)[0], np.arctanh(vals))
        np.testing.assert_allclose(col_out(out, 3)[0], 1.0 / np.tan(vals))


def test_logarithm_two_arg():
    b = make_batch(np.array([2.0, 10.0, 3.0]),
                   np.array([8.0, 1000.0, 81.0]))
    out = run_project(
        [mexpr.Logarithm(ref(0, dt.FLOAT64), ref(1, dt.FLOAT64))], b)
    np.testing.assert_allclose(col_out(out)[0], [3.0, 3.0, 4.0],
                               rtol=1e-12)


def test_weekday_vs_dayofweek():
    import jax.numpy as jnp

    # 1970-01-01 (epoch day 0) was a Thursday
    days = jnp.asarray(np.array([0, 1, 2, 3, 4], dtype=np.int32))
    b = ColumnarBatch([Column(dt.DATE, days, None)], 5)
    out = run_project([dtexpr.WeekDay(ref(0, dt.DATE)),
                       dtexpr.DayOfWeek(ref(0, dt.DATE))], b)
    assert list(col_out(out, 0)[0]) == [3, 4, 5, 6, 0]   # Thu=3 Mon-based
    assert list(col_out(out, 1)[0]) == [5, 6, 7, 1, 2]   # Thu=5 Sun-based


def test_time_add_and_to_unix_timestamp():
    import jax.numpy as jnp

    ts = jnp.asarray(np.array([86_400_000_000, 1_000_000],
                              dtype=np.int64))
    b = ColumnarBatch([Column(dt.TIMESTAMP, ts, None)], 2)
    out = run_project(
        [dtexpr.TimeAdd(ref(0, dt.TIMESTAMP),
                        Literal(3_600_000_000, dt.INT64)),
         dtexpr.ToUnixTimestamp(ref(0, dt.TIMESTAMP))], b)
    assert list(col_out(out, 0)[0]) == [90_000_000_000, 3_601_000_000]
    assert list(col_out(out, 1)[0]) == [86_400, 1]


def test_substring_index():
    b = make_batch(["www.apache.org", "a.b", "noseparator", None])
    out = run_project(
        [sexpr.SubstringIndex(ref(0, dt.STRING), ".", 2),
         sexpr.SubstringIndex(ref(0, dt.STRING), ".", -1)], b)
    got2, _ = col_out(out, 0)
    got_1, _ = col_out(out, 1)
    # 'a.b' has one delimiter, so count=2 keeps the whole string (Spark)
    assert list(got2) == ["www.apache", "a.b", "noseparator", None]
    assert list(got_1) == ["org", "b", "noseparator", None]


def test_regexp_replace_simple_pattern():
    b = make_batch(["hello world", "nothing", None])
    out = run_project(
        [sexpr.RegExpReplace(ref(0, dt.STRING), "o", "0")], b)
    got, _ = col_out(out)
    assert list(got) == ["hell0 w0rld", "n0thing", None]


def test_regexp_replace_regex_pattern_falls_back():
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.execs.basic import CpuFallbackExec
    from spark_rapids_tpu.plan import nodes as pn
    from spark_rapids_tpu.plan.overrides import apply_overrides

    plan = pn.ProjectNode(
        [Alias(sexpr.RegExpReplace(ref(0, dt.STRING), "o+", "0"), "r")],
        pn.ScanNode(pn.InMemorySource(
            {"s": np.array(["foo", "oo"], dtype=object)})))
    exec_ = apply_overrides(plan, RapidsConf())
    assert isinstance(exec_, CpuFallbackExec)
    assert any("regex-free" in r for r in exec_.reasons)
    # the oracle-side fallback runs the real regex
    from spark_rapids_tpu.execs.base import collect

    got = collect(exec_)
    assert list(got["r"]) == ["f0", "0"]


def test_normalize_nan_and_zero():
    from spark_rapids_tpu.expressions.constraints import (
        KnownFloatingPointNormalized, NormalizeNaNAndZero)

    vals = np.array([-0.0, 0.0, np.nan, 1.5])
    b = make_batch(vals)
    out = run_project(
        [KnownFloatingPointNormalized(
            NormalizeNaNAndZero(ref(0, dt.FLOAT64)))], b)
    got, _ = col_out(out)
    assert not np.signbit(got[0])  # -0.0 normalized
    assert np.isnan(got[2]) and got[3] == 1.5


def test_fused_kernel_reuse_across_instances():
    """Structurally identical projections/filters share ONE jitted fn
    (fresh per-query plans must not re-trace); different types or
    literals must NOT collide."""
    from spark_rapids_tpu.expressions.compiler import (CompiledFilter,
                                                       CompiledProjection)

    def proj(lit):
        return CompiledProjection(
            [Add(Multiply(ref(0, dt.FLOAT64), Literal(lit)),
                 ref(1, dt.FLOAT64))])

    p1, p2 = proj(2.0), proj(2.0)
    assert p1.fused and p1._jit is p2._jit
    p3 = proj(3.0)
    assert p3._jit is not p1._jit
    # same ordinal, different declared type -> different kernels
    pa = CompiledProjection([Add(ref(0, dt.INT64), Literal(1))])
    pb = CompiledProjection([Add(ref(0, dt.INT32), Literal(1))])
    assert pa._jit is not pb._jit

    f1 = CompiledFilter(GreaterThan(ref(0, dt.FLOAT64), Literal(0.5)))
    f2 = CompiledFilter(GreaterThan(ref(0, dt.FLOAT64), Literal(0.5)))
    assert f1.fused and f1._mask is f2._mask

    # correctness through the shared kernel
    b = make_batch(np.array([1.0, 2.0]), np.array([10.0, 20.0]))
    np.testing.assert_allclose(col_out(p2(b))[0], [12.0, 24.0])
