"""DataFrame API tests: the user-facing surface, oracle-checked against
pandas directly (not just the CPU engine) so the API semantics themselves
are pinned."""
import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.api import Session, col, functions as F, lit, when
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema

from tests.compare import assert_frames_equal


@pytest.fixture()
def session():
    return Session()


@pytest.fixture()
def pdf():
    rng = np.random.default_rng(0)
    n = 400
    return pd.DataFrame({
        "k": rng.integers(0, 10, n),
        "v": rng.random(n) * 100,
        "s": [f"name{int(i) % 4}" for i in rng.integers(0, 100, n)],
    })


@pytest.fixture()
def df(session, pdf):
    return session.create_dataframe(pdf)


def _sorted(df):
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def test_select_and_arithmetic(df, pdf):
    out = df.select("k", (col("v") * 2 + 1).alias("v2")).collect()
    assert list(out.columns) == ["k", "v2"]
    np.testing.assert_allclose(out["v2"].astype(float),
                               pdf["v"] * 2 + 1, rtol=1e-12)


def test_filter_where(df, pdf):
    out = df.filter((col("v") > 50) & (col("k") != 3)).collect()
    expect = pdf[(pdf.v > 50) & (pdf.k != 3)]
    assert len(out) == len(expect)


def test_group_by_agg(df, pdf):
    out = (df.group_by("k")
             .agg(F.sum(col("v")).alias("sv"),
                  F.count("*").alias("n"),
                  F.avg(col("v")).alias("av"))
             .order_by("k").collect())
    expect = pdf.groupby("k").agg(
        sv=("v", "sum"), n=("v", "size"), av=("v", "mean")).reset_index()
    np.testing.assert_allclose(out["sv"].astype(float), expect["sv"],
                               rtol=1e-9)
    assert list(out["n"].astype(int)) == list(expect["n"])


def test_join(session, pdf):
    left = session.create_dataframe(pdf)
    dim = session.create_dataframe(pd.DataFrame(
        {"k2": range(10), "label": [f"L{i}" for i in range(10)]}))
    out = left.join(dim, on=[("k", "k2")], how="inner").collect()
    assert len(out) == len(pdf)
    assert set(out.columns) == {"k", "v", "s", "k2", "label"}


def test_with_column_and_drop(df, pdf):
    out = (df.with_column("flag", when(col("v") > 50, "hi")
                          .otherwise("lo"))
             .drop("s").collect())
    assert list(out.columns) == ["k", "v", "flag"]
    expect = np.where(pdf.v > 50, "hi", "lo")
    assert list(out["flag"]) == list(expect)


def test_order_by_limit(df, pdf):
    out = df.order_by("v", ascending=False).limit(5).collect()
    expect = pdf.sort_values("v", ascending=False).head(5)
    np.testing.assert_allclose(out["v"].astype(float), expect["v"],
                               rtol=1e-12)


def test_distinct_union_count(session):
    a = session.create_dataframe({"x": [1, 2, 2, 3]})
    b = session.create_dataframe({"x": [3, 4]})
    u = a.union(b)
    assert u.count() == 6
    d = sorted(u.distinct().collect()["x"].astype(int))
    assert d == [1, 2, 3, 4]


def test_string_functions(df, pdf):
    out = df.select(
        F.upper(col("s")).alias("u"),
        F.length(col("s")).alias("ln"),
        col("s").contains("3").alias("c3")).collect()
    assert list(out["u"]) == [s.upper() for s in pdf["s"]]
    assert list(out["ln"].astype(int)) == [len(s) for s in pdf["s"]]
    assert list(out["c3"].astype(bool)) == ["3" in s for s in pdf["s"]]


def test_cast_and_between(df, pdf):
    out = df.select(
        col("v").cast(dt.INT64).alias("vi"),
        col("v").between(25, 75).alias("mid")).collect()
    assert list(out["vi"].astype(int)) == [int(v) for v in pdf["v"]]
    assert list(out["mid"].astype(bool)) == \
        [(25 <= v <= 75) for v in pdf["v"]]


def test_nulls_through_api(session):
    pdf = pd.DataFrame({"a": [1.0, None, 3.0], "b": ["x", None, "z"]})
    df = session.create_dataframe(pdf)
    out = df.select(col("a").is_null().alias("an"),
                    F.coalesce(col("a"), lit(-1.0)).alias("af")).collect()
    assert list(out["an"].astype(bool)) == [False, True, False]
    assert [float(v) for v in out["af"]] == [1.0, -1.0, 3.0]


def test_read_write_roundtrip(session, tmp_path, pdf):
    src = tmp_path / "in.parquet"
    pq.write_table(pa.Table.from_pandas(pdf), src)
    df = session.read.parquet(str(src))
    stats = (df.filter(col("v") > 10).write
             .partition_by("k").parquet(str(tmp_path / "out")))
    assert stats["num_rows"].astype(int).sum() == int((pdf.v > 10).sum())
    back = session.read.parquet(str(tmp_path / "out")).collect()
    assert len(back) == int((pdf.v > 10).sum())


def test_explain_reports_plan(df):
    text = df.filter(col("v") > 0).explain()
    assert "Filter" in text and "Scan" in text
    assert text.lstrip().startswith("*"), "plan should be on TPU"


def test_udf_through_api(session):
    df = session.create_dataframe({"x": list(range(20))})
    triple = F.udf(lambda x: x * 3, dt.INT64)
    out = df.select(triple(col("x")).alias("t")).collect()
    assert list(out["t"].astype(int)) == [3 * i for i in range(20)]


def test_range_and_agg_global(session):
    df = session.range(100)
    out = df.agg(F.sum(col("id")).alias("s"),
                 F.count("*").alias("n")).collect()
    assert int(out["s"].iloc[0]) == 4950
    assert int(out["n"].iloc[0]) == 100


def test_api_matches_cpu_engine(df):
    """Whole-pipeline equality through both engines (the reference's
    golden comparison applied to the API layer)."""
    from spark_rapids_tpu.cpu.engine import execute_cpu

    pipeline = (df.filter(col("v") > 20)
                  .with_column("bucket", col("k") % 3)
                  .group_by("bucket")
                  .agg(F.sum(col("v")).alias("sv"),
                       F.max(col("s")).alias("ms")))
    cpu_df = execute_cpu(pipeline._plan).to_pandas()
    assert_frames_equal(cpu_df, pipeline.collect(), approx_float=1e-9)


def test_cache_materializes_once(session, pdf):
    df = session.create_dataframe(pdf).filter(col("v") > 10).cache()
    a = df.collect()
    # mutate nothing; second collect must serve from the cache holder
    from spark_rapids_tpu.execs.cache import CacheNode

    assert isinstance(df._plan, CacheNode)
    assert df._plan.holder.is_materialized
    b = df.group_by("k").count().collect()
    assert b["count"].astype(int).sum() == len(a)
    df.unpersist()
    assert not df._plan.holder.is_materialized


def test_cache_survives_spill(session, pdf, tmp_path):
    from spark_rapids_tpu.memory.catalog import (BufferCatalog,
                                                 reset_catalog)

    cat = reset_catalog(BufferCatalog(spill_dir=str(tmp_path)))
    try:
        df = session.create_dataframe(pdf).cache()
        a = df.collect()
        assert cat.synchronous_spill(0) > 0   # evict HBM tier entirely
        assert cat.spill_host_to_disk(0) > 0  # and the host tier
        b = df.collect()
        assert_frames_equal(a, b)
    finally:
        reset_catalog(BufferCatalog())


def test_repartition_roundtrip(session, pdf):
    df = session.create_dataframe(pdf)
    r = df.repartition(4, "k")
    out = r.collect()
    assert len(out) == len(pdf)
    rr = df.repartition(3)
    assert len(rr.collect()) == len(pdf)


def test_coalesce_partitions(session, tmp_path, pdf):
    from spark_rapids_tpu.api import Session

    for k in range(6):
        pq.write_table(pa.Table.from_pandas(pdf.iloc[k * 60:(k + 1) * 60]),
                       tmp_path / f"f{k}.parquet")
    # a tiny reader byte target keeps the six small files as six scan
    # partitions (FilePartition packing would fold them into one,
    # leaving coalesce(2) nothing to do)
    s = Session(conf={"rapids.tpu.sql.reader.batchSizeBytes": 1024})
    df = s.read.parquet(str(tmp_path))
    c = df.coalesce(2)
    exec_ = c._exec()
    assert exec_.num_partitions == 2
    out = c.collect()
    assert len(out) == 360


def test_last_metrics_after_collect(df):
    pipe = df.filter(col("v") > 10).group_by("k").count()
    assert pipe.last_metrics() == {}
    pipe.collect()
    m = pipe.last_metrics()
    assert any("Aggregate" in k for k in m)
    agg_key = next(k for k in m if "Aggregate" in k)
    assert m[agg_key]["rows"] > 0


def test_na_functions(session):
    pdf2 = pd.DataFrame({"a": [1.0, None, 3.0, None],
                         "s": ["x", None, "z", "w"],
                         "i": [10, 20, 30, 40]})
    df = session.create_dataframe(pdf2)
    filled = df.fillna(-1.0, subset=["a"]).collect()
    assert [float(v) for v in filled["a"]] == [1.0, -1.0, 3.0, -1.0]
    assert filled["s"][1] is None or pd.isna(filled["s"][1])
    fs = df.fillna("??").collect()
    assert list(fs["s"]) == ["x", "??", "z", "w"]
    assert pd.isna(fs["a"][1])  # numeric untouched by a string fill
    assert df.dropna().count() == 2           # rows 0 and 2
    assert df.dropna(subset=["a"]).count() == 2
    assert df.dropna(how="all").count() == 4  # 'i' is never null


def test_rename_and_todf(df):
    r = df.with_column_renamed("v", "value")
    assert r.columns == ["k", "value", "s"]
    t = df.to_df("c1", "c2", "c3")
    assert t.columns == ["c1", "c2", "c3"]
    assert len(t.collect()) == 400


def test_sample_and_describe(session, pdf):
    s2 = Session({"rapids.tpu.sql.incompatibleOps.enabled": True})
    df = s2.create_dataframe(pdf)
    frac = df.sample(0.3, seed=5).count() / len(pdf)
    assert 0.2 < frac < 0.4
    # deterministic per seed
    assert df.sample(0.3, seed=5).count() == \
        df.sample(0.3, seed=5).count()
    d = session.create_dataframe(pdf).describe("v")
    assert int(d["count(v)"].iloc[0]) == len(pdf)
    assert abs(float(d["mean(v)"].iloc[0]) - pdf.v.mean()) < 1e-9
