"""tpulint test suite: per-code fixtures, the allowlist contract, the
runtime lock-order tracker, the subprocess CI-gate fence, and the q26
plan-level sync-map exactness check.

The fixture tests write tiny source trees under tmp_path shaped like
the real package (``<root>/spark_rapids_tpu/...``) so path-scoped
rules (device-path TPU401, lockorder self-exemption) apply exactly as
they do on the repo. The gate fence runs ``scripts/lint_check.py`` in
a subprocess against a tree seeded with one violation from EACH of the
four diagnostic families and demands a nonzero exit — proving the gate
cannot be wired out of CI silently.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
LINT = os.path.join(ROOT, "scripts", "lint_check.py")


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------


def _tree(tmp_path, files):
    """Write {relpath: source} under tmp_path and return its str path."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(tmp_path)


def _codes(findings):
    return [f.code for f in findings]


# ---------------------------------------------------------------------------
# TPU1xx host-sync fixtures
# ---------------------------------------------------------------------------


def test_tpu101_np_coerce_flagged_and_device_get_exempt(tmp_path):
    from spark_rapids_tpu.analysis import host_sync
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": """
        import numpy as np
        import jax

        def bad(x):
            return np.asarray(x)

        def explicit(x):
            return np.asarray(jax.device_get(x))

        def literal():
            return np.asarray([1, 2, 3])
    """})
    fs = host_sync.run(root)
    assert _codes(fs) == ["TPU101"]
    assert fs[0].qualname == "bad"


def test_tpu102_item_flagged(tmp_path):
    from spark_rapids_tpu.analysis import host_sync
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": """
        def bad(x):
            return x.item()

        def indexed(x):
            return x.item(0)   # numpy-style indexed item: host array
    """})
    fs = [f for f in host_sync.run(root) if f.code == "TPU102"]
    assert len(fs) == 1 and fs[0].qualname == "bad"


def test_tpu103_barrier_flagged(tmp_path):
    from spark_rapids_tpu.analysis import host_sync
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": """
        import jax

        def bad(x):
            jax.block_until_ready(x)
    """})
    assert _codes(host_sync.run(root)) == ["TPU103"]


def test_tpu104_truth_tests(tmp_path):
    from spark_rapids_tpu.analysis import host_sync
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": """
        import jax.numpy as jnp

        def direct(x):
            if jnp.any(x > 0):
                return 1

        def via_name(x):
            flag = jnp.all(x)
            while not flag:
                break

        def metadata(dt):
            if jnp.issubdtype(dt, jnp.integer):   # host bool: exempt
                return 1
    """})
    fs = [f for f in host_sync.run(root) if f.code == "TPU104"]
    assert sorted(f.qualname for f in fs) == ["direct", "via_name"]


# ---------------------------------------------------------------------------
# TPU2xx recompile fixtures
# ---------------------------------------------------------------------------


def test_tpu201_jit_in_body_flagged_decorator_exempt(tmp_path):
    from spark_rapids_tpu.analysis import recompile
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": """
        from functools import partial
        import jax

        _STEP = jax.jit(lambda x: x + 1)   # module level: fine

        @partial(jax.jit, static_argnums=0)
        def decorated(n, x):
            return x * n

        def bad(x):
            return jax.jit(lambda v: v * 2)(x)
    """})
    fs = [f for f in recompile.run(root) if f.code == "TPU201"]
    assert len(fs) == 1 and fs[0].qualname == "bad"


def test_tpu202_raw_shape_flagged_bucketed_exempt(tmp_path):
    from spark_rapids_tpu.analysis import recompile
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": """
        import jax.numpy as jnp
        from spark_rapids_tpu.ops.buckets import bucket_capacity

        def bad(xs):
            return jnp.zeros(len(xs))

        def quantized(xs):
            cap = bucket_capacity(len(xs))
            return jnp.zeros(cap)
    """})
    fs = [f for f in recompile.run(root) if f.code == "TPU202"]
    assert len(fs) == 1 and fs[0].qualname == "bad"


def test_tpu203_weak_literal_flagged_dtype_exempt(tmp_path):
    from spark_rapids_tpu.analysis import recompile
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": """
        import jax.numpy as jnp

        def bad():
            return jnp.asarray(1)

        def kw():
            return jnp.asarray(1, dtype=jnp.int32)

        def positional(dt):
            return jnp.asarray(0, dt)
    """})
    fs = [f for f in recompile.run(root) if f.code == "TPU203"]
    assert len(fs) == 1 and fs[0].qualname == "bad"


def test_tpu204_direct_pallas_call_flagged_registry_exempt(tmp_path):
    from spark_rapids_tpu.analysis import recompile
    root = _tree(tmp_path, {
        # the registry itself: the ONE sanctioned pl.pallas_call site
        "spark_rapids_tpu/native/kernels/__init__.py": """
            def pallas_call(kernel, *, out_shape, **kw):
                from spark_rapids_tpu.shims import get_shims
                pl = get_shims().pallas()
                return pl.pallas_call(kernel, out_shape=out_shape,
                                      interpret=True, **kw)
        """,
        # kernel module routing through the registry: exempt
        "spark_rapids_tpu/native/kernels/good.py": """
            from spark_rapids_tpu.native import kernels as nk

            def fine(kern, shape):
                return nk.pallas_call(kern, out_shape=shape)
        """,
        # direct pl.pallas_call outside the registry: flagged
        "spark_rapids_tpu/execs/bad.py": """
            from jax.experimental import pallas as pl

            def bad(kern, shape):
                return pl.pallas_call(kern, out_shape=shape,
                                      interpret=False)
        """})
    fs = [f for f in recompile.run(root) if f.code == "TPU204"]
    assert len(fs) == 1 and fs[0].qualname == "bad"
    assert fs[0].path.endswith("bad.py")


# ---------------------------------------------------------------------------
# TPU3xx lock fixtures (static)
# ---------------------------------------------------------------------------

_LOCK_SRC = """
    import threading
    import time
    from spark_rapids_tpu.utils import lockorder

    OUTER = lockorder.make_lock("service.query")        # rank 20
    INNER = lockorder.make_lock("memory.semaphore")     # rank 108
    RAW = threading.Lock()

    def ordered():
        with OUTER:
            with INNER:
                pass

    def inverted():
        with INNER:
            with OUTER:
                pass

    def blocking():
        with OUTER:
            time.sleep(0.1)
"""


def test_tpu301_static_inversion(tmp_path):
    from spark_rapids_tpu.analysis import locks
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": _LOCK_SRC})
    fs = locks.run(root)
    inv = [f for f in fs if f.code == "TPU301"]
    assert len(inv) == 1 and inv[0].qualname == "inverted"
    assert "service.query" in inv[0].message


def test_tpu302_blocking_under_lock(tmp_path):
    from spark_rapids_tpu.analysis import locks
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": _LOCK_SRC})
    blk = [f for f in locks.run(root) if f.code == "TPU302"]
    assert len(blk) == 1 and blk[0].qualname == "blocking"


def test_tpu303_raw_lock(tmp_path):
    from spark_rapids_tpu.analysis import locks
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": _LOCK_SRC})
    raw = [f for f in locks.run(root) if f.code == "TPU303"]
    assert len(raw) == 1 and raw[0].line == 8


# ---------------------------------------------------------------------------
# TPU4xx robustness fixtures
# ---------------------------------------------------------------------------


def test_tpu401_broad_except_on_device_path(tmp_path):
    from spark_rapids_tpu.analysis import robustness
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": """
        from spark_rapids_tpu.memory.retry import is_oom_error

        def bad(run):
            try:
                return run()
            except Exception:
                return None

        def gated(run):
            try:
                return run()
            except Exception as e:
                if is_oom_error(e):
                    raise
                return None

        def guard():
            try:
                import cupy
            except Exception:
                cupy = None
    """})
    fs = [f for f in robustness.run(root) if f.code == "TPU401"]
    assert len(fs) == 1 and fs[0].qualname == "bad"


def test_tpu401_only_on_device_path(tmp_path):
    from spark_rapids_tpu.analysis import robustness
    root = _tree(tmp_path, {"spark_rapids_tpu/plan/m.py": """
        def host_side(run):
            try:
                return run()
            except Exception:
                return None
    """})
    assert not [f for f in robustness.run(root) if f.code == "TPU401"]


def test_tpu402_unknown_knob(tmp_path):
    from spark_rapids_tpu.analysis import robustness
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/m.py": """
        BAD = "rapids.tpu.thisKnob.doesNotExist"
        GOOD = "rapids.tpu.debug.lockOrder.enabled"
        FAMILY_PREFIX = "rapids.tpu.sql.exec."   # key family, not a key
    """})
    fs = [f for f in robustness.run(root) if f.code == "TPU402"]
    assert len(fs) == 1
    assert "thisKnob.doesNotExist" in fs[0].message


def test_tpu403_undocumented_knob(tmp_path):
    from spark_rapids_tpu.analysis import robustness
    # a docs/configs.md that documents nothing: every non-internal
    # registered knob is reported; absent docs file -> no TPU403
    root = _tree(tmp_path, {"docs/configs.md": "# empty\n"})
    fs = [f for f in robustness.run(root) if f.code == "TPU403"]
    assert fs, "expected TPU403 for every undocumented registered knob"
    assert not any("rapids.tpu.sql.test.enabled" in f.message
                   for f in fs), "internal knobs are docs-exempt"
    assert not [f for f in robustness.run(str(tmp_path / "nowhere"))
                if f.code == "TPU403"]


# ---------------------------------------------------------------------------
# allowlist contract
# ---------------------------------------------------------------------------


def test_allowlist_justification_mandatory():
    from spark_rapids_tpu.analysis.allowlist import (Allowlist,
                                                     AllowlistError)
    with pytest.raises(AllowlistError, match="justification"):
        Allowlist.parse("TPU101 pkg/m.py::f\n")
    with pytest.raises(AllowlistError, match="unknown diagnostic"):
        Allowlist.parse("TPU999 pkg/m.py::f -- because\n")


def test_allowlist_scopes():
    from spark_rapids_tpu.analysis.allowlist import Allowlist
    from spark_rapids_tpu.analysis.diagnostics import Finding
    al = Allowlist.parse("""
        TPU101 pkg/a.py::C.f -- exact site
        TPU102 pkg/b.py -- whole module
        TPU103 pkg/bench/* -- harness glob
    """)
    hit = Finding("TPU101", "pkg/a.py", 3, "C.f", "m")
    miss_fn = Finding("TPU101", "pkg/a.py", 9, "C.g", "m")
    miss_code = Finding("TPU104", "pkg/a.py", 3, "C.f", "m")
    file_hit = Finding("TPU102", "pkg/b.py", 1, "anything", "m")
    glob_hit = Finding("TPU103", "pkg/bench/x.py", 1, "run", "m")
    assert al.allows(hit) and al.allows(file_hit) and al.allows(glob_hit)
    assert not al.allows(miss_fn) and not al.allows(miss_code)
    assert al.filter([hit, miss_fn]) == [miss_fn]
    assert al.unused_entries([hit]) == [
        ("TPU102", "pkg/b.py", "whole module"),
        ("TPU103", "pkg/bench/*", "harness glob")]


def test_repo_allowlist_loads_and_is_exact():
    """Every entry in the checked-in allowlist parses, matches at least
    one current finding (no stale exemptions), and the filtered set is
    empty — the same invariant lint_check.py gates on."""
    from spark_rapids_tpu import analysis
    from spark_rapids_tpu.analysis.allowlist import Allowlist
    al = Allowlist.load()
    assert al.entries, "repo allowlist should not be empty"
    fs = analysis.run_all()
    assert al.filter(fs) == []
    assert al.unused_entries(fs) == []


# ---------------------------------------------------------------------------
# runtime lock-order tracker
# ---------------------------------------------------------------------------


def test_lockorder_runtime_inversion():
    """A→B passes, B→A raises in raise mode: the runtime complement of
    the static TPU301 pass, over the same declared hierarchy."""
    from spark_rapids_tpu.utils import lockorder
    a = lockorder.make_lock("service.query")       # rank 20
    b = lockorder.make_lock("memory.semaphore")    # rank 108
    if not lockorder.enabled():
        pytest.skip("lock-order tracking disabled in this environment")
    lockorder.set_raise_mode(True)
    try:
        with a:
            with b:
                pass                               # declared order: fine
        with pytest.raises(lockorder.LockOrderViolation):
            with b:
                with a:
                    pass
    finally:
        lockorder.set_raise_mode(False)
        lockorder.reset_violations()


def test_lockorder_group_exemption():
    """planBarrier group members may interleave in any order (the plan
    DAG is acyclic) but still order against locks outside the group."""
    from spark_rapids_tpu.utils import lockorder
    chain = lockorder.make_lock("execs.fused.chainPrep")         # 36
    bcast = lockorder.make_lock("exchange.broadcast.materialize")  # 38
    svc = lockorder.make_lock("service.query")                   # 20
    if not lockorder.enabled():
        pytest.skip("lock-order tracking disabled in this environment")
    lockorder.set_raise_mode(True)
    try:
        with bcast:
            with chain:        # lower rank inside group member: exempt
                pass
        with pytest.raises(lockorder.LockOrderViolation):
            with bcast:
                with svc:      # outside the group: ranks still apply
                    pass
    finally:
        lockorder.set_raise_mode(False)
        lockorder.reset_violations()


def test_lockorder_undeclared_name_rejected():
    from spark_rapids_tpu.utils import lockorder
    if not lockorder.enabled():
        pytest.skip("lock-order tracking disabled in this environment")
    with pytest.raises(lockorder.LockOrderViolation, match="not declared"):
        lockorder.make_lock("no.such.lock")


# ---------------------------------------------------------------------------
# the CI gate, end to end
# ---------------------------------------------------------------------------


def _run_lint(*argv, timeout=240):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable, LINT, *argv], env=env,
                          capture_output=True, text=True,
                          timeout=timeout)


def test_gate_clean_on_repo():
    out = _run_lint()
    assert out.returncode == 0, out.stdout + out.stderr
    assert "0 new vs baseline" in out.stdout


def test_gate_fails_on_seeded_violations_all_families(tmp_path):
    """One seeded violation per family; lint_check.py must exit
    nonzero and name all four, or the gate is decorative."""
    root = _tree(tmp_path, {
        "spark_rapids_tpu/execs/seeded.py": """
            import threading
            import numpy as np
            import jax

            _RAW = threading.Lock()                      # TPU303

            def sync(x):
                return np.asarray(x)                     # TPU101

            def retrace(x):
                return jax.jit(lambda v: v)(x)           # TPU201

            def swallow(run):
                try:
                    return run()
                except Exception:                        # TPU401
                    return None
        """})
    out = _run_lint("--root", root)
    assert out.returncode == 1, out.stdout + out.stderr
    for family in ("TPU101", "TPU201", "TPU303", "TPU401"):
        assert family in out.stdout, (family, out.stdout)


def test_gate_json_output(tmp_path):
    root = _tree(tmp_path, {"spark_rapids_tpu/execs/seeded.py": """
        import numpy as np

        def sync(x):
            return np.asarray(x)
    """})
    json_path = tmp_path / "findings.json"
    out = _run_lint("--root", root, "--json", str(json_path))
    assert out.returncode == 1
    data = json.loads(json_path.read_text())
    assert data["total"] == 1 and data["allowlisted"] == 0
    [f] = data["new"]
    assert f["code"] == "TPU101"
    assert f["path"] == "spark_rapids_tpu/execs/seeded.py"


# ---------------------------------------------------------------------------
# q26 plan-level sync map
# ---------------------------------------------------------------------------


def test_q26_sync_map_exact():
    """tpcxbb q26 sf0.1: the compiled plan's sync map is EXACTLY the
    batched duplicate-flag fetch plus the root result fetch — any third
    entry is a new ~105 ms round trip the dispatch fence would pay for.
    Subprocess for the same reason as the dispatch fence: planning
    imports compute modules, and the shared dataset dir is reused."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, LINT, "--sync-map",
         "--data-dir", os.path.join("/tmp", "srt_dispatch_fence")],
        env=env, capture_output=True, text=True, timeout=580)
    assert out.returncode == 0, out.stderr[-3000:]
    lines = [ln for ln in out.stdout.splitlines() if ln.strip()]
    kinds = sorted(ln.split(None, 1)[1].rsplit(None, 1)[0].strip()
                   for ln in lines)
    assert kinds == ["duplicate-flag fetch", "result fetch"], out.stdout
