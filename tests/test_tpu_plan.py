"""End-to-end plan tests: CPU oracle vs TPU override pipeline
(the SparkQueryCompareTestSuite layer of the reference, SURVEY.md §4)."""
import numpy as np
import pytest

from compare import assert_cpu_and_tpu_equal, assert_frames_equal
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.expressions import (Add, Alias, And, Average,
                                          BoundReference, CaseWhen, Cast,
                                          Count, Divide, EqualTo,
                                          GreaterThan, If, IsNotNull,
                                          LessThan, Literal, Max, Min,
                                          Multiply, Subtract, Sum)
from spark_rapids_tpu.ops.sortkeys import SortKeySpec
from spark_rapids_tpu.plan import nodes as pn

RNG = np.random.default_rng(42)


def find(e, klass):
    """All execs of ``klass`` in the converted tree."""
    out = [e] if isinstance(e, klass) else []
    for c in e.children:
        out += find(c, klass)
    return out


def ref(i, t, nullable=True):
    return BoundReference(i, t, nullable)


def scan(data, validity=None):
    return pn.ScanNode(pn.InMemorySource(data, validity=validity))


def random_table(n=1000, with_nulls=True, seed=0):
    rng = np.random.default_rng(seed)
    data = {
        "k": rng.integers(0, 20, n).astype(np.int64),
        "v": rng.normal(size=n),
        "w": rng.integers(-100, 100, n).astype(np.int64),
    }
    validity = {}
    if with_nulls:
        validity = {"k": rng.random(n) > 0.1, "v": rng.random(n) > 0.1}
    return data, validity


def test_project_filter_pipeline():
    data, validity = random_table()
    plan = scan(data, validity)
    plan = pn.FilterNode(
        And(GreaterThan(ref(1, dt.FLOAT64), Literal(-0.5)),
            IsNotNull(ref(0, dt.INT64))), plan)
    plan = pn.ProjectNode(
        [Alias(Add(ref(0, dt.INT64), Literal(1)), "k1"),
         Alias(Multiply(ref(1, dt.FLOAT64), Literal(2.0)), "v2"),
         Alias(If(LessThan(ref(2, dt.INT64), Literal(0)),
                  Literal(-1), Literal(1)), "sgn")], plan)
    assert_cpu_and_tpu_equal(plan)


def test_case_when_cast():
    data, validity = random_table(300, seed=1)
    plan = scan(data, validity)
    plan = pn.ProjectNode(
        [Alias(CaseWhen(
            [(LessThan(ref(2, dt.INT64), Literal(-50)), Literal(0)),
             (LessThan(ref(2, dt.INT64), Literal(0)), Literal(1))],
            Literal(2)), "bucket"),
         Alias(Cast(ref(2, dt.INT64), dt.FLOAT64), "wf"),
         Alias(Cast(ref(1, dt.FLOAT64), dt.INT64), "vi")], plan)
    assert_cpu_and_tpu_equal(plan)


def test_groupby_aggregate_single_partition():
    data, validity = random_table(2000, seed=2)
    plan = scan(data, validity)
    aggs = [pn.AggCall(Sum(ref(1, dt.FLOAT64)), "s"),
            pn.AggCall(Count(ref(1, dt.FLOAT64)), "c"),
            pn.AggCall(Count(), "n"),
            pn.AggCall(Min(ref(2, dt.INT64)), "lo"),
            pn.AggCall(Max(ref(2, dt.INT64)), "hi"),
            pn.AggCall(Average(ref(1, dt.FLOAT64)), "m")]
    plan = pn.AggregateNode([ref(0, dt.INT64)], aggs, plan,
                            grouping_names=["k"])
    assert_cpu_and_tpu_equal(plan, approx_float=1e-9)


def test_global_aggregate():
    data, validity = random_table(500, seed=3)
    plan = scan(data, validity)
    aggs = [pn.AggCall(Sum(ref(2, dt.INT64)), "s"),
            pn.AggCall(Count(), "n")]
    plan = pn.AggregateNode([], aggs, plan)
    assert_cpu_and_tpu_equal(plan)


def test_global_aggregate_empty():
    plan = scan({"v": np.array([], dtype=np.int64)})
    aggs = [pn.AggCall(Sum(ref(0, dt.INT64)), "s"),
            pn.AggCall(Count(), "n")]
    plan = pn.AggregateNode([], aggs, plan)
    assert_cpu_and_tpu_equal(plan)


def test_sort_with_nulls_and_limit():
    data, validity = random_table(500, seed=4)
    plan = scan(data, validity)
    plan = pn.SortNode([SortKeySpec.spark_default(1, ascending=False),
                        SortKeySpec.spark_default(0)], plan)
    plan = pn.LimitNode(37, plan)
    assert_cpu_and_tpu_equal(plan, sort=False)


@pytest.mark.parametrize("kind", ["inner", "left", "right", "full",
                                  "left_semi", "left_anti"])
def test_join_kinds(kind):
    rng = np.random.default_rng(5)
    nl, nr = 400, 150
    left = scan({"k": rng.integers(0, 50, nl).astype(np.int64),
                 "v": rng.normal(size=nl)},
                {"k": rng.random(nl) > 0.05})
    right = scan({"k2": rng.integers(0, 50, nr).astype(np.int64),
                  "w": rng.integers(0, 1000, nr).astype(np.int64)},
                 {"k2": rng.random(nr) > 0.05})
    plan = pn.JoinNode(kind, left, right, [0], [0])
    assert_cpu_and_tpu_equal(plan)


def test_join_with_condition():
    rng = np.random.default_rng(6)
    n = 200
    left = scan({"k": rng.integers(0, 20, n).astype(np.int64),
                 "v": rng.integers(0, 100, n).astype(np.int64)})
    right = scan({"k2": rng.integers(0, 20, 50).astype(np.int64),
                  "w": rng.integers(0, 100, 50).astype(np.int64)})
    cond = GreaterThan(ref(3, dt.INT64), ref(1, dt.INT64))
    plan = pn.JoinNode("inner", left, right, [0], [0], condition=cond)
    assert_cpu_and_tpu_equal(plan)


def test_string_join_keys():
    left = scan({"s": np.array(["a", "b", "c", "a", None], dtype=object),
                 "v": np.arange(5, dtype=np.int64)})
    right = scan({"s2": np.array(["a", "c", "x"], dtype=object),
                  "w": np.array([10, 20, 30], dtype=np.int64)})
    plan = pn.JoinNode("inner", left, right, [0], [0])
    assert_cpu_and_tpu_equal(plan)


def test_union_expand_limit():
    a = scan({"x": np.arange(10, dtype=np.int64)})
    b = scan({"x": np.arange(100, 110, dtype=np.int64)})
    u = pn.UnionNode([a, b])
    plan = pn.ExpandNode([[ref(0, dt.INT64), Literal(0)],
                          [Multiply(ref(0, dt.INT64), Literal(2)),
                           Literal(1)]], u, ["x", "tag"])
    assert_cpu_and_tpu_equal(plan)


def test_window_functions():
    rng = np.random.default_rng(7)
    n = 300
    plan = scan({"p": rng.integers(0, 10, n).astype(np.int64),
                 "o": rng.permutation(n).astype(np.int64),
                 "v": rng.normal(size=n)},
                {"v": rng.random(n) > 0.1})
    calls = [pn.WindowCall("row_number", "rn"),
             pn.WindowCall("rank", "rk"),
             pn.WindowCall("dense_rank", "dr"),
             pn.WindowCall(Sum(ref(2, dt.FLOAT64)), "rs",
                           frame=pn.WindowFrame(None, 0)),
             pn.WindowCall(Min(ref(2, dt.FLOAT64)), "rmin",
                           frame=pn.WindowFrame(None, 0)),
             pn.WindowCall(Max(ref(2, dt.FLOAT64)), "pmax",
                           frame=pn.WindowFrame(None, None)),
             pn.WindowCall(Count(ref(2, dt.FLOAT64)), "rc",
                           frame=pn.WindowFrame(-2, 2)),
             pn.WindowCall(Average(ref(2, dt.FLOAT64)), "ra",
                           frame=pn.WindowFrame(-3, 0)),
             # frames that are EMPTY at partition edges (regression: the
             # clamp must not pull in row 0 / the last row)
             pn.WindowCall(Sum(ref(2, dt.FLOAT64)), "prev2",
                           frame=pn.WindowFrame(-2, -1)),
             pn.WindowCall(Count(ref(2, dt.FLOAT64)), "next2",
                           frame=pn.WindowFrame(1, 2)),
             pn.WindowCall(("lag", ref(2, dt.FLOAT64)), "lg"),
             pn.WindowCall(("lead", ref(1, dt.INT64)), "ld")]
    plan = pn.WindowNode([0], [SortKeySpec.spark_default(1)], calls, plan)
    assert_cpu_and_tpu_equal(plan)


def test_range_node():
    plan = pn.RangeNode(5, 500, 7)
    plan = pn.FilterNode(
        EqualTo(Literal(0),
                Add(ref(0, dt.INT64), Multiply(ref(0, dt.INT64),
                                               Literal(-1)))), plan)
    assert_cpu_and_tpu_equal(plan)


def test_fallback_unsupported_agg():
    """first(ignoreNulls) windows etc. that the TPU doesn't do fall back
    with a reason, and results still match (assertDidFallBack analogue,
    Plugin.scala:155-231)."""
    from spark_rapids_tpu.expressions.aggregates import First

    data, validity = random_table(200, seed=8)
    plan = scan(data, validity)
    calls = [pn.WindowCall(First(ref(1, dt.FLOAT64), ignore_nulls=True),
                           "f")]
    wplan = pn.WindowNode([0], [SortKeySpec.spark_default(2)], calls, plan)
    from spark_rapids_tpu.plan.overrides import explain

    text = explain(wplan)
    assert "ignoreNulls" in text and "!" in text
    assert_cpu_and_tpu_equal(wplan, require_on_tpu=False)


def test_window_first_last_on_device():
    """first/last (ignoreNulls=False) window aggregates run on TPU for
    row and range frames."""
    from spark_rapids_tpu.expressions.aggregates import First, Last

    rng = np.random.default_rng(23)
    n = 300
    plan = scan({"p": rng.integers(0, 5, n).astype(np.int64),
                 "o": rng.integers(0, 50, n).astype(np.int64),
                 "v": rng.normal(size=n)},
                {"v": rng.random(n) > 0.15})
    calls = [
        pn.WindowCall(First(ref(2, dt.FLOAT64)), "f_run",
                      frame=pn.WindowFrame(None, 0)),
        pn.WindowCall(Last(ref(2, dt.FLOAT64)), "l_run",
                      frame=pn.WindowFrame(None, 0)),
        pn.WindowCall(First(ref(2, dt.FLOAT64)), "f_bounded",
                      frame=pn.WindowFrame(-3, -1)),
        pn.WindowCall(Last(ref(2, dt.FLOAT64)), "l_range",
                      frame=pn.WindowFrame(-4, 4, kind="range")),
    ]
    wnode = pn.WindowNode([0], [SortKeySpec.spark_default(1)], calls,
                          plan)
    assert_cpu_and_tpu_equal(wnode, approx_float=1e-12)


def test_fallback_mixed_tree_keeps_tpu_children():
    """A CPU-only parent over a TPU-able child: child accelerates, parent
    falls back, results match. MULTI-distinct (different inputs) is the
    remaining fallback case (GpuOverrides distinct fallback,
    aggregate.scala:56-130; single-input distinct — even mixed with plain
    aggregates — now rewrites to dedup-then-aggregate)."""
    data, validity = random_table(300, seed=9)
    child = pn.FilterNode(GreaterThan(ref(2, dt.INT64), Literal(0)),
                          scan(data, validity))
    aggs = [pn.AggCall(Sum(ref(1, dt.FLOAT64), distinct=True), "f"),
            pn.AggCall(Sum(ref(2, dt.INT64), distinct=True), "s")]
    plan = pn.AggregateNode([ref(0, dt.INT64)], aggs, child,
                            grouping_names=["k"])
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.execs.basic import CpuFallbackExec
    from spark_rapids_tpu.cpu.engine import execute_cpu
    from spark_rapids_tpu.plan.overrides import apply_overrides

    exec_ = apply_overrides(plan)
    assert isinstance(exec_, CpuFallbackExec)
    assert exec_.children, "TPU-able child subtree should be preserved"
    cpu_df = execute_cpu(plan).to_pandas()
    assert_frames_equal(cpu_df, collect(exec_))


def test_test_mode_raises_on_fallback():
    from spark_rapids_tpu.plan.overrides import PlanOnCpuError, \
        apply_overrides

    data, validity = random_table(50, seed=10)
    # MULTI-distinct over different inputs stays unsupported (the
    # optimizer rewrites only the single-distinct-input shape)
    aggs = [pn.AggCall(Sum(ref(1, dt.FLOAT64), distinct=True), "f"),
            pn.AggCall(Sum(ref(2, dt.INT64), distinct=True), "c")]
    plan = pn.AggregateNode([ref(0, dt.INT64)], aggs,
                            scan(data, validity))
    conf = RapidsConf({"rapids.tpu.sql.test.enabled": True})
    with pytest.raises(PlanOnCpuError):
        apply_overrides(plan, conf)


def test_op_config_gate_disables_exec():
    data, validity = random_table(50, seed=11)
    plan = pn.FilterNode(GreaterThan(ref(2, dt.INT64), Literal(0)),
                         scan(data, validity))
    conf = RapidsConf({"rapids.tpu.sql.exec.FilterNode": False})
    from spark_rapids_tpu.execs.basic import CpuFallbackExec
    from spark_rapids_tpu.plan.overrides import apply_overrides

    exec_ = apply_overrides(plan, conf)
    assert isinstance(exec_, CpuFallbackExec)
    assert any("disabled" in r for r in exec_.reasons)
    assert_cpu_and_tpu_equal(plan, conf, require_on_tpu=False)


def test_incompat_math_gated():
    from spark_rapids_tpu.expressions.math import Exp

    data, _ = random_table(20, with_nulls=False, seed=12)
    plan = pn.ProjectNode([Alias(Exp(ref(1, dt.FLOAT64)), "e")],
                          scan(data))
    from spark_rapids_tpu.execs.basic import CpuFallbackExec
    from spark_rapids_tpu.plan.overrides import apply_overrides

    assert isinstance(apply_overrides(plan), CpuFallbackExec)
    conf = RapidsConf({"rapids.tpu.sql.incompatibleOps.enabled": True})
    exec_ = apply_overrides(plan, conf)
    assert not isinstance(exec_, CpuFallbackExec)
    assert_cpu_and_tpu_equal(plan, conf, approx_float=1e-7,
                             require_on_tpu=False)


def test_window_lag_bad_default_falls_back():
    """A lead/lag default that can't coerce into the input column's
    physical dtype must fall back at plan time, not crash at execution
    (review finding: FLOAT/DATE columns previously slipped through)."""
    rng = np.random.default_rng(5)
    n = 50
    plan = scan({"p": rng.integers(0, 4, n).astype(np.int64),
                 "o": rng.permutation(n).astype(np.int64),
                 "v": rng.normal(size=n)})
    calls = [pn.WindowCall(("lag", ref(2, dt.FLOAT64)), "lg",
                           default="not-a-number")]
    wnode = pn.WindowNode([0], [SortKeySpec.spark_default(1)], calls, plan)
    from spark_rapids_tpu.plan.overrides import apply_overrides
    from spark_rapids_tpu.execs.basic import CpuFallbackExec
    ex = apply_overrides(wnode, RapidsConf())
    assert isinstance(ex, CpuFallbackExec)
    # int default over a float column is fine and must stay on TPU
    ok = pn.WindowNode([0], [SortKeySpec.spark_default(1)],
                       [pn.WindowCall(("lag", ref(2, dt.FLOAT64)), "lg",
                                      default=7)], plan)
    assert_cpu_and_tpu_equal(ok)


def test_window_range_frames():
    """RANGE frames: value-based bounds over the order key, nulls first
    and all-equal, device vs CPU oracle."""
    rng = np.random.default_rng(21)
    n = 400
    plan = scan({"p": rng.integers(0, 6, n).astype(np.int64),
                 "t": rng.integers(0, 80, n).astype(np.int64),
                 "v": rng.normal(size=n)},
                {"t": rng.random(n) > 0.08})
    calls = [
        pn.WindowCall(Sum(ref(2, dt.FLOAT64)), "rsum",
                      frame=pn.WindowFrame(-10, 0, kind="range")),
        pn.WindowCall(Count(ref(2, dt.FLOAT64)), "rcnt",
                      frame=pn.WindowFrame(-5, 5, kind="range")),
        pn.WindowCall(Average(ref(2, dt.FLOAT64)), "ravg",
                      frame=pn.WindowFrame(None, 0, kind="range")),
        pn.WindowCall(Sum(ref(2, dt.FLOAT64)), "rfut",
                      frame=pn.WindowFrame(0, None, kind="range")),
    ]
    wnode = pn.WindowNode([0], [SortKeySpec.spark_default(1)], calls,
                          plan)
    assert_cpu_and_tpu_equal(wnode, approx_float=1e-9)


def test_window_range_frame_minmax_falls_back():
    rng = np.random.default_rng(22)
    n = 60
    plan = scan({"p": rng.integers(0, 3, n).astype(np.int64),
                 "t": rng.integers(0, 30, n).astype(np.int64),
                 "v": rng.normal(size=n)})
    calls = [pn.WindowCall(Min(ref(2, dt.FLOAT64)), "rmin",
                           frame=pn.WindowFrame(-5, 5, kind="range"))]
    wnode = pn.WindowNode([0], [SortKeySpec.spark_default(1)], calls,
                          plan)
    from spark_rapids_tpu.execs.basic import CpuFallbackExec
    from spark_rapids_tpu.plan.overrides import apply_overrides
    ex = apply_overrides(wnode, RapidsConf())
    assert isinstance(ex, CpuFallbackExec)
    assert_cpu_and_tpu_equal(wnode, require_on_tpu=False)


def test_filter_fuses_into_aggregate():
    """Aggregate over Filter fuses the keep-mask into the groupby
    (no FilterExec in the exec tree); results match the oracle
    including all-rows-filtered and empty-global-agg cases."""
    from spark_rapids_tpu.execs.aggregate import HashAggregateExec
    from spark_rapids_tpu.execs.basic import FilterExec
    from spark_rapids_tpu.plan.overrides import apply_overrides

    rng = np.random.default_rng(31)
    n = 500
    plan = scan({"k": rng.integers(0, 9, n).astype(np.int64),
                 "v": rng.random(n)},
                {"v": rng.random(n) > 0.1})
    cond = GreaterThan(ref(1, dt.FLOAT64), Literal(0.4))
    agg = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(Sum(ref(1, dt.FLOAT64)), "sv"),
         pn.AggCall(Count(ref(1, dt.FLOAT64)), "cv")],
        pn.FilterNode(cond, plan), grouping_names=["k"])
    ex = apply_overrides(agg, RapidsConf())

    assert not find(ex, FilterExec), "filter must fuse into the agg"
    aggs = find(ex, HashAggregateExec)
    # the filter mask must ride into the groupby sort: either as the
    # agg's fused_filter or absorbed as a FilterStep of a fused chain
    from spark_rapids_tpu.execs.fused import FilterStep, FusedAggregateExec

    assert any(
        a.fused_filter is not None or
        (isinstance(a, FusedAggregateExec) and
         any(isinstance(st, FilterStep) for st in a.chain.steps))
        for a in aggs)
    assert_cpu_and_tpu_equal(agg, approx_float=1e-9)

    # filter that drops everything: grouped -> zero rows
    agg_none = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(Count(ref(1, dt.FLOAT64)), "cv")],
        pn.FilterNode(GreaterThan(ref(1, dt.FLOAT64), Literal(2.0)),
                      plan),
        grouping_names=["k"])
    assert_cpu_and_tpu_equal(agg_none)

    # global aggregate over all-filtered input: count=0, sum NULL
    glob = pn.AggregateNode(
        [], [pn.AggCall(Sum(ref(1, dt.FLOAT64)), "sv"),
             pn.AggCall(Count(ref(1, dt.FLOAT64)), "cv")],
        pn.FilterNode(GreaterThan(ref(1, dt.FLOAT64), Literal(2.0)),
                      plan))
    assert_cpu_and_tpu_equal(glob)


# ---------------------------------------------------------------------------
# Brute-force joins (BroadcastNestedLoopJoinExec / CartesianProductExec —
# GpuOverrides.scala:1837-1856: both disabled by default, OOM risk)


def _cross_inputs(nl=40, nr=25, seed=13):
    rng = np.random.default_rng(seed)
    left = scan({"a": rng.integers(0, 50, nl).astype(np.int64),
                 "b": rng.normal(size=nl)},
                {"a": rng.random(nl) > 0.1})
    right = scan({"c": rng.integers(0, 50, nr).astype(np.int64),
                  "d": rng.integers(0, 9, nr).astype(np.int64)},
                 {"c": rng.random(nr) > 0.1})
    return left, right


def test_cross_join_disabled_by_default():
    from spark_rapids_tpu.execs.basic import CpuFallbackExec
    from spark_rapids_tpu.plan.overrides import apply_overrides

    left, right = _cross_inputs()
    plan = pn.JoinNode("cross", left, right, [], [])
    exec_ = apply_overrides(plan, RapidsConf())
    assert isinstance(exec_, CpuFallbackExec)
    assert any("disabled by default" in r for r in exec_.reasons)
    assert_cpu_and_tpu_equal(plan, require_on_tpu=False)


@pytest.mark.parametrize("with_cond", [False, True])
def test_broadcast_nested_loop_join(with_cond):
    from spark_rapids_tpu.execs.joins import BroadcastNestedLoopJoinExec
    from spark_rapids_tpu.plan.overrides import apply_overrides

    left, right = _cross_inputs()
    cond = GreaterThan(ref(3, dt.INT64), ref(0, dt.INT64)) \
        if with_cond else None
    plan = pn.JoinNode("cross", left, right, [], [], condition=cond)
    conf = RapidsConf(
        {"rapids.tpu.sql.exec.BroadcastNestedLoopJoinExec": True})
    exec_ = apply_overrides(plan, conf)

    assert find(exec_, BroadcastNestedLoopJoinExec)
    assert_cpu_and_tpu_equal(plan, conf)


@pytest.mark.parametrize("with_cond", [False, True])
def test_cartesian_product_partition_grid(with_cond):
    from spark_rapids_tpu.execs.joins import CartesianProductExec
    from spark_rapids_tpu.plan.overrides import apply_overrides

    left, right = _cross_inputs(seed=14)
    # both sides multi-partition: the output partition grid is l x r
    left = pn.ShuffleExchangeNode(("round_robin",), 3, left)
    right = pn.ShuffleExchangeNode(("round_robin",), 2, right)
    cond = LessThan(ref(1, dt.FLOAT64), Literal(0.3)) if with_cond \
        else None
    plan = pn.JoinNode("cross", left, right, [], [], condition=cond)
    conf = RapidsConf({"rapids.tpu.sql.exec.CartesianProductExec": True})
    exec_ = apply_overrides(plan, conf)

    carts = find(exec_, CartesianProductExec)
    assert carts and carts[0].num_partitions == 6
    assert_cpu_and_tpu_equal(plan, conf)


def test_nested_loop_join_string_payload():
    """Non-referenced payload columns (incl. strings) must survive the
    fused-condition path untouched."""
    left = scan({"s": np.array(["x", "y", None, "z"], dtype=object),
                 "n": np.arange(4, dtype=np.int64)})
    right = scan({"m": np.array([1, 3], dtype=np.int64),
                  "t": np.array(["p", None], dtype=object)})
    cond = GreaterThan(ref(2, dt.INT64), ref(1, dt.INT64))
    plan = pn.JoinNode("cross", left, right, [], [], condition=cond)
    conf = RapidsConf(
        {"rapids.tpu.sql.exec.BroadcastNestedLoopJoinExec": True})
    assert_cpu_and_tpu_equal(plan, conf)


# ---------------------------------------------------------------------------
# Generate (explode/posexplode of created arrays — GpuGenerateExec.scala:
# only Explode/PosExplode(CreateArray(exprs)) is supported in v0.3)


@pytest.mark.parametrize("include_pos", [False, True])
def test_generate_explode_created_array(include_pos):
    data, validity = random_table(300, seed=21)
    plan = pn.GenerateNode(
        [ref(1, dt.FLOAT64),
         Multiply(ref(1, dt.FLOAT64), Literal(2.0)),
         Add(ref(1, dt.FLOAT64), Literal(1.0))],
        scan(data, validity),
        required_ordinals=[0, 2],
        value_name="v", include_pos=include_pos)
    assert_cpu_and_tpu_equal(plan, sort=False)


def test_generate_lowered_to_expand():
    from spark_rapids_tpu.execs.basic import ExpandExec
    from spark_rapids_tpu.plan.overrides import apply_overrides

    data, _ = random_table(20, with_nulls=False, seed=22)
    plan = pn.GenerateNode([ref(0, dt.INT64), ref(2, dt.INT64)],
                           scan(data), required_ordinals=[1],
                           include_pos=True)
    assert find(apply_overrides(plan, RapidsConf()), ExpandExec)
    assert_cpu_and_tpu_equal(plan, sort=False)


def test_api_explode():
    import pandas as pd

    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col

    s = Session()
    try:
        df = s.create_dataframe(pd.DataFrame(
            {"k": [1, 2, 3], "a": [10.0, 20.0, 30.0],
             "b": [0.5, 1.5, 2.5]}))
        out = df.explode(col("a"), col("b"), value_name="x",
                         pos=True).collect()
        assert len(out) == 6
        assert list(out["pos"]) == [0, 1, 0, 1, 0, 1]
        assert list(out["x"]) == [10.0, 0.5, 20.0, 1.5, 30.0, 2.5]
    finally:
        s.stop()


def test_out_of_core_global_sort_spills():
    """A global sort whose input exceeds the sort budget takes the
    range-bucketed out-of-core path: staged chunks + bucket slices are
    spillable, no single resident batch exceeds the budget, and the
    yielded bucket stream is globally ordered (SURVEY §5.7 — no
    RequireSingleBatch cliff). Multi-key with nulls + descending."""
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.execs.basic import ScanExec
    from spark_rapids_tpu.execs.sort import SortExec
    from spark_rapids_tpu.columnar.batch import Schema
    from spark_rapids_tpu.cpu.engine import execute_cpu
    from tests.compare import assert_frames_equal

    rng = np.random.default_rng(6)
    n = 50_000
    data = {"a": rng.integers(0, 1000, n).astype(np.int64),
            "b": rng.normal(size=n)}
    validity = {"b": rng.random(n) > 0.05}
    plan = pn.SortNode([SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1, ascending=False)],
                       scan(data, validity))
    cpu = execute_cpu(plan).to_pandas()

    node = scan(data, validity)
    exec_ = SortExec([SortKeySpec.spark_default(0),
                      SortKeySpec.spark_default(1, ascending=False)],
                     ScanExec(pn.InMemorySource(data, validity=validity),
                              node.output_schema()),
                     global_sort=True, sort_budget_rows=6000)
    batches = [b for b in exec_.execute(0)
               if b.realized_num_rows() > 0]
    assert len(batches) > 4, "out-of-core path must yield many buckets"
    assert max(b.realized_num_rows() for b in batches) < 50_000
    tpu = collect(exec_)
    assert_frames_equal(cpu, tpu, sort=False)


@pytest.mark.parametrize("kind", ["inner", "left", "left_semi",
                                  "left_anti", "full"])
def test_out_of_core_join_build_exceeds_budget(kind):
    """A join whose build side exceeds the batch budget takes the
    hash-bucketed out-of-core path (SURVEY §5.7: no RequireSingleBatch
    cliff, the sort exec's treatment applied to joins — r3 verdict #5):
    both sides bucket by key into spillable slices, each bucket joins at
    a bounded size, and every join kind stays exact (unmatched left/full
    rows surface from their own bucket; build rows emit exactly once)."""
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.execs.basic import ScanExec
    from spark_rapids_tpu.execs.joins import ShuffledHashJoinExec
    from spark_rapids_tpu.cpu.engine import execute_cpu
    from tests.compare import assert_frames_equal

    rng = np.random.default_rng(11)
    nl, nr = 9000, 24_000
    # dangling keys on both sides: unmatched stream rows (left/full) and
    # unmatched build rows (full) both cross bucket boundaries
    ldata = {"k": rng.integers(0, 4000, nl).astype(np.int64),
             "v": rng.normal(size=nl)}
    lvalid = {"k": rng.random(nl) > 0.03}
    rdata = {"k2": rng.integers(2000, 6000, nr).astype(np.int64),
             "w": rng.integers(0, 100, nr).astype(np.int64)}
    plan = pn.JoinNode(kind, scan(ldata, lvalid), scan(rdata), [0], [0])
    cpu = execute_cpu(plan).to_pandas()

    lnode, rnode = scan(ldata, lvalid), scan(rdata)
    exec_ = ShuffledHashJoinExec(
        kind, ScanExec(pn.InMemorySource(ldata, validity=lvalid),
                       lnode.output_schema()),
        ScanExec(pn.InMemorySource(rdata), rnode.output_schema()),
        [0], [0], plan.output_schema(), join_budget_rows=5000)
    batches = [b for b in exec_.execute(0)
               if b.realized_num_rows() > 0]
    assert len(batches) > 4, \
        "build 24k rows over a 5k budget must run many buckets"
    assert max(b.realized_num_rows() for b in batches) < cpu.shape[0] \
        or cpu.shape[0] == 0
    tpu = collect(exec_)
    assert_frames_equal(cpu, tpu, sort=True)


def test_window_supported_matrix_pinned():
    """The supported window frame x aggregate matrix, asserted the way
    the reference pins window specs (GpuWindowExpression.scala:208-263):
    each (call, frame) pair either plans on-TPU or falls back — never
    raises at execution (r3 verdict weak #7)."""
    from spark_rapids_tpu.execs.basic import CpuFallbackExec
    from spark_rapids_tpu.expressions.aggregates import (Average, Count,
                                                         First, Last, Max,
                                                         Min, Sum)
    from spark_rapids_tpu.plan.overrides import apply_overrides

    rng = np.random.default_rng(4)
    data = {"k": rng.integers(0, 6, 300).astype(np.int64),
            "o": rng.integers(0, 50, 300).astype(np.int64),
            "v": rng.normal(size=300)}
    vref = ref(2, dt.FLOAT64)
    running = pn.WindowFrame(None, 0)
    whole = pn.WindowFrame(None, None)
    bounded = pn.WindowFrame(-2, 2)
    vrange = pn.WindowFrame(kind="range", lower=-3, upper=3)
    cases = [
        # (call, on_tpu?)
        (pn.WindowCall("row_number", "c"), True),
        (pn.WindowCall("rank", "c"), True),
        (pn.WindowCall("dense_rank", "c"), True),
        (pn.WindowCall(("lead", vref), "c", offset=2), True),
        (pn.WindowCall(("lag", vref), "c", offset=1, default=0.0), True),
        (pn.WindowCall(Sum(vref), "c", frame=running), True),
        (pn.WindowCall(Sum(vref), "c", frame=whole), True),
        (pn.WindowCall(Sum(vref), "c", frame=bounded), True),
        (pn.WindowCall(Sum(vref), "c", frame=vrange), True),
        (pn.WindowCall(Count(vref), "c", frame=bounded), True),
        (pn.WindowCall(Count(None), "c", frame=running), True),
        (pn.WindowCall(Average(vref), "c", frame=vrange), True),
        (pn.WindowCall(Min(vref), "c", frame=running), True),
        (pn.WindowCall(Max(vref), "c", frame=whole), True),
        (pn.WindowCall(First(vref), "c", frame=bounded), True),
        (pn.WindowCall(Last(vref), "c", frame=running), True),
        # the pinned FALLBACK half of the matrix
        (pn.WindowCall(Min(vref), "c", frame=bounded), False),
        (pn.WindowCall(Max(vref), "c", frame=vrange), False),
        (pn.WindowCall(First(vref, ignore_nulls=True), "c",
                       frame=running), False),
    ]
    order = [SortKeySpec(1, True, True)]
    for call, on_tpu in cases:
        plan = pn.WindowNode([0], order, [call], scan(data))
        exec_ = apply_overrides(plan)
        is_fallback = isinstance(exec_, CpuFallbackExec)
        assert is_fallback != on_tpu, \
            (call.fn, call.frame, "expected on_tpu" if on_tpu
             else "expected fallback")
        # every supported cell also EXECUTES and matches the oracle
        if on_tpu:
            assert_cpu_and_tpu_equal(plan, sort=True)


def test_out_of_core_window_exceeds_budget():
    """A partitioned window whose input exceeds the batch budget
    hash-buckets by PARTITION BY keys and windows bucket-by-bucket
    (SURVEY §5.7 - r3 verdict: windows were the last single-batch
    cliff). Groups never span buckets, so rank/running-sum stay exact
    across the split."""
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.execs.basic import ScanExec
    from spark_rapids_tpu.execs.window import WindowExec
    from spark_rapids_tpu.expressions.aggregates import Sum
    from spark_rapids_tpu.cpu.engine import execute_cpu
    from tests.compare import assert_frames_equal

    rng = np.random.default_rng(21)
    n = 40_000
    data = {"g": rng.integers(0, 300, n).astype(np.int64),
            "o": rng.integers(0, 1000, n).astype(np.int64),
            "v": rng.normal(size=n)}
    validity = {"v": rng.random(n) > 0.05}
    calls = [pn.WindowCall("rank", "r"),
             pn.WindowCall(Sum(ref(2, dt.FLOAT64)), "rs",
                           frame=pn.WindowFrame(None, 0))]
    order = [SortKeySpec(1, True, True)]
    plan = pn.WindowNode([0], order, calls, scan(data, validity))
    cpu = execute_cpu(plan).to_pandas()

    node = scan(data, validity)
    exec_ = WindowExec([0], order, calls,
                       ScanExec(pn.InMemorySource(data,
                                                  validity=validity),
                                node.output_schema()),
                       plan.output_schema(), window_budget_rows=6000)
    batches = [b for b in exec_.execute(0)
               if b.realized_num_rows() > 0]
    assert len(batches) > 4, "must emit one batch per bucket"
    assert max(b.realized_num_rows() for b in batches) < n
    tpu = collect(exec_)
    assert_frames_equal(cpu, tpu, sort=True)
