"""Native host-runtime tests: LZ4 codec, bitmaps, CRC, envelopes, and the
compressed disk-spill path.

The native compressor's output is independently validated by the
pure-Python LZ4 block decompressor (format oracle), mirroring how the
reference trusts nvcomp only through round-trip tests.
"""
import os

import numpy as np
import pytest

from spark_rapids_tpu import native
from spark_rapids_tpu.columnar import compression, dtypes as dt, serde


def test_native_library_builds_and_loads():
    assert native.available(), (
        "native library failed to build/load; g++ is baked into the image "
        "so this must work here")


def _corpora():
    rng = np.random.default_rng(0)
    return {
        "empty": b"",
        "tiny": b"abc",
        "min_block": b"x" * 13,
        "repetitive": b"abcd" * 10_000,
        "text": (b"the quick brown fox jumps over the lazy dog " * 500),
        "random": rng.integers(0, 256, 50_000, dtype=np.uint8).tobytes(),
        "runs": b"".join(bytes([i % 7]) * (i % 100 + 1)
                         for i in range(500)),
        "int64s": np.arange(20_000, dtype=np.int64).tobytes(),
    }


@pytest.mark.parametrize("name", list(_corpora()))
def test_lz4_roundtrip_native(name):
    data = _corpora()[name]
    comp = native.lz4_compress(data)
    assert native.lz4_decompress(comp, len(data)) == data


@pytest.mark.parametrize("name", ["repetitive", "text", "runs", "int64s"])
def test_lz4_actually_compresses(name):
    data = _corpora()[name]
    comp = native.lz4_compress(data)
    assert len(comp) < len(data) * 0.6, (name, len(comp), len(data))


@pytest.mark.parametrize("name", list(_corpora()))
def test_lz4_native_output_decodes_with_python_oracle(name):
    """Format-conformance check: an independent decoder must read the
    native compressor's stream."""
    data = _corpora()[name]
    comp = native.lz4_compress(data)
    assert native._py_lz4_decompress(comp, len(data)) == data


def test_lz4_fuzz_roundtrip():
    rng = np.random.default_rng(7)
    for _ in range(50):
        n = int(rng.integers(0, 5000))
        # mix of random and self-similar content
        base = rng.integers(0, 8, max(n // 3, 1), dtype=np.uint8).tobytes()
        data = (base * 4)[:n]
        comp = native.lz4_compress(data)
        assert native.lz4_decompress(comp, len(data)) == data


def test_lz4_malformed_input_raises():
    with pytest.raises((ValueError, RuntimeError)):
        # token promises a long match but stream ends
        native.lz4_decompress(b"\xff\xff\xff", 1000)


def test_pack_unpack_bits():
    rng = np.random.default_rng(3)
    for n in (0, 1, 7, 8, 9, 63, 64, 1000):
        bools = rng.random(n) > 0.4
        packed = native.pack_bits(bools.astype(np.uint8))
        assert len(packed) == (n + 7) // 8
        out = native.unpack_bits(packed, n)
        np.testing.assert_array_equal(out, bools)
        # cross-check against numpy's packbits
        assert packed == np.packbits(bools, bitorder="little").tobytes()


def test_crc32c_known_vector():
    # RFC 3720 test vector: 32 zero bytes -> 0x8A9136AA
    assert native.crc32c(b"\x00" * 32) == 0x8A9136AA
    assert native.crc32c(b"123456789") == 0xE3069283


def test_envelope_roundtrip_all_codecs():
    payload = b"hello world " * 1000
    for codec in ("none", "lz4", "zlib"):
        wrapped = compression.wrap(payload, codec)
        assert compression.unwrap(wrapped) == payload
        if codec != "none":
            assert len(wrapped) < len(payload)


def test_envelope_detects_corruption():
    wrapped = bytearray(compression.wrap(b"data" * 100, "lz4"))
    wrapped[-1] ^= 0xFF
    with pytest.raises(ValueError, match="checksum"):
        compression.unwrap(bytes(wrapped))


def test_envelope_incompressible_stores_raw():
    rng = np.random.default_rng(1)
    payload = rng.integers(0, 256, 10_000, dtype=np.uint8).tobytes()
    wrapped = compression.wrap(payload, "lz4")
    assert len(wrapped) <= len(payload) + 17
    assert compression.unwrap(wrapped) == payload


def test_serde_packed_validity_roundtrip():
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.column import Column, StringColumn
    from spark_rapids_tpu.columnar.batch import ColumnarBatch

    rng = np.random.default_rng(5)
    n = 1000
    vals = rng.integers(-50, 50, n)
    valid = rng.random(n) > 0.3
    strs = [None if rng.random() < 0.2 else f"v{i % 13}"
            for i in range(n)]
    batch = ColumnarBatch(
        [Column.from_numpy(vals.astype(np.int64), dtype=dt.INT64,
                           validity=valid),
         StringColumn.from_strings(strs)], n)
    hb = serde.to_host_batch(batch)
    raw = serde.serialize_host_batch(hb)
    hb2 = serde.deserialize_host_batch(raw)
    assert hb2.num_rows == n
    np.testing.assert_array_equal(
        np.asarray(hb.columns[0].validity, dtype=bool),
        np.asarray(hb2.columns[0].validity, dtype=bool))
    np.testing.assert_array_equal(hb.columns[0].data, hb2.columns[0].data)
    # packed validity beats byte-per-bool on the wire
    assert len(raw) < hb.nbytes()


def test_disk_spill_roundtrip_compressed(tmp_path):
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.memory.catalog import BufferCatalog

    cat = BufferCatalog(spill_dir=str(tmp_path), disk_codec="lz4")
    vals = np.tile(np.arange(100, dtype=np.int64), 100)  # repetitive
    batch = ColumnarBatch([Column.from_numpy(vals, dtype=dt.INT64)],
                          10_000)
    bid = cat.register(batch, priority=0)
    assert cat.synchronous_spill(0) > 0
    assert cat.spill_host_to_disk(0) > 0
    files = [f for f in os.listdir(tmp_path) if f.endswith(".srt")]
    assert files
    # tiled int64 pattern compresses well on disk
    assert os.path.getsize(tmp_path / files[0]) < vals.nbytes / 2
    back = cat.acquire(bid)
    np.testing.assert_array_equal(
        np.asarray(back.columns[0].data)[:10_000], vals)
    cat.release(bid)
