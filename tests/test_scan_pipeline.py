"""Async scan pipeline tests (io/scanpipe): pruning differentials,
prefetch-depth identity, failure loudness, spillable landings, and
cluster-mode split distribution.

Model: the reference's GpuParquetScan row-group filter tests plus the
multi-threaded/coalescing reader matrix (parquet_test.py reader_opt
dimension) — every pipeline configuration must be a pure performance
knob: bit-identical batches, CPU-engine-as-oracle results.
"""
import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.execs.base import collect
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import BoundReference, Literal
from spark_rapids_tpu.io import ParquetSource, arrow_conv, scanpipe
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.overrides import apply_overrides

from tests.compare import assert_frames_equal

ROW_GROUP = 100


@pytest.fixture(autouse=True)
def _clean_scanpipe():
    scanpipe.clear_cache()
    scanpipe.reset_stats()
    yield
    scanpipe.clear_cache()
    scanpipe.reset_stats()


def _edge_table(n=1000):
    """Sorted key with NULLs exactly at every row-group's min/max edge
    rows (positions 0 and ROW_GROUP-1 of each group), so footer stats
    come from interior rows and a pruning decision that mishandled
    nulls-at-edges would either drop live rows or keep dead groups.
    The last group is ALL-null in ``k`` — no usable stats, must be
    conservatively kept."""
    k = np.arange(n, dtype=np.int64)
    null_mask = np.zeros(n, dtype=bool)
    null_mask[0::ROW_GROUP] = True
    null_mask[ROW_GROUP - 1::ROW_GROUP] = True
    null_mask[n - ROW_GROUP:] = True
    rng = np.random.default_rng(11)
    v = rng.random(n) * 1e3
    s = [None if i % 17 == 0 else f"s{i % 23}" for i in range(n)]
    return pa.table({
        "k": pa.array(k, mask=null_mask),
        "v": pa.array(v),
        "s": pa.array(s, type=pa.string()),
    })


def _edge_file(tmp_path, n=1000):
    path = str(tmp_path / "edges.parquet")
    pq.write_table(_edge_table(n), path, row_group_size=ROW_GROUP)
    return path


def _filtered_plan(src, lo):
    cond = P.GreaterThanOrEqual(BoundReference(0, dt.INT64),
                                Literal(lo, dt.INT64))
    return pn.FilterNode(cond, pn.ScanNode(src))


def test_pruned_vs_unpruned_bitexact(tmp_path):
    """Row-group pruning is invisible to results: the pruned scan and
    the scan-everything scan produce bit-identical filtered frames,
    with NULL keys sitting on every group's stat edges."""
    path = _edge_file(tmp_path)
    lo = 750

    on = RapidsConf({cfg.SCAN_PRUNING_ENABLED.key: True})
    off = RapidsConf({cfg.SCAN_PRUNING_ENABLED.key: False})
    pruned_src = ParquetSource(path, filters=[("k", ">=", lo)], conf=on)
    plain_src = ParquetSource(path, filters=[("k", ">=", lo)], conf=off)

    pruned = collect(apply_overrides(_filtered_plan(pruned_src, lo), on),
                     on)
    # pruning really happened: groups [0, 700) have max < 750. The
    # all-null tail group has no usable stats and must survive pruning
    # (conservative keep). Counters are global because the planner's
    # pushdown rebuilds the source via with_filters().
    assert scanpipe.snapshot()["chunks_pruned"] == 7
    assert scanpipe.snapshot()["bytes_pruned"] > 0

    scanpipe.reset_stats()
    full = collect(apply_overrides(_filtered_plan(plain_src, lo), off),
                   off)
    assert scanpipe.snapshot()["chunks_pruned"] == 0

    assert list(pruned.columns) == list(full.columns)
    assert len(pruned) == len(full) > 0
    for c in pruned.columns:
        a, b = pruned[c].to_numpy(), full[c].to_numpy()
        assert a.dtype == b.dtype
        if a.dtype.kind == "f":
            # bit-exact, including NaN representation
            assert np.array_equal(a.view(np.uint64), b.view(np.uint64))
        else:
            assert np.array_equal(a, b)

    # NULL keys never leak through the filter despite living at the
    # stats edges of kept groups
    assert pruned["k"].notna().all() and (pruned["k"] >= lo).all()

    # oracle agreement on top of the differential
    from spark_rapids_tpu.cpu.engine import execute_cpu

    oracle = execute_cpu(_filtered_plan(
        ParquetSource(path, filters=[("k", ">=", lo)]), lo)).to_pandas()
    assert_frames_equal(oracle, full)


def _scan_tables(src, conf):
    """Per-batch arrow tables for every partition, preserving batch
    boundaries (collect() would hide them)."""
    exec_ = apply_overrides(pn.ScanNode(src), conf)
    out = []
    for p in range(exec_.num_partitions):
        for b in exec_.execute(p):
            if b.realized_num_rows():
                out.append(arrow_conv.batch_to_arrow(b, exec_.schema))
    return out


def test_prefetch_depth_zero_byte_identity(tmp_path):
    """prefetch.depth=0 (strict synchronous) and depth=3 (pipelined)
    yield the same batch boundaries and byte-identical buffers: depth
    is a pure overlap knob, never a semantics knob."""
    path = _edge_file(tmp_path)
    batches = {}
    for depth in (0, 3):
        conf = RapidsConf({cfg.SCAN_PREFETCH_DEPTH.key: depth})
        batches[depth] = _scan_tables(ParquetSource(path, conf=conf),
                                      conf)
    assert len(batches[0]) == len(batches[3]) > 0
    for sync_t, async_t in zip(batches[0], batches[3]):
        assert sync_t.num_rows == async_t.num_rows
        assert sync_t.equals(async_t)
        # byte-level: identical buffer contents, not just equal values
        for name in sync_t.column_names:
            ca = sync_t.column(name).combine_chunks()
            cb = async_t.column(name).combine_chunks()
            for ba, bb in zip(ca.buffers(), cb.buffers()):
                assert (ba is None) == (bb is None)
                if ba is not None:
                    assert ba.to_pybytes() == bb.to_pybytes()


@pytest.mark.parametrize("depth", [0, 2])
def test_truncated_file_fails_loudly(tmp_path, depth):
    """A file truncated between planning and the read raises — it must
    never come back as a silently short result (both the synchronous
    and the prefetching consumer propagate the producer's error)."""
    path = _edge_file(tmp_path)
    conf = RapidsConf({cfg.SCAN_PREFETCH_DEPTH.key: depth})
    src = ParquetSource(path, conf=conf)
    n_splits = src.num_splits()          # splits planned pre-truncation
    assert n_splits >= 1
    raw = open(path, "rb").read()
    with open(path, "wb") as f:          # rip off the footer mid-plan
        f.write(raw[:len(raw) // 2])
    exec_ = apply_overrides(pn.ScanNode(src), conf)
    with pytest.raises(Exception, match="(?i)parquet|footer|invalid"):
        for p in range(exec_.num_partitions):
            for _ in exec_.execute(p):
                pass


def test_landed_scan_spill_roundtrip(tmp_path):
    """A landed scan survives device -> host -> disk demotion and still
    serves bit-exact batches from the scan cache."""
    from spark_rapids_tpu.memory.catalog import get_catalog

    path = _edge_file(tmp_path)
    conf = RapidsConf({cfg.SCAN_LANDING_SPILLABLE.key: True})
    src = ParquetSource(path, conf=conf)
    plan = pn.ScanNode(src)

    first = collect(apply_overrides(plan, conf), conf)
    assert scanpipe.cache_len() == 1
    assert scanpipe.snapshot()["cache_hits"] == 0
    assert scanpipe.cache_device_bytes() > 0

    # demote every landed buffer: device -> host, then host -> disk
    catalog = get_catalog()
    catalog.synchronous_spill(0)
    assert scanpipe.cache_device_bytes() == 0
    catalog.spill_host_to_disk(0)

    again = collect(apply_overrides(plan, conf), conf)
    assert scanpipe.snapshot()["cache_hits"] == 1
    assert list(first.columns) == list(again.columns)
    for c in first.columns:
        a, b = first[c].to_numpy(), again[c].to_numpy()
        if a.dtype.kind == "f":
            assert np.array_equal(a.view(np.uint64), b.view(np.uint64))
        else:
            assert np.array_equal(a, b)

    # rewriting the file invalidates the landing (version key), no
    # stale serve
    pq.write_table(_edge_table(300), path, row_group_size=ROW_GROUP)
    fresh_src = ParquetSource(path, conf=conf)
    fresh = collect(apply_overrides(pn.ScanNode(fresh_src), conf), conf)
    assert len(fresh) == 300
    assert scanpipe.snapshot()["cache_hits"] == 1  # miss, not a hit


def test_cluster_scan_disjoint_splits(tmp_path):
    """Cluster mode: executors (including the separate worker process)
    scan DISJOINT splits of the same parquet directory and the merged
    result matches the single-process oracle."""
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.runtime.cluster import shutdown_session_cluster

    for k in range(6):
        t = pa.table({
            "g": np.array([f"g{i % 4}" for i in range(200)],
                          dtype=object),
            "x": np.random.default_rng(k).integers(
                0, 1000, 200).astype(np.int64),
        })
        pq.write_table(t, tmp_path / f"part-{k}.parquet")

    def view_source():
        s = ParquetSource(str(tmp_path))
        s.pack_splits = False            # 6 files -> 6 disjoint splits
        assert s.num_splits() == 6
        return s

    query = ("SELECT g, sum(x) AS total, count(*) AS n FROM t "
             "GROUP BY g ORDER BY g")
    plain = Session()
    plain.create_temp_view("t", pn.ScanNode(view_source()))
    expected = plain.sql(query).collect()

    s = Session({
        "rapids.tpu.cluster.enabled": True,
        "rapids.tpu.cluster.executors": 2,
        "rapids.tpu.cluster.workers": 1,
        "rapids.tpu.sql.shuffle.partitions": 4,
    })
    try:
        s.create_temp_view("t", pn.ScanNode(view_source()))
        got = s.sql(query).collect()
    finally:
        shutdown_session_cluster()
    assert_frames_equal(expected, got, sort=False)
    assert got["n"].sum() == 1200        # every split scanned once
