"""Multi-host mesh topology + elastic membership (round-18 tentpole).

Three layers under test, bottom-up:

- :class:`parallel.mesh.HostTopology` — the explicit ``hosts x (data x
  model)`` axis map, its ICI/DCN seam classification, and the recorded
  (never silent) clamps/downgrades ``session_mesh`` applies.
- the per-seam in-program decision in ``parallel.spmd.in_program_mesh``:
  a Mesh*Exec subtree shipped whole to one executor keeps its collective
  ICI in-program even in cluster mode, while exchange lowerings that
  cross the process boundary take the DCN/TCP path — and every decision
  lands in the seam telemetry with an exact reason.
- elastic membership in ``runtime.cluster.ClusterRuntime``: ``add_host``
  (operator/autoscaler scale-up) and ``remove_host`` (planned
  decommission driving the PR-15 lineage ladder), plus the
  host-granularity fault ordinals (``killHostAtStage``,
  ``partitionDcnAtRequest``) that make host loss a deterministic CPU-CI
  event.

The differential suite emulates 2 hosts x 4 devices: the driver plus
two worker processes, each reconstructing a 4-device virtual-CPU mesh
slice, checked bit-exact against a single-process oracle running the
SAME mesh shape (same shard_map programs => identical float reduction
order; a no-mesh oracle only matches to tolerance).
"""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import Session
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.parallel import mesh as pmesh
from spark_rapids_tpu.parallel import spmd
from spark_rapids_tpu.parallel.mesh import (HostTopology, data_model_mesh,
                                            mesh_model_size)
from spark_rapids_tpu.runtime import recovery
from spark_rapids_tpu.runtime.cluster import (active_cluster,
                                              session_cluster,
                                              shutdown_session_cluster)
from spark_rapids_tpu.shuffle import fault_injection

MESH_CONF = {
    "rapids.tpu.mesh.enabled": True,
    "rapids.tpu.mesh.devices": 4,
    "rapids.tpu.sql.shuffle.partitions": 4,
    "rapids.tpu.sql.autoBroadcastJoinThreshold": 0,
}

CLUSTER_CONF = dict(MESH_CONF, **{
    "rapids.tpu.cluster.enabled": True,
    "rapids.tpu.cluster.workers": 2,
    "rapids.tpu.cluster.executors": 1,
})

JOIN_Q = ("SELECT s.k AS k, count(*) AS n, sum(s.v) AS sv, "
          "sum(d.w) AS sw FROM sales s JOIN dim d ON s.k = d.id "
          "GROUP BY s.k ORDER BY s.k")
GROUPBY_Q = ("SELECT k, count(*) AS n, sum(v) AS sv, min(v) AS mn, "
             "max(v) AS mx FROM sales GROUP BY k ORDER BY k")
SORT_Q = "SELECT k, v FROM sales ORDER BY v, k"


@pytest.fixture()
def cluster_teardown():
    yield
    shutdown_session_cluster()
    fault_injection.get_injector().disarm()


def _views(s: Session, n=3000) -> None:
    rng = np.random.default_rng(7)
    s.create_temp_view("sales", s.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 50, n).astype(np.int64),
        "v": rng.normal(size=n)}))
        .repartition(3, "k"))
    s.create_temp_view("dim", s.create_dataframe(pd.DataFrame({
        "id": np.arange(50, dtype=np.int64),
        "w": rng.normal(size=50)}))
        .repartition(2, "id"))


def _mesh_oracle(query: str) -> pd.DataFrame:
    """Single-process oracle with the SAME mesh shape as the cluster
    session: identical shard_map programs give identical float
    reduction order, so the differential can demand bit-exactness."""
    s = Session(dict(MESH_CONF))
    _views(s)
    return s.sql(query).collect()


# ---------------------------------------------------------------------------
# HostTopology / mesh construction
# ---------------------------------------------------------------------------


def test_host_topology_axis_math():
    t = HostTopology(n_hosts=2, devices_per_host=4)
    assert t.data_per_host == 4
    assert t.global_data == 8
    assert t.total_devices == 8
    assert [t.host_of(i) for i in range(8)] == [0] * 4 + [1] * 4
    assert t.seam(0, 3) == "ici"
    assert t.seam(3, 4) == "dcn"
    assert t.seam(7, 7) == "ici"
    with pytest.raises(AssertionError):
        t.host_of(8)
    assert t.axis_layout() == {"hosts": 2, "data_per_host": 4,
                               "model": 1, "global_data": 8,
                               "total_devices": 8}


def test_host_topology_model_axis_carves_data():
    t = HostTopology(n_hosts=2, devices_per_host=4, model=2)
    assert t.data_per_host == 2
    assert t.global_data == 4
    assert t.total_devices == 8
    assert t.seam(1, 2) == "dcn"  # host 0 holds data slots 0..1 only


def test_data_model_mesh_axes():
    m = data_model_mesh(2, 2)
    assert m.axis_names == (pmesh.DATA_AXIS, pmesh.MODEL_AXIS)
    assert m.shape[pmesh.DATA_AXIS] == 2
    assert m.shape[pmesh.MODEL_AXIS] == 2
    assert mesh_model_size(m) == 2
    # model=1 stays the plain 1-D data mesh (shard_map cache identity)
    m1 = data_model_mesh(4, 1)
    assert m1.axis_names == (pmesh.DATA_AXIS,)
    assert mesh_model_size(m1) == 1


def test_session_mesh_clamp_is_recorded_not_silent():
    import jax

    avail = len(jax.devices())
    want = avail + 56
    pre = pmesh.mesh_fallback_snapshot()
    m = pmesh.session_mesh(RapidsConf({
        "rapids.tpu.mesh.enabled": True,
        "rapids.tpu.mesh.devices": want}))
    assert m is not None and m.devices.size == avail
    delta = pmesh.mesh_fallback_delta(pre)
    key = (f"rapids.tpu.mesh.devices={want} exceeds the attached "
           f"backend ({avail} devices): clamped to {avail}")
    assert delta == {key: 1}, delta


def test_session_mesh_drops_starved_model_axis():
    pre = pmesh.mesh_fallback_snapshot()
    m = pmesh.session_mesh(RapidsConf({
        "rapids.tpu.mesh.enabled": True,
        "rapids.tpu.mesh.devices": 4,
        "rapids.tpu.mesh.modelDevices": 4}))
    # 4 devices / model=4 leaves 1 data device: axis dropped, recorded
    assert m is not None and mesh_model_size(m) == 1
    assert m.shape[pmesh.DATA_AXIS] == 4
    (reason,) = pmesh.mesh_fallback_delta(pre)
    assert reason == ("rapids.tpu.mesh.modelDevices=4 leaves fewer "
                      "than 2 data devices out of 4: model axis "
                      "dropped")


def test_session_mesh_carves_model_axis():
    m = pmesh.session_mesh(RapidsConf({
        "rapids.tpu.mesh.enabled": True,
        "rapids.tpu.mesh.devices": 8,
        "rapids.tpu.mesh.modelDevices": 2}))
    assert m is not None
    assert m.shape[pmesh.DATA_AXIS] == 4
    assert mesh_model_size(m) == 2


def test_session_topology_counts_cluster_hosts():
    t = pmesh.session_topology(RapidsConf(dict(CLUSTER_CONF)))
    assert t is not None
    assert t.n_hosts == 3  # driver + 2 workers
    assert t.devices_per_host == 4
    # explicit host count wins over inference
    t2 = pmesh.session_topology(RapidsConf(dict(
        CLUSTER_CONF, **{"rapids.tpu.mesh.hosts": 2})))
    assert t2 is not None and t2.n_hosts == 2
    assert pmesh.session_topology(RapidsConf(
        {"rapids.tpu.mesh.enabled": False})) is None


# ---------------------------------------------------------------------------
# per-seam in-program decision + seam telemetry
# ---------------------------------------------------------------------------


def test_seam_single_host_records_ici():
    pre = spmd.seam_snapshot()
    m = spmd.in_program_mesh(RapidsConf(dict(MESH_CONF)), "join")
    assert m is not None
    delta = spmd.seam_delta(pre)
    assert delta == {
        "join: ici: single host: no DCN seam in session": 1}, delta


def test_seam_cluster_local_stays_ici_in_program():
    """The per-seam decision replacing the all-or-nothing cluster gate:
    a host-local Mesh*Exec subtree keeps its collective in-program even
    with cluster mode on."""
    pre = spmd.seam_snapshot()
    conf = RapidsConf(dict(CLUSTER_CONF))
    m = spmd.in_program_mesh(conf, "groupby", cluster_local=True)
    assert m is not None, "cluster_local seam must stay ICI in-program"
    delta = spmd.seam_delta(pre)
    assert delta == {"groupby: ici: intra-host slice: collective "
                     "spans one process's devices": 1}, delta


def test_seam_cluster_exchange_takes_dcn():
    pre = spmd.seam_snapshot()
    pre_fb = spmd.fallback_snapshot()
    conf = RapidsConf(dict(CLUSTER_CONF))
    assert spmd.in_program_mesh(conf, "exchange") is None
    assert spmd.seam_delta(pre) == {
        "exchange: dcn: inter-host exchange: blocks cross the process "
        "boundary, TCP carries the DCN seam": 1}
    # the legacy fallback reason is preserved alongside the seam record
    fb = spmd.fallback_delta(pre_fb)
    assert fb == {"exchange: cross-host DCN: cluster mode shuffles "
                  "over TCP (shuffle/tcp.py)": 1}, fb


def test_seam_intra_host_ici_opt_out_restores_blanket_gate():
    pre = spmd.seam_snapshot()
    conf = RapidsConf(dict(CLUSTER_CONF, **{
        "rapids.tpu.shuffle.seam.intraHostIci.enabled": False}))
    assert spmd.in_program_mesh(conf, "sort", cluster_local=True) is None
    assert spmd.seam_delta(pre) == {
        "sort: dcn: intra-host ICI disabled by "
        "rapids.tpu.shuffle.seam.intraHostIci.enabled": 1}


def test_model_axis_gates_in_program_shuffle():
    pre = spmd.fallback_snapshot()
    conf = RapidsConf(dict(MESH_CONF, **{
        "rapids.tpu.mesh.devices": 8,
        "rapids.tpu.mesh.modelDevices": 2}))
    assert spmd.in_program_mesh(conf, "join") is None
    (reason,) = spmd.fallback_delta(pre)
    assert reason == ("join: model-parallel axis active: in-program "
                      "shuffle rides the data axis only")


# ---------------------------------------------------------------------------
# emulated 2-host x 4-device differential suite
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("query", [JOIN_Q, GROUPBY_Q, SORT_Q],
                         ids=["hash_join", "group_by", "sort"])
def test_multihost_differential_bit_exact(query, cluster_teardown):
    """join / group-by / sort over driver + 2 worker processes (each a
    4-device virtual mesh slice), bit-exact against the single-process
    same-mesh oracle — ``DataFrame.equals``, not approximate compare:
    identical shard_map programs must give identical bits."""
    oracle = _mesh_oracle(query)
    s = Session(dict(CLUSTER_CONF))
    _views(s)
    got = s.sql(query).collect()
    assert got.equals(oracle), "cluster result diverged from the " \
        "same-mesh single-process oracle"
    runtime = session_cluster(s.conf)
    assert runtime is not None and len(runtime.workers) == 2


def test_multihost_seam_decisions_recorded(cluster_teardown):
    """One cluster join: the seam telemetry must hold BOTH sides of the
    per-seam decision — DCN records for every materialized cluster
    exchange, ICI records for the host-local mesh subtrees — with the
    exact reason strings the docs promise."""
    s = Session(dict(CLUSTER_CONF))
    _views(s)
    pre = spmd.seam_snapshot()
    got = s.sql(JOIN_Q).collect()
    assert len(got) == 50
    delta = spmd.seam_delta(pre)
    dcn_key = ("exchange: dcn: cluster exchange: map outputs cross "
               "the host boundary over TCP")
    # >= 2 materialized cluster exchanges: the mesh lowering absorbs
    # one join side into the shipped subtree, the rest cross the seam
    assert delta.get(dcn_key, 0) >= 2, delta
    ici = {k: n for k, n in delta.items() if ": ici: " in k}
    assert ici, f"no ICI seam decision recorded: {delta}"
    assert all(("intra-host slice: collective spans one process's "
                "devices") in k or "single host" in k
               for k in ici), ici


# ---------------------------------------------------------------------------
# elastic membership: add/remove/kill hosts
# ---------------------------------------------------------------------------


def test_add_and_remove_host_drive_recovery_ladder(cluster_teardown):
    """Scale-up then planned decommission mid-session: the new slot
    takes placements, the removed slot's map outputs re-run through the
    PR-15 lineage ladder (invalidate -> re-run exactly the lost maps),
    queries before/between/after stay bit-exact, and the recovery
    counters tell the story."""
    oracle = _mesh_oracle(JOIN_Q)
    s = Session(dict(CLUSTER_CONF))
    _views(s)
    assert s.sql(JOIN_Q).collect().equals(oracle)

    rt = active_cluster()
    assert rt is not None
    pre = recovery.snapshot()

    eid = rt.add_host(reason="test scale-up")
    assert eid == "exec-worker-2"
    assert sorted(rt.live_worker_slots()) == [
        "exec-worker-0", "exec-worker-1", "exec-worker-2"]
    assert s.sql(JOIN_Q).collect().equals(oracle)

    rerun = rt.remove_host("exec-worker-0", reason="test scale-down")
    assert rerun, "decommission re-ran no maps: the removed slot " \
        "held registered output"
    assert sorted(rt.live_worker_slots()) == [
        "exec-worker-1", "exec-worker-2"]
    # decommission is NOT a fault: no blacklist entry, no respawn
    assert "exec-worker-0" in rt.decommissioned
    assert s.sql(JOIN_Q).collect().equals(oracle)

    delta = recovery.delta(pre)
    assert delta["hosts_added"] == 1
    assert delta["hosts_removed"] == 1
    assert delta["maps_rerun"] >= len(rerun)
    assert delta["executors_blacklisted"] == 0
    assert delta["workers_respawned"] == 0
    actions = [e["action"] for e in rt.scale_events]
    assert actions == ["add", "remove"]


def test_kill_host_at_stage_recovers_bit_exact(cluster_teardown):
    """Deterministic host loss: ``killHostAtStage=4`` SIGKILLs the
    output-owning worker at the fourth stage boundary — the final
    exchange's reduce entry, when every map output is registered, the
    worst moment to lose a host. Recovery must discover the death
    organically (fetch failures), respawn the slot, re-run its maps,
    and still produce the bit-exact answer."""
    oracle = _mesh_oracle(JOIN_Q)
    pre = recovery.snapshot()
    s = Session(dict(CLUSTER_CONF))
    _views(s)
    fault_injection.arm_from_conf(RapidsConf({
        "rapids.tpu.shuffle.faultInjection.enabled": True,
        "rapids.tpu.shuffle.faultInjection.killHostAtStage": 4}))
    try:
        got = s.sql(JOIN_Q).collect()
        stats = fault_injection.get_injector().stats()
    finally:
        fault_injection.get_injector().disarm()
    assert got.equals(oracle)
    assert stats["host_kills"] == 1, stats
    delta = recovery.delta(pre)
    assert delta["workers_respawned"] >= 1, delta
    assert delta["maps_rerun"] >= 1, delta


def test_partition_dcn_at_request_retries_through(cluster_teardown):
    """A transient DCN partition (a burst of injected transport
    failures on the inter-host link) resolves through the transport
    retry + stage-retry ladder, bit-exact, with the partition counted
    once in the recovery stats."""
    oracle = _mesh_oracle(JOIN_Q)
    pre = recovery.snapshot()
    s = Session(dict(CLUSTER_CONF))
    _views(s)
    fault_injection.arm_from_conf(RapidsConf({
        "rapids.tpu.shuffle.faultInjection.enabled": True,
        "rapids.tpu.shuffle.faultInjection.partitionDcnAtRequest": 3,
        "rapids.tpu.shuffle.faultInjection.consecutive": 2}))
    try:
        got = s.sql(JOIN_Q).collect()
        stats = fault_injection.get_injector().stats()
    finally:
        fault_injection.get_injector().disarm()
    assert got.equals(oracle)
    assert stats["dcn_partitions"] == 1, stats
    assert stats["dcn_drops"] >= 2, stats
    assert recovery.delta(pre).get("dcn_partitions", 0) == 1


def test_injector_host_and_dcn_ordinals_are_deterministic():
    inj = fault_injection.ShuffleFaultInjector()
    inj.arm(kill_host_at_stage=2)
    assert [inj.should_kill_host_at_stage() for _ in range(4)] == \
        [False, True, False, False]
    inj.arm(partition_dcn_at_request=3, consecutive=2)
    assert [inj.should_partition_dcn() for _ in range(5)] == \
        [False, False, True, True, False]
    assert inj.stats()["dcn_partitions"] == 1
    inj.disarm()


# ---------------------------------------------------------------------------
# autoscaler
# ---------------------------------------------------------------------------


def _autoscale_conf(**extra):
    return RapidsConf(dict({
        "rapids.tpu.cluster.enabled": True,
        "rapids.tpu.cluster.autoscale.enabled": True,
        "rapids.tpu.cluster.autoscale.queueDepthHigh": 2,
        "rapids.tpu.cluster.autoscale.maxWorkers": 3,
        "rapids.tpu.cluster.autoscale.cooldownSec": 0.0,
    }, **extra))


class _FakeRuntime:
    def __init__(self, slots=1):
        self._slots = ["exec-worker-%d" % i for i in range(slots)]
        self.added = []
        self.removed = []

    def live_worker_slots(self):
        return list(self._slots)

    def add_host(self, reason=""):
        eid = "exec-worker-%d" % len(self._slots)
        self._slots.append(eid)
        self.added.append(reason)
        return eid

    def remove_host(self, executor_id, reason=""):
        self._slots.remove(executor_id)
        self.removed.append((executor_id, reason))
        return []


def test_autoscaler_fires_on_queue_pressure(monkeypatch):
    from spark_rapids_tpu.runtime import cluster as rc
    from spark_rapids_tpu.service.autoscaler import ClusterAutoscaler

    fake = _FakeRuntime(slots=1)
    monkeypatch.setattr(rc, "active_cluster", lambda: fake)
    a = ClusterAutoscaler(_autoscale_conf())
    assert a.observe(queue_depth=1, inflight=0) is None  # below high
    eid = a.observe(queue_depth=4, inflight=1)
    assert eid == "exec-worker-1"
    assert a.scale_ups == 1
    assert "queue depth 4 >= 2" in a.last_reason
    assert fake.added == ["autoscaler: queue depth 4 >= 2 with 1 "
                          "inflight"]
    # grows to the ceiling, then refuses
    assert a.observe(queue_depth=9, inflight=0) == "exec-worker-2"
    assert a.observe(queue_depth=9, inflight=0) is None  # at max 3
    assert a.scale_ups == 2


def test_autoscaler_cooldown_and_gates(monkeypatch):
    from spark_rapids_tpu.runtime import cluster as rc
    from spark_rapids_tpu.service.autoscaler import ClusterAutoscaler

    fake = _FakeRuntime(slots=1)
    monkeypatch.setattr(rc, "active_cluster", lambda: fake)
    a = ClusterAutoscaler(_autoscale_conf(**{
        "rapids.tpu.cluster.autoscale.cooldownSec": 3600.0}))
    assert a.observe(queue_depth=5, inflight=0) is not None
    assert a.observe(queue_depth=50, inflight=0) is None  # in cooldown
    assert a.scale_ups == 1
    # disabled without cluster mode: autoscale extends membership, it
    # never creates it
    off = ClusterAutoscaler(RapidsConf({
        "rapids.tpu.cluster.autoscale.enabled": True}))
    assert not off.enabled
    assert off.observe(queue_depth=99, inflight=0) is None
    # no active cluster runtime -> no-op even when enabled
    monkeypatch.setattr(rc, "active_cluster", lambda: None)
    a2 = ClusterAutoscaler(_autoscale_conf())
    assert a2.observe(queue_depth=99, inflight=0) is None


def test_autoscaler_scale_down_after_sustained_idle(monkeypatch):
    """queueDepthLow + zero inflight sustained past idleSec fires
    remove_host on the NEWEST worker (LIFO), mirror of scale-up."""
    import time as _time

    from spark_rapids_tpu.runtime import cluster as rc
    from spark_rapids_tpu.service.autoscaler import ClusterAutoscaler

    fake = _FakeRuntime(slots=3)
    monkeypatch.setattr(rc, "active_cluster", lambda: fake)
    a = ClusterAutoscaler(_autoscale_conf(**{
        "rapids.tpu.cluster.autoscale.queueDepthLow": 0,
        "rapids.tpu.cluster.autoscale.idleSec": 10.0,
    }))
    # first idle observation only ARMS the window
    assert a.observe(queue_depth=0, inflight=0) is None
    assert a.scale_downs == 0
    # window not yet elapsed -> no fire
    a.observe(queue_depth=0, inflight=0)
    assert a.scale_downs == 0
    # backdate the window: sustained idle -> newest worker leaves
    a._idle_since = _time.monotonic() - 100.0
    a.observe(queue_depth=0, inflight=0)
    assert a.scale_downs == 1
    assert fake.live_worker_slots() == ["exec-worker-0",
                                        "exec-worker-1"]
    assert a.last_removed_executor_id == "exec-worker-2"
    eid, reason = fake.removed[0]
    assert eid == "exec-worker-2" and "autoscaler:" in reason
    s = a.stats()
    assert s["scale_downs"] == 1 and s["min_workers"] == 1


def test_autoscaler_scale_down_gates(monkeypatch):
    """Inflight work, queued work, cooldown, and the minWorkers floor
    each hold a shrink back; negative queueDepthLow disables it."""
    import time as _time

    from spark_rapids_tpu.runtime import cluster as rc
    from spark_rapids_tpu.service.autoscaler import ClusterAutoscaler

    fake = _FakeRuntime(slots=2)
    monkeypatch.setattr(rc, "active_cluster", lambda: fake)
    a = ClusterAutoscaler(_autoscale_conf(**{
        "rapids.tpu.cluster.autoscale.queueDepthLow": 0,
        "rapids.tpu.cluster.autoscale.idleSec": 0.0,
        "rapids.tpu.cluster.autoscale.minWorkers": 1,
    }))
    # inflight work resets the idle window entirely
    a._idle_since = _time.monotonic() - 100.0
    assert a.observe(queue_depth=0, inflight=1) is None
    assert a._idle_since is None and a.scale_downs == 0
    # idleSec=0: arm on the first idle pump, fire on the second
    a.observe(queue_depth=0, inflight=0)
    a.observe(queue_depth=0, inflight=0)
    assert a.scale_downs == 1
    # at the floor: never below minWorkers
    a.observe(queue_depth=0, inflight=0)
    a.observe(queue_depth=0, inflight=0)
    assert a.scale_downs == 1
    assert fake.live_worker_slots() == ["exec-worker-0"]
    # default conf: queueDepthLow < 0 -> scale-down disabled outright
    fake2 = _FakeRuntime(slots=3)
    monkeypatch.setattr(rc, "active_cluster", lambda: fake2)
    b = ClusterAutoscaler(_autoscale_conf(**{
        "rapids.tpu.cluster.autoscale.idleSec": 0.0}))
    for _ in range(4):
        b.observe(queue_depth=0, inflight=0)
    assert b.scale_downs == 0 and len(fake2.live_worker_slots()) == 3


def test_autoscaler_scale_down_cooldown_spans_directions(monkeypatch):
    """The cooldown is shared across scale directions: a fresh
    scale-up holds the next scale-down back (flap damping)."""
    import time as _time

    from spark_rapids_tpu.runtime import cluster as rc
    from spark_rapids_tpu.service.autoscaler import ClusterAutoscaler

    fake = _FakeRuntime(slots=1)
    monkeypatch.setattr(rc, "active_cluster", lambda: fake)
    a = ClusterAutoscaler(_autoscale_conf(**{
        "rapids.tpu.cluster.autoscale.cooldownSec": 3600.0,
        "rapids.tpu.cluster.autoscale.queueDepthLow": 0,
        "rapids.tpu.cluster.autoscale.idleSec": 0.0,
    }))
    assert a.observe(queue_depth=5, inflight=0) is not None  # scale up
    a._idle_since = _time.monotonic() - 100.0
    a.observe(queue_depth=0, inflight=0)
    assert a.scale_downs == 0  # inside the shared cooldown
    a._last_at = _time.monotonic() - 7200.0  # cooldown elapses
    a._idle_since = _time.monotonic() - 100.0
    a.observe(queue_depth=0, inflight=0)
    assert a.scale_downs == 1


# ---------------------------------------------------------------------------
# tcp retry policy (jitter + reconnect cap)
# ---------------------------------------------------------------------------


def test_tcp_retry_policy_from_conf():
    from spark_rapids_tpu.shuffle import tcp

    before = dict(tcp._retry_policy)
    try:
        tcp.configure_retry_from_conf(RapidsConf({
            "rapids.tpu.shuffle.retry.maxReconnects": 5,
            "rapids.tpu.shuffle.retry.jitterMs": 25}))
        assert tcp._retry_policy == {"max_reconnects": 5,
                                     "jitter_ms": 25}
    finally:
        tcp.configure_retry(**before)


def test_tcp_connection_picks_up_policy():
    from spark_rapids_tpu.shuffle import tcp

    before = dict(tcp._retry_policy)
    try:
        tcp.configure_retry(max_reconnects=7, jitter_ms=40)
        conn = tcp.TcpConnection("127.0.0.1", 1)
        assert conn._max_retries == 7
        assert conn._jitter_s == pytest.approx(0.040)
        # explicit constructor arg still wins over the policy
        conn2 = tcp.TcpConnection("127.0.0.1", 1,
                                  max_transient_retries=2)
        assert conn2._max_retries == 2
    finally:
        tcp.configure_retry(**before)


# ---------------------------------------------------------------------------
# runner surfaces mesh fallbacks + seam decisions
# ---------------------------------------------------------------------------


def test_runner_embeds_mesh_and_seam_telemetry(tmp_path):
    """The runner JSON carries ``mesh_fallbacks`` and ``seam_decisions``
    next to ``shuffle_fallbacks`` — satellite 1's 'surfaced, not
    silent' contract for the session_mesh clamp. Subprocess because
    dispatch telemetry must install before the compute modules import
    (same constraint as the dispatch-budget fence)."""
    import json
    import os
    import subprocess
    import sys

    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = (
        "import json, sys\n"
        f"sys.path.insert(0, {root!r})\n"
        "from spark_rapids_tpu.utils import dispatch as disp\n"
        "disp.install()\n"
        "from spark_rapids_tpu.benchmarks.runner import BenchmarkRunner\n"
        f"r = BenchmarkRunner({str(tmp_path)!r}, 0.01)\n"
        "rec = r.run('tpch_q6', iterations=1, warmup=0)\n"
        "tel = rec['dispatch_telemetry']\n"
        "print(json.dumps(sorted(tel)))\n")
    out = subprocess.run(
        [sys.executable, "-c", script],
        env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-3000:]
    keys = json.loads(out.stdout.strip().splitlines()[-1])
    assert "mesh_fallbacks" in keys, keys
    assert "seam_decisions" in keys, keys
    assert "shuffle_fallbacks" in keys, keys
