"""Memory subsystem tests, standalone against the allocator like the
reference's RapidsDeviceMemoryStoreSuite / RapidsHostMemoryStoreSuite /
RapidsDiskStoreSuite / RapidsBufferCatalogSuite (SURVEY.md §4)."""
import threading

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.columnar import serde
from spark_rapids_tpu.memory import (
    ACTIVE_ON_DECK_PRIORITY,
    OUTPUT_FOR_SHUFFLE_PRIORITY,
    BufferCatalog,
    SpillableBatch,
    StorageTier,
    TpuSemaphore,
    with_oom_retry,
)


def make_batch(n=100, with_nulls=True, with_strings=False, seed=0):
    rng = np.random.default_rng(seed)
    vals = rng.integers(0, 1000, n).astype(np.int64)
    validity = (rng.random(n) > 0.2) if with_nulls else None
    cols = [Column.from_numpy(vals, dt.INT64, validity=validity),
            Column.from_numpy(rng.random(n), dt.FLOAT64)]
    if with_strings:
        strs = [None if rng.random() < 0.1 else f"s{rng.integers(0, 50)}"
                for _ in range(n)]
        cols.append(StringColumn.from_strings(strs))
    cap = cols[0].capacity
    cols = [c.with_capacity(cap) for c in cols]
    return ColumnarBatch(cols, n)


def batch_equal(a: ColumnarBatch, b: ColumnarBatch):
    assert a.realized_num_rows() == b.realized_num_rows()
    n = a.realized_num_rows()
    for ca, cb in zip(a.columns, b.columns):
        va, ma = ca.to_numpy(n)
        vb, mb = cb.to_numpy(n)
        if ma is None:
            assert mb is None
            np.testing.assert_array_equal(va, vb)
        else:
            np.testing.assert_array_equal(ma, mb)
            np.testing.assert_array_equal(va[ma], vb[mb])


class TestSerde:
    def test_roundtrip_host(self):
        b = make_batch(with_strings=True)
        hb = serde.to_host_batch(b)
        back = serde.to_device_batch(hb)
        batch_equal(b, back)

    def test_roundtrip_bytes(self):
        b = make_batch(with_strings=True, seed=3)
        data = serde.serialize_host_batch(serde.to_host_batch(b))
        back = serde.to_device_batch(serde.deserialize_host_batch(data))
        batch_equal(b, back)

    def test_empty_columns_batch(self):
        b = ColumnarBatch([], 42)  # rows-only degenerate batch
        data = serde.serialize_host_batch(serde.to_host_batch(b))
        back = serde.deserialize_host_batch(data)
        assert back.num_rows == 42 and back.columns == []


class TestCatalog:
    def test_register_acquire_release(self):
        cat = BufferCatalog()
        b = make_batch()
        bid = cat.register(b, ACTIVE_ON_DECK_PRIORITY)
        assert cat.tier_of(bid) is StorageTier.DEVICE
        got = cat.acquire(bid)
        batch_equal(b, got)
        cat.release(bid)
        cat.remove(bid)
        assert bid not in cat

    def test_spill_to_host_and_back(self):
        cat = BufferCatalog()
        b = make_batch(with_strings=True)
        bid = cat.register(b, OUTPUT_FOR_SHUFFLE_PRIORITY)
        spilled = cat.synchronous_spill(0)
        assert spilled > 0
        assert cat.tier_of(bid) is StorageTier.HOST
        assert cat.device_bytes == 0
        got = cat.acquire(bid)
        assert cat.tier_of(bid) is StorageTier.DEVICE
        batch_equal(b, got)
        cat.release(bid)

    def test_spill_cascade_to_disk(self, tmp_path):
        cat = BufferCatalog(host_budget=0, spill_dir=str(tmp_path))
        b = make_batch(with_strings=True, seed=7)
        bid = cat.register(b, OUTPUT_FOR_SHUFFLE_PRIORITY)
        cat.synchronous_spill(0)  # device→host then cascades host→disk
        assert cat.tier_of(bid) is StorageTier.DISK
        assert cat.host_bytes == 0
        got = cat.acquire(bid)
        batch_equal(b, got)
        cat.release(bid)
        cat.remove(bid)

    def test_spill_priority_order(self):
        cat = BufferCatalog()
        lo = cat.register(make_batch(seed=1), OUTPUT_FOR_SHUFFLE_PRIORITY)
        hi = cat.register(make_batch(seed=2), ACTIVE_ON_DECK_PRIORITY)
        # spill just enough for one buffer: shuffle output goes first
        cat.synchronous_spill(cat.device_bytes - 1)
        assert cat.tier_of(lo) is StorageTier.HOST
        assert cat.tier_of(hi) is StorageTier.DEVICE

    def test_acquired_buffer_cannot_spill(self):
        cat = BufferCatalog()
        bid = cat.register(make_batch(), OUTPUT_FOR_SHUFFLE_PRIORITY)
        cat.acquire(bid)
        assert cat.synchronous_spill(0) == 0  # pinned
        assert cat.tier_of(bid) is StorageTier.DEVICE
        cat.release(bid)
        assert cat.synchronous_spill(0) > 0

    def test_device_budget_spills_on_register(self):
        one = make_batch(seed=1)
        size = one.device_memory_size()
        cat = BufferCatalog(device_budget=size)
        a = cat.register(one, OUTPUT_FOR_SHUFFLE_PRIORITY)
        b = cat.register(make_batch(seed=2), OUTPUT_FOR_SHUFFLE_PRIORITY)
        assert cat.device_bytes <= size
        assert StorageTier.HOST in (cat.tier_of(a), cat.tier_of(b))

    def test_concurrent_register_spill(self):
        cat = BufferCatalog()
        ids = []
        lock = threading.Lock()

        def worker(seed):
            bid = cat.register(make_batch(seed=seed),
                               OUTPUT_FOR_SHUFFLE_PRIORITY)
            with lock:
                ids.append(bid)
            got = cat.acquire(bid)
            assert got is not None
            cat.release(bid)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        spill = threading.Thread(target=lambda: cat.synchronous_spill(0))
        spill.start()
        for t in ts + [spill]:
            t.join()
        for bid in ids:
            batch = cat.acquire(bid)
            assert batch.realized_num_rows() == 100
            cat.release(bid)


class TestSpillableBatch:
    def test_lifecycle(self):
        cat = BufferCatalog()
        b = make_batch()
        with SpillableBatch(b, ACTIVE_ON_DECK_PRIORITY, catalog=cat) as sb:
            cat.synchronous_spill(0)
            with sb.acquired() as got:
                batch_equal(b, got)
        assert len(cat) == 0


class TestSemaphore:
    def test_reentrant_per_task(self):
        sem = TpuSemaphore(1)
        sem.acquire_if_necessary(task_id=1)
        sem.acquire_if_necessary(task_id=1)  # no deadlock
        assert sem.holds(task_id=1)
        sem.release_if_necessary(task_id=1)
        assert not sem.holds(task_id=1)
        sem.acquire_if_necessary(task_id=2)
        sem.release_if_necessary(task_id=2)

    def test_limits_concurrency(self):
        sem = TpuSemaphore(2)
        running = []
        peak = []
        lock = threading.Lock()

        def task(tid):
            sem.acquire_if_necessary(task_id=tid)
            with lock:
                running.append(tid)
                peak.append(len(running))
            with lock:
                running.remove(tid)
            sem.release_if_necessary(task_id=tid)

        ts = [threading.Thread(target=task, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert max(peak) <= 2


class TestOomRetry:
    def test_spills_and_retries(self):
        cat = BufferCatalog()
        cat.register(make_batch(), OUTPUT_FOR_SHUFFLE_PRIORITY)
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory "
                                   "allocating 123 bytes")
            return "ok"

        assert with_oom_retry(fn, catalog=cat) == "ok"
        assert cat.device_bytes < make_batch().device_memory_size() + 1

    def test_non_oom_reraises(self):
        with pytest.raises(ValueError):
            with_oom_retry(lambda: (_ for _ in ()).throw(ValueError("x")))


class TestCatalogRaces:
    def test_remove_while_acquired_defers(self, tmp_path):
        cat = BufferCatalog(host_budget=0, spill_dir=str(tmp_path))
        b = make_batch(seed=11)
        bid = cat.register(b, OUTPUT_FOR_SHUFFLE_PRIORITY)
        cat.synchronous_spill(0)  # to disk
        got = cat.acquire(bid)
        cat.remove(bid)  # must defer: still acquired
        batch_equal(b, got)
        assert bid in cat
        cat.release(bid)  # completes the deferred removal
        assert bid not in cat

    def test_nested_with_does_not_drop_outer_permit(self):
        sem = TpuSemaphore(1)
        with sem:
            with sem:  # reentrant inner scope
                pass
            assert sem.holds()  # outer still holds after inner exit
        assert not sem.holds()


class TestCatalogConcurrentPressure:
    """Satellite coverage (PR 6): the catalog under concurrent
    unspill/release/remove_owner traffic, spill attempts against
    acquired buffers, and disk-tier corruption detection."""

    def test_unspill_races_release_and_remove_owner(self, tmp_path):
        """Readers acquire (unspilling from disk) while another thread
        sweeps remove_owner and a third keeps spilling: every acquire
        that wins sees intact data; removal of acquired entries defers;
        nothing deadlocks or leaks."""
        from spark_rapids_tpu.memory.catalog import set_buffer_owner

        cat = BufferCatalog(host_budget=0, spill_dir=str(tmp_path))
        owner = ("q", 1)
        prev = set_buffer_owner(owner)
        try:
            ids = [cat.register(make_batch(seed=i, with_strings=True),
                                OUTPUT_FOR_SHUFFLE_PRIORITY)
                   for i in range(6)]
        finally:
            set_buffer_owner(prev)
        cat.synchronous_spill(0)  # all to disk (host budget 0)
        errors = []
        stop = threading.Event()

        def reader(bid):
            try:
                while not stop.is_set():
                    try:
                        b = cat.acquire(bid)
                    except KeyError:
                        return  # removed by the sweeper: fine
                    try:
                        assert b.realized_num_rows() == 100
                    finally:
                        cat.release(bid)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        def spiller():
            try:
                for _ in range(20):
                    cat.synchronous_spill(0)
            except Exception as e:  # noqa: BLE001
                errors.append(e)

        threads = [threading.Thread(target=reader, args=(bid,))
                   for bid in ids] + [threading.Thread(target=spiller)]
        for t in threads:
            t.start()
        import time as _t

        _t.sleep(0.1)
        removed = cat.remove_owner(owner)  # races the readers
        stop.set()
        for t in threads:
            t.join(10)
        assert not errors
        assert removed == 6
        # deferred removals complete once readers released
        assert len(cat) == 0

    def test_spill_skips_acquired_buffer_under_concurrency(self):
        """A refcount>0 buffer never spills even while another thread
        hammers synchronous_spill."""
        cat = BufferCatalog()
        pinned = cat.register(make_batch(seed=1),
                              OUTPUT_FOR_SHUFFLE_PRIORITY)
        victim = cat.register(make_batch(seed=2),
                              OUTPUT_FOR_SHUFFLE_PRIORITY)
        cat.acquire(pinned)
        spillers = [threading.Thread(
            target=lambda: cat.synchronous_spill(0)) for _ in range(4)]
        for t in spillers:
            t.start()
        for t in spillers:
            t.join(10)
        assert cat.tier_of(pinned) is StorageTier.DEVICE
        assert cat.tier_of(victim) is StorageTier.HOST
        cat.release(pinned)
        cat.synchronous_spill(0)
        assert cat.tier_of(pinned) is StorageTier.HOST

    def test_truncated_spill_file_raises_clear_error(self, tmp_path):
        """Disk-tier corruption (truncated file) surfaces as
        SpillCorruptionError naming the buffer — never garbage rows."""
        import os

        from spark_rapids_tpu.memory import SpillCorruptionError

        cat = BufferCatalog(host_budget=0, spill_dir=str(tmp_path))
        bid = cat.register(make_batch(seed=3, with_strings=True),
                           OUTPUT_FOR_SHUFFLE_PRIORITY)
        cat.synchronous_spill(0)
        assert cat.tier_of(bid) is StorageTier.DISK
        path = os.path.join(str(tmp_path), f"spill-{bid}.srt")
        size = os.path.getsize(path)
        with open(path, "rb+") as f:
            f.truncate(size // 2)
        with pytest.raises(SpillCorruptionError,
                           match=f"buffer {bid}"):
            cat.acquire(bid)
        # the entry survives the failed unspill and stays removable
        cat.remove(bid)
        assert bid not in cat

    def test_bitflip_spill_file_fails_checksum(self, tmp_path):
        from spark_rapids_tpu.memory import SpillCorruptionError

        cat = BufferCatalog(host_budget=0, spill_dir=str(tmp_path))
        bid = cat.register(make_batch(seed=4),
                           OUTPUT_FOR_SHUFFLE_PRIORITY)
        cat.synchronous_spill(0)
        import os

        path = os.path.join(str(tmp_path), f"spill-{bid}.srt")
        with open(path, "rb+") as f:
            f.seek(-1, os.SEEK_END)
            last = f.read(1)
            f.seek(-1, os.SEEK_END)
            f.write(bytes([last[0] ^ 0xFF]))
        with pytest.raises(SpillCorruptionError):
            cat.acquire(bid)


class TestAsyncSpill:
    def test_async_host_to_disk_commits_after_flush(self, tmp_path):
        cat = BufferCatalog(host_budget=0, spill_dir=str(tmp_path),
                            async_spill=True)
        b = make_batch(seed=21, with_strings=True)
        bid = cat.register(b, OUTPUT_FOR_SHUFFLE_PRIORITY)
        cat.synchronous_spill(0)  # D2H inline, H2D handed to writer
        cat.flush_spills()
        assert cat.tier_of(bid) is StorageTier.DISK
        assert cat.host_bytes == 0
        got = cat.acquire(bid)
        batch_equal(b, got)
        cat.release(bid)
        cat.remove(bid)
        # close() ends the writer thread (no parked daemon pinning the
        # catalog) and the catalog stays usable afterwards
        writer_thread = cat._writer._thread
        cat.close()
        assert not writer_thread.is_alive()
        bid2 = cat.register(make_batch(seed=22),
                            OUTPUT_FOR_SHUFFLE_PRIORITY)
        cat.synchronous_spill(0)
        cat.flush_spills()
        assert cat.tier_of(bid2) is StorageTier.DISK
        cat.close()

    def test_acquire_races_inflight_write(self, tmp_path):
        """Acquiring while the writer still owns the host batch either
        unspills from host (write aborts, file unlinked) or from the
        committed disk file — both hand back intact data."""
        cat = BufferCatalog(host_budget=0, spill_dir=str(tmp_path),
                            async_spill=True)
        batches = {cat.register(make_batch(seed=30 + i),
                                OUTPUT_FOR_SHUFFLE_PRIORITY): i
                   for i in range(4)}
        cat.synchronous_spill(0)
        for bid in batches:
            got = cat.acquire(bid)  # may race the in-flight write
            assert got.realized_num_rows() == 100
            cat.release(bid)
        cat.flush_spills()
        for bid in list(batches):
            cat.remove(bid)
        assert len(cat) == 0
        cat.close()

    def test_writer_backpressure_bounded_queue(self, tmp_path):
        """A burst of evictions completes (the depth-2 queue blocks
        the submitter, never drops or deadlocks)."""
        cat = BufferCatalog(host_budget=0, spill_dir=str(tmp_path),
                            async_spill=True)
        ids = [cat.register(make_batch(seed=50 + i),
                            OUTPUT_FOR_SHUFFLE_PRIORITY)
               for i in range(10)]
        cat.synchronous_spill(0)
        cat.flush_spills()
        assert all(cat.tier_of(bid) is StorageTier.DISK for bid in ids)
        cat.close()


def test_hashed_priority_queue():
    from spark_rapids_tpu.memory.hashed_pq import HashedPriorityQueue

    q = HashedPriorityQueue()
    items = [(f"b{i}", ((i * 7) % 5, i)) for i in range(50)]
    for it, key in items:
        q.push(it, key)
    assert len(q) == 50 and "b3" in q
    # removal of arbitrary members
    assert q.remove("b3") and not q.remove("b3")
    # priority update resorts
    q.update("b10", (-1, 0))
    assert q.peek() == "b10"
    # pops come out in key order
    order = [q.pop() for _ in range(len(q))]
    keys = dict(items)
    assert order[0] == "b10"
    rest = order[1:]
    assert rest == sorted(rest, key=lambda it: keys[it])
    assert q.pop() is None


def test_victim_selection_uses_queues(tmp_path):
    """Spill order: lowest (priority, seq) first, pinned entries skipped,
    re-exposed after release."""
    import numpy as np

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.memory.catalog import (BufferCatalog,
                                                 StorageTier)

    cat = BufferCatalog(spill_dir=str(tmp_path))

    def mk(n):
        return ColumnarBatch(
            [Column.from_numpy(np.arange(n, dtype=np.int64))], n)

    hi = cat.register(mk(256), priority=100)
    lo = cat.register(mk(256), priority=0)
    mid = cat.register(mk(256), priority=50)
    # pin the lowest-priority entry: it must be skipped
    cat.acquire(lo)
    cat.synchronous_spill(cat.device_bytes - 1)  # spill exactly one
    assert cat.tier_of(mid) is StorageTier.HOST  # mid, not pinned lo
    assert cat.tier_of(lo) is StorageTier.DEVICE
    cat.release(lo)
    cat.synchronous_spill(0)
    assert cat.tier_of(lo) is StorageTier.HOST
    assert cat.tier_of(hi) is StorageTier.HOST
    # everything re-acquirable after the shuffle of tiers
    for bid in (hi, lo, mid):
        b = cat.acquire(bid)
        assert b.realized_num_rows() == 256
        cat.release(bid)


def test_address_space_allocator():
    from spark_rapids_tpu.memory.address_space import \
        AddressSpaceAllocator

    a = AddressSpaceAllocator(1000)
    o1 = a.allocate(400)
    o2 = a.allocate(400)
    assert {o1, o2} == {0, 400}
    assert a.allocate(400) is None  # only 200 left
    o3 = a.allocate(200)
    assert o3 == 800 and a.available_bytes == 0
    a.free(o2)
    # coalescing: freeing the middle then an end must merge
    a.free(o3)
    assert a.largest_free_block == 600
    assert a.allocate(600) == 400
    a.free(o1)
    import pytest as _p

    with _p.raises(KeyError):
        a.free(123)
