"""Composable random data generators — the integration_tests data_gen.py
DSL of the reference (per-type gens, special values, nullable wrappers,
seeds; SURVEY.md §4)."""
from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.plan import nodes as pn


class DataGen:
    dtype: dt.DType

    def __init__(self, nullable: float = 0.1):
        self.null_prob = nullable

    def _values(self, rng: np.random.Generator, n: int) -> np.ndarray:
        raise NotImplementedError

    def generate(self, rng: np.random.Generator, n: int):
        data = self._values(rng, n)
        validity = rng.random(n) >= self.null_prob \
            if self.null_prob > 0 else np.ones(n, dtype=bool)
        return data, validity


class _IntGen(DataGen):
    lo: int
    hi: int

    def _values(self, rng, n):
        vals = rng.integers(self.lo, self.hi, n, endpoint=True)
        # seed boundary values like the reference's special cases
        for v in (self.lo, self.hi, 0):
            if n > 3:
                vals[rng.integers(0, n)] = v
        return vals.astype(self.dtype.np_dtype)


class ByteGen(_IntGen):
    dtype, lo, hi = dt.INT8, -128, 127


class ShortGen(_IntGen):
    dtype, lo, hi = dt.INT16, -(1 << 15), (1 << 15) - 1


class IntegerGen(_IntGen):
    dtype, lo, hi = dt.INT32, -(1 << 31), (1 << 31) - 1


class LongGen(_IntGen):
    dtype, lo, hi = dt.INT64, -(1 << 63), (1 << 63) - 1


class SmallIntGen(_IntGen):
    """Small-range ints: friendly keys for joins/groupbys."""

    dtype, lo, hi = dt.INT64, -50, 50


class BooleanGen(DataGen):
    dtype = dt.BOOLEAN

    def _values(self, rng, n):
        return rng.random(n) > 0.5


class _FloatGen(DataGen):
    specials = (float("nan"), float("inf"), float("-inf"), -0.0, 0.0)

    def _values(self, rng, n):
        vals = (rng.random(n) * 2 - 1) * 10.0 ** rng.integers(-3, 6, n)
        for s in self.specials:
            if n > len(self.specials):
                vals[rng.integers(0, n)] = s
        return vals.astype(self.dtype.np_dtype)


class DoubleGen(_FloatGen):
    dtype = dt.FLOAT64


class FloatGen(_FloatGen):
    dtype = dt.FLOAT32


class StringGen(DataGen):
    dtype = dt.STRING

    def __init__(self, nullable: float = 0.1, alphabet: str = "abXY z01_",
                 max_len: int = 8):
        super().__init__(nullable)
        self.alphabet = alphabet
        self.max_len = max_len

    def _values(self, rng, n):
        letters = np.array(list(self.alphabet))
        out = np.empty(n, dtype=object)
        lens = rng.integers(0, self.max_len + 1, n)
        for i in range(n):
            out[i] = "".join(rng.choice(letters, lens[i]))
        return out


class DateGen(DataGen):
    dtype = dt.DATE

    def _values(self, rng, n):
        days = rng.integers(-3650, 20000, n)  # ~1960..2024
        return days.astype("datetime64[D]")


class TimestampGen(DataGen):
    dtype = dt.TIMESTAMP

    def _values(self, rng, n):
        us = rng.integers(0, 1_700_000_000, n) * np.int64(1_000_000)
        return us.astype("datetime64[us]")


ALL_GENS: Sequence[DataGen] = (
    ByteGen(), ShortGen(), IntegerGen(), LongGen(), BooleanGen(),
    DoubleGen(), FloatGen(), StringGen(), DateGen(), TimestampGen())

NUMERIC_GENS = (ByteGen(), ShortGen(), IntegerGen(), LongGen(),
                DoubleGen(), FloatGen())


def gen_scan(gens: Dict[str, DataGen], n: int = 100,
             seed: int = 0) -> pn.ScanNode:
    """Fuzzed in-memory scan: one column per generator."""
    rng = np.random.default_rng(seed)
    data, validity, names, types = {}, {}, [], []
    for name, g in gens.items():
        d, v = g.generate(rng, n)
        data[name] = d
        validity[name] = v
        names.append(name)
        types.append(g.dtype)
    from spark_rapids_tpu.columnar.batch import Schema

    return pn.ScanNode(pn.InMemorySource(
        data, schema=Schema(names, types), validity=validity))
