"""Nondeterministic / partition-context expression tests (§2.5:
rand, spark_partition_id, monotonically_increasing_id)."""
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.execs.base import collect
from spark_rapids_tpu.expressions import nondeterministic as nd
from spark_rapids_tpu.expressions.base import Alias, BoundReference
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.overrides import apply_overrides

from tests.compare import assert_cpu_and_tpu_equal

CONF = RapidsConf({"rapids.tpu.sql.test.enabled": True,
                   "rapids.tpu.sql.incompatibleOps.enabled": True})


def scan(n=100):
    return pn.ScanNode(pn.InMemorySource(
        {"x": np.arange(n, dtype=np.int64)}))


def _proj(exprs, names, child):
    return pn.ProjectNode(exprs, child, names=names)


def test_single_partition_matches_oracle():
    """One partition: pid/rowpos/rand formulas agree bit-for-bit with the
    CPU oracle."""
    plan = _proj(
        [BoundReference(0, dt.INT64), nd.SparkPartitionID(),
         nd.MonotonicallyIncreasingID(), nd.Rand(seed=7)],
        ["x", "pid", "mid", "r"], scan(200))
    assert_cpu_and_tpu_equal(plan, sort=False, conf=CONF,
                             approx_float=0.0)


def test_multi_partition_structure(tmp_path):
    for k in range(3):
        pq.write_table(pa.table(
            {"x": np.arange(k * 100, k * 100 + 100, dtype=np.int64)}),
            tmp_path / f"f{k}.parquet")
    # per-file partitions: disable FilePartition packing so the three
    # tiny files stay three scan partitions (the structure under test)
    src = ParquetSource(str(tmp_path))
    src.pack_splits = False
    plan = _proj(
        [BoundReference(0, dt.INT64), nd.SparkPartitionID(),
         nd.MonotonicallyIncreasingID(), nd.Rand(seed=3)],
        ["x", "pid", "mid", "r"], pn.ScanNode(src))
    df = collect(apply_overrides(plan, CONF))
    pids = df["pid"].astype(int)
    mids = df["mid"].astype(int)
    assert set(pids) == {0, 1, 2}
    # Spark encoding: partition << 33 | position
    assert all(mids[i] == (pids[i] << 33) + (i % 100)
               for i in range(len(df)))
    assert mids.is_unique
    rs = df["r"].astype(float)
    assert ((rs >= 0) & (rs < 1)).all()
    assert rs.nunique() > 290  # essentially all distinct
    # rand depends on partition: partition streams differ
    assert not np.allclose(sorted(rs[pids == 0]), sorted(rs[pids == 1]))


def test_rand_deterministic_per_seed():
    plan = _proj([nd.Rand(seed=11)], ["r"], scan(50))
    a = collect(apply_overrides(plan, CONF))["r"].astype(float)
    b = collect(apply_overrides(plan, CONF))["r"].astype(float)
    np.testing.assert_array_equal(a, b)
    c = collect(apply_overrides(
        _proj([nd.Rand(seed=12)], ["r"], scan(50)), CONF))["r"]
    assert not np.array_equal(a, c.astype(float))


def test_rand_uniformity():
    plan = _proj([nd.Rand(seed=0)], ["r"], scan(20_000))
    r = collect(apply_overrides(plan, CONF))["r"].astype(float)
    assert abs(r.mean() - 0.5) < 0.01
    hist, _ = np.histogram(r, bins=10, range=(0, 1))
    assert hist.min() > 1600  # no empty decile

def test_rand_disabled_without_incompat_flag():
    plan = _proj([nd.Rand(seed=0)], ["r"], scan(10))
    exec_ = apply_overrides(plan, RapidsConf())
    assert type(exec_).__name__ == "CpuFallbackExec"


def test_row_base_advances_across_batches():
    """Multiple batches in one partition must continue the id stream."""
    src = pn.InMemorySource({"x": np.arange(5000, dtype=np.int64)})
    plan = _proj([nd.MonotonicallyIncreasingID()], ["mid"],
                 pn.ScanNode(src))
    conf = CONF.with_overrides(
        {"rapids.tpu.sql.reader.batchSizeRows": 1000})
    df = collect(apply_overrides(plan, conf))
    np.testing.assert_array_equal(df["mid"].astype(int),
                                  np.arange(5000))


def test_input_file_name_and_block(tmp_path):
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.expressions.base import Alias, BoundReference
    from spark_rapids_tpu.expressions.nondeterministic import (
        InputFileBlockLength, InputFileBlockStart, InputFileName)
    from spark_rapids_tpu.io import ParquetSource
    from spark_rapids_tpu.plan import nodes as pn

    from compare import assert_cpu_and_tpu_equal

    d = tmp_path / "t"
    os.makedirs(d)
    for i in range(3):
        pq.write_table(
            pa.table({"x": np.arange(i * 10, i * 10 + 5,
                                     dtype=np.int64)}),
            str(d / f"f{i}.parquet"))
    plan = pn.ProjectNode(
        [Alias(BoundReference(0, dt.INT64), "x"),
         Alias(InputFileName(), "fname"),
         Alias(InputFileBlockStart(), "bstart"),
         Alias(InputFileBlockLength(), "blen")],
        pn.ScanNode(ParquetSource(str(d))))
    exec_ = assert_cpu_and_tpu_equal(plan)
    from spark_rapids_tpu.execs.base import collect
    out = collect(exec_)
    assert out["fname"].str.contains("f0.parquet").sum() == 5
    # parquet block offsets come from the row-group byte extent
    assert (out["bstart"] >= 0).all()
    assert (out["blen"] > 0).all()


def test_input_file_block_per_row_group(tmp_path):
    """Multiple row groups in ONE file -> distinct block starts per
    split (Spark InputFileBlockStart semantics)."""
    import os

    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.expressions.base import Alias, BoundReference
    from spark_rapids_tpu.expressions.nondeterministic import (
        InputFileBlockStart, InputFileName)
    from spark_rapids_tpu.io import ParquetSource
    from spark_rapids_tpu.plan import nodes as pn

    d = tmp_path / "rg"
    os.makedirs(d)
    pq.write_table(pa.table({"x": np.arange(4000, dtype=np.int64)}),
                   str(d / "one.parquet"), row_group_size=1000)
    conf = RapidsConf({"rapids.tpu.sql.reader.batchSizeBytes": 4000})
    src_ = ParquetSource(str(d), conf=conf)
    plan = pn.ProjectNode(
        [Alias(BoundReference(0, dt.INT64), "x"),
         Alias(InputFileName(), "fname"),
         Alias(InputFileBlockStart(), "bstart")],
        pn.ScanNode(src_))
    from compare import assert_cpu_and_tpu_equal
    exec_ = assert_cpu_and_tpu_equal(plan)
    from spark_rapids_tpu.execs.base import collect
    out = collect(exec_)
    if src_.num_splits() > 1:
        assert out["bstart"].nunique() > 1


def test_input_file_name_outside_scan_is_empty():
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.expressions.base import Alias, BoundReference
    from spark_rapids_tpu.expressions.nondeterministic import (
        InputFileBlockStart, InputFileName)
    from spark_rapids_tpu.plan import nodes as pn

    from compare import assert_cpu_and_tpu_equal

    plan = pn.ProjectNode(
        [Alias(BoundReference(0, dt.INT64), "x"),
         Alias(InputFileName(), "fname"),
         Alias(InputFileBlockStart(), "bstart")],
        pn.ScanNode(pn.InMemorySource(
            {"x": np.arange(6, dtype=np.int64)})))
    exec_ = assert_cpu_and_tpu_equal(plan)
    from spark_rapids_tpu.execs.base import collect
    out = collect(exec_)
    assert set(out["fname"]) == {""}
    assert set(out["bstart"]) == {-1}
