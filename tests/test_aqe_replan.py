"""Runtime replanning (AQE tentpole): the three replan rules — skew
splitting, join-strategy switching, and stats-driven re-bucketing —
each fire end-to-end through the planner, are counted in the replan
telemetry, and leave results identical to the static plan and the CPU
oracle. Plus the two correctness keystones underneath: the host mirror
of the device partition hash (skew detection before the collective)
and the dense-probe/hash-probe differential."""
import numpy as np
import pandas as pd
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.cpu.engine import execute_cpu
from spark_rapids_tpu.execs import adaptive
from spark_rapids_tpu.execs.adaptive import (AdaptiveShuffledJoinExec,
                                             AdaptiveShuffleReaderExec)
from spark_rapids_tpu.execs.base import collect
from spark_rapids_tpu.execs.joins import BroadcastHashJoinExec
from spark_rapids_tpu.expressions.base import BoundReference, Literal
from spark_rapids_tpu.expressions.predicates import LessThan
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.overrides import apply_overrides


def _find(exec_, klass):
    out, stack = [], [exec_]
    while stack:
        e = stack.pop()
        if isinstance(e, klass):
            out.append(e)
        stack.extend(e.children)
    return out


def _sorted_rows(df):
    return df.sort_values(list(df.columns)).reset_index(drop=True)


def _assert_same(got, want, exact=True):
    pd.testing.assert_frame_equal(_sorted_rows(got), _sorted_rows(want),
                                  check_dtype=False,
                                  check_exact=exact)


# ---------------------------------------------------------------------------
# keystone 1: the host mirror of the device partition hash
# ---------------------------------------------------------------------------


def test_host_mirror_matches_device_partition_ids():
    """Skew detection runs the partition hash on the HOST before the
    in-program collective: it must be bit-equal to the device kernel
    across null keys and float canonicalization (NaN payloads, -0.0)."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.ops import hashing

    rng = np.random.default_rng(17)
    n, num_out = 257, 7
    ints = rng.integers(-1000, 1000, n).astype(np.int64)
    iv = rng.random(n) > 0.1
    floats = rng.random(n) * 100 - 50
    floats[::13] = np.nan
    floats[::17] = -0.0
    floats[::19] = 0.0
    fv = rng.random(n) > 0.15
    batch = ColumnarBatch(
        [Column.from_numpy(ints, dt.INT64, validity=iv),
         Column.from_numpy(floats, dt.FLOAT64, validity=fv)], n)
    types = [dt.INT64, dt.FLOAT64]
    for keys in ([0], [1], [0, 1]):
        h = np.asarray(hashing.hash_columns(batch, keys, types))
        dev = h % num_out
        dev = np.where(dev < 0, dev + num_out, dev)
        host = hashing.host_partition_ids(
            [ints, floats], [iv, fv], types, keys, num_out)
        np.testing.assert_array_equal(host, dev[:n],
                                      err_msg=f"keys={keys}")


# ---------------------------------------------------------------------------
# keystone 2: dense direct-address probe == hash probe, all kinds
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("kind", ["inner", "left", "left_semi",
                                  "left_anti"])
def test_dense_probe_matches_hash_probe(kind):
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.ops import join as join_ops

    rng = np.random.default_rng(23)
    bn, sn = 120, 200
    bk = rng.integers(10, 40, bn).astype(np.int64)  # duplicate keys
    bv_valid = rng.random(bn) > 0.1
    sk = rng.integers(0, 60, sn).astype(np.int64)  # out-of-range probes
    sv_valid = rng.random(sn) > 0.1
    build = ColumnarBatch(
        [Column.from_numpy(bk, dt.INT64, validity=bv_valid),
         Column.from_numpy(rng.random(bn), dt.FLOAT64)], bn)
    stream = ColumnarBatch(
        [Column.from_numpy(sk, dt.INT64, validity=sv_valid),
         Column.from_numpy(rng.random(sn), dt.FLOAT64)], sn)
    btypes = [dt.INT64, dt.FLOAT64]

    kmin, kmax, nvalid = join_ops.measure_key_range(
        build.columns[0], build.num_rows_device())
    assert nvalid > 0
    dense = join_ops.prepare_build_dense(
        build, [0], btypes, [dt.INT64], kmin, kmax - kmin + 1)
    assert dense is not None
    jt = {"left_semi": "leftsemi", "left_anti": "leftanti"}.get(kind,
                                                                kind)
    out_d, _ = join_ops.equi_join(stream, build, [0], [0], btypes,
                                  btypes, jt, prepared=dense)
    hashed = join_ops.prepare_build(build, [0], btypes, [dt.INT64])
    out_h, _ = join_ops.equi_join(stream, build, [0], [0], btypes,
                                  btypes, jt, prepared=hashed)
    _assert_same(out_d.to_pandas(), out_h.to_pandas())


# ---------------------------------------------------------------------------
# end-to-end: each replan rule through the planner
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def skew_join_data(tmp_path_factory):
    """Left side with 60% of rows on one key (4 scan partitions), a
    uniform right side (2 scan partitions)."""
    root = tmp_path_factory.mktemp("aqe")
    rng = np.random.default_rng(31)
    n = 2400
    k = rng.integers(0, 50, n).astype(np.int64)
    k[rng.random(n) < 0.6] = 7
    for i in range(4):
        sl = slice(i * n // 4, (i + 1) * n // 4)
        pq.write_table(pa.table({"k": k[sl],
                                 "v": rng.random(n // 4)}),
                       root / f"left{i}.parquet")
    m = 400
    k2 = rng.integers(0, 50, m).astype(np.int64)
    for i in range(2):
        sl = slice(i * m // 2, (i + 1) * m // 2)
        pq.write_table(pa.table({"k2": k2[sl],
                                 "w": rng.random(m // 2)}),
                       root / f"right{i}.parquet")
    return root


def _skew_plan(root):
    lsrc = ParquetSource([str(root / f"left{i}.parquet")
                          for i in range(4)])
    lsrc.pack_splits = False
    rsrc = ParquetSource([str(root / f"right{i}.parquet")
                          for i in range(2)])
    rsrc.pack_splits = False
    return pn.JoinNode("inner", pn.ScanNode(lsrc), pn.ScanNode(rsrc),
                       [0], [0])


@pytest.fixture(scope="module")
def static_reference(skew_join_data):
    """The static planner's output (adaptive off): the byte-identity
    baseline every replanned run must reproduce."""
    conf = RapidsConf({"rapids.tpu.sql.test.enabled": True,
                       "rapids.tpu.sql.adaptive.enabled": False,
                       "rapids.tpu.sql.autoBroadcastJoinThreshold": 0})
    exec_ = apply_overrides(_skew_plan(skew_join_data), conf)
    assert not _find(exec_, AdaptiveShuffledJoinExec)
    assert not _find(exec_, AdaptiveShuffleReaderExec)
    return collect(exec_)


def test_static_plan_matches_cpu_oracle(skew_join_data,
                                        static_reference):
    cpu = execute_cpu(_skew_plan(skew_join_data)).to_pandas()
    _assert_same(static_reference, cpu, exact=False)


def test_skew_split_replans_and_matches(skew_join_data,
                                        static_reference):
    """Rule 1 host path: forcing the skew cut under the hot partition
    splits it into sub-reads, records skew_split events, and changes
    nothing about the result."""
    conf = RapidsConf({
        "rapids.tpu.sql.test.enabled": True,
        "rapids.tpu.sql.autoBroadcastJoinThreshold": 0,
        "rapids.tpu.sql.adaptive.advisoryPartitionSizeBytes": 1024,
        "rapids.tpu.sql.adaptive.skewJoin."
        "skewedPartitionThresholdInBytes": 64,
        "rapids.tpu.sql.adaptive.skewJoin.skewedPartitionFactor": 1.5,
    })
    before = adaptive.replan_snapshot()
    exec_ = apply_overrides(_skew_plan(skew_join_data), conf)
    assert _find(exec_, AdaptiveShuffledJoinExec)
    got = collect(exec_)
    events = adaptive.replan_delta(before)
    assert any(k.startswith("skew_split") for k in events), events
    _assert_same(got, static_reference)


def test_runtime_broadcast_switch(tmp_path, static_reference,
                                  skew_join_data):
    """Rule 2: build side ESTIMATED over the broadcast threshold but
    MEASURED under it flips shuffled->broadcast at execute time."""
    rng = np.random.default_rng(37)
    for i in range(4):
        pq.write_table(pa.table(
            {"k2": rng.integers(0, 50, 12500).astype(np.int64),
             "w": rng.random(12500)}), tmp_path / f"r{i}.parquet")
    lsrc = ParquetSource([str(skew_join_data / f"left{i}.parquet")
                          for i in range(4)])
    lsrc.pack_splits = False
    rsrc = ParquetSource([str(tmp_path / f"r{i}.parquet")
                          for i in range(4)])
    rsrc.pack_splits = False
    # keeps ~2% of build rows: the scan-statistics estimate stays big
    filt = pn.FilterNode(LessThan(BoundReference(0, dt.INT64),
                                  Literal(1)), pn.ScanNode(rsrc))
    plan = pn.JoinNode("inner", pn.ScanNode(lsrc), filt, [0], [0])

    static = collect(apply_overrides(plan, RapidsConf(
        {"rapids.tpu.sql.test.enabled": True,
         "rapids.tpu.sql.adaptive.enabled": False,
         "rapids.tpu.sql.autoBroadcastJoinThreshold": 0})))

    conf = RapidsConf({
        "rapids.tpu.sql.test.enabled": True,
        "rapids.tpu.sql.autoBroadcastJoinThreshold": 48 * 1024})
    before = adaptive.replan_snapshot()
    exec_ = apply_overrides(plan, conf)
    assert _find(exec_, AdaptiveShuffledJoinExec), \
        "estimate must stay above the threshold at plan time"
    got = collect(exec_)
    events = adaptive.replan_delta(before)
    assert any("shuffled->broadcast" in k for k in events), events
    assert _find(exec_, BroadcastHashJoinExec)
    _assert_same(got, static)


def test_dense_switch_replans_and_matches(skew_join_data,
                                          static_reference):
    """Rule 2 dense flavor: a narrow measured key range flips the hash
    probe to the direct-address table, result unchanged."""
    conf = RapidsConf({
        "rapids.tpu.sql.test.enabled": True,
        "rapids.tpu.sql.autoBroadcastJoinThreshold": 0,
        "rapids.tpu.sql.adaptive.denseJoin.enabled": True,
        "rapids.tpu.sql.adaptive.denseJoin.minBuildRows": 1,
    })
    before = adaptive.replan_snapshot()
    got = collect(apply_overrides(_skew_plan(skew_join_data), conf))
    events = adaptive.replan_delta(before)
    assert any("hash->dense" in k for k in events), events
    _assert_same(got, static_reference)


def test_rebucket_records_events_and_matches(skew_join_data,
                                             static_reference):
    """Rule 3a: coalesced groups concatenate to the measured row count
    (progcache right-rung), counted as rebucket events."""
    conf = RapidsConf({
        "rapids.tpu.sql.test.enabled": True,
        "rapids.tpu.sql.autoBroadcastJoinThreshold": 0,
        "rapids.tpu.sql.adaptive.rebucket.enabled": True,
    })
    before = adaptive.replan_snapshot()
    got = collect(apply_overrides(_skew_plan(skew_join_data), conf))
    events = adaptive.replan_delta(before)
    assert any(k.startswith("rebucket") for k in events), events
    _assert_same(got, static_reference)


def test_adaptive_disabled_is_static(skew_join_data, static_reference):
    """The master gate: adaptive.enabled=false must reproduce the
    static planner byte for byte and leave the telemetry silent."""
    conf = RapidsConf({
        "rapids.tpu.sql.test.enabled": True,
        "rapids.tpu.sql.adaptive.enabled": False,
        "rapids.tpu.sql.autoBroadcastJoinThreshold": 0,
        # skew knobs armed but master-gated off: nothing may fire
        "rapids.tpu.sql.adaptive.advisoryPartitionSizeBytes": 1024,
        "rapids.tpu.sql.adaptive.skewJoin."
        "skewedPartitionThresholdInBytes": 64,
    })
    before = adaptive.replan_snapshot()
    exec_ = apply_overrides(_skew_plan(skew_join_data), conf)
    assert not _find(exec_, AdaptiveShuffledJoinExec)
    got = collect(exec_)
    assert adaptive.replan_delta(before) == {}
    _assert_same(got, static_reference)


# ---------------------------------------------------------------------------
# rule 1 on the in-program path: salting before the collective
# ---------------------------------------------------------------------------


def test_in_program_salting_matches_host_path():
    """A hot hash partition is salted across mesh devices before the
    all_to_all; per-output-partition content is unchanged vs the host
    path and the salt is counted as a skew_salt replan event."""
    from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.parallel.mesh import data_mesh
    from spark_rapids_tpu.parallel.spmd import SkewSpec
    from tests.test_spmd_shuffle import _drain_exchange, _rows_exec

    rng = np.random.default_rng(43)

    def mk(n, hot_frac):
        keys = rng.integers(0, 40, n).astype(np.int64)
        keys[rng.random(n) < hot_frac] = 7  # hot key
        kv = rng.random(n) > 0.1
        vals = rng.random(n)
        return keys, kv, vals

    parts = [[mk(1000, 0.75)], [mk(1000, 0.75)],
             [mk(1000, 0.75)], [mk(1000, 0.75)]]
    num_out = 5

    host = ShuffleExchangeExec(("hash", [0]), num_out, _rows_exec(parts))
    want = _drain_exchange(host)

    before = adaptive.replan_snapshot()
    prog = ShuffleExchangeExec(("hash", [0]), num_out, _rows_exec(parts))
    prog.enable_in_program(data_mesh(8),
                           skew=SkewSpec(factor=2.0, threshold=1024,
                                         max_splits=8))
    got = _drain_exchange(prog)
    assert prog.in_program
    events = adaptive.replan_delta(before)
    assert any(k.startswith("skew_salt") for k in events), events
    for p in range(num_out):
        assert got[p] == want[p], f"partition {p} diverged"


# ---------------------------------------------------------------------------
# rule 3b: measured cardinalities feed footprint admission
# ---------------------------------------------------------------------------


def test_runtime_stats_feed_footprint():
    from spark_rapids_tpu.plan.optimizer import estimate_footprint_bytes

    class _Node:
        def __init__(self, names):
            self._names = names
            self.children = []

        def output_schema(self):
            from spark_rapids_tpu.columnar.batch import Schema
            return Schema(self._names,
                          [dt.INT64] * len(self._names))

    sig = ("aqe_test_col_a", "aqe_test_col_b")
    adaptive.record_cardinality(sig, 5000)
    adaptive.record_cardinality(sig, 3000)  # max wins
    assert adaptive.cardinality_lookup(sig) == 5000
    assert adaptive.plan_cardinality_rows(_Node(list(sig))) == 5000
    assert adaptive.plan_cardinality_rows(_Node(["unseen"])) is None

    node = _Node(list(sig))
    with_stats = estimate_footprint_bytes(
        node, default_rows=1 << 20,
        runtime_rows=adaptive.plan_cardinality_rows)
    without = estimate_footprint_bytes(node, default_rows=1 << 20)
    assert with_stats < without  # 5000 measured rows << 1M default
