"""Cross-process shuffle over real TCP sockets.

The reference's transport is exercised against real peers only in
cluster CI (SURVEY §4); its protocol layer is testable locally. Here the
full stack — metadata/windowed-chunk/release protocol, inflight
throttle, fetch-failure conversion, stage retry — runs over real
listening sockets, including against a SECOND OS PROCESS serving one
executor's catalog (shuffle/remote_worker.py), which the reference
cannot do without a GPU cluster. Reference flow:
RapidsShuffleInternalManager.scala:200-305 (manager wiring),
UCX.scala:70-266 (transport), RapidsShuffleIterator.scala:242-300
(fetch-failure -> stage retry)."""
import os
import subprocess
import sys
import json

import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.shuffle import LocalCluster, ShuffleFetchFailedError
from spark_rapids_tpu.shuffle.remote_worker import make_block_batch


def batch_values(b):
    n = b.realized_num_rows()
    data, valid = b.columns[0].to_numpy(n)
    return [int(v) if (valid is None or valid[i]) else None
            for i, v in enumerate(np.asarray(data)[:n])]


def expect_values(spans):
    return sorted(v for lo, n in spans for v in range(lo, lo + n)
                  if v % 7 != 3)


# ---------------------------------------------------------------- in-process

def test_tcp_transport_single_process(tmp_path):
    """The same cluster runtime with every executor behind a real
    socket: local hits stay catalog-zero-copy, remote reads ride TCP."""
    from spark_rapids_tpu.shuffle.tcp import TcpTransport

    c = LocalCluster(3, spill_dir=str(tmp_path), transport="tcp",
                     bounce_size=512, max_inflight=2048)
    try:
        assert isinstance(c.transport, TcpTransport)
        for map_id, ex in enumerate([0, 1, 2]):
            c.write_map_output(1, map_id, ex,
                               {0: make_block_batch(map_id * 100, 40)})
        got = []
        for b in c.read_partition(1, 0, reader_executor_index=0):
            got.extend(v for v in batch_values(b) if v is not None)
        assert sorted(got) == expect_values([(0, 40), (100, 40),
                                             (200, 40)])
        it = c.last_iterator
        assert it.local_blocks_read == 1
        assert it.remote_blocks_read == 2
        # windowed transfer really chunked at bounce size over the wire
        client = c._clients[("exec-0", "exec-1")]
        assert client.throttle.peak <= 2048
    finally:
        c.shutdown()


# ---------------------------------------------------------------- 2 process

def spawn_worker(config: dict):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, "-m", "spark_rapids_tpu.shuffle.remote_worker"],
        stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    proc.stdin.write(json.dumps(config) + "\n")
    proc.stdin.flush()
    line = proc.stdout.readline().strip()
    assert line.startswith("READY "), line
    _, host, port = line.split()
    return proc, host, int(port)


@pytest.fixture()
def worker_cluster(tmp_path):
    """A 2-executor local cluster + 1 remote executor in a second OS
    process holding map task 2's output."""
    c = LocalCluster(2, spill_dir=str(tmp_path), transport="tcp")
    procs = []
    yield c, procs
    for p in procs:
        try:
            p.stdin.close()
            p.wait(timeout=10)
        except Exception:
            p.kill()
    c.shutdown()


def test_two_process_shuffled_read(worker_cluster):
    """A reduce task reads one partition whose blocks live in THIS
    process (2 executors) and in ANOTHER OS process — metadata, windowed
    chunks and release all cross the process boundary."""
    c, procs = worker_cluster
    proc, host, port = spawn_worker({
        "executor_id": "exec-remote",
        "blocks": [[1, 2, 0, 200, 500], [1, 2, 1, 900, 10]]})
    procs.append(proc)
    c.register_remote_executor("exec-remote", host, port)

    c.write_map_output(1, 0, 0, {0: make_block_batch(0, 50)})
    c.write_map_output(1, 1, 1, {0: make_block_batch(100, 50)})
    c.register_remote_map_output(1, 2, "exec-remote", {0, 1})

    got = []
    for b in c.read_partition(1, 0, reader_executor_index=0):
        got.extend(v for v in batch_values(b) if v is not None)
    assert sorted(got) == expect_values([(0, 50), (100, 50), (200, 500)])
    it = c.last_iterator
    assert it.remote_blocks_read == 2  # exec-1 (in-proc TCP) + remote
    assert it.remote_bytes_read > 0

    # partition 1 lives ONLY on the remote process
    got1 = []
    for b in c.read_partition(1, 1, reader_executor_index=1):
        got1.extend(v for v in batch_values(b) if v is not None)
    assert sorted(got1) == expect_values([(900, 10)])


def test_two_process_join_shapes(worker_cluster):
    """A shuffled-join-shaped read: both join sides' partitions fetched
    across the process boundary, then joined locally; result must match
    the pure-local oracle."""
    import pandas as pd

    c, procs = worker_cluster
    # side A partitioned output on the remote process, side B local
    proc, host, port = spawn_worker({
        "executor_id": "exec-remote",
        "blocks": [[5, 0, 0, 0, 30], [6, 0, 0, 10, 30]]})
    procs.append(proc)
    c.register_remote_executor("exec-remote", host, port)
    c.register_remote_map_output(5, 0, "exec-remote", {0})
    c.register_remote_map_output(6, 0, "exec-remote", {0})

    a = [v for b in c.read_partition(5, 0, reader_executor_index=0)
         for v in batch_values(b) if v is not None]
    bvals = [v for b in c.read_partition(6, 0, reader_executor_index=0)
             for v in batch_values(b) if v is not None]
    got = pd.merge(pd.DataFrame({"k": a}), pd.DataFrame({"k": bvals}),
                   on="k")
    expect = sorted(set(a) & set(bvals))
    assert sorted(got["k"].tolist()) == expect
    assert len(expect) > 0


def test_two_process_hangup_fetch_failure_then_stage_retry(worker_cluster):
    """Fault injection: the remote peer drops the connection mid-chunk
    (Hangup). The read surfaces a fetch failure naming the peer; the
    driver invalidates its map outputs and re-runs the map task locally
    (lineage/stage retry, SURVEY §5.3) — after which the read succeeds."""
    c, procs = worker_cluster
    proc, host, port = spawn_worker({
        "executor_id": "exec-remote",
        "blocks": [[9, 0, 0, 0, 2000]],
        "hangup_after_chunks": 0})
    procs.append(proc)
    c.register_remote_executor("exec-remote", host, port)
    c.register_remote_map_output(9, 0, "exec-remote", {0})

    with pytest.raises(ShuffleFetchFailedError) as e:
        list(c.read_partition(9, 0, reader_executor_index=0))
    assert e.value.executor_id == "exec-remote"

    lost = c.invalidate_map_output(9, "exec-remote")
    assert lost == [0]
    for map_id in lost:
        c.write_map_output(9, map_id, 0, {0: make_block_batch(0, 2000)})
    got = []
    for b in c.read_partition(9, 0, reader_executor_index=0):
        got.extend(v for v in batch_values(b) if v is not None)
    assert sorted(got) == expect_values([(0, 2000)])


def test_serving_executor_spills_then_unspills_on_serve(tmp_path):
    """Round-5 (round-4 weak #3): a serving executor under memory
    pressure SPILLS its cached shuffle blocks (device -> host/disk) and
    transparently unspills them when a remote reduce task fetches —
    the reference's RapidsShuffleInternalManager.scala:249-269
    catalog-backed unspill-on-serve."""
    from spark_rapids_tpu.memory.catalog import StorageTier

    # budget far below one map output's bytes forces immediate spill
    c = LocalCluster(2, spill_dir=str(tmp_path), transport="tcp",
                     device_budget=4096)
    try:
        n = 4000  # int64 data + validity >> 4096 bytes
        for map_id in range(3):
            c.write_map_output(7, map_id, 0,
                               {0: make_block_batch(map_id * 10_000, n)})
        ex0 = c.executors[0]
        tiers = [ex0.buffer_catalog.tier_of(sb.buffer_id)
                 for sb in ex0.shuffle_catalog._blocks.values()]
        assert any(t != StorageTier.DEVICE for t in tiers), tiers

        # remote read from executor 1: the serving side must unspill
        got = []
        for b in c.read_partition(7, 0, reader_executor_index=1):
            got.extend(v for v in batch_values(b) if v is not None)
        want = expect_values([(0, n), (10_000, n), (20_000, n)])
        assert sorted(got) == want
        it = c.last_iterator
        assert it.remote_blocks_read == 3  # all served cross-executor
    finally:
        c.shutdown()


def test_transient_fault_retries_with_backoff(tmp_path):
    """Satellite (PR 6): a peer that hiccups — drops the connection on
    the first two requests — costs bounded backoff + reconnect, NOT a
    fetch failure and a whole stage re-run. A PERSISTENT fault still
    exhausts the retry budget and surfaces as TransportError, and a
    peer-reported semantic error is never retried."""
    from spark_rapids_tpu.memory.catalog import BufferCatalog
    from spark_rapids_tpu.shuffle.catalog import ShuffleBufferCatalog
    from spark_rapids_tpu.shuffle.meta import BlockId
    from spark_rapids_tpu.shuffle.tcp import (Hangup, TcpConnection,
                                              TcpShuffleServer)
    from spark_rapids_tpu.shuffle.transport import (ShuffleServer,
                                                    TransportError)

    cat = ShuffleBufferCatalog(BufferCatalog(spill_dir=str(tmp_path)))
    block = BlockId(1, 0, 0)
    cat.register(block, make_block_batch(0, 64))
    server = ShuffleServer("exec-flaky", cat)
    fails = {"n": 2, "seen": 0}

    def flaky_metadata(blocks):
        fails["seen"] += 1
        if fails["seen"] <= fails["n"]:
            raise Hangup()

    server.on_metadata = flaky_metadata
    ts = TcpShuffleServer(server)
    try:
        conn = TcpConnection(ts.host, ts.port)
        import time as _t

        t0 = _t.monotonic()
        metas = conn.request_metadata([block], timeout=10.0)
        took = _t.monotonic() - t0
        assert len(metas) == 1 and metas[0].num_rows == 64
        assert fails["seen"] == 3  # 2 hangups + the success
        assert took < 5.0  # backoff stayed far under the timeout
        # chunk fetch works over the recovered connection
        data = conn.request_chunk(block, 0, metas[0].payload_len)
        assert len(data) == metas[0].payload_len

        # persistent fault: retry budget exhausts, error surfaces
        fails["n"], fails["seen"] = 10_000, 0
        with pytest.raises(TransportError):
            conn.request_metadata([block], timeout=3.0)
        assert fails["seen"] == 1 + TcpConnection.MAX_TRANSIENT_RETRIES

        # semantic (peer-reported) error: exactly ONE attempt
        server.on_metadata = None
        missing = BlockId(9, 9, 9)
        calls = {"n": 0}

        def counting(blocks):
            calls["n"] += 1

        server.on_metadata = counting
        with pytest.raises(TransportError) as ei:
            conn.request_metadata([missing], timeout=3.0)
        assert "not found" in str(ei.value)
        assert calls["n"] == 1  # no retry of a semantic error
        conn.close()
    finally:
        ts.close()
