"""Runtime bootstrap tests (§2.1 plugin-init analogue)."""
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.memory import semaphore as sem
from spark_rapids_tpu.memory.catalog import get_catalog
from spark_rapids_tpu import runtime
from spark_rapids_tpu.runtime.device import TpuDeviceManager


@pytest.fixture(autouse=True)
def _teardown():
    yield
    runtime.shutdown()


def test_initialize_wires_globals(tmp_path):
    conf = RapidsConf({
        "rapids.tpu.sql.concurrentTpuTasks": 5,
        "rapids.tpu.memory.spillDir": str(tmp_path),
        "rapids.tpu.shuffle.compression.codec": "zlib",
    })
    env = runtime.initialize(conf)
    assert env.semaphore is sem.get()
    assert env.catalog is get_catalog()
    assert env.catalog._spill_dir == str(tmp_path)
    assert env.catalog.disk_codec == "zlib"
    assert env.shuffle_codec == "zlib"
    assert env.device.platform in ("cpu", "tpu")
    # semaphore honors the conf
    for _ in range(5):
        assert env.semaphore.acquire_if_necessary(task_id=_) is True
    assert env.semaphore.holds(task_id=0)


def test_initialize_idempotent_replaces():
    e1 = runtime.initialize(RapidsConf())
    e2 = runtime.initialize(RapidsConf(
        {"rapids.tpu.sql.concurrentTpuTasks": 1}))
    assert runtime.get_env() is e2
    assert e1 is not e2


def test_device_budget_math():
    dm = TpuDeviceManager()
    dm.hbm_bytes = lambda: 16 << 30  # pretend 16 GiB HBM
    conf = RapidsConf({"rapids.tpu.memory.hbm.allocFraction": 0.5,
                       "rapids.tpu.memory.hbm.reserve": 1 << 30})
    assert dm.device_budget(conf) == (8 << 30) - (1 << 30)
    bad = RapidsConf({"rapids.tpu.memory.hbm.allocFraction": 0.01,
                      "rapids.tpu.memory.hbm.reserve": 8 << 30})
    with pytest.raises(RuntimeError, match="non-positive"):
        dm.device_budget(bad)


def test_budget_none_without_memory_stats():
    dm = TpuDeviceManager()
    dm.hbm_bytes = lambda: None
    assert dm.device_budget(RapidsConf()) is None


def test_bad_device_ordinal():
    with pytest.raises(RuntimeError, match="out of range"):
        runtime.initialize(RapidsConf(), device_ordinal=512)
