"""Columnar core tests: Column/StringColumn/ColumnarBatch + host interop."""
import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column, Scalar, StringColumn, \
    unify_dictionaries
from spark_rapids_tpu.columnar import hostcol
from spark_rapids_tpu.ops.buckets import bucket_capacity


def test_bucket_capacity():
    assert bucket_capacity(0) == 128
    assert bucket_capacity(128) == 128
    assert bucket_capacity(129) == 256
    assert bucket_capacity(1000) == 1024


def test_column_roundtrip_numeric():
    vals = np.arange(10, dtype=np.int64) * 3
    c = Column.from_numpy(vals)
    assert c.dtype is dt.INT64
    assert c.capacity == 128
    out, validity = c.to_numpy(10)
    np.testing.assert_array_equal(out, vals)
    assert validity is None


def test_column_nulls():
    vals = np.array([1.5, 2.5, 3.5])
    validity = np.array([True, False, True])
    c = Column.from_numpy(vals, validity=validity)
    out, v = c.to_numpy(3)
    assert v is not None
    np.testing.assert_array_equal(v, validity)
    assert out[0] == 1.5 and out[2] == 3.5


def test_string_column_sorted_dictionary():
    c = StringColumn.from_strings(["banana", "apple", None, "cherry", "apple"])
    # dictionary sorted => code order is lexicographic order
    assert list(c.dictionary) == ["apple", "banana", "cherry"]
    out, v = c.to_numpy(5)
    assert list(out) == ["banana", "apple", None, "cherry", "apple"]


def test_unify_dictionaries():
    a = StringColumn.from_strings(["x", "z"])
    b = StringColumn.from_strings(["y", "z"])
    ua, ub = unify_dictionaries([a, b])
    assert list(ua.dictionary) == ["x", "y", "z"] == list(ub.dictionary)
    assert list(ua.to_numpy(2)[0]) == ["x", "z"]
    assert list(ub.to_numpy(2)[0]) == ["y", "z"]


def test_arrow_roundtrip():
    table = pa.table({
        "i": pa.array([1, 2, None], type=pa.int32()),
        "d": pa.array([1.0, None, 3.0], type=pa.float64()),
        "s": pa.array(["a", None, "c"]),
        "b": pa.array([True, False, None]),
    })
    batch, schema = hostcol.from_arrow_table(table)
    assert schema.names == ["i", "d", "s", "b"]
    assert batch.realized_num_rows() == 3
    back = hostcol.to_arrow_table(batch, schema)
    assert back.to_pydict() == table.to_pydict()


def test_rows_roundtrip():
    schema = Schema(["a", "b"], [dt.INT64, dt.STRING])
    rows = [(1, "x"), (None, "y"), (3, None)]
    batch = hostcol.rows_to_columnar(rows, schema)
    assert hostcol.columnar_to_rows(batch) == rows


def test_batch_slice():
    vals = np.arange(300, dtype=np.int64)
    b = ColumnarBatch([Column.from_numpy(vals)], 300)
    s = b.slice(100, 50)
    assert s.realized_num_rows() == 50
    out, _ = s.columns[0].to_numpy(50)
    np.testing.assert_array_equal(out, np.arange(100, 150))


def test_scalar_column():
    c = Column.from_scalar(Scalar(dt.INT32, 7), 128)
    out, _ = c.to_numpy(5)
    np.testing.assert_array_equal(out, [7] * 5)
    n = Column.from_scalar(Scalar(dt.INT32, None), 128)
    _, v = n.to_numpy(5)
    assert not v.any()
