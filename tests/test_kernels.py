"""Native Pallas kernel differential fences (native/kernels).

The kernel layer's correctness contract is BIT-EQUALITY with the jnp
implementations it replaces: for every routed op the gate-on and
gate-off paths must agree exactly — across composite keys, nulls,
empty partitions, string dictionaries, the streaming fold seam and the
8-shard SPMD mesh. CPU CI runs the kernels through the Pallas
interpreter (the registry pins ``interpret=True`` off-TPU), so these
fences exercise the same kernel bodies that compile for TPU.
"""
from __future__ import annotations

import numpy as np
import pandas as pd
import pytest

import jax
import jax.numpy as jnp

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.native import kernels as nk
from spark_rapids_tpu.ops import join as J
from spark_rapids_tpu.ops import sort as osort
from spark_rapids_tpu.ops.sortkeys import SortKeySpec

from tests.compare import assert_frames_equal


@pytest.fixture(autouse=True)
def _gates_reset():
    """Every test starts and ends at the shipped defaults (master off)."""
    nk.reset_config()
    yield
    nk.reset_config()


# ---------------------------------------------------------------------------
# gate defaults and knob routing
# ---------------------------------------------------------------------------


def test_gates_default_off_and_conf_routing():
    assert not nk.enabled("join")
    assert not nk.enabled("sort")
    assert not nk.enabled("strings")
    from spark_rapids_tpu.config import RapidsConf

    conf = RapidsConf({"rapids.tpu.native.kernels.enabled": True,
                       "rapids.tpu.native.kernels.sort": False})
    nk.configure_from_conf(conf)
    assert nk.enabled("join") and nk.enabled("strings")
    assert not nk.enabled("sort")      # sub-gate wins under the master
    tok_on = nk.cache_token()
    nk.reset_config()
    assert nk.cache_token() != tok_on  # knob flips must miss jit caches


# ---------------------------------------------------------------------------
# hash-join probe kernel: differential triples over ops/join.equi_join
# ---------------------------------------------------------------------------


def _join_batch(n, cap, keyspace, seed, with_str=False):
    r = np.random.default_rng(seed)
    k1 = r.integers(0, keyspace, size=cap).astype(np.int64)
    k2 = r.integers(0, 3, size=cap).astype(np.int32)
    val = r.integers(0, 1000, size=cap).astype(np.int64)
    v1 = r.random(cap) > 0.15          # nulls on the first key column
    cols = [Column(dt.INT64, jnp.asarray(k1), jnp.asarray(v1)),
            Column(dt.INT32, jnp.asarray(k2), None),
            Column(dt.INT64, jnp.asarray(val), None)]
    types = [dt.INT64, dt.INT32, dt.INT64]
    if with_str:
        dic = np.array(["a", "bb", "ccc", "dddd"], dtype=object)
        codes = jnp.asarray(r.integers(0, 4, size=cap).astype(np.int32))
        cols.append(StringColumn(codes, dic, None))
        types.append(dt.STRING)
    return ColumnarBatch(cols, n), types


def _join_rows(out, out_types):
    n = int(jax.device_get(out.num_rows_device()))
    rows = []
    for i in range(n):
        row = []
        for c in out.columns:
            d = np.asarray(jax.device_get(c.data))[i]
            valid = c.validity is None or \
                bool(np.asarray(jax.device_get(c.validity))[i])
            if isinstance(c, StringColumn):
                row.append(str(c.dictionary[int(d)]) if valid else None)
            else:
                row.append(d.item() if valid else None)
        rows.append(tuple(row))
    return sorted(rows, key=lambda r: tuple((x is None, x) for x in r))


def _run_join(join_type, kernels_on, sk, bk, with_str=False,
              prepared=False):
    nk.configure(enabled=kernels_on)
    s, st = _join_batch(90, 128, 40, seed=1, with_str=with_str)
    b, bt = _join_batch(50, 64, 40, seed=2, with_str=with_str)
    prep = None
    if prepared:
        prep = J.prepare_build(b, bk, bt, [st[o] for o in sk])
        assert prep is not None
    out, ot = J.equi_join(s, b, sk, bk, st, bt, join_type=join_type,
                          prepared=prep)
    return _join_rows(out, ot)


@pytest.mark.parametrize("join_type",
                         ["inner", "left", "leftsemi", "leftanti",
                          "full"])
def test_join_probe_kernel_differential(join_type):
    """kernel == jnp, per join type, over single-column, composite and
    string keys (nulls on the probe/build key), plus the
    build-once/probe-many prepared path."""
    for sk, bk, ws in [([0], [0], False),        # single int64 key
                       ([0, 1], [0, 1], False),  # composite key
                       ([3], [3], True)]:        # string key
        base = _run_join(join_type, False, sk, bk, with_str=ws)
        kern = _run_join(join_type, True, sk, bk, with_str=ws)
        assert base == kern, (join_type, sk, ws)
    # prepared build table reused across probes (non-string keys)
    base = _run_join(join_type, False, [0, 1], [0, 1], prepared=True)
    kern = _run_join(join_type, True, [0, 1], [0, 1], prepared=True)
    assert base == kern, (join_type, "prepared")


def test_join_probe_kernel_empty_build():
    nk.configure(enabled=True)
    s, st = _join_batch(10, 16, 5, seed=3)
    b, bt = _join_batch(0, 8, 5, seed=4)
    out, _ = J.equi_join(s, b, [0], [0], st, bt, join_type="inner")
    assert int(jax.device_get(out.num_rows_device())) == 0
    out, _ = J.equi_join(s, b, [0], [0], st, bt, join_type="left")
    assert int(jax.device_get(out.num_rows_device())) == 10


def test_probe_table_matches_searchsorted():
    """The probe kernel's (lo, cnt) contract IS searchsorted
    left/right over the hash-sorted build side — checked directly."""
    from spark_rapids_tpu.native.kernels import join as njoin

    nk.configure(enabled=True)
    r = np.random.default_rng(7)
    h_b = jnp.asarray(r.integers(-2**62, 2**62, size=64))
    n_valid = jnp.asarray(48)           # tail is padding
    maxh = jnp.iinfo(jnp.int64).max
    h_b = jnp.where(jnp.arange(64) < 48, h_b, maxh)
    sh = jnp.sort(h_b)
    table = njoin.build_table(sh, n_valid, njoin.table_bits_for(64))
    h_p = jnp.asarray(np.concatenate(
        [r.choice(np.asarray(jax.device_get(sh))[:48], 20),
         r.integers(-2**62, 2**62, size=12)]))
    lo, cnt = njoin.probe(table, h_p)
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(lo)),
        np.searchsorted(np.asarray(jax.device_get(sh)),
                        np.asarray(jax.device_get(h_p)), side="left"))
    np.testing.assert_array_equal(
        np.asarray(jax.device_get(cnt)),
        np.searchsorted(np.asarray(jax.device_get(sh)),
                        np.asarray(jax.device_get(h_p)), side="right") -
        np.searchsorted(np.asarray(jax.device_get(sh)),
                        np.asarray(jax.device_get(h_p)), side="left"))


# ---------------------------------------------------------------------------
# segmented sort / partition kernels
# ---------------------------------------------------------------------------


def test_partition_order_matches_stable_argsort():
    from spark_rapids_tpu.native.kernels import sort as nsort

    nk.configure(enabled=True)
    r = np.random.default_rng(11)
    for mask in [r.random(257) > 0.5, np.ones(64, bool),
                 np.zeros(64, bool), np.array([True])]:
        m = jnp.asarray(mask)
        got = np.asarray(jax.device_get(nsort.partition_order(m)))
        want = np.asarray(jax.device_get(
            jnp.argsort(~m, stable=True)))
        np.testing.assert_array_equal(got, want)


def _sort_batch(cap, n, seed, float_key=False):
    r = np.random.default_rng(seed)
    k1 = r.integers(-50, 50, size=cap).astype(np.int64)
    v1 = r.random(cap) > 0.2
    k2 = r.random(cap) if float_key else \
        r.integers(0, 5, size=cap).astype(np.int32)
    pay = r.integers(0, 10**6, size=cap).astype(np.int64)
    cols = [Column(dt.INT64, jnp.asarray(k1), jnp.asarray(v1)),
            Column(dt.FLOAT64 if float_key else dt.INT32,
                   jnp.asarray(k2), None),
            Column(dt.INT64, jnp.asarray(pay), None)]
    types = [dt.INT64, dt.FLOAT64 if float_key else dt.INT32, dt.INT64]
    return ColumnarBatch(cols, n), types


@pytest.mark.parametrize("float_key", [False, True])
def test_sort_batch_differential(float_key):
    """kernel == jnp through ops/sort.sort_batch: composite keys with
    nulls, asc/desc and NULLS FIRST/LAST; a float key exercises the
    kernel's fallback (no f64 bitcast on TPU) which must STILL agree."""
    specs = (SortKeySpec(0, ascending=False, nulls_first=False),
             SortKeySpec(1, ascending=True, nulls_first=True))

    def run(on):
        nk.configure(enabled=on)
        batch, types = _sort_batch(160, 117, seed=5,
                                   float_key=float_key)
        out = osort.sort_batch(batch, list(specs), types)
        return [np.asarray(jax.device_get(c.data))[:117]
                for c in out.columns] + \
               [None if c.validity is None else
                np.asarray(jax.device_get(c.validity))[:117]
                for c in out.columns]

    for a, b in zip(run(False), run(True)):
        if a is None:
            assert b is None
        else:
            np.testing.assert_array_equal(a, b)


def test_sort_indices_differential():
    specs = (SortKeySpec(0, ascending=True, nulls_first=False),)

    def run(on):
        nk.configure(enabled=on)
        batch, types = _sort_batch(96, 96, seed=9)
        return np.asarray(jax.device_get(
            osort.sort_indices(batch, list(specs), types)))

    np.testing.assert_array_equal(run(False), run(True))


def test_sort_empty_partition():
    """Zero live rows: every row is padding; kernel and jnp must agree
    on the (vacuous) permutation head."""
    nk.configure(enabled=True)
    batch, types = _sort_batch(64, 0, seed=13)
    out = osort.sort_batch(batch, [SortKeySpec(0)], types)
    assert int(jax.device_get(out.num_rows_device())) == 0


# ---------------------------------------------------------------------------
# dictionary-string kernels
# ---------------------------------------------------------------------------

_WORDS = ["", "a", "apple", "APPLESAUCE", "banana split", "a%b_c",
          "100%", "under_score", "the quick brown fox", "x", "ab" * 40]


def _string_colv(words, cap=64, seed=3, with_nulls=True):
    from spark_rapids_tpu.expressions.base import ColV

    r = np.random.default_rng(seed)
    dic = np.unique(np.array(words, dtype=object).astype(str)) \
        .astype(object)
    codes = jnp.asarray(r.integers(0, len(dic), cap).astype(np.int32))
    validity = jnp.asarray(r.random(cap) > 0.2) if with_nulls else None
    col = StringColumn(codes, dic, validity)
    return ColV(dt.STRING, codes, validity, col), dic


class _Child:
    """Minimal child expression yielding a fixed ColV."""

    children = []
    _colv = None

    def eval(self, ctx):
        return _Child._colv


def test_string_predicates_differential():
    """LIKE / contains / startswith / endswith: kernel == host
    dictionary map, over escapes, wildcards and nulls."""
    from spark_rapids_tpu.expressions import strings as S

    def run(on, build):
        nk.configure(enabled=on)
        colv, _dic = _string_colv(_WORDS)
        _Child._colv = colv
        res = build().eval(None)
        vals = np.asarray(jax.device_get(res.data))
        vmask = None if res.validity is None else \
            np.asarray(jax.device_get(res.validity))
        return vals, vmask

    cases = [
        lambda: S.Like(_Child(), "%apple%"),
        lambda: S.Like(_Child(), "a%b\\_c"),
        lambda: S.Like(_Child(), "100\\%"),
        lambda: S.Like(_Child(), "_pple"),
        lambda: S.Like(_Child(), "%quick%fox"),
        lambda: S.Contains(_Child(), "an"),
        lambda: S.StartsWith(_Child(), "a"),
        lambda: S.EndsWith(_Child(), "x"),
    ]
    for build in cases:
        base_v, base_m = run(False, build)
        kern_v, kern_m = run(True, build)
        np.testing.assert_array_equal(base_v, kern_v)
        if base_m is None:
            assert kern_m is None
        else:
            np.testing.assert_array_equal(base_m, kern_m)


def test_substring_differential():
    from spark_rapids_tpu.expressions import strings as S

    def run(on, pos, length):
        nk.configure(enabled=on)
        colv, _dic = _string_colv(_WORDS, seed=17)
        _Child._colv = colv
        res = S.Substring(_Child(), pos, length).eval(None)
        codes = np.asarray(jax.device_get(res.data))
        return [str(res.scol.dictionary[c]) for c in codes]

    for pos, length in [(1, 3), (2, 100), (-3, 2), (0, 2), (5, 0)]:
        assert run(False, pos, length) == run(True, pos, length), \
            (pos, length)


def test_string_kernel_non_ascii_fallback():
    """`_` wildcards and substring need ASCII byte==char; a non-ASCII
    dictionary must fall back (predicate_colv returns None) rather
    than answer wrong."""
    from spark_rapids_tpu.native.kernels import strings as nks

    nk.configure(enabled=True)
    colv, _dic = _string_colv(["café", "naïve", "日本語", "plain"],
                              with_nulls=False)
    assert nks.predicate_colv(colv, "like", "pl_in", "\\") is None
    assert nks.substring_colv(colv, 1, 2) is None
    # but byte-exact predicates still run on UTF-8
    got = nks.predicate_colv(colv, "contains", "ai")
    assert got is not None


def test_string_kernel_knob_off_returns_none():
    from spark_rapids_tpu.native.kernels import strings as nks

    colv, _dic = _string_colv(_WORDS)
    assert nks.predicate_colv(colv, "contains", "a") is None
    assert nks.substring_colv(colv, 1, 2) is None


# ---------------------------------------------------------------------------
# streaming fold seam
# ---------------------------------------------------------------------------


def test_streaming_fold_with_kernels_on():
    """A standing aggregation folded over appended micro-batches with
    kernels ON must match the batch oracle at every emit point — the
    fold seam re-enters the fused chain whose trace routed through the
    kernels."""
    from spark_rapids_tpu.api import Session

    nk.configure(enabled=True)
    s = Session()
    s.create_streaming_table(
        "events", Schema(["k", "v"], [dt.INT64, dt.INT64]))
    q = s.sql("SELECT k, SUM(v) AS sv, COUNT(v) AS c "
              "FROM events GROUP BY k")
    try:
        sq = s.service.register_standing(q)
        seen = []
        for i in range(3):
            r = np.random.default_rng(i)
            b = {"k": r.integers(0, 7, 120 + 11 * i).astype(np.int64),
                 "v": r.integers(0, 100,
                                 120 + 11 * i).astype(np.int64)}
            seen.append(pd.DataFrame(b))
            s.append_batch("events", b)
            oracle = pd.concat(seen, ignore_index=True).groupby("k") \
                .agg(sv=("v", "sum"), c=("v", "count")).reset_index()
            assert_frames_equal(oracle, sq.results())
    finally:
        s.stop()


# ---------------------------------------------------------------------------
# SPMD mesh
# ---------------------------------------------------------------------------


def test_spmd_mesh_8_shard_bitexact():
    """Join + group-by + sort on the 8-shard mesh with kernels ON must
    be BIT-equal to the single-device kernels-on run and to the
    kernels-off run: kernel routing happens inside the shard_map
    programs and changes nothing observable."""
    from spark_rapids_tpu.api import Session

    n = 997                 # not divisible by 8: uneven shards
    r = np.random.default_rng(23)
    fact = pd.DataFrame({
        "k": r.integers(0, 40, n).astype(np.int64),
        "v": r.integers(0, 1000, n).astype(np.int64)})
    dim = pd.DataFrame({"k": np.arange(40, dtype=np.int64),
                        "w": (np.arange(40, dtype=np.int64) * 3) % 7})

    def run(mesh, kernels_on):
        nk.configure(enabled=kernels_on)
        conf = {"rapids.tpu.mesh.enabled": True,
                "rapids.tpu.mesh.devices": 8} if mesh else {}
        s = Session(conf)
        try:
            s.create_temp_view("fact", s.create_dataframe(fact))
            s.create_temp_view("dim", s.create_dataframe(dim))
            return s.sql(
                "SELECT dim.w AS w, SUM(fact.v) AS sv, COUNT(*) AS c "
                "FROM fact JOIN dim ON fact.k = dim.k "
                "GROUP BY dim.w ORDER BY w").to_pandas()
        finally:
            s.stop()

    base = run(mesh=False, kernels_on=False)
    single = run(mesh=False, kernels_on=True)
    mesh = run(mesh=True, kernels_on=True)
    for other, tag in ((single, "single+kernels"), (mesh, "mesh")):
        assert list(base.columns) == list(other.columns)
        for c in base.columns:
            np.testing.assert_array_equal(
                base[c].to_numpy(), other[c].to_numpy(),
                err_msg=f"{tag}: col {c}")
