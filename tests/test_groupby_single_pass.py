"""Chunked vs single-pass groupby equivalence (PR 13 satellite).

ops/groupby.py historically split wide aggregate lists (> _AOT_MAX_AGGS
columns at capacity >= _AOT_CHUNK_MIN_CAP on the sort path) into two
launches — the libtpu v5e AOT-segfault workaround. ``single_pass=True``
(the default) emits ONE wide launch instead; the chunked loop survives
as an escape hatch (knob rapids.tpu.sql.groupby.singlePass.enabled).
This suite pins the contract that the two emissions are the SAME
aggregate: bit-exact results across the _AOT_MAX_AGGS width boundary
and the _AOT_CHUNK_MIN_CAP capacity boundary, with and without a fused
filter mask, and that dense/sort/chunked/single-pass all agree on
order-insensitive aggregates. It also covers the exec-level
_COMPACT_WIDE_MIN_CAP pre-pass composing with the knob.
"""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.ops import groupby as gb
from spark_rapids_tpu.ops.groupby import AggSpec

WIDE_CAP = gb._AOT_CHUNK_MIN_CAP          # chunking engages at this cap

# 9 aggregates (> _AOT_MAX_AGGS = 6) over a float and an int column.
# Chunked and single-pass share the SAME sort kernel and per-column
# segmented reductions, so even the float sum must be bit-exact between
# them (unlike dense-vs-sort, where only order-insensitive aggs are).
WIDE_AGGS = [AggSpec("sum", 1), AggSpec("min", 1), AggSpec("max", 1),
             AggSpec("count", 1), AggSpec("sum", 2), AggSpec("min", 2),
             AggSpec("max", 2), AggSpec("count", 2),
             AggSpec("count_star")]

ORDER_INSENSITIVE_WIDE = [AggSpec("min", 1), AggSpec("max", 1),
                          AggSpec("count", 1), AggSpec("sum", 2),
                          AggSpec("min", 2), AggSpec("max", 2),
                          AggSpec("count_star")]       # 7 > 6, exact on
                                                       # any kernel


def _wide_batch(rng, n, span, with_stats=False):
    keys = rng.integers(0, span, n).astype(np.int64)
    keys[:min(span, n)] = np.arange(min(span, n))
    f = rng.standard_normal(n)
    f[rng.random(n) < 0.05] = np.nan
    f[rng.random(n) < 0.05] = -0.0
    i = rng.integers(-1000, 1000, n).astype(np.int64)
    kcol = Column.from_numpy(keys)
    if with_stats:
        kcol.stats = (0, span - 1)
    cols = [kcol,
            Column.from_numpy(f, validity=rng.random(n) > 0.1),
            Column.from_numpy(i, validity=rng.random(n) > 0.1)]
    return ColumnarBatch(cols, n), [dt.INT64, dt.FLOAT64, dt.INT64]


def _rows(out, num_aggs):
    """Realized (key -> agg tuple) dict with float BITS for exactness."""
    import jax

    n = out.realized_num_rows()
    cols = []
    for c in out.columns:
        data = np.asarray(jax.device_get(c.data))[:n]
        if data.dtype.kind == "f":
            data = data.view(f"u{data.dtype.itemsize}")
        valid = np.ones(n, bool) if c.validity is None else \
            np.asarray(jax.device_get(c.validity))[:n]
        cols.append((data, valid))
    rows = {}
    for i in range(n):
        key = (cols[0][0][i].item(), bool(cols[0][1][i]))
        rows[key] = tuple(
            (cols[j][0][i].item(), bool(cols[j][1][i]))
            for j in range(1, 1 + num_aggs))
    return rows


def _count_launches(fn):
    """Run ``fn`` counting _groupby invocations (the chunk loop calls
    it once per chunk; single-pass exactly once)."""
    calls = []
    real = gb._groupby

    def spy(*a, **k):
        calls.append(1)
        return real(*a, **k)

    gb._groupby = spy
    try:
        out = fn()
    finally:
        gb._groupby = real
    return out, len(calls)


@pytest.mark.parametrize("masked", [False, True])
def test_single_pass_matches_chunked_bit_exact(masked):
    """At chunk-eligible shape (9 aggs, cap >= _AOT_CHUNK_MIN_CAP, sort
    path) the chunked loop issues 2 launches and single-pass 1, and the
    results — including float sums and NaN/-0.0 bits — are identical.
    Both with and without a fused filter live_mask."""
    rng = np.random.default_rng(42 + masked)
    n = WIDE_CAP
    b, types = _wide_batch(rng, n, 1000)
    mask = (rng.random(b.capacity) > 0.3) if masked else None
    out_c, nc = _count_launches(lambda: gb.groupby_aggregate(
        b, [0], WIDE_AGGS, types, live_mask=mask, single_pass=False))
    out_s, ns = _count_launches(lambda: gb.groupby_aggregate(
        b, [0], WIDE_AGGS, types, live_mask=mask, single_pass=True))
    assert nc == 2 and ns == 1
    assert _rows(out_c[0], len(WIDE_AGGS)) == \
        _rows(out_s[0], len(WIDE_AGGS))


def test_agg_width_boundary():
    """Exactly _AOT_MAX_AGGS aggs never chunk (either mode); one more
    chunks under single_pass=False and stays whole under True, with
    bit-identical results either way."""
    rng = np.random.default_rng(7)
    b, types = _wide_batch(rng, WIDE_CAP, 500)
    six = WIDE_AGGS[:gb._AOT_MAX_AGGS]
    out6, n6 = _count_launches(lambda: gb.groupby_aggregate(
        b, [0], six, types, single_pass=False))
    assert n6 == 1
    seven = WIDE_AGGS[:gb._AOT_MAX_AGGS + 1]
    out_c, n7c = _count_launches(lambda: gb.groupby_aggregate(
        b, [0], seven, types, single_pass=False))
    out_s, n7s = _count_launches(lambda: gb.groupby_aggregate(
        b, [0], seven, types, single_pass=True))
    assert n7c == 2 and n7s == 1
    assert _rows(out_c[0], 7) == _rows(out_s[0], 7)
    # the 6-agg prefix of the 7-agg run matches the 6-agg run: adding
    # an aggregate must not perturb its neighbours
    assert _rows(out6[0], 6) == {
        k: v[:6] for k, v in _rows(out_c[0], 7).items()}


def test_capacity_boundary_skips_chunking():
    """One bucket below _AOT_CHUNK_MIN_CAP the chunk loop never engages
    (the AOT defect is shape-gated), so both modes are one launch and
    trivially identical."""
    rng = np.random.default_rng(11)
    b, types = _wide_batch(rng, WIDE_CAP // 2, 500)
    assert b.capacity < gb._AOT_CHUNK_MIN_CAP
    out_c, nc = _count_launches(lambda: gb.groupby_aggregate(
        b, [0], WIDE_AGGS, types, single_pass=False))
    out_s, ns = _count_launches(lambda: gb.groupby_aggregate(
        b, [0], WIDE_AGGS, types, single_pass=True))
    assert nc == 1 and ns == 1
    assert _rows(out_c[0], len(WIDE_AGGS)) == \
        _rows(out_s[0], len(WIDE_AGGS))


def test_dense_sort_chunked_single_pass_all_agree():
    """Order-insensitive wide aggregate, dense-eligible key span: the
    dense sweep (stats), the sort kernel, the chunked sort loop and the
    single-pass sort launch all produce the same bits. Dense also never
    chunks (no sort module to protect), even under single_pass=False."""
    rng = np.random.default_rng(13)
    n = WIDE_CAP
    b_stats, types = _wide_batch(rng, n, 100, with_stats=True)
    b_plain = ColumnarBatch(list(b_stats.columns), n)
    b_plain.columns[0] = Column(dt.INT64, b_stats.columns[0].data,
                                b_stats.columns[0].validity)  # no stats
    na = len(ORDER_INSENSITIVE_WIDE)
    out_d, nd = _count_launches(lambda: gb.groupby_aggregate(
        b_stats, [0], ORDER_INSENSITIVE_WIDE, types,
        single_pass=False))
    assert nd == 1          # will_dense short-circuits the chunk gate
    out_s, _ = _count_launches(lambda: gb.groupby_aggregate(
        b_plain, [0], ORDER_INSENSITIVE_WIDE, types, single_pass=True))
    out_c, ncc = _count_launches(lambda: gb.groupby_aggregate(
        b_plain, [0], ORDER_INSENSITIVE_WIDE, types,
        single_pass=False))
    assert ncc == 2
    rows = _rows(out_d[0], na)
    assert rows == _rows(out_s[0], na) == _rows(out_c[0], na)


def test_exec_compact_wide_composes_with_single_pass(monkeypatch):
    """Exec level: the _COMPACT_WIDE_MIN_CAP pre-pass (compact filtered
    survivors before a wide sort-path aggregate) and the single-pass
    knob compose — with the boundary lowered into range the compaction
    engages and both knob settings still match the CPU oracle; at the
    default boundary (capacity far below 1<<22) it must NOT engage."""
    from compare import assert_cpu_and_tpu_equal
    from spark_rapids_tpu import config as cfg
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.execs.aggregate import HashAggregateExec
    from spark_rapids_tpu.plan import nodes as pn
    from spark_rapids_tpu.sql import parse, plan_statement

    rng = np.random.default_rng(17)
    n = 4096
    src = pn.InMemorySource(
        {"k": rng.integers(0, 1000, n).astype(np.int64),
         "v": rng.standard_normal(n),
         "w": rng.integers(-50, 50, n).astype(np.int64)},
        validity={"v": rng.random(n) > 0.1})
    sql = ("SELECT k, sum(v) AS a1, min(v) AS a2, max(v) AS a3, "
           "count(v) AS a4, sum(w) AS a5, min(w) AS a6, max(w) AS a7 "
           "FROM t WHERE v > 0.2 GROUP BY k ORDER BY k")
    plan = plan_statement(parse(sql), {"t": src})

    compacted = []
    real = HashAggregateExec._maybe_compact_wide

    def spy(self, b, mask):
        nb, nm = real(self, b, mask)
        if mask is not None and nm is None:
            compacted.append(nb.capacity)
        return nb, nm

    monkeypatch.setattr(HashAggregateExec, "_maybe_compact_wide", spy)
    for min_cap in (256, HashAggregateExec._COMPACT_WIDE_MIN_CAP):
        monkeypatch.setattr(HashAggregateExec, "_COMPACT_WIDE_MIN_CAP",
                            min_cap)
        for sp in (True, False):
            compacted.clear()
            conf = RapidsConf().with_overrides(
                {cfg.GROUPBY_SINGLE_PASS.key: sp})
            assert_cpu_and_tpu_equal(plan, conf=conf, sort=False,
                                     approx_float=1e-9)
            if min_cap == 256:
                assert compacted, \
                    "compact-wide pre-pass should engage below the " \
                    "lowered boundary"
            else:
                assert not compacted
