"""API-drift validation (the reference's api_validation module,
ApiValidation.scala:24-60: reflection-diff Gpu exec signatures against
Spark's). Here the invariants are internal: every plan node must be
covered by BOTH engines, and every registered expression must evaluate
on BOTH engines — so the accelerated path and the oracle can never drift
structurally."""
import inspect

import pytest

from spark_rapids_tpu.cpu import engine as cpu_engine
from spark_rapids_tpu.cpu import evaluator as cpu_eval
from spark_rapids_tpu.expressions.base import Expression
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan import overrides


def _all_plan_nodes():
    out = [klass for _, klass in inspect.getmembers(pn, inspect.isclass)
           if issubclass(klass, pn.PlanNode) and klass is not pn.PlanNode]
    from spark_rapids_tpu.execs.python_exec import MapInPandasNode
    from spark_rapids_tpu.io.write import WriteFilesNode

    out += [MapInPandasNode, WriteFilesNode]
    return out


def test_every_plan_node_has_planner_rule():
    missing = [k.__name__ for k in _all_plan_nodes()
               if k not in overrides._NODE_RULES]
    assert not missing, (
        f"plan nodes without a TpuOverrides rule: {missing} — add a "
        "NodeRule (or an explicit fallback decision) for each")


def test_every_plan_node_has_cpu_engine_impl():
    missing = [k.__name__ for k in _all_plan_nodes()
               if k not in cpu_engine._NODES]
    assert not missing, (
        f"plan nodes the CPU oracle cannot execute: {missing}")


def _registered_expressions():
    return [k for k in overrides._EXPR_RULES
            if issubclass(k, Expression)]


def test_every_registered_expression_evaluates_on_cpu():
    from spark_rapids_tpu.expressions.aggregates import AggregateFunction

    missing = []
    for klass in _registered_expressions():
        if issubclass(klass, AggregateFunction):
            continue  # evaluated through the aggregate exec, not eval_expr
        if klass in cpu_eval._DISPATCH:
            continue
        if any(issubclass(klass, k) for k in cpu_eval._DISPATCH):
            continue
        if hasattr(klass, "eval_cpu"):
            continue
        missing.append(klass.__name__)
    assert not missing, (
        f"registered expressions the CPU oracle cannot evaluate: "
        f"{missing}")


def test_every_registered_expression_has_device_eval():
    from spark_rapids_tpu.expressions.aggregates import AggregateFunction

    missing = []
    for klass in _registered_expressions():
        if issubclass(klass, AggregateFunction):
            continue
        if "eval" not in {m for k in klass.__mro__ if k is not Expression
                          for m in vars(k)}:
            missing.append(klass.__name__)
    assert not missing, (
        f"registered expressions without a device eval: {missing}")


def test_aggregate_functions_declare_partial_contract():
    """Partial/final split requires coherent update/merge halves
    (CudfAggregate pairs, AggregateFunctions.scala:531)."""
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.expressions import aggregates as A
    from spark_rapids_tpu.expressions.base import BoundReference

    child = BoundReference(0, dt.FLOAT64)
    for klass in (A.Sum, A.Min, A.Max, A.Count, A.Average, A.First,
                  A.Last):
        inst = klass(child)
        assert inst.partial_types(), klass.__name__
        assert inst.update_ops(), klass.__name__
        assert inst.merge_ops(), klass.__name__
        assert len(inst.update_ops()) == len(inst.partial_types())


# ---------------------------------------------------------------------------
# Shim loader (SURVEY.md §2.13: ShimLoader + SparkShimServiceProvider)


def test_shim_provider_version_probe():
    from spark_rapids_tpu import shims

    assert shims.ModernJaxShimProvider.matches("0.9.0")
    assert shims.ModernJaxShimProvider.matches("1.2.3")
    assert not shims.ModernJaxShimProvider.matches("0.4.30")
    assert shims.LegacyJaxShimProvider.matches("0.4.30")
    assert shims.LegacyJaxShimProvider.matches("0.5.1")
    assert not shims.LegacyJaxShimProvider.matches("0.6.0")


def test_shim_loader_resolves_and_caches():
    import jax

    from spark_rapids_tpu import shims

    s1 = shims.get_shims()
    assert s1 is shims.get_shims()
    # the resolved shard_map is the one the running jax serves
    assert s1.shard_map() is not None
    assert shims._resolve(jax.__version__) is not s1  # fresh build


def test_shim_unsupported_version_raises():
    import pytest

    from spark_rapids_tpu import shims

    with pytest.raises(RuntimeError, match="shim provider"):
        shims._resolve("0.3.25")


def test_shim_provider_override(monkeypatch):
    from spark_rapids_tpu import shims

    monkeypatch.setenv(
        shims.OVERRIDE_ENV,
        "spark_rapids_tpu.shims.LegacyJaxShimProvider")
    resolved = shims._resolve("0.3.25")  # probe would fail; override wins
    assert type(resolved).__name__ == "_LegacyJaxShims"
