"""ML handoff + pandas-exec tests (§2.6 ColumnarRdd / §2.12 analogues)."""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.cpu.engine import execute_cpu
from spark_rapids_tpu.execs.base import collect
from spark_rapids_tpu.execs.python_exec import MapInPandasNode
from spark_rapids_tpu.expressions.base import BoundReference
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import Literal
from spark_rapids_tpu.ml import (batch_to_torch, collect_feature_matrix,
                                 exec_to_device_matrices)
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.overrides import apply_overrides

from tests.compare import assert_frames_equal


def scan(n=500, seed=4):
    rng = np.random.default_rng(seed)
    return pn.ScanNode(pn.InMemorySource(
        {"a": rng.integers(0, 100, n).astype(np.int64),
         "b": rng.random(n),
         "s": np.array([f"x{k % 5}" for k in range(n)], dtype=object)},
        validity={"b": rng.random(n) > 0.1}))


def test_feature_matrix_from_pipeline():
    plan = pn.FilterNode(
        P.GreaterThan(BoundReference(0, dt.INT64), Literal(50)), scan())
    exec_ = apply_overrides(plan, RapidsConf())
    mat = collect_feature_matrix(exec_)
    # string column excluded; rows = filter survivors; NULL -> NaN
    cpu = execute_cpu(plan).to_pandas()
    assert mat.shape == (len(cpu), 2)
    nan_count = int(np.isnan(np.asarray(mat)[:, 1]).sum())
    assert nan_count == int(cpu["b"].isna().sum())
    np.testing.assert_allclose(
        np.asarray(mat)[:, 0],
        cpu["a"].astype(np.float64).to_numpy().astype(np.float32))


def test_streamed_device_matrices():
    exec_ = apply_overrides(scan(), RapidsConf())
    total = 0
    for feats, valid in exec_to_device_matrices(exec_):
        assert feats.shape == valid.shape
        assert feats.shape[1] == 2
        total += feats.shape[0]
    assert total == 500


def test_batch_to_torch_dlpack():
    torch = pytest.importorskip("torch")
    exec_ = apply_overrides(scan(100), RapidsConf())
    batches = [b for p in range(exec_.num_partitions)
               for b in exec_.execute(p)]
    tensors = batch_to_torch(batches[0], exec_.schema.types)
    assert 0 in tensors and 1 in tensors and 2 not in tensors
    assert tensors[0].shape[0] == 100
    assert tensors[0].dtype == torch.int64


def test_map_in_pandas_matches_oracle():
    def double_and_tag(df: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({
            "a2": df["a"].astype("int64") * 2,
            "tag": df["s"].astype(str) + "!",
        })

    schema = Schema(["a2", "tag"], [dt.INT64, dt.STRING])
    plan = MapInPandasNode(double_and_tag, schema, scan(300))
    conf = RapidsConf({"rapids.tpu.sql.exec.MapInPandasNode": True})
    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, conf)
    assert type(exec_).__name__ == "MapInPandasExec"
    assert_frames_equal(cpu_df, collect(exec_))


def test_map_in_pandas_disabled_by_default():
    schema = Schema(["a"], [dt.INT64])
    plan = MapInPandasNode(lambda df: df[["a"]], schema, scan(50))
    exec_ = apply_overrides(plan, RapidsConf())
    assert type(exec_).__name__ == "CpuFallbackExec"
    cpu_df = execute_cpu(plan).to_pandas()
    assert_frames_equal(cpu_df, collect(exec_))


def test_map_in_pandas_null_handling():
    def keep_nulls(df: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({"b": df["b"]})

    schema = Schema(["b"], [dt.FLOAT64])
    plan = MapInPandasNode(keep_nulls, schema, scan(200))
    conf = RapidsConf({"rapids.tpu.sql.exec.MapInPandasNode": True})
    cpu_df = execute_cpu(plan).to_pandas()
    assert cpu_df["b"].isna().any()
    exec_ = apply_overrides(plan, conf)
    assert_frames_equal(cpu_df, collect(exec_))


def test_grouped_map_in_pandas_matches_oracle():
    from spark_rapids_tpu.execs.python_exec import GroupedMapInPandasNode

    def summarize(g: pd.DataFrame) -> pd.DataFrame:
        return pd.DataFrame({
            "a": [int(g["a"].iloc[0])],
            "total": [float(pd.to_numeric(g["b"],
                                          errors="coerce").sum())],
            "n": [len(g)],
        })

    schema = Schema(["a", "total", "n"],
                    [dt.INT64, dt.FLOAT64, dt.INT64])
    base = scan(400)
    # group by a % 10 -> project first so keys are plain columns
    from spark_rapids_tpu.expressions import arithmetic as ar
    from spark_rapids_tpu.expressions.base import Alias, Literal

    proj = pn.ProjectNode(
        [Alias(ar.Remainder(BoundReference(0, dt.INT64),
                            Literal(10, dt.INT64)), "a"),
         Alias(BoundReference(1, dt.FLOAT64), "b")], base)
    plan = GroupedMapInPandasNode([0], summarize, schema, proj)
    conf = RapidsConf(
        {"rapids.tpu.sql.exec.GroupedMapInPandasNode": True})
    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, conf)
    assert type(exec_).__name__ == "GroupedMapInPandasExec"
    assert_frames_equal(cpu_df, collect(exec_), approx_float=1e-9)


def test_grouped_map_through_api():
    import pandas as _pd

    from spark_rapids_tpu.api import Session

    s = Session({"rapids.tpu.sql.exec.GroupedMapInPandasNode": True})
    df = s.create_dataframe(_pd.DataFrame(
        {"k": [1, 1, 2, 2, 2, 3], "v": [1.0, 2.0, 3.0, 4.0, 5.0, 6.0]}))

    def center(g: _pd.DataFrame) -> _pd.DataFrame:
        v = g["v"].astype(float)
        return _pd.DataFrame({"k": g["k"].astype(int),
                              "centered": v - v.mean()})

    schema = Schema(["k", "centered"], [dt.INT64, dt.FLOAT64])
    out = df.group_by("k").apply_in_pandas(center, schema).collect()
    assert len(out) == 6
    got = out.groupby(out["k"].astype(int))["centered"].apply(
        lambda x: round(float(x.astype(float).sum()), 9))
    assert all(v == 0 for v in got)


def test_grouped_map_disabled_by_default():
    from spark_rapids_tpu.execs.python_exec import GroupedMapInPandasNode

    plan = GroupedMapInPandasNode(
        [0], lambda g: g[["a"]], Schema(["a"], [dt.INT64]), scan(50))
    exec_ = apply_overrides(plan, RapidsConf())
    assert type(exec_).__name__ == "CpuFallbackExec"


def test_cogrouped_map_matches_oracle():
    from spark_rapids_tpu.api import Session

    s = Session({"rapids.tpu.sql.exec.CoGroupedMapInPandasNode": True})
    left = s.create_dataframe(pd.DataFrame(
        {"k": [1, 1, 2, 4], "v": [1.0, 2.0, 3.0, 9.0]}))
    right = s.create_dataframe(pd.DataFrame(
        {"k2": [1, 2, 2, 3], "w": [10.0, 20.0, 30.0, 40.0]}))

    def merge(lg: pd.DataFrame, rg: pd.DataFrame) -> pd.DataFrame:
        k = int(lg["k"].iloc[0]) if len(lg) else int(rg["k2"].iloc[0])
        return pd.DataFrame({
            "k": [k],
            "lsum": [float(pd.to_numeric(lg["v"],
                                         errors="coerce").sum())
                     if len(lg) else 0.0],
            "rsum": [float(pd.to_numeric(rg["w"],
                                         errors="coerce").sum())
                     if len(rg) else 0.0],
        })

    schema = Schema(["k", "lsum", "rsum"],
                    [dt.INT64, dt.FLOAT64, dt.FLOAT64])
    out = (left.group_by("k").cogroup(right.group_by("k2"))
           .apply_in_pandas(merge, schema).collect())
    got = {int(r.k): (float(r.lsum), float(r.rsum))
           for r in out.itertuples()}
    # keys from EITHER side appear; missing side contributes 0
    assert got == {1: (3.0, 10.0), 2: (3.0, 50.0), 3: (0.0, 40.0),
                   4: (9.0, 0.0)}
    # CPU oracle agrees
    plan = (left.group_by("k").cogroup(right.group_by("k2"))
            .apply_in_pandas(merge, schema))._plan
    cpu = execute_cpu(plan).to_pandas()
    assert_frames_equal(cpu, out)


def test_cogrouped_nan_keys_match_across_sides():
    """Regression (review finding): NaN keys from the two sides must
    land in ONE paired call, not one call per side."""
    from spark_rapids_tpu.columnar.batch import Schema as _S
    from spark_rapids_tpu.execs.python_exec import _apply_cogrouped

    lpdf = pd.DataFrame({"k": [1.0, float("nan")], "v": [1.0, 2.0]})
    rpdf = pd.DataFrame({"k2": [float("nan")], "w": [10.0]})

    calls = []

    def fn(lg, rg):
        calls.append((len(lg), len(rg)))
        return pd.DataFrame({"n": [len(lg) + len(rg)]})

    out = _apply_cogrouped(lpdf, rpdf, ["k"], ["k2"], fn,
                           _S(["n"], [dt.INT64]))
    assert len(out) == 2  # groups: k=1.0 and k=NaN
    assert (1, 1) in calls  # the NaN group saw BOTH sides


def test_from_device_arrays_round_trip():
    """Device arrays (jax / dlpack) -> DataFrame -> query, no host
    round trip on the accelerated path."""
    import jax.numpy as jnp

    from spark_rapids_tpu.api import Session, col, functions as F
    from spark_rapids_tpu.execs.basic import DeviceBatchesExec
    from spark_rapids_tpu.ml import from_device_arrays

    s = Session()
    k = jnp.asarray(np.arange(100) % 5)
    v = jnp.asarray(np.arange(100, dtype=np.float64))
    df = from_device_arrays(s, [k, v], ["k", "v"],
                            [dt.INT64, dt.FLOAT64])
    exec_ = df.filter(col("v") >= 0)._exec()
    scans = [e for e in _walk(exec_)
             if isinstance(e, DeviceBatchesExec)]
    assert scans, "device source must not round-trip through host"
    out = (df.group_by("k").agg(F.sum(col("v")).alias("sv"))
             .order_by("k").collect())
    expect = [sum(range(i, 100, 5)) for i in range(5)]
    assert [int(x) for x in out["sv"]] == expect


def test_torch_tensor_ingestion():
    torch = pytest.importorskip("torch")

    from spark_rapids_tpu.api import Session, col
    from spark_rapids_tpu.ml import from_device_arrays

    s = Session()
    t = torch.arange(50, dtype=torch.int64)
    df = from_device_arrays(s, [t], ["x"], [dt.INT64])
    assert df.filter(col("x") > 39).count() == 10


def _walk(e):
    yield e
    for c in e.children:
        yield from _walk(c)


def test_window_in_pandas_matches_oracle():
    from spark_rapids_tpu.execs.python_exec import WindowInPandasNode
    from spark_rapids_tpu.ops.sortkeys import SortKeySpec

    def running_share(g: pd.DataFrame):
        v = pd.to_numeric(g["b"], errors="coerce").fillna(0.0)
        total = float(v.sum()) or 1.0
        return (v.cumsum() / total).tolist()

    from spark_rapids_tpu.expressions import arithmetic as ar
    from spark_rapids_tpu.expressions.base import Alias, Literal

    base = scan(300)
    proj = pn.ProjectNode(
        [Alias(ar.Remainder(BoundReference(0, dt.INT64),
                            Literal(7, dt.INT64)), "a"),
         Alias(BoundReference(1, dt.FLOAT64), "b")], base)
    plan = WindowInPandasNode([0], [SortKeySpec.spark_default(1)],
                              running_share, "share", dt.FLOAT64, proj)
    conf = RapidsConf({"rapids.tpu.sql.exec.WindowInPandasNode": True})
    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, conf)
    assert type(exec_).__name__ == "WindowInPandasExec"
    assert_frames_equal(cpu_df, collect(exec_), approx_float=1e-9)


def test_window_in_pandas_disabled_by_default():
    from spark_rapids_tpu.execs.python_exec import WindowInPandasNode
    from spark_rapids_tpu.ops.sortkeys import SortKeySpec

    plan = WindowInPandasNode([0], [SortKeySpec.spark_default(1)],
                              lambda g: [0.0] * len(g), "z", dt.FLOAT64,
                              scan(40))
    exec_ = apply_overrides(plan, RapidsConf())
    assert type(exec_).__name__ == "CpuFallbackExec"
    cpu_df = execute_cpu(plan).to_pandas()
    assert_frames_equal(cpu_df, collect(exec_), approx_float=1e-9)


def test_arrow_eval_python_scalar_udfs():
    from spark_rapids_tpu.execs.python_exec import ArrowEvalPythonNode

    def plus(a, b):
        return a.astype(float) + b.astype(float)

    def neg(a):
        return -pd.to_numeric(a, errors="coerce")

    base = scan(200)
    plan = ArrowEvalPythonNode(
        [(plus, [0, 0], "twice", dt.FLOAT64),
         (neg, [1], "nb", dt.FLOAT64)], base)
    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, RapidsConf())
    assert type(exec_).__name__ == "ArrowEvalPythonExec"
    assert_frames_equal(cpu_df, collect(exec_), approx_float=1e-9)


def test_aggregate_in_pandas_matches_oracle():
    from spark_rapids_tpu.execs.python_exec import AggregateInPandasNode
    from spark_rapids_tpu.expressions import arithmetic as ar
    from spark_rapids_tpu.expressions.base import Alias, Literal

    def spread(g: pd.DataFrame):
        v = pd.to_numeric(g["b"], errors="coerce")
        return (float(v.max() - v.min()), int(len(g)))

    base = scan(300)
    proj = pn.ProjectNode(
        [Alias(ar.Remainder(BoundReference(0, dt.INT64),
                            Literal(6, dt.INT64)), "a"),
         Alias(BoundReference(1, dt.FLOAT64), "b")], base)
    schema = Schema(["a", "spread", "n"],
                    [dt.INT64, dt.FLOAT64, dt.INT64])
    plan = AggregateInPandasNode([0], spread, schema, proj)
    conf = RapidsConf(
        {"rapids.tpu.sql.exec.AggregateInPandasNode": True})
    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, conf)
    assert type(exec_).__name__ == "AggregateInPandasExec"
    assert_frames_equal(cpu_df, collect(exec_), approx_float=1e-9)

    # disabled by default -> CPU fallback
    assert type(apply_overrides(plan, RapidsConf())).__name__ == \
        "CpuFallbackExec"


def test_window_in_pandas_nulls_first_ordering():
    """Direct expectation (not oracle-vs-oracle): ASC default = NULLS
    FIRST, so the window fn must see null order-key rows first."""
    from spark_rapids_tpu.execs.python_exec import WindowInPandasNode
    from spark_rapids_tpu.ops.sortkeys import SortKeySpec

    seen = []

    def record(g: pd.DataFrame):
        seen.append([None if pd.isna(v) else float(v)
                     for v in g["b"]])
        return list(range(len(g)))

    plan = WindowInPandasNode(
        [0], [SortKeySpec.spark_default(1)], record, "pos", dt.INT64,
        pn.ScanNode(pn.InMemorySource(
            {"a": np.array([1, 1, 1, 1], dtype=np.int64),
             "b": np.array([5.0, 2.0, 9.0, 3.0])},
            validity={"b": np.array([True, False, True, True])})))
    execute_cpu(plan)
    assert seen == [[None, 3.0, 5.0, 9.0]]

    seen.clear()
    plan2 = WindowInPandasNode(
        [0], [SortKeySpec(1, ascending=False, nulls_first=False)],
        record, "pos", dt.INT64, plan.children[0])
    execute_cpu(plan2)
    assert seen == [[9.0, 5.0, 3.0, None]]


def test_pandas_udf_in_worker_process():
    """rapids.tpu.python.worker.process.enabled runs the UDF in a pooled
    SEPARATE process (python/rapids/worker.py + daemon.py model): the
    UDF observes a different pid, closures ship via cloudpickle, and
    results match the in-process path."""
    import os

    import numpy as np
    import pandas as pd

    from spark_rapids_tpu.api import Session

    parent = os.getpid()
    bias = 3.5  # closure capture crosses the process boundary

    def fn(pdf):
        return pd.DataFrame({"y": pdf["x"] * 2 + bias,
                             "pid": [os.getpid()] * len(pdf)})

    from spark_rapids_tpu.columnar.batch import Schema
    from spark_rapids_tpu.columnar import dtypes as dt

    schema = Schema(["y", "pid"], [dt.FLOAT64, dt.INT64])
    s = Session({"rapids.tpu.python.worker.process.enabled": True,
                 "rapids.tpu.python.worker.processes": 1,
                 "rapids.tpu.sql.exec.MapInPandasNode": True})
    df = s.create_dataframe(pd.DataFrame(
        {"x": np.arange(50, dtype=np.float64)}))
    out = df.map_in_pandas(fn, schema).collect()
    assert (out["y"].to_numpy() ==
            np.arange(50, dtype=np.float64) * 2 + bias).all()
    pids = set(out["pid"])
    assert len(pids) == 1 and parent not in pids, \
        "UDF must have run in a separate worker process"


def test_pandas_udf_worker_crash_isolated():
    """A UDF that kills its interpreter surfaces as an error — the
    ENGINE process survives, the pool replaces the dead worker, and the
    next query succeeds."""
    import os

    import numpy as np
    import pandas as pd
    import pytest

    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.columnar.batch import Schema
    from spark_rapids_tpu.columnar import dtypes as dt

    schema = Schema(["y"], [dt.FLOAT64])

    def boom(pdf):
        os._exit(17)

    def fine(pdf):
        return pd.DataFrame({"y": pdf["x"] + 1})

    s = Session({"rapids.tpu.python.worker.process.enabled": True,
                 "rapids.tpu.python.worker.processes": 1,
                 "rapids.tpu.sql.exec.MapInPandasNode": True})
    df = s.create_dataframe(pd.DataFrame(
        {"x": np.arange(10, dtype=np.float64)}))
    with pytest.raises(RuntimeError, match="worker died"):
        df.map_in_pandas(boom, schema).collect()
    out = df.map_in_pandas(fine, schema).collect()
    assert out["y"].tolist() == [float(i + 1) for i in range(10)]
