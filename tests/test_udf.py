"""UDF-compiler tests (the reference's OpcodeSuite pattern, SURVEY.md
§2.11: compile dozens of lambdas, assert both result equality AND that
compilation actually replaced the UDF — or deliberately didn't)."""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.cpu.engine import execute_cpu
from spark_rapids_tpu.execs.base import collect
from spark_rapids_tpu.expressions.base import Alias, BoundReference
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.overrides import apply_overrides
from spark_rapids_tpu.udf import (PythonUdf, compile_udf,
                                  compile_udfs_in_plan, sym_if)

from tests.compare import assert_cpu_and_tpu_equal, assert_frames_equal


def ref(i, t):
    return BoundReference(i, t)


def scan(n=200, seed=0):
    rng = np.random.default_rng(seed)
    return pn.ScanNode(pn.InMemorySource({
        "i": rng.integers(-100, 100, n).astype(np.int64),
        "f": rng.random(n) * 10 - 5,
        "s": np.array([f"Word{k % 9}" if k % 7 else None
                       for k in range(n)], dtype=object),
    }))


def _compiles(fn, args):
    return compile_udf(fn, args) is not None


# -- tracing unit tests (which lambdas compile) ---------------------------

def test_arithmetic_lambdas_compile():
    a = ref(0, dt.INT64)
    b = ref(1, dt.FLOAT64)
    assert _compiles(lambda x: x + 1, [a])
    assert _compiles(lambda x: 2 * x - 3, [a])
    assert _compiles(lambda x: (x + 1) * (x - 1) % 7, [a])
    assert _compiles(lambda x, y: x / (y + 100.5), [a, b])
    assert _compiles(lambda x: -abs(x) + +x, [a])
    assert _compiles(lambda x: x ** 2, [b])


def test_comparison_and_boolean_lambdas_compile():
    a = ref(0, dt.INT64)
    assert _compiles(lambda x: (x > 3) & (x < 10), [a])
    assert _compiles(lambda x: (x == 5) | ~(x >= 0), [a])
    assert _compiles(lambda x: x != 7, [a])


def test_string_lambdas_compile():
    s = ref(0, dt.STRING)
    assert _compiles(lambda x: x.upper(), [s])
    assert _compiles(lambda x: x.strip().lower(), [s])
    assert _compiles(lambda x: x.startswith("W"), [s])
    assert _compiles(lambda x: x.replace("o", "0"), [s])
    assert _compiles(lambda x: x + "!", [s])
    assert _compiles(lambda x: "pre-" + x, [s])
    assert _compiles(lambda x: x.length(), [s])


def test_conditional_via_sym_if_compiles():
    a = ref(0, dt.INT64)
    assert _compiles(lambda x: sym_if(x > 0, x, -x), [a])


def test_python_if_falls_back():
    a = ref(0, dt.INT64)
    assert not _compiles(lambda x: x if x > 0 else -x, [a])
    assert not _compiles(lambda x: 1 if True and x > 0 else 0, [a])


def test_unknown_calls_fall_back():
    import math

    a = ref(0, dt.FLOAT64)
    assert not _compiles(lambda x: math.sqrt(x), [a])  # C fn rejects proxy
    assert not _compiles(lambda x: str(x), [a])
    assert not _compiles(lambda x: {"a": x}, [a])


def test_sqrt_method_compiles():
    a = ref(0, dt.FLOAT64)
    assert _compiles(lambda x: x.sqrt(), [a])


# -- end-to-end: compiled UDFs stay on TPU, equal to row-wise oracle ------


def _plan_with_udf(fn, child_exprs, ret, n=200):
    base = scan(n)
    udf = PythonUdf(fn, child_exprs, ret)
    return pn.ProjectNode(
        [Alias(ref(0, dt.INT64), "i"), Alias(udf, "u")], base)


def test_compiled_udf_runs_on_tpu_and_matches():
    plan = _plan_with_udf(lambda x: x * 2 + 1, [ref(0, dt.INT64)],
                          dt.INT64)
    rewritten = compile_udfs_in_plan(plan)
    assert not any(isinstance(e, PythonUdf) or
                   any(isinstance(c, PythonUdf) for c in e.children)
                   for e in rewritten.exprs), "udf must be compiled away"
    # whole plan on TPU (test mode asserts no fallback)
    assert_cpu_and_tpu_equal(plan)


def test_compiled_string_udf_matches():
    # compare on the REWRITTEN plan: a compiled UDF is null-propagating
    # (Upper(NULL)=NULL) whereas the row-wise path hands None to the
    # function — the reference's compiler makes the same semantic trade
    # (bytecode becomes null-safe Catalyst expressions)
    plan = compile_udfs_in_plan(_plan_with_udf(
        lambda s: s.upper().replace("W", "V"),
        [ref(2, dt.STRING)], dt.STRING))
    assert_cpu_and_tpu_equal(plan)


def test_compiled_conditional_udf_matches():
    plan = _plan_with_udf(
        lambda x: sym_if(x % 2 == 0, x // 2, 3 * x + 1),
        [ref(0, dt.INT64)], dt.INT64)
    assert_cpu_and_tpu_equal(plan)


def test_return_type_cast_applied():
    # traced tree yields INT64; declared return FLOAT64 -> cast inserted
    plan = _plan_with_udf(lambda x: x + 1, [ref(0, dt.INT64)],
                          dt.FLOAT64)
    rewritten = compile_udfs_in_plan(plan)
    u = rewritten.exprs[1].children[0]
    assert u.dtype is dt.FLOAT64
    assert_cpu_and_tpu_equal(plan)


def test_untraceable_udf_falls_back_and_matches():
    """The silent-fallback contract: results still correct via row-wise
    CPU evaluation, and the plan reports the fallback."""
    def weird(x):
        return None if x % 10 == 0 else int(str(abs(x))[::-1])

    plan = _plan_with_udf(weird, [ref(0, dt.INT64)], dt.INT64)
    conf = RapidsConf()
    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, conf)
    assert type(exec_).__name__ == "CpuFallbackExec"
    assert any("PythonUdf" in r for r in exec_.reasons)
    tpu_df = collect(exec_)
    assert_frames_equal(cpu_df, tpu_df)


def test_udf_null_semantics_row_wise():
    """NULL input arrives as None; None result becomes NULL."""
    def f(s):
        return None if s is None else s.lower()

    # keep it uncompilable (is-None check) so the row path runs
    plan = _plan_with_udf(f, [ref(2, dt.STRING)], dt.STRING)
    cpu_df = execute_cpu(plan).to_pandas()
    nulls = cpu_df["u"].isna()
    assert nulls.any()
    exec_ = apply_overrides(plan, RapidsConf())
    assert_frames_equal(cpu_df, collect(exec_))


def test_udf_compiler_disabled_by_conf():
    plan = _plan_with_udf(lambda x: x + 1, [ref(0, dt.INT64)], dt.INT64)
    conf = RapidsConf({"rapids.tpu.sql.udfCompiler.enabled": False})
    exec_ = apply_overrides(plan, conf)
    assert type(exec_).__name__ == "CpuFallbackExec"


def test_udf_in_filter_condition():
    base = scan(300)
    udf = PythonUdf(lambda x: (x % 3 == 0) & (x > 0),
                    [ref(0, dt.INT64)], dt.BOOLEAN)
    plan = pn.FilterNode(udf, base)
    assert_cpu_and_tpu_equal(plan)
