"""UDF-compiler tests (the reference's OpcodeSuite pattern, SURVEY.md
§2.11: compile dozens of lambdas, assert both result equality AND that
compilation actually replaced the UDF — or deliberately didn't)."""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.cpu.engine import execute_cpu
from spark_rapids_tpu.execs.base import collect
from spark_rapids_tpu.expressions.base import Alias, BoundReference
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.overrides import apply_overrides
from spark_rapids_tpu.udf import (PythonUdf, compile_udf,
                                  compile_udfs_in_plan, sym_if)

from tests.compare import assert_cpu_and_tpu_equal, assert_frames_equal


def ref(i, t):
    return BoundReference(i, t)


def scan(n=200, seed=0):
    rng = np.random.default_rng(seed)
    return pn.ScanNode(pn.InMemorySource({
        "i": rng.integers(-100, 100, n).astype(np.int64),
        "f": rng.random(n) * 10 - 5,
        "s": np.array([f"Word{k % 9}" if k % 7 else None
                       for k in range(n)], dtype=object),
    }))


def _compiles(fn, args):
    return compile_udf(fn, args) is not None


# -- tracing unit tests (which lambdas compile) ---------------------------

def test_arithmetic_lambdas_compile():
    a = ref(0, dt.INT64)
    b = ref(1, dt.FLOAT64)
    assert _compiles(lambda x: x + 1, [a])
    assert _compiles(lambda x: 2 * x - 3, [a])
    assert _compiles(lambda x: (x + 1) * (x - 1) % 7, [a])
    assert _compiles(lambda x, y: x / (y + 100.5), [a, b])
    assert _compiles(lambda x: -abs(x) + +x, [a])
    assert _compiles(lambda x: x ** 2, [b])


def test_comparison_and_boolean_lambdas_compile():
    a = ref(0, dt.INT64)
    assert _compiles(lambda x: (x > 3) & (x < 10), [a])
    assert _compiles(lambda x: (x == 5) | ~(x >= 0), [a])
    assert _compiles(lambda x: x != 7, [a])


def test_string_lambdas_compile():
    s = ref(0, dt.STRING)
    assert _compiles(lambda x: x.upper(), [s])
    assert _compiles(lambda x: x.strip().lower(), [s])
    assert _compiles(lambda x: x.startswith("W"), [s])
    assert _compiles(lambda x: x.replace("o", "0"), [s])
    assert _compiles(lambda x: x + "!", [s])
    assert _compiles(lambda x: "pre-" + x, [s])
    assert _compiles(lambda x: x.length(), [s])


def test_conditional_via_sym_if_compiles():
    a = ref(0, dt.INT64)
    assert _compiles(lambda x: sym_if(x > 0, x, -x), [a])


def test_python_if_now_compiles_via_bytecode():
    # the bytecode executor folds real branches into If — previously a
    # fallback, now the reference-parity capability (OpcodeSuite role)
    a = ref(0, dt.INT64)
    assert _compiles(lambda x: x if x > 0 else -x, [a])
    assert _compiles(lambda x: 1 if True and x > 0 else 0, [a])


def test_unknown_calls_fall_back():
    import math

    a = ref(0, dt.FLOAT64)
    # math.sqrt rejects the proxy in the trace, but the bytecode path
    # recognizes it (the Instruction.scala method table analogue)
    assert _compiles(lambda x: math.sqrt(x), [a])
    assert not _compiles(lambda x: str(x), [a])
    assert not _compiles(lambda x: {"a": x}, [a])


def test_sqrt_method_compiles():
    a = ref(0, dt.FLOAT64)
    assert _compiles(lambda x: x.sqrt(), [a])


# -- end-to-end: compiled UDFs stay on TPU, equal to row-wise oracle ------


def _plan_with_udf(fn, child_exprs, ret, n=200):
    base = scan(n)
    udf = PythonUdf(fn, child_exprs, ret)
    return pn.ProjectNode(
        [Alias(ref(0, dt.INT64), "i"), Alias(udf, "u")], base)


def test_compiled_udf_runs_on_tpu_and_matches():
    plan = _plan_with_udf(lambda x: x * 2 + 1, [ref(0, dt.INT64)],
                          dt.INT64)
    rewritten = compile_udfs_in_plan(plan)
    assert not any(isinstance(e, PythonUdf) or
                   any(isinstance(c, PythonUdf) for c in e.children)
                   for e in rewritten.exprs), "udf must be compiled away"
    # whole plan on TPU (test mode asserts no fallback)
    assert_cpu_and_tpu_equal(plan)


def test_compiled_string_udf_matches():
    # compare on the REWRITTEN plan: a compiled UDF is null-propagating
    # (Upper(NULL)=NULL) whereas the row-wise path hands None to the
    # function — the reference's compiler makes the same semantic trade
    # (bytecode becomes null-safe Catalyst expressions)
    plan = compile_udfs_in_plan(_plan_with_udf(
        lambda s: s.upper().replace("W", "V"),
        [ref(2, dt.STRING)], dt.STRING))
    assert_cpu_and_tpu_equal(plan)


def test_compiled_conditional_udf_matches():
    plan = _plan_with_udf(
        lambda x: sym_if(x % 2 == 0, x // 2, 3 * x + 1),
        [ref(0, dt.INT64)], dt.INT64)
    assert_cpu_and_tpu_equal(plan)


def test_return_type_cast_applied():
    # traced tree yields INT64; declared return FLOAT64 -> cast inserted
    plan = _plan_with_udf(lambda x: x + 1, [ref(0, dt.INT64)],
                          dt.FLOAT64)
    rewritten = compile_udfs_in_plan(plan)
    u = rewritten.exprs[1].children[0]
    assert u.dtype is dt.FLOAT64
    assert_cpu_and_tpu_equal(plan)


def test_untraceable_udf_falls_back_and_matches():
    """The silent-fallback contract: results still correct via row-wise
    CPU evaluation, and the plan reports the fallback."""
    def weird(x):
        return None if x % 10 == 0 else int(str(abs(x))[::-1])

    plan = _plan_with_udf(weird, [ref(0, dt.INT64)], dt.INT64)
    conf = RapidsConf()
    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, conf)
    assert type(exec_).__name__ == "CpuFallbackExec"
    assert any("PythonUdf" in r for r in exec_.reasons)
    tpu_df = collect(exec_)
    assert_frames_equal(cpu_df, tpu_df)


def test_udf_null_semantics_row_wise():
    """NULL input arrives as None; None result becomes NULL."""
    def f(s):
        return None if s is None else s.lower()

    # keep it uncompilable (is-None check) so the row path runs
    plan = _plan_with_udf(f, [ref(2, dt.STRING)], dt.STRING)
    cpu_df = execute_cpu(plan).to_pandas()
    nulls = cpu_df["u"].isna()
    assert nulls.any()
    exec_ = apply_overrides(plan, RapidsConf())
    assert_frames_equal(cpu_df, collect(exec_))


def test_udf_compiler_disabled_by_conf():
    plan = _plan_with_udf(lambda x: x + 1, [ref(0, dt.INT64)], dt.INT64)
    conf = RapidsConf({"rapids.tpu.sql.udfCompiler.enabled": False})
    exec_ = apply_overrides(plan, conf)
    assert type(exec_).__name__ == "CpuFallbackExec"


def test_udf_in_filter_condition():
    base = scan(300)
    udf = PythonUdf(lambda x: (x % 3 == 0) & (x > 0),
                    [ref(0, dt.INT64)], dt.BOOLEAN)
    plan = pn.FilterNode(udf, base)
    assert_cpu_and_tpu_equal(plan)


# ---------------------------------------------------------------------------
# Bytecode symbolic executor (udf/bytecode.py — the OpcodeSuite role:
# compile branchy functions, assert they replaced the UDF AND match the
# row-wise oracle)


def _assert_compiles_and_matches(fn, in_types, ret_type, data,
                                 validity=None):
    """Compile via the bytecode path, then compare TPU pipeline vs the
    UNCOMPILED row-wise CPU evaluation of the same function."""
    import numpy as np

    from compare import assert_frames_equal
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.cpu.engine import execute_cpu
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.expressions.base import Alias, BoundReference
    from spark_rapids_tpu.plan import nodes as pn
    from spark_rapids_tpu.plan.overrides import apply_overrides
    from spark_rapids_tpu.udf.tracer import (PythonUdf, compile_udf,
                                             compile_udfs_in_plan)

    args = [BoundReference(i, t) for i, t in enumerate(in_types)]
    compiled = compile_udf(fn, args)
    assert compiled is not None, f"{fn.__name__} failed to compile"

    plan = pn.ProjectNode(
        [Alias(PythonUdf(fn, args, ret_type), "r")],
        pn.ScanNode(pn.InMemorySource(data, validity=validity)))
    rewritten = compile_udfs_in_plan(plan)
    assert not any(
        isinstance(e, PythonUdf)
        for e in rewritten.exprs[0].collect(lambda x: True)), \
        "udf must be replaced in the plan"
    # oracle: the ORIGINAL plan's row-wise PythonUdf evaluation
    cpu_df = execute_cpu(plan).to_pandas()
    tpu_df = collect(apply_overrides(rewritten, RapidsConf(
        {"rapids.tpu.sql.incompatibleOps.enabled": True})))
    assert_frames_equal(cpu_df, tpu_df, approx_float=1e-9)


def test_bytecode_if_else():
    import numpy as np

    def f(x, y):
        if x > 0.5:
            return x + y
        else:
            return x - y

    rng = np.random.default_rng(0)
    _assert_compiles_and_matches(
        f, [dt.FLOAT64, dt.FLOAT64], dt.FLOAT64,
        {"x": rng.random(200), "y": rng.random(200)})


def test_bytecode_elif_chain_and_locals():
    import numpy as np

    def f(x):
        z = x * 2.0
        if z > 1.5:
            r = z - 1.0
        elif z > 0.5:
            r = z
        else:
            r = -z
        return r

    rng = np.random.default_rng(1)
    _assert_compiles_and_matches(f, [dt.FLOAT64], dt.FLOAT64,
                                 {"x": rng.random(300)})


def test_bytecode_boolean_ops():
    import numpy as np

    def f(x, y):
        if x > 0.2 and y > 0.2 or x > 0.9:
            return 1.0
        return 0.0

    rng = np.random.default_rng(2)
    _assert_compiles_and_matches(
        f, [dt.FLOAT64, dt.FLOAT64], dt.FLOAT64,
        {"x": rng.random(200), "y": rng.random(200)})


def test_bytecode_is_none_and_in():
    import numpy as np

    def f(k):
        if k is None:
            return -1
        if k in (2, 5, 7):
            return 1
        return 0

    rng = np.random.default_rng(3)
    _assert_compiles_and_matches(
        f, [dt.INT64], dt.INT64,
        {"k": rng.integers(0, 10, 200)},
        {"k": rng.random(200) > 0.2})


def test_bytecode_string_methods():
    import numpy as np

    def f(s):
        if s is None:
            return None
        if s.startswith("a"):
            return s.upper()
        return s.strip().lower()

    vals = np.array(["abc", " XyZ ", "aQ", None, "zz"], dtype=object)
    _assert_compiles_and_matches(f, [dt.STRING], dt.STRING, {"s": vals})


def test_bytecode_math_calls():
    import math

    import numpy as np

    def f(x, y):
        return math.sqrt(abs(x)) + max(x, y)

    rng = np.random.default_rng(4)
    _assert_compiles_and_matches(
        f, [dt.FLOAT64, dt.FLOAT64], dt.FLOAT64,
        {"x": rng.random(100) - 0.5, "y": rng.random(100)})


def test_bytecode_concrete_loop_unrolls_via_trace():
    """Loops with CONCRETE bounds compile by unrolling in the direct
    trace (data-independent control flow is fine)."""
    from spark_rapids_tpu.columnar import dtypes as dtt
    from spark_rapids_tpu.expressions.base import BoundReference
    from spark_rapids_tpu.udf.tracer import compile_udf

    def f(x):
        t = 0.0
        for _ in range(3):
            t = t + x
        return t

    assert compile_udf(f, [BoundReference(0, dtt.FLOAT64)]) is not None


def test_bytecode_data_dependent_loop_falls_back():
    from spark_rapids_tpu.columnar import dtypes as dtt
    from spark_rapids_tpu.expressions.base import BoundReference
    from spark_rapids_tpu.udf.tracer import compile_udf

    def f(x):
        t = x
        while t > 1.0:
            t = t / 2.0
        return t

    assert compile_udf(f, [BoundReference(0, dtt.FLOAT64)]) is None


def test_bytecode_truthiness_falls_back():
    """Branching on a non-boolean traced value (Python truthiness) must
    NOT compile — SQL has no 0-is-false semantics."""
    from spark_rapids_tpu.columnar import dtypes as dtt
    from spark_rapids_tpu.expressions.base import BoundReference
    from spark_rapids_tpu.udf.tracer import compile_udf

    def f(k):
        if k:
            return 1
        return 0

    assert compile_udf(f, [BoundReference(0, dtt.INT64)]) is None


def test_bytecode_null_condition_is_falsy():
    """A NULL boolean condition must take the Python-falsy (else)
    branch in the compiled expression, matching row-wise evaluation."""
    import numpy as np

    def f(flag):
        if flag:
            return 1
        return 0

    _assert_compiles_and_matches(
        f, [dt.BOOLEAN], dt.INT64,
        {"flag": np.array([True, False, True])},
        {"flag": np.array([True, True, False])})
