"""Chaos fence: OOM resilience end to end (ROADMAP item 3).

Three guarantees, all on CPU CI:

1. A query whose estimated working set is >= 4x an artificially small
   device budget completes ORACLE-MATCHED through the retry ladder +
   three-tier spill chain (device -> host -> compressed disk, async
   writer), with nonzero spill counters.
2. The same query under deterministic OOM injection (no budget cap)
   also completes oracle-matched, with nonzero retry/split counters.
3. An over-budget query submitted to the query service is ADMITTED in
   flagged out-of-core mode — not parked in the queue — and the
   shed-vs-run policy knob sheds it instead when asked.

``scripts/chaos_check.py`` runs the same suite as a standalone CLI.
"""
import threading

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.api import Session, col, functions as F
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.memory import fault_injection as FI
from spark_rapids_tpu.memory import retry as R
from spark_rapids_tpu.memory.catalog import get_catalog
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.optimizer import estimate_footprint_bytes
from spark_rapids_tpu.service import (OutOfCoreRejected, QueryService,
                                      QueryState)

pytestmark = pytest.mark.chaos

N_FACT = 40_000
N_DIM = 64


@pytest.fixture(autouse=True)
def _clean_state():
    FI.get_injector().disarm()
    R.reset_config()
    yield
    FI.get_injector().disarm()
    R.reset_config()


def _frames(seed=11):
    rng = np.random.default_rng(seed)
    fact = pd.DataFrame({
        "k": rng.integers(0, N_DIM, N_FACT).astype(np.int64),
        "v": rng.random(N_FACT),
        "w": rng.integers(0, 1000, N_FACT).astype(np.int64)})
    dim = pd.DataFrame({
        "k": np.arange(N_DIM, dtype=np.int64),
        "cat": (np.arange(N_DIM, dtype=np.int64) % 7)})
    return fact, dim


def _q26_class(s, fact_df, dim_df):
    """join + filter + groupby-agg + order by — the q26-class shape
    that exercises join staging, aggregate update/merge and sort."""
    return (fact_df.join(dim_df, on="k")
            .filter(col("v") > 0.2)
            .group_by("cat")
            .agg(F.sum(col("v")).alias("sv"),
                 F.count("*").alias("n"),
                 F.max(col("w")).alias("mw"))
            .order_by("cat"))


def _oracle(fact, dim):
    j = fact.merge(dim, on="k")
    j = j[j["v"] > 0.2]
    out = (j.groupby("cat")
            .agg(sv=("v", "sum"), n=("v", "size"), mw=("w", "max"))
            .reset_index()
            .sort_values("cat")
            .reset_index(drop=True))
    return out


def _assert_matches(got, want):
    got = got.reset_index(drop=True)
    assert list(got.columns) == list(want.columns)
    assert len(got) == len(want)
    np.testing.assert_array_equal(got["cat"].to_numpy(),
                                  want["cat"].to_numpy())
    np.testing.assert_allclose(got["sv"].to_numpy(dtype=float),
                               want["sv"].to_numpy(dtype=float),
                               rtol=1e-9)
    np.testing.assert_array_equal(got["n"].to_numpy(dtype=np.int64),
                                  want["n"].to_numpy(dtype=np.int64))
    np.testing.assert_array_equal(got["mw"].to_numpy(dtype=np.int64),
                                  want["mw"].to_numpy(dtype=np.int64))


def test_four_x_over_budget_completes_oracle_matched(tmp_path):
    """THE fence: working set >= 4x the device budget, tiny host tier
    (so the chain reaches compressed disk), async spill writer on —
    and the result still matches the CPU oracle.

    The query is a row-level global sort above a join: SortExec stages
    its WHOLE input as spillable chunks and, past its row budget, takes
    the range-bucketed out-of-core path — the heavy rows genuinely
    live in the catalog and must survive device -> host -> disk."""
    rng = np.random.default_rng(11)
    n = 150_000  # above the sort exec's 65536-row budget floor
    fact = pd.DataFrame({
        "k": rng.integers(0, N_DIM, n).astype(np.int64),
        "v": rng.random(n),
        "w": rng.integers(0, 1000, n).astype(np.int64)})
    dim = pd.DataFrame({
        "k": np.arange(N_DIM, dtype=np.int64),
        "cat": (np.arange(N_DIM, dtype=np.int64) % 7)})

    def sort_q(s):
        return (s.create_dataframe(fact)
                .join(s.create_dataframe(dim), on="k")
                .filter(col("v") > 0.2)
                .order_by("w", "k", "cat", "v"))

    probe = Session()
    plan = sort_q(probe)._plan
    footprint = estimate_footprint_bytes(plan)
    # staged bytes are the joined+filtered rows; half of that bounds
    # the budget so the catalog MUST evict, and the 4x fence holds by
    # construction (footprint >= staged input)
    staged = int(n * 0.8) * (8 + 8 + 8 + 8 + 4)
    budget = min(footprint // 4, staged // 2)
    assert footprint >= 4 * budget > 0

    s = Session({
        cfg.DEVICE_BUDGET.key: budget,
        cfg.HOST_SPILL_STORAGE_SIZE.key: max(budget // 2, 1 << 16),
        cfg.SPILL_DIR.key: str(tmp_path),
        cfg.SPILL_ASYNC_WRITE.key: True,
    }, initialize_runtime=True)
    try:
        cat = s.runtime.catalog
        assert cat.device_budget == budget and cat.async_spill
        got = sort_q(s).collect()
        cat.flush_spills()
        j = fact.merge(dim, on="k")
        want = (j[j["v"] > 0.2]
                .sort_values(["w", "k", "cat", "v"], kind="stable")
                .reset_index(drop=True))
        got = got.reset_index(drop=True)[list(want.columns)]
        for c in want.columns:
            np.testing.assert_array_equal(
                got[c].to_numpy(), want[c].to_numpy(),
                err_msg=f"column {c}")
        # the run must actually have gone through the spill chain
        assert cat.spilled_device_bytes > 0
        assert cat.spilled_host_bytes > 0  # disk tier reached
    finally:
        s.stop()


def test_injected_oom_completes_oracle_matched():
    """Deterministic RESOURCE_EXHAUSTED at the aggregate + join sites,
    long enough bursts to force real splits — results still match."""
    fact, dim = _frames(seed=5)
    s = Session()
    FI.arm_from_conf(RapidsConf({
        cfg.FAULT_INJECTION_ENABLED.key: True,
        cfg.FAULT_INJECTION_AT_CALL.key: 1,
        cfg.FAULT_INJECTION_SITES.key: "aggregate.update,join.probe",
        cfg.FAULT_INJECTION_CONSECUTIVE.key: 3,
        cfg.FAULT_INJECTION_MAX.key: 6,
    }))
    pre = R.snapshot()
    got = _q26_class(s, s.create_dataframe(fact),
                     s.create_dataframe(dim)).collect()
    d = R.delta(pre)
    _assert_matches(got, _oracle(fact, dim))
    inj = FI.get_injector().stats()
    assert inj["injections"] > 0
    assert d["oom_retries"] >= 2   # both spill rungs climbed
    assert d["oom_splits"] >= 1    # and a genuine split happened
    assert d["gave_ups"] == 0


def test_injected_oom_probability_sweep_bounded():
    """Probabilistic injection across every guarded site, bounded by
    maxInjections: p=1.0 fails the first guarded call AND its first
    spill retry, then the cap clears the ladder — still
    oracle-matched. (The cap keeps the sweep below the give-up rung;
    seeded sub-1.0 sweeps are the chaos_check CLI's domain.)"""
    fact, dim = _frames(seed=8)
    s = Session()
    FI.get_injector().arm(probability=1.0, seed=42, consecutive=1,
                          max_injections=2)
    pre = R.snapshot()
    got = _q26_class(s, s.create_dataframe(fact),
                     s.create_dataframe(dim)).collect()
    _assert_matches(got, _oracle(fact, dim))
    assert FI.get_injector().stats()["injections"] == 2
    assert R.delta(pre)["oom_retries"] == 2


# -- out-of-core admission ---------------------------------------------------


class _GateSource(pn.DataSource):
    """Single-split source that blocks on an event — pins a query in
    RUNNING deterministically."""

    def __init__(self, rows=200):
        self.rows = rows
        self.gate = threading.Event()

    def schema(self):
        return Schema(["k", "v"], [dt.INT64, dt.FLOAT64])

    def num_splits(self):
        return 1

    def split_origin(self, p):
        return None

    def split_stats(self, p):
        return None

    def estimated_row_count(self):
        return self.rows

    def read_host_split(self, p):
        assert self.gate.wait(timeout=30), "gate never opened"
        rng = np.random.default_rng(p)
        return ({"k": rng.integers(0, 8, self.rows).astype(np.int64),
                 "v": rng.random(self.rows)},
                {"k": None, "v": None})


def test_over_budget_query_admitted_out_of_core():
    """Budget-exceeding query is admitted (flagged out-of-core) while
    ANOTHER query is still inflight — not parked until the device
    drains."""
    small_src = _GateSource(rows=200)
    small_plan = pn.ScanNode(small_src)
    small_fp = estimate_footprint_bytes(small_plan)
    budget = 4 * small_fp
    s = Session()
    rng = np.random.default_rng(2)
    whale_df = s.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 16, 50_000).astype(np.int64),
        "v": rng.random(50_000)}))
    whale_q = whale_df.group_by("k").agg(F.sum(col("v")).alias("sv"))
    whale_fp = estimate_footprint_bytes(whale_q._plan)
    assert whale_fp > budget > 2 * small_fp

    svc = QueryService(RapidsConf({
        cfg.SERVICE_ADMISSION_BUDGET.key: budget,
        cfg.SERVICE_MAX_CONCURRENT.key: 4}))
    try:
        h_small = svc.submit(small_plan, tenant="a")
        # whale: footprint > whole budget -> flagged out-of-core,
        # charged half the budget, admitted NEXT TO the gated query
        h_whale = svc.submit(whale_q, tenant="b")
        got = h_whale.result(timeout=120)
        assert h_small.poll() in (QueryState.RUNNING,
                                  QueryState.ADMITTED)  # still gated
        stats = svc.stats()
        assert stats.counters["admitted_out_of_core"] >= 1
        rec = [q for q in stats.per_query
               if q["query_id"] == h_whale.query_id][0]
        assert rec["out_of_core"] is True
        # oracle parity for the whale
        want = (whale_df.collect().groupby("k")
                .agg(sv=("v", "sum")).reset_index()
                .sort_values("k").reset_index(drop=True))
        got = got.sort_values("k").reset_index(drop=True)
        np.testing.assert_allclose(got["sv"], want["sv"], rtol=1e-9)
        small_src.gate.set()
        assert len(h_small.result(timeout=30)) == 200
    finally:
        small_src.gate.set()
        svc.shutdown()
        s.stop()


def test_out_of_core_policy_shed_rejects():
    s = Session()
    rng = np.random.default_rng(3)
    df = s.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 16, 50_000).astype(np.int64),
        "v": rng.random(50_000)}))
    q = df.group_by("k").agg(F.sum(col("v")).alias("sv"))
    fp = estimate_footprint_bytes(q._plan)
    svc = QueryService(RapidsConf({
        cfg.SERVICE_ADMISSION_BUDGET.key: fp // 8,
        cfg.SERVICE_OUT_OF_CORE_POLICY.key: "shed"}))
    try:
        with pytest.raises(OutOfCoreRejected) as ei:
            svc.submit(q, tenant="t")
        assert ei.value.footprint == fp
        assert svc.stats().counters["shed"] == 1
    finally:
        svc.shutdown()
        s.stop()


def test_out_of_core_disabled_keeps_legacy_wait():
    """outOfCore.enabled=false restores the old behavior: the whale is
    NOT flagged and simply waits for an empty device (it still runs
    solo eventually)."""
    s = Session()
    rng = np.random.default_rng(4)
    df = s.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 16, 50_000).astype(np.int64),
        "v": rng.random(50_000)}))
    q = df.group_by("k").agg(F.sum(col("v")).alias("sv"))
    fp = estimate_footprint_bytes(q._plan)
    svc = QueryService(RapidsConf({
        cfg.SERVICE_ADMISSION_BUDGET.key: fp // 8,
        cfg.SERVICE_OUT_OF_CORE.key: False}))
    try:
        h = svc.submit(q, tenant="t")
        h.result(timeout=120)  # empty device admits it solo
        rec = [x for x in svc.stats().per_query
               if x["query_id"] == h.query_id][0]
        assert rec["out_of_core"] is False
        assert svc.stats().counters["admitted_out_of_core"] == 0
    finally:
        svc.shutdown()
        s.stop()


def test_service_stats_carry_retry_counters():
    """Injected OOM during a service-run query lands in ServiceStats:
    per-query retry block + service-level counters."""
    fact, dim = _frames(seed=9)
    s = Session()
    try:
        FI.get_injector().arm(at_call=1, consecutive=1,
                              sites=["aggregate"], max_injections=2)
        h = _q26_class(s, s.create_dataframe(fact),
                       s.create_dataframe(dim)).collect_async(
            tenant="chaos")
        got = h.result(timeout=120)
        _assert_matches(got, _oracle(fact, dim))
        stats = s.service.stats()
        assert stats.counters["oom_retries"] >= 1
        rec = [q for q in stats.per_query
               if q["query_id"] == h.query_id][0]
        assert rec["retry"]["oom_retries"] >= 1
        assert stats.retry["totals"]["oom_retries"] >= 1
    finally:
        s.stop()
