"""Benchmark-suite smoke tests (TpchLikeSparkSuite analogue: every query
runs on the accelerated path and matches the CPU oracle at tiny SF)."""
import json

import pytest

from spark_rapids_tpu.benchmarks import datagen, tpcds, tpch
from spark_rapids_tpu.benchmarks.runner import BenchmarkRunner
from spark_rapids_tpu.config import RapidsConf

from tests.compare import assert_cpu_and_tpu_equal

SF = 0.001


def _tiered(queries, smoke_pick):
    """One representative query per TPC family stays in the smoke tier;
    the rest of the matrix is the nightly `full` tier (VERDICT r3 #8:
    the 140-query matrix outgrew the per-push window)."""
    return [q if q == smoke_pick else
            pytest.param(q, marks=pytest.mark.full)
            for q in sorted(queries)]


@pytest.fixture(autouse=True)
def _shed_jit_memory():
    """The 70+ benchmark queries compile thousands of x64 CPU
    executables; jax's in-process caches retain every one and the suite
    process eventually segfaults inside XLA compile (memory
    exhaustion). Clearing per test keeps the process bounded — reloads
    come from the persistent on-disk cache."""
    yield
    import jax

    jax.clear_caches()
    from spark_rapids_tpu.expressions import compiler as _c

    _c._FUSED_CACHE.clear()


@pytest.fixture(scope="module")
def data_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpch")
    datagen.write_tables(str(d), SF)
    return str(d)


@pytest.fixture(scope="module")
def tpcds_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("tpcds")
    tpcds.write_tables(str(d), SF)
    return str(d)


@pytest.mark.parametrize("query", _tiered(tpch.QUERIES, "q6"))
def test_query_on_tpu_matches_oracle(data_dir, query):
    plan = tpch.QUERIES[query](data_dir)
    conf = RapidsConf({"rapids.tpu.sql.test.enabled": True})
    assert_cpu_and_tpu_equal(plan, conf=conf, approx_float=1e-6)


# rank() over FLOAT aggregates: tie-breaks are implementation-defined
# (engines may round same-set sums to different last ulps — the rollup
# levels each re-aggregate the same rows). For these queries the rank
# column is checked SEMANTICALLY per engine (ordering + tie
# consistency vs its own sums) instead of bit-compared across engines;
# the reference documents the same float-agg nondeterminism
# (its variableFloatAgg opt-in exists for exactly this).
_RANK_OVER_FLOAT = {
    "tpcds_q67": {"rk": (["i_category"], "sumsales")},
}


@pytest.mark.parametrize("query", _tiered(tpcds.QUERIES, "q3"))
def test_tpcds_query_on_tpu_matches_oracle(tpcds_dir, query):
    plan = tpcds.QUERIES[query](tpcds_dir)
    # several TPC-DS queries cross-join 1-row aggregate subqueries
    # (q9/q28/q88/q90 buckets, scalar subqueries); the brute-force join
    # is default-off like the reference (GpuOverrides.scala:1837-1856) —
    # the suite opts in exactly as the reference's integration tests do
    conf = RapidsConf({
        "rapids.tpu.sql.test.enabled": True,
        "rapids.tpu.sql.exec.BroadcastNestedLoopJoinExec": True,
        "rapids.tpu.sql.exec.CartesianProductExec": True,
    })
    assert_cpu_and_tpu_equal(plan, conf=conf, approx_float=1e-6,
                             rank_over=_RANK_OVER_FLOAT.get(query))


@pytest.fixture(scope="module")
def tpcxbb_dir(tmp_path_factory):
    from spark_rapids_tpu.benchmarks import tpcxbb

    d = tmp_path_factory.mktemp("tpcxbb")
    tpcxbb.write_tables(str(d), SF)
    return str(d)


def _tpcxbb_queries():
    from spark_rapids_tpu.benchmarks import tpcxbb

    return sorted(tpcxbb.QUERIES)


@pytest.mark.parametrize("query", _tiered(_tpcxbb_queries(), "q7"))
def test_tpcxbb_query_on_tpu_matches_oracle(tpcxbb_dir, query):
    from spark_rapids_tpu.benchmarks import tpcxbb

    plan = tpcxbb.QUERIES[query](tpcxbb_dir)
    conf = RapidsConf({"rapids.tpu.sql.test.enabled": True})
    assert_cpu_and_tpu_equal(plan, conf=conf, approx_float=1e-6)


def test_q1_returns_flag_groups(data_dir):
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.plan.overrides import apply_overrides

    df = collect(apply_overrides(tpch.QUERIES["tpch_q1"](data_dir),
                                 RapidsConf()))
    # 3 return flags x 2 line statuses
    assert len(df) == 6
    assert df["count_order"].astype(int).sum() > 0


def test_runner_json_output(data_dir, capsys):
    from spark_rapids_tpu.benchmarks import runner as runner_mod

    runner_mod.main(["--benchmark", "tpch_q6", "--sf", str(SF),
                     "--iterations", "2", "--warmup", "1", "--compare",
                     "--data-dir", data_dir])
    out = capsys.readouterr().out
    result = json.loads(out)
    assert result["benchmark"] == "tpch_q6"
    assert len(result["iterations"]) == 2
    assert result["compare"]["matches_cpu"], result["compare"]["detail"]
    assert "query_plan" in result and "metrics" in result
    assert result["env"]["device_count"] >= 1


def test_mortgage_etl_matches_oracle(tmp_path):
    from spark_rapids_tpu.benchmarks import mortgage

    mortgage.gen_tables(str(tmp_path), sf=0.005)
    plan = mortgage.etl(str(tmp_path))
    conf = RapidsConf({"rapids.tpu.sql.test.enabled": True})
    assert_cpu_and_tpu_equal(plan, conf=conf, approx_float=1e-6,
                             sort=False)


def test_mortgage_through_runner(tmp_path, capsys):
    import json as _json

    from spark_rapids_tpu.benchmarks import runner as runner_mod

    runner_mod.main(["--benchmark", "mortgage_etl", "--sf", "0.003",
                     "--iterations", "1", "--warmup", "0", "--compare",
                     "--data-dir", str(tmp_path / "m")])
    result = _json.loads(capsys.readouterr().out)
    assert result["compare"]["matches_cpu"], result["compare"]["detail"]
    assert result["rows_returned"] >= 1


def test_wide_shuffle_bench_on_mesh():
    """BASELINE config #4 smoke: the wide-shuffle benchmark runs over the
    8-device mesh and the exchanged aggregate is exact."""
    from spark_rapids_tpu.benchmarks.shuffle_bench import run

    result = run(rows=20_000, n_keys=512, n_devices=8, iterations=1,
                 warmup=1)
    assert result["devices"] == 8
    assert result["groups"] == 512
    assert result["sum_ok"]
    assert result["rows_per_sec"] > 0
