"""SQL front-end tests: parse -> plan -> both engines agree, and the
planned SQL matches the equivalent hand-built DataFrame results."""
import numpy as np
import pandas as pd
import pytest

from compare import assert_cpu_and_tpu_equal, assert_frames_equal
from spark_rapids_tpu.api import Session
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.sql import SqlError, parse, plan_statement


def _catalog(seed=0, n=300):
    rng = np.random.default_rng(seed)
    t = pn.InMemorySource(
        {"k": rng.integers(0, 10, n).astype(np.int64),
         "v": np.round(rng.random(n) * 100, 3),
         "s": np.array([f"name{i % 7}" for i in range(n)],
                       dtype=object),
         "d": (np.datetime64("1995-01-01") +
               rng.integers(0, 1000, n)).astype("datetime64[D]")},
        validity={"v": rng.random(n) > 0.1})
    u = pn.InMemorySource(
        {"k2": rng.integers(0, 10, 40).astype(np.int64),
         "w": rng.integers(0, 50, 40).astype(np.int64)})
    return {"t": t, "u": u}


def run_sql(sql, seed=0, **kw):
    plan = plan_statement(parse(sql), _catalog(seed))
    assert_cpu_and_tpu_equal(plan, **kw)
    return plan


def test_select_where_order_limit():
    run_sql("SELECT k, v FROM t WHERE v > 50.0 AND k <> 3 "
            "ORDER BY v DESC, k LIMIT 17", sort=False)


def test_select_star_and_exprs():
    run_sql("SELECT *, v * 2.0 AS v2, -v AS nv FROM t")


def test_group_by_aggregates():
    run_sql("SELECT k, sum(v) AS sv, count(*) AS n, avg(v) AS av, "
            "min(v) AS mn, max(v) AS mx FROM t GROUP BY k ORDER BY k",
            sort=False, approx_float=1e-9)


def test_aggregate_of_expression_and_having():
    run_sql("SELECT k, sum(v) / count(v) AS manual_avg FROM t "
            "GROUP BY k HAVING count(*) > 20 ORDER BY k", sort=False)


def test_global_aggregate():
    run_sql("SELECT sum(v) AS s, count(*) AS n FROM t")


def test_join_with_residual_condition():
    run_sql("SELECT t.k, t.v, u.w FROM t JOIN u ON t.k = u.k2 "
            "AND u.w > 25")


def test_left_and_semi_joins():
    run_sql("SELECT t.k, u.w FROM t LEFT JOIN u ON t.k = u.k2")
    run_sql("SELECT k, v FROM t LEFT SEMI JOIN u ON t.k = u.k2")
    run_sql("SELECT k, v FROM t LEFT ANTI JOIN u ON t.k = u.k2")


def test_subquery_in_from():
    run_sql("SELECT kk, total FROM (SELECT k AS kk, sum(v) AS total "
            "FROM t GROUP BY k) agg WHERE total > 100.0 ORDER BY kk",
            sort=False)


def test_case_when_in_between_like():
    run_sql("SELECT k, CASE WHEN v > 66.0 THEN 'hi' WHEN v > 33.0 "
            "THEN 'mid' ELSE 'lo' END AS bucket FROM t")
    run_sql("SELECT k FROM t WHERE k IN (1, 3, 5) OR v BETWEEN 10.0 "
            "AND 20.0")
    run_sql("SELECT s FROM t WHERE s LIKE 'name1%'")


def test_date_literal_and_functions():
    run_sql("SELECT year(d) AS y, month(d) AS m, count(*) AS n FROM t "
            "WHERE d >= DATE '1995-06-01' GROUP BY year(d), month(d) "
            "ORDER BY y, m", sort=False)


def test_distinct_and_cast():
    run_sql("SELECT DISTINCT k FROM t ORDER BY k", sort=False)
    run_sql("SELECT CAST(k AS string) AS ks, CAST(v AS int) AS vi "
            "FROM t")


def test_count_distinct():
    run_sql("SELECT k, count(DISTINCT s) AS ds FROM t GROUP BY k "
            "ORDER BY k", sort=False)


def test_is_null_and_not():
    run_sql("SELECT k FROM t WHERE v IS NULL")
    run_sql("SELECT k FROM t WHERE v IS NOT NULL AND NOT k = 2")


def test_order_by_position_and_alias():
    run_sql("SELECT k, sum(v) AS sv FROM t GROUP BY k ORDER BY 2 DESC",
            sort=False)
    run_sql("SELECT k, sum(v) AS sv FROM t GROUP BY k ORDER BY sv",
            sort=False)


def test_sql_through_session_api():
    s = Session()
    pdf = pd.DataFrame({"a": [1, 2, 2, 3], "b": [10.0, 5.0, 7.0, 1.0]})
    s.create_temp_view("x", s.create_dataframe(pdf))
    out = s.sql("SELECT a, sum(b) AS sb FROM x GROUP BY a ORDER BY a") \
        .collect()
    assert list(out["a"]) == [1, 2, 3]
    assert list(out["sb"]) == [10.0, 12.0, 1.0]


def test_sql_errors_are_loud():
    cat = _catalog()
    with pytest.raises(SqlError, match="not found"):
        plan_statement(parse("SELECT z FROM t"), cat)
    with pytest.raises(SqlError, match="table"):
        plan_statement(parse("SELECT a FROM missing"), cat)
    with pytest.raises(SqlError):
        parse("SELECT FROM t")
    with pytest.raises(SqlError, match="equi"):
        plan_statement(parse("SELECT t.k FROM t JOIN u ON t.v > u.w"),
                       cat)


def test_tpch_q1_as_sql():
    """The reference's headline query shape, straight from SQL text."""
    rng = np.random.default_rng(9)
    n = 2000
    li = pn.InMemorySource({
        "l_returnflag": np.array(["A", "N", "R"], dtype=object)[
            rng.integers(0, 3, n)],
        "l_linestatus": np.array(["F", "O"], dtype=object)[
            rng.integers(0, 2, n)],
        "l_quantity": rng.integers(1, 51, n).astype(np.float64),
        "l_extendedprice": np.round(rng.random(n) * 1000, 2),
        "l_discount": np.round(rng.integers(0, 11, n) / 100, 2),
        "l_tax": np.round(rng.integers(0, 9, n) / 100, 2),
        "l_shipdate": (np.datetime64("1994-01-01") +
                       rng.integers(0, 1500, n)).astype("datetime64[D]"),
    })
    sql = """
        SELECT l_returnflag, l_linestatus,
               sum(l_quantity) AS sum_qty,
               sum(l_extendedprice) AS sum_base_price,
               sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
               sum(l_extendedprice * (1 - l_discount) * (1 + l_tax))
                   AS sum_charge,
               avg(l_quantity) AS avg_qty,
               avg(l_extendedprice) AS avg_price,
               avg(l_discount) AS avg_disc,
               count(*) AS count_order
        FROM lineitem
        WHERE l_shipdate <= DATE '1998-09-02'
        GROUP BY l_returnflag, l_linestatus
        ORDER BY l_returnflag, l_linestatus
    """
    plan = plan_statement(parse(sql), {"lineitem": li})
    assert_cpu_and_tpu_equal(plan, sort=False, approx_float=1e-6)


def test_cross_join_on_is_inner():
    """CROSS JOIN ... ON must behave as an inner join (Spark parse), not
    silently drop the condition."""
    cat = {"a": pn.InMemorySource({"k": np.array([1, 2], np.int64)}),
           "b": pn.InMemorySource({"k2": np.array([1, 3], np.int64)})}
    plan = plan_statement(
        parse("SELECT k, k2 FROM a CROSS JOIN b ON k = k2"), cat)
    from spark_rapids_tpu.cpu.engine import execute_cpu

    assert len(execute_cpu(plan).to_pandas()) == 1
    assert_cpu_and_tpu_equal(plan)


def test_limit_float_and_case_insensitive_table():
    cat = _catalog()
    with pytest.raises(SqlError, match="LIMIT"):
        parse("SELECT k FROM t LIMIT 2.5")
    plan = plan_statement(parse("SELECT K FROM T LIMIT 3"), cat)
    from spark_rapids_tpu.cpu.engine import execute_cpu

    assert len(execute_cpu(plan).to_pandas()) == 3


def _tpcds_catalog(tmp_path):
    from spark_rapids_tpu.benchmarks import tpcds
    from spark_rapids_tpu.io import ParquetSource

    d = str(tmp_path / "tpcds_sql")
    tpcds.write_tables(d, 0.001,
                       tables=["store_sales", "item", "date_dim"])
    import os

    return {t: ParquetSource(os.path.join(d, t))
            for t in ("store_sales", "item", "date_dim")}


def test_reference_tpcds_q3_verbatim(tmp_path):
    """The reference's ACTUAL q3 SQL text (TpcdsLikeSpark.scala:788),
    comma-FROM join syntax and all, parsed and executed on both
    engines."""
    sql = """
        SELECT dt.d_year, item.i_brand_id brand_id, item.i_brand brand,
               SUM(ss_ext_sales_price) sum_agg
        FROM  date_dim dt, store_sales, item
        WHERE dt.d_date_sk = store_sales.ss_sold_date_sk
          AND store_sales.ss_item_sk = item.i_item_sk
          AND item.i_manufact_id = 128
          AND dt.d_moy=11
        GROUP BY dt.d_year, item.i_brand, item.i_brand_id
        ORDER BY dt.d_year, sum_agg desc, brand_id
        LIMIT 100
    """
    plan = plan_statement(parse(sql), _tpcds_catalog(tmp_path))
    assert_cpu_and_tpu_equal(plan, sort=False, approx_float=1e-6)


def test_reference_tpcds_q55_verbatim(tmp_path):
    """TpcdsLikeSpark.scala:2946 q55, verbatim."""
    sql = """
        select i_brand_id brand_id, i_brand brand,
           sum(ss_ext_sales_price) ext_price
         from date_dim, store_sales, item
         where d_date_sk = ss_sold_date_sk
           and ss_item_sk = i_item_sk
           and i_manager_id=28
           and d_moy=11
           and d_year=1999
         group by i_brand, i_brand_id
         order by ext_price desc, brand_id
         limit 100
    """
    plan = plan_statement(parse(sql), _tpcds_catalog(tmp_path))
    assert_cpu_and_tpu_equal(plan, sort=False, approx_float=1e-6)


def test_reference_tpcds_q42_verbatim(tmp_path):
    """TpcdsLikeSpark.scala:2445 q42, verbatim — aggregate call repeated
    in ORDER BY."""
    sql = """
        select dt.d_year, item.i_category_id, item.i_category,
               sum(ss_ext_sales_price)
         from   date_dim dt, store_sales, item
         where dt.d_date_sk = store_sales.ss_sold_date_sk
           and store_sales.ss_item_sk = item.i_item_sk
           and item.i_manager_id = 1
           and dt.d_moy=11
           and dt.d_year=2000
         group by   dt.d_year
             ,item.i_category_id
             ,item.i_category
         order by       sum(ss_ext_sales_price) desc,dt.d_year
             ,item.i_category_id
             ,item.i_category
         limit 100
    """
    plan = plan_statement(parse(sql), _tpcds_catalog(tmp_path))
    assert_cpu_and_tpu_equal(plan, sort=False, approx_float=1e-6)
