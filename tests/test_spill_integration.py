"""Out-of-core integration: whole query pipelines under a tiny device
budget, so shuffle blocks / broadcast tables / cached batches spill
through host to compressed disk MID-QUERY and unspill on demand — the
§2.3 machinery exercised end-to-end rather than per-store."""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import Session, col, functions as F
from spark_rapids_tpu.memory.catalog import BufferCatalog, reset_catalog

from tests.compare import assert_frames_equal


@pytest.fixture()
def tiny_budget_catalog(tmp_path):
    # ~64 KiB device budget: any shuffle of a few thousand rows spills
    cat = reset_catalog(BufferCatalog(device_budget=64 << 10,
                                      host_budget=128 << 10,
                                      spill_dir=str(tmp_path)))
    yield cat
    reset_catalog(BufferCatalog())


def _data(n=6000, seed=3):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({
        "k": rng.integers(0, 40, n),
        "v": rng.random(n) * 100,
        "s": np.array([f"g{int(x) % 7}" for x in rng.integers(0, 99, n)],
                      dtype=object),
    })


def test_shuffle_spills_and_query_still_correct(tiny_budget_catalog,
                                                tmp_path):
    """A repartition moves RAW rows through the shuffle block cache
    (aggregation would shrink them first), so a 64 KiB budget forces
    mid-query spills; the aggregate over the spilled blocks must still
    be exact."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    pdf = _data()
    for i in range(4):
        pq.write_table(pa.Table.from_pandas(
            pdf.iloc[i * 1500:(i + 1) * 1500]),
            tmp_path / f"in{i}.parquet")
    s = Session()
    df = s.read.parquet(str(tmp_path))
    out = (df.repartition(6, "s")
             .filter(col("v") > 5)
             .group_by("s")
             .agg(F.sum(col("v")).alias("sv"),
                  F.count("*").alias("n"))
             .collect())
    cat = tiny_budget_catalog
    assert cat.spilled_device_bytes > 0, \
        "the shuffle cache must have spilled under a 64 KiB budget"
    exp = (pdf[pdf.v > 5].groupby("s")
           .agg(sv=("v", "sum"), n=("v", "size")).reset_index())
    got = out.sort_values("s").reset_index(drop=True)
    exp = exp.sort_values("s").reset_index(drop=True)
    np.testing.assert_allclose(got["sv"].astype(float), exp["sv"],
                               rtol=1e-9)
    assert list(got["n"].astype(int)) == list(exp["n"])


def test_broadcast_join_spills(tiny_budget_catalog):
    """A build side larger than the device budget spills on
    registration and unspills per probe."""
    s = Session()
    pdf = _data(4000)
    fact = s.create_dataframe(pdf)
    nd = 20_000
    dim = s.create_dataframe(pd.DataFrame(
        {"k2": np.arange(nd) % 40,
         "w": np.arange(nd, dtype=np.float64)}))
    out = fact.join(dim, on=[("k", "k2")], how="left_semi").collect()
    assert len(out) == len(pdf)  # every k has dim matches
    assert tiny_budget_catalog.spilled_device_bytes > 0


def test_cache_spill_disk_roundtrip(tiny_budget_catalog):
    s = Session()
    pdf = _data(5000)
    df = s.create_dataframe(pdf).cache()
    a = df.collect()
    cat = tiny_budget_catalog
    # force everything down to the disk tier, then re-read
    cat.synchronous_spill(0)
    cat.spill_host_to_disk(0)
    assert cat.spilled_host_bytes > 0
    b = df.collect()
    assert_frames_equal(a, b)
