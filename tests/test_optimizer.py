"""Plan-optimizer rule tests (CollapseProject / CombineFilters /
push-filter-through-projection)."""
import numpy as np

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import predicates as P
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Literal)
from spark_rapids_tpu.expressions.nondeterministic import Rand
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.optimizer import optimize

from tests.compare import assert_cpu_and_tpu_equal


def ref(i, t=dt.INT64):
    return BoundReference(i, t)


def scan(n=300, seed=0):
    rng = np.random.default_rng(seed)
    return pn.ScanNode(pn.InMemorySource(
        {"a": rng.integers(0, 100, n).astype(np.int64),
         "b": rng.random(n)}))


def test_collapse_adjacent_projects():
    p1 = pn.ProjectNode([Alias(ar.Add(ref(0), Literal(1)), "x"),
                         Alias(ref(1, dt.FLOAT64), "b")], scan())
    p2 = pn.ProjectNode([Alias(ar.Multiply(ref(0), Literal(2)), "y")],
                        p1)
    out = optimize(p2)
    assert isinstance(out, pn.ProjectNode)
    assert isinstance(out.children[0], pn.ScanNode)
    assert out.output_schema().names == ["y"]
    assert_cpu_and_tpu_equal(p2)


def test_collapse_guard_against_duplication():
    """An expensive inner expression referenced twice must NOT inline."""
    inner = pn.ProjectNode(
        [Alias(ar.Multiply(ref(0), ref(0)), "sq")], scan())
    outer = pn.ProjectNode(
        [Alias(ar.Add(ref(0), ref(0)), "dbl")], inner)
    out = optimize(outer)
    # still two projects: sq used twice and is non-trivial
    assert isinstance(out.children[0], pn.ProjectNode)
    assert_cpu_and_tpu_equal(outer)


def test_combine_filters():
    f1 = pn.FilterNode(P.GreaterThan(ref(0), Literal(10)), scan())
    f2 = pn.FilterNode(P.LessThan(ref(0), Literal(90)), f1)
    out = optimize(f2)
    assert isinstance(out, pn.FilterNode)
    assert isinstance(out.children[0], pn.ScanNode)
    assert isinstance(out.condition, P.And)
    assert_cpu_and_tpu_equal(f2)


def test_filter_pushes_through_projection():
    proj = pn.ProjectNode([Alias(ar.Add(ref(0), Literal(5)), "a5"),
                           Alias(ref(1, dt.FLOAT64), "b")], scan())
    filt = pn.FilterNode(P.GreaterThan(ref(0), Literal(50)), proj)
    out = optimize(filt)
    assert isinstance(out, pn.ProjectNode)
    assert isinstance(out.children[0], pn.FilterNode)
    assert isinstance(out.children[0].children[0], pn.ScanNode)
    assert_cpu_and_tpu_equal(filt)


def test_nondeterministic_blocks_pushdown():
    proj = pn.ProjectNode([Alias(Rand(seed=1), "r"),
                           Alias(ref(0), "a")], scan())
    filt = pn.FilterNode(
        P.GreaterThan(ref(0, dt.FLOAT64), Literal(0.5)), proj)
    out = optimize(filt)
    # rand() must evaluate once per input row BEFORE filtering; the
    # rewrite would re-randomize — plan stays Filter(Project)
    assert isinstance(out, pn.FilterNode)


def test_long_chain_collapses_fully():
    node = scan()
    for k in range(4):
        node = pn.ProjectNode(
            [Alias(ar.Add(ref(0), Literal(1)), "a"),
             Alias(ref(1, dt.FLOAT64), "b")], node)
    node = pn.FilterNode(P.GreaterThan(ref(0), Literal(52)), node)
    out = optimize(node)
    # one project over one filter over the scan
    assert isinstance(out, pn.ProjectNode)
    assert isinstance(out.children[0], pn.FilterNode)
    assert isinstance(out.children[0].children[0], pn.ScanNode)
    assert_cpu_and_tpu_equal(node)


def test_distinct_aggregate_rewrite():
    """count/sum(DISTINCT x) rewrites to dedup-then-aggregate and runs
    fully on TPU; results match the oracle."""
    import numpy as np

    from compare import assert_cpu_and_tpu_equal
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.config import RapidsConf
    from spark_rapids_tpu.expressions import BoundReference, Count, Sum
    from spark_rapids_tpu.plan import nodes as pn

    rng = np.random.default_rng(44)
    n = 400
    plan = pn.AggregateNode(
        [BoundReference(0, dt.INT64)],
        [pn.AggCall(Count(BoundReference(1, dt.INT64), distinct=True),
                    "dc"),
         pn.AggCall(Sum(BoundReference(1, dt.INT64), distinct=True),
                    "ds")],
        pn.ScanNode(pn.InMemorySource(
            {"k": rng.integers(0, 8, n).astype(np.int64),
             "v": rng.integers(0, 20, n).astype(np.int64)},
            validity={"v": rng.random(n) > 0.15})),
        grouping_names=["k"])
    conf = RapidsConf({"rapids.tpu.sql.test.enabled": True})
    assert_cpu_and_tpu_equal(plan, conf=conf)


def _star_tables(seed=3):
    """A star schema written in a BAD join order: fact joined to the
    BIGGEST dim first, smallest last."""
    rng = np.random.default_rng(seed)
    fact = pn.ScanNode(pn.InMemorySource({
        "f_d1": rng.integers(0, 50, 5000).astype(np.int64),
        "f_d2": rng.integers(0, 800, 5000).astype(np.int64),
        "f_d3": rng.integers(0, 8, 5000).astype(np.int64),
        "f_v": rng.random(5000)}))
    d_big = pn.ScanNode(pn.InMemorySource({
        "b_k": np.arange(800, dtype=np.int64),
        "b_w": rng.integers(0, 9, 800).astype(np.int64)}))
    d_mid = pn.ScanNode(pn.InMemorySource({
        "m_k": np.arange(50, dtype=np.int64),
        "m_w": rng.integers(0, 9, 50).astype(np.int64)}))
    d_small = pn.ScanNode(pn.InMemorySource({
        "s_k": np.arange(8, dtype=np.int64),
        "s_w": rng.integers(0, 9, 8).astype(np.int64)}))
    return fact, d_big, d_mid, d_small


def _chain_sizes(node):
    """Build-side estimated sizes down the left-deep inner-join chain."""
    from spark_rapids_tpu.plan.optimizer import estimate_rows

    sizes = []
    while isinstance(node, pn.JoinNode) and node.kind == "inner":
        sizes.append(estimate_rows(node.children[1]))
        node = node.children[0]
    return list(reversed(sizes))


def test_greedy_join_reorder_star_schema():
    """Scan-stats reordering (r3 verdict #6): a fact-first greedy order
    joins the smallest dimension earliest regardless of the written
    order, and results stay oracle-exact."""
    fact, d_big, d_mid, d_small = _star_tables()
    # written order: fact x big x mid x small (worst-first)
    j1 = pn.JoinNode("inner", fact, d_big, [1], [0])
    j2 = pn.JoinNode("inner", j1, d_mid, [0], [0])
    j3 = pn.JoinNode("inner", j2, d_small, [2], [0])
    out = optimize(j3)
    # the restore-projection keeps the original column order
    assert out.output_schema().names == j3.output_schema().names
    node = out
    while not isinstance(node, pn.JoinNode):
        node = node.children[0]
    sizes = _chain_sizes(node)
    assert sizes == sorted(sizes), sizes
    assert sizes[0] == 8 and sizes[-1] == 800
    assert_cpu_and_tpu_equal(j3, sort=True)


def test_join_reorder_keeps_transitive_edges():
    """Every key equality applies when its later-placed endpoint
    arrives: reordering may change WHICH join enforces an edge but can
    never drop one."""
    rng = np.random.default_rng(9)
    a = pn.ScanNode(pn.InMemorySource({
        "a_k": rng.integers(0, 30, 2000).astype(np.int64),
        "a_v": rng.random(2000)}))
    b = pn.ScanNode(pn.InMemorySource({
        "b_k": rng.integers(0, 30, 400).astype(np.int64)}))
    c = pn.ScanNode(pn.InMemorySource({
        "c_k": rng.integers(0, 30, 25).astype(np.int64)}))
    # a.k = b.k and b.k = c.k (c only reachable through b)
    j = pn.JoinNode("inner", pn.JoinNode("inner", a, b, [0], [0]),
                    c, [2], [0])
    out = optimize(j)
    assert out.output_schema().names == j.output_schema().names
    assert_cpu_and_tpu_equal(j, sort=True)


def test_join_reorder_leaves_outer_and_conditioned_joins():
    """Only condition-free inner chains reorder; outer joins and
    residual conditions pin the written order."""
    fact, d_big, d_mid, _ = _star_tables()
    j1 = pn.JoinNode("left", fact, d_big, [1], [0])
    j2 = pn.JoinNode("inner", j1, d_mid, [0], [0])
    out = optimize(j2)
    assert isinstance(out, pn.JoinNode)
    assert out.children[1] is d_mid  # untouched
    assert_cpu_and_tpu_equal(j2, sort=True)


def test_filter_pushes_below_join():
    """WHERE conjuncts referencing one join side push below the join
    (PushPredicateThroughJoin subset): the explicit-JOIN / DataFrame
    .join().filter() form gets the same plans as the implicit form."""
    from spark_rapids_tpu.expressions.predicates import And, GreaterThan, LessThan

    fact, d_big, _m, _s = _star_tables()
    j = pn.JoinNode("inner", fact, d_big, [1], [0])
    cond = And(LessThan(ref(3, dt.FLOAT64), Literal(0.5)),   # fact.f_v
               GreaterThan(ref(5), Literal(2)))              # big.b_w
    plan = pn.FilterNode(cond, j)
    out = optimize(plan)
    node = out
    while isinstance(node, pn.ProjectNode):
        node = node.children[0]
    assert isinstance(node, pn.JoinNode), type(node)
    assert isinstance(node.children[0], pn.FilterNode)
    assert isinstance(node.children[1], pn.FilterNode)
    assert_cpu_and_tpu_equal(plan, sort=True)


def test_filter_does_not_push_into_left_join_right_side():
    """A right-side conjunct above a LEFT join must stay above it:
    pre-filtering the right side turns dropped rows into null-extended
    ones."""
    from spark_rapids_tpu.expressions.predicates import GreaterThan

    fact, d_big, _m, _s = _star_tables()
    j = pn.JoinNode("left", fact, d_big, [1], [0])
    plan = pn.FilterNode(GreaterThan(ref(5), Literal(2)), j)
    out = optimize(plan)
    assert isinstance(out, pn.FilterNode)
    assert isinstance(out.children[0], pn.JoinNode)
    assert_cpu_and_tpu_equal(plan, sort=True)


def test_small_build_side_broadcasts_instead_of_shuffling():
    """Spark's autoBroadcastJoinThreshold from scan statistics: a
    multi-partition join whose build side is estimated under the
    threshold plans as broadcast (no exchange pair); 0 disables."""
    from spark_rapids_tpu.execs.adaptive import AdaptiveShuffledJoinExec
    from spark_rapids_tpu.execs.joins import (BroadcastHashJoinExec,
                                              ShuffledHashJoinExec)
    from spark_rapids_tpu.plan.overrides import apply_overrides

    rng = np.random.default_rng(2)
    big = {"k": rng.integers(0, 50, 3000).astype(np.int64),
           "v": rng.random(3000)}
    small = {"k2": np.arange(50, dtype=np.int64),
             "w": rng.random(50)}
    plan = pn.JoinNode(
        "inner",
        pn.ShuffleExchangeNode(("round_robin",), 3,
                               pn.ScanNode(pn.InMemorySource(big))),
        pn.ScanNode(pn.InMemorySource(small)), [0], [0])

    def top_join(e):
        from spark_rapids_tpu.execs.fused import FusedChainExec

        while not isinstance(e, (BroadcastHashJoinExec,
                                 ShuffledHashJoinExec,
                                 AdaptiveShuffledJoinExec)):
            if isinstance(e, FusedChainExec):
                # the broadcast join was absorbed into a fused chain;
                # its unfused form is preserved as the fallback subtree
                e = e.fallback
                continue
            e = e.children[0]
        return e

    exec_ = apply_overrides(plan, RapidsConf())
    assert isinstance(top_join(exec_), BroadcastHashJoinExec)
    exec_ = apply_overrides(plan, RapidsConf(
        {"rapids.tpu.sql.autoBroadcastJoinThreshold": 0}))
    # AQE (default on) defers the shuffled join's final strategy to
    # execute time; with it off the static planner must still emit the
    # plain shuffled join
    assert isinstance(top_join(exec_), AdaptiveShuffledJoinExec)
    exec_ = apply_overrides(plan, RapidsConf(
        {"rapids.tpu.sql.autoBroadcastJoinThreshold": 0,
         "rapids.tpu.sql.adaptive.enabled": False}))
    assert isinstance(top_join(exec_), ShuffledHashJoinExec)
    assert_cpu_and_tpu_equal(plan, sort=True)


def test_optimizer_preserves_semantics_fuzz():
    """Property check over random join trees + filters: optimize(plan)
    and plan produce IDENTICAL results on the CPU engine (pure numpy -
    no device in the loop), guarding the pushdown/reorder rules'
    ordinal bookkeeping across shapes no hand-written case covers."""
    import pandas as pd

    from spark_rapids_tpu.cpu.engine import execute_cpu
    from spark_rapids_tpu.expressions.predicates import (And, GreaterThan,
                                                         LessThan)

    kinds = ["inner", "inner", "left", "left_semi", "left_anti"]
    for seed in range(12):
        rng = np.random.default_rng(100 + seed)
        n_rels = int(rng.integers(2, 5))
        rels = []
        for ri in range(n_rels):
            n = int(rng.integers(20, 400))
            rels.append(pn.ScanNode(pn.InMemorySource({
                f"k{ri}": rng.integers(0, 25, n).astype(np.int64),
                f"v{ri}": np.round(rng.random(n) * 100, 3)})))
        node = rels[0]
        width = 2
        for ri in range(1, n_rels):
            kind = kinds[int(rng.integers(0, len(kinds)))]
            lkey = int(rng.integers(0, width))
            lkey -= lkey % 2  # key columns sit at even ordinals
            node = pn.JoinNode(kind, node, rels[ri], [lkey], [0])
            width = len(node.output_schema())
        out_w = len(node.output_schema())
        conj = []
        for _ in range(int(rng.integers(1, 4))):
            o = int(rng.integers(0, out_w))
            t = node.output_schema().types[o]
            if t is dt.INT64:
                conj.append(GreaterThan(ref(o), Literal(
                    int(rng.integers(0, 20)))))
            else:
                conj.append(LessThan(ref(o, dt.FLOAT64), Literal(
                    float(rng.random() * 90))))
        cond = conj[0]
        for c in conj[1:]:
            cond = And(cond, c)
        plan = pn.FilterNode(cond, node)
        want = execute_cpu(plan).to_pandas()
        got = execute_cpu(optimize(plan)).to_pandas()
        key = list(want.columns)
        want = want.sort_values(key).reset_index(drop=True)
        got = got.sort_values(key).reset_index(drop=True)
        pd.testing.assert_frame_equal(want, got, check_dtype=False,
                                      atol=1e-9)


# ---------------------------------------------------------------------------
# Round-5 plan-quality guard: optimizer decisions must never produce a
# plan costlier (static dispatch estimate) than the written order.
# ---------------------------------------------------------------------------

from spark_rapids_tpu.io import ParquetSource  # noqa: E402


def _star_join_plan(tmp_path, n_dims=6, fact_rows=20_000):
    """q72/q64-class shape: a fact table written LAST in the join order
    joined against several small dims — the written order is maximally
    bad (dims joined together first), so reordering must win or tie."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    rng = np.random.default_rng(64)
    fact = {"f0": rng.integers(0, 50, fact_rows).astype(np.int64)}
    for d in range(n_dims):
        fact[f"k{d}"] = rng.integers(0, 40, fact_rows).astype(np.int64)
    pq.write_table(pa.table(fact), tmp_path / "fact.parquet")
    scans = []
    for d in range(n_dims):
        pq.write_table(pa.table({
            "id": np.arange(40, dtype=np.int64),
            f"w{d}": rng.random(40)}), tmp_path / f"dim{d}.parquet")
        scans.append(pn.ScanNode(ParquetSource(
            str(tmp_path / f"dim{d}.parquet"))))
    fact_scan = pn.ScanNode(ParquetSource(str(tmp_path / "fact.parquet")))

    # written order: the fact joins every dim one by one — each key a
    # different column, so the reorderer has real freedom
    plan = fact_scan
    for d in range(n_dims):
        plan = pn.JoinNode("inner", plan, scans[d], [1 + d], [0])
    return plan


def test_join_reorder_never_costlier_than_written_order(tmp_path):
    from spark_rapids_tpu.plan.optimizer import plan_cost
    from spark_rapids_tpu.plan.overrides import apply_overrides

    plan = _star_join_plan(tmp_path)
    base = apply_overrides(plan, RapidsConf(
        {"rapids.tpu.sql.optimizer.enabled": False}))
    opt = apply_overrides(plan, RapidsConf())
    assert plan_cost(opt) <= plan_cost(base), (
        plan_cost(opt), plan_cost(base), opt.tree_string())
    # semantics unchanged by the reorder
    assert_cpu_and_tpu_equal(plan, sort=True)


def test_broadcast_decision_never_costlier(tmp_path):
    """The stats-driven broadcast threshold must strictly reduce the
    static plan cost vs forcing the shuffled path on the same query."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.plan.optimizer import plan_cost
    from spark_rapids_tpu.plan.overrides import apply_overrides

    rng = np.random.default_rng(72)
    pq.write_table(pa.table({
        "k": rng.integers(0, 50, 30_000).astype(np.int64),
        "v": rng.random(30_000)}), tmp_path / "fact.parquet")
    pq.write_table(pa.table({
        "id": np.arange(50, dtype=np.int64),
        "w": rng.random(50)}), tmp_path / "dim.parquet")
    plan = pn.JoinNode(
        "inner",
        pn.ShuffleExchangeNode(("round_robin",), 3, pn.ScanNode(
            ParquetSource(str(tmp_path / "fact.parquet")))),
        pn.ScanNode(ParquetSource(str(tmp_path / "dim.parquet"))),
        [0], [0])
    bcast = apply_overrides(plan, RapidsConf())
    shuf = apply_overrides(plan, RapidsConf(
        {"rapids.tpu.sql.autoBroadcastJoinThreshold": 0}))
    assert plan_cost(bcast) < plan_cost(shuf), (
        plan_cost(bcast), plan_cost(shuf))


def test_ndv_estimate_from_footer_stats(tmp_path):
    """Footer (lo, hi) bounds on an integral key feed the join-size
    estimate: |A join B| = |A||B|/max(ndv) instead of max(|A|,|B|)."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.plan.optimizer import (estimate_key_ndv,
                                                 estimate_rows)

    rng = np.random.default_rng(9)
    pq.write_table(pa.table({
        "k": rng.integers(0, 100, 10_000).astype(np.int64)}),
        tmp_path / "a.parquet")
    pq.write_table(pa.table({
        "id": np.arange(100, dtype=np.int64),
        "w": rng.random(100)}), tmp_path / "b.parquet")
    a = pn.ScanNode(ParquetSource(str(tmp_path / "a.parquet")))
    b = pn.ScanNode(ParquetSource(str(tmp_path / "b.parquet")))
    ndv = estimate_key_ndv(b, 0)
    assert ndv is not None and 50 <= ndv <= 100, ndv
    j = pn.JoinNode("inner", a, b, [0], [0])
    est = estimate_rows(j)
    # fact-sided: ~|A| * |B| / ndv(B.id) == ~|A|
    assert est is not None and 5_000 <= est <= 20_000, est
