"""Dense-vs-sort groupby float semantics (r5 ask #8).

The groupby has two kernels: the sort-free dense sweep for host-known
key spaces of <= 128 slots (ops/groupby._DENSE_MAX_GROUPS) and the
variadic-sort path for everything else. Their float-reduction trees
differ, so ops/groupby.py:123-144 gates grouping-set (ROLLUP/CUBE)
aggregates off the dense path ONLY when an order-sensitive float
reduction is present — order-insensitive aggregates (min/max/count,
integer sums) must be bit-exact on BOTH paths, with ties, NaN, -0.0
and nulls in play, straddling the 128-slot boundary. This is the
property suite that pins that contract.
"""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.ops import groupby as gb
from spark_rapids_tpu.ops.groupby import AggSpec

# order-insensitive aggs: result independent of the reduction tree
ORDER_INSENSITIVE = [AggSpec("min", 1), AggSpec("max", 1),
                     AggSpec("count", 1), AggSpec("count_star")]


def _make_batch(rng, n, span, vdtype, with_stats):
    """Keys 0..span-1 with ties; float values seeded with NaN, -0.0,
    +0.0, exact ties and nulls."""
    keys = rng.integers(0, span, n).astype(np.int64)
    keys[: span] = np.arange(span)          # every slot occupied
    vals = rng.standard_normal(n).astype(vdtype.np_dtype)
    vals[rng.random(n) < 0.1] = np.nan
    vals[rng.random(n) < 0.1] = vdtype.np_dtype.type(-0.0)
    vals[rng.random(n) < 0.1] = vdtype.np_dtype.type(0.0)
    vals[rng.random(n) < 0.15] = vdtype.np_dtype.type(1.5)  # ties
    validity = rng.random(n) > 0.1
    kcol = Column.from_numpy(keys)
    if with_stats:
        kcol.stats = (0, span - 1)
    vcol = Column.from_numpy(vals, validity=validity)
    return ColumnarBatch([kcol, vcol], n)


def _rows(out, num_aggs):
    """Realized (key -> agg tuple) dict with float bits for exactness."""
    import jax

    n = out.realized_num_rows()
    cols = []
    for c in out.columns:
        data = np.asarray(jax.device_get(c.data))[:n]
        if data.dtype.kind == "f":
            data = data.view(f"u{data.dtype.itemsize}")
        valid = np.ones(n, bool) if c.validity is None else \
            np.asarray(jax.device_get(c.validity))[:n]
        cols.append((data, valid))
    rows = {}
    for i in range(n):
        key = (cols[0][0][i].item(), bool(cols[0][1][i]))
        rows[key] = tuple(
            (cols[j][0][i].item(), bool(cols[j][1][i]))
            for j in range(1, 1 + num_aggs))
    return rows


@pytest.mark.parametrize("vdtype", [dt.FLOAT32, dt.FLOAT64])
@pytest.mark.parametrize("span", [96, 127, 128])
def test_dense_and_sort_paths_bit_exact(vdtype, span):
    """Within the dense-eligible regime (quantized span <= 128 slots),
    order-insensitive aggregates must agree BIT-exactly between the
    dense sweep (stats present) and the sort kernel (stats absent) —
    including NaN payload bits and the sign of zero."""
    rng = np.random.default_rng(span * 7 + vdtype.byte_width)
    n = 4000
    dtypes = [dt.INT64, vdtype]
    dense_b = _make_batch(rng, n, span, vdtype, with_stats=True)
    sort_b = ColumnarBatch(list(dense_b.columns), n)
    sort_b.columns[0] = Column(dt.INT64, dense_b.columns[0].data,
                               dense_b.columns[0].validity)  # no stats
    out_d, _ = gb.groupby_aggregate(dense_b, [0], ORDER_INSENSITIVE,
                                    dtypes)
    out_s, _ = gb.groupby_aggregate(sort_b, [0], ORDER_INSENSITIVE,
                                    dtypes)
    rows_d = _rows(out_d, len(ORDER_INSENSITIVE))
    rows_s = _rows(out_s, len(ORDER_INSENSITIVE))
    assert rows_d == rows_s


def test_boundary_span_129_uses_sort_even_with_stats():
    """One slot past the boundary (span 129 quantizes to 256 > 128):
    stats or not, the sort kernel runs, and results still match the
    stats-free run exactly."""
    rng = np.random.default_rng(11)
    n = 2000
    dtypes = [dt.INT64, dt.FLOAT64]
    b_stats = _make_batch(rng, n, 129, dt.FLOAT64, with_stats=True)
    b_plain = ColumnarBatch(list(b_stats.columns), n)
    b_plain.columns[0] = Column(dt.INT64, b_stats.columns[0].data,
                                b_stats.columns[0].validity)
    seen = _spy_paths(lambda: gb.groupby_aggregate(
        b_stats, [0], ORDER_INSENSITIVE, dtypes))
    assert seen == ["sort"]
    out_a, _ = gb.groupby_aggregate(b_stats, [0], ORDER_INSENSITIVE,
                                    dtypes)
    out_b, _ = gb.groupby_aggregate(b_plain, [0], ORDER_INSENSITIVE,
                                    dtypes)
    assert _rows(out_a, 4) == _rows(out_b, 4)


def _spy_paths(fn):
    """Run ``fn`` recording which kernel each _groupby call selects
    (from its FINAL static args — the jit cache never hides this
    because the capture happens before dispatch)."""
    seen = []
    real = gb._groupby

    def spy(cols, dtypes, key_ordinals, aggs, num_rows, live_mask=None,
            key_ranges=None, dense_ok=True):
        key_has_v = tuple(cols[o][1] is not None for o in key_ordinals)
        dense = dense_ok and gb._dense_layout(
            list(dtypes), list(key_ordinals), key_ranges,
            key_has_v) is not None
        seen.append("dense" if dense else "sort")
        return real(cols, dtypes, key_ordinals, aggs, num_rows,
                    live_mask=live_mask, key_ranges=key_ranges,
                    dense_ok=dense_ok)

    gb._groupby = spy
    try:
        fn()
    finally:
        gb._groupby = real
    return seen


@pytest.mark.parametrize("vdtype,expect", [
    (dt.FLOAT32, "sort"), (dt.FLOAT64, "sort")])
def test_grouping_set_gating_float_sum_forces_sort(vdtype, expect):
    """dense_ok=False (the grouping-set caller) + a FLOAT sum must take
    the sort path even when the key span is dense-eligible: the dense
    sweep's reduction tree is position-dependent and would split
    rank()-over-sum ties across ROLLUP levels (ops/groupby.py:123-144)."""
    rng = np.random.default_rng(3)
    b = _make_batch(rng, 1000, 64, vdtype, with_stats=True)
    seen = _spy_paths(lambda: gb.groupby_aggregate(
        b, [0], [AggSpec("sum", 1)], [dt.INT64, vdtype],
        dense_ok=False))
    assert seen == [expect]


def test_grouping_set_gating_order_insensitive_keeps_dense():
    """dense_ok=False with ONLY order-insensitive aggregates flips back
    to the dense path (the gate suppresses order-SENSITIVE float
    reductions, not the kernel): integer sums, counts and min/max are
    exact on any reduction tree."""
    rng = np.random.default_rng(4)
    n = 1000
    keys = rng.integers(0, 64, n).astype(np.int64)
    ivals = rng.integers(-100, 100, n).astype(np.int64)
    kcol = Column.from_numpy(keys)
    kcol.stats = (0, 63)
    b = ColumnarBatch([kcol, Column.from_numpy(ivals)], n)
    seen = _spy_paths(lambda: gb.groupby_aggregate(
        b, [0], [AggSpec("sum", 1), AggSpec("min", 1),
                 AggSpec("count_star")], [dt.INT64, dt.INT64],
        dense_ok=False))
    assert seen == ["dense"]
    # ...but a float min/max stays order-insensitive too: float min
    # with dense_ok=False also keeps the dense kernel
    b2 = _make_batch(rng, 1000, 64, dt.FLOAT64, with_stats=True)
    seen2 = _spy_paths(lambda: gb.groupby_aggregate(
        b2, [0], [AggSpec("min", 1), AggSpec("max", 1)],
        [dt.INT64, dt.FLOAT64], dense_ok=False))
    assert seen2 == ["dense"]


def test_order_sensitive_float_sum_paths_both_run():
    """Sanity on the split the gate exists for: a float sum across the
    two kernels agrees to tolerance (NOT necessarily bitwise — that is
    exactly why grouping sets pin one path) and count/min/max remain
    bit-exact alongside."""
    rng = np.random.default_rng(5)
    n = 4000
    dtypes = [dt.INT64, dt.FLOAT64]
    aggs = [AggSpec("sum", 1), AggSpec("min", 1), AggSpec("count", 1)]
    b_dense = _make_batch(rng, n, 32, dt.FLOAT64, with_stats=True)
    # scrub NaN for the tolerance compare (NaN != NaN)
    import jax

    vals = np.asarray(jax.device_get(b_dense.columns[1].data)).copy()
    vals[np.isnan(vals)] = 1.25
    b_dense.columns[1] = Column(dt.FLOAT64, vals,
                                b_dense.columns[1].validity)
    b_sort = ColumnarBatch(list(b_dense.columns), n)
    b_sort.columns[0] = Column(dt.INT64, b_dense.columns[0].data,
                               b_dense.columns[0].validity)
    out_d, _ = gb.groupby_aggregate(b_dense, [0], aggs, dtypes)
    out_s, _ = gb.groupby_aggregate(b_sort, [0], aggs, dtypes)
    rows_d = _rows(out_d, len(aggs))
    rows_s = _rows(out_s, len(aggs))
    assert rows_d.keys() == rows_s.keys()
    for k in rows_d:
        sd, ss = rows_d[k][0], rows_s[k][0]
        assert sd[1] == ss[1]  # validity agrees
        if sd[1]:
            np.testing.assert_allclose(
                np.uint64(sd[0]).view(np.float64),
                np.uint64(ss[0]).view(np.float64), rtol=1e-9)
        assert rows_d[k][1:] == rows_s[k][1:]  # min/count bit-exact
