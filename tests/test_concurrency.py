"""Concurrent task execution within a host (SURVEY §2.10: the
reference oversubscribes the device with N concurrent Spark tasks while
GpuSemaphore bounds device entry, GpuSemaphore.scala:27-161,
RapidsConf.scala:340). Here the task pool (rapids.tpu.sql.taskThreads)
drives partitions concurrently; scans' host I/O overlaps device work."""
import threading
import time

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.execs.base import collect, run_partitions
from spark_rapids_tpu.memory.semaphore import TpuSemaphore


def test_run_partitions_preserves_order_and_uses_threads():
    seen = []

    def fn(p):
        time.sleep(0.02 * (4 - p))  # later partitions finish first
        seen.append(threading.get_ident())
        return p * 10

    out = run_partitions(4, fn, task_threads=4)
    assert out == [0, 10, 20, 30]
    assert len(set(seen)) > 1


def test_semaphore_bounds_concurrent_device_entry():
    """With 6 task threads and 2 permits, at most 2 tasks hold the
    device at once — and the pool genuinely runs tasks in parallel."""
    sem = TpuSemaphore(2)
    in_flight = []
    peak = [0]
    lock = threading.Lock()

    def task(p):
        with sem:
            with lock:
                in_flight.append(p)
                peak[0] = max(peak[0], len(in_flight))
            time.sleep(0.05)
            with lock:
                in_flight.remove(p)

    t0 = time.perf_counter()
    run_partitions(6, task, task_threads=6)
    wall = time.perf_counter() - t0
    assert peak[0] == 2          # blocked at N, but reached N
    assert wall < 6 * 0.05       # and genuinely overlapped


class _SlowSource:
    """DataSource stub whose host read sleeps — models parquet decode
    latency that the pool should overlap across partitions."""

    def __init__(self, n_splits: int, delay: float):
        self.n = n_splits
        self.delay = delay

    def num_splits(self):
        return self.n

    def split_origin(self, p):
        return None

    def split_stats(self, p):
        return None

    def read_host_split(self, p):
        time.sleep(self.delay)
        vals = np.arange(p * 100, p * 100 + 50, dtype=np.int64)
        return {"v": vals}, {"v": None}


def _slow_scan(n_splits, delay):
    from spark_rapids_tpu.execs.basic import ScanExec

    return ScanExec(_SlowSource(n_splits, delay),
                    Schema(["v"], [dt.INT64]))


def test_concurrent_scan_overlaps_io():
    delay = 0.15
    serial = collect(_slow_scan(4, delay),
                     conf=RapidsConf({"rapids.tpu.sql.taskThreads": 1}))
    t0 = time.perf_counter()
    parallel = collect(_slow_scan(4, delay),
                       conf=RapidsConf({"rapids.tpu.sql.taskThreads": 4}))
    wall = time.perf_counter() - t0
    assert parallel["v"].tolist() == serial["v"].tolist()
    assert wall < 4 * delay * 0.8, wall  # overlapped, not serialized


def test_concurrent_query_matches_serial(tmp_path):
    """Full pipeline (scan -> filter -> shuffle exchange -> join ->
    aggregate) under the task pool must equal the serial run."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.api import Session, col, functions as F

    rng = np.random.default_rng(11)
    n = 20_000
    tdir = tmp_path / "t"
    tdir.mkdir()
    for i in range(6):  # several splits -> several scan partitions
        sl = slice(i * n // 6, (i + 1) * n // 6)
        pq.write_table(pa.table({
            "k": rng.integers(0, 40, n).astype(np.int64)[sl],
            "v": rng.random(n)[sl]}), str(tdir / f"p{i}.parquet"))
    ddir = tmp_path / "d"
    ddir.mkdir()
    pq.write_table(pa.table({
        "dk": np.arange(0, 40, dtype=np.int64),
        "w": rng.random(40)}), str(ddir / "d.parquet"))

    def run(threads):
        s = Session({"rapids.tpu.sql.taskThreads": threads,
                     "rapids.tpu.sql.shuffle.partitions": 4})
        f = s.read.parquet(str(tdir)).filter(col("v") > 0.25)
        d = s.read.parquet(str(ddir))
        j = f.join(d, [("k", "dk")], "inner")
        out = j.group_by("k").agg(
            F.sum(col("v")).alias("sv"), F.count("*").alias("n"),
            F.max(col("w")).alias("mw"))
        return out.collect().sort_values("k").reset_index(drop=True)

    serial = run(1)
    par = run(6)
    assert par["k"].tolist() == serial["k"].tolist()
    np.testing.assert_allclose(par["sv"], serial["sv"], rtol=1e-9)
    assert par["n"].tolist() == serial["n"].tolist()
    np.testing.assert_allclose(par["mw"], serial["mw"])
