"""Streaming ingestion & standing queries (service/streaming): the
acceptance suite. The load-bearing fences:

- EQUIVALENCE: a standing query folded over N appended micro-batches —
  including out-of-order / late ones — must match the batch engine run
  over the concatenated input (the batch engine is the oracle; the
  stream table's read_host IS the concatenation).
- RESILIENCE: a fold that trips an injected OOM at its own retry sites
  walks the same spill/halve ladder as a batch aggregation and still
  produces the oracle answer.
- LIFECYCLE: cancel (including cancel MID-FOLD through the test seam)
  releases every owner-tagged catalog buffer — ``owner_refcounts`` must
  come back empty, the same leak fence batch queries have.
"""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.api import Session
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.memory import fault_injection as FI
from spark_rapids_tpu.memory.catalog import get_catalog
from spark_rapids_tpu.plan.incremental import (IncrementalUnsupported,
                                               analyze)
from spark_rapids_tpu.service import QueryService
from spark_rapids_tpu.service.streaming import stats as sstats
from spark_rapids_tpu.service.streaming.standing import (
    CANCELLED, EMITTING, FAILED, StreamingStateOverflow)

from tests.compare import assert_frames_equal

SCHEMA = Schema(["k", "v", "ev"], [dt.INT64, dt.FLOAT64, dt.INT64])
AGG_SQL = ("SELECT k, SUM(v) AS sv, COUNT(v) AS c "
           "FROM events GROUP BY k")


@pytest.fixture(autouse=True)
def _clean_injector():
    FI.get_injector().disarm()
    yield
    FI.get_injector().disarm()


def _batch(seed, n=200, nk=7, t0=0):
    rng = np.random.default_rng(seed)
    return {"k": rng.integers(0, nk, n).astype(np.int64),
            "v": rng.random(n),
            "ev": (t0 + rng.integers(0, 1000, n)).astype(np.int64)}


def _frame(batches):
    return pd.concat([pd.DataFrame(b) for b in batches],
                     ignore_index=True)


def _session():
    s = Session()
    src = s.create_streaming_table("events", SCHEMA)
    return s, src


def _oracle(frame):
    return frame.groupby("k").agg(
        sv=("v", "sum"), c=("v", "count")).reset_index()


# -- equivalence ------------------------------------------------------------


def test_incremental_matches_batch_over_appends():
    """q5lite-style streaming aggregation over N appended micro-batches
    == the batch engine over the concatenated input, checked at EVERY
    emit point (not just the end)."""
    s, src = _session()
    df = s.sql(AGG_SQL)
    try:
        sq = s.service.register_standing(df)
        seen = []
        for i in range(6):
            b = _batch(seed=i, n=150 + 37 * i, t0=i * 1000)
            seen.append(b)
            s.append_batch("events", b)
            assert sq.state == EMITTING and sq.folds == i + 1
            # the batch engine over the SAME table is the oracle
            assert_frames_equal(_oracle(_frame(seen)), sq.results())
            assert_frames_equal(df.to_pandas(), sq.results())
        assert sq.rows_folded == sum(len(b["k"]) for b in seen)
    finally:
        s.stop()


def test_catchup_folds_preexisting_deltas():
    """Registering AFTER appends must fold the backlog immediately —
    a standing query never misses data that landed before it."""
    s, src = _session()
    try:
        batches = [_batch(seed=i) for i in range(3)]
        for b in batches:
            src.append(b)
        sq = s.service.register_standing(s.sql(AGG_SQL))
        assert sq.folds == 3
        assert_frames_equal(_oracle(_frame(batches)), sq.results())
    finally:
        s.stop()


def test_out_of_order_late_batches_merge_to_oracle():
    """Late rows (event time at-or-below the watermark on arrival)
    re-merge through the same merge specs: the final answer equals the
    batch oracle over ALL rows, and the late-row counter proves the
    late path actually ran."""
    s, src = _session()
    try:
        sq = s.service.register_standing(
            s.sql(AGG_SQL), event_time_col="ev", watermark_ms=100,
            late_policy="merge")
        on_time = [_batch(seed=i, t0=10_000 * (i + 1))
                   for i in range(3)]
        for b in on_time:
            s.append_batch("events", b)
        assert sq.watermark == max(int(np.max(b["ev"]))
                                   for b in on_time) - 100
        late = _batch(seed=9, t0=0)   # far below the watermark
        s.append_batch("events", late)
        assert sq.late_rows_remerged == len(late["k"])
        assert_frames_equal(_oracle(_frame(on_time + [late])),
                            sq.results())
        # max() watermark never retreats on out-of-order arrival
        assert sq.watermark == max(int(np.max(b["ev"]))
                                   for b in on_time) - 100
    finally:
        s.stop()


def test_late_policy_drop_excludes_late_rows():
    s, src = _session()
    try:
        sq = s.service.register_standing(
            s.sql(AGG_SQL), event_time_col="ev", watermark_ms=0,
            late_policy="drop")
        first = _batch(seed=1, t0=50_000)
        s.append_batch("events", first)
        late = _batch(seed=2, t0=0)
        s.append_batch("events", late)
        assert sq.late_rows_dropped == len(late["k"])
        # oracle over the on-time rows only
        assert_frames_equal(_oracle(_frame([first])), sq.results())
    finally:
        s.stop()


def test_windowed_finalization_under_watermark():
    """Grouping by a window-end column: final_only emits exactly the
    windows the watermark has passed."""
    s = Session()
    s.create_streaming_table(
        "w", Schema(["wend", "v"], [dt.INT64, dt.INT64]))
    try:
        sq = s.service.register_standing(
            s.sql("SELECT wend, SUM(v) AS sv FROM w GROUP BY wend"),
            event_time_col="wend", window_col="wend",
            watermark_ms=500)
        s.append_batch("w", {"wend": np.array([1000, 2000, 3000]),
                             "v": np.array([1, 2, 3])})
        # watermark = 3000 - 500 = 2500: windows 1000 and 2000 final
        fin = sq.results(final_only=True)
        assert sorted(fin["wend"]) == [1000, 2000]
        full = sq.results(final_only=False)
        assert sorted(full["wend"]) == [1000, 2000, 3000]
    finally:
        s.stop()


def test_streaming_join_keeps_dimension_build_across_folds():
    """A streaming fact joined against a non-streaming dimension: the
    per-fold exec reset clears only delta-reachable state, so the
    dimension build materializes ONCE and every fold still matches the
    batch oracle."""
    from spark_rapids_tpu.execs.exchange import BroadcastExchangeExec

    s = Session()
    s.create_streaming_table(
        "fact", Schema(["k", "v"], [dt.INT64, dt.INT64]))
    dim = pd.DataFrame({"k": np.arange(8, dtype=np.int64),
                        "w": np.arange(8, dtype=np.int64) * 10})
    s.create_temp_view("dim", s.create_dataframe(dim))
    q = s.sql("SELECT dim.w AS w, SUM(fact.v) AS sv FROM fact "
              "JOIN dim ON fact.k = dim.k GROUP BY dim.w")
    try:
        sq = s.service.register_standing(q)
        state = sq.agg_state
        builds = [e for e in _walk_execs(state._child_exec)
                  if isinstance(e, BroadcastExchangeExec)]
        seen = []
        cached_after_first = None
        for i in range(4):
            b = {"k": np.random.RandomState(i).randint(0, 8, 100)
                 .astype(np.int64),
                 "v": np.arange(100, dtype=np.int64)}
            seen.append(b)
            s.append_batch("fact", b)
            if builds and not _reaches_delta(state, builds[0]):
                if cached_after_first is None:
                    cached_after_first = builds[0]._cached
                    assert cached_after_first is not None
                else:
                    # the SAME materialized build object, not a rebuild
                    assert builds[0]._cached is cached_after_first
        fact = _frame(seen)
        oracle = fact.merge(dim, on="k").groupby("w").agg(
            sv=("v", "sum")).reset_index()
        assert_frames_equal(oracle, sq.results())
        assert_frames_equal(q.to_pandas(), sq.results())
    finally:
        s.stop()


def _walk_execs(root):
    out, stack = [], [root]
    while stack:
        e = stack.pop()
        out.append(e)
        stack.extend(getattr(e, "children", ()))
        if hasattr(e, "builds"):
            stack.extend(e.builds)
            stack.append(e.fallback)
    return out


def _reaches_delta(state, e):
    return state._reaches_delta(e, {})


def test_pandas_append_with_nulls():
    """Session.append_batch accepts a pandas frame; NaNs become
    validity masks exactly like create_dataframe, and COUNT(v) counts
    only valid rows."""
    s = Session()
    s.create_streaming_table(
        "t", Schema(["k", "v"], [dt.INT64, dt.FLOAT64]))
    try:
        sq = s.service.register_standing(
            s.sql("SELECT k, SUM(v) AS sv, COUNT(v) AS c "
                  "FROM t GROUP BY k"))
        pdf = pd.DataFrame({"k": [0, 0, 1, 1, 1],
                            "v": [1.0, np.nan, 2.0, np.nan, 4.0]})
        s.append_batch("t", pdf)
        res = sq.results().sort_values("k").reset_index(drop=True)
        assert list(res["c"]) == [1, 2]
        assert res["sv"].tolist() == pytest.approx([1.0, 6.0])
    finally:
        s.stop()


# -- resilience -------------------------------------------------------------


def test_injected_oom_fold_walks_retry_ladder():
    """An injected OOM at the fold's own retry sites must not change
    the answer — the ladder spills/halves and the fold completes; the
    per-owner retry ledger records the retries."""
    s, src = _session()
    try:
        sq = s.service.register_standing(s.sql(AGG_SQL))
        b0 = _batch(seed=0)
        s.append_batch("events", b0)
        FI.get_injector().arm(at_call=1, consecutive=1,
                              sites=["streaming.fold"])
        b1 = _batch(seed=1)
        s.append_batch("events", b1)
        FI.get_injector().disarm()
        assert sq.state == EMITTING, sq.error
        assert_frames_equal(_oracle(_frame([b0, b1])), sq.results())
        from spark_rapids_tpu.memory import retry as R
        owner = R.owner_stats(sq.owner_tag)
        assert owner["oom_retries"] >= 1, \
            "the injected fold OOM must be visible in the retry ledger"
        per_site = R.stats()["per_site"]
        assert any(site.startswith("streaming.fold")
                   and d["oom_retries"] >= 1
                   for site, d in per_site.items()), per_site
    finally:
        s.stop()


def test_max_state_bytes_fails_query_and_tears_down():
    s, src = _session()
    try:
        sq = s.service.register_standing(s.sql(AGG_SQL),
                                         max_state_bytes=1)
        s.append_batch("events", _batch(seed=0))
        assert sq.state == FAILED
        assert isinstance(sq.error, StreamingStateOverflow)
        assert get_catalog().owner_refcounts(sq.owner_tag) == {}, \
            "state-overflow teardown leaked owner-tagged buffers"
        with pytest.raises(StreamingStateOverflow):
            sq.results()
        # the append itself survived: batch queries still see the rows
        assert src.total_rows == 200
    finally:
        s.stop()


# -- lifecycle / leak fence -------------------------------------------------


def test_cancel_mid_fold_releases_owner_tags():
    """Cancel landing BETWEEN fold steps (through the deterministic
    test seam): the fold aborts, the standing query is CANCELLED, and
    the catalog holds ZERO buffers under its owner tag."""
    s, src = _session()
    try:
        sq = s.service.register_standing(s.sql(AGG_SQL))
        s.append_batch("events", _batch(seed=0))
        calls = []

        def hook():
            # fire the cancel exactly once, mid-fold
            if not calls:
                calls.append(1)
                sq._cancel_requested = True

        sq._fold_hook = hook
        s.append_batch("events", _batch(seed=1))
        assert calls, "the fold never reached the seam"
        assert sq.state == CANCELLED
        assert get_catalog().owner_refcounts(sq.owner_tag) == {}, \
            "cancel mid-fold leaked owner-tagged catalog buffers"
        from spark_rapids_tpu.service.types import QueryCancelled
        with pytest.raises(QueryCancelled):
            sq.results()
        # later appends land (the table outlives the query) but are
        # not folded by the dead query
        s.append_batch("events", _batch(seed=2))
        assert sq.folds == 1 and src.num_appends == 3
    finally:
        s.stop()


def test_cancel_idle_releases_owner_tags():
    s, src = _session()
    try:
        sq = s.service.register_standing(s.sql(AGG_SQL))
        for i in range(3):
            s.append_batch("events", _batch(seed=i))
        assert sq.agg_state.state_bytes() > 0
        assert sq.cancel() and sq.state == CANCELLED
        assert get_catalog().owner_refcounts(sq.owner_tag) == {}
        assert sq.agg_state.state_bytes() == 0
    finally:
        s.stop()


def test_shutdown_cancels_standing_queries():
    s, src = _session()
    sq = s.service.register_standing(s.sql(AGG_SQL))
    s.append_batch("events", _batch(seed=0))
    tag = sq.owner_tag
    s.stop()
    assert sq.terminal
    assert get_catalog().owner_refcounts(tag) == {}


# -- validation -------------------------------------------------------------


def test_unsupported_shapes_are_rejected():
    s, src = _session()
    try:
        # no aggregate on top
        with pytest.raises(IncrementalUnsupported, match="aggregation"):
            analyze(s.sql("SELECT k, v FROM events"))
        # no streaming source at all
        s.create_temp_view("plain", s.create_dataframe(
            {"k": np.array([1]), "v": np.array([1.0])}))
        with pytest.raises(IncrementalUnsupported,
                           match="no streaming table"):
            analyze(s.sql("SELECT k, SUM(v) AS sv FROM plain "
                          "GROUP BY k"))
        # bad knobs
        with pytest.raises(ValueError, match="late_policy"):
            s.service.register_standing(s.sql(AGG_SQL),
                                        late_policy="teleport")
        with pytest.raises(ValueError, match="event_time_col"):
            s.service.register_standing(s.sql(AGG_SQL),
                                        event_time_col="nope")
        # disabled by conf
        s2 = Session({cfg.STREAMING_ENABLED.key: False})
        src2 = s2.create_streaming_table("events", SCHEMA)
        try:
            with pytest.raises(RuntimeError, match="disabled"):
                s2.service.register_standing(s2.sql(AGG_SQL))
        finally:
            s2.stop()
    finally:
        s.stop()


def test_ragged_and_missing_column_appends_rejected():
    s, src = _session()
    try:
        with pytest.raises(ValueError, match="missing columns"):
            src.append({"k": np.array([1])})
        with pytest.raises(ValueError, match="ragged"):
            src.append({"k": np.array([1, 2]), "v": np.array([1.0]),
                        "ev": np.array([0, 1])})
    finally:
        s.stop()


# -- observability ----------------------------------------------------------


def test_service_stats_streaming_block():
    pre = sstats.snapshot()
    s, src = _session()
    try:
        sq = s.service.register_standing(s.sql(AGG_SQL),
                                         event_time_col="ev")
        for i in range(2):
            s.append_batch("events", _batch(seed=i, t0=i * 10_000))
        sq.results()
        st = s.service.stats().streaming
        for key in ("standing_live", "folds", "state_bytes",
                    "device_resident_bytes", "watermark_lag_ms",
                    "late_rows_remerged", "standing"):
            assert key in st, f"streaming stats block missing {key}"
        assert st["standing_live"] == 1
        d = sstats.delta(pre)
        assert d["appends"] == 2 and d["folds"] == 2
        assert d["emits"] >= 1 and d["rows_appended"] == \
            sq.rows_folded
        mine = [q for q in st["standing"]
                if q["standing_id"] == sq.query_id]
        assert mine and mine[0]["state"] == EMITTING
        assert mine[0]["folds"] == 2
    finally:
        s.stop()
