"""Dispatch-budget regression fence + unit tests for the round-6
dispatch-coalescing work.

Behind the axon tunnel every dispatch (jit call, eager op, device_get)
costs ~105 ms of fixed round-trip overhead (BASELINE.md), so the
DISPATCH COUNT of a query — not its on-device time — sets the wall
clock floor. The fence below pins the full-query dispatch count of
tpcxbb q26 (scan -> filter -> broadcast join -> grouped aggregate ->
HAVING -> project -> ORDER BY) so a future PR cannot silently re-add
round trips: at 105 ms each, one stray ``device_get`` in a hot path is
a >10% regression on the real hardware even though it is invisible on
a local CPU run.

The fence runs in a SUBPROCESS because dispatch telemetry must wrap
``jax.jit`` before the compute modules import (module-level ``@jit``
decorators capture the binding); inside a long-lived pytest process
that moment is long gone.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

import numpy as np
import pytest

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# the hard ceiling for tpcxbb q26 at sf 0.1: measured 5 after the
# in-program build + single-pass groupby work (was 8 after the round-6
# whole-plan coalescing, 16 before that): stage0 = build-inlined chain
# + groupby + sort-tail chain, stage3 = 1 chain, result_sync = 1 fetch.
# See docs/tuning-guide.md "Dispatch cost model & stage fusion" for the
# stage-by-stage budget.
Q26_DISPATCH_BUDGET = 5

_FENCE_SCRIPT = r"""
import json, os, sys
sys.path.insert(0, __ROOT__)
from spark_rapids_tpu.utils import dispatch as disp
disp.install()   # BEFORE any compute module import
from spark_rapids_tpu.benchmarks.runner import (ALL_BENCHMARKS,
                                                BenchmarkRunner)
from spark_rapids_tpu.execs.base import collect
from spark_rapids_tpu.plan.overrides import apply_overrides

data_dir = __DATA_DIR__
r = BenchmarkRunner(data_dir, 0.1)
r.ensure_data("tpcxbb_q26")

# warm run: traces + compiles; the fence measures the steady state the
# driver's bench also reports
plan = ALL_BENCHMARKS["tpcxbb_q26"](data_dir)
collect(apply_overrides(plan, r.conf))

pre = disp.snapshot()
pre_stage = disp.stage_snapshot()
plan = ALL_BENCHMARKS["tpcxbb_q26"](data_dir)
df = collect(apply_overrides(plan, r.conf))
d = disp.delta(pre)

cmp_ = r.compare_results("tpcxbb_q26", df)
print(json.dumps({
    "dispatch_count": d["dispatch_count"],
    "detail": d,
    "per_stage": disp.stage_delta(pre_stage),
    "matches_cpu": cmp_["matches_cpu"],
    "mismatch": cmp_.get("detail", ""),
}))
"""


def test_q26_full_query_dispatch_budget(tmp_path):
    """tpcxbb q26 sf0.1, warm, end to end: dispatch_count <= 5 AND the
    result still matches the CPU oracle (a budget met by breaking the
    query would be worthless). Every dispatch must also carry a stage
    label — the old stray ``<unstaged>`` device_get is now part of the
    documented ``result_sync`` stage, and nothing may regress to an
    unattributed bucket."""
    # persistent data dir (marker-guarded, like bench.py's): datagen is
    # the expensive part and the tables are deterministic per sf
    data_dir = os.path.join("/tmp", "srt_dispatch_fence")
    script = _FENCE_SCRIPT.replace("__ROOT__", repr(ROOT)).replace(
        "__DATA_DIR__", repr(data_dir))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=580)
    assert out.returncode == 0, out.stderr[-3000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert rec["matches_cpu"], rec["mismatch"]
    assert rec["dispatch_count"] <= Q26_DISPATCH_BUDGET, (
        f"dispatch_count {rec['dispatch_count']} exceeds the "
        f"{Q26_DISPATCH_BUDGET}-dispatch fence; per-source "
        f"{rec['detail']}, per-stage {rec['per_stage']} — a new host "
        f"sync or un-fused launch crept into the pipeline (each one "
        f"costs ~105 ms behind the tunnel)")
    # attribution fence: every warm dispatch belongs to a pipeline
    # stage or the documented end-of-query result_sync fetch; an
    # <unstaged> bucket means an unattributed host sync came back
    assert "<unstaged>" not in (rec["per_stage"] or {}), rec["per_stage"]
    assert rec["per_stage"].get("result_sync", 0) >= 1, rec["per_stage"]


# ---------------------------------------------------------------------------
# unit tests for the round-6 satellite fixes
# ---------------------------------------------------------------------------


def test_narrow_uint_dictionary_boundary():
    """Exactly-256/65536-entry dictionaries pack at the narrow width:
    max code is len-1 (ADVICE r5: the old call passed len and lost the
    power-of-two boundary cases)."""
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import Schema
    from spark_rapids_tpu.execs import interop

    n = interop._PACK_MIN_ROWS
    for n_dict, want in ((256, np.uint8), (257, np.uint16)):
        vals = np.array([f"s{i:05d}" for i in range(n_dict)],
                        dtype=object)
        data = {"s": vals[np.arange(n) % n_dict]}
        packed = interop.pack_host(data, {"s": None},
                                   Schema(["s"], [dt.STRING]))
        (kind, bi, _vi, typ, dictionary, _st) = packed.col_specs[0]
        assert len(dictionary) == n_dict
        assert packed.host_bufs[bi].dtype == np.dtype(want), (
            n_dict, packed.host_bufs[bi].dtype)
        # decode must round-trip exactly
        b = interop.upload_packed(packed)
        got, _ = b.columns[0].to_numpy(n)
        assert list(got[:5]) == list(data["s"][:5])


def test_prep_cache_recovers_from_transient_sync_failure(monkeypatch):
    """A device_get failure during the prep flag sync must POP the
    (exchange, key) cache entry — like the launch-failure path — so a
    retry by a later consumer succeeds instead of seeing the poisoned
    entry forever (ADVICE r5)."""
    import types

    import jax

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import Schema
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.execs import fused
    from spark_rapids_tpu.execs.basic import DeviceBatchesExec
    from spark_rapids_tpu.execs.exchange import BroadcastExchangeExec

    keys = np.arange(8, dtype=np.int64)
    batch = ColumnarBatch(
        [Column.from_numpy(keys, dtype=dt.INT64)], len(keys))
    src = types.SimpleNamespace(batches=[batch])
    exch = BroadcastExchangeExec(
        DeviceBatchesExec(src, Schema(["k"], [dt.INT64])))

    real_get = jax.device_get
    boom = {"armed": True}

    def flaky_get(x):
        if boom["armed"]:
            boom["armed"] = False
            raise RuntimeError("transient tunnel error")
        return real_get(x)

    monkeypatch.setattr(jax, "device_get", flaky_get)
    with pytest.raises(RuntimeError, match="transient"):
        fused.prepare_build(exch, [0], [dt.INT64], [dt.INT64])
    # the poisoned entry must be gone: this retry re-launches and wins
    prep = fused.prepare_build(exch, [0], [dt.INT64], [dt.INT64])
    assert prep.ok


def test_chain_program_tag_includes_probe_mode():
    """Dense-probe and hash-probe variants of one chain must carry
    DIFFERENT telemetry names/crc tags (ADVICE r5: they shared one,
    blurring per-program dispatch attribution)."""
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.execs.fused import FusedChain, JoinStep

    chain = FusedChain(
        [JoinStep("inner", [0], [0], 0, [dt.INT64], [dt.INT64])],
        [dt.INT64], 1)
    names = set()
    for modes in ((True,), (False,)):
        prog = chain._build_program(True, modes)
        name = getattr(prog, "__name__", None) or \
            prog.__wrapped__.__name__
        names.add(name)
        assert name.startswith("fused_chain[join]")
    assert len(names) == 2, names
    # and the cache keys differ too (correctness was already keyed)
    assert chain.chain_key(True, (True,)) != \
        chain.chain_key(True, (False,))


def test_chain_program_label_marks_inline_build():
    """The build-inlined chain variant must carry a ``build+`` label
    prefix and a distinct cache key: telemetry readers tell a first
    launch that prepared the builds in-program apart from the steady-
    state probe-only launches of the same chain."""
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.execs.fused import FusedChain, JoinStep

    chain = FusedChain(
        [JoinStep("inner", [0], [0], 0, [dt.INT64], [dt.INT64])],
        [dt.INT64], 1)
    inline = (((0,), (dt.INT64,), (dt.INT64,), 0, 0),)
    prog_probe = chain._build_program(True, (False,))
    prog_inline = chain._build_program(True, (False,), (), inline)
    name_p = getattr(prog_probe, "__name__", None) or \
        prog_probe.__wrapped__.__name__
    name_i = getattr(prog_inline, "__name__", None) or \
        prog_inline.__wrapped__.__name__
    assert name_p.startswith("fused_chain[join]"), name_p
    assert name_i.startswith("fused_chain[build+join]"), name_i
    assert chain.chain_key(True, (False,)) != \
        chain.chain_key(True, (False,), (), inline)


def test_arrow_dictionary_with_null_slot():
    """A null INSIDE an arrow DictionaryArray's dictionary must fold
    into the validity mask — not surface as the literal string 'None'
    (ADVICE r5)."""
    pa = pytest.importorskip("pyarrow")

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.io.arrow_conv import column_to_host

    col = pa.DictionaryArray.from_arrays(
        pa.array([0, 1, 2, 0, 1], type=pa.int32()),
        pa.array(["b", None, "a"]))
    hs, valid = column_to_host(col, dt.STRING)
    assert valid is not None
    assert list(valid) == [True, False, True, True, False]
    decoded = [hs.dictionary[c] if v else None
               for c, v in zip(hs.codes, valid)]
    assert decoded == ["b", None, "a", "b", None]
    assert "None" not in set(hs.dictionary[hs.codes[valid]])


def test_spillable_deferred_count_realizes_batched():
    """defer_count keeps the register path sync-free and
    realize_counts fetches many counts in one transfer."""
    import jax.numpy as jnp

    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.memory.spillable import SpillableBatch

    handles = []
    for n in (3, 5):
        b = ColumnarBatch(
            [Column.from_numpy(np.arange(8, dtype=np.int64),
                               dtype=dt.INT64)],
            jnp.asarray(n, dtype=jnp.int32))  # lazy device count
        handles.append(SpillableBatch(b, 0, defer_count=True))
    assert all(sb._rows is None for sb in handles)
    SpillableBatch.realize_counts(handles)
    assert [sb.num_rows for sb in handles] == [3, 5]
    for sb in handles:
        sb.close()


def test_sort_tail_fusion_matches_unfused():
    """The absorbed post-aggregate tail (defer_final + SortStep) must
    produce frames identical to the conf-disabled path — including
    HAVING over the final projection and a DESC sort with nulls."""
    import pandas as pd

    from compare import assert_frames_equal
    from spark_rapids_tpu.api import Session

    rng = np.random.default_rng(23)
    n = 500
    df = pd.DataFrame({
        "k": rng.integers(0, 40, n).astype(np.int64),
        "v": rng.normal(size=n)})
    df.loc[rng.integers(0, n, 25), "v"] = None
    sql = ("SELECT k, sum(v) AS sv, count(*) AS c FROM t "
           "GROUP BY k HAVING count(*) > 5 ORDER BY sv DESC, k")
    frames = []
    for tail in (True, False):
        s = Session(conf={"rapids.tpu.sql.fusion.sortTail": tail})
        s.create_temp_view("t", s.create_dataframe(df))
        frames.append(s.sql(sql).collect())
    assert_frames_equal(frames[0], frames[1])


def test_defer_scan_decode_matches_eager(tmp_path):
    """A packed parquet scan feeding a fused chain must produce the
    same frame whether the decode runs standalone or inlined in the
    chain program (>= _PACK_MIN_ROWS rows so packing engages)."""
    import pandas as pd

    from compare import assert_frames_equal
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.execs.interop import _PACK_MIN_ROWS

    pa = pytest.importorskip("pyarrow")
    pq = pytest.importorskip("pyarrow.parquet")

    n = _PACK_MIN_ROWS + 1000
    rng = np.random.default_rng(29)
    tbl = pa.table({
        "k": pa.array(rng.integers(0, 50, n).astype(np.int64)),
        "cat": pa.array([f"c{int(i) % 7}"
                         for i in rng.integers(0, 7, n)]),
        "v": pa.array(rng.integers(0, 1000, n).astype(np.int64))})
    path = str(tmp_path / "t.parquet")
    pq.write_table(tbl, path)
    sql = ("SELECT k, count(*) AS c, sum(v) AS sv FROM t "
           "WHERE cat = 'c3' AND v > 100 GROUP BY k ORDER BY k")
    frames = []
    for defer in (True, False):
        s = Session(conf={
            "rapids.tpu.sql.fusion.deferScanDecode": defer})
        s.register_parquet("t", path)
        frames.append(s.sql(sql).collect())
    assert_frames_equal(frames[0], frames[1])


def test_defer_final_not_absorbed_through_shared_intermediate():
    """defer_final mutates the aggregate's output contract; when the
    Project between Sort and Agg is SHARED with a second consumer, the
    absorption must decline — otherwise the second consumer reads raw
    partials as finalized columns."""
    import pandas as pd

    from compare import assert_frames_equal
    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.execs.aggregate import HashAggregateExec
    from spark_rapids_tpu.execs.basic import ProjectExec
    from spark_rapids_tpu.execs.basic import UnionExec
    from spark_rapids_tpu.execs.fused import fuse_pipelines
    from spark_rapids_tpu.execs.sort import SortExec
    from spark_rapids_tpu.execs.base import collect
    from spark_rapids_tpu.ops.sortkeys import SortKeySpec

    # build the exec tree by hand so the ProjectExec object is shared
    # by two parents (the CTE shape)
    s = Session()
    pdf = pd.DataFrame({"k": np.arange(200) % 9,
                        "v": np.arange(200, dtype=np.float64)})
    s.create_temp_view("t", s.create_dataframe(pdf))
    agg_exec_tree = s.sql(
        "SELECT k, sum(v) AS sv FROM t GROUP BY k")._exec()
    # locate the aggregate (strip any coalesce/wrappers above it)
    node = agg_exec_tree
    while not isinstance(node, HashAggregateExec):
        node = node.children[0]
    agg = node
    proj = ProjectExec(
        [__import__("spark_rapids_tpu.expressions.base",
                    fromlist=["BoundReference"]).BoundReference(i, t)
         for i, t in enumerate(agg.schema.types)],
        agg, agg.schema)
    sort_parent = SortExec([SortKeySpec.spark_default(0)], proj)
    root = UnionExec([sort_parent, proj], proj.schema)
    fused_root = fuse_pipelines(root, None)
    assert agg.defer_final is False, (
        "defer_final leaked through a shared Project: the second "
        "Union arm would read raw partials")
    # and the result must equal pandas on both arms
    got = collect(fused_root)
    kcol, vcol = got.columns[0], got.columns[1]
    want = pdf.groupby("k").agg(sv=("v", "sum")).reset_index()
    arm = got.iloc[:len(want)].reset_index(drop=True)
    arm2 = got.iloc[len(want):].reset_index(drop=True)
    for a in (arm, arm2):
        a = a.sort_values(kcol).reset_index(drop=True)
        assert np.allclose(a[vcol].astype(float).values,
                           want["sv"].values)


def test_cut_stages_labels_and_estimates():
    """The stage-cutting pass labels every exec reachable from the
    root (children AND broadcast builds) with a stage and attaches a
    positive dispatch estimate per stage."""
    import pandas as pd

    from spark_rapids_tpu.api import Session
    from spark_rapids_tpu.plan.optimizer import cut_stages

    s = Session()
    df = pd.DataFrame({"k": np.arange(100) % 7,
                       "v": np.arange(100, dtype=np.float64)})
    s.create_temp_view("t", s.create_dataframe(df))
    ex = s.sql("SELECT k, sum(v) AS sv FROM t WHERE v > 10 "
               "GROUP BY k ORDER BY k")._exec()
    stages = cut_stages(ex)
    assert stages and all(st["ops"] for st in stages)
    assert all(st["est_dispatches"] >= 0 for st in stages)
    assert sum(st["est_dispatches"] for st in stages) > 0
    labels = set()

    def walk(e):
        labels.add(getattr(e, "_stage_label", None))
        for c in e.children:
            walk(c)
        for bx in getattr(e, "builds", ()) or ():
            walk(bx)
    walk(ex)
    assert None not in labels


@pytest.mark.slow
def test_sf1_oracle_smoke():
    """Slow tier: one full query at sf 1 through scripts/sf1_check.py —
    warm dispatch count within budget, result oracle-matched, every
    dispatch stage-attributed. q6 is the cheapest sf-1 query; the
    nightly fence (scripts/sf1_check.py default) runs q1 too."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, os.path.join(ROOT, "scripts", "sf1_check.py"),
         "--queries", "tpch_q6", "--sf", "1.0"],
        env=env, capture_output=True, text=True, timeout=1800)
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    rec = json.loads(out.stdout)
    assert rec["ok"], rec
