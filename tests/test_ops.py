"""Kernel-surface tests against numpy/pandas oracles (the reference's
CPU-as-oracle methodology, SURVEY.md §4, applied per kernel)."""
import jax.numpy as jnp
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.ops import concat, filter as filt, groupby, hashing, \
    join, partition, sort
from spark_rapids_tpu.ops.groupby import AggSpec
from spark_rapids_tpu.ops.sortkeys import SortKeySpec


def make_batch(*arrays, validities=None, n=None):
    cols = []
    for i, a in enumerate(arrays):
        v = validities[i] if validities else None
        if isinstance(a[0] if len(a) else "", str) or (
                len(a) and a[0] is None and isinstance(a, list)):
            cols.append(StringColumn.from_strings(list(a)))
        else:
            cols.append(Column.from_numpy(np.asarray(a), validity=v))
    nn = n if n is not None else len(arrays[0])
    return ColumnarBatch(cols, nn)


# ---------------------------------------------------------------- filter

def test_filter_compact():
    b = make_batch(np.arange(10, dtype=np.int64))
    keep = jnp.asarray(np.pad(np.arange(10) % 3 == 0, (0, 118)))
    out = filt.compact_batch(b, keep)
    assert out.realized_num_rows() == 4
    vals, _ = out.columns[0].to_numpy(4)
    np.testing.assert_array_equal(vals, [0, 3, 6, 9])


def test_filter_null_predicate_drops():
    b = make_batch(np.arange(4, dtype=np.int64))
    keep = jnp.asarray(np.pad([True, True, False, True], (0, 124)))
    keep_valid = jnp.asarray(np.pad([True, False, True, True], (0, 124)))
    out = filt.compact_batch(b, keep, keep_valid)
    vals, _ = out.columns[0].to_numpy(out.realized_num_rows())
    np.testing.assert_array_equal(vals, [0, 3])


# ---------------------------------------------------------------- sort

def test_sort_two_keys_desc_nulls():
    a = np.array([3, 1, 2, 1, 3], dtype=np.int64)
    b = np.array([1.0, 2.0, np.nan, 1.0, -0.0])
    bv = np.array([True, True, True, False, True])
    batch = make_batch(a, b, validities=[None, bv])
    specs = [SortKeySpec.spark_default(0, True),
             SortKeySpec.spark_default(1, False)]  # b DESC -> nulls last
    out = sort.sort_batch(batch, specs, [dt.INT64, dt.FLOAT64])
    n = out.realized_num_rows()
    av, _ = out.columns[0].to_numpy(n)
    bvals, bval_v = out.columns[1].to_numpy(n)
    np.testing.assert_array_equal(av, [1, 1, 2, 3, 3])
    # a=1: b desc -> 2.0 then NULL(last); a=2: NaN; a=3: 1.0 then -0.0
    assert bvals[0] == 2.0
    assert bval_v is not None and not bval_v[1]
    assert np.isnan(bvals[2])
    assert bvals[3] == 1.0


def test_sort_nan_sorts_greatest_asc():
    x = np.array([np.nan, 1.0, -np.inf, np.inf, -1.0])
    batch = make_batch(x)
    out = sort.sort_batch(batch, [SortKeySpec.spark_default(0, True)],
                          [dt.FLOAT64])
    vals, _ = out.columns[0].to_numpy(5)
    assert vals[0] == -np.inf and vals[3] == np.inf and np.isnan(vals[4])


def test_sort_strings():
    s = ["pear", "apple", None, "fig"]
    batch = make_batch(s)
    out = sort.sort_batch(batch, [SortKeySpec.spark_default(0, True)],
                          [dt.STRING])
    vals, _ = out.columns[0].to_numpy(4)
    assert list(vals) == [None, "apple", "fig", "pear"]  # ASC nulls first


# ---------------------------------------------------------------- groupby

def test_groupby_sum_count_min_max():
    keys = np.array([2, 1, 2, 1, 3, 2], dtype=np.int64)
    vals = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
    vv = np.array([True, True, False, True, True, True])
    batch = make_batch(keys, vals, validities=[None, vv])
    out, out_types = groupby.groupby_aggregate(
        batch, [0],
        [AggSpec("sum", 1), AggSpec("count", 1), AggSpec("min", 1),
         AggSpec("max", 1), AggSpec("count_star")],
        [dt.INT64, dt.FLOAT64])
    n = out.realized_num_rows()
    assert n == 3
    df = out.to_pandas()
    df.columns = ["k", "sum", "cnt", "mn", "mx", "cs"]
    df = df.sort_values("k").reset_index(drop=True)
    np.testing.assert_array_equal(df["k"], [1, 2, 3])
    np.testing.assert_array_equal(df["sum"], [6.0, 7.0, 5.0])
    np.testing.assert_array_equal(df["cnt"], [2, 2, 1])
    np.testing.assert_array_equal(df["mn"], [2.0, 1.0, 5.0])
    np.testing.assert_array_equal(df["mx"], [4.0, 6.0, 5.0])
    np.testing.assert_array_equal(df["cs"], [2, 3, 1])


def test_groupby_null_keys_group_together():
    keys = np.array([1, 0, 1, 0], dtype=np.int64)
    kv = np.array([True, False, True, False])
    vals = np.array([1, 2, 3, 4], dtype=np.int64)
    batch = make_batch(keys, vals, validities=[kv, None])
    out, _ = groupby.groupby_aggregate(batch, [0], [AggSpec("sum", 1)],
                                       [dt.INT64, dt.INT64])
    assert out.realized_num_rows() == 2
    kvals, kvalid = out.columns[0].to_numpy(2)
    sums, _ = out.columns[1].to_numpy(2)
    # nulls-first grouping: first group is the null key
    assert kvalid is not None and not kvalid[0]
    assert sums[0] == 6 and sums[1] == 4


def test_groupby_all_null_sum_is_null():
    keys = np.array([1, 1], dtype=np.int64)
    vals = np.array([0.0, 0.0])
    vv = np.array([False, False])
    batch = make_batch(keys, vals, validities=[None, vv])
    out, _ = groupby.groupby_aggregate(batch, [0], [AggSpec("sum", 1)],
                                       [dt.INT64, dt.FLOAT64])
    _, sv = out.columns[1].to_numpy(1)
    assert sv is not None and not sv[0]


def test_groupby_string_keys():
    s = ["b", "a", "b", None, "a", None]
    vals = np.arange(6, dtype=np.int64)
    batch = make_batch(s, vals)
    out, _ = groupby.groupby_aggregate(batch, [0], [AggSpec("sum", 1)],
                                       [dt.STRING, dt.INT64])
    assert out.realized_num_rows() == 3
    kvals, _ = out.columns[0].to_numpy(3)
    sums, _ = out.columns[1].to_numpy(3)
    m = dict(zip(kvals, sums))
    assert m["a"] == 5 and m["b"] == 2 and m[None] == 8


def test_reduce_grand_aggregate():
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    batch = make_batch(vals)
    out, _ = groupby.reduce_aggregate(
        batch, [AggSpec("sum", 0), AggSpec("count_star"),
                AggSpec("min", 0)], [dt.FLOAT64])
    assert out.realized_num_rows() == 1
    assert out.columns[0].to_numpy(1)[0][0] == 10.0
    assert out.columns[1].to_numpy(1)[0][0] == 4
    assert out.columns[2].to_numpy(1)[0][0] == 1.0


def test_groupby_nan_and_negzero_group():
    keys = np.array([np.nan, np.nan, -0.0, 0.0])
    vals = np.ones(4, dtype=np.int64)
    batch = make_batch(keys, vals)
    out, _ = groupby.groupby_aggregate(batch, [0], [AggSpec("count", 1)],
                                       [dt.FLOAT64, dt.INT64])
    assert out.realized_num_rows() == 2  # NaN==NaN, -0.0==0.0


# ---------------------------------------------------------------- hashing

def test_hash_deterministic_across_batches():
    a1 = make_batch(np.array([1, 2, 3], dtype=np.int64))
    a2 = make_batch(np.array([3, 2, 1], dtype=np.int64))
    h1 = np.asarray(hashing.hash_columns(a1, [0], [dt.INT64]))[:3]
    h2 = np.asarray(hashing.hash_columns(a2, [0], [dt.INT64]))[:3]
    np.testing.assert_array_equal(h1, h2[::-1])


def test_hash_strings_dictionary_independent():
    s1 = make_batch(["apple", "kiwi"])
    s2 = make_batch(["kiwi", "zebra", "apple"])
    h1 = np.asarray(hashing.hash_columns(s1, [0], [dt.STRING]))
    h2 = np.asarray(hashing.hash_columns(s2, [0], [dt.STRING]))
    assert h1[1] == h2[0]  # kiwi hashes equal despite different dicts
    assert h1[0] == h2[2]


# ---------------------------------------------------------------- partition

def test_hash_partition_routes_consistently():
    k = np.array([5, 6, 5, 7, 6, 5], dtype=np.int64)
    b = make_batch(k)
    out, counts = partition.hash_partition(b, [0], [dt.INT64], 4)
    assert counts.sum() == 6
    parts = partition.slice_partitions(out, counts)
    seen = {}
    for p, pb in enumerate(parts):
        if pb is None:
            continue
        vals, _ = pb.columns[0].to_numpy(pb.realized_num_rows())
        for v in vals:
            assert seen.setdefault(v, p) == p  # same key -> same partition
    assert sum(counts) == 6


def test_round_robin_partition():
    b = make_batch(np.arange(10, dtype=np.int64))
    out, counts = partition.round_robin_partition(b, 3)
    assert counts.sum() == 10
    assert sorted(counts.tolist(), reverse=True)[0] == 4


# ---------------------------------------------------------------- concat

def test_concat_batches():
    b1 = make_batch(np.arange(5, dtype=np.int64))
    b2 = make_batch(np.arange(5, 8, dtype=np.int64))
    out = concat.concat_batches([b1, b2])
    assert out.realized_num_rows() == 8
    vals, _ = out.columns[0].to_numpy(8)
    np.testing.assert_array_equal(vals, np.arange(8))


def test_concat_strings_and_nulls():
    b1 = make_batch(["a", "c"], np.array([1.0, 2.0]))
    b2 = make_batch(["b", None], np.array([3.0, np.nan]),
                    validities=[None, np.array([True, False])])
    out = concat.concat_batches([b1, b2])
    svals, _ = out.columns[0].to_numpy(4)
    dvals, dv = out.columns[1].to_numpy(4)
    assert list(svals) == ["a", "c", "b", None]
    assert dv is not None and list(dv) == [True, True, True, False]


# ---------------------------------------------------------------- join

def _join_oracle(left, right, how):
    l = pd.DataFrame({"k": left[0], "lv": left[1]})
    r = pd.DataFrame({"k": right[0], "rv": right[1]})
    return l.merge(r, on="k", how=how)


@pytest.mark.parametrize("how", ["inner", "left"])
def test_equi_join_vs_pandas(how):
    lk = np.array([1, 2, 3, 4, 2], dtype=np.int64)
    lv = np.arange(5, dtype=np.int64)
    rk = np.array([2, 2, 4, 5], dtype=np.int64)
    rv = np.arange(10, 14, dtype=np.int64)
    lb = make_batch(lk, lv)
    rb = make_batch(rk, rv)
    out, types = join.equi_join(lb, rb, [0], [0],
                                [dt.INT64, dt.INT64], [dt.INT64, dt.INT64],
                                how)
    n = out.realized_num_rows()
    got = out.to_pandas()
    got.columns = ["k", "lv", "k2", "rv"]
    got = got[["k", "lv", "rv"]].sort_values(["k", "lv", "rv"],
                                             na_position="last"
                                             ).reset_index(drop=True)
    exp = _join_oracle((lk, lv), (rk, rv), how)[["k", "lv", "rv"]] \
        .sort_values(["k", "lv", "rv"], na_position="last") \
        .reset_index(drop=True)
    assert len(got) == len(exp)
    np.testing.assert_array_equal(got["k"].to_numpy(np.int64),
                                  exp["k"].to_numpy(np.int64))
    np.testing.assert_array_equal(
        got["rv"].astype("float64").fillna(-1).to_numpy(),
        exp["rv"].astype("float64").fillna(-1).to_numpy())


def test_semi_anti_join():
    lk = np.array([1, 2, 3, 4], dtype=np.int64)
    lv = np.arange(4, dtype=np.int64)
    rk = np.array([2, 4, 4], dtype=np.int64)
    lb = make_batch(lk, lv)
    rb = make_batch(rk, np.zeros(3, dtype=np.int64))
    semi, _ = join.equi_join(lb, rb, [0], [0],
                             [dt.INT64, dt.INT64], [dt.INT64, dt.INT64],
                             "leftsemi")
    vals, _ = semi.columns[0].to_numpy(semi.realized_num_rows())
    assert sorted(vals.tolist()) == [2, 4]
    anti, _ = join.equi_join(lb, rb, [0], [0],
                             [dt.INT64, dt.INT64], [dt.INT64, dt.INT64],
                             "leftanti")
    vals, _ = anti.columns[0].to_numpy(anti.realized_num_rows())
    assert sorted(vals.tolist()) == [1, 3]


def test_join_null_keys_never_match():
    lk = np.array([1, 0], dtype=np.int64)
    lkv = np.array([True, False])
    rk = np.array([1, 0], dtype=np.int64)
    rkv = np.array([True, False])
    lb = make_batch(lk, np.arange(2, dtype=np.int64), validities=[lkv, None])
    rb = make_batch(rk, np.arange(2, dtype=np.int64), validities=[rkv, None])
    out, _ = join.equi_join(lb, rb, [0], [0],
                            [dt.INT64, dt.INT64], [dt.INT64, dt.INT64],
                            "inner")
    assert out.realized_num_rows() == 1


def test_full_outer_join():
    lk = np.array([1, 2], dtype=np.int64)
    rk = np.array([2, 3], dtype=np.int64)
    lb = make_batch(lk, np.array([10, 20], dtype=np.int64))
    rb = make_batch(rk, np.array([200, 300], dtype=np.int64))
    out, _ = join.equi_join(lb, rb, [0], [0],
                            [dt.INT64, dt.INT64], [dt.INT64, dt.INT64],
                            "full")
    assert out.realized_num_rows() == 3


def test_string_key_join_across_dictionaries():
    lb = make_batch(["apple", "fig"], np.array([1, 2], dtype=np.int64))
    rb = make_batch(["fig", "zebra"], np.array([30, 40], dtype=np.int64))
    out, _ = join.equi_join(lb, rb, [0], [0],
                            [dt.STRING, dt.INT64], [dt.STRING, dt.INT64],
                            "inner")
    assert out.realized_num_rows() == 1
    svals, _ = out.columns[0].to_numpy(1)
    assert svals[0] == "fig"


def test_reduce_first_last_empty_batch_is_null():
    # first/last over zero rows must be NULL, not padding garbage
    batch = make_batch(np.array([], dtype=np.float64))
    out, _ = groupby.reduce_aggregate(
        batch, [AggSpec("first", 0), AggSpec("last", 0),
                AggSpec("count", 0)], [dt.FLOAT64])
    assert out.realized_num_rows() == 1
    fv, fm = out.columns[0].to_numpy(1)
    lv, lm = out.columns[1].to_numpy(1)
    assert fm is not None and not fm[0]
    assert lm is not None and not lm[0]
    assert out.columns[2].to_numpy(1)[0][0] == 0


def test_groupby_live_mask_fused_filter():
    """live_mask fuses a filter into the groupby sort; results must equal
    filter-then-groupby. Regression: kept rows located beyond the
    post-filter count must not be treated as padding."""
    import jax.numpy as jnp

    from spark_rapids_tpu.ops import groupby as gb

    rng = np.random.default_rng(0)
    n = 4096
    keys = rng.integers(0, 37, n).astype(np.int64)
    vals = rng.random(n)
    # keep mask biased so many kept rows sit in the BACK half
    keep = (np.arange(n) > n // 2) | (rng.random(n) < 0.1)
    cols = [(jnp.asarray(keys), None), (jnp.asarray(vals), None)]
    (kd, kv), (ad, av), ng = gb._groupby(
        cols, (dt.INT64, dt.FLOAT64), (0,),
        (gb.AggSpec("sum", 1), gb.AggSpec("count_star")),
        jnp.int32(n), live_mask=jnp.asarray(keep))
    ng = int(ng)
    got_keys = np.asarray(kd[0])[:ng]
    got_sums = np.asarray(ad[0])[:ng]
    got_cnts = np.asarray(ad[1])[:ng]
    import pandas as pd

    expect = (pd.DataFrame({"k": keys[keep], "v": vals[keep]})
              .groupby("k").agg(s=("v", "sum"), c=("v", "size")))
    assert ng == len(expect)
    order = np.argsort(got_keys)
    np.testing.assert_array_equal(got_keys[order], expect.index.values)
    np.testing.assert_allclose(got_sums[order], expect["s"], rtol=1e-9)
    np.testing.assert_array_equal(got_cnts[order], expect["c"])


# -------------------------------------------- float-sum IEEE edge cases

def test_groupby_float_sum_running_total_overflow_confined():
    """All-finite inputs whose RUNNING total overflows must not poison
    later groups: the isfinite(grand total) predicate routes to the
    per-segment-scan tail (cumsum diffs would give inf-inf = NaN)."""
    keys = np.array([0, 0, 1], dtype=np.int64)
    vals = np.array([1.5e308, 1.5e308, 1.0])
    batch = make_batch(keys, vals)
    out, _ = groupby.groupby_aggregate(batch, [0], [AggSpec("sum", 1)],
                                       [dt.INT64, dt.FLOAT64])
    sums, _ = out.columns[1].to_numpy(2)
    assert np.isinf(sums[0]) and sums[0] > 0
    assert sums[1] == 1.0


def test_groupby_sum_of_squares_square_overflow():
    """A finite input whose SQUARE overflows must produce +inf, not be
    silently dropped (the predicate must test the squared lane)."""
    keys = np.array([0, 0, 0, 0], dtype=np.int64)
    vals = np.array([1e200, 1.0, 2.0, 3.0])
    batch = make_batch(keys, vals)
    out, _ = groupby.groupby_aggregate(
        batch, [0], [AggSpec("sum_of_squares", 1)],
        [dt.INT64, dt.FLOAT64])
    sums, _ = out.columns[1].to_numpy(1)
    assert np.isinf(sums[0]) and sums[0] > 0


def test_groupby_float_sum_no_cross_group_cancellation():
    """A huge group preceding a tiny one must not destroy the tiny
    group's sum: global cumsum diffs carry error scaling with the
    running prefix of OTHER groups (r2 advisor repro: group-1 sum came
    back 0.0 instead of 2.0). The per-segment scan confines error."""
    keys = np.array([0, 0, 1, 1], dtype=np.int64)
    vals = np.array([1e16, 1e16, 1.0, 1.0])
    batch = make_batch(keys, vals)
    out, _ = groupby.groupby_aggregate(batch, [0], [AggSpec("sum", 1)],
                                       [dt.INT64, dt.FLOAT64])
    sums, _ = out.columns[1].to_numpy(2)
    assert sums[0] == 2e16
    assert sums[1] == 2.0


def test_groupby_packed_key_large_magnitude_int64():
    """int64/TIMESTAMP keys with small span but magnitude above 2^31:
    the packed-lane decode must widen BEFORE adding the range base (r2
    advisor repro: OverflowError / wrapped keys)."""
    base = 5_000_000_000
    keys = np.array([base, base + 1, base, base + 1], dtype=np.int64)
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    batch = make_batch(keys, vals)
    kcol = batch.columns[0]
    kcol.stats = (base, base + 1)
    qlo, qhi = groupby.key_range_of(kcol, dt.INT64)
    assert qlo <= base and base + 1 <= qhi
    out, _ = groupby.groupby_aggregate(batch, [0], [AggSpec("sum", 1)],
                                       [dt.INT64, dt.FLOAT64])
    got_k, _ = out.columns[0].to_numpy(2)
    sums, _ = out.columns[1].to_numpy(2)
    order = np.argsort(got_k)
    np.testing.assert_array_equal(got_k[order], [base, base + 1])
    np.testing.assert_allclose(sums[order], [4.0, 6.0])


def test_groupby_packed_key_large_magnitude_with_nulls():
    """Same large-magnitude decode, via the has-validity branch."""
    base = -5_000_000_000
    keys = np.array([base, base + 2, base, 0], dtype=np.int64)
    valid = np.array([True, True, True, False])
    vals = np.array([1.0, 2.0, 3.0, 4.0])
    batch = make_batch(keys, vals, validities=[valid, None])
    batch.columns[0].stats = (base, base + 2)
    out, _ = groupby.groupby_aggregate(batch, [0], [AggSpec("sum", 1)],
                                       [dt.INT64, dt.FLOAT64])
    got_k, got_kv = out.columns[0].to_numpy(3)
    sums, _ = out.columns[1].to_numpy(3)
    rows = sorted(zip(got_kv, got_k, sums))
    # null group first in Spark ASC ordering of our kernel (rank 0)
    assert rows[0][0] == np.False_ and rows[0][2] == 4.0
    assert (rows[1][1], rows[1][2]) == (base, 4.0)
    assert (rows[2][1], rows[2][2]) == (base + 2, 2.0)


def test_groupby_stats_survive_projection_and_pack():
    """Upload-time int stats flow through a passthrough projection into
    the groupby (packed-key path) without changing results."""
    from spark_rapids_tpu.ops.groupby import key_range_of

    from spark_rapids_tpu.api import Session, col, functions as F
    import pandas as pd

    pdf = pd.DataFrame({"k": np.array([5, 7, 5, 9], dtype=np.int64),
                        "v": [1.0, 2.0, 3.0, 4.0]})
    s = Session()
    df = s.create_dataframe(pdf)
    got = df.group_by("k").agg(F.sum(col("v")).alias("sv")).collect()
    got = got.sort_values("k").reset_index(drop=True)
    assert got["k"].tolist() == [5, 7, 9]
    assert got["sv"].tolist() == [4.0, 2.0, 4.0]

    # and the stats themselves exist at the scan boundary
    from spark_rapids_tpu.execs.interop import host_to_batch
    from spark_rapids_tpu.columnar.batch import Schema

    b = host_to_batch({"k": pdf["k"].to_numpy()}, {},
                      Schema(["k"], [dt.INT64]))
    assert b.columns[0].stats == (5, 9)
    # key ranges are quantized to pow2 spans on an aligned base
    qlo, qhi = key_range_of(b.columns[0], dt.INT64)
    assert qlo <= 5 and 9 <= qhi


def test_quantize_range():
    from spark_rapids_tpu.ops.groupby import quantize_range

    for lo, hi in [(0, 65535), (3, 17), (-7, 9), (100, 100),
                   (5_000_000_000, 5_000_000_001), (-20, -3)]:
        qlo, qhi = quantize_range(lo, hi)
        span = qhi - qlo + 1
        assert qlo <= lo and hi <= qhi
        assert span & (span - 1) == 0  # power-of-two span
        assert span <= 4 * max(hi - lo + 1, 1)
    # stability: nearby batches land on the SAME signature
    assert quantize_range(3, 17) == quantize_range(2, 16)
    assert quantize_range(0, 65535) == (0, 65535)


def test_derive_stats_through_projection():
    """Projected keys (k % 4, k + 10, year(d), cast) keep host-known
    ranges so the groupby still packs keys (r2 verdict weak #7)."""
    import jax.numpy as jnp

    from spark_rapids_tpu.expressions import arithmetic as ar
    from spark_rapids_tpu.expressions import datetime as dte
    from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                                   Literal)
    from spark_rapids_tpu.expressions.cast import Cast
    from spark_rapids_tpu.expressions.compiler import derive_stats

    k = Column.from_numpy(np.arange(5, 95, dtype=np.int64))
    k.stats = (5, 94)
    d = Column.from_numpy(np.arange(11000, 12000, dtype=np.int32),
                          dtype=dt.DATE)
    d.stats = (11000, 11999)   # 2000-02-14 .. 2002-11-09
    cols = [k, d]
    ref = BoundReference(0, dt.INT64)
    assert derive_stats(ref, cols) == (5, 94)
    assert derive_stats(Alias(ref, "x"), cols) == (5, 94)
    assert derive_stats(ar.Pmod(ref, Literal(4, dt.INT64)), cols) == (0, 3)
    assert derive_stats(ar.Add(ref, Literal(10, dt.INT64)), cols) == \
        (15, 104)
    assert derive_stats(ar.Subtract(Literal(100, dt.INT64), ref),
                        cols) == (6, 95)
    assert derive_stats(ar.Multiply(ref, Literal(-2, dt.INT64)),
                        cols) == (-188, -10)
    assert derive_stats(Cast(ref, dt.INT32), cols) == (5, 94)
    y = derive_stats(dte.Year(BoundReference(1, dt.DATE)), cols)
    assert y == (2000, 2002)
    # non-derivable -> None
    assert derive_stats(ar.Add(ref, ref), cols) is None
    # date<->timestamp casts SCALE units — bounds must not pass through
    assert derive_stats(Cast(BoundReference(1, dt.DATE), dt.TIMESTAMP),
                        cols) is None
    # arithmetic whose bounds exceed the EXPRESSION dtype wraps on
    # device — no stats (r3 review finding)
    k32 = Column.from_numpy(np.arange(0, 60001, 30000, dtype=np.int32),
                            dtype=dt.INT32)
    k32.stats = (0, 60000)
    assert derive_stats(ar.Multiply(BoundReference(0, dt.INT32),
                                    Literal(100000, dt.INT32)),
                        [k32]) is None


def test_parquet_footer_stats_feed_packed_keys(tmp_path):
    """Parquet scans get Column.stats from footer statistics — no
    upload-time host pass — and the groupby packs keys off them."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.io import ParquetSource

    rng = np.random.default_rng(2)
    tdir = tmp_path / "t"
    tdir.mkdir()
    ks = rng.integers(10, 50, 500).astype(np.int64)
    pq.write_table(pa.table({"k": ks, "v": rng.random(500)}),
                   str(tdir / "a.parquet"))
    src = ParquetSource(str(tdir))
    st = src.split_stats(0)
    assert st is not None and st["k"] == (int(ks.min()), int(ks.max()))

    from spark_rapids_tpu.api import Session, col, functions as F

    s = Session()
    s.register_parquet("t", str(tdir))
    df = s.sql("SELECT k, SUM(v) AS sv FROM t GROUP BY k")
    exec_ = df._exec()
    # find the scan output column and check stats arrived
    scan = exec_
    while scan.children:
        scan = scan.children[0]
    b = next(scan.execute(0))
    assert b.columns[0].stats == (int(ks.min()), int(ks.max()))
    got = df.collect().sort_values("k").reset_index(drop=True)
    import pandas as pd

    want = (pd.DataFrame({"k": ks, "v": rng.random(500) * 0 + 1})
            .groupby("k").size())
    assert got["k"].tolist() == sorted(set(ks.tolist()))


def test_groupby_wide_agg_list_chunks():
    """>=7 aggregate columns at capacity >=32768 split into chunks of 6
    (the libtpu AOT segfault workaround, ops/groupby.py _AOT_MAX_AGGS):
    chunked results must be identical to the oracle — every chunk
    re-sorts deterministically so group order matches across chunks."""
    import jax
    import pandas as pd

    from spark_rapids_tpu.ops import groupby as gb

    rng = np.random.default_rng(13)
    cap, n, nagg = 1 << 15, 30_000, 8
    keys = rng.integers(0, 700, cap).astype(np.int64)
    live = np.arange(cap) < n
    cols = [Column(dt.INT64, jnp.asarray(keys), jnp.asarray(live))]
    vals = []
    for i in range(nagg):
        v = rng.integers(-50, 100, cap).astype(np.int64)
        vals.append(v)
        cols.append(Column(dt.INT64, jnp.asarray(v), None))
    b = ColumnarBatch(cols, n)
    aggs = [gb.AggSpec("sum", i + 1) for i in range(nagg)]
    assert nagg > gb._AOT_MAX_AGGS and cap >= gb._AOT_CHUNK_MIN_CAP
    out, _types = gb.groupby_aggregate(b, [0], aggs,
                                       [dt.INT64] * (nagg + 1))
    ng = out.realized_num_rows()
    pdf = pd.DataFrame({"k": keys[:n],
                        **{f"a{i}": vals[i][:n] for i in range(nagg)}})
    want = pdf.groupby("k").sum().sort_index()
    assert ng == len(want)
    k = np.asarray(jax.device_get(out.columns[0].data))[:ng]
    order = np.argsort(k)
    for i in range(nagg):
        got = np.asarray(jax.device_get(out.columns[1 + i].data))[:ng]
        np.testing.assert_array_equal(got[order],
                                      want[f"a{i}"].to_numpy())


# -------------------------------------------------- dense (sort-free) path

def _run_groupby_path(cols, dtypes, key_ords, aggs, n, key_ranges,
                      live_mask=None):
    from spark_rapids_tpu.ops import groupby as gb

    (kd, kv), (ad, av), ng = gb._groupby(
        cols, tuple(dtypes), tuple(key_ords), tuple(aggs), jnp.int32(n),
        live_mask=live_mask, key_ranges=key_ranges)
    ng = int(ng)
    out = {}
    for i in range(len(key_ords)):
        d = np.asarray(kd[i])[:ng].astype(object)
        if kv[i] is not None:
            d[~np.asarray(kv[i])[:ng]] = None
        out[f"k{i}"] = d
    for i in range(len(aggs)):
        d = np.asarray(ad[i])[:ng].astype(object)
        if av[i] is not None:
            d[~np.asarray(av[i])[:ng]] = None
        out[f"a{i}"] = d
    return pd.DataFrame(out), ng


def test_groupby_dense_matches_sort_path_all_ops():
    """The sort-free dense path (host-known key space <= 128 slots) must
    agree with the sort path op-for-op, including null keys, null
    inputs, bool keys, and a fused live-mask. Differential: same inputs
    through both kernels (key_ranges present vs absent), results
    compared after a key sort."""
    from spark_rapids_tpu.ops import groupby as gb

    rng = np.random.default_rng(17)
    cap, n = 2048, 1900
    k1 = rng.integers(10, 15, cap).astype(np.int64)
    k1v = rng.random(cap) > 0.15
    k2 = rng.integers(0, 2, cap).astype(bool)
    x = rng.normal(3.0, 50.0, cap)
    xv = rng.random(cap) > 0.25
    iy = rng.integers(-40, 90, cap).astype(np.int64)
    bz = rng.integers(0, 2, cap).astype(bool)
    bzv = rng.random(cap) > 0.5
    cols = [(jnp.asarray(k1), jnp.asarray(k1v)),
            (jnp.asarray(k2), None),
            (jnp.asarray(x), jnp.asarray(xv)),
            (jnp.asarray(iy), None),
            (jnp.asarray(bz), jnp.asarray(bzv))]
    dtypes = [dt.INT64, dt.BOOLEAN, dt.FLOAT64, dt.INT64, dt.BOOLEAN]
    aggs = [gb.AggSpec("sum", 2), gb.AggSpec("sum", 3),
            gb.AggSpec("sum_of_squares", 2), gb.AggSpec("count", 2),
            gb.AggSpec("count_star"), gb.AggSpec("min", 2),
            gb.AggSpec("max", 3), gb.AggSpec("min", 4),
            gb.AggSpec("max", 4), gb.AggSpec("first", 2),
            gb.AggSpec("last", 3), gb.AggSpec("any_valid", 2),
            gb.AggSpec("m2", 2), gb.AggSpec("rterm", 2)]
    ranges = (gb.quantize_range(10, 14), (0, 1))
    assert gb._dense_layout(dtypes, (0, 1), ranges,
                            (True, False)) is not None
    live = jnp.asarray(rng.random(cap) > 0.2)
    for mask in (None, live):
        dense, ng_d = _run_groupby_path(cols, dtypes, (0, 1), aggs, n,
                                        ranges, live_mask=mask)
        sortp, ng_s = _run_groupby_path(cols, dtypes, (0, 1), aggs, n,
                                        None, live_mask=mask)
        assert ng_d == ng_s and ng_d > 0
        key = ["k0", "k1"]
        dense = dense.sort_values(key, na_position="first",
                                  ignore_index=True)
        sortp = sortp.sort_values(key, na_position="first",
                                  ignore_index=True)
        for c in dense.columns:
            a, b = dense[c].to_numpy(), sortp[c].to_numpy()
            an = np.array([v is None for v in a])
            bn = np.array([v is None for v in b])
            np.testing.assert_array_equal(an, bn, err_msg=c)
            af = np.array([0.0 if v is None else float(v) for v in a])
            bf = np.array([0.0 if v is None else float(v) for v in b])
            np.testing.assert_allclose(af, bf, rtol=1e-9, err_msg=c)


def test_groupby_dense_wide_agg_list_skips_chunking():
    """A wide agg list over a dense-eligible key space must NOT chunk
    (the dense kernel never builds the module the AOT workaround guards
    against) and must match pandas."""
    from spark_rapids_tpu.ops import groupby as gb

    rng = np.random.default_rng(23)
    cap, n, nagg = 1 << 15, 30_000, 9
    keys = rng.integers(0, 5, cap).astype(np.int64)
    cols = [Column(dt.INT64, jnp.asarray(keys), None,
                   stats=(0, 4))]
    vals = []
    for i in range(nagg):
        v = rng.normal(0, 10, cap)
        vals.append(v)
        cols.append(Column(dt.FLOAT64, jnp.asarray(v), None))
    b = ColumnarBatch(cols, n)
    aggs = [gb.AggSpec("sum", i + 1) for i in range(nagg)]
    out, _types = gb.groupby_aggregate(b, [0], aggs,
                                       [dt.INT64] + [dt.FLOAT64] * nagg)
    ng = out.realized_num_rows()
    pdf = pd.DataFrame({"k": keys[:n],
                        **{f"a{i}": vals[i][:n] for i in range(nagg)}})
    want = pdf.groupby("k").sum().sort_index()
    assert ng == len(want)
    import jax

    k = np.asarray(jax.device_get(out.columns[0].data))[:ng]
    order = np.argsort(k)
    for i in range(nagg):
        got = np.asarray(jax.device_get(out.columns[1 + i].data))[:ng]
        np.testing.assert_allclose(got[order], want[f"a{i}"].to_numpy(),
                                   rtol=1e-9)


def test_groupby_dense_string_keys_and_empty():
    """String keys ride the dense path through their dictionary range;
    an all-dead batch yields zero groups."""
    s = ["b", "a", "b", None, "c", "a"]
    v = np.array([1.0, 2.0, 3.0, 4.0, 5.0, 6.0])
    batch = make_batch(np.asarray(s, dtype=object), v)
    out, _ = groupby.groupby_aggregate(batch, [0], [AggSpec("sum", 1)],
                                       [dt.STRING, dt.FLOAT64])
    df = out.to_pandas()
    df.columns = ["k", "s"]
    df = df.sort_values("k", na_position="first").reset_index(drop=True)
    assert df["s"].tolist() == [4.0, 8.0, 4.0, 5.0]
    assert df["k"].tolist()[1:] == ["a", "b", "c"]
    empty = make_batch(np.asarray(["x", "y"], dtype=object),
                       np.array([1.0, 2.0]), n=0)
    out2, _ = groupby.groupby_aggregate(empty, [0], [AggSpec("sum", 1)],
                                        [dt.STRING, dt.FLOAT64])
    assert out2.realized_num_rows() == 0
