"""SPMD in-program shuffle: differential oracles, the host-path
equivalence contract, fallback-reason recording, and the zero-hidden-
sync plan map.

The tentpole's correctness story is three-way agreement: the SAME
relational work must produce identical results on (a) the multi-device
mesh with in-program ``all_to_all`` exchanges, (b) the single-process
device path, and (c) the pandas CPU oracle — across 1/2/8 shards,
uneven partition sizes, and shards that receive zero rows. CPU CI
provides the 8 virtual devices via ``xla_force_host_platform_device_
count`` (conftest).
"""
import subprocess
import sys

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import Session
from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import col
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.parallel import spmd


def _mesh_session(n_dev, extra=None):
    conf = {"rapids.tpu.mesh.enabled": True,
            "rapids.tpu.mesh.devices": n_dev}
    conf.update(extra or {})
    return Session(conf)


def _normalize(df, sort_cols):
    out = df.sort_values(sort_cols, na_position="last") \
        .reset_index(drop=True)
    return out


def _assert_triple(mesh_df, plain_df, oracle_df, sort_cols):
    """mesh == single-device == CPU oracle, column by column."""
    assert len(mesh_df) == len(plain_df) == len(oracle_df)
    m = _normalize(mesh_df, sort_cols)
    p = _normalize(plain_df, sort_cols)
    o = _normalize(oracle_df, sort_cols)
    for ci in range(len(m.columns)):
        g = m.iloc[:, ci].to_numpy(np.float64)
        for other, tag in ((p, "single-device"), (o, "cpu-oracle")):
            w = other.iloc[:, ci].to_numpy(np.float64)
            np.testing.assert_allclose(
                g, w, rtol=1e-9, equal_nan=True,
                err_msg=f"col {m.columns[ci]} vs {tag}")


# ---------------------------------------------------------------------------
# differential oracles: group-by / hash join / sort across shard counts
# ---------------------------------------------------------------------------

# 997 rows: deliberately not divisible by any mesh size, so every
# shard count exercises uneven per-device partitions
_N = 997


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_groupby_mesh_matches_single_and_cpu(n_dev):
    rng = np.random.default_rng(101 + n_dev)
    df = pd.DataFrame({
        "k": pd.array([None if x == 0 else int(x)
                       for x in rng.integers(0, 37, _N)], dtype="Int64"),
        "v": rng.random(_N),
    })

    def run(sess):
        out = sess.create_dataframe(df).group_by("k").agg(
            F.sum(col("v")).alias("s"), F.count("*").alias("n"))
        return out.collect()

    got = run(_mesh_session(n_dev))
    want = run(Session({}))
    oracle = (df.groupby("k", dropna=False)["v"]
              .agg(["sum", "size"]).reset_index())
    oracle.columns = ["k", "s", "n"]
    _assert_triple(got, want, oracle, ["k"])


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_hash_join_mesh_matches_single_and_cpu(n_dev):
    rng = np.random.default_rng(211 + n_dev)
    left = pd.DataFrame({
        "k": rng.integers(0, 53, _N).astype(np.int64),
        "v": rng.random(_N),
    })
    right = pd.DataFrame({
        "k2": rng.integers(20, 80, 311).astype(np.int64),
        "w": rng.random(311),
    })

    def run(sess):
        return sess.create_dataframe(left).join(
            sess.create_dataframe(right), on=[("k", "k2")],
            how="inner").collect()

    got = run(_mesh_session(n_dev))
    want = run(Session({}))
    oracle = left.merge(right, left_on="k", right_on="k2", how="inner")
    oracle = oracle[["k", "v", "k2", "w"]]
    _assert_triple(got, want, oracle, ["k", "v", "w"])


@pytest.mark.parametrize("n_dev", [1, 2, 8])
def test_sort_mesh_matches_single_and_cpu(n_dev):
    rng = np.random.default_rng(307 + n_dev)
    df = pd.DataFrame({
        "a": rng.integers(0, 60, _N).astype(np.int64),
        "b": rng.random(_N),
    })

    def run(sess):
        return sess.create_dataframe(df).order_by(
            "a", "b", ascending=[True, False]).collect()

    got = run(_mesh_session(n_dev))
    want = run(Session({}))
    oracle = df.sort_values(["a", "b"], ascending=[True, False]) \
        .reset_index(drop=True)
    # ORDER BY compares positionally: no re-sort before comparing
    for c in ("a", "b"):
        np.testing.assert_allclose(got[c].to_numpy(np.float64),
                                   want[c].to_numpy(np.float64),
                                   rtol=1e-9)
        np.testing.assert_allclose(got[c].to_numpy(np.float64),
                                   oracle[c].to_numpy(np.float64),
                                   rtol=1e-9)


def test_empty_partition_shards_match():
    """Fewer rows than devices: most mesh positions receive ZERO rows
    and the collectives must still line up (the all_to_all ships empty
    blocks + zero counts, not ragged shapes)."""
    df = pd.DataFrame({
        "k": np.array([3, 3, 7, 11, 7], dtype=np.int64),
        "v": np.array([0.5, 1.5, 2.5, 3.5, 4.5]),
    })

    def run(sess):
        return sess.create_dataframe(df).group_by("k").agg(
            F.sum(col("v")).alias("s"),
            F.count("*").alias("n")).collect()

    got = run(_mesh_session(8))
    want = run(Session({}))
    oracle = df.groupby("k")["v"].agg(["sum", "size"]).reset_index()
    oracle.columns = ["k", "s", "n"]
    _assert_triple(got, want, oracle, ["k"])


def test_skewed_keys_uneven_shards_match():
    """One hot key: after hash routing one device owns most rows while
    others are near-empty — per-device receive capacities and counts
    must absorb the skew."""
    rng = np.random.default_rng(43)
    k = np.where(rng.random(_N) < 0.8, 5,
                 rng.integers(0, 29, _N)).astype(np.int64)
    df = pd.DataFrame({"k": k, "v": rng.random(_N)})

    def run(sess):
        return sess.create_dataframe(df).group_by("k").agg(
            F.sum(col("v")).alias("s")).collect()

    got = run(_mesh_session(8))
    want = run(Session({}))
    oracle = df.groupby("k")["v"].sum().reset_index()
    oracle.columns = ["k", "s"]
    _assert_triple(got, want, oracle, ["k"])


# ---------------------------------------------------------------------------
# ShuffleExchangeExec: in-program mode is partition-for-partition
# interchangeable with the host path
# ---------------------------------------------------------------------------


def _rows_exec(parts):
    """A leaf exec yielding fixed in-memory batches per partition
    (``parts``: list of (keys, key_valid, vals) per input partition;
    an empty list means that partition produces nothing)."""
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.execs.base import TpuExec

    class _Rows(TpuExec):
        def __init__(self):
            super().__init__([], Schema(["k", "v"],
                                        [dt.INT64, dt.FLOAT64]))

        @property
        def num_partitions(self):
            return len(parts)

        def execute(self, partition=0):
            for keys, kv, vals in parts[partition]:
                yield ColumnarBatch(
                    [Column.from_numpy(keys, dt.INT64, validity=kv),
                     Column.from_numpy(vals, dt.FLOAT64)], len(keys))

    return _Rows()


def _drain_exchange(ex):
    """partition -> multiset of (key_or_None, value) rows."""
    out = {}
    for p in range(ex.num_out_partitions):
        rows = []
        for b in ex.execute(p):
            pdf = b.to_pandas()
            for _, r in pdf.iterrows():
                key = r.iloc[0]
                key = None if pd.isna(key) else int(key)
                rows.append((key, float(r.iloc[1])))
        out[p] = sorted(rows, key=lambda t: (t[0] is None, t[0], t[1]))
    return out


def test_exchange_in_program_matches_host_path():
    """NUM_OUT != n_dev, null keys, an empty input partition: the
    in-program exchange must land every row in EXACTLY the partition
    the host partition kernel picks — the contract that lets one
    sibling of a co-partitioned join flip in-program while the other
    stays on the host path."""
    from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.parallel.mesh import data_mesh

    rng = np.random.default_rng(59)

    def mk(n):
        keys = rng.integers(-40, 40, n).astype(np.int64)
        kv = rng.random(n) > 0.15  # null keys hash via _NULL_HASH
        vals = rng.random(n)
        return keys, kv, vals

    parts = [[mk(37), mk(23)], [], [mk(41)]]
    num_out = 5  # != 8 devices: pids wrap the mesh axis

    host = ShuffleExchangeExec(("hash", [0]), num_out, _rows_exec(parts))
    want = _drain_exchange(host)

    prog = ShuffleExchangeExec(("hash", [0]), num_out, _rows_exec(parts))
    prog.enable_in_program(data_mesh(8))
    got = _drain_exchange(prog)

    assert prog.in_program
    for p in range(num_out):
        assert got[p] == want[p], f"partition {p} diverged"
    # MapStatus sizes answer from the same blocks on both paths
    assert len(host.map_output_sizes()) == \
        len(prog.map_output_sizes()) == num_out


def test_exchange_in_program_all_rows_one_device():
    """Every key hashes to one pid: 7 of 8 devices receive nothing and
    one receives everything — the receive capacity must hold the full
    input (the _exchange cap covers worst-case skew)."""
    from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.parallel.mesh import data_mesh

    n = 193
    keys = np.full(n, 12345, dtype=np.int64)
    kv = np.ones(n, dtype=bool)
    vals = np.arange(n, dtype=np.float64)
    parts = [[(keys, kv, vals)]]

    host = ShuffleExchangeExec(("hash", [0]), 4, _rows_exec(parts))
    want = _drain_exchange(host)
    prog = ShuffleExchangeExec(("hash", [0]), 4, _rows_exec(parts))
    prog.enable_in_program(data_mesh(8))
    got = _drain_exchange(prog)
    assert got == want
    total = sum(len(v) for v in got.values())
    assert total == n


# ---------------------------------------------------------------------------
# fallback gates: every "no" is recorded with its reason
# ---------------------------------------------------------------------------


def test_fallback_disabled_knob():
    conf = RapidsConf({cfg.MESH_ENABLED.key: True,
                       cfg.SHUFFLE_IN_PROGRAM.key: False})
    before = spmd.fallback_snapshot()
    assert spmd.in_program_mesh(conf, "join") is None
    delta = spmd.fallback_delta(before)
    assert delta == {
        f"join: disabled by {cfg.SHUFFLE_IN_PROGRAM.key}": 1}


def test_fallback_cluster_mode_dcn():
    conf = RapidsConf({cfg.MESH_ENABLED.key: True,
                       cfg.CLUSTER_ENABLED.key: True})
    before = spmd.fallback_snapshot()
    assert spmd.in_program_mesh(conf, "exchange") is None
    (reason,) = spmd.fallback_delta(before)
    assert reason.startswith("exchange: cross-host DCN")


def test_fallback_non_uniform_reason_passthrough():
    conf = RapidsConf({cfg.MESH_ENABLED.key: True})
    before = spmd.fallback_snapshot()
    assert spmd.in_program_mesh(
        conf, "sort", keyed=False,
        reason_if_unkeyed="range partitioning routes host-side") is None
    (reason,) = spmd.fallback_delta(before)
    assert reason == ("sort: non-uniform: range partitioning routes "
                      "host-side")


def test_fallback_min_rows_floor():
    conf = RapidsConf({cfg.MESH_ENABLED.key: True,
                       cfg.SHUFFLE_IN_PROGRAM_MIN_ROWS.key: 1000})
    before = spmd.fallback_snapshot()
    assert spmd.in_program_mesh(conf, "groupby", est_rows=10) is None
    (reason,) = spmd.fallback_delta(before)
    assert "below" in reason and "10 < 1000" in reason
    # at/above the floor the mesh comes back
    assert spmd.in_program_mesh(conf, "groupby",
                                est_rows=5000) is not None


def test_fallback_mesh_not_requested_is_silent():
    """No mesh, no decision: nothing recorded (a single-device run must
    not spam 'fewer than 2 devices' for every exchange)."""
    before = spmd.fallback_snapshot()
    assert spmd.in_program_mesh(RapidsConf({}), "join") is None
    assert spmd.in_program_mesh(None, "join") is None
    assert spmd.fallback_delta(before) == {}


def test_override_walk_flips_only_eligible_exchanges():
    """plan/overrides._enable_in_program_exchanges: hash+numeric flips,
    string schema records its reason, disabled knob records its reason
    — and with no mesh nothing happens."""
    from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.plan.overrides import \
        _enable_in_program_exchanges

    def mk_ex():
        return ShuffleExchangeExec(("hash", [0]),
                                   4, _rows_exec([[]]))

    ex = mk_ex()
    _enable_in_program_exchanges(ex, RapidsConf({}))
    assert not ex.in_program  # no mesh requested

    conf = RapidsConf({cfg.MESH_ENABLED.key: True})
    ex = mk_ex()
    _enable_in_program_exchanges(ex, conf)
    assert ex.in_program and ex._in_program_mesh is not None

    before = spmd.fallback_snapshot()
    ex = mk_ex()
    off = RapidsConf({cfg.MESH_ENABLED.key: True,
                      cfg.SHUFFLE_IN_PROGRAM.key: False})
    _enable_in_program_exchanges(ex, off)
    assert not ex.in_program
    (reason,) = spmd.fallback_delta(before)
    assert "disabled" in reason


def test_override_walk_string_schema_falls_back():
    from spark_rapids_tpu.columnar import dtypes as dt
    from spark_rapids_tpu.columnar.batch import Schema
    from spark_rapids_tpu.execs.base import TpuExec
    from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.plan.overrides import \
        _enable_in_program_exchanges

    class _StrLeaf(TpuExec):
        def __init__(self):
            super().__init__([], Schema(["k", "s"],
                                        [dt.INT64, dt.STRING]))

    ex = ShuffleExchangeExec(("hash", [0]), 4, _StrLeaf())
    before = spmd.fallback_snapshot()
    _enable_in_program_exchanges(
        ex, RapidsConf({cfg.MESH_ENABLED.key: True}))
    assert not ex.in_program
    (reason,) = spmd.fallback_delta(before)
    assert "string" in reason


# ---------------------------------------------------------------------------
# telemetry: the distributed stage attributes ONE launch with a program
# label naming the shuffle step
# ---------------------------------------------------------------------------


_TELEMETRY_SNIPPET = r"""
import json
import numpy as np
from spark_rapids_tpu.utils import dispatch as disp
disp.install()  # must precede compute-module imports (wraps jax.jit)
import spark_rapids_tpu  # noqa: F401
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.parallel.mesh import data_mesh
from spark_rapids_tpu.parallel.shuffle import (
    distributed_batch_from_host, shuffle_step)

mesh = data_mesh(8)
rng = np.random.default_rng(3)
keys = rng.integers(0, 100, 500).astype(np.int64)
vals = rng.random(500)
datas, valids, counts, _ = distributed_batch_from_host(
    mesh, [keys, vals], [dt.INT64, dt.FLOAT64])
before = disp.stage_programs_snapshot()
step = shuffle_step(mesh, [dt.INT64, dt.FLOAT64], [0], 8)
out = step(datas, valids, counts)
import jax
jax.device_get(out[3])
print(json.dumps(disp.stage_program_delta(before)))
"""


def test_shuffle_step_program_label_attributed():
    import json
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", _TELEMETRY_SNIPPET], env=env,
        capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-3000:]
    delta = json.loads(out.stdout.strip().splitlines()[-1])
    labels = [lab for stage in delta.values() for lab in stage]
    assert any("_run_shuffle_step" in lab for lab in labels), delta
    # one compiled launch for the exchange, one device_get to read it
    jit_launches = sum(
        n for stage in delta.values() for lab, n in stage.items()
        if "_run_shuffle_step" in lab)
    assert jit_launches == 1, delta


# ---------------------------------------------------------------------------
# plan-level sync map: the in-program path has ZERO hidden host syncs
# ---------------------------------------------------------------------------


def test_mesh_plan_sync_map_names_every_sync():
    """Every sync a mesh plan pays is a NAMED boundary entry (leaf
    staging / result gather / root fetch); mesh-internal execs — whose
    exchanges run as in-program all_to_all — contribute nothing."""
    from spark_rapids_tpu.analysis.plan_sync import sync_map

    rng = np.random.default_rng(71)
    li = pd.DataFrame({
        "l_orderkey": rng.integers(0, 300, 2000).astype(np.int64),
        "l_quantity": rng.integers(1, 50, 2000).astype(np.int64),
    })
    ords = pd.DataFrame({
        "o_orderkey": np.arange(300, dtype=np.int64),
        "o_pri": rng.integers(0, 3, 300).astype(np.int64),
    })
    sess = _mesh_session(8, {"rapids.tpu.sql.autoBroadcastJoinThreshold": 0})
    sess.create_temp_view("lineitem", sess.create_dataframe(li))
    sess.create_temp_view("orders", sess.create_dataframe(ords))
    root = sess.sql(
        "SELECT o_pri, l_orderkey, SUM(l_quantity) AS q "
        "FROM lineitem JOIN orders ON l_orderkey = o_orderkey "
        "GROUP BY o_pri, l_orderkey "
        "ORDER BY q DESC, o_pri, l_orderkey")._exec()
    plan = root.tree_string()
    assert "MeshShuffledJoinExec" in plan, plan
    assert "MeshGroupByExec" in plan, plan

    entries = sync_map(root)
    named = {"duplicate-flag fetch", "result fetch",
             "mesh shard staging (leaf input)", "mesh result gather",
             "mesh exchange map-side staging"}
    for e in entries:
        assert e["kind"] in named, e

    mesh_entries = [e for e in entries if e["op"].startswith("Mesh")]
    # gathers appear EXACTLY at mesh->host boundaries (a mesh exec
    # whose consumer is non-mesh); a mesh exec feeding a mesh parent
    # hands DistributedBatch shards on-device and never gathers
    def walk(node, mesh_parent, out):
        is_mesh = type(node).__name__.startswith("Mesh")
        if is_mesh and not mesh_parent:
            out.append(type(node).__name__)
        for c in node.children:
            walk(c, is_mesh, out)
        return out

    boundary_ops = walk(root, False, [])
    gathers = sorted(e["op"] for e in mesh_entries
                     if e["kind"] == "mesh result gather")
    assert gathers == sorted(boundary_ops), (entries, boundary_ops)
    # the join feeds the mesh groupby directly: mesh-internal, so its
    # exchange is the in-program all_to_all — no gather entry for it
    assert not any(e["op"] == "MeshShuffledJoinExec" and
                   e["kind"] == "mesh result gather"
                   for e in mesh_entries), entries
    assert "MeshShuffledJoinExec" not in boundary_ops, plan
