"""CPU-vs-TPU golden comparison harness.

The reference's core test idea (SparkQueryCompareTestSuite.scala:153-161,
integration_tests asserts.py): run the same plan on the CPU engine (the
oracle) and through the TPU override pipeline, then assert equal results
with knobs for sort-before-compare and float approximation.
"""
import numpy as np
import pandas as pd

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.cpu.engine import execute_cpu
from spark_rapids_tpu.execs.base import collect
from spark_rapids_tpu.plan.overrides import apply_overrides


def _normalize(df: pd.DataFrame, sort: bool) -> pd.DataFrame:
    out = {}
    for c in df.columns:
        s = df[c]
        vals = []
        for v in s:
            if v is None or v is pd.NA:
                vals.append(None)
            elif isinstance(v, (float, np.floating)) and np.isnan(v):
                vals.append(float("nan"))  # NaN is a value, not NULL
            elif isinstance(v, (bool, np.bool_)):
                vals.append(bool(v))
            elif isinstance(v, (int, np.integer)):
                vals.append(int(v))
            elif isinstance(v, (float, np.floating)):
                vals.append(float(v))
            else:
                vals.append(str(v))
        out[c] = vals
    # object dtype everywhere: pandas would otherwise coerce int+None
    # columns to float64/NaN, and NaN poisons row-sort comparisons
    norm = pd.DataFrame(
        {c: pd.Series(v, dtype=object) for c, v in out.items()},
        columns=list(df.columns))
    if sort and len(norm):
        rows = list(zip(*[out[c] for c in df.columns])) if out else []

        def row_key(i):
            return tuple(
                (v is None, "" if v is None else type(v).__name__,
                 isinstance(v, float) and np.isnan(v),
                 0 if v is None or (isinstance(v, float) and np.isnan(v))
                 else v) for v in rows[i])

        order = sorted(range(len(rows)), key=row_key)
        norm = norm.iloc[order]
    return norm.reset_index(drop=True)


def assert_frames_equal(cpu: pd.DataFrame, tpu: pd.DataFrame,
                        sort: bool = True, approx_float: float = 1e-9):
    assert list(cpu.columns) == list(tpu.columns), \
        f"column mismatch: {list(cpu.columns)} vs {list(tpu.columns)}"
    a = _normalize(cpu, sort)
    b = _normalize(tpu, sort)
    assert len(a) == len(b), f"row count: cpu={len(a)} tpu={len(b)}"
    for col in a.columns:
        av, bv = list(a[col]), list(b[col])
        for i, (x, y) in enumerate(zip(av, bv)):
            if x is None or y is None:
                assert x is None and y is None, \
                    f"{col}[{i}]: cpu={x!r} tpu={y!r}"
            elif isinstance(x, float) and isinstance(y, float):
                if np.isnan(x) or np.isnan(y):
                    assert np.isnan(x) and np.isnan(y), \
                        f"{col}[{i}]: cpu={x!r} tpu={y!r}"
                else:
                    assert x == y or \
                        abs(x - y) <= approx_float * max(abs(x), abs(y),
                                                         1.0), \
                        f"{col}[{i}]: cpu={x!r} tpu={y!r}"
            else:
                assert x == y, f"{col}[{i}]: cpu={x!r} tpu={y!r}"


def assert_cpu_and_tpu_equal(plan, conf: RapidsConf = None,
                             sort: bool = True, approx_float: float = 1e-9,
                             require_on_tpu: bool = True):
    """The testSparkResultsAreEqual analogue. ``require_on_tpu`` enables
    the test-mode whole-plan-on-TPU assertion
    (GpuTransitionOverrides.scala:270-326)."""
    conf = conf or RapidsConf()
    if require_on_tpu:
        conf = conf.with_overrides({"rapids.tpu.sql.test.enabled": True})
    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, conf)
    tpu_df = collect(exec_)
    assert_frames_equal(cpu_df, tpu_df, sort=sort,
                        approx_float=approx_float)
    return exec_
