"""CPU-vs-TPU golden comparison harness.

The reference's core test idea (SparkQueryCompareTestSuite.scala:153-161,
integration_tests asserts.py): run the same plan on the CPU engine (the
oracle) and through the TPU override pipeline, then assert equal results
with knobs for sort-before-compare and float approximation.
"""
import numpy as np
import pandas as pd

from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.cpu.engine import execute_cpu
from spark_rapids_tpu.execs.base import collect
from spark_rapids_tpu.plan.overrides import apply_overrides


def _normalize(df: pd.DataFrame, sort: bool,
               approx_float: float = 1e-9) -> pd.DataFrame:
    out = {}
    for c in df.columns:
        s = df[c]
        vals = []
        for v in s:
            if v is None or v is pd.NA:
                vals.append(None)
            elif isinstance(v, (float, np.floating)) and np.isnan(v):
                vals.append(float("nan"))  # NaN is a value, not NULL
            elif isinstance(v, (bool, np.bool_)):
                vals.append(bool(v))
            elif isinstance(v, (int, np.integer)):
                vals.append(int(v))
            elif isinstance(v, (float, np.floating)):
                vals.append(float(v))
            else:
                vals.append(str(v))
        out[c] = vals
    # object dtype everywhere: pandas would otherwise coerce int+None
    # columns to float64/NaN, and NaN poisons row-sort comparisons
    norm = pd.DataFrame(
        {c: pd.Series(v, dtype=object) for c, v in out.items()},
        columns=list(df.columns))
    if sort and len(norm):
        rows = list(zip(*[out[c] for c in df.columns])) if out else []
        # floats sort by a tolerance-rounded key: two engines may
        # legally differ in the last ulps (within approx_float), and
        # raw-value sorting would then align DIFFERENT rows of frames
        # holding the same row set (q67's rank-over-near-tied-sums
        # shape). Ties in the rounded key are broken by the row's
        # other columns as usual.
        sig = max(3, int(round(-np.log10(max(approx_float,
                                             1e-15)))) - 1)

        def fkey(v):
            if not isinstance(v, float) or np.isnan(v) or \
                    not np.isfinite(v):
                return v
            return float(f"{v:.{sig}g}")

        def row_key(i):
            # rounded key first (aligns legal last-ulp divergence),
            # RAW value second (rows whose floats genuinely differ by
            # more than the tolerance still order consistently in both
            # frames instead of falling back to frame order)
            return tuple(
                (v is None, "" if v is None else type(v).__name__,
                 isinstance(v, float) and np.isnan(v),
                 0 if v is None or (isinstance(v, float) and np.isnan(v))
                 else fkey(v),
                 0 if v is None or (isinstance(v, float) and np.isnan(v))
                 else v) for v in rows[i])

        order = sorted(range(len(rows)), key=row_key)
        norm = norm.iloc[order]
    return norm.reset_index(drop=True)


def _check_rank_semantics(df: pd.DataFrame, rank_col: str,
                          part_cols, float_col: str,
                          approx_float: float) -> None:
    """Within each partition, the rank column must order the float
    column monotonically (DESC, within tolerance) and tie consistently:
    equal ranks imply equal floats. Used instead of cross-engine rank
    equality for rank()-over-float-aggregate queries, where two engines
    may legally round same-set sums to different last ulps and so break
    ties differently (the reference documents the same float-agg
    nondeterminism — its variableFloatAgg opt-in exists for this)."""
    for _, g in df.groupby(part_cols, dropna=False):
        g = g.sort_values(rank_col)
        rk = g[rank_col].to_numpy()
        fv = g[float_col].astype(float).to_numpy()
        assert (rk >= 1).all(), f"{rank_col}: rank < 1"
        for i in range(1, len(g)):
            tol = approx_float * max(abs(fv[i - 1]), abs(fv[i]), 1.0)
            if rk[i] == rk[i - 1]:
                assert abs(fv[i] - fv[i - 1]) <= tol, \
                    f"{rank_col}: tied ranks with different {float_col}"
            else:
                assert rk[i] > rk[i - 1]
                assert fv[i] <= fv[i - 1] + tol, \
                    f"{rank_col}: rank order violates {float_col} DESC"
        # bit-identical floats within THIS engine's frame must share a
        # rank — catches a kernel regressing rank() to row_number()
        # (ties always split) without needing the cross-engine bits
        seen = {}
        for r, v in zip(rk, fv):
            bits = np.float64(v).tobytes()
            if bits in seen:
                assert seen[bits] == r, \
                    f"{rank_col}: equal {float_col} bits, ranks " \
                    f"{seen[bits]} != {r} (rank() should tie)"
            else:
                seen[bits] = r


def assert_frames_equal(cpu: pd.DataFrame, tpu: pd.DataFrame,
                        sort: bool = True, approx_float: float = 1e-9,
                        rank_over: dict = None):
    assert list(cpu.columns) == list(tpu.columns), \
        f"column mismatch: {list(cpu.columns)} vs {list(tpu.columns)}"
    if rank_over:
        for rcol, (pcols, fcol) in rank_over.items():
            _check_rank_semantics(cpu, rcol, pcols, fcol, approx_float)
            _check_rank_semantics(tpu, rcol, pcols, fcol, approx_float)
        drop = list(rank_over)
        cpu = cpu.drop(columns=drop)
        tpu = tpu.drop(columns=drop)
    a = _normalize(cpu, sort, approx_float)
    b = _normalize(tpu, sort, approx_float)
    assert len(a) == len(b), f"row count: cpu={len(a)} tpu={len(b)}"
    for col in a.columns:
        av, bv = list(a[col]), list(b[col])
        for i, (x, y) in enumerate(zip(av, bv)):
            if x is None or y is None:
                assert x is None and y is None, \
                    f"{col}[{i}]: cpu={x!r} tpu={y!r}"
            elif isinstance(x, float) and isinstance(y, float):
                if np.isnan(x) or np.isnan(y):
                    assert np.isnan(x) and np.isnan(y), \
                        f"{col}[{i}]: cpu={x!r} tpu={y!r}"
                else:
                    assert x == y or \
                        abs(x - y) <= approx_float * max(abs(x), abs(y),
                                                         1.0), \
                        f"{col}[{i}]: cpu={x!r} tpu={y!r}"
            else:
                assert x == y, f"{col}[{i}]: cpu={x!r} tpu={y!r}"


def assert_cpu_and_tpu_equal(plan, conf: RapidsConf = None,
                             sort: bool = True, approx_float: float = 1e-9,
                             require_on_tpu: bool = True,
                             rank_over: dict = None):
    """The testSparkResultsAreEqual analogue. ``require_on_tpu`` enables
    the test-mode whole-plan-on-TPU assertion
    (GpuTransitionOverrides.scala:270-326)."""
    conf = conf or RapidsConf()
    if require_on_tpu:
        conf = conf.with_overrides({"rapids.tpu.sql.test.enabled": True})
    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, conf)
    tpu_df = collect(exec_)
    assert_frames_equal(cpu_df, tpu_df, sort=sort,
                        approx_float=approx_float, rank_over=rank_over)
    return exec_
