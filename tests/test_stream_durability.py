"""Streaming durability (PR 19): checkpoint + ingest WAL + exactly-once
restart recovery. The load-bearing fences:

- EXACTLY-ONCE: stop a durable session (suspend + final checkpoint),
  start a fresh one against the same checkpoint dir, re-create the
  table (WAL replay) and re-register the query (checkpoint restore):
  every delta folds exactly once ACROSS the restart, and the answer is
  bit-exact against the batch oracle over all appended data.
- TORN ARTIFACTS: a checkpoint that lost its atomic rename is rejected
  on CRC and recovery falls back — older checkpoint, then full WAL
  refold. A WAL record torn at the TAIL is truncated and tolerated; a
  bad record MID-log (valid data after it) raises a loud
  WalCorruptionError — never silent data loss.
- ACCOUNTING: in-flight durability bytes (unsynced WAL, queued async
  checkpoint blobs) charge the service admission budget; every
  recovery surface has a counter.

The SIGKILL (kill -9 mid-fold) variant of the exactly-once fence needs
a real process death and lives in scripts/stream_durability_check.py
(recorded as STREAM_r02.json).
"""
import os
import struct
import threading
import zlib

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.api import Session
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.memory.catalog import SpillCorruptionError
from spark_rapids_tpu.service.streaming import stats as sstats
from spark_rapids_tpu.service.streaming.durability import (
    CheckpointStore, StreamWal, WalCorruptionError, safe_name)
from spark_rapids_tpu.service.streaming.standing import (EMITTING, FAILED,
                                                         SUSPENDED)
from spark_rapids_tpu.service.types import QueryCancelled
from spark_rapids_tpu.shuffle.fault_injection import get_injector

from tests.compare import assert_frames_equal

SCHEMA = Schema(["k", "v"], [dt.INT64, dt.INT64])
AGG_SQL = ("SELECT k, SUM(v) AS sv, COUNT(v) AS c "
           "FROM events GROUP BY k")


@pytest.fixture(autouse=True)
def _clean_injector():
    get_injector().disarm()
    yield
    get_injector().disarm()


def _batch(seed, n=300, nk=9):
    rng = np.random.default_rng(seed)
    return {"k": rng.integers(0, nk, n).astype(np.int64),
            "v": rng.integers(0, 1000, n).astype(np.int64)}


def _oracle(nbatches, **kw):
    frame = pd.concat([pd.DataFrame(_batch(i, **kw))
                       for i in range(nbatches)], ignore_index=True)
    return frame.groupby("k").agg(
        sv=("v", "sum"), c=("v", "count")).reset_index()


def _durable_session(tmp_path, **extra):
    conf = {cfg.STREAMING_CHECKPOINT_DIR.key: str(tmp_path / "ckpt")}
    conf.update(extra)
    s = Session(conf)
    src = s.create_streaming_table("events", SCHEMA)
    return s, src


# -- WAL unit fences --------------------------------------------------------


def test_wal_roundtrip(tmp_path):
    wal = StreamWal(str(tmp_path))
    for i in range(3):
        b = _batch(i, n=50)
        wal.append(i, b, {}, 50)
    wal.close()
    records = StreamWal(str(tmp_path)).replay()
    assert [r[0] for r in records] == [0, 1, 2]
    for i, (_seq, data, _validity, n) in enumerate(records):
        assert n == 50
        np.testing.assert_array_equal(data["k"], _batch(i, n=50)["k"])


def test_wal_torn_tail_tolerated(tmp_path):
    wal = StreamWal(str(tmp_path))
    for i in range(3):
        wal.append(i, _batch(i, n=40), {}, 40)
    wal.close()
    size = os.path.getsize(wal.path)
    with open(wal.path, "r+b") as fh:
        fh.truncate(size - 7)  # rip the last record mid-body
    pre = sstats.snapshot()
    wal2 = StreamWal(str(tmp_path))
    records = wal2.replay()
    assert [r[0] for r in records] == [0, 1]
    assert sstats.delta(pre)["torn_rejected"] == 1
    # the torn bytes are gone: appends continue cleanly after them
    wal2.append(2, _batch(2, n=40), {}, 40)
    wal2.close()
    assert [r[0] for r in StreamWal(str(tmp_path)).replay()] == [0, 1, 2]


def test_wal_midlog_corruption_is_loud(tmp_path):
    wal = StreamWal(str(tmp_path))
    for i in range(3):
        wal.append(i, _batch(i, n=40), {}, 40)
    wal.close()
    with open(wal.path, "r+b") as fh:
        fh.seek(30)  # inside the FIRST record's body
        fh.write(b"\xff\xfe")
    with pytest.raises(WalCorruptionError, match="mid-log"):
        StreamWal(str(tmp_path)).replay()


def test_wal_undecodable_record_chains_cause(tmp_path):
    """A record that passes CRC but fails to decode is corruption with
    the underlying error CHAINED — the SpillCorruptionError idiom, so
    the log says what actually broke."""
    wal = StreamWal(str(tmp_path))
    wal.append(0, _batch(0, n=10), {}, 10)
    wal.close()
    body = b"not a pickle at all"
    with open(wal.path, "ab") as fh:
        fh.write(struct.pack("<II", len(body), zlib.crc32(body)) + body)
    with pytest.raises(WalCorruptionError) as ei:
        StreamWal(str(tmp_path)).replay()
    assert isinstance(ei.value, SpillCorruptionError)
    assert ei.value.__cause__ is not None


def test_wal_truncate_injection_models_torn_tail(tmp_path):
    """The truncateWalAt ordinal persists half a record's frame; the
    NEXT replay truncates it off and keeps everything before it."""
    wal = StreamWal(str(tmp_path))
    wal.append(0, _batch(0, n=30), {}, 30)
    get_injector().arm(truncate_wal_at=1)
    wal.append(1, _batch(1, n=30), {}, 30)  # torn mid-write
    get_injector().disarm()
    wal.close()
    assert get_injector().stats()["armed"] is False
    records = StreamWal(str(tmp_path)).replay()
    assert [r[0] for r in records] == [0]


# -- checkpoint store unit fences -------------------------------------------


def test_checkpoint_roundtrip_and_retention(tmp_path):
    store = CheckpointStore(str(tmp_path), retain=2)
    for i in range(5):
        store.write({"cursor": i}, f"payload{i}".encode())
    meta, payload = store.load_latest()
    assert meta["cursor"] == 4 and payload == b"payload4"
    # retention keeps the newest 2 of the 5
    assert store.checkpoint_count() == 2


def test_checkpoint_torn_falls_back_to_older(tmp_path):
    store = CheckpointStore(str(tmp_path), retain=4)
    store.write({"cursor": 1}, b"older")
    get_injector().arm(torn_checkpoint_at=1)
    store.write({"cursor": 2}, b"newer-but-torn")
    get_injector().disarm()
    pre = sstats.snapshot()
    meta, payload = store.load_latest()
    assert meta["cursor"] == 1 and payload == b"older"
    assert sstats.delta(pre)["torn_rejected"] == 1
    # seq allocation continues past the torn file
    store.write({"cursor": 3}, b"newest")
    assert store.load_latest()[0]["cursor"] == 3


def test_safe_name_collision_free():
    a, b = safe_name("ev/nts"), safe_name("ev:nts")
    assert a != b and "/" not in a and ":" not in b


# -- restart recovery (exactly-once) ----------------------------------------


def test_restart_recovers_exactly_once(tmp_path):
    """Stop -> new Session -> replay + restore -> continue: every delta
    folds exactly once across the restart, counters tell the story."""
    s, src = _durable_session(tmp_path)
    sq = s.service.register_standing(s.sql(AGG_SQL), name="q")
    for i in range(4):
        s.append_batch("events", _batch(i))
    assert sq.folds == 4
    s.stop()
    assert sq.state == SUSPENDED
    with pytest.raises(QueryCancelled, match="suspended"):
        sq.results()

    pre = sstats.snapshot()
    s2, src2 = _durable_session(tmp_path)
    assert src2.num_appends == 4  # WAL replay rebuilt the table
    assert s2.service.recovery_report["tables"]
    sq2 = s2.service.register_standing(s2.sql(AGG_SQL), name="q")
    assert sq2.state == EMITTING
    assert sq2.folds == 4  # restored, NOT refolded
    for i in range(4, 6):
        s2.append_batch("events", _batch(i))
    assert sq2.folds == 6
    assert_frames_equal(_oracle(6), sq2.results())
    # the batch engine over the replayed table is the same oracle
    assert_frames_equal(s2.sql(AGG_SQL).to_pandas(), sq2.results())
    d = sstats.delta(pre)
    assert d["wal_replays"] == 1 and d["recoveries"] == 1
    assert d["folds"] == 2  # exactly the post-restart deltas
    s2.stop()


def test_restart_without_checkpoint_refolds_from_wal(tmp_path):
    """Every checkpoint torn -> recovery rejects them all and falls
    back to a full refold of the replayed WAL — still bit-exact."""
    s, _src = _durable_session(tmp_path)
    get_injector().arm(torn_checkpoint_at=1, consecutive=10 ** 6)
    sq = s.service.register_standing(s.sql(AGG_SQL), name="q")
    for i in range(3):
        s.append_batch("events", _batch(i))
    s.stop()  # the final checkpoint tears too
    get_injector().disarm()
    assert sq.state == SUSPENDED

    pre = sstats.snapshot()
    s2, src2 = _durable_session(tmp_path)
    sq2 = s2.service.register_standing(s2.sql(AGG_SQL), name="q")
    d = sstats.delta(pre)
    assert d["torn_rejected"] >= 1 and d["recoveries"] == 0
    assert sq2.folds == 3  # full refold of the WAL deltas
    assert_frames_equal(_oracle(3), sq2.results())
    s2.stop()


def test_changed_plan_signature_refolds(tmp_path):
    """A checkpoint from a DIFFERENT query shape must not be adopted
    under the same name — signature mismatch falls back to refold."""
    s, _src = _durable_session(tmp_path)
    s.service.register_standing(s.sql(AGG_SQL), name="q")
    for i in range(2):
        s.append_batch("events", _batch(i))
    s.stop()

    s2, _src2 = _durable_session(tmp_path)
    other = "SELECT k, SUM(v) AS total FROM events GROUP BY k"
    pre = sstats.snapshot()
    sq2 = s2.service.register_standing(s2.sql(other), name="q")
    assert sstats.delta(pre)["recoveries"] == 0
    assert sq2.folds == 2  # refolded, not restored
    oracle = pd.concat([pd.DataFrame(_batch(i)) for i in range(2)],
                       ignore_index=True).groupby("k").agg(
        total=("v", "sum")).reset_index()
    assert_frames_equal(oracle, sq2.results())
    s2.stop()


def test_state_overflow_writes_final_checkpoint(tmp_path):
    """maxStateBytes failure parks a RESTARTABLE query: the final
    checkpoint covers the fold that tripped the bound, so a restart
    with a raised budget resumes instead of refolding everything."""
    s, _src = _durable_session(tmp_path)
    pre = sstats.snapshot()
    sq = s.service.register_standing(s.sql(AGG_SQL), name="q",
                                     max_state_bytes=1)
    s.append_batch("events", _batch(0))
    assert sq.state == FAILED
    assert isinstance(sq.error, Exception)
    assert sstats.delta(pre)["final_checkpoints"] == 1
    s.stop()

    pre = sstats.snapshot()
    s2, _src2 = _durable_session(tmp_path)
    sq2 = s2.service.register_standing(s2.sql(AGG_SQL), name="q")
    assert sstats.delta(pre)["recoveries"] == 1
    assert sq2.folds == 1  # the overflowed fold is NOT refolded
    s2.append_batch("events", _batch(1))
    assert_frames_equal(_oracle(2), sq2.results())
    s2.stop()


def test_concurrent_ingest_during_checkpoint(tmp_path):
    """Threaded ingest with per-fold async checkpoints: the sequence
    cursor keeps WAL order = fold order, and a restart lands bit-exact
    whatever interleaving the writer thread saw."""
    s, _src = _durable_session(tmp_path)
    s.service.register_standing(s.sql(AGG_SQL), name="q")
    errors = []

    def feed(lo, hi):
        try:
            for i in range(lo, hi):
                s.append_batch("events", _batch(i, n=120))
        except Exception as e:  # noqa: BLE001 - surfaced via errors
            errors.append(e)

    threads = [threading.Thread(target=feed, args=(lo, lo + 3))
               for lo in (0, 3, 6)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    s.stop()

    s2, src2 = _durable_session(tmp_path)
    assert src2.num_appends == 9
    sq2 = s2.service.register_standing(s2.sql(AGG_SQL), name="q")
    assert sq2.folds == 9
    assert_frames_equal(_oracle(9, n=120), sq2.results())
    s2.stop()


def test_checkpoint_retention_prunes_files(tmp_path):
    s, _src = _durable_session(
        tmp_path, **{cfg.STREAMING_CHECKPOINT_RETAIN.key: "2"})
    s.service.register_standing(s.sql(AGG_SQL), name="q")
    for i in range(6):
        s.append_batch("events", _batch(i, n=80))
    dur = s.service.streaming.durability
    dur.drain()
    store = dur.store_for("events", "q")
    assert store.checkpoint_count() <= 2
    s.stop()


def test_durability_bytes_charge_admission(tmp_path):
    """Unsynced WAL bytes are part of the service's extra admission
    charge (the same ledger cached fragments and streaming state
    use)."""
    s, _src = _durable_session(
        tmp_path, **{cfg.STREAMING_CHECKPOINT_WAL_SYNC.key: "1000"})
    svc = s.service
    svc.register_standing(s.sql(AGG_SQL), name="q")
    s.append_batch("events", _batch(0))
    pending = svc.streaming.durability_pending_bytes()
    assert pending > 0  # fsync batched: the tail is still in flight
    assert svc.admission.extra_bytes_fn() >= pending
    s.stop()
    # drain+close fsync'd everything
    assert svc.streaming.durability_pending_bytes() == 0


def test_non_durable_session_unchanged(tmp_path):
    """No checkpoint dir -> no WAL, no checkpoint files, cancel (not
    suspend) at shutdown — the PR 14 behavior exactly."""
    s = Session()
    s.create_streaming_table("events", SCHEMA)
    sq = s.service.register_standing(s.sql(AGG_SQL), name="q")
    s.append_batch("events", _batch(0))
    assert not s.service.streaming.durability.enabled
    assert s.service.streaming.durability_pending_bytes() == 0
    s.stop()
    assert sq.state != SUSPENDED
