"""Concurrent query service (service/): admission control, fair stage
scheduling, backpressure — the acceptance suite of the multi-tenant
subsystem. Smoke tier; everything runs on the virtual CPU mesh."""
import threading
import time

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.api import Session, col, functions as F
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.memory import semaphore as sem
from spark_rapids_tpu.memory.catalog import (BufferCatalog, get_catalog,
                                             set_buffer_owner)
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.service import (DeadlineExceeded, QueryService,
                                      QueryState, ServiceOverloaded)
from spark_rapids_tpu.service.admission import parse_fairness_weights


def _frame(rng, n=4000, nk=12):
    return pd.DataFrame({
        "k": rng.integers(0, nk, n).astype(np.int64),
        "v": rng.random(n)})


def _agg_query(s, df):
    return df.filter(col("v") > 0.2).group_by("k").agg(
        F.sum(col("v")).alias("sv"), F.count("*").alias("n"))


def _sorted(frame):
    return frame.sort_values("k").reset_index(drop=True)


class GateSource(pn.DataSource):
    """Multi-split source whose reads block on per-split events —
    deterministic control over how long a query's stages run."""

    def __init__(self, n_splits=2, rows=200, open_all=False):
        self.n = n_splits
        self.rows = rows
        self.gates = [threading.Event() for _ in range(n_splits)]
        if open_all:
            for g in self.gates:
                g.set()

    def schema(self):
        return Schema(["k", "v"], [dt.INT64, dt.FLOAT64])

    def num_splits(self):
        return self.n

    def split_origin(self, p):
        return None

    def split_stats(self, p):
        return None

    def estimated_row_count(self):
        return self.n * self.rows

    def read_host_split(self, p):
        assert self.gates[p].wait(timeout=30), f"gate {p} never opened"
        rng = np.random.default_rng(p)
        return ({"k": rng.integers(0, 8, self.rows).astype(np.int64),
                 "v": rng.random(self.rows)},
                {"k": None, "v": None})


# -- (a) concurrent correctness ---------------------------------------------


def test_concurrent_submissions_match_serial():
    """8+ concurrently submitted queries all complete, each
    oracle-matched against its own serial collect()."""
    s = Session({"rapids.tpu.sql.shuffle.partitions": 2})
    rng = np.random.default_rng(7)
    df_a = s.create_dataframe(_frame(rng))
    df_b = s.create_dataframe(_frame(rng, n=3000, nk=5))
    qa, qb = _agg_query(s, df_a), _agg_query(s, df_b)
    serial = {"a": _sorted(qa.collect()), "b": _sorted(qb.collect())}
    handles = [(which, (qa if which == "a" else qb).collect_async(
        tenant=f"t{i % 3}"))
        for i, which in enumerate("abababab")]
    assert len(handles) >= 8
    for which, h in handles:
        got = _sorted(h.result(timeout=120))
        pd.testing.assert_frame_equal(got, serial[which])
        assert h.poll() is QueryState.DONE
    stats = s.service.stats()
    assert stats.counters["done"] >= 8
    assert stats.counters["failed"] == 0
    s.stop()


def test_sql_async_path():
    s = Session()
    rng = np.random.default_rng(1)
    s.create_temp_view("t", s.create_dataframe(_frame(rng)))
    want = _sorted(
        s.sql("SELECT k, sum(v) AS sv FROM t GROUP BY k").collect())
    h = s.sql_async("SELECT k, sum(v) AS sv FROM t GROUP BY k",
                    tenant="sqltenant")
    got = _sorted(h.result(timeout=60))
    pd.testing.assert_frame_equal(got, want)
    s.stop()


# -- (b) admission bounds HBM ------------------------------------------------


def test_admission_bounds_hbm_budget():
    """Two queries whose combined footprint exceeds the budget never
    run together: the second WAITS (QUEUED) while the first is
    inflight, then runs — nothing is rejected and nothing OOMs."""
    src1, src2 = GateSource(1), GateSource(1, open_all=True)
    plan1 = pn.ScanNode(src1)
    plan2 = pn.ScanNode(src2)
    svc = QueryService(RapidsConf({
        cfg.SERVICE_MAX_CONCURRENT.key: 4,
        # footprint = 200 rows * (8+1 + 8+1) bytes = 3600; one fits,
        # two do not
        cfg.SERVICE_ADMISSION_BUDGET.key: 5000}))
    h1 = svc.submit(plan1, tenant="a")
    h2 = svc.submit(plan2, tenant="b")
    deadline = time.time() + 5
    while h1.poll() not in (QueryState.RUNNING,) and \
            time.time() < deadline:
        time.sleep(0.01)
    # q1 blocked inside its gated scan, holding its admission charge:
    # q2 must be waiting at admission, not running
    time.sleep(0.2)
    assert h1.poll() in (QueryState.RUNNING, QueryState.ADMITTED)
    assert h2.poll() is QueryState.QUEUED
    assert svc.admission.inflight_bytes <= svc.admission.budget_bytes
    src1.gates[0].set()
    assert len(h1.result(timeout=30)) == 200
    assert len(h2.result(timeout=30)) == 200  # admitted after release
    assert svc.admission.inflight_bytes == 0
    svc.shutdown()


def test_footprint_estimate_monotone():
    from spark_rapids_tpu.plan.optimizer import estimate_footprint_bytes

    small = pn.ScanNode(GateSource(1, rows=100))
    big = pn.ScanNode(GateSource(1, rows=100000))
    assert estimate_footprint_bytes(big) > \
        estimate_footprint_bytes(small) > 0
    # unknown-cardinality plans fall back to the configured default
    class _NoEst(GateSource):
        def estimated_row_count(self):
            return None
    assert estimate_footprint_bytes(pn.ScanNode(_NoEst(1)),
                                    default_rows=1000) == \
        estimate_footprint_bytes(pn.ScanNode(GateSource(1, rows=1000)))


# -- (c) tenant fairness -----------------------------------------------------


def test_tenant_fairness_no_starvation():
    """Tenant A floods 10 queries; tenant B submits 1. WRR admission
    puts B near the front — B finishes before all but the first couple
    of A's queries instead of queueing behind all 10."""
    s = Session()
    rng = np.random.default_rng(3)
    q = _agg_query(s, s.create_dataframe(_frame(rng, n=20000)))
    svc = QueryService(RapidsConf({cfg.SERVICE_MAX_CONCURRENT.key: 1}),
                       session=s)
    a_handles = [svc.submit(q, tenant="A") for _ in range(10)]
    b_handle = svc.submit(q, tenant="B")
    b_handle.result(timeout=120)
    for h in a_handles:
        h.result(timeout=120)
    b_done = b_handle._query.finished_at
    a_before_b = sum(h._query.finished_at < b_done for h in a_handles)
    assert a_before_b <= 3, \
        f"tenant B starved: {a_before_b} of A's queries finished first"
    # and B's queue time is bounded by a few of A's runs, not all 10
    a_total_run = sum(h._query.run_time_s() for h in a_handles)
    assert b_handle._query.queue_time_s() < a_total_run
    svc.shutdown()
    s.stop()


def test_fairness_weight_parsing():
    assert parse_fairness_weights("a:2, b:1") == {"a": 2, "b": 1}
    assert parse_fairness_weights("") == {}
    assert parse_fairness_weights("junk,x:notint,y:3") == {"y": 3}


# -- (d) cancel / deadline release resources --------------------------------


def _leak_probe_plan():
    """Scan -> repartition -> groupby: the exchange stages catalog
    buffers mid-query, so an abandoned run WOULD leak without the
    owner cleanup."""
    src = GateSource(2, rows=500)
    scan = pn.ScanNode(src)
    shuffled = pn.ShuffleExchangeNode(("hash", [0]), 2, scan)
    from spark_rapids_tpu.expressions.base import BoundReference
    from spark_rapids_tpu.expressions import aggregates as A

    agg = pn.AggregateNode(
        [BoundReference(0, dt.INT64)],
        [pn.AggCall(A.Sum(BoundReference(1, dt.FLOAT64)), "sv")],
        shuffled, grouping_names=["k"])
    return src, agg


def test_cancel_releases_permits_and_buffers():
    src, plan = _leak_probe_plan()
    src.gates[0].set()
    svc = QueryService(RapidsConf({cfg.SERVICE_MAX_CONCURRENT.key: 2}))
    h = svc.submit(plan, tenant="c")
    q = h._query
    deadline = time.time() + 10
    while q.slices_done < 1 and time.time() < deadline:
        time.sleep(0.01)
    assert h.cancel()
    src.gates[1].set()  # let the blocked stage finish so cancel lands
    with pytest.raises(Exception) as ei:
        h.result(timeout=30)
    assert type(ei.value).__name__ == "QueryCancelled"
    assert h.poll() is QueryState.CANCELLED
    # no catalog leak: every buffer the query registered is gone
    assert get_catalog().owner_refcounts(q.owner_tag) == {}
    # no permit leak
    semaphore = sem.get()
    assert semaphore.available() == semaphore.max_permits
    svc.shutdown()


def test_deadline_expiry_releases_resources():
    src, plan = _leak_probe_plan()
    src.gates[0].set()
    svc = QueryService(RapidsConf({cfg.SERVICE_MAX_CONCURRENT.key: 2}))
    h = svc.submit(plan, tenant="d", deadline=0.3)
    q = h._query
    # gate 1 opens only AFTER the deadline: the slice in flight finishes
    # late and the next boundary check expires the query
    threading.Timer(0.6, src.gates[1].set).start()
    with pytest.raises(DeadlineExceeded):
        h.result(timeout=30)
    assert h.poll() is QueryState.FAILED
    assert get_catalog().owner_refcounts(q.owner_tag) == {}
    semaphore = sem.get()
    assert semaphore.available() == semaphore.max_permits
    stats = svc.stats()
    assert stats.counters["deadline_expired"] == 1
    svc.shutdown()


def test_deadline_expires_while_queued():
    """A queued query past its deadline fails lazily without ever
    being admitted (no resources to release)."""
    src, _ = _leak_probe_plan()
    blocker_src = GateSource(1)
    svc = QueryService(RapidsConf({cfg.SERVICE_MAX_CONCURRENT.key: 1}))
    h1 = svc.submit(pn.ScanNode(blocker_src), tenant="x")
    h2 = svc.submit(pn.ScanNode(GateSource(1, open_all=True)),
                    tenant="y", deadline=0.15)
    time.sleep(0.3)
    assert h2.poll() is QueryState.FAILED
    with pytest.raises(DeadlineExceeded):
        h2.result(timeout=5)
    blocker_src.gates[0].set()
    h1.result(timeout=30)
    svc.shutdown()


# -- (e) shedding + stats ----------------------------------------------------


def test_overload_sheds_instead_of_deadlocking():
    blocker = GateSource(1)
    svc = QueryService(RapidsConf({
        cfg.SERVICE_MAX_CONCURRENT.key: 1,
        cfg.SERVICE_QUEUE_LIMIT.key: 2}))
    h1 = svc.submit(pn.ScanNode(blocker), tenant="t")
    waiting = [svc.submit(pn.ScanNode(GateSource(1, open_all=True)),
                          tenant="t") for _ in range(2)]
    with pytest.raises(ServiceOverloaded) as ei:
        svc.submit(pn.ScanNode(GateSource(1, open_all=True)),
                   tenant="t")
    assert ei.value.queue_depth == 2
    assert ei.value.queue_limit == 2
    assert ei.value.tenant == "t"
    stats = svc.stats()
    assert stats.queue_depth == 2
    assert stats.counters["shed"] == 1
    assert "hit_rate" in stats.to_dict()["progcache"]
    # shedding didn't wedge the service: everything queued completes
    blocker.gates[0].set()
    h1.result(timeout=30)
    for h in waiting:
        h.result(timeout=30)
    assert svc.stats().queue_depth == 0
    svc.shutdown()


def test_shed_band_spares_light_tenant():
    """Between queueLimit and 2x, only tenants at/above their fair
    share shed: a flooding tenant cannot fill every queue slot and
    starve a light tenant at the front door. At the 2x hard ceiling
    everybody sheds."""
    blocker = GateSource(1)
    svc = QueryService(RapidsConf({
        cfg.SERVICE_MAX_CONCURRENT.key: 1,
        cfg.SERVICE_QUEUE_LIMIT.key: 2}))
    h1 = svc.submit(pn.ScanNode(blocker), tenant="flood")
    flood = [svc.submit(pn.ScanNode(GateSource(1, open_all=True)),
                        tenant="flood") for _ in range(2)]
    # queue full (2/2) entirely with tenant "flood": flood sheds...
    with pytest.raises(ServiceOverloaded):
        svc.submit(pn.ScanNode(GateSource(1, open_all=True)),
                   tenant="flood")
    # ...but a light tenant's first submission still gets in
    light = svc.submit(pn.ScanNode(GateSource(1, open_all=True)),
                       tenant="light")
    # beyond the 2x hard ceiling even new tenants shed
    spill = []
    with pytest.raises(ServiceOverloaded):
        for i in range(10):
            spill.append(svc.submit(
                pn.ScanNode(GateSource(1, open_all=True)),
                tenant=f"fresh{i}"))
    assert svc.stats().queue_depth <= 2 * 2
    blocker.gates[0].set()
    h1.result(timeout=30)
    for h in flood + [light] + spill:
        h.result(timeout=30)
    svc.shutdown()


def test_service_stats_in_bench_json(tmp_path):
    """benchmarks/service_bench.py emits runner-shaped JSON with the
    ServiceStats block: queue depth, shed count, progcache hit rate,
    per-query queue-time vs run-time."""
    from spark_rapids_tpu.benchmarks.service_bench import \
        run_service_bench

    out = run_service_bench(str(tmp_path / "tpch"), sf=0.001,
                            queries=4, mix=["tpch_q6", "tpch_q1"],
                            tenants=2)
    assert out["concurrent_queries"] == 4
    assert len(out["per_query"]) == 4
    for rec in out["per_query"]:
        assert rec["queue_time_s"] >= 0
        assert rec["run_time_s"] >= 0
    ss = out["service_stats"]
    assert ss["queue_depth"] == 0
    assert ss["counters"]["done"] == 4
    assert ss["counters"]["shed"] == 0
    assert "hit_rate" in ss["progcache"]
    assert ss["queue_time_hist"]["count"] == 4
    # the multi-tenant win: repeated shapes share compiled programs
    assert ss["progcache"]["hits"] >= 0


# -- scheduler internals -----------------------------------------------------


def test_stalled_query_spill_demotion():
    """Buffers owned by a stalled query out-rank everything as spill
    victims; resuming restores their priority."""
    from spark_rapids_tpu.memory import priorities
    from spark_rapids_tpu.memory.spillable import SpillableBatch
    from spark_rapids_tpu.columnar.column import Column
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    from spark_rapids_tpu.service.scheduler import STALLED_SPILL_BIAS

    cat = BufferCatalog()

    def batch():
        return ColumnarBatch(
            [Column.from_numpy(np.arange(100, dtype=np.int64))], 100)

    prev = set_buffer_owner(("svc-query", 42))
    sb_stalled = SpillableBatch(batch(),
                                priorities.ACTIVE_ON_DECK_PRIORITY,
                                catalog=cat)
    set_buffer_owner(None)
    sb_other = SpillableBatch(batch(),
                              priorities.OUTPUT_FOR_SHUFFLE_PRIORITY,
                              catalog=cat)
    set_buffer_owner(prev)
    # normally the shuffle-output buffer (priority 0) spills first
    assert cat._pick_spill_victim(
        cat.tier_of(sb_other.buffer_id)).buffer_id == sb_other.buffer_id
    cat._requeue(cat._entries[sb_other.buffer_id])
    # demoted: the stalled query's ACTIVE buffer becomes the victim
    assert cat.set_owner_bias(("svc-query", 42),
                              STALLED_SPILL_BIAS) == 1
    assert cat._pick_spill_victim(
        cat.tier_of(sb_stalled.buffer_id)).buffer_id == \
        sb_stalled.buffer_id
    cat._requeue(cat._entries[sb_stalled.buffer_id])
    # restored on resume
    cat.set_owner_bias(("svc-query", 42), 0)
    assert cat._pick_spill_victim(
        cat.tier_of(sb_other.buffer_id)).buffer_id == sb_other.buffer_id
    # owner bookkeeping
    assert set(cat.owner_refcounts(("svc-query", 42))) == \
        {sb_stalled.buffer_id}
    assert cat.remove_owner(("svc-query", 42)) == 1
    assert cat.owner_refcounts(("svc-query", 42)) == {}


def test_shutdown_finalizes_inflight_queries():
    """shutdown() must terminate RUNNING/ADMITTED queries itself —
    workers are gone, so no future slice will observe the cancel flag;
    a result() waiter must not hang and resources must release."""
    src = GateSource(2)
    src.gates[0].set()
    svc = QueryService(RapidsConf({cfg.SERVICE_MAX_CONCURRENT.key: 2}))
    h = svc.submit(pn.ScanNode(src), tenant="z")
    q = h._query
    deadline = time.time() + 10
    while q.slices_done < 1 and time.time() < deadline:
        time.sleep(0.01)
    src.gates[1].set()  # let the in-flight slice drain during join()
    svc.shutdown()
    assert h.poll() is QueryState.CANCELLED
    with pytest.raises(Exception):
        h.result(timeout=5)
    assert get_catalog().owner_refcounts(q.owner_tag) == {}


def test_owner_tag_propagates_to_task_pool_threads():
    """Batches registered from exec-internal task-pool threads (e.g.
    exchange materialization under run_partitions) must carry the
    submitting query's owner tag, or cancel cleanup would miss them."""
    from spark_rapids_tpu.execs.base import run_partitions
    from spark_rapids_tpu.memory.catalog import current_buffer_owner

    tag = ("svc-query", 777)
    prev = set_buffer_owner(tag)
    try:
        seen = run_partitions(4, lambda p: current_buffer_owner(),
                              task_threads=4)
    finally:
        set_buffer_owner(prev)
    assert seen == [tag] * 4


def test_batching_and_slo_smoke():
    """Fast tier-1 smoke over the serving layer: a service with
    micro-batching + warmup enabled completes a small multi-tenant
    burst, and the stats snapshot carries the batching block and the
    latency percentiles the SLO harness consumes. (Deterministic
    coalescing/SLO fences live in tests/test_batching.py and
    scripts/slo_check.py.)"""
    s = Session()
    rng = np.random.default_rng(41)
    q = _agg_query(s, s.create_dataframe(_frame(rng)))
    svc = QueryService(RapidsConf({
        cfg.SERVICE_BATCHING_WINDOW_MS.key: 5.0,
        cfg.SERVICE_WARMUP_ENABLED.key: True}), session=s)
    svc.register_template(q, "agg")
    want = _sorted(q.collect())
    handles = [svc.submit(q, tenant=f"t{i % 3}") for i in range(6)]
    for h in handles:
        pd.testing.assert_frame_equal(_sorted(h.result(timeout=120)),
                                      want)
    snap = svc.stats().to_dict()
    svc.shutdown()
    s.stop()
    b = snap["batching"]
    assert b["enabled"] and b["launches"] >= 1
    assert b["coalesced_participants"] >= 0
    for hist in (snap["queue_time_hist"], snap["run_time_hist"]):
        for key in ("p50_s", "p95_s", "p99_s"):
            assert hist[key] >= 0
    assert snap["latency"]["run_p99_s"] >= \
        snap["latency"]["run_p50_s"] >= 0
    assert "buckets" in snap["progcache"]


def test_query_failure_propagates():
    class BoomSource(GateSource):
        def read_host_split(self, p):
            raise RuntimeError("boom in stage")

    svc = QueryService(RapidsConf({}))
    h = svc.submit(pn.ScanNode(BoomSource(1)), tenant="e")
    with pytest.raises(RuntimeError, match="boom in stage"):
        h.result(timeout=30)
    assert h.poll() is QueryState.FAILED
    assert svc.stats().counters["failed"] == 1
    svc.shutdown()
