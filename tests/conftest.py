"""Test bootstrap: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective paths are
validated on XLA's host platform with 8 virtual devices (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402

import spark_rapids_tpu  # noqa: E402,F401  (enables x64 before jax use)


@pytest.fixture(scope="session")
def n_virtual_devices():
    import jax

    return len(jax.devices())
