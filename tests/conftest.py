"""Test bootstrap: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective paths are
validated on XLA's host platform with 8 virtual devices (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

# Runtime lock-order assertions for the whole tier-1 run
# (rapids.tpu.debug.lockOrder.enabled). Must be set BEFORE the package
# imports: every lock is wrapped (or not) at creation time. Record mode
# (the default): violations accumulate instead of raising mid-test, and
# pytest_sessionfinish below fails the run if any were observed.
os.environ.setdefault("RAPIDS_TPU_DEBUG_LOCKORDER_ENABLED", "1")

import pytest  # noqa: E402

import spark_rapids_tpu  # noqa: E402,F401  (enables x64 before jax use)
from spark_rapids_tpu.utils import lockorder  # noqa: E402

# The axon TPU bootstrap (sitecustomize) overrides jax_platforms via
# jax.config.update at interpreter start, so the env var alone is not
# enough — force the CPU backend explicitly before any backend init.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", \
    "tests must run on the virtual CPU mesh, not the real TPU"
assert len(jax.devices()) >= 8, \
    "xla_force_host_platform_device_count=8 did not take effect"


@pytest.fixture(scope="session")
def n_virtual_devices():
    return len(jax.devices())


def pytest_collection_modifyitems(config, items):
    """Two-tier suite: anything not explicitly `full` (the 140-query TPC
    oracle matrices) is the `smoke` tier — `pytest -m smoke` stays under
    the per-push CI window; plain `pytest tests/` is the nightly run."""
    for item in items:
        if "full" not in item.keywords:
            item.add_marker(pytest.mark.smoke)


def pytest_sessionfinish(session, exitstatus):
    """Fail the run if any lock-order inversion was recorded anywhere in
    the suite — the dynamic half of tpulint's TPU301 (the static pass
    only sees nestings it can prove; this catches the interleavings)."""
    viols = lockorder.violations()
    if not viols:
        return
    rep = session.config.pluginmanager.get_plugin("terminalreporter")
    for v in viols:
        msg = ("LOCK-ORDER VIOLATION: acquired %(acquiring)r (rank "
               "%(acquiring_rank)d) while holding %(held)r (rank "
               "%(held_rank)d) on thread %(thread)s\n%(stack)s" % v)
        if rep:
            rep.write_line(msg, red=True)
        else:  # pragma: no cover - no terminal plugin
            print(msg)
    session.exitstatus = 3
