"""Test bootstrap: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding/collective paths are
validated on XLA's host platform with 8 virtual devices (the driver separately
dry-run-compiles the multi-chip path via __graft_entry__.dryrun_multichip).
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import pytest  # noqa: E402

import spark_rapids_tpu  # noqa: E402,F401  (enables x64 before jax use)

# The axon TPU bootstrap (sitecustomize) overrides jax_platforms via
# jax.config.update at interpreter start, so the env var alone is not
# enough — force the CPU backend explicitly before any backend init.
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
assert jax.devices()[0].platform == "cpu", \
    "tests must run on the virtual CPU mesh, not the real TPU"
assert len(jax.devices()) >= 8, \
    "xla_force_host_platform_device_count=8 did not take effect"


@pytest.fixture(scope="session")
def n_virtual_devices():
    return len(jax.devices())


def pytest_collection_modifyitems(config, items):
    """Two-tier suite: anything not explicitly `full` (the 140-query TPC
    oracle matrices) is the `smoke` tier — `pytest -m smoke` stays under
    the per-push CI window; plain `pytest tests/` is the nightly run."""
    for item in items:
        if "full" not in item.keywords:
            item.add_marker(pytest.mark.smoke)
