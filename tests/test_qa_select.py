"""Generated SELECT matrix over fuzzed data — the reference's
qa_nightly_select_test.py role: a wide sweep of (expression x input type)
combinations, every one checked against the CPU oracle with special
values (NaN/Inf/-0.0/boundaries/NULLs) in play."""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.expressions import arithmetic as ar
from spark_rapids_tpu.expressions import conditional as cond
from spark_rapids_tpu.expressions import math as mth
from spark_rapids_tpu.expressions import predicates as pr
from spark_rapids_tpu.expressions import strings as st
from spark_rapids_tpu.expressions import datetime as dte
from spark_rapids_tpu.expressions.base import (Alias, BoundReference,
                                               Literal)
from spark_rapids_tpu.expressions.cast import Cast
from spark_rapids_tpu.plan import nodes as pn

from tests import data_gen as dg
from tests.compare import assert_cpu_and_tpu_equal

CONF = RapidsConf({
    "rapids.tpu.sql.test.enabled": True,
    "rapids.tpu.sql.incompatibleOps.enabled": True,
    "rapids.tpu.sql.variableFloatAgg.enabled": True,
})


def _project(exprs, scan):
    return pn.ProjectNode(
        [Alias(e, f"o{i}") for i, e in enumerate(exprs)], scan)


def ref(i, t):
    return BoundReference(i, t)


# ---------------------------------------------------------------------------
# binary arithmetic x numeric type matrix
# ---------------------------------------------------------------------------

_ARITH = [ar.Add, ar.Subtract, ar.Multiply, ar.Divide, ar.Remainder,
          ar.Pmod]


@pytest.mark.parametrize("op", _ARITH, ids=lambda o: o.__name__)
@pytest.mark.parametrize("gen", dg.NUMERIC_GENS,
                         ids=lambda g: g.dtype.name)
def test_binary_arith_matrix(op, gen, subtests=None):
    scan = dg.gen_scan({"a": gen, "b": type(gen)()}, n=150,
                       seed=hash((op.__name__, gen.dtype.name)) % 10_000)
    a, b = ref(0, gen.dtype), ref(1, gen.dtype)
    exprs = [op(a, b), op(a, Literal(3)), op(Literal(7), b)]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF,
                             approx_float=1e-6)


@pytest.mark.parametrize("op", [pr.EqualTo, pr.LessThan,
                                pr.GreaterThanOrEqual,
                                pr.EqualNullSafe],
                         ids=lambda o: o.__name__)
@pytest.mark.parametrize("gen", [dg.IntegerGen(), dg.DoubleGen(),
                                 dg.StringGen(), dg.DateGen()],
                         ids=lambda g: g.dtype.name)
def test_comparison_matrix(op, gen):
    scan = dg.gen_scan({"a": gen, "b": type(gen)()}, n=150, seed=5)
    exprs = [op(ref(0, gen.dtype), ref(1, gen.dtype))]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF)


@pytest.mark.parametrize("op", [mth.Sqrt, mth.Exp, mth.Log, mth.Sin,
                                mth.Cos, mth.Tanh, mth.Floor, mth.Ceil,
                                mth.Rint, mth.Asinh, mth.Acosh,
                                mth.Atanh, mth.Cot],
                         ids=lambda o: o.__name__)
def test_unary_math_matrix(op):
    scan = dg.gen_scan({"a": dg.DoubleGen()}, n=200, seed=6)
    assert_cpu_and_tpu_equal(
        _project([op(ref(0, dt.FLOAT64))], scan), conf=CONF,
        approx_float=1e-6)


@pytest.mark.parametrize("op", [st.Upper, st.Lower, st.Length,
                                st.StringTrim, st.Reverse, st.InitCap],
                         ids=lambda o: o.__name__)
def test_unary_string_matrix(op):
    scan = dg.gen_scan({"s": dg.StringGen()}, n=150, seed=7)
    assert_cpu_and_tpu_equal(
        _project([op(ref(0, dt.STRING))], scan), conf=CONF)


@pytest.mark.parametrize("op", [dte.Year, dte.Month, dte.DayOfMonth,
                                dte.DayOfWeek, dte.DayOfYear,
                                dte.Quarter, dte.LastDay],
                         ids=lambda o: o.__name__)
def test_date_field_matrix(op):
    scan = dg.gen_scan({"d": dg.DateGen()}, n=150, seed=8)
    assert_cpu_and_tpu_equal(
        _project([op(ref(0, dt.DATE))], scan), conf=CONF)


_CAST_PAIRS = [
    (dg.IntegerGen(), dt.INT64), (dg.IntegerGen(), dt.FLOAT64),
    (dg.IntegerGen(), dt.STRING), (dg.LongGen(), dt.INT32),
    (dg.DoubleGen(), dt.INT64), (dg.DoubleGen(), dt.FLOAT32),
    (dg.BooleanGen(), dt.INT32), (dg.ByteGen(), dt.INT16),
    (dg.SmallIntGen(), dt.STRING), (dg.DateGen(), dt.TIMESTAMP),
    (dg.TimestampGen(), dt.DATE),
]


@pytest.mark.parametrize("gen,to", _CAST_PAIRS,
                         ids=lambda p: getattr(p, "name", str(p)))
def test_cast_matrix(gen, to):
    scan = dg.gen_scan({"a": gen}, n=150, seed=9)
    assert_cpu_and_tpu_equal(
        _project([Cast(ref(0, gen.dtype), to)], scan), conf=CONF)


def test_conditional_over_fuzz():
    scan = dg.gen_scan({"a": dg.IntegerGen(), "b": dg.IntegerGen(),
                        "p": dg.BooleanGen()}, n=200, seed=10)
    a, b, p = ref(0, dt.INT32), ref(1, dt.INT32), ref(2, dt.BOOLEAN)
    exprs = [
        cond.If(p, a, b),
        cond.Coalesce([a, b, Literal(0, dt.INT32)]),
        cond.CaseWhen([(pr.GreaterThan(a, b), a),
                       (pr.IsNull(a), Literal(-1, dt.INT32))], b),
    ]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF)


def test_aggregate_over_fuzz():
    from spark_rapids_tpu.expressions import aggregates as A

    scan = dg.gen_scan({"k": dg.SmallIntGen(), "v": dg.DoubleGen(),
                        "i": dg.IntegerGen()}, n=300, seed=11)
    agg = pn.AggregateNode(
        [ref(0, dt.INT64)],
        [pn.AggCall(A.Sum(ref(1, dt.FLOAT64)), "sv"),
         pn.AggCall(A.Min(ref(2, dt.INT32)), "mn"),
         pn.AggCall(A.Max(ref(1, dt.FLOAT64)), "mx"),
         pn.AggCall(A.Count(ref(1, dt.FLOAT64)), "cv"),
         pn.AggCall(A.Average(ref(2, dt.INT32)), "av")],
        scan, grouping_names=["k"])
    assert_cpu_and_tpu_equal(agg, conf=CONF, approx_float=1e-6)


def test_sort_over_fuzz_with_specials():
    """NaN/-0.0/NULL ordering under Spark total order."""
    scan = dg.gen_scan({"a": dg.DoubleGen(nullable=0.2),
                        "b": dg.IntegerGen()}, n=250, seed=12)
    from spark_rapids_tpu.ops.sortkeys import SortKeySpec

    plan = pn.SortNode([SortKeySpec.spark_default(0),
                        SortKeySpec.spark_default(1, ascending=False)],
                       scan)
    assert_cpu_and_tpu_equal(plan, conf=CONF, sort=False)


def test_join_over_fuzz():
    left = dg.gen_scan({"k": dg.SmallIntGen(), "v": dg.DoubleGen()},
                       n=200, seed=13)
    right = dg.gen_scan({"k2": dg.SmallIntGen(), "w": dg.StringGen()},
                        n=150, seed=14)
    for kind in ("inner", "left", "left_semi", "left_anti"):
        plan = pn.JoinNode(kind, left, right, [0], [0])
        assert_cpu_and_tpu_equal(plan, conf=CONF, approx_float=1e-6)


@pytest.mark.parametrize("op_name", ["BitwiseAnd", "BitwiseOr",
                                     "BitwiseXor"])
@pytest.mark.parametrize("gen", [dg.IntegerGen(), dg.LongGen(),
                                 dg.ShortGen()],
                         ids=lambda g: g.dtype.name)
def test_bitwise_binary_matrix(op_name, gen):
    from spark_rapids_tpu.expressions import bitwise as bw

    op = getattr(bw, op_name)
    scan = dg.gen_scan({"a": gen, "b": type(gen)()}, n=150, seed=21)
    exprs = [op(ref(0, gen.dtype), ref(1, gen.dtype)),
             bw.BitwiseNot(ref(0, gen.dtype))]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF)


@pytest.mark.parametrize("op_name", ["ShiftLeft", "ShiftRight",
                                     "ShiftRightUnsigned"])
@pytest.mark.parametrize("gen", [dg.IntegerGen(), dg.LongGen()],
                         ids=lambda g: g.dtype.name)
def test_shift_matrix(op_name, gen):
    from spark_rapids_tpu.expressions import bitwise as bw
    from spark_rapids_tpu.expressions.base import Literal

    op = getattr(bw, op_name)
    scan = dg.gen_scan({"a": gen, "s": dg.IntegerGen()}, n=150, seed=22)
    # fuzzed shift amounts exercise the Java width mask (s & 31/63)
    exprs = [op(ref(0, gen.dtype), ref(1, dt.INT32)),
             op(ref(0, gen.dtype), Literal(3, dt.INT32)),
             op(ref(0, gen.dtype), Literal(0, dt.INT32)),
             op(ref(0, gen.dtype), Literal(65, dt.INT32))]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF)


def test_string_binary_matrix():
    scan = dg.gen_scan({"s": dg.StringGen(), "t": dg.StringGen()},
                       n=150, seed=30)
    s = ref(0, dt.STRING)
    exprs = [
        st.Substring(s, 2, 3),
        st.Substring(s, -3, None),
        st.StringReplace(s, "a", "ZZ"),
        st.StringRepeat(s, 2),
        st.StringLPad(s, 6, "*"),
        st.StringRPad(s, 6, "*"),
        st.StartsWith(s, "a"),
        st.EndsWith(s, "z"),
        st.Contains(s, "X"),
        st.Like(s, "a%b_"),
        st.StringLocate("b", s),
        st.ConcatStrings([s, ref(1, dt.STRING)]),
    ]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF)


def test_in_and_null_predicates():
    scan = dg.gen_scan({"a": dg.IntegerGen(nullable=0.2),
                        "s": dg.StringGen(nullable=0.2),
                        "f": dg.DoubleGen(nullable=0.2)}, n=200,
                       seed=31)
    exprs = [
        pr.In(ref(0, dt.INT32), [Literal(v, dt.INT32)
                                 for v in (0, 7, -12, 2**31 - 1)]),
        pr.In(ref(1, dt.STRING), [Literal(v) for v in ("ab", "", "X z")]),
        pr.IsNull(ref(0, dt.INT32)),
        pr.IsNotNull(ref(1, dt.STRING)),
        pr.IsNaN(ref(2, dt.FLOAT64)),
        pr.AtLeastNNonNulls(2, [ref(0, dt.INT32), ref(1, dt.STRING),
                                ref(2, dt.FLOAT64)]),
    ]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF)


def test_datetime_arithmetic_matrix():
    scan = dg.gen_scan({"d": dg.DateGen(), "d2": dg.DateGen(),
                        "ts": dg.TimestampGen(),
                        "n": dg.SmallIntGen()}, n=150, seed=32)
    exprs = [
        dte.DateAdd(ref(0, dt.DATE), Cast(ref(3, dt.INT64), dt.INT32)),
        dte.DateSub(ref(0, dt.DATE), Literal(30, dt.INT32)),
        dte.DateDiff(ref(0, dt.DATE), ref(1, dt.DATE)),
        dte.Hour(ref(2, dt.TIMESTAMP)),
        dte.Minute(ref(2, dt.TIMESTAMP)),
        dte.Second(ref(2, dt.TIMESTAMP)),
        dte.Year(Cast(ref(2, dt.TIMESTAMP), dt.DATE)),
    ]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF)


# ---------------------------------------------------------------------------
# round-2 expression additions: two-arg log, weekday/time math, string
# index/replace, normalization wrappers
# ---------------------------------------------------------------------------


def test_logarithm_matrix():
    scan = dg.gen_scan({"a": dg.DoubleGen(), "b": dg.DoubleGen()},
                       n=200, seed=31)
    assert_cpu_and_tpu_equal(
        _project([mth.Logarithm(ref(0, dt.FLOAT64),
                                ref(1, dt.FLOAT64))], scan),
        conf=CONF, approx_float=1e-6)


def test_weekday_timeadd_tounix_matrix():
    scan = dg.gen_scan({"d": dg.DateGen(), "t": dg.TimestampGen()},
                       n=200, seed=32)
    exprs = [dte.WeekDay(ref(0, dt.DATE)),
             dte.ToUnixTimestamp(ref(1, dt.TIMESTAMP)),
             dte.TimeAdd(ref(1, dt.TIMESTAMP),
                         Literal(3_600_000_000, dt.INT64))]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF)


def test_substring_index_regexp_replace_matrix():
    scan = dg.gen_scan({"s": dg.StringGen()}, n=200, seed=33)
    exprs = [st.SubstringIndex(ref(0, dt.STRING), "a", 1),
             st.SubstringIndex(ref(0, dt.STRING), "b", -2),
             st.RegExpReplace(ref(0, dt.STRING), "a", "_")]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF)


def test_normalize_wrappers_matrix():
    from spark_rapids_tpu.expressions.constraints import (
        KnownFloatingPointNormalized, NormalizeNaNAndZero)

    scan = dg.gen_scan({"a": dg.DoubleGen()}, n=200, seed=34)
    exprs = [KnownFloatingPointNormalized(
        NormalizeNaNAndZero(ref(0, dt.FLOAT64)))]
    assert_cpu_and_tpu_equal(_project(exprs, scan), conf=CONF)
