"""Planner→mesh lowering: full queries from Session.sql run on the virtual
8-device mesh (VERDICT round-1 item #2). The same SQL with the mesh flag
off is the oracle — both paths share nothing below the planner branch
(single-process execs vs shard_map collectives)."""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import Session


def _mesh_session(n_dev=8):
    return Session({"rapids.tpu.mesh.enabled": True,
                    "rapids.tpu.mesh.devices": n_dev})


def _plain_session():
    return Session({})


def _tpch_tables(rng, n_li=4000, n_ord=700, n_cust=80):
    cust = pd.DataFrame({
        "c_custkey": np.arange(n_cust, dtype=np.int64),
        "c_mktsegment": rng.choice(["BUILDING", "MACHINERY", "AUTO"],
                                   n_cust),
    })
    ord_df = pd.DataFrame({
        "o_orderkey": np.arange(n_ord, dtype=np.int64),
        "o_custkey": rng.integers(0, n_cust, n_ord).astype(np.int64),
        "o_orderdate": rng.integers(8000, 11000, n_ord).astype(np.int64),
        "o_shippriority": rng.integers(0, 3, n_ord).astype(np.int64),
    })
    li = pd.DataFrame({
        "l_orderkey": rng.integers(0, n_ord, n_li).astype(np.int64),
        "l_extendedprice": rng.random(n_li) * 1000,
        "l_discount": rng.random(n_li) * 0.1,
        "l_quantity": rng.integers(1, 50, n_li).astype(np.int64),
        "l_returnflag": rng.choice(["A", "N", "R"], n_li),
        "l_linestatus": rng.choice(["O", "F"], n_li),
        "l_shipdate": rng.integers(9000, 12000, n_li).astype(np.int64),
    })
    return cust, ord_df, li


def _register_all(sess, cust, ord_df, li):
    sess.create_temp_view("customer", sess.create_dataframe(cust))
    sess.create_temp_view("orders", sess.create_dataframe(ord_df))
    sess.create_temp_view("lineitem", sess.create_dataframe(li))


Q1 = """
SELECT l_returnflag, l_linestatus,
       SUM(l_quantity) AS sum_qty,
       SUM(l_extendedprice) AS sum_base_price,
       SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       AVG(l_quantity) AS avg_qty,
       AVG(l_extendedprice) AS avg_price,
       COUNT(*) AS count_order
FROM lineitem
WHERE l_shipdate <= 11000
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT o_orderkey,
       SUM(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING'
  AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND o_orderdate < 9500
  AND l_shipdate > 9500
GROUP BY o_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10
"""


def _run_both(sql):
    rng = np.random.default_rng(31)
    tables = _tpch_tables(rng)
    mesh_sess = _mesh_session()
    _register_all(mesh_sess, *tables)
    mesh_df = mesh_sess.sql(sql)
    mesh_plan = mesh_df._exec().tree_string()
    got = mesh_df.collect()

    plain = _plain_session()
    _register_all(plain, *tables)
    want = plain.sql(sql).collect()
    return got, want, mesh_plan


def _assert_frames_equal(got, want, sort_by=None):
    assert list(got.columns) == list(want.columns)
    if sort_by:
        got = got.sort_values(sort_by).reset_index(drop=True)
        want = want.sort_values(sort_by).reset_index(drop=True)
    assert len(got) == len(want)
    for c in got.columns:
        g, w = got[c], want[c]
        if g.dtype.kind == "f" or w.dtype.kind == "f":
            np.testing.assert_allclose(g.to_numpy(np.float64),
                                       w.to_numpy(np.float64), rtol=1e-9)
        else:
            assert g.tolist() == w.tolist(), c


def test_q1_on_mesh_matches_plain():
    got, want, plan = _run_both(Q1)
    assert "MeshGroupByExec" in plan, plan
    _assert_frames_equal(got, want)


def test_q3_shape_on_mesh_matches_plain():
    got, want, plan = _run_both(Q3)
    assert "MeshShuffledJoinExec" in plan, plan
    assert "MeshGroupByExec" in plan, plan
    _assert_frames_equal(got, want)


def test_mesh_join_kinds_match_plain():
    rng = np.random.default_rng(5)
    left = pd.DataFrame({
        "k": rng.integers(0, 40, 300).astype(np.int64),
        "v": rng.random(300),
    })
    right = pd.DataFrame({
        "k2": np.arange(0, 30, dtype=np.int64),
        "w": rng.random(30),
    })
    for kind in ("inner", "left", "left_semi", "left_anti"):
        ms = _mesh_session()
        ml = ms.create_dataframe(left)
        mr = ms.create_dataframe(right)
        got_df = ml.join(mr, on=[("k", "k2")], how=kind)
        plan = got_df._exec().tree_string()
        assert "MeshShuffledJoinExec" in plan, (kind, plan)
        got = got_df.collect()

        ps = _plain_session()
        pl = ps.create_dataframe(left)
        pr = ps.create_dataframe(right)
        want = pl.join(pr, on=[("k", "k2")], how=kind).collect()
        sort_cols = [c for c in got.columns]
        _assert_frames_equal(got, want, sort_by=sort_cols[:2])


def test_mesh_join_duplicate_build_keys_falls_back_correct():
    # both sides carry duplicate keys -> many-to-many; the dup flag must
    # fire on both orientations and the local kernel must produce the
    # exact expansion
    rng = np.random.default_rng(9)
    left = pd.DataFrame({
        "k": rng.integers(0, 10, 200).astype(np.int64),
        "v": np.arange(200, dtype=np.int64),
    })
    right = pd.DataFrame({
        "k2": rng.integers(0, 10, 150).astype(np.int64),
        "w": np.arange(150, dtype=np.int64),
    })
    ms = _mesh_session()
    ml, mr = ms.create_dataframe(left), ms.create_dataframe(right)
    got = ml.join(mr, on=[("k", "k2")], how="inner").collect()

    want = left.merge(right, left_on="k", right_on="k2", how="inner")
    assert len(got) == len(want)
    got_s = got.sort_values(["k", "v", "w"]).reset_index(drop=True)
    want_s = want.sort_values(["k", "v", "w"]).reset_index(drop=True)
    for c in ("k", "v", "k2", "w"):
        assert got_s[c].tolist() == want_s[c].tolist(), c


def test_mesh_groupby_null_keys_and_strings():
    rng = np.random.default_rng(13)
    n = 500
    key = rng.choice(["x", "y", "z", None], n, p=[0.3, 0.3, 0.3, 0.1])
    df = pd.DataFrame({"k": key, "v": rng.random(n)})
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col

    ms = _mesh_session()
    mdf = ms.create_dataframe(df).group_by("k").agg(
        F.sum(col("v")).alias("s"), F.count("*").alias("n"))
    plan = mdf._exec().tree_string()
    assert "MeshGroupByExec" in plan, plan
    got = mdf.collect()
    want = (df.groupby("k", dropna=False)["v"]
            .agg(["sum", "size"]).reset_index())
    assert len(got) == len(want)
    gs = got.sort_values(got.columns[0], na_position="last") \
        .reset_index(drop=True)
    ws = want.sort_values("k", na_position="last").reset_index(drop=True)
    np.testing.assert_allclose(
        gs.iloc[:, 1].to_numpy(np.float64),
        ws["sum"].to_numpy(np.float64), rtol=1e-9)
    assert gs.iloc[:, 2].tolist() == ws["size"].tolist()
