"""Planner→mesh lowering: full queries from Session.sql run on the virtual
8-device mesh (VERDICT round-1 item #2). The same SQL with the mesh flag
off is the oracle — both paths share nothing below the planner branch
(single-process execs vs shard_map collectives)."""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import Session


def _mesh_session(n_dev=8):
    return Session({"rapids.tpu.mesh.enabled": True,
                    "rapids.tpu.mesh.devices": n_dev})


def _plain_session():
    return Session({})


def _tpch_tables(rng, n_li=4000, n_ord=700, n_cust=80):
    cust = pd.DataFrame({
        "c_custkey": np.arange(n_cust, dtype=np.int64),
        "c_mktsegment": rng.choice(["BUILDING", "MACHINERY", "AUTO"],
                                   n_cust),
    })
    ord_df = pd.DataFrame({
        "o_orderkey": np.arange(n_ord, dtype=np.int64),
        "o_custkey": rng.integers(0, n_cust, n_ord).astype(np.int64),
        "o_orderdate": rng.integers(8000, 11000, n_ord).astype(np.int64),
        "o_shippriority": rng.integers(0, 3, n_ord).astype(np.int64),
    })
    li = pd.DataFrame({
        "l_orderkey": rng.integers(0, n_ord, n_li).astype(np.int64),
        "l_extendedprice": rng.random(n_li) * 1000,
        "l_discount": rng.random(n_li) * 0.1,
        "l_quantity": rng.integers(1, 50, n_li).astype(np.int64),
        "l_returnflag": rng.choice(["A", "N", "R"], n_li),
        "l_linestatus": rng.choice(["O", "F"], n_li),
        "l_shipdate": rng.integers(9000, 12000, n_li).astype(np.int64),
    })
    return cust, ord_df, li


def _register_all(sess, cust, ord_df, li):
    sess.create_temp_view("customer", sess.create_dataframe(cust))
    sess.create_temp_view("orders", sess.create_dataframe(ord_df))
    sess.create_temp_view("lineitem", sess.create_dataframe(li))


Q1 = """
SELECT l_returnflag, l_linestatus,
       SUM(l_quantity) AS sum_qty,
       SUM(l_extendedprice) AS sum_base_price,
       SUM(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       AVG(l_quantity) AS avg_qty,
       AVG(l_extendedprice) AS avg_price,
       COUNT(*) AS count_order
FROM lineitem
WHERE l_shipdate <= 11000
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

Q3 = """
SELECT o_orderkey,
       SUM(l_extendedprice * (1 - l_discount)) AS revenue,
       o_orderdate, o_shippriority
FROM customer, orders, lineitem
WHERE c_mktsegment = 'BUILDING'
  AND c_custkey = o_custkey
  AND l_orderkey = o_orderkey
  AND o_orderdate < 9500
  AND l_shipdate > 9500
GROUP BY o_orderkey, o_orderdate, o_shippriority
ORDER BY revenue DESC, o_orderdate
LIMIT 10
"""


def _run_both(sql):
    rng = np.random.default_rng(31)
    tables = _tpch_tables(rng)
    mesh_sess = _mesh_session()
    _register_all(mesh_sess, *tables)
    mesh_df = mesh_sess.sql(sql)
    mesh_plan = mesh_df._exec().tree_string()
    got = mesh_df.collect()

    plain = _plain_session()
    _register_all(plain, *tables)
    want = plain.sql(sql).collect()
    return got, want, mesh_plan


def _assert_frames_equal(got, want, sort_by=None):
    assert list(got.columns) == list(want.columns)
    if sort_by:
        got = got.sort_values(sort_by).reset_index(drop=True)
        want = want.sort_values(sort_by).reset_index(drop=True)
    assert len(got) == len(want)
    for c in got.columns:
        g, w = got[c], want[c]
        if g.dtype.kind == "f" or w.dtype.kind == "f":
            np.testing.assert_allclose(g.to_numpy(np.float64),
                                       w.to_numpy(np.float64), rtol=1e-9)
        else:
            assert g.tolist() == w.tolist(), c


def test_q1_on_mesh_matches_plain():
    got, want, plan = _run_both(Q1)
    assert "MeshGroupByExec" in plan, plan
    _assert_frames_equal(got, want)


def test_q3_shape_on_mesh_matches_plain():
    got, want, plan = _run_both(Q3)
    assert "MeshShuffledJoinExec" in plan, plan
    assert "MeshGroupByExec" in plan, plan
    _assert_frames_equal(got, want)


def test_mesh_join_kinds_match_plain():
    rng = np.random.default_rng(5)
    left = pd.DataFrame({
        "k": rng.integers(0, 40, 300).astype(np.int64),
        "v": rng.random(300),
    })
    right = pd.DataFrame({
        "k2": np.arange(0, 30, dtype=np.int64),
        "w": rng.random(30),
    })
    for kind in ("inner", "left", "left_semi", "left_anti"):
        ms = _mesh_session()
        ml = ms.create_dataframe(left)
        mr = ms.create_dataframe(right)
        got_df = ml.join(mr, on=[("k", "k2")], how=kind)
        plan = got_df._exec().tree_string()
        assert "MeshShuffledJoinExec" in plan, (kind, plan)
        got = got_df.collect()

        ps = _plain_session()
        pl = ps.create_dataframe(left)
        pr = ps.create_dataframe(right)
        want = pl.join(pr, on=[("k", "k2")], how=kind).collect()
        sort_cols = [c for c in got.columns]
        _assert_frames_equal(got, want, sort_by=sort_cols[:2])


def test_mesh_full_outer_asymmetric_ordinals_matches_plain():
    """FULL OUTER lowers to the mesh (left half UNION null-extended anti
    half, sharded union). Key ordinals deliberately DIFFER between the
    sides (left key at ordinal 1, right key at ordinal 0) — the r3
    advisor found the anti half would apply left-side ordinals to the
    right relation if _compute_kind read self.left_keys."""
    rng = np.random.default_rng(17)
    left = pd.DataFrame({
        "v": rng.random(260),
        "k": rng.integers(0, 50, 260).astype(np.int64),
    })
    right = pd.DataFrame({
        "k2": rng.integers(20, 70, 90).astype(np.int64),
        "w": rng.random(90),
        "x": rng.integers(0, 5, 90).astype(np.int64),
    })
    ms = _mesh_session()
    got_df = ms.create_dataframe(left).join(
        ms.create_dataframe(right), on=[("k", "k2")], how="full")
    plan = got_df._exec().tree_string()
    assert "MeshShuffledJoinExec" in plan, plan
    got = got_df.collect()

    ps = _plain_session()
    want = ps.create_dataframe(left).join(
        ps.create_dataframe(right), on=[("k", "k2")], how="full").collect()
    assert len(got) == len(want)
    key = ["k", "k2", "v", "w"]
    gs = got.sort_values(key, na_position="last").reset_index(drop=True)
    ws = want.sort_values(key, na_position="last").reset_index(drop=True)
    for c in got.columns:
        g = gs[c].to_numpy(np.float64)
        w = ws[c].to_numpy(np.float64)
        np.testing.assert_allclose(g, w, rtol=1e-9, equal_nan=True)


def test_mesh_full_outer_union_stays_sharded(monkeypatch):
    """The full-outer union must not gather either half to the host:
    exactly ONE _gather_db fires (the final collect), never per-half
    (round-3 verdict: _full_union _gather_db-ed both halves)."""
    from spark_rapids_tpu.parallel import execs as pex

    rng = np.random.default_rng(23)
    left = pd.DataFrame({
        "k": rng.integers(0, 30, 200).astype(np.int64),
        "v": np.arange(200, dtype=np.int64)})
    right = pd.DataFrame({
        "k2": rng.integers(10, 40, 80).astype(np.int64),
        "w": np.arange(80, dtype=np.int64)})
    calls = []
    real = pex._gather_db

    def counting(db, n_dev):
        calls.append(len(db.dtypes))
        return real(db, n_dev)

    monkeypatch.setattr(pex, "_gather_db", counting)
    ms = _mesh_session()
    got = ms.create_dataframe(left).join(
        ms.create_dataframe(right), on=[("k", "k2")], how="full").collect()
    assert len(calls) == 1, calls

    want = left.merge(right, left_on="k", right_on="k2", how="outer")
    assert len(got) == len(want)


def test_mesh_full_outer_string_keys_matches_plain():
    """String-keyed FULL OUTER: dictionaries unify ONCE in the full
    branch (keys_unified), and both halves' codes stay consistent for
    the union."""
    rng = np.random.default_rng(41)
    lk = rng.choice(["ash", "birch", "cedar", "oak", "pine"], 120)
    rk = rng.choice(["cedar", "oak", "pine", "sequoia", "yew"], 70)
    left = pd.DataFrame({"k": lk, "v": np.arange(120, dtype=np.int64)})
    right = pd.DataFrame({"k2": rk, "w": np.arange(70, dtype=np.int64)})
    ms = _mesh_session()
    got_df = ms.create_dataframe(left).join(
        ms.create_dataframe(right), on=[("k", "k2")], how="full")
    assert "MeshShuffledJoinExec" in got_df._exec().tree_string()
    got = got_df.collect()

    want = left.merge(right, left_on="k", right_on="k2", how="outer")
    assert len(got) == len(want)
    key = ["k", "k2", "v", "w"]
    gs = got.sort_values(key, na_position="last").reset_index(drop=True)
    ws = want.sort_values(key, na_position="last").reset_index(drop=True)
    for c in ("v", "w"):
        np.testing.assert_allclose(
            gs[c].to_numpy(np.float64), ws[c].to_numpy(np.float64),
            rtol=0, equal_nan=True)
    for c in ("k", "k2"):
        assert [x if isinstance(x, str) else None
                for x in gs[c]] == \
            [x if isinstance(x, str) else None for x in ws[c]], c


def test_mesh_right_outer_matches_plain():
    """RIGHT joins flip to left + column reorder before the mesh branch;
    the reordering projection must stay consumable by chained parents."""
    rng = np.random.default_rng(29)
    left = pd.DataFrame({
        "k": rng.integers(0, 25, 150).astype(np.int64),
        "v": rng.random(150)})
    right = pd.DataFrame({
        "k2": rng.integers(10, 45, 60).astype(np.int64),
        "w": rng.random(60)})
    ms = _mesh_session()
    got_df = ms.create_dataframe(left).join(
        ms.create_dataframe(right), on=[("k", "k2")], how="right")
    plan = got_df._exec().tree_string()
    assert "MeshShuffledJoinExec" in plan, plan
    got = got_df.collect()

    ps = _plain_session()
    want = ps.create_dataframe(left).join(
        ps.create_dataframe(right), on=[("k", "k2")], how="right").collect()
    assert len(got) == len(want)
    key = ["k2", "w", "k"]
    gs = got.sort_values(key, na_position="last").reset_index(drop=True)
    ws = want.sort_values(key, na_position="last").reset_index(drop=True)
    for c in got.columns:
        np.testing.assert_allclose(
            gs[c].to_numpy(np.float64), ws[c].to_numpy(np.float64),
            rtol=1e-9, equal_nan=True)


def test_mesh_join_many_to_many_stays_on_mesh():
    # both sides carry duplicate keys -> many-to-many; the single-key
    # EXPANSION step handles arbitrary fan-out ON the mesh (round 3 —
    # previously this shape dup-flagged and fell back to one device)
    rng = np.random.default_rng(9)
    left = pd.DataFrame({
        "k": rng.integers(0, 10, 200).astype(np.int64),
        "v": np.arange(200, dtype=np.int64),
    })
    right = pd.DataFrame({
        "k2": rng.integers(0, 10, 150).astype(np.int64),
        "w": np.arange(150, dtype=np.int64),
    })
    ms = _mesh_session()
    ml, mr = ms.create_dataframe(left), ms.create_dataframe(right)
    got = ml.join(mr, on=[("k", "k2")], how="inner").collect()

    want = left.merge(right, left_on="k", right_on="k2", how="inner")
    assert len(got) == len(want)
    got_s = got.sort_values(["k", "v", "w"]).reset_index(drop=True)
    want_s = want.sort_values(["k", "v", "w"]).reset_index(drop=True)
    for c in ("k", "v", "k2", "w"):
        assert got_s[c].tolist() == want_s[c].tolist(), c


def test_mesh_groupby_null_keys_and_strings():
    rng = np.random.default_rng(13)
    n = 500
    key = rng.choice(["x", "y", "z", None], n, p=[0.3, 0.3, 0.3, 0.1])
    df = pd.DataFrame({"k": key, "v": rng.random(n)})
    from spark_rapids_tpu.api import functions as F
    from spark_rapids_tpu.api.column import col

    ms = _mesh_session()
    mdf = ms.create_dataframe(df).group_by("k").agg(
        F.sum(col("v")).alias("s"), F.count("*").alias("n"))
    plan = mdf._exec().tree_string()
    assert "MeshGroupByExec" in plan, plan
    got = mdf.collect()
    want = (df.groupby("k", dropna=False)["v"]
            .agg(["sum", "size"]).reset_index())
    assert len(got) == len(want)
    gs = got.sort_values(got.columns[0], na_position="last") \
        .reset_index(drop=True)
    ws = want.sort_values("k", na_position="last").reset_index(drop=True)
    np.testing.assert_allclose(
        gs.iloc[:, 1].to_numpy(np.float64),
        ws["sum"].to_numpy(np.float64), rtol=1e-9)
    assert gs.iloc[:, 2].tolist() == ws["size"].tolist()


def test_mesh_expand_join_left_with_nulls():
    """Left join, many-to-many, null keys on both sides: null keys never
    match but left rows survive with null build columns."""
    rng = np.random.default_rng(21)
    left = pd.DataFrame({
        "k": pd.array([None if x == 0 else int(x)
                       for x in rng.integers(0, 8, 250)], dtype="Int64"),
        "v": np.arange(250, dtype=np.int64)})
    right = pd.DataFrame({
        "k2": pd.array([None if x == 1 else int(x)
                        for x in rng.integers(0, 8, 120)], dtype="Int64"),
        "w": np.arange(120, dtype=np.int64)})
    ms = _mesh_session()
    got_df = ms.create_dataframe(left).join(
        ms.create_dataframe(right), on=[("k", "k2")], how="left")
    assert "MeshShuffledJoinExec" in got_df._exec().tree_string()
    got = got_df.collect()
    want = left.dropna().merge(right.dropna(), left_on="k",
                               right_on="k2", how="inner")
    matched_v = set(want["v"].tolist())
    unmatched = [v for v in left["v"] if v not in matched_v]
    assert len(got) == len(want) + len(unmatched)
    g_matched = got[got["w"].notna()]
    assert sorted(g_matched["v"].tolist()) == sorted(want["v"].tolist())


def test_mesh_expand_join_overflow_grows_bucket():
    """A single hot key whose expansion exceeds the initial static
    output bucket: the step must grow the bucket (recompile), never
    return truncated results."""
    left = pd.DataFrame({"k": np.zeros(200, dtype=np.int64),
                         "v": np.arange(200, dtype=np.int64)})
    right = pd.DataFrame({"k2": np.zeros(150, dtype=np.int64),
                          "w": np.arange(150, dtype=np.int64)})
    ms = _mesh_session()
    got_df = ms.create_dataframe(left).join(
        ms.create_dataframe(right), on=[("k", "k2")], how="inner")
    assert "MeshShuffledJoinExec" in got_df._exec().tree_string()
    got = got_df.collect()
    assert len(got) == 200 * 150
    assert got["v"].sum() == 150 * np.arange(200).sum()
    assert got["w"].sum() == 200 * np.arange(150).sum()


def test_mesh_global_sort():
    """ORDER BY lowers onto the mesh (sampled bounds + all_to_all +
    per-chip sort) and the gathered result is globally ordered —
    including DESC keys, nulls, floats and ties."""
    rng = np.random.default_rng(33)
    n = 3000
    df = pd.DataFrame({
        "a": rng.integers(0, 50, n).astype(np.int64),
        "b": pd.array([None if x == 0 else float(x)
                       for x in np.round(rng.random(n) * 4, 1)],
                      dtype="Float64"),
        "s": rng.choice(["p", "q", "r"], n),
    })
    ms = _mesh_session()
    mdf = ms.create_dataframe(df).order_by("a", "b",
                                           ascending=[True, False])
    plan = mdf._exec().tree_string()
    assert "MeshSortExec" in plan, plan
    got = mdf.collect()

    ps = _plain_session()
    want = ps.create_dataframe(df).order_by(
        "a", "b", ascending=[True, False]).collect()
    assert len(got) == n
    np.testing.assert_array_equal(got["a"].to_numpy(),
                                  want["a"].to_numpy())
    gb = got["b"].to_numpy(dtype=object)
    wb = want["b"].to_numpy(dtype=object)
    for i in range(n):
        gv = None if gb[i] is None or (isinstance(gv := gb[i], float)
                                       and np.isnan(gv)) else float(gb[i])
        wv = None if wb[i] is None or (isinstance(wv := wb[i], float)
                                       and np.isnan(wv)) else float(wb[i])
        assert gv == wv, (i, gb[i], wb[i])


def test_sharded_handoff_skips_host_staging(monkeypatch):
    """Chained mesh execs (join feeding groupby feeding sort) must pass
    DistributedBatch directly: the host staging hop (_shard_batch) fires
    only for the LEAF inputs, never between mesh execs (round-3 verdict
    item #6)."""
    from spark_rapids_tpu.parallel import execs as pex

    rng = np.random.default_rng(7)
    cust, ord_df, li = _tpch_tables(rng)
    sess = _mesh_session()
    _register_all(sess, cust, ord_df, li)
    plain = _plain_session()
    _register_all(plain, cust, ord_df, li)
    # join (int keys) -> groupby (ref-only input) -> global sort
    sql = ("SELECT o_shippriority, l_orderkey, SUM(l_quantity) AS q "
           "FROM lineitem JOIN orders ON l_orderkey = o_orderkey "
           "GROUP BY o_shippriority, l_orderkey "
           "ORDER BY q DESC, o_shippriority, l_orderkey LIMIT 50")
    calls = []
    real = pex._shard_batch

    def counting(mesh, batch, dtypes):
        calls.append(len(dtypes))
        return real(mesh, batch, dtypes)

    monkeypatch.setattr(pex, "_shard_batch", counting)
    got = sess.sql(sql).collect()
    want = plain.sql(sql).collect()
    pd.testing.assert_frame_equal(got.reset_index(drop=True),
                                  want.reset_index(drop=True),
                                  check_dtype=False, atol=1e-9)
    # leaf staging only: the two join inputs (lineitem, orders).
    # groupby consumes the join's DistributedBatch; the groupby OUTPUT
    # legitimately re-stages (final projection is single-device), but
    # the sort then... consumes that host batch. Exactly 2 leaf shards
    # + at most 1 re-stage after the groupby finalize.
    assert len(calls) <= 3, calls


def test_mesh_window_rank_and_agg_matches_plain():
    """q89/q51-class windows lower onto the mesh (r3 verdict #4): rank +
    running sum + whole-partition avg over hash-routed partitions match
    the single-device path, including string partition keys and NULLs."""
    got, want, plan = _run_both("""
SELECT l_returnflag, l_orderkey, l_quantity,
       RANK() OVER (PARTITION BY l_returnflag ORDER BY l_quantity) AS r,
       SUM(l_quantity) OVER (PARTITION BY l_returnflag
                             ORDER BY l_quantity, l_orderkey) AS rsum,
       AVG(l_extendedprice) OVER (PARTITION BY l_returnflag) AS pavg
FROM lineitem WHERE l_shipdate > 9100
""")
    assert "MeshWindowExec" in plan, plan
    _assert_frames_equal(got, want,
                         sort_by=["l_returnflag", "l_orderkey",
                                  "l_quantity", "r"])


def test_mesh_window_lead_lag_frames_match_plain():
    got, want, plan = _run_both("""
SELECT o_custkey, o_orderkey,
       ROW_NUMBER() OVER (PARTITION BY o_custkey
                          ORDER BY o_orderdate, o_orderkey) AS rn,
       LEAD(o_orderdate, 1) OVER (PARTITION BY o_custkey
                                  ORDER BY o_orderdate, o_orderkey)
           AS nxt,
       LAG(o_orderdate, 1, -1) OVER (PARTITION BY o_custkey
                                     ORDER BY o_orderdate, o_orderkey)
           AS prv,
       SUM(o_shippriority) OVER (PARTITION BY o_custkey
                                 ORDER BY o_orderdate, o_orderkey
                                 ROWS BETWEEN 2 PRECEDING AND CURRENT ROW)
           AS wsum
FROM orders
""")
    assert "MeshWindowExec" in plan, plan
    _assert_frames_equal(got, want, sort_by=["o_custkey", "rn"])


def test_mesh_window_over_join_stays_sharded(monkeypatch):
    """window over a mesh join consumes the join's DistributedBatch and
    hands a sharded result onward — no host staging between mesh execs
    (counted via _shard_batch, like the join/groupby hand-off test)."""
    from spark_rapids_tpu.parallel import execs as pex

    sql = """
SELECT o_orderkey, l_quantity,
       ROW_NUMBER() OVER (PARTITION BY o_orderkey
                          ORDER BY l_quantity DESC, l_extendedprice) AS rn
FROM lineitem, orders
WHERE l_orderkey = o_orderkey AND o_orderdate < 9500
ORDER BY o_orderkey, rn
LIMIT 80
"""
    rng = np.random.default_rng(31)
    tables = _tpch_tables(rng)
    mesh_sess = _mesh_session()
    _register_all(mesh_sess, *tables)
    calls = []
    real = pex._shard_batch

    def counting(mesh, batch, dtypes):
        calls.append(len(dtypes))
        return real(mesh, batch, dtypes)

    monkeypatch.setattr(pex, "_shard_batch", counting)
    mesh_df = mesh_sess.sql(sql)
    plan = mesh_df._exec().tree_string()
    assert "MeshWindowExec" in plan, plan
    assert "MeshShuffledJoinExec" in plan, plan
    got = mesh_df.collect()

    plain = _plain_session()
    _register_all(plain, *tables)
    want = plain.sql(sql).collect()
    _assert_frames_equal(got, want)
    # leaf staging only (join's two scan inputs): the window consumed
    # the join's DistributedBatch without a host round trip
    assert len(calls) == 2, calls


def test_mesh_filter_between_mesh_execs_stays_sharded(monkeypatch):
    """A FilterExec between mesh execs applies per chip (mask + local
    compaction, parallel/filter_step.py) instead of gathering the chain
    to host — the explicit-JOIN form plans exactly this shape (the
    planner keeps the WHERE above the join)."""
    from spark_rapids_tpu.parallel import execs as pex

    # the predicate references BOTH sides, so no pushdown rule can move
    # it below the join - it must run as a sharded mesh filter
    sql = """
SELECT o_orderkey, l_quantity,
       ROW_NUMBER() OVER (PARTITION BY o_orderkey
                          ORDER BY l_quantity DESC, l_extendedprice) AS rn
FROM lineitem JOIN orders ON l_orderkey = o_orderkey
WHERE o_orderdate < l_shipdate
ORDER BY o_orderkey, rn
LIMIT 80
"""
    rng = np.random.default_rng(31)
    tables = _tpch_tables(rng)
    mesh_sess = _mesh_session()
    _register_all(mesh_sess, *tables)
    calls = []
    real = pex._shard_batch

    def counting(mesh, batch, dtypes):
        calls.append(len(dtypes))
        return real(mesh, batch, dtypes)

    monkeypatch.setattr(pex, "_shard_batch", counting)
    mesh_df = mesh_sess.sql(sql)
    plan = mesh_df._exec().tree_string()
    assert "FilterExec" in plan, plan
    assert "MeshWindowExec" in plan, plan
    got = mesh_df.collect()

    plain = _plain_session()
    _register_all(plain, *tables)
    want = plain.sql(sql).collect()
    _assert_frames_equal(got, want)
    assert len(calls) == 2, calls  # join leaves only; filter ran sharded
