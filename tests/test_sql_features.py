"""SQL front-end features added for TPCx-BB breadth (round 3): IN/NOT IN
subqueries, one-sided semi-join ON conditions, HAVING/ORDER BY alias
resolution, constant folding, round/datediff/pmod scalars,
stddev/variance aggregates and the mixed distinct rewrite. Reference
semantics: Spark SQL (the reference accelerates these same shapes via
GpuOverrides; RewritePredicateSubquery for the subquery forms)."""
import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu.api import Session


@pytest.fixture()
def sess():
    s = Session()
    rng = np.random.default_rng(0)
    n = 400
    s.create_temp_view("sales", s.create_dataframe(pd.DataFrame({
        "k": rng.integers(1, 30, n),
        "v": np.round(rng.random(n) * 100, 2),
        "t": rng.integers(1, 40, n),
    })))
    s.create_temp_view("dim", s.create_dataframe(pd.DataFrame({
        "id": np.arange(1, 31), "cat": rng.integers(0, 4, 30),
    })))
    return s


def test_in_subquery_semi_join(sess):
    got = sess.sql("SELECT COUNT(*) AS n FROM sales WHERE k IN "
                   "(SELECT id FROM dim WHERE cat = 1)").collect()
    dim = sess.sql("SELECT id FROM dim WHERE cat = 1").collect()
    all_ = sess.sql("SELECT k FROM sales").collect()
    want = int(all_["k"].isin(dim["id"]).sum())
    assert int(got["n"][0]) == want


def test_not_in_subquery_null_aware():
    s = Session()
    s.create_temp_view("l", s.create_dataframe(pd.DataFrame(
        {"x": pd.array([1, 2, None, 4], dtype="Int64")})))
    s.create_temp_view("r", s.create_dataframe(pd.DataFrame(
        {"y": pd.array([2, 3], dtype="Int64")})))
    s.create_temp_view("rn", s.create_dataframe(pd.DataFrame(
        {"y": pd.array([2, None], dtype="Int64")})))
    got = s.sql(
        "SELECT x FROM l WHERE x NOT IN (SELECT y FROM r)").collect()
    assert sorted(got["x"].tolist()) == [1, 4]
    # any NULL in the subquery -> empty (SQL three-valued logic)
    got2 = s.sql(
        "SELECT x FROM l WHERE x NOT IN (SELECT y FROM rn)").collect()
    assert len(got2) == 0


def test_semi_join_one_sided_on_condition(sess):
    got = sess.sql("""
        SELECT COUNT(*) AS n FROM sales s
        LEFT SEMI JOIN dim d ON s.k = d.id AND d.cat = 2
    """).collect()
    dim = sess.sql("SELECT id FROM dim WHERE cat = 2").collect()
    all_ = sess.sql("SELECT k FROM sales").collect()
    assert int(got["n"][0]) == int(all_["k"].isin(dim["id"]).sum())


def test_anti_join_left_side_on_condition_rejected(sess):
    from spark_rapids_tpu.sql.parser import SqlError

    with pytest.raises(SqlError):
        sess.sql("SELECT * FROM sales s LEFT ANTI JOIN dim d "
                 "ON s.k = d.id AND s.v > 3")


def test_having_and_order_by_alias(sess):
    got = sess.sql("""
        SELECT k, COUNT(*) AS cnt FROM sales GROUP BY k
        HAVING cnt >= 10 ORDER BY cnt DESC, k LIMIT 5
    """).collect()
    df = sess.sql("SELECT k FROM sales").collect()
    vc = df["k"].value_counts()
    want = vc[vc >= 10].reset_index()
    want.columns = ["k", "cnt"]
    want = want.sort_values(["cnt", "k"],
                            ascending=[False, True]).head(5)
    assert got["k"].tolist() == want["k"].tolist()
    assert got["cnt"].tolist() == want["cnt"].tolist()


def test_constant_folding_in_list_and_division(sess):
    got = sess.sql("SELECT COUNT(*) AS n FROM sales "
                   "WHERE k IN (3, (3 + 1)) AND v > 2.0 / 4.0").collect()
    df = sess.sql("SELECT k, v FROM sales").collect()
    want = int((df["k"].isin([3, 4]) & (df["v"] > 0.5)).sum())
    assert int(got["n"][0]) == want


def test_round_half_up(sess):
    s = Session()
    s.create_temp_view("t", s.create_dataframe(pd.DataFrame(
        {"v": [2.5, -2.5, 1.25, 1.35, 10.0]})))
    got = s.sql("SELECT round(v, 0) AS r0, round(v, 1) AS r1 "
                "FROM t").collect()
    assert got["r0"].tolist() == [3.0, -3.0, 1.0, 1.0, 10.0]
    # 1.25 is exact -> HALF_UP 1.3; double 1.35 is 1.35000...0089 -> 1.4.
    # allclose: XLA lowers the /10 as *0.1 (1 ulp off exact division)
    np.testing.assert_allclose(got["r1"], [2.5, -2.5, 1.3, 1.4, 10.0],
                               rtol=1e-12)


def test_stddev_variance_aggregates(sess):
    got = sess.sql("""
        SELECT k, stddev_samp(v) AS sd, var_samp(v) AS vs,
               stddev_pop(v) AS sp, var_pop(v) AS vp
        FROM sales GROUP BY k ORDER BY k
    """).collect()
    df = sess.sql("SELECT k, v FROM sales").collect()
    g = df.groupby("k")["v"]
    np.testing.assert_allclose(got["sd"], g.std(ddof=1).values,
                               rtol=1e-6)
    np.testing.assert_allclose(got["vs"], g.var(ddof=1).values,
                               rtol=1e-6)
    np.testing.assert_allclose(got["sp"], g.std(ddof=0).values,
                               rtol=1e-6)
    np.testing.assert_allclose(got["vp"], g.var(ddof=0).values,
                               rtol=1e-6)


def test_stddev_samp_single_row_is_nan():
    s = Session()
    s.create_temp_view("t", s.create_dataframe(pd.DataFrame(
        {"k": [1, 2, 2], "v": [5.0, 1.0, 3.0]})))
    got = s.sql("SELECT k, stddev_samp(v) AS sd FROM t GROUP BY k "
                "ORDER BY k").collect()
    # Spark CentralMomentAgg: n == 1 -> NaN (a value), not NULL
    assert np.isnan(got["sd"][0])
    np.testing.assert_allclose(got["sd"][1], np.std([1.0, 3.0], ddof=1))


def test_variance_large_magnitude_no_cancellation():
    """var over large-magnitude low-variance data must not collapse to
    0.0 (r3 review: the raw sum-of-squares formula lost all precision at
    |x| ~ 1e8; the m2 kernel op computes the moment shifted)."""
    s = Session()
    s.create_temp_view("t", s.create_dataframe(pd.DataFrame(
        {"k": [1, 1, 2, 2, 2], "v": [1e8, 1e8 + 1,
                                     7e7 + 0.1, 7e7 + 0.2, 7e7 + 0.3]})))
    got = s.sql("SELECT k, var_samp(v) AS vs FROM t GROUP BY k "
                "ORDER BY k").collect()
    np.testing.assert_allclose(got["vs"][0], 0.5, rtol=1e-9)
    np.testing.assert_allclose(got["vs"][1], 0.01, rtol=1e-6)


def test_mixed_distinct_and_plain_aggregates(sess):
    got = sess.sql("""
        SELECT k, COUNT(DISTINCT t) AS dt, COUNT(v) AS c, SUM(v) AS sv,
               MIN(v) AS mn, MAX(v) AS mx
        FROM sales GROUP BY k ORDER BY k
    """).collect()
    df = sess.sql("SELECT k, v, t FROM sales").collect()
    g = df.groupby("k")
    np.testing.assert_array_equal(got["dt"],
                                  g["t"].nunique().values)
    np.testing.assert_array_equal(got["c"], g["v"].count().values)
    np.testing.assert_allclose(got["sv"], g["v"].sum().values,
                               rtol=1e-9)
    np.testing.assert_allclose(got["mn"], g["v"].min().values)
    np.testing.assert_allclose(got["mx"], g["v"].max().values)


def test_ungrouped_mixed_distinct_on_empty_input():
    """count(a) must stay 0 (not NULL) on empty input — the mixed
    rewrite is skipped for ungrouped counts (r3 review finding)."""
    s = Session()
    s.create_temp_view("t", s.create_dataframe(pd.DataFrame(
        {"a": [1.0], "b": [2]})))
    got = s.sql("SELECT COUNT(a) AS c, COUNT(DISTINCT b) AS d FROM t "
                "WHERE a > 100").collect()
    assert int(got["c"][0]) == 0
    assert int(got["d"][0]) == 0


def test_least_skips_nan_greatest_propagates():
    """Spark orders NaN LARGEST: least() skips NaN, greatest() keeps it."""
    s = Session()
    # NaN must be COMPUTED: pandas-ingested NaN becomes NULL (pyspark
    # createDataFrame semantics), which greatest/least legitimately skip
    s.create_temp_view("t", s.create_dataframe(pd.DataFrame(
        {"a": [-1.0, 1.0], "b": [2.0, 5.0]})))
    got = s.sql("SELECT least(sqrt(a), b) AS l, "
                "greatest(sqrt(a), b) AS g FROM t").collect()
    assert got["l"].tolist() == [2.0, 1.0]
    assert np.isnan(got["g"][0]) and got["g"][1] == 5.0


def test_greatest_over_strings_rejected():
    from spark_rapids_tpu.sql.parser import SqlError

    s = Session()
    s.create_temp_view("t", s.create_dataframe(pd.DataFrame(
        {"a": ["x"], "b": ["y"]})))
    with pytest.raises(SqlError):
        s.sql("SELECT greatest(a, b) FROM t")


def test_datediff_and_pmod(sess):
    s = Session()
    s.create_temp_view("t", s.create_dataframe(pd.DataFrame(
        {"d": pd.to_datetime(["2001-03-10", "2001-03-20"]),
         "x": [7, -7]})))
    got = s.sql("SELECT datediff(d, '2001-03-16') AS dd, "
                "pmod(x, 5) AS pm FROM t").collect()
    assert got["dd"].tolist() == [-6, 4]
    assert got["pm"].tolist() == [2, 3]


def test_scalar_subquery_with_outer_aggregate():
    """TPC-DS q32/q92 shape: an aggregate compared against a scalar
    subquery — the sub's column must survive the aggregation (r3 review:
    it used to vanish with the pre-agg scope)."""
    s = Session()
    s.create_temp_view("a", s.create_dataframe(pd.DataFrame(
        {"k": [1, 1, 2, 2, 2], "x": [1.0, 2.0, 3.0, 4.0, 5.0]})))
    s.create_temp_view("b", s.create_dataframe(pd.DataFrame(
        {"y": [10.0, 20.0]})))
    got = s.sql("SELECT SUM(x) / (SELECT SUM(y) FROM b) AS r "
                "FROM a").collect()
    np.testing.assert_allclose(got["r"][0], 15.0 / 30.0)
    got2 = s.sql("SELECT k, COUNT(*) AS c FROM a "
                 "WHERE x > (SELECT AVG(y) FROM b) - 13.5 "
                 "GROUP BY k ORDER BY k").collect()
    # avg(y)=15 -> threshold 1.5 -> x in {2,3,4,5}
    assert got2["k"].tolist() == [1, 2]
    assert got2["c"].tolist() == [1, 3]
    # scalar sub INSIDE an aggregate argument evaluates pre-grouping
    got3 = s.sql("SELECT k, SUM(x - (SELECT AVG(y) FROM b) / 15.0) "
                 "AS s FROM a GROUP BY k ORDER BY k").collect()
    np.testing.assert_allclose(got3["s"], [1.0, 9.0])


# ---------------------------------------------------------------------------
# round-3 TPC-DS breadth features: ROLLUP, EXISTS, INTERSECT/EXCEPT,
# simple CASE, || concatenation


def test_rollup_levels_and_grouping(sess):
    got = sess.sql(
        "SELECT k, t, SUM(v) AS s, grouping(k) AS gk, grouping(t) AS gt "
        "FROM sales GROUP BY ROLLUP(k, t) "
        "ORDER BY gk, gt, k, t").collect()
    base = sess.sql("SELECT k, t, v FROM sales").collect()
    detail = base.groupby(["k", "t"])["v"].sum()
    subtot = base.groupby("k")["v"].sum()
    total = base["v"].sum()
    assert len(got) == len(detail) + len(subtot) + 1
    # detail rows first (gk=gt=0), then per-k subtotals (gt=1 only),
    # then the grand total (gk=gt=1)
    d = got[(got["gk"] == 0) & (got["gt"] == 0)]
    np.testing.assert_allclose(
        sorted(d["s"]), sorted(detail.values), rtol=1e-9)
    sub = got[(got["gk"] == 0) & (got["gt"] == 1)]
    assert sub["t"].isna().all()
    np.testing.assert_allclose(
        sub.sort_values("k")["s"].values,
        subtot.sort_index().values, rtol=1e-9)
    g = got[got["gk"] == 1]
    assert len(g) == 1 and g["k"].isna().all()
    np.testing.assert_allclose(g["s"].values[0], total, rtol=1e-9)


def test_rollup_grouping_in_expressions(sess):
    # TPC-DS q36/q86 shape: grouping() inside CASE and arithmetic
    got = sess.sql(
        "SELECT grouping(k) + grouping(t) AS lvl, "
        "CASE WHEN grouping(t) = 0 THEN k END AS pk, SUM(v) AS s "
        "FROM sales GROUP BY ROLLUP(k, t) ORDER BY lvl, pk, s").collect()
    assert set(got["lvl"]) == {0, 1, 2}
    assert got[got["lvl"] == 2]["pk"].isna().all()


def test_exists_correlated_semi(sess):
    got = sess.sql(
        "SELECT count(*) AS n FROM dim WHERE EXISTS "
        "(SELECT * FROM sales WHERE k = id AND v > 90)").collect()
    hit = sess.sql("SELECT k FROM sales WHERE v > 90").collect()
    dim = sess.sql("SELECT id FROM dim").collect()
    want = int(dim["id"].isin(hit["k"]).sum())
    assert int(got["n"][0]) == want


def test_not_exists_correlated_anti(sess):
    got = sess.sql(
        "SELECT count(*) AS n FROM dim WHERE NOT EXISTS "
        "(SELECT * FROM sales WHERE sales.k = dim.id)").collect()
    ks = sess.sql("SELECT k FROM sales").collect()
    dim = sess.sql("SELECT id FROM dim").collect()
    want = int((~dim["id"].isin(ks["k"])).sum())
    assert int(got["n"][0]) == want


def test_exists_inner_join_in_subquery(sess):
    # TPC-DS q10/q35 shape: the EXISTS subquery itself comma-joins
    # tables; only the correlated conjunct becomes the join key
    got = sess.sql(
        "SELECT count(*) AS n FROM dim WHERE EXISTS "
        "(SELECT * FROM sales, dim d2 WHERE k = dim.id "
        "AND t = d2.id AND d2.cat = 1)").collect()
    import pandas as pd_
    sales = sess.sql("SELECT k, t FROM sales").collect()
    dim = sess.sql("SELECT id, cat FROM dim").collect()
    inner = sales.merge(dim[dim["cat"] == 1], left_on="t",
                        right_on="id")
    want = int(dim["id"].isin(inner["k"]).sum())
    assert int(got["n"][0]) == want


def test_uncorrelated_exists_rejected(sess):
    from spark_rapids_tpu.sql.parser import SqlError
    with pytest.raises(SqlError, match="uncorrelated EXISTS"):
        sess.sql("SELECT * FROM dim WHERE EXISTS "
                 "(SELECT * FROM sales WHERE v > 1)")


def test_intersect_and_except(sess):
    inter = sess.sql("SELECT k FROM sales WHERE v > 50 INTERSECT "
                     "SELECT k FROM sales WHERE t > 20").collect()
    a = set(sess.sql("SELECT DISTINCT k FROM sales WHERE v > 50"
                     ).collect()["k"])
    b = set(sess.sql("SELECT DISTINCT k FROM sales WHERE t > 20"
                     ).collect()["k"])
    assert set(inter["k"]) == (a & b)
    exc = sess.sql("SELECT k FROM sales WHERE v > 50 EXCEPT "
                   "SELECT k FROM sales WHERE t > 20").collect()
    assert set(exc["k"]) == (a - b)
    # chained: (A INTERSECT B) EXCEPT C, left-associative
    c = set(sess.sql("SELECT DISTINCT k FROM sales WHERE v < 5"
                     ).collect()["k"])
    chain = sess.sql(
        "SELECT k FROM sales WHERE v > 50 INTERSECT "
        "SELECT k FROM sales WHERE t > 20 EXCEPT "
        "SELECT k FROM sales WHERE v < 5").collect()
    assert set(chain["k"]) == (a & b) - c


def test_simple_case(sess):
    got = sess.sql(
        "SELECT CASE k WHEN 1 THEN 'one' WHEN 2 THEN 'two' "
        "ELSE 'many' END AS w, count(*) AS n FROM sales "
        "GROUP BY CASE k WHEN 1 THEN 'one' WHEN 2 THEN 'two' "
        "ELSE 'many' END ORDER BY w").collect()
    base = sess.sql("SELECT k FROM sales").collect()["k"]
    want = {"one": int((base == 1).sum()), "two": int((base == 2).sum()),
            "many": int((base > 2).sum())}
    assert dict(zip(got["w"], got["n"])) == \
        {k: v for k, v in want.items() if v}


def test_concat_operator():
    s = Session()
    s.create_temp_view("t", s.create_dataframe(pd.DataFrame(
        {"a": ["x", "y"], "b": ["1", "2"]})))
    got = s.sql("SELECT a || ', ' || b AS c FROM t ORDER BY c").collect()
    assert got["c"].tolist() == ["x, 1", "y, 2"]


def test_setops_null_safe():
    """SQL set ops treat NULLs as EQUAL (Spark's <=> in the semi/anti
    rewrite): A EXCEPT A must be empty even with NULL rows, and a NULL
    row intersects with a NULL row."""
    s = Session()
    s.create_temp_view("a", s.create_dataframe(pd.DataFrame(
        {"x": pd.array([1, 2, None], dtype="Int64")})))
    s.create_temp_view("b", s.create_dataframe(pd.DataFrame(
        {"x": pd.array([2, None], dtype="Int64")})))
    got = s.sql("SELECT x FROM a EXCEPT SELECT x FROM a").collect()
    assert len(got) == 0
    got = s.sql("SELECT x FROM a EXCEPT SELECT x FROM b").collect()
    assert got["x"].tolist() == [1]
    got = s.sql("SELECT x FROM a INTERSECT SELECT x FROM b").collect()
    vals = set(None if pd.isna(v) else int(v) for v in got["x"])
    assert vals == {2, None}


def test_setops_nan_and_negzero_normalized():
    """Set ops treat NaN = NaN and -0.0 = 0.0 (Spark's
    NormalizeNaNAndZero): A EXCEPT A over a NaN-bearing float column
    cancels the NaN rows, and NaN never collides with true 0.0.

    NaN enters COMPUTATIONALLY (SQRT of a negative) — pandas ingest
    conflates NaN with NULL, so raw NaN inputs become nulls upstream."""
    s = Session()
    # SQRT(x): [-1 -> NaN, 2.25 -> 1.5, 0 -> 0.0, -0.0 -> -0.0]
    s.create_temp_view("raw_a", s.create_dataframe(pd.DataFrame(
        {"x": np.array([-1.0, 2.25, 0.0], dtype=np.float64)})))
    s.create_temp_view("raw_b", s.create_dataframe(pd.DataFrame(
        {"x": np.array([-1.0], dtype=np.float64),
         "z": np.array([-0.0], dtype=np.float64)})))
    a = "SELECT SQRT(x) AS y FROM raw_a"
    got = s.sql(f"{a} EXCEPT {a}").collect()
    assert len(got) == 0
    # b carries {NaN (sqrt -1), -0.0}: 0.0 == -0.0 cancels, 1.5 survives
    s.create_temp_view("b_view", s.sql(
        "SELECT SQRT(x) AS y FROM raw_b UNION ALL SELECT z FROM raw_b"))
    b = "SELECT y FROM b_view"
    got = s.sql(f"{a} EXCEPT {b}").collect()
    assert got["y"].tolist() == [1.5]
    got = s.sql(f"{a} INTERSECT {b}").collect()
    vals = sorted(got["y"], key=lambda v: (not np.isnan(v), v))
    assert len(vals) == 2 and np.isnan(vals[0]) and vals[1] == 0.0
    # NaN must NOT equal a true 0.0 row
    got = s.sql(f"SELECT z AS y FROM raw_b INTERSECT {b}").collect()
    assert got["y"].tolist() == [0.0]  # matches b's -0.0, not its NaN


def test_exists_subquery_with_local_cte(sess):
    """EXISTS over a subquery that defines its own CTE: the correlation
    classifier must register the subquery's WITH clause before planning
    its FROM relations (r3 advisor finding)."""
    got = sess.sql(
        "SELECT count(*) AS n FROM dim WHERE EXISTS "
        "(WITH big AS (SELECT k FROM sales WHERE v > 50) "
        " SELECT * FROM big WHERE big.k = id)").collect()
    want = sess.sql(
        "SELECT count(*) AS n FROM dim WHERE EXISTS "
        "(SELECT * FROM sales WHERE v > 50 AND k = id)").collect()
    assert got["n"].tolist() == want["n"].tolist()
    assert int(got["n"].iloc[0]) > 0


def test_exists_limit_rejected(sess):
    from spark_rapids_tpu.sql.parser import SqlError
    with pytest.raises(SqlError, match="ORDER BY/LIMIT"):
        sess.sql("SELECT * FROM dim WHERE EXISTS "
                 "(SELECT * FROM sales WHERE k = id LIMIT 0)")
