"""Semantic result & fragment cache (service/cache): the acceptance
suite. Every fence is an ORACLE fence — hit, miss, follower, degraded
and spilled paths must all return the exact frame a cache-off run
returns — plus invalidation (a version bump is never served stale) and
the resource contracts (single-flight, OOM-degrade, disk round trip)."""
import os
import threading
import time

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.api import Session
from spark_rapids_tpu.benchmarks.runner import (ALL_BENCHMARKS,
                                                BenchmarkRunner)
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.cpu.engine import execute_cpu
from spark_rapids_tpu.memory import fault_injection as FI
from spark_rapids_tpu.memory.catalog import (BufferCatalog, StorageTier,
                                             get_catalog, reset_catalog)
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.fingerprint import plan_fingerprint
from spark_rapids_tpu.service import QueryService
from spark_rapids_tpu.service.cache import snapshots

from tests.compare import assert_frames_equal

SF = 0.001


@pytest.fixture(scope="module")
def tpch_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("cache_tpch"))
    BenchmarkRunner(d, SF).ensure_data("tpch_q1")
    return d


@pytest.fixture(scope="module")
def tpcxbb_dir(tmp_path_factory):
    d = str(tmp_path_factory.mktemp("cache_tpcxbb"))
    BenchmarkRunner(d, SF).ensure_data("tpcxbb_q26")
    return d


@pytest.fixture(autouse=True)
def _clean_injector():
    FI.get_injector().disarm()
    yield
    FI.get_injector().disarm()


def _write(path: str, df: pd.DataFrame) -> None:
    import pyarrow as pa
    import pyarrow.parquet as pq

    pq.write_table(pa.Table.from_pandas(df), path)
    # parquet rewrites within one mtime tick must still version-bump
    os.utime(path, ns=(time.time_ns(), time.time_ns()))


def _tbl(seed=7, n=4000, nk=12):
    rng = np.random.default_rng(seed)
    return pd.DataFrame({"k": rng.integers(0, nk, n).astype(np.int64),
                         "v": rng.random(n)})


AGG_SQL = "SELECT k, SUM(v) AS sv, COUNT(*) AS n FROM t GROUP BY k"


# -- (1) hit / miss / off oracle fence --------------------------------------


@pytest.mark.parametrize("qname", ["tpch_q1", "tpch_q6", "tpcxbb_q26"])
def test_hit_miss_off_oracle_fence(qname, tpch_dir, tpcxbb_dir):
    """Three runs of a real TPC query — cold miss, warm hit, and a
    cache-disabled control — must all match the CPU oracle, and the hit
    must do zero device work (no scheduler slices at all)."""
    data_dir = tpcxbb_dir if qname.startswith("tpcxbb") else tpch_dir
    plan_fn = ALL_BENCHMARKS[qname]
    oracle = execute_cpu(plan_fn(data_dir)).to_pandas()

    svc = QueryService()
    try:
        # fresh plan objects per submit: the key is STRUCTURAL, not
        # object identity — two dashboards building the same query
        # independently must collide on one entry
        h_miss = svc.submit(plan_fn(data_dir))
        miss = h_miss.result(timeout=600)
        h_hit = svc.submit(plan_fn(data_dir))
        hit = h_hit.result(timeout=600)
        st = svc.stats()
        assert st.cache["result"]["hits"] == 1
        assert st.cache["result"]["misses"] >= 1
        rec = [q for q in st.per_query
               if q["query_id"] == h_hit.query_id][0]
        assert rec["slices"] == 0, "a result-cache hit must not run"
        assert rec["run_time_s"] is not None and rec["run_time_s"] >= 0
    finally:
        svc.shutdown()

    off = QueryService({cfg.SERVICE_CACHE_ENABLED.key: False})
    try:
        control = off.submit(plan_fn(data_dir)).result(timeout=600)
        assert off.stats().cache["enabled"] is False
        assert off.stats().cache["result"]["hits"] == 0
    finally:
        off.shutdown()

    assert_frames_equal(oracle, miss)
    assert_frames_equal(oracle, hit)
    assert_frames_equal(oracle, control)


# -- (2) invalidation: data changed under the same plan ---------------------


def test_version_bump_invalidates(tmp_path):
    """Rewriting the backing parquet between two identical submits must
    produce the NEW answer — the file's (mtime, size) participates in
    the key, so the old entry is simply unreachable."""
    p = str(tmp_path / "t.parquet")
    old = _tbl(seed=1)
    _write(p, old)
    s = Session()
    s.register_parquet("t", p)
    q = s.sql(AGG_SQL)
    svc = QueryService(s.conf, session=s)
    try:
        r1 = svc.submit(q).result(timeout=300)
        assert_frames_equal(
            old.groupby("k").agg(sv=("v", "sum"),
                                 n=("v", "size")).reset_index(), r1)
        new = _tbl(seed=2)
        _write(p, new)
        r2 = svc.submit(q).result(timeout=300)
        assert_frames_equal(
            new.groupby("k").agg(sv=("v", "sum"),
                                 n=("v", "size")).reset_index(), r2)
        st = svc.stats().cache
        assert st["result"]["hits"] == 0, \
            "a rewritten table must never serve the old frame"
        assert st["result"]["misses"] == 2
    finally:
        svc.shutdown()


def test_manual_bump_changes_fingerprint(tmp_path):
    p = str(tmp_path / "t.parquet")
    _write(p, _tbl())
    s = Session()
    s.register_parquet("t", p)
    plan = s.sql(AGG_SQL)._plan
    before = plan_fingerprint(plan)
    assert before is not None
    assert snapshots.bump_plan(plan) == 1
    after = plan_fingerprint(plan)
    assert after is not None and after.key != before.key


# -- (3) replaced temp view is a snapshot event (satellite 2) ---------------


def test_replaced_temp_view_not_served_stale(tmp_path):
    """createOrReplaceTempView over an existing name bumps the displaced
    target's snapshot version: a plan captured against the OLD view must
    re-compute after the replace, never serve its pre-replace cached
    result (the silent-replace staleness regression)."""
    from spark_rapids_tpu.io import ParquetSource

    pa_, pb = str(tmp_path / "a.parquet"), str(tmp_path / "b.parquet")
    _write(pa_, _tbl(seed=3))
    _write(pb, _tbl(seed=4))
    s = Session()
    assert s.table_version("t") == 0
    assert s.create_temp_view("t", ParquetSource(pa_)) == 1
    q_old = s.sql(AGG_SQL)  # plans against (and pins) the OLD source
    svc = QueryService(s.conf, session=s)
    try:
        r1 = svc.submit(q_old).result(timeout=300)
        assert svc.stats().cache["result"]["misses"] == 1
        assert s.create_temp_view("t", ParquetSource(pb)) == 2
        assert s.table_version("t") == 2
        r2 = svc.submit(q_old).result(timeout=300)
        st = svc.stats().cache
        assert st["result"]["hits"] == 0, \
            "replaced view's old cached result was served"
        assert st["result"]["misses"] == 2
        # the old plan still reads the old files — same ANSWER, but it
        # must have been recomputed, not replayed
        assert_frames_equal(r1, r2)
        # a plan over the replacement source computes the new data
        r3 = svc.submit(s.sql(AGG_SQL)).result(timeout=300)
        assert_frames_equal(
            _tbl(seed=4).groupby("k").agg(
                sv=("v", "sum"), n=("v", "size")).reset_index(), r3)
    finally:
        svc.shutdown()


# -- (4) single-flight ------------------------------------------------------


class SlowKeyedSource(pn.DataSource):
    """Keyable via the cache_identity/cache_version protocol (GateSource
    and InMemorySource are unkeyable BY DESIGN), with a gate so the
    leader is provably still running when followers arrive."""

    def __init__(self, tag: str, n=2000):
        self.tag = tag
        self.n = n
        self.gate = threading.Event()
        self.reads = 0

    def cache_identity(self):
        return ("slow-keyed", self.tag)

    def cache_version(self):
        return 1

    def schema(self):
        return Schema(["k", "v"], [dt.INT64, dt.FLOAT64])

    def estimated_row_count(self):
        return self.n

    def read_host(self):
        assert self.gate.wait(timeout=60), "gate never opened"
        self.reads += 1
        rng = np.random.default_rng(11)
        return ({"k": rng.integers(0, 6, self.n).astype(np.int64),
                 "v": rng.random(self.n)}, {"k": None, "v": None})


def test_single_flight_concurrent_identical_misses():
    """N concurrent identical submissions compute ONCE: the first
    becomes leader, the rest park as followers and are served the
    leader's frame at finalize."""
    from spark_rapids_tpu.api import col, functions as F

    from spark_rapids_tpu.api.dataframe import DataFrame

    s = Session()
    src = SlowKeyedSource("sf")
    base = DataFrame(pn.ScanNode(src), s)
    q = base.filter(col("v") > 0.2).group_by("k").agg(
        F.sum(col("v")).alias("sv"), F.count("*").alias("n"))
    svc = QueryService(s.conf, session=s)
    try:
        handles = [svc.submit(q, tenant=f"t{i}") for i in range(4)]
        # all four accepted while the leader is gated; open the gate
        time.sleep(0.1)
        src.gate.set()
        frames = [h.result(timeout=300) for h in handles]
        st = svc.stats().cache["result"]
        assert st["single_flight_followers"] == 3
        assert st["misses"] >= 1
        assert src.reads == 1, \
            f"single-flight must compute once, read {src.reads}x"
        ref = frames[0].sort_values("k").reset_index(drop=True)
        for f in frames[1:]:
            pd.testing.assert_frame_equal(
                f.sort_values("k").reset_index(drop=True), ref)
    finally:
        src.gate.set()
        svc.shutdown()


# -- (5) OOM while materializing degrades to cache-off ----------------------


def test_oom_during_capture_degrades_not_corrupts(tmp_path):
    """An injected OOM inside fragment materialization drops the entry
    and streams the subtree fresh — the query completes oracle-matched.
    After disarm the next run captures, and the third run serves."""
    p = str(tmp_path / "t.parquet")
    src_df = _tbl(seed=5)
    _write(p, src_df)
    s = Session()
    s.register_parquet("t", p)
    q = s.sql(AGG_SQL)
    oracle = src_df.groupby("k").agg(sv=("v", "sum"),
                                     n=("v", "size")).reset_index()
    # result tier off so every submit drives the fragment path
    svc = QueryService({cfg.SERVICE_CACHE_RESULT.key: False},
                       session=s)
    try:
        FI.get_injector().arm(at_call=1, consecutive=1,
                              sites=["cache.fragment.materialize"],
                              max_injections=1)
        r1 = svc.submit(q).result(timeout=300)
        assert_frames_equal(oracle, r1)
        st = svc.stats().cache["fragment"]
        assert st["oom_degraded"] >= 1
        assert st["entries"] == 0, "the half-built entry must be gone"
        FI.get_injector().disarm()
        r2 = svc.submit(q).result(timeout=300)  # recapture succeeds
        assert_frames_equal(oracle, r2)
        assert svc.stats().cache["fragment"]["published"] >= 1
        r3 = svc.submit(q).result(timeout=300)  # and now it serves
        assert_frames_equal(oracle, r3)
        assert svc.stats().cache["fragment"]["hits"] >= 1
    finally:
        svc.shutdown()


# -- (6) cached fragment round-trips the disk tier bit-exact ----------------


def test_fragment_spill_disk_roundtrip_bit_exact(tmp_path):
    cat = reset_catalog(BufferCatalog(
        spill_dir=str(tmp_path / "spill")))
    try:
        p = str(tmp_path / "t.parquet")
        _write(p, _tbl(seed=6))
        s = Session()
        s.register_parquet("t", p)
        q = s.sql(AGG_SQL)
        svc = QueryService({cfg.SERVICE_CACHE_RESULT.key: False},
                           session=s)
        try:
            r1 = svc.submit(q).result(timeout=300)
            assert svc.stats().cache["fragment"]["published"] >= 1
            # force every cached handle through host down to disk
            cat.synchronous_spill(0)
            cat.spill_host_to_disk(0)
            tiers = [cat.tier_of(h.buffer_id)
                     for e in svc.cache._fragments.values()
                     if e._parts
                     for hs in e._parts.values() for h in hs]
            assert tiers and all(t is StorageTier.DISK for t in tiers)
            r2 = svc.submit(q).result(timeout=300)
            assert svc.stats().cache["fragment"]["hits"] >= 1
            pd.testing.assert_frame_equal(
                r1.sort_values("k").reset_index(drop=True),
                r2.sort_values("k").reset_index(drop=True),
                check_exact=True)
        finally:
            svc.shutdown()
    finally:
        reset_catalog(BufferCatalog())


# -- (7) eviction-vs-liveness: graft pins, TTL-vs-pins, leak, promotion -----


def test_graft_pins_entry_and_ttl_defers_eviction(tmp_path):
    """A READY entry grafted as a serve leaf is pinned from graft time:
    neither LRU pressure nor TTL expiry may close its parts while the
    referencing query could still be queued. Expiry marks a pinned
    entry stale and the LAST unpin evicts it; a closed entry raises
    FragmentUnavailable instead of serving an empty (wrong) batch."""
    from spark_rapids_tpu.service.cache import fragments as frag_mod

    p = str(tmp_path / "t.parquet")
    _write(p, _tbl(seed=9))
    s = Session()
    s.register_parquet("t", p)
    q = s.sql(AGG_SQL)
    svc = QueryService({cfg.SERVICE_CACHE_RESULT.key: False},
                       session=s)
    try:
        svc.submit(q).result(timeout=300)
        mgr = svc.cache
        assert svc.stats().cache["fragment"]["published"] >= 1
        _, pending, served = mgr.graft_fragments(q._plan)
        assert not pending and len(served) == 1
        entry = served[0]
        assert entry.pins == 1, "graft must pin the serve leaf's entry"
        # LRU pressure far past the budget: a pinned entry is not a
        # candidate, so the parts must survive untouched
        with mgr._lock:
            mgr._evict_locked(mgr.max_bytes + entry.bytes + 1)
        assert entry.state == frag_mod.READY \
            and entry._parts is not None, \
            "LRU evicted a pinned entry out from under a live graft"
        # TTL expiry observed while pinned: the lookup misses (a fresh
        # capture is registered) but the parts must NOT close — a
        # server could be mid-iteration on them
        mgr.ttl_s = 0.001
        entry.created_at -= 10.0
        _, pending2, served2 = mgr.graft_fragments(q._plan)
        assert entry not in served2
        assert entry.stale and entry._parts is not None, \
            "TTL eviction must defer while pinned (use-after-close)"
        mgr.abort_pending(pending2)
        mgr.release_served(served2)
        # the last unpin performs the deferred eviction
        mgr.release_served([entry])
        assert entry.state == frag_mod.ABORTED and entry._parts is None
        # and serving a closed entry fails loudly, never empty-frame
        with pytest.raises(frag_mod.FragmentUnavailable):
            next(frag_mod._serve(entry, entry.schema, 0))
    finally:
        svc.shutdown()


def test_planning_failure_releases_fragment_registrations(
        tmp_path, monkeypatch):
    """An exception between graft_fragments and Query registration must
    abort the query's PENDING entries and drop its graft pins — a
    leaked PENDING key would block every future capture of that subplan
    forever (PENDING-elsewhere keys are never waited on)."""
    from spark_rapids_tpu.plan import optimizer as opt_mod

    p = str(tmp_path / "t.parquet")
    _write(p, _tbl(seed=10))
    s = Session()
    s.register_parquet("t", p)
    q = s.sql(AGG_SQL)
    svc = QueryService({cfg.SERVICE_CACHE_RESULT.key: False},
                       session=s)
    try:
        real = opt_mod.estimate_footprint_bytes

        def boom(*a, **k):
            raise RuntimeError("injected planner fault")

        monkeypatch.setattr(opt_mod, "estimate_footprint_bytes", boom)
        with pytest.raises(RuntimeError, match="injected planner"):
            svc.submit(q)
        st = svc.stats().cache["fragment"]
        assert st["pending"] == 0 and st["entries"] == 0, \
            "planner fault leaked PENDING fragment entries"
        monkeypatch.setattr(opt_mod, "estimate_footprint_bytes", real)
        svc.submit(q).result(timeout=300)
        assert svc.stats().cache["fragment"]["published"] >= 1, \
            "the key must remain capturable after the failed submit"
    finally:
        svc.shutdown()


def test_cancelled_leader_promotes_follower():
    """Single-flight followers are independent client submissions:
    cancelling the leader must NOT cancel them — one follower is
    promoted to a fresh leader that computes the shared plan itself,
    and every follower still gets the oracle frame."""
    from spark_rapids_tpu.api import col, functions as F
    from spark_rapids_tpu.api.dataframe import DataFrame
    from spark_rapids_tpu.service.types import QueryCancelled

    s = Session()
    src = SlowKeyedSource("promote")
    base = DataFrame(pn.ScanNode(src), s)
    q = base.group_by("k").agg(F.sum(col("v")).alias("sv"))
    # fragment tier off: the cancelled leader may have published its
    # captured fragment before the cancel landed, and a promoted
    # leader serving from it would (correctly) skip the re-read this
    # test uses as its promotion witness
    svc = QueryService({cfg.SERVICE_CACHE_FRAGMENT.key: False},
                       session=s)
    try:
        leader = svc.submit(q, tenant="t0")
        deadline = time.time() + 30
        while leader.poll().value != "RUNNING" \
                and time.time() < deadline:
            time.sleep(0.01)
        followers = [svc.submit(q, tenant=f"t{i}") for i in (1, 2)]
        assert svc.stats().cache["result"][
            "single_flight_followers"] == 2
        assert leader.cancel()
        src.gate.set()
        frames = [h.result(timeout=300) for h in followers]
        with pytest.raises(QueryCancelled):
            leader.result(timeout=60)
        assert src.reads == 2, \
            f"want leader+promoted reads (2), got {src.reads}"
        rng = np.random.default_rng(11)
        raw = pd.DataFrame(
            {"k": rng.integers(0, 6, src.n).astype(np.int64),
             "v": rng.random(src.n)})
        oracle = raw.groupby("k").agg(sv=("v", "sum")).reset_index()
        for f in frames:
            assert_frames_equal(oracle, f)
    finally:
        src.gate.set()
        svc.shutdown()


# -- (10) a streaming append is a snapshot event (PR 14 satellite) ----------


def test_streaming_append_is_a_snapshot_event():
    """Appending a micro-batch to a streaming table bumps its snapshot
    version: a dashboard result cached BEFORE the append must never be
    served after it — the post-append submit recomputes over old+new
    rows. Identical resubmits between appends still hit."""
    s = Session()
    schema = Schema(["k", "v"], [dt.INT64, dt.FLOAT64])
    src = s.create_streaming_table("t", schema)
    first = _tbl(seed=3, n=1000)
    src.append(first)
    q = s.sql(AGG_SQL)
    svc = QueryService(s.conf, session=s)
    try:
        oracle1 = first.groupby("k").agg(
            sv=("v", "sum"), n=("v", "size")).reset_index()
        assert_frames_equal(oracle1, svc.submit(q).result(timeout=300))
        assert_frames_equal(oracle1, svc.submit(q).result(timeout=300))
        st = svc.stats().cache
        assert st["result"]["hits"] == 1, \
            "identical resubmit with no append in between must hit"
        extra = _tbl(seed=4, n=500)
        svc.ingest(src, extra)   # the service-side append surface
        both = pd.concat([first, extra], ignore_index=True)
        assert_frames_equal(
            both.groupby("k").agg(sv=("v", "sum"),
                                  n=("v", "size")).reset_index(),
            svc.submit(q).result(timeout=300))
        st = svc.stats().cache
        assert st["result"]["hits"] == 1, \
            "an appended table must never serve the pre-append frame"
        # and the new version is itself cacheable at the new key
        svc.submit(q).result(timeout=300)
        assert svc.stats().cache["result"]["hits"] == 2
    finally:
        svc.shutdown()
