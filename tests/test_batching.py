"""Cross-tenant micro-batching serving layer (service/batching):
bucket ladder, shape-bucket registry + warmup, micro-batch coalescing
with per-query attribution, the cross-tenant compile fence, and the
SLO harness. Smoke tier; everything runs on the virtual CPU mesh."""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pandas as pd
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import Schema
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions import predicates as pr
from spark_rapids_tpu.expressions.base import BoundReference, Literal
from spark_rapids_tpu.ops import buckets as ladder
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.service import QueryService
from spark_rapids_tpu.service.batching import (MicroBatcher,
                                               get_registry)
from spark_rapids_tpu.service.batching import slo

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- shared plan/source helpers ---------------------------------------------


class GateSource(pn.DataSource):
    """Single-split source gated on an event, deterministic data per
    seed — lets a test hold two queries at the same pipeline point and
    release them together so their stage dispatches land inside one
    micro-batch window."""

    def __init__(self, rows=1000, seed=0, gated=True):
        self.rows = rows
        self.seed = seed
        self.gate = threading.Event()
        if not gated:
            self.gate.set()

    def schema(self):
        return Schema(["k", "v"], [dt.INT64, dt.FLOAT64])

    def num_splits(self):
        return 1

    def split_origin(self, p):
        return None

    def split_stats(self, p):
        return None

    def estimated_row_count(self):
        return self.rows

    def host_frame(self):
        rng = np.random.default_rng(self.seed)
        return pd.DataFrame({
            "k": rng.integers(0, 8, self.rows).astype(np.int64),
            "v": rng.random(self.rows)})

    def read_host_split(self, p):
        assert self.gate.wait(timeout=60), "gate never opened"
        f = self.host_frame()
        return ({"k": f["k"].values, "v": f["v"].values},
                {"k": None, "v": None})


def _agg_plan(src):
    """filter(v > 0.2) -> group_by(k).sum(v): override-plans into a
    FusedAggregateExec whose chain program is the coalescing unit."""
    scan = pn.ScanNode(src)
    filt = pn.FilterNode(
        pr.GreaterThan(BoundReference(1, dt.FLOAT64),
                       Literal(0.2, dt.FLOAT64)), scan)
    return pn.AggregateNode(
        [BoundReference(0, dt.INT64)],
        [pn.AggCall(A.Sum(BoundReference(1, dt.FLOAT64)), "sv"),
         pn.AggCall(A.Count(BoundReference(1, dt.FLOAT64)), "n")],
        filt, grouping_names=["k"])


def _oracle(src):
    f = src.host_frame()
    f = f[f["v"] > 0.2]
    return (f.groupby("k").agg(sv=("v", "sum"), n=("v", "count"))
            .reset_index().sort_values("k").reset_index(drop=True))


def _sorted(frame):
    return frame.sort_values("k").reset_index(drop=True)


def _assert_oracle(got, src):
    want = _oracle(src)
    got = _sorted(got)
    assert list(got["k"].astype(np.int64)) == list(want["k"])
    assert np.allclose(got["sv"].astype(float).values,
                       want["sv"].values)
    assert list(got["n"].astype(np.int64)) == list(want["n"])


# -- (a) the capacity ladder -------------------------------------------------


def test_ladder_default_is_power_of_two():
    assert ladder.bucket_capacity(1) == 128
    assert ladder.bucket_capacity(128) == 128
    assert ladder.bucket_capacity(129) == 256
    assert ladder.bucket_capacity(1024) == 1024
    assert ladder.bucket_capacity(1025) == 2048
    assert ladder.ladder_rungs(1024) == [128, 256, 512, 1024]
    assert ladder.is_bucketed(512) and not ladder.is_bucketed(384)


def test_ladder_growth_configurable():
    try:
        ladder.set_ladder_growth(4.0)
        assert ladder.bucket_capacity(129) == 512
        assert ladder.bucket_capacity(513) == 2048
        rungs = ladder.ladder_rungs(2048)
        assert rungs == [128, 512, 2048]
        assert all(ladder.is_bucketed(r) for r in rungs)
        assert not ladder.is_bucketed(1024)
        # rungs strictly increase even at a degenerate growth factor
        ladder.set_ladder_growth(1.01)
        rungs = ladder.ladder_rungs(1000)
        assert all(b > a for a, b in zip(rungs, rungs[1:]))
    finally:
        ladder.set_ladder_growth(2.0)


def test_footprint_uses_bucketed_shapes():
    """The admission footprint charges the PADDED capacity the device
    actually pins, not the raw row count."""
    from spark_rapids_tpu.plan.optimizer import estimate_footprint_bytes

    at_edge = estimate_footprint_bytes(
        pn.ScanNode(GateSource(rows=1024, gated=False)))
    over_edge = estimate_footprint_bytes(
        pn.ScanNode(GateSource(rows=1025, gated=False)))
    just_under = estimate_footprint_bytes(
        pn.ScanNode(GateSource(rows=1000, gated=False)))
    assert at_edge == just_under          # same 1024 bucket
    assert over_edge == 2 * at_edge       # next rung doubles


# -- (b) micro-batcher unit behavior ----------------------------------------


def _jit_double():
    import jax

    @jax.jit
    def double(xs, n):
        return [x * 2 for x in xs], n + 1
    return double


def test_microbatcher_coalesces_concurrent_calls():
    import jax.numpy as jnp

    prog = _jit_double()
    mb = MicroBatcher(window_s=2.0, max_batch=8, enabled=True,
                      inflight_fn=lambda: 2)
    results = {}

    def one(tag, offset):
        args = ([jnp.arange(4.0) + offset],
                jnp.asarray(offset, jnp.int32))
        results[tag] = mb.call("prog", prog, args, {},
                               query_id=tag, multi=True)

    ts = [threading.Thread(target=one, args=(i, i)) for i in (1, 2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    st = mb.stats()
    assert st["coalesced_launches"] == 1
    assert st["coalesced_participants"] == 2
    for tag in (1, 2):
        outs, n = results[tag]
        assert np.allclose(np.asarray(outs[0]),
                           (np.arange(4.0) + tag) * 2)
        assert int(n) == tag + 1


def test_microbatcher_solo_and_disabled_paths():
    import jax.numpy as jnp

    prog = _jit_double()
    args = ([jnp.arange(4.0)], jnp.asarray(0, jnp.int32))
    # leader alone: window expires, plain launch, correct result
    mb = MicroBatcher(window_s=0.02, max_batch=4, enabled=True,
                      inflight_fn=lambda: 2)
    outs, n = mb.call("p", prog, args, {}, query_id=7, multi=True)
    assert np.allclose(np.asarray(outs[0]), np.arange(4.0) * 2)
    assert mb.stats()["launches"] == 1
    assert mb.stats()["coalesced_launches"] == 0
    # multi=False with no live peers: no hold at all
    mb2 = MicroBatcher(window_s=5.0, max_batch=4, enabled=True,
                       inflight_fn=lambda: 1)
    t0 = time.perf_counter()
    mb2.call("p", prog, args, {}, query_id=7, multi=False)
    assert time.perf_counter() - t0 < 1.0
    # disabled: passthrough
    mb3 = MicroBatcher(window_s=5.0, max_batch=4, enabled=False)
    t0 = time.perf_counter()
    mb3.call("p", prog, args, {}, query_id=7, multi=True)
    assert time.perf_counter() - t0 < 1.0
    # maxBatch normalizes DOWN to a power of two: every admissible
    # quantized group size is then pre-compilable by warm_coalesced
    assert MicroBatcher(window_s=1.0, max_batch=6).max_batch == 4
    assert MicroBatcher(window_s=1.0, max_batch=8).max_batch == 8


def test_microbatcher_incompatible_shapes_do_not_group():
    import jax.numpy as jnp

    prog = _jit_double()
    mb = MicroBatcher(window_s=0.05, max_batch=8, enabled=True,
                      inflight_fn=lambda: 2)
    out = {}

    def one(tag, n):
        args = ([jnp.arange(float(n))], jnp.asarray(0, jnp.int32))
        out[tag] = mb.call("prog", prog, args, {}, query_id=tag,
                           multi=True)

    ts = [threading.Thread(target=one, args=("a", 4)),
          threading.Thread(target=one, args=("b", 8))]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert mb.stats()["coalesced_launches"] == 0  # different buckets
    assert len(np.asarray(out["a"][0][0])) == 4
    assert len(np.asarray(out["b"][0][0])) == 8


def test_microbatcher_error_propagates_to_all_participants():
    import jax

    @jax.jit
    def bad(xs, n):
        return [x * 2 for x in xs], n

    def boom(*a, **k):
        raise RuntimeError("synthetic device error")

    # poison the raw fn so the coalesced program build fails
    bad_prog = bad
    object.__getattribute__(bad_prog, "__wrapped__")

    class FakeProg:
        __wrapped__ = staticmethod(boom)

        def __call__(self, *a, **k):
            return boom()

    mb = MicroBatcher(window_s=1.0, max_batch=8, enabled=True,
                      inflight_fn=lambda: 2)
    errs = []

    def one(tag):
        import jax.numpy as jnp

        try:
            mb.call("prog", FakeProg(), ([jnp.arange(4.0)],), {},
                    query_id=tag, multi=True)
        except RuntimeError as e:
            errs.append(str(e))

    ts = [threading.Thread(target=one, args=(i,)) for i in (1, 2)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(30)
    assert len(errs) == 2
    assert all("synthetic device error" in e for e in errs)


# -- (c) coalesced dispatch attribution --------------------------------------


def test_coalesced_attribution_shares_sum_to_physical(monkeypatch):
    """One physical launch serving K queries: global tagged count +1,
    each participant +1/K (shares sum to the launch count) and one
    coalesced-participation entry each."""
    from spark_rapids_tpu.utils import dispatch as disp

    monkeypatch.setattr(disp, "_installed", True)
    base_tagged = disp.tagged_total()
    qtok = disp.enter_query(9001)
    try:
        disp._bump_stage("jit")              # plain dispatch: +1 to q
        ctok = disp.enter_coalesced([9001, 9002, 9003])
        try:
            disp._bump_stage("jit")          # coalesced: 1/3 each
        finally:
            disp.exit_coalesced(ctok)
    finally:
        disp.exit_query(qtok)
    counts = disp.query_counts()
    coal = disp.query_coalesced_counts()
    assert counts[9001] == pytest.approx(1 + 1 / 3)
    assert counts[9002] == pytest.approx(1 / 3)
    assert counts[9003] == pytest.approx(1 / 3)
    assert coal == {9001: 1, 9002: 1, 9003: 1}
    assert disp.tagged_total() - base_tagged == pytest.approx(2.0)
    assert sum(disp.pop_query_count(q) for q in (9001, 9002, 9003)) \
        == pytest.approx(disp.tagged_total() - base_tagged)
    for q in (9001, 9002, 9003):
        disp.pop_query_coalesced(q)


_ATTRIBUTION_FENCE = r"""
import json, sys
sys.path.insert(0, __ROOT__)
from spark_rapids_tpu.utils import dispatch as disp
disp.install()   # BEFORE any compute module import
sys.path.insert(0, __TESTS__)
import threading, time
import numpy as np
from test_batching import GateSource, _agg_plan, _oracle
from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.service import QueryService

svc = QueryService(RapidsConf({
    cfg.SERVICE_BATCHING_WINDOW_MS.key: 500.0,
    cfg.SERVICE_MAX_CONCURRENT.key: 4}))
srcs = [GateSource(seed=i) for i in range(4)]
handles = [svc.submit(_agg_plan(s), tenant=f"t{i}")
           for i, s in enumerate(srcs)]
time.sleep(0.3)
for s in srcs:
    s.gate.set()
rows = [len(h.result(timeout=120)) for h in handles]
per_query = [float(h._query.dispatches) for h in handles]
coalesced = [int(h._query.coalesced) for h in handles]
stats = svc.batcher.stats()
svc.shutdown()
print(json.dumps({
    "rows": rows,
    "per_query_sum": sum(per_query),
    "tagged_total": disp.tagged_total(),
    "coalesced": coalesced,
    "batcher": stats,
}))
"""


def test_attribution_sum_matches_physical_launches_subprocess():
    """End-to-end fence (telemetry must wrap jax.jit pre-import, hence
    the subprocess): with coalescing active, the SUM of per-query
    ServiceStats dispatch counts equals the physical launch count the
    global telemetry saw — one launch serving K queries is counted
    once, not K times."""
    script = _ATTRIBUTION_FENCE \
        .replace("__ROOT__", repr(ROOT)) \
        .replace("__TESTS__", repr(os.path.dirname(
            os.path.abspath(__file__))))
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run([sys.executable, "-c", script], env=env,
                         capture_output=True, text=True, timeout=560)
    assert out.returncode == 0, out.stderr[-3000:]
    rec = json.loads(out.stdout.strip().splitlines()[-1])
    assert all(r > 0 for r in rec["rows"])
    # the attribution invariant: shares sum to physical tagged count
    assert rec["per_query_sum"] == pytest.approx(rec["tagged_total"],
                                                 rel=1e-6)
    # and coalescing actually happened: >= 1 shared launch, each
    # participant ledgered once per launch it rode
    assert rec["batcher"]["coalesced_launches"] >= 1
    assert sum(rec["coalesced"]) == \
        rec["batcher"]["coalesced_participants"]


# -- (d) the cross-tenant serving fences -------------------------------------


def test_coalesced_results_match_oracle_different_tenants():
    """Two same-template queries from different tenants coalesce into
    one physical stage launch and BOTH results match the per-tenant
    oracle (per-query row counts masked inside the shared program)."""
    svc = QueryService(RapidsConf({
        cfg.SERVICE_BATCHING_WINDOW_MS.key: 500.0,
        cfg.SERVICE_MAX_CONCURRENT.key: 4}))
    pre = svc.batcher.stats()["coalesced_launches"]
    s1, s2 = GateSource(seed=11), GateSource(seed=22)
    h1 = svc.submit(_agg_plan(s1), tenant="alice")
    h2 = svc.submit(_agg_plan(s2), tenant="bob")
    time.sleep(0.3)      # both slices parked at their gates
    s1.gate.set()
    s2.gate.set()
    r1, r2 = h1.result(timeout=120), h2.result(timeout=120)
    st = svc.batcher.stats()
    svc.shutdown()
    assert st["coalesced_launches"] - pre >= 1
    _assert_oracle(r1, s1)
    _assert_oracle(r2, s2)


def test_bucket_boundary_rows_coalesce_or_split_correctly():
    """Rows exactly at a bucket edge (1024) share that bucket and stay
    coalescible; one row over (1025) pads to the NEXT rung — a
    different group — and both still match their oracles."""
    assert ladder.bucket_capacity(1024) == 1024
    assert ladder.bucket_capacity(1025) == 2048
    svc = QueryService(RapidsConf({
        cfg.SERVICE_BATCHING_WINDOW_MS.key: 300.0,
        cfg.SERVICE_MAX_CONCURRENT.key: 4}))
    srcs = [GateSource(rows=1024, seed=1), GateSource(rows=1024, seed=2),
            GateSource(rows=1025, seed=3)]
    handles = [svc.submit(_agg_plan(s), tenant=f"t{i}")
               for i, s in enumerate(srcs)]
    time.sleep(0.3)
    for s in srcs:
        s.gate.set()
    frames = [h.result(timeout=120) for h in handles]
    st = svc.batcher.stats()
    svc.shutdown()
    for f, s in zip(frames, srcs):
        _assert_oracle(f, s)
    # the two 1024-row tenants shared a launch; the 1025-row tenant
    # could not have joined their bucket (group size stays <= 2)
    assert st["coalesced_launches"] >= 1
    assert st["coalesced_participants"] <= 2 * st["coalesced_launches"]


def test_concurrent_same_template_compiles_once_per_bucket():
    """8 concurrent same-template different-tenant queries: at most
    one trace/compile per stage program (single-flight), cross-tenant
    hit rate >= 7/8, results oracle-matched."""
    from spark_rapids_tpu.expressions import compiler as comp

    def run_serial_cold():
        comp._FUSED_CACHE.clear()
        before = dict(comp._FUSED_CACHE_STATS)
        svc = QueryService(RapidsConf({}))
        src = GateSource(seed=100, gated=False)
        svc.submit(_agg_plan(src), tenant="warm").result(timeout=120)
        svc.shutdown()
        return comp._FUSED_CACHE_STATS["misses"] - before["misses"]

    distinct_programs = run_serial_cold()
    assert distinct_programs >= 1

    comp._FUSED_CACHE.clear()
    before = dict(comp._FUSED_CACHE_STATS)
    svc = QueryService(RapidsConf({
        cfg.SERVICE_MAX_CONCURRENT.key: 8,
        cfg.SERVICE_BATCHING_WINDOW_MS.key: 50.0}))
    srcs = [GateSource(seed=200 + i) for i in range(8)]
    handles = [svc.submit(_agg_plan(s), tenant=f"tenant{i}")
               for i, s in enumerate(srcs)]
    time.sleep(0.4)      # all 8 admitted and parked at their gates
    for s in srcs:
        s.gate.set()
    frames = [h.result(timeout=180) for h in handles]
    svc.shutdown()
    d_miss = comp._FUSED_CACHE_STATS["misses"] - before["misses"]
    d_hit = comp._FUSED_CACHE_STATS["hits"] - before["hits"]
    assert d_miss <= distinct_programs, (
        f"{d_miss} compiles for 8 concurrent instances of a "
        f"{distinct_programs}-program template: the single-flight "
        f"program cache raced")
    hit_rate = d_hit / (d_hit + d_miss)
    assert hit_rate >= 7 / 8, (d_hit, d_miss)
    for f, s in zip(frames, srcs):
        _assert_oracle(f, s)


# -- (e) shape-bucket registry + warmup --------------------------------------


def test_registry_records_and_warms_ladder():
    import jax

    reg = get_registry().__class__()   # fresh instance, not the global

    @jax.jit
    def prog(datas, num_rows, scale):
        return [d * scale for d in datas]

    import jax.numpy as jnp

    args = ([jnp.zeros(1024), jnp.zeros(1024)],
            jnp.asarray(1000, jnp.int32), 3)
    reg.record(("progkey",), prog, args, {})
    reg.record(("progkey",), prog, args, {})
    st = reg.stats()
    assert st["programs"] == 1
    assert st["bucket_executables"] == 1
    assert st["observations"] == 2
    assert st["bucket_reuses"] == 1
    report = reg.warm()
    # rungs below 1024 replayed: 128/256/512 (1024 itself observed)
    assert report == {"programs": 1, "replays": 3, "errors": 0,
                      "rungs_skipped": 0}
    assert reg.stats()["warmed"] == 4
    # idempotent: nothing new to replay
    assert reg.warm()["replays"] == 0
    # capping at the input rung skips the rungs above it and says so
    report = reg.warm(max_rung=256)
    assert report["replays"] == 0
    assert report["rungs_skipped"] == 2


def test_register_template_warms_progcache():
    """After warmup, a tenant's first same-template query re-traces
    NOTHING: the satellite's 'first request doesn't eat the compile'."""
    from spark_rapids_tpu.expressions import compiler as comp

    svc = QueryService(RapidsConf({
        cfg.SERVICE_WARMUP_ENABLED.key: True}))
    report = svc.register_template(
        _agg_plan(GateSource(seed=400, gated=False)), "agg_template")
    assert report is not None and report["templates"] == 1
    before = dict(comp._FUSED_CACHE_STATS)
    src = GateSource(seed=401, gated=False)
    got = svc.submit(_agg_plan(src), tenant="cold").result(timeout=120)
    svc.shutdown()
    assert comp._FUSED_CACHE_STATS["misses"] == before["misses"], \
        "a warmed template still paid a trace/compile"
    _assert_oracle(got, src)
    assert svc.stats().counters["done"] >= 2  # warmup run + tenant run


# -- (f) SLO harness ----------------------------------------------------------


def test_poisson_gaps_deterministic_and_rate_shaped():
    a = slo.poisson_gaps(10.0, 500, seed=3)
    b = slo.poisson_gaps(10.0, 500, seed=3)
    assert a == b
    assert abs(sum(a) / len(a) - 0.1) < 0.02   # mean gap ~ 1/rate
    assert slo.poisson_gaps(0, 3) == [0.0, 0.0, 0.0]


def test_percentile_nearest_rank():
    vals = list(range(1, 101))
    assert slo.percentile(vals, 50) == 50
    assert slo.percentile(vals, 99) == 99
    assert slo.percentile(vals, 100) == 100
    assert slo.percentile([], 99) == 0.0


def test_open_loop_run_and_slo_block():
    svc = QueryService(RapidsConf({
        cfg.SERVICE_MAX_CONCURRENT.key: 4}))
    rec = slo.run_open_loop(
        svc, lambda i: _agg_plan(GateSource(seed=500 + i,
                                            gated=False)),
        offered_qps=50.0, n_queries=6, tenants=3, seed=5)
    stats = svc.stats()
    svc.shutdown()
    assert rec["done"] == 6 and rec["failed"] == 0
    assert rec["latency_s"]["total"]["p99"] > 0
    assert 0.0 <= rec["shed_rate"] <= 1.0
    block = slo.slo_block([rec], serial_s=10.0, ratio=3.0)
    assert block["criterion"]["pass"] is True   # trivially: 10s serial
    assert block["criterion"]["at_offered_qps"] == 50.0
    # percentiles surfaced in the service histograms too
    snap = stats.to_dict()
    assert "p99_s" in snap["run_time_hist"]
    assert snap["latency"]["run_p99_s"] >= 0
    assert "batching" in snap
