"""Adaptive shuffle-read (AQE-equivalent) tests."""
import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.execs.adaptive import (AdaptiveShuffleReaderExec,
                                             MapOutputStatistics,
                                             coalesce_groups)
from spark_rapids_tpu.expressions import aggregates as A
from spark_rapids_tpu.expressions.base import BoundReference
from spark_rapids_tpu.io import ParquetSource
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.overrides import apply_overrides

from tests.compare import assert_cpu_and_tpu_equal


def test_coalesce_groups_algorithm():
    stats = MapOutputStatistics([10, 10, 10, 100, 5, 5, 5, 5])
    groups = coalesce_groups(stats, advisory_bytes=30)
    # contiguity + full coverage, groups near the target
    assert [p for g in groups for p in g] == list(range(8))
    assert groups == [[0, 1, 2], [3], [4, 5, 6, 7]]


def test_coalesce_groups_min_partitions():
    stats = MapOutputStatistics([1] * 8)
    groups = coalesce_groups(stats, advisory_bytes=1 << 30,
                             min_partitions=4)
    assert len(groups) >= 4
    assert [p for g in groups for p in g] == list(range(8))


def test_coalesce_min_parallelism_splits_byte_balanced():
    """Forced-parallelism splits cut at the byte-balanced point, not
    the index midpoint: one heavy partition must not drag half the
    light ones along with it."""
    stats = MapOutputStatistics([100, 1, 1, 1])
    groups = coalesce_groups(stats, advisory_bytes=1 << 30,
                             min_partitions=2)
    # midpoint would give [[0, 1], [2, 3]] (101 vs 2 bytes)
    assert groups == [[0], [1, 2, 3]]


def test_coalesce_min_parallelism_equal_sizes_midpoint():
    """With uniform sizes the byte-balanced cut IS the midpoint."""
    stats = MapOutputStatistics([10, 10, 10, 10])
    groups = coalesce_groups(stats, advisory_bytes=1 << 30,
                             min_partitions=2)
    assert groups == [[0, 1], [2, 3]]
    assert [p for g in groups for p in g] == list(range(4))


def test_skew_detection():
    sizes = [10] * 9 + [10_000_000_000]
    stats = MapOutputStatistics(sizes)
    assert stats.skewed_partitions() == [9]
    assert MapOutputStatistics([10] * 10).skewed_partitions() == []


def test_skew_detection_edges():
    # empty exchange: no partitions, no skew
    assert MapOutputStatistics([]).skewed_partitions() == []
    # strict >: everything exactly AT the cut is not skewed
    assert MapOutputStatistics([10, 10, 10]).skewed_partitions(
        factor=1.0, threshold=0) == []
    # every partition over the cut: all flagged (the cut is
    # max(threshold, factor*median), so a sub-1 factor exposes the
    # threshold floor and uniform-but-huge partitions all qualify)
    assert MapOutputStatistics([100, 100, 100]).skewed_partitions(
        factor=0.5, threshold=60) == [0, 1, 2]
    # threshold floors detection even with an aggressive factor
    assert MapOutputStatistics([1, 1, 40]).skewed_partitions(
        factor=1.5, threshold=1000) == []
    # all-zero sizes never divide by zero or flag anything
    assert MapOutputStatistics([0, 0, 0]).skewed_partitions(
        factor=1.0, threshold=0) == []


@pytest.fixture()
def multifile_scan(tmp_path):
    rng = np.random.default_rng(0)
    for k in range(4):
        n = 500
        t = pa.table({
            "k": rng.integers(0, 40, n).astype(np.int64),
            "v": rng.random(n),
        })
        pq.write_table(t, tmp_path / f"f{k}.parquet")
    src = ParquetSource(str(tmp_path))
    # these tests exercise multi-partition shuffle structure: keep the
    # tiny files as separate scan partitions (packing would collapse
    # the plan to a single partition and erase the exchanges under test)
    src.pack_splits = False
    return pn.ScanNode(src)


def _agg_plan(scan):
    return pn.AggregateNode(
        [BoundReference(0, dt.INT64)],
        [pn.AggCall(A.Sum(BoundReference(1, dt.FLOAT64)), "sv"),
         pn.AggCall(A.Count(BoundReference(1, dt.FLOAT64)), "cv")],
        scan, grouping_names=["k"])


def _find(exec_, klass):
    out = []
    stack = [exec_]
    while stack:
        e = stack.pop()
        if isinstance(e, klass):
            out.append(e)
        stack.extend(e.children)
    return out


def test_adaptive_agg_coalesces_and_matches(multifile_scan):
    plan = _agg_plan(multifile_scan)
    conf = RapidsConf({"rapids.tpu.sql.test.enabled": True})
    exec_ = assert_cpu_and_tpu_equal(plan, conf=conf, approx_float=1e-6)
    readers = _find(exec_, AdaptiveShuffleReaderExec)
    assert readers, "adaptive reader must wrap the hash exchange"
    r = readers[0]
    # tiny data -> far fewer coalesced groups than shuffle partitions
    assert r.num_partitions < r.exchange.num_out_partitions


def test_adaptive_disabled_no_reader(multifile_scan):
    plan = _agg_plan(multifile_scan)
    conf = RapidsConf({"rapids.tpu.sql.adaptive.enabled": False})
    exec_ = apply_overrides(plan, conf)
    assert not _find(exec_, AdaptiveShuffleReaderExec)
    assert_cpu_and_tpu_equal(plan, conf=conf, approx_float=1e-6)


def test_adaptive_join_sides_stay_aligned(tmp_path, multifile_scan):
    rng = np.random.default_rng(1)
    n = 300
    t = pa.table({"k2": rng.integers(0, 40, n).astype(np.int64),
                  "w": rng.random(n)})
    pq.write_table(t, tmp_path / "right.parquet")
    pq.write_table(t, tmp_path / "right2.parquet")
    right = pn.ScanNode(ParquetSource(
        [str(tmp_path / "right.parquet"), str(tmp_path / "right2.parquet")]))
    plan = pn.JoinNode("inner", multifile_scan, right, [0], [0])
    # the shuffled path is the scenario under test: keep the small
    # build side from taking the broadcast-threshold shortcut
    conf = RapidsConf({"rapids.tpu.sql.test.enabled": True,
                       "rapids.tpu.sql.autoBroadcastJoinThreshold": 0})
    exec_ = assert_cpu_and_tpu_equal(plan, conf=conf, approx_float=1e-6)
    readers = _find(exec_, AdaptiveShuffleReaderExec)
    assert len(readers) == 2
    # shared spec: identical groups on both sides
    assert readers[0].groups == readers[1].groups


def test_distributed_global_sort_range_partitioned(tmp_path):
    """Global sort over a multi-partition scan goes through a sampled
    range exchange (no single-partition funnel) and stays ordered."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.execs.sort import SortExec
    from spark_rapids_tpu.ops.sortkeys import SortKeySpec

    rng = np.random.default_rng(3)
    for k in range(4):
        pq.write_table(pa.table(
            {"v": rng.random(400) * 1000,
             "tag": rng.integers(0, 5, 400).astype(np.int64)}),
            tmp_path / f"s{k}.parquet")
    src = ParquetSource(str(tmp_path))
    src.pack_splits = False  # multi-partition structure under test
    scan = pn.ScanNode(src)
    plan = pn.SortNode([SortKeySpec.spark_default(0)], scan)
    conf = RapidsConf({"rapids.tpu.sql.test.enabled": True})
    exec_ = apply_overrides(plan, conf)
    exchanges = _find(exec_, ShuffleExchangeExec)
    assert exchanges and exchanges[0].partitioning[0] == "range"
    assert exchanges[0].num_out_partitions > 1
    assert isinstance(exec_, SortExec)
    # compare IN ORDER against the oracle
    from spark_rapids_tpu.cpu.engine import execute_cpu
    from spark_rapids_tpu.execs.base import collect
    from tests.compare import assert_frames_equal

    cpu_df = execute_cpu(plan).to_pandas()
    assert_frames_equal(cpu_df, collect(exec_), sort=False)


def test_distributed_sort_descending_strings(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    from spark_rapids_tpu.ops.sortkeys import SortKeySpec

    rng = np.random.default_rng(4)
    for k in range(3):
        strs = np.array([f"w{int(x)}" if x > 2 else None
                         for x in rng.integers(0, 40, 200)], dtype=object)
        pq.write_table(pa.table({"s": pa.array(strs, type=pa.string())}),
                       tmp_path / f"p{k}.parquet")
    src = ParquetSource(str(tmp_path))
    src.pack_splits = False  # multi-partition structure under test
    scan = pn.ScanNode(src)
    plan = pn.SortNode([SortKeySpec.spark_default(0, ascending=False)],
                       scan)
    from spark_rapids_tpu.cpu.engine import execute_cpu
    from spark_rapids_tpu.execs.base import collect
    from tests.compare import assert_frames_equal

    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, RapidsConf(
        {"rapids.tpu.sql.test.enabled": True}))
    assert_frames_equal(cpu_df, collect(exec_), sort=False)


def test_distributed_multikey_global_sort(tmp_path):
    """Multi-key global sorts range-partition on full key tuples: ties
    on the first key must not split across partition boundaries."""
    rng = np.random.default_rng(7)
    for k in range(4):
        n = 300
        pq.write_table(pa.table({
            # heavy first-key ties force the lexicographic tiebreak
            "a": rng.integers(0, 4, n).astype(np.int64),
            "b": rng.random(n),
            "s": np.array([f"t{int(x)}" if x > 1 else None
                           for x in rng.integers(0, 30, n)],
                          dtype=object),
        }), tmp_path / f"m{k}.parquet")
    from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.ops.sortkeys import SortKeySpec

    src = ParquetSource(str(tmp_path))
    src.pack_splits = False  # multi-partition structure under test
    scan = pn.ScanNode(src)
    plan = pn.SortNode(
        [SortKeySpec.spark_default(0),
         SortKeySpec.spark_default(2, ascending=False),
         SortKeySpec.spark_default(1)], scan)
    conf = RapidsConf({"rapids.tpu.sql.test.enabled": True})
    from spark_rapids_tpu.cpu.engine import execute_cpu
    from spark_rapids_tpu.execs.base import collect
    from tests.compare import assert_frames_equal

    cpu_df = execute_cpu(plan).to_pandas()
    exec_ = apply_overrides(plan, conf)
    exchanges = _find(exec_, ShuffleExchangeExec)
    assert exchanges and exchanges[0].partitioning[0] == "range"
    assert exchanges[0].num_out_partitions > 1
    assert_frames_equal(cpu_df, collect(exec_), sort=False)
