"""Shuffle transport/catalog tests.

Models the reference's device-less shuffle testing (SURVEY.md §4:
RapidsShuffleClientSuite / RapidsShuffleIteratorSuite mock the transport —
no UCX, no second process): a LocalCluster of in-process executors, fault
hooks on the server for error paths, and spill interplay against real
BufferCatalogs.
"""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column, StringColumn
from spark_rapids_tpu.shuffle import (BlockId, LocalCluster,
                                      ShuffleFetchFailedError)
from spark_rapids_tpu.shuffle.transport import (ShuffleClient,
                                                TransportError)


def make_batch(lo: int, n: int, with_strings: bool = True
               ) -> ColumnarBatch:
    vals = np.arange(lo, lo + n, dtype=np.int64)
    valid = (vals % 7) != 3
    cols = [Column.from_numpy(vals, dtype=dt.INT64, validity=valid)]
    if with_strings:
        cols.append(StringColumn.from_strings(
            [None if v % 5 == 0 else f"s{v % 11}" for v in vals]))
    return ColumnarBatch(cols, n)


def batch_values(b: ColumnarBatch):
    n = b.realized_num_rows()
    data, valid = b.columns[0].to_numpy(n)
    return [int(v) if (valid is None or valid[i]) else None
            for i, v in enumerate(np.asarray(data)[:n])]


@pytest.fixture()
def cluster(tmp_path):
    c = LocalCluster(3, spill_dir=str(tmp_path))
    yield c
    c.shutdown()


def test_local_and_remote_reads(cluster):
    # 3 map tasks spread over executors, 2 partitions each
    for map_id, ex in enumerate([0, 1, 2]):
        cluster.write_map_output(1, map_id, ex, {
            0: make_batch(map_id * 100, 10),
            1: make_batch(map_id * 100 + 50, 5),
        })
    got = []
    for b in cluster.read_partition(1, 0, reader_executor_index=0):
        got.extend(v for v in batch_values(b) if v is not None)
    expect = [v for m in range(3) for v in range(m * 100, m * 100 + 10)
              if v % 7 != 3]
    assert sorted(got) == sorted(expect)
    it = cluster.last_iterator
    assert it.local_blocks_read == 1      # map 0 lives on the reader
    assert it.remote_blocks_read == 2
    assert it.remote_bytes_read > 0


def test_string_columns_survive_transport(cluster):
    cluster.write_map_output(2, 0, 1, {0: make_batch(0, 20)})
    batches = list(cluster.read_partition(2, 0, reader_executor_index=0))
    assert len(batches) == 1
    b = batches[0]
    n = b.realized_num_rows()
    sc = b.columns[1]
    data, valid = sc.to_numpy(n)
    vals = [data[i] if valid is None or valid[i] else None
            for i in range(n)]
    expect = [None if v % 5 == 0 else f"s{v % 11}" for v in range(20)]
    assert list(vals) == expect


def test_degenerate_empty_block_is_meta_only(cluster):
    cluster.write_map_output(3, 0, 0, {0: make_batch(0, 5)})
    # register an explicitly empty batch for a second map task
    empty = ColumnarBatch(
        [Column.from_numpy(np.array([], dtype=np.int64), dtype=dt.INT64)],
        0)
    cluster.executor(0).shuffle_catalog.register(BlockId(3, 1, 0), empty)
    cluster._map_outputs.setdefault(3, {})[1] = ("exec-0",
                                                 frozenset({0}))
    got = list(cluster.read_partition(3, 0, reader_executor_index=1))
    # the empty block contributed no batch, only metadata
    total = sum(b.realized_num_rows() for b in got)
    assert total == 5
    meta = cluster.executor(0).shuffle_catalog.meta(BlockId(3, 1, 0))
    assert meta.num_rows == 0 and meta.payload_len == 0


def test_windowed_transfer_and_throttle(tmp_path):
    # tiny bounce buffers force many windows; tiny inflight budget forces
    # serialization of windows — transfer must still be exact
    c = LocalCluster(2, spill_dir=str(tmp_path), bounce_size=512,
                     max_inflight=1024)
    try:
        c.write_map_output(1, 0, 1, {0: make_batch(0, 5000,
                                                   with_strings=False)})
        got = []
        for b in c.read_partition(1, 0, reader_executor_index=0):
            got.extend(v for v in batch_values(b) if v is not None)
        assert len(got) == sum(1 for v in range(5000) if v % 7 != 3)
        client = c._clients[("exec-0", "exec-1")]
        assert client.throttle.peak <= 1024
    finally:
        c.shutdown()


def test_fetch_from_spilled_block_unspills(tmp_path):
    """Shuffle blocks that spilled to host/disk are served after unspill
    (RapidsShuffleServer acquires catalog buffers 'possibly unspilling')."""
    c = LocalCluster(2, spill_dir=str(tmp_path))
    try:
        c.write_map_output(1, 0, 1, {0: make_batch(0, 1000)})
        owner = c.executor(1)
        assert owner.buffer_catalog.synchronous_spill(0) > 0
        assert owner.buffer_catalog.spill_host_to_disk(0) > 0
        got = []
        for b in c.read_partition(1, 0, reader_executor_index=0):
            got.extend(v for v in batch_values(b) if v is not None)
        assert len(got) == sum(1 for v in range(1000) if v % 7 != 3)
    finally:
        c.shutdown()


def test_missing_block_raises_fetch_failure(cluster):
    cluster.write_map_output(1, 0, 1, {0: make_batch(0, 10)})
    # the tracker claims exec-2 holds map 99's output, but the executor
    # lost it (e.g. restarted): the read MUST fail, never silently skip
    cluster._map_outputs[1][99] = ("exec-2", frozenset({0}))
    with pytest.raises(ShuffleFetchFailedError):
        list(cluster.read_partition(1, 0, reader_executor_index=0))
    # a locally-lost tracked block also fails (reader-side hole)
    cluster._map_outputs[1].pop(99)
    cluster._map_outputs[1][7] = ("exec-0", frozenset({0}))
    with pytest.raises(ShuffleFetchFailedError):
        list(cluster.read_partition(1, 0, reader_executor_index=0))


def test_transport_error_converts_to_fetch_failure(cluster):
    """Server-side failure surfaces as a fetch failure naming the peer
    (RapidsShuffleIterator.scala:242-300 error conversion)."""
    cluster.write_map_output(1, 0, 1, {0: make_batch(0, 10)})

    def boom(blocks):
        raise TransportError("injected metadata failure")

    cluster.executor(1).server.on_metadata = boom
    with pytest.raises(ShuffleFetchFailedError, match="exec-1"):
        list(cluster.read_partition(1, 0, reader_executor_index=0))


def test_corrupted_chunk_detected_by_checksum(cluster):
    cluster.write_map_output(1, 0, 1, {0: make_batch(0, 500)})
    server = cluster.executor(1).server
    orig = server.handle_chunk

    def corrupt(block, offset, length):
        data = bytearray(orig(block, offset, length))
        if len(data) > 20:
            data[20] ^= 0xFF
        return bytes(data)

    server.handle_chunk = corrupt
    with pytest.raises(ShuffleFetchFailedError, match="checksum"):
        list(cluster.read_partition(1, 0, reader_executor_index=0))


def test_unregister_shuffle_drops_blocks(cluster):
    cluster.write_map_output(1, 0, 0, {0: make_batch(0, 10)})
    cluster.write_map_output(2, 0, 0, {0: make_batch(0, 10)})
    assert len(cluster.executor(0).shuffle_catalog) == 2
    cluster.unregister_shuffle(1)
    assert len(cluster.executor(0).shuffle_catalog) == 1
    assert not cluster.executor(0).shuffle_catalog.has_block(
        BlockId(1, 0, 0))
    # shuffle 2 unaffected
    got = list(cluster.read_partition(2, 0, reader_executor_index=0))
    assert sum(b.realized_num_rows() for b in got) == 10


def test_concurrent_reduce_tasks(cluster):
    """Many reduce tasks fetching from the same server concurrently (the
    single progress thread serializes request handling, like UCX)."""
    import threading

    for map_id in range(4):
        cluster.write_map_output(1, map_id, map_id % 3, {
            p: make_batch(map_id * 1000 + p * 100, 50) for p in range(4)})
    results = {}
    errors = []

    def read(p):
        try:
            got = []
            for b in cluster.read_partition(1, p,
                                            reader_executor_index=p % 3):
                got.extend(v for v in batch_values(b) if v is not None)
            results[p] = sorted(got)
        except BaseException as e:  # pragma: no cover
            errors.append(e)

    threads = [threading.Thread(target=read, args=(p,)) for p in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    for p in range(4):
        expect = sorted(
            v for m in range(4)
            for v in range(m * 1000 + p * 100, m * 1000 + p * 100 + 50)
            if v % 7 != 3)
        assert results[p] == expect


def test_stage_retry_after_executor_loss(cluster):
    """The reference's recovery model is Spark lineage/task-retry
    (SURVEY §5.3): a lost executor produces fetch failures; invalidating
    its map outputs and re-running those tasks elsewhere restores reads."""
    for map_id, ex in enumerate([0, 1, 2]):
        cluster.write_map_output(7, map_id, ex,
                                 {0: make_batch(map_id * 100, 10)})
    # executor 1 dies: blocks gone, tracker stale
    cluster.lose_executor(1)
    with pytest.raises(ShuffleFetchFailedError) as e:
        list(cluster.read_partition(7, 0, reader_executor_index=0))
    failed_exec = e.value.executor_id
    assert failed_exec == "exec-1"
    # driver-side recovery: invalidate + re-run the lost map task on a
    # surviving executor (lineage recomputation)
    lost = cluster.invalidate_map_output(7, failed_exec)
    assert lost == [1]
    for map_id in lost:
        cluster.write_map_output(7, map_id, 2,
                                 {0: make_batch(map_id * 100, 10)})
    got = []
    for b in cluster.read_partition(7, 0, reader_executor_index=0):
        got.extend(v for v in batch_values(b) if v is not None)
    expect = [v for m in range(3) for v in range(m * 100, m * 100 + 10)
              if v % 7 != 3]
    assert sorted(got) == sorted(expect)
