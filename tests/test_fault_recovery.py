"""Lineage-based fault recovery: the ladder that survives worker death,
fetch failures, and transport faults mid-query.

The reference escalates shuffle transport errors into Spark fetch
failures so the scheduler invalidates the dead executor's MapStatus and
re-runs the lost map tasks (RapidsShuffleIterator.scala:242-300); this
file fences our port of that ladder rung by rung — the deterministic
fault injector (shuffle/fault_injection.py), the multi-block fetch
failure contract, stale-client eviction against a RESTARTED peer, the
worker-handle liveness timeout + close() drain (a hung or oversized
reply must never deadlock the driver), the LocalCluster
lose/invalidate/re-register round trip, and the SPMD in-program
exchange degrading to the host path on a device error. The end-to-end
composition (kill + drop + truncate inside one query, oracle-matched)
lives in scripts/dist_chaos_check.py."""
import json
import time

import numpy as np
import pytest

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.runtime import recovery
from spark_rapids_tpu.shuffle import LocalCluster, ShuffleFetchFailedError
from spark_rapids_tpu.shuffle.fault_injection import (ShuffleFaultInjector,
                                                      arm_from_conf,
                                                      get_injector)
from spark_rapids_tpu.shuffle.meta import BlockId
from spark_rapids_tpu.shuffle.remote_worker import make_block_batch

from test_tcp_shuffle import batch_values, expect_values, spawn_worker

# the fault-recovery fence rides the chaos tier (runs in tier-1;
# scripts/dist_chaos_check.py is the CLI twin with --fast for CI)
pytestmark = pytest.mark.chaos


@pytest.fixture(autouse=True)
def _disarm():
    yield
    get_injector().disarm()


# ------------------------------------------------------------- injector


def test_trigger_fires_at_nth_with_burst():
    inj = ShuffleFaultInjector()
    inj.arm(drop_at_request=3, consecutive=2)
    # requests 3 and 4 drop (burst of 2), nothing before or after
    assert [inj.should_drop() for _ in range(6)] == \
        [False, False, True, True, False, False]
    assert inj.stats()["drops"] == 2
    assert inj.stats()["requests"] == 6


def test_truncate_halves_the_nth_chunk_payload():
    inj = ShuffleFaultInjector()
    inj.arm(truncate_at_request=2)
    payload = bytes(range(64))
    assert inj.maybe_truncate(payload) == payload
    short = inj.maybe_truncate(payload)
    assert short == payload[:32]
    assert inj.maybe_truncate(payload) == payload
    assert inj.stats()["truncations"] == 1
    # sub-2-byte payloads are never eligible (nothing to halve)
    inj.arm(truncate_at_request=1)
    assert inj.maybe_truncate(b"x") == b"x"


def test_seeded_probability_is_deterministic_and_capped():
    def run():
        inj = ShuffleFaultInjector()
        inj.arm(probability=0.5, seed=1234, max_injections=3)
        return [inj.should_drop() for _ in range(40)]

    a, b = run(), run()
    assert a == b  # same seed, same drops
    assert sum(a) == 3  # max_injections caps the chaos sweep


def test_kill_trigger_and_disarm():
    inj = ShuffleFaultInjector()
    inj.arm(kill_before_task=2)
    assert [inj.should_kill_task() for _ in range(3)] == \
        [False, True, False]
    inj.disarm()
    assert not inj.armed
    assert not inj.should_drop()
    assert inj.maybe_truncate(b"abcd") == b"abcd"


def test_arm_from_conf_roundtrip():
    conf = RapidsConf({
        cfg.SHUFFLE_FI_ENABLED.key: True,
        cfg.SHUFFLE_FI_DROP_AT.key: 5,
        cfg.SHUFFLE_FI_CONSECUTIVE.key: 4,
        cfg.SHUFFLE_FI_MAX.key: 9})
    assert arm_from_conf(conf)
    inj = get_injector()
    assert inj.armed
    fired = [inj.should_drop() for _ in range(10)]
    assert fired.index(True) == 4 and sum(fired) == 4
    assert not arm_from_conf(RapidsConf({}))
    assert not inj.armed


# ------------------------------------- fetch-failure contract (S2)


def test_fetch_failed_error_carries_all_blocks_and_progress():
    blocks = [BlockId(7, m, 0) for m in range(3)]
    e = ShuffleFetchFailedError(blocks, "exec-9", "boom",
                                batches_yielded=5)
    assert e.blocks == blocks and e.block == blocks[0]
    assert e.executor_id == "exec-9" and e.batches_yielded == 5
    assert "3 block(s)" in str(e) and "5 yielded" in str(e)
    # single-block call sites pass a bare BlockId
    e1 = ShuffleFetchFailedError(BlockId(1, 2, 3), "exec-0", "x")
    assert e1.blocks == [BlockId(1, 2, 3)]
    with pytest.raises(AssertionError):
        ShuffleFetchFailedError([], "exec-0", "empty")


def test_peer_fetch_failure_names_every_lost_block(tmp_path):
    """One dead peer holding TWO maps of the partition: the fetch
    failure lists both blocks, so recovery invalidates exactly the lost
    maps in one shot instead of discovering them one stage-retry at a
    time."""
    c = LocalCluster(2, spill_dir=str(tmp_path), transport="tcp")
    try:
        c.write_map_output(3, 0, 0, {0: make_block_batch(0, 10)})
        c.write_map_output(3, 1, 1, {0: make_block_batch(100, 10)})
        c.write_map_output(3, 2, 1, {0: make_block_batch(200, 10)})
        # executor 1 dies: socket gone, both its maps unreachable
        c.transport._servers["exec-1"].close()
        with pytest.raises(ShuffleFetchFailedError) as ei:
            list(c.read_partition(3, 0, reader_executor_index=0))
        e = ei.value
        assert e.executor_id == "exec-1"
        assert sorted(b.map_id for b in e.blocks) == [1, 2]
    finally:
        c.shutdown()


# --------------------------- lose/invalidate/re-register round trip (S4)


def test_local_cluster_recovery_round_trip(tmp_path):
    """The full LocalCluster-level lineage cycle: an executor loses its
    cached blocks, the tracked read converts to a fetch failure (never a
    silent skip), invalidation returns exactly the lost maps, the re-run
    lands on a survivor, and the re-read serves complete data."""
    c = LocalCluster(3, spill_dir=str(tmp_path), transport="tcp")
    try:
        spans = {0: (0, 30), 1: (100, 30), 2: (200, 30)}
        for mid, (lo, n) in spans.items():
            c.write_map_output(11, mid, mid, {0: make_block_batch(lo, n)})
        # tracked-block-lost-by-owner: executor 1's catalog empties but
        # the tracker still names it
        c.lose_executor(1)
        with pytest.raises(ShuffleFetchFailedError) as ei:
            list(c.read_partition(11, 0, reader_executor_index=0))
        assert ei.value.executor_id == "exec-1"

        lost = c.invalidate_map_output(11, "exec-1")
        assert lost == [1]
        # re-registration is idempotent against double-invalidation
        assert c.invalidate_map_output(11, "exec-1") == []
        for mid in lost:
            lo, n = spans[mid]
            c.write_map_output(11, mid, 2, {0: make_block_batch(lo, n)})
        got = []
        for b in c.read_partition(11, 0, reader_executor_index=0):
            got.extend(v for v in batch_values(b) if v is not None)
        assert sorted(got) == expect_values(list(spans.values()))
    finally:
        c.shutdown()


def test_owner_lost_local_block_is_fetch_failure(tmp_path):
    """The OWNER itself reads a tracked block it no longer holds: still
    a fetch failure naming the local executor — partial results must be
    impossible, even for local hits."""
    c = LocalCluster(2, spill_dir=str(tmp_path), transport="tcp")
    try:
        c.write_map_output(4, 0, 0, {0: make_block_batch(0, 10)})
        c.lose_executor(0)
        with pytest.raises(ShuffleFetchFailedError) as ei:
            list(c.read_partition(4, 0, reader_executor_index=0))
        assert ei.value.executor_id == "exec-0"
        assert "missing local block" in str(ei.value)
    finally:
        c.shutdown()


# --------------------------------------- stale-client eviction (S1)


def test_restarted_peer_reachable_after_eviction(tmp_path):
    """A peer dies and RESTARTS on a new port: the first failed fetch
    must evict the cached client, so after re-registration the next
    read connects to the new address instead of failing on the stale
    socket forever (the bug: _clients cached broken connections for the
    process lifetime)."""
    c = LocalCluster(1, spill_dir=str(tmp_path), transport="tcp")
    procs = []
    try:
        proc, host, port = spawn_worker({
            "executor_id": "exec-remote",
            "blocks": [[21, 0, 0, 0, 50]]})
        procs.append(proc)
        c.register_remote_executor("exec-remote", host, port)
        c.register_remote_map_output(21, 0, "exec-remote", {0})
        got = [v for b in c.read_partition(21, 0, 0)
               for v in batch_values(b) if v is not None]
        assert sorted(got) == expect_values([(0, 50)])
        assert ("exec-0", "exec-remote") in c._clients

        proc.kill()
        proc.wait()
        with pytest.raises(ShuffleFetchFailedError):
            list(c.read_partition(21, 0, reader_executor_index=0))
        # the failure evicted the broken client
        assert ("exec-0", "exec-remote") not in c._clients

        # same executor id, NEW process, NEW port
        proc2, host2, port2 = spawn_worker({
            "executor_id": "exec-remote",
            "blocks": [[21, 0, 0, 0, 50]]})
        procs.append(proc2)
        c.register_remote_executor("exec-remote", host2, port2)
        got = [v for b in c.read_partition(21, 0, 0)
               for v in batch_values(b) if v is not None]
        assert sorted(got) == expect_values([(0, 50)])
    finally:
        for p in procs:
            p.kill()
        c.shutdown()


# --------------------------- worker handle liveness + close() (S3)


def _spawn_handle(executor_id, **kw):
    from spark_rapids_tpu.runtime.cluster import RemoteWorkerHandle

    return RemoteWorkerHandle.spawn(executor_id, **kw)


def test_close_survives_oversized_error_reply():
    """Regression: a worker blocked mid-write on a reply larger than
    the OS pipe buffer (here a traceback embedding an 8 MiB command)
    used to deadlock close() — the driver waited for exit while the
    worker waited for the driver to read. The reader thread keeps
    draining, so close() must finish promptly and leave no process."""
    h = _spawn_handle("exec-close-test")
    # the task loop asserts cmd == run_map with the OFFENDING dict in
    # the assertion message — the error reply embeds all 8 MiB
    h.proc.stdin.write(json.dumps(
        {"cmd": "boom", "junk": "z" * (8 << 20)}) + "\n")
    h.proc.stdin.flush()
    t0 = time.monotonic()
    h.close()
    took = time.monotonic() - t0
    assert took < 10.0, f"close() stalled {took:.1f}s"
    assert not h.alive


def test_run_map_times_out_on_hung_worker():
    """A worker that stops responding mid-task: run_map bounds its wait
    (taskTimeoutSec), KILLS the hung process (a late completion must
    never double-register output), and raises ConnectionError so the
    scheduler re-places the task."""

    class _SleepBomb:
        def __reduce__(self):
            return (time.sleep, (30,))

    h = _spawn_handle("exec-hang-test", task_timeout=1.0)
    t0 = time.monotonic()
    with pytest.raises(ConnectionError) as ei:
        h.run_map({"bomb": _SleepBomb()})
    took = time.monotonic() - t0
    assert "unresponsive" in str(ei.value)
    assert took < 15.0
    assert not h.alive  # killed, not left hanging
    h.close()


def test_run_map_reports_death_at_submit():
    h = _spawn_handle("exec-dead-test")
    h.kill()
    with pytest.raises(ConnectionError):
        h.run_map({"shuffle_id": 0})
    h.close()


def test_injected_kill_fires_before_nth_task():
    get_injector().arm(kill_before_task=1)
    h = _spawn_handle("exec-kill-test")
    try:
        with pytest.raises(ConnectionError):
            h.run_map({"shuffle_id": 0})
        assert not h.alive
        assert get_injector().stats()["kills"] == 1
    finally:
        h.close()


# ------------------------------------------- SPMD degrade (tentpole d)


def test_in_program_exchange_degrades_to_host_on_device_error():
    """A device error inside the compiled in-program exchange: the
    leader catches it, records the degrade, and the SAME exchange
    re-materializes on the host/TCP path — identical results, one
    degrade per query, never a crash. InjectedOOM classifies as a
    device error, so the CPU fence drives the real except path."""
    from spark_rapids_tpu.execs.exchange import ShuffleExchangeExec
    from spark_rapids_tpu.memory import fault_injection as mem_fi
    from spark_rapids_tpu.parallel import spmd
    from spark_rapids_tpu.parallel.mesh import data_mesh

    rng = np.random.default_rng(77)
    n = 120
    keys = rng.integers(0, 9, n).astype(np.int64)
    kv = np.ones(n, dtype=bool)
    vals = rng.random(n)
    parts = [[(keys, kv, vals)]]

    from test_spmd_shuffle import _drain_exchange, _rows_exec

    host = ShuffleExchangeExec(("hash", [0]), 4, _rows_exec(parts))
    want = _drain_exchange(host)

    prog = ShuffleExchangeExec(("hash", [0]), 4, _rows_exec(parts))
    prog.enable_in_program(data_mesh(8))
    before = recovery.snapshot()
    fb_before = spmd.fallback_snapshot()
    mem_fi.get_injector().arm(at_call=1, sites=["exchange.inProgram"])
    try:
        got = _drain_exchange(prog)
    finally:
        mem_fi.get_injector().disarm()

    assert got == want  # bit-identical partition placement
    assert not prog.in_program  # degraded once, stays host for the query
    assert recovery.delta(before)["spmd_degrades"] == 1
    fb = spmd.fallback_delta(fb_before)
    assert fb == {f"exchange: {spmd.DEGRADE_DEVICE_ERROR}": 1}


def test_in_program_exchange_reraises_non_device_errors():
    """A plan/user error inside the in-program path is NOT degradable:
    it would fail identically on the host, so it surfaces unchanged
    (degrading would just run the query twice to the same failure)."""
    from spark_rapids_tpu.parallel import spmd

    assert not spmd.is_degradable_device_error(ValueError("bad plan"))
    assert not spmd.is_degradable_device_error(KeyError("col"))
    from spark_rapids_tpu.memory.fault_injection import InjectedOOM

    assert spmd.is_degradable_device_error(InjectedOOM("site", 1))
    assert spmd.is_degradable_device_error(MemoryError())


# ------------------------------------------------- recovery counters


def test_recovery_counter_snapshot_delta():
    before = recovery.snapshot()
    recovery.bump("fetch_failures")
    recovery.bump("maps_rerun", 3)
    d = recovery.delta(before)
    assert d["fetch_failures"] == 1 and d["maps_rerun"] == 3
    assert d["workers_respawned"] == 0
    assert set(d) == set(recovery.snapshot())


def test_service_stats_carry_recovery_block():
    from spark_rapids_tpu.service.stats import ServiceStats

    s = ServiceStats(
        queue_depth=0, running=0, admitted_inflight=0, inflight_bytes=0,
        budget_bytes=None, counters={}, queue_time_hist={},
        run_time_hist={}, per_query=[], progcache={}, semaphore={},
        recovery=recovery.snapshot())
    d = s.to_dict()
    assert set(d["recovery"]) == set(recovery.snapshot())
