"""Split-and-retry framework + fault injection (memory/retry.py,
memory/fault_injection.py) — the unit half of the OOM-resilience
subsystem; tests/test_chaos.py is the end-to-end fence."""
import numpy as np
import pytest

from spark_rapids_tpu.columnar import dtypes as dt
from spark_rapids_tpu.columnar.batch import ColumnarBatch
from spark_rapids_tpu.columnar.column import Column
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.memory import retry as R
from spark_rapids_tpu.memory import fault_injection as FI
from spark_rapids_tpu.memory.catalog import (BufferCatalog,
                                             set_buffer_owner)
from spark_rapids_tpu.memory.oom import with_oom_retry


OOM_MSG = "RESOURCE_EXHAUSTED: Out of memory allocating 1234 bytes"


@pytest.fixture(autouse=True)
def _clean_state():
    FI.get_injector().disarm()
    R.reset_config()
    yield
    FI.get_injector().disarm()
    R.reset_config()


def make_batch(n=256, seed=0):
    rng = np.random.default_rng(seed)
    return ColumnarBatch(
        [Column.from_numpy(rng.integers(0, 1000, n).astype(np.int64))],
        n)


class TestIsOomError:
    def test_xla_resource_exhausted_matches(self):
        assert R.is_oom_error(RuntimeError(OOM_MSG))
        assert R.is_oom_error(RuntimeError(
            "Resource exhausted: while allocating"))
        assert R.is_oom_error(MemoryError())
        assert R.is_oom_error(FI.InjectedOOM("site", 1))

    def test_user_data_mentioning_oom_does_not_match(self):
        # the old bare-substring scan classified these as device OOM
        assert not R.is_oom_error(ValueError("column 'OOM' not found"))
        assert not R.is_oom_error(KeyError("OOM"))
        assert not R.is_oom_error(RuntimeError(
            "parse error near token 'OOM'"))
        assert not R.is_oom_error(RuntimeError(
            "user wrote RESOURCE_EXHAUSTEDISH"))

    def test_non_runtime_error_never_matches(self):
        assert not R.is_oom_error(ValueError(OOM_MSG))


class TestSpillLadder:
    def test_spills_then_succeeds(self):
        cat = BufferCatalog()
        cat.register(make_batch(), priority=0)
        before = cat.device_bytes
        calls = []

        def fn():
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError(OOM_MSG)
            return "ok"

        pre = R.snapshot()
        assert R.with_retry_no_split(fn, catalog=cat, tag="t1") == "ok"
        d = R.delta(pre)
        assert d["oom_retries"] == 1
        assert d["spilled_bytes"] == before  # spill-to-half spilled all
        assert cat.device_bytes == 0

    def test_give_up_chains_original_error(self):
        cat = BufferCatalog()

        def always_oom():
            raise RuntimeError(OOM_MSG)

        with pytest.raises(R.SplitAndRetryOOM) as ei:
            R.with_retry_no_split(always_oom, catalog=cat, tag="t2")
        assert isinstance(ei.value.__cause__, RuntimeError)
        assert "RESOURCE_EXHAUSTED" in str(ei.value.__cause__)

    def test_non_oom_error_passes_through_immediately(self):
        calls = []

        def fn():
            calls.append(1)
            raise ValueError("not an OOM")

        with pytest.raises(ValueError):
            R.with_retry_no_split(fn, catalog=BufferCatalog())
        assert len(calls) == 1  # no retry on a user error

    def test_legacy_with_oom_retry_shim(self):
        cat = BufferCatalog()
        assert with_oom_retry(lambda: 42, catalog=cat) == 42
        with pytest.raises(ValueError):
            with_oom_retry(lambda: (_ for _ in ()).throw(ValueError()),
                           catalog=cat)


class TestSplitAndRetry:
    def test_splits_until_fits(self):
        """fn rejects items above a size bound; the ladder halves the
        input until every part fits, and the parts cover the input."""
        cat = BufferCatalog()

        def fn(item):
            if item[1] - item[0] > 25:
                raise RuntimeError(OOM_MSG)
            return item

        def split(item):
            lo, hi = item
            if hi - lo <= 1:
                return None
            mid = (lo + hi) // 2
            return [(lo, mid), (mid, hi)]

        pre = R.snapshot()
        out = R.with_retry((0, 100), fn, split=split, catalog=cat,
                           tag="t3", max_spill_retries=0)
        assert out[0][0] == 0 and out[-1][1] == 100
        for (a, b), (c, d) in zip(out, out[1:]):
            assert b == c  # contiguous cover, in order
        assert all(b - a <= 25 for a, b in out)
        assert R.delta(pre)["oom_splits"] >= 3

    def test_split_depth_bound_gives_up(self):
        def always_oom(item):
            raise RuntimeError(OOM_MSG)

        with pytest.raises(R.SplitAndRetryOOM):
            R.with_retry((0, 1024), always_oom,
                         split=lambda it: [(it[0], sum(it) // 2),
                                           (sum(it) // 2, it[1])],
                         catalog=BufferCatalog(), tag="t4",
                         max_spill_retries=0, max_split_depth=3)

    def test_halve_batch_covers_rows(self):
        b = make_batch(101)
        halves = R.halve_batch(b)
        assert len(halves) == 2
        assert halves[0].realized_num_rows() + \
            halves[1].realized_num_rows() == 101
        one = ColumnarBatch(b.columns, 1).slice(0, 1)
        assert R.halve_batch(one) is None

    def test_config_wiring(self):
        conf = RapidsConf({"rapids.tpu.memory.retry.maxSpillRetries": 0,
                           "rapids.tpu.memory.retry.maxSplitDepth": 0})
        R.configure_from_conf(conf)
        calls = []

        def fn():
            calls.append(1)
            raise RuntimeError(OOM_MSG)

        with pytest.raises(R.SplitAndRetryOOM):
            R.with_retry_no_split(fn, catalog=BufferCatalog())
        assert len(calls) == 1  # zero spill rungs configured


class TestFaultInjection:
    def test_at_call_fires_deterministically(self):
        inj = FI.get_injector()
        inj.arm(at_call=2, consecutive=1)
        inj.maybe_inject("a")  # call 1: clean
        with pytest.raises(FI.InjectedOOM):
            inj.maybe_inject("a")  # call 2: fires
        inj.maybe_inject("a")  # burst over
        assert inj.stats()["injections"] == 1

    def test_sites_prefix_filter(self):
        inj = FI.get_injector()
        inj.arm(at_call=1, sites=["join"])
        inj.maybe_inject("aggregate.update")  # ineligible
        with pytest.raises(FI.InjectedOOM):
            inj.maybe_inject("join.probe")

    def test_consecutive_pushes_ladder_to_split(self):
        """consecutive=3 fails the first try AND both spill retries,
        forcing a genuine split; the halves then run clean."""
        FI.get_injector().arm(at_call=1, consecutive=3)
        cat = BufferCatalog()
        pre = R.snapshot()
        out = R.with_retry((0, 8), lambda it: it,
                           split=lambda it: [(it[0], sum(it) // 2),
                                             (sum(it) // 2, it[1])],
                           catalog=cat, tag="x")
        assert out == [(0, 4), (4, 8)]
        d = R.delta(pre)
        assert d["oom_retries"] == 2 and d["oom_splits"] == 1

    def test_probability_mode_is_seeded(self):
        def run(seed):
            inj = FI.FaultInjector()
            inj.arm(probability=0.5, seed=seed, max_injections=100)
            fired = []
            for i in range(50):
                try:
                    inj.maybe_inject("s")
                    fired.append(False)
                except FI.InjectedOOM:
                    fired.append(True)
            return fired

        assert run(7) == run(7)
        assert any(run(7)) and not all(run(7))

    def test_max_injections_caps(self):
        inj = FI.get_injector()
        inj.arm(probability=1.0, seed=1, max_injections=2)
        hits = 0
        for _ in range(10):
            try:
                inj.maybe_inject("s")
            except FI.InjectedOOM:
                hits += 1
        assert hits == 2

    def test_arm_from_conf(self):
        conf = RapidsConf({
            "rapids.tpu.memory.faultInjection.enabled": True,
            "rapids.tpu.memory.faultInjection.atCall": 1,
            "rapids.tpu.memory.faultInjection.sites": "sort",
        })
        assert FI.arm_from_conf(conf)
        inj = FI.get_injector()
        with pytest.raises(FI.InjectedOOM):
            inj.maybe_inject("sort.concat")
        assert not FI.arm_from_conf(RapidsConf())
        assert not FI.get_injector().armed


class TestPerOwnerAccounting:
    def test_owner_attribution_and_pop(self):
        cat = BufferCatalog()
        owner = ("svc-query", 991)
        prev = set_buffer_owner(owner)
        try:
            calls = []

            def fn():
                calls.append(1)
                if len(calls) == 1:
                    raise RuntimeError(OOM_MSG)
                return 1

            R.with_retry_no_split(fn, catalog=cat, tag="owned")
        finally:
            set_buffer_owner(prev)
        assert R.owner_stats(owner)["oom_retries"] == 1
        popped = R.pop_owner_stats(owner)
        assert popped["oom_retries"] == 1
        assert R.owner_stats(owner)["oom_retries"] == 0  # popped
