"""End-to-end SQL over the multi-process cluster runtime.

The round-4 structural item: in the reference, the shuffle transport
lives INSIDE the shuffle manager real queries use — map tasks write
partitioned batches into their executor's catalog and MapStatus names
the owner (RapidsShuffleInternalManager.scala:90-191); reduce tasks
read local hits zero-copy plus remote blocks through the transport
(RapidsCachingReader.scala:59-145); a fetch failure drives stage retry
(RapidsShuffleIterator.scala:242-300). Here ``Session.sql`` executes a
join+groupby whose shuffles cross a REAL process boundary: at least one
map task runs inside a second OS process (shuffle/remote_worker.py task
mode), serves its output over TCP, and a killed worker surfaces as a
fetch failure that re-runs its map tasks on survivors."""
import numpy as np
import pandas as pd
import pytest

from compare import assert_frames_equal
from spark_rapids_tpu.api import Session
from spark_rapids_tpu.runtime.cluster import (ClusterShuffleExchangeExec,
                                              session_cluster,
                                              shutdown_session_cluster)

CONF = {
    "rapids.tpu.cluster.enabled": True,
    "rapids.tpu.cluster.executors": 2,
    "rapids.tpu.cluster.workers": 1,
    "rapids.tpu.sql.shuffle.partitions": 4,
    # tiny test tables must SHUFFLE (the scenario under test), not
    # take the small-build broadcast shortcut
    "rapids.tpu.sql.autoBroadcastJoinThreshold": 0,
}

QUERY = ("SELECT d.name AS name, sum(s.v) AS total, count(*) AS n "
         "FROM sales s JOIN dim d ON s.k = d.id "
         "GROUP BY d.name ORDER BY name")


@pytest.fixture(scope="module")
def cluster_teardown():
    yield
    shutdown_session_cluster()


def _views(s: Session, n=400) -> None:
    """Multi-partition inputs: a single-partition source makes the
    planner broadcast the join and skip the aggregate exchange, leaving
    nothing for the cluster runtime to do."""
    rng = np.random.default_rng(7)
    s.create_temp_view("sales", s.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 20, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64)}))
        .repartition(3, "k"))
    s.create_temp_view("dim", s.create_dataframe(pd.DataFrame({
        "id": np.arange(20, dtype=np.int64),
        "name": np.array([f"g{i % 5}" for i in range(20)],
                         dtype=object)}))
        .repartition(2, "id"))


def _expected() -> pd.DataFrame:
    plain = Session()
    _views(plain)
    return plain.sql(QUERY).collect()


def _cluster_exchanges(node, out=None):
    out = [] if out is None else out
    if isinstance(node, ClusterShuffleExchangeExec):
        out.append(node)
    for c in node.children:
        _cluster_exchanges(c, out)
    return out


def _worker_assignments(runtime):
    return [eid for maps in runtime.assignments.values()
            for eid in maps.values() if eid.startswith("exec-worker")]


def test_cluster_sql_two_processes(cluster_teardown):
    """Session.sql join+groupby: every hash/single exchange runs through
    per-executor shuffle catalogs over TCP, with >=1 map task executed
    by a separate worker process (which itself FETCHES its nested
    shuffle inputs from the driver process's executors)."""
    s = Session(CONF)
    _views(s)
    df = s.sql(QUERY)
    got = df.collect()
    assert_frames_equal(_expected(), got, sort=False)

    # the plan really was cluster-lowered, not silently single-process
    exchanges = _cluster_exchanges(df._last_exec)
    assert len(exchanges) >= 3  # join sides + final aggregate
    assert all(ex.shuffle_id is not None for ex in exchanges)

    # at least one map task ran in the second OS process and its output
    # came back over real sockets (correctness above proves the read:
    # those blocks exist nowhere else)
    runtime = session_cluster(s.conf)
    assert runtime is not None and len(runtime.workers) == 1
    assert runtime.workers[0].alive
    assert _worker_assignments(runtime), \
        "no map task was placed on the worker process"


def test_cluster_worker_death_stage_retry(cluster_teardown):
    """Kill the worker AFTER its map outputs registered: the reduce read
    hits a dead TCP peer, converts to a fetch failure, the tracker
    invalidates the dead executor's outputs, and its map tasks re-run on
    the surviving in-process executors (Spark's recovery model)."""
    s = Session(CONF)
    _views(s, n=350)
    df = s.sql(QUERY)
    exec_ = df._exec()

    # map side first: materialize every cluster shuffle, so the worker
    # holds real output when it dies
    for ex in _cluster_exchanges(exec_):
        ex._materialize()
    runtime = session_cluster(s.conf)
    owned = _worker_assignments(runtime)
    assert owned, "worker owned no map output before the kill"
    runtime.workers[0].kill()

    from spark_rapids_tpu.execs.base import collect
    got = collect(exec_, conf=s.conf)

    plain = Session()
    _views(plain, n=350)
    assert_frames_equal(plain.sql(QUERY).collect(), got, sort=False)

    # recovery really rewrote the tracker for every shuffle the reduce
    # pass read: the re-runs landed on survivors. (Shuffles whose maps
    # the dead worker held but which were never re-read keep their stale
    # entries — recovery is lazy, as in Spark.)
    dead = runtime.workers[0].executor_id
    top_sid = _cluster_exchanges(exec_)[0].shuffle_id
    maps = runtime.cluster._map_outputs[top_sid]
    assert maps and all(eid != dead for eid, _parts in maps.values())
