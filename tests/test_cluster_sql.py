"""End-to-end SQL over the multi-process cluster runtime.

The round-4 structural item: in the reference, the shuffle transport
lives INSIDE the shuffle manager real queries use — map tasks write
partitioned batches into their executor's catalog and MapStatus names
the owner (RapidsShuffleInternalManager.scala:90-191); reduce tasks
read local hits zero-copy plus remote blocks through the transport
(RapidsCachingReader.scala:59-145); a fetch failure drives stage retry
(RapidsShuffleIterator.scala:242-300). Here ``Session.sql`` executes a
join+groupby whose shuffles cross a REAL process boundary: at least one
map task runs inside a second OS process (shuffle/remote_worker.py task
mode), serves its output over TCP, and a killed worker surfaces as a
fetch failure that re-runs its map tasks on survivors."""
import numpy as np
import pandas as pd
import pytest

from compare import assert_frames_equal
from spark_rapids_tpu.api import Session
from spark_rapids_tpu.runtime.cluster import (ClusterShuffleExchangeExec,
                                              session_cluster,
                                              shutdown_session_cluster)

CONF = {
    "rapids.tpu.cluster.enabled": True,
    "rapids.tpu.cluster.executors": 2,
    "rapids.tpu.cluster.workers": 1,
    "rapids.tpu.sql.shuffle.partitions": 4,
    # tiny test tables must SHUFFLE (the scenario under test), not
    # take the small-build broadcast shortcut
    "rapids.tpu.sql.autoBroadcastJoinThreshold": 0,
}

QUERY = ("SELECT d.name AS name, sum(s.v) AS total, count(*) AS n "
         "FROM sales s JOIN dim d ON s.k = d.id "
         "GROUP BY d.name ORDER BY name")


@pytest.fixture(scope="module")
def cluster_teardown():
    yield
    shutdown_session_cluster()


def _views(s: Session, n=400) -> None:
    """Multi-partition inputs: a single-partition source makes the
    planner broadcast the join and skip the aggregate exchange, leaving
    nothing for the cluster runtime to do."""
    rng = np.random.default_rng(7)
    s.create_temp_view("sales", s.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 20, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64)}))
        .repartition(3, "k"))
    s.create_temp_view("dim", s.create_dataframe(pd.DataFrame({
        "id": np.arange(20, dtype=np.int64),
        "name": np.array([f"g{i % 5}" for i in range(20)],
                         dtype=object)}))
        .repartition(2, "id"))


def _expected() -> pd.DataFrame:
    plain = Session()
    _views(plain)
    return plain.sql(QUERY).collect()


def _cluster_exchanges(node, out=None):
    out = [] if out is None else out
    if isinstance(node, ClusterShuffleExchangeExec):
        out.append(node)
    for c in node.children:
        _cluster_exchanges(c, out)
    return out


def _worker_assignments(runtime):
    return [eid for maps in runtime.assignments.values()
            for eid in maps.values() if eid.startswith("exec-worker")]


def test_cluster_sql_two_processes(cluster_teardown):
    """Session.sql join+groupby: every hash/single exchange runs through
    per-executor shuffle catalogs over TCP, with >=1 map task executed
    by a separate worker process (which itself FETCHES its nested
    shuffle inputs from the driver process's executors)."""
    s = Session(CONF)
    _views(s)
    df = s.sql(QUERY)
    got = df.collect()
    assert_frames_equal(_expected(), got, sort=False)

    # the plan really was cluster-lowered, not silently single-process
    exchanges = _cluster_exchanges(df._last_exec)
    assert len(exchanges) >= 3  # join sides + final aggregate
    assert all(ex.shuffle_id is not None for ex in exchanges)

    # at least one map task ran in the second OS process and its output
    # came back over real sockets (correctness above proves the read:
    # those blocks exist nowhere else)
    runtime = session_cluster(s.conf)
    assert runtime is not None and len(runtime.workers) == 1
    assert runtime.workers[0].alive
    assert _worker_assignments(runtime), \
        "no map task was placed on the worker process"


def test_cluster_worker_death_stage_retry(cluster_teardown):
    """Kill the worker AFTER its map outputs registered: the reduce read
    hits a dead TCP peer, converts to a fetch failure, the tracker
    invalidates the dead executor's outputs, and its map tasks re-run on
    the surviving in-process executors (Spark's recovery model)."""
    s = Session(CONF)
    _views(s, n=350)
    df = s.sql(QUERY)
    exec_ = df._exec()

    # map side first: materialize every cluster shuffle, so the worker
    # holds real output when it dies
    for ex in _cluster_exchanges(exec_):
        ex._materialize()
    runtime = session_cluster(s.conf)
    owned = _worker_assignments(runtime)
    assert owned, "worker owned no map output before the kill"
    runtime.workers[0].kill()

    from spark_rapids_tpu.execs.base import collect
    got = collect(exec_, conf=s.conf)

    plain = Session()
    _views(plain, n=350)
    assert_frames_equal(plain.sql(QUERY).collect(), got, sort=False)

    # recovery really rewrote the tracker for every shuffle the reduce
    # pass read: the re-runs landed on survivors. (Shuffles whose maps
    # the dead worker held but which were never re-read keep their stale
    # entries — recovery is lazy, as in Spark.)
    dead = runtime.workers[0].executor_id
    top_sid = _cluster_exchanges(exec_)[0].shuffle_id
    maps = runtime.cluster._map_outputs[top_sid]
    assert maps and all(eid != dead for eid, _parts in maps.values())


def test_mesh_subtree_ships_to_worker_process(cluster_teardown):
    """Round-5 composition (SURVEY §5.8 ICI+DCN): a cluster map task
    whose subtree contains MESH execs runs INSIDE a worker process —
    the mesh reconstructs from a shipped axis-size spec over the
    worker's own virtual devices (ICI collectives intra-task), and the
    task's output comes back over the TCP shuffle (DCN between
    executors). No silent local placement: the exchange's fallback list
    must stay empty."""
    import numpy as np
    import pandas as pd

    from spark_rapids_tpu.parallel.execs import (MeshGroupByExec,
                                                 MeshShuffledJoinExec)

    conf = dict(CONF)
    conf["rapids.tpu.mesh.enabled"] = True
    conf["rapids.tpu.mesh.devices"] = 4
    conf["rapids.tpu.cluster.executors"] = 1
    s = Session(conf)
    rng = np.random.default_rng(11)
    n = 600
    s.create_temp_view("sales", s.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 30, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64)})))
    s.create_temp_view("dim", s.create_dataframe(pd.DataFrame({
        "id": np.arange(30, dtype=np.int64),
        "g": (np.arange(30) % 4).astype(np.int64)})))
    # mesh-lowered join+groupby BELOW a cluster hash exchange: the
    # repartition forces a cluster shuffle whose single map task IS the
    # whole mesh subtree
    inner = s.sql("SELECT dim.g AS g, sum(sales.v) AS sv FROM sales "
                  "JOIN dim ON sales.k = dim.id GROUP BY dim.g")
    df = inner.repartition(2, "g")
    exec_ = df._exec()
    # the subtree under the cluster exchange really is mesh-lowered
    found_mesh = []

    def walk(node):
        if isinstance(node, (MeshGroupByExec, MeshShuffledJoinExec)):
            found_mesh.append(node)
        for c in node.children:
            walk(c)
    walk(exec_)
    assert found_mesh, exec_.tree_string()

    runtime = session_cluster(s.conf)
    assert runtime is not None and runtime.mesh_devices >= 2
    # steer placement so the mesh map task lands on the WORKER process,
    # not the in-process executor (injectable placement seam — no
    # coupling to the round-robin counter internals)
    wid = runtime.workers[0].executor_id
    runtime.placement_hook = \
        lambda sid, mid, targets: wid if wid in targets else None

    from spark_rapids_tpu.execs.base import collect
    try:
        got = collect(exec_, conf=s.conf)
    finally:
        runtime.placement_hook = None  # module-cached runtime

    # rebuild views on a plain session for the oracle
    plain = Session()
    rng = np.random.default_rng(11)
    plain.create_temp_view("sales", plain.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 30, n).astype(np.int64),
        "v": rng.integers(0, 100, n).astype(np.int64)})))
    plain.create_temp_view("dim", plain.create_dataframe(pd.DataFrame({
        "id": np.arange(30, dtype=np.int64),
        "g": (np.arange(30) % 4).astype(np.int64)})))
    want = plain.sql("SELECT dim.g AS g, sum(sales.v) AS sv FROM sales "
                     "JOIN dim ON sales.k = dim.id GROUP BY dim.g").collect()
    assert_frames_equal(want, got, sort=True)

    # the mesh task really ran in the worker process (no silent local
    # placement), and its blocks served over TCP
    exchanges = _cluster_exchanges(exec_)
    assert exchanges
    for ex in exchanges:
        assert ex.local_fallbacks == [], ex.local_fallbacks
    owned = _worker_assignments(runtime)
    assert owned, ("mesh map task was not placed on the worker",
                   runtime.assignments)


def test_cluster_global_order_by_crosses_processes(cluster_teardown):
    """Round-5: cluster-mode range exchange — the driver aggregates
    per-map key samples (remote maps sample IN the worker process),
    resolves bounds, and ships partition tasks with bounds attached;
    the global ORDER BY's rows cross OS processes and come back in
    exact global order (GpuRangePartitioner.scala:42-95 two-job
    split)."""
    import numpy as np
    import pandas as pd

    conf = dict(CONF)
    # a tiny batch budget keeps the 500-row sort DISTRIBUTED: with the
    # default budget the cluster exchange would (correctly) collapse
    # this input to one partition and never range-partition at all
    conf["rapids.tpu.sql.batchSizeBytes"] = 1024
    s = Session(conf)
    rng = np.random.default_rng(23)
    n = 500
    pdf = pd.DataFrame({
        "k": rng.integers(0, 1000, n).astype(np.int64),
        "v": rng.normal(size=n)})
    s.create_temp_view("t", s.create_dataframe(pdf).repartition(3, "k"))
    df = s.sql("SELECT k, v FROM t ORDER BY k, v")
    got = df.collect()
    exec_ = df._last_exec
    ranges = [ex for ex in _cluster_exchanges(exec_)
              if ex.partitioning[0] == "range"]
    assert ranges, exec_.tree_string()

    plain = Session()
    plain.create_temp_view("t", plain.create_dataframe(pdf))
    want = plain.sql("SELECT k, v FROM t ORDER BY k, v").collect()
    assert_frames_equal(want, got, sort=False)  # exact global order
    # bounds resolved and the shuffle materialized through the cluster
    assert all(ex.partitioning[2] is not None for ex in ranges)
    assert all(ex.shuffle_id is not None for ex in ranges)


def test_cluster_adaptive_coalesced_read(cluster_teardown):
    """Round-5: AQE above a cluster exchange — partition sizes come
    from the tracker's MapStatus sizes (not an in-process block store),
    and tiny partitions coalesce into fewer reduce groups while the
    result still matches (GpuCustomShuffleReaderExec role)."""
    import numpy as np
    import pandas as pd

    from spark_rapids_tpu.execs.adaptive import AdaptiveShuffleReaderExec

    conf = dict(CONF)
    conf["rapids.tpu.sql.shuffle.partitions"] = 4
    s = Session(conf)
    rng = np.random.default_rng(29)
    n = 400
    s.create_temp_view("t", s.create_dataframe(pd.DataFrame({
        "k": rng.integers(0, 10, n).astype(np.int64),
        "v": rng.integers(0, 50, n).astype(np.int64)}))
        .repartition(3, "k"))
    df = s.sql("SELECT k, sum(v) AS sv, count(*) AS n FROM t GROUP BY k")
    exec_ = df._exec()

    readers = []

    def walk(node):
        if isinstance(node, AdaptiveShuffleReaderExec):
            readers.append(node)
        for c in node.children:
            walk(c)
    walk(exec_)
    assert readers, exec_.tree_string()
    from spark_rapids_tpu.runtime.cluster import ClusterShuffleExchangeExec
    assert any(isinstance(r.exchange, ClusterShuffleExchangeExec)
               for r in readers), exec_.tree_string()

    got = df.collect()
    # regenerate identical data for the oracle
    rng2 = np.random.default_rng(29)
    pdf = pd.DataFrame({"k": rng2.integers(0, 10, n).astype(np.int64),
                        "v": rng2.integers(0, 50, n).astype(np.int64)})
    plain = Session()
    plain.create_temp_view("t", plain.create_dataframe(pdf))
    want = plain.sql(
        "SELECT k, sum(v) AS sv, count(*) AS n FROM t GROUP BY k").collect()
    assert_frames_equal(want, got, sort=True)
    # the tracker sizes actually coalesced the 4 tiny partitions
    r = next(r for r in readers
             if isinstance(r.exchange, ClusterShuffleExchangeExec))
    assert len(r.groups) < r.exchange.num_out_partitions, r.groups


def test_cluster_concurrent_fetch_failure_recovery(cluster_teardown):
    """Two reduce tasks failing on the SAME dead peer concurrently:
    recovery serializes on _recover_lock; the second finds the tracker
    already repaired and rebuilds its stub — no partial data, no
    double re-run of the same map (round-4 weak #3)."""
    import threading

    import numpy as np
    import pandas as pd

    s = Session(CONF)
    _views(s, n=400)
    df = s.sql(QUERY)
    exec_ = df._exec()
    for ex in _cluster_exchanges(exec_):
        ex._materialize()
    runtime = session_cluster(s.conf)
    owned = _worker_assignments(runtime)
    assert owned, "worker owned no map output before the kill"
    runtime.workers[0].kill()

    from spark_rapids_tpu.execs.base import collect
    results: dict = {}
    errs: list = []

    def run(tag):
        try:
            results[tag] = collect(exec_, conf=s.conf)
        except Exception as e:  # noqa: BLE001 - recorded for assertion
            errs.append(e)

    t1 = threading.Thread(target=run, args=("a",))
    t2 = threading.Thread(target=run, args=("b",))
    t1.start(); t2.start(); t1.join(); t2.join()
    assert not errs, errs
    plain = Session()
    _views(plain, n=400)
    want = plain.sql(QUERY).collect()
    assert_frames_equal(want, results["a"], sort=False)
    assert_frames_equal(want, results["b"], sort=False)
    # the re-read shuffle's tracker never references the dead executor
    # afterwards (recovery is lazy: shuffles never re-read keep stale
    # entries, same as the single-failure test above)
    dead = runtime.workers[0].executor_id
    top_sid = _cluster_exchanges(exec_)[0].shuffle_id
    maps = runtime.cluster._map_outputs[top_sid]
    assert maps and all(eid != dead for eid, _p in maps.values())
