// Native host runtime for the TPU columnar engine.
//
// The reference reaches native code for its host-side data plane through
// cuDF JNI + nvcomp (LZ4 batch compression, NvcompLZ4CompressionCodec.scala)
// and UCX. The TPU build's device compute is XLA; this library supplies the
// host-side hot loops that stay native in any serious runtime:
//
//   - LZ4 block-format compress/decompress (shuffle + spill payloads; the
//     nvcomp-LZ4 analogue). Clean-room implementation of the public block
//     format (token | literals | offset | matchlen sequences).
//   - validity bitmap pack/unpack (bool bytes <-> bits; 8x smaller wire
//     validity, like cudf's packed validity masks).
//   - CRC32C (Castagnoli) checksums for spill-file integrity.
//
// Exposed as a C ABI consumed via ctypes (no pybind11 in the image).
#include <cstdint>
#include <cstring>
#include <cstddef>

extern "C" {

// ---------------------------------------------------------------------------
// LZ4 block format
// ---------------------------------------------------------------------------

static inline uint32_t read32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

static const int kMinMatch = 4;
static const int kHashBits = 16;

static inline uint32_t hash4(uint32_t v) {
  return (v * 2654435761u) >> (32 - kHashBits);
}

// Worst-case compressed size for n input bytes (classic LZ4 bound).
long srt_lz4_max_compressed(long n) {
  return n + n / 255 + 16;
}

// Returns compressed size, or -1 if dst is too small.
long srt_lz4_compress(const uint8_t* src, long n, uint8_t* dst,
                      long dst_cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  // spec: last match must start >= 12 bytes before end; last 5 bytes are
  // always literals
  const uint8_t* const mflimit = (n >= 13) ? iend - 12 : src;
  const uint8_t* const matchlimit = iend - 5;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;
  const uint8_t* anchor = src;

  if (n > 0 && n < 13) goto last_literals;  // too small to form matches

  {
    // hash table of positions (offsets from src), 0 = empty sentinel via
    // first-position ambiguity handled by verifying the match bytes
    static thread_local uint32_t table[1 << kHashBits];
    std::memset(table, 0, sizeof(table));

    while (ip < mflimit) {
      uint32_t h = hash4(read32(ip));
      const uint8_t* match = src + table[h];
      table[h] = (uint32_t)(ip - src);
      if (match >= ip || ip - match > 65535 ||
          read32(match) != read32(ip)) {
        ++ip;
        continue;
      }
      // extend match forward
      const uint8_t* mp = match + kMinMatch;
      const uint8_t* cp = ip + kMinMatch;
      while (cp < matchlimit && *cp == *mp) { ++cp; ++mp; }
      long mlen = cp - ip - kMinMatch;
      long llen = ip - anchor;
      // emit token
      uint8_t* token = op;
      if (op + 1 + llen + llen / 255 + 8 > oend) return -1;
      ++op;
      if (llen >= 15) {
        *token = 15 << 4;
        long rest = llen - 15;
        while (rest >= 255) { *op++ = 255; rest -= 255; }
        *op++ = (uint8_t)rest;
      } else {
        *token = (uint8_t)(llen << 4);
      }
      std::memcpy(op, anchor, llen);
      op += llen;
      // offset
      uint16_t off = (uint16_t)(ip - match);
      *op++ = (uint8_t)(off & 0xff);
      *op++ = (uint8_t)(off >> 8);
      // match length
      if (mlen >= 15) {
        *token |= 15;
        long rest = mlen - 15;
        while (rest >= 255) {
          if (op >= oend) return -1;
          *op++ = 255;
          rest -= 255;
        }
        if (op >= oend) return -1;
        *op++ = (uint8_t)rest;
      } else {
        *token |= (uint8_t)mlen;
      }
      ip = cp;
      anchor = ip;
      if (ip < mflimit) table[hash4(read32(ip - 2))] = (uint32_t)(ip - 2 - src);
    }
  }

last_literals: {
    long llen = iend - anchor;
    if (op + 1 + llen + llen / 255 > oend) return -1;
    uint8_t* token = op++;
    if (llen >= 15) {
      *token = 15 << 4;
      long rest = llen - 15;
      while (rest >= 255) { *op++ = 255; rest -= 255; }
      *op++ = (uint8_t)rest;
    } else {
      *token = (uint8_t)(llen << 4);
    }
    std::memcpy(op, anchor, llen);
    op += llen;
  }
  return op - dst;
}

// Returns decompressed size, or -1 on malformed/overflow input.
long srt_lz4_decompress(const uint8_t* src, long n, uint8_t* dst,
                        long dst_cap) {
  const uint8_t* ip = src;
  const uint8_t* const iend = src + n;
  uint8_t* op = dst;
  uint8_t* const oend = dst + dst_cap;

  while (ip < iend) {
    uint8_t token = *ip++;
    // literals
    long llen = token >> 4;
    if (llen == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        llen += b;
      } while (b == 255);
    }
    if (ip + llen > iend || op + llen > oend) return -1;
    std::memcpy(op, ip, llen);
    ip += llen;
    op += llen;
    if (ip >= iend) break;  // last sequence has no match part
    // offset
    if (ip + 2 > iend) return -1;
    uint16_t off = (uint16_t)(ip[0] | (ip[1] << 8));
    ip += 2;
    if (off == 0 || op - dst < off) return -1;
    // match length
    long mlen = (token & 15) + kMinMatch;
    if ((token & 15) == 15) {
      uint8_t b;
      do {
        if (ip >= iend) return -1;
        b = *ip++;
        mlen += b;
      } while (b == 255);
    }
    if (op + mlen > oend) return -1;
    const uint8_t* mp = op - off;
    if (off >= 8) {
      // non-overlapping enough for memcpy chunks
      long rest = mlen;
      while (rest >= 8) { std::memcpy(op, mp, 8); op += 8; mp += 8; rest -= 8; }
      while (rest--) *op++ = *mp++;
    } else {
      while (mlen--) *op++ = *mp++;  // overlapping copy, byte-wise
    }
  }
  return op - dst;
}

// ---------------------------------------------------------------------------
// Validity bitmap pack/unpack (bool bytes <-> LSB-first bits)
// ---------------------------------------------------------------------------

long srt_pack_bits(const uint8_t* bools, long n, uint8_t* out) {
  long nbytes = (n + 7) / 8;
  std::memset(out, 0, nbytes);
  for (long i = 0; i < n; ++i) {
    if (bools[i]) out[i >> 3] |= (uint8_t)(1u << (i & 7));
  }
  return nbytes;
}

long srt_unpack_bits(const uint8_t* bits, long n, uint8_t* bools) {
  for (long i = 0; i < n; ++i) {
    bools[i] = (bits[i >> 3] >> (i & 7)) & 1;
  }
  return n;
}

// ---------------------------------------------------------------------------
// CRC32C (Castagnoli, table-driven)
// ---------------------------------------------------------------------------

static uint32_t crc_table[256];
static bool crc_init_done = false;

static void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k)
      c = (c & 1) ? (0x82F63B78u ^ (c >> 1)) : (c >> 1);
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t srt_crc32c(const uint8_t* data, long n, uint32_t seed) {
  if (!crc_init_done) crc_init();
  uint32_t c = seed ^ 0xFFFFFFFFu;
  for (long i = 0; i < n; ++i)
    c = crc_table[(c ^ data[i]) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

}  // extern "C"
