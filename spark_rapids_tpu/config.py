"""Typed configuration system.

Mirrors the reference's RapidsConf builder DSL and registry
(sql-plugin/.../RapidsConf.scala:171-260: ``conf("key").doc(...)
.booleanConf.createWithDefault``), including:

- typed entries with docs and defaults, byte-size parsing,
- a global registry used to generate documentation (RapidsConf.help,
  RapidsConf.scala:133-168 -> docs/configs.md),
- auto-generated per-operator enable flags added by the planning layer
  (ReplacementRule.confKey, GpuOverrides.scala:129-137) checked during
  tagging, with incompat / disabled-by-default levels
  (GpuOverrides.scala:84-97).

Keys use the ``rapids.tpu.*`` namespace (the reference uses
``spark.rapids.*``).
"""
from __future__ import annotations

import os
import re
import threading
from spark_rapids_tpu.utils import lockorder
from typing import Any, Callable, Dict, Generic, List, Optional, TypeVar

T = TypeVar("T")

_REGISTRY: "Dict[str, ConfEntry]" = {}
_REGISTRY_LOCK = lockorder.make_lock("config.registry")

_BYTE_SUFFIXES = {
    "b": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30, "t": 1 << 40,
}


def parse_bytes(v) -> int:
    """Parse '512m', '2g', '1024' into bytes (ConfHelper.byteFromString
    analogue, RapidsConf.scala)."""
    if isinstance(v, (int, float)):
        return int(v)
    m = re.fullmatch(r"\s*(\d+(?:\.\d+)?)\s*([bkmgt]?)b?\s*", str(v).lower())
    if not m:
        raise ValueError(f"cannot parse byte size: {v!r}")
    num, suf = float(m.group(1)), m.group(2) or "b"
    return int(num * _BYTE_SUFFIXES[suf])


def _parse_bool(v) -> bool:
    if isinstance(v, bool):
        return v
    return str(v).strip().lower() in ("true", "1", "yes", "on")


class ConfEntry(Generic[T]):
    def __init__(self, key: str, default: T, doc: str,
                 converter: Callable[[Any], T], internal: bool = False):
        self.key = key
        self.default = default
        self.doc = doc
        self.converter = converter
        self.internal = internal

    def get(self, conf: "RapidsConf") -> T:
        return conf.get(self)

    def help(self) -> str:
        return f"{self.key}|{self.doc}|{self.default}"


class _Builder:
    """``conf("key").doc(...).boolean_conf.create_with_default(x)``"""

    def __init__(self, key: str):
        self._key = key
        self._doc = ""
        self._internal = False
        self._converter: Callable = lambda v: v

    def doc(self, d: str) -> "_Builder":
        self._doc = d
        return self

    def internal(self) -> "_Builder":
        self._internal = True
        return self

    @property
    def boolean_conf(self) -> "_Builder":
        self._converter = _parse_bool
        return self

    @property
    def int_conf(self) -> "_Builder":
        self._converter = int
        return self

    @property
    def double_conf(self) -> "_Builder":
        self._converter = float
        return self

    @property
    def string_conf(self) -> "_Builder":
        self._converter = str
        return self

    @property
    def bytes_conf(self) -> "_Builder":
        self._converter = parse_bytes
        return self

    def create_with_default(self, default) -> ConfEntry:
        entry = ConfEntry(self._key, default, self._doc, self._converter,
                          self._internal)
        with _REGISTRY_LOCK:
            _REGISTRY[self._key] = entry
        return entry


def conf(key: str) -> _Builder:
    return _Builder(key)


def registered_entries() -> List[ConfEntry]:
    with _REGISTRY_LOCK:
        return list(_REGISTRY.values())


#: keys present when plan/overrides finished importing — the exact set
#: a fresh docs-generation process sees. Per-op flags registered later
#: (overrides.NodeMeta, one per plan-node class at apply time) are an
#: open set no static docs file can contain.
_DOCS_SNAPSHOT: Optional[frozenset] = None


def snapshot_docs_registry() -> frozenset:
    """Freeze (once) and return the import-time registry key set."""
    global _DOCS_SNAPSHOT
    if _DOCS_SNAPSHOT is None:
        with _REGISTRY_LOCK:
            _DOCS_SNAPSHOT = frozenset(_REGISTRY)
    return _DOCS_SNAPSHOT


def register_op_flag(kind: str, name: str, desc: str,
                     default_enabled: bool = True,
                     incompat: Optional[str] = None) -> ConfEntry:
    """Auto-generated per-op enable flag: rapids.tpu.sql.<kind>.<Name>
    (ReplacementRule.confKey analogue, GpuOverrides.scala:129-137)."""
    key = f"rapids.tpu.sql.{kind}.{name}"
    with _REGISTRY_LOCK:
        if key in _REGISTRY:
            return _REGISTRY[key]
    doc = desc + (f" (incompatible: {incompat})" if incompat else "")
    return conf(key).doc(doc).boolean_conf.create_with_default(
        default_enabled and incompat is None)


# ---------------------------------------------------------------------------
# Core entries (subset of RapidsConf.scala:271-707 that applies TPU-side).
# ---------------------------------------------------------------------------

SQL_ENABLED = conf("rapids.tpu.sql.enabled").doc(
    "Enable (true) or disable (false) TPU acceleration of queries."
).boolean_conf.create_with_default(True)

EXPLAIN = conf("rapids.tpu.sql.explain").doc(
    "Explain why parts of a query were or were not placed on the TPU: "
    "NONE, ALL, NOT_ON_TPU."
).string_conf.create_with_default("NONE")

INCOMPATIBLE_OPS = conf("rapids.tpu.sql.incompatibleOps.enabled").doc(
    "Enable operators that produce results that differ in corner cases "
    "from Spark CPU semantics."
).boolean_conf.create_with_default(False)

HAS_NANS = conf("rapids.tpu.sql.hasNans").doc(
    "Assume floating point data may contain NaNs (affects agg/join planning)."
).boolean_conf.create_with_default(True)

VARIABLE_FLOAT_AGG = conf("rapids.tpu.sql.variableFloatAgg.enabled").doc(
    "Allow float aggregations whose result may vary with evaluation order."
).boolean_conf.create_with_default(False)

CONCURRENT_TPU_TASKS = conf("rapids.tpu.sql.concurrentTpuTasks").doc(
    "Number of tasks that can execute concurrently per TPU chip "
    "(admission control; GpuSemaphore analogue, RapidsConf.scala:340)."
).int_conf.create_with_default(2)

TASK_THREADS = conf("rapids.tpu.sql.taskThreads").doc(
    "Worker threads driving partitions concurrently within this process "
    "(the role of Spark's executor task slots). More threads than "
    "concurrentTpuTasks lets host I/O (parquet decode, spill) overlap "
    "device compute while the semaphore bounds device entry "
    "(GpuSemaphore.scala:27-161 oversubscription strategy)."
).int_conf.create_with_default(4)

BATCH_SIZE_BYTES = conf("rapids.tpu.sql.batchSizeBytes").doc(
    "Target coalesced batch size in bytes (RapidsConf.scala:353-358; the "
    "reference defaults to 2GiB, we default lower: XLA prefers bounded "
    "shapes and HBM/chip is smaller than a V100's 32GB)."
).bytes_conf.create_with_default(512 << 20)

MAX_READER_BATCH_SIZE_ROWS = conf(
    "rapids.tpu.sql.reader.batchSizeRows").doc(
    "Soft cap on rows per reader batch."
).int_conf.create_with_default(1 << 21)

MAX_READER_BATCH_SIZE_BYTES = conf(
    "rapids.tpu.sql.reader.batchSizeBytes").doc(
    "Soft cap on bytes per reader batch."
).bytes_conf.create_with_default(256 << 20)

SCAN_PREFETCH_DEPTH = conf("rapids.tpu.io.scan.prefetch.depth").doc(
    "Bounded depth of the async scan pipeline's packed-slice queue "
    "(io/scanpipe.py): an IO thread reads and packs up to this many "
    "slices ahead of the device upload, so decode and H2D transfer "
    "hide behind downstream compute. 0 disables the pipeline entirely "
    "(fully synchronous read->pack->upload on the caller thread — the "
    "byte-identity reference path the ingest fence compares against). "
    "Queued packed slices charge the service admission budget as "
    "backpressure."
).int_conf.create_with_default(2)

SCAN_PRUNING_ENABLED = conf("rapids.tpu.io.scan.pruning.enabled").doc(
    "Prune row groups (parquet) / stripes (ORC) whose footer min/max "
    "statistics cannot match the pushed-down filters, BEFORE any data "
    "byte is read. Pruning is conservative: chunks without statistics "
    "are always kept, and the plan's FilterNode still applies exact "
    "semantics. Disable to measure pruning effectiveness "
    "(scripts/ingest_check.py does)."
).boolean_conf.create_with_default(True)

SCAN_LANDING_SPILLABLE = conf(
    "rapids.tpu.io.scan.landing.spillable.enabled").doc(
    "Land scan results as snapshot-versioned SpillableBatches in the "
    "scan cache (keyed on per-file (mtime_ns, size)): a re-scan of "
    "unchanged files hits warm device/host/disk tiers instead of the "
    "filesystem. Cached bytes charge the service admission budget and "
    "spill under the scan-cache priority before any query's working "
    "batches."
).boolean_conf.create_with_default(False)

SCAN_MAX_PARTITION_BYTES = conf("rapids.tpu.io.scan.maxPartitionBytes").doc(
    "Target on-disk bytes per scan partition (Spark's "
    "sql.files.maxPartitionBytes): one file larger than this splits on "
    "parquet row-group boundaries so a single giant file parallelizes "
    "like many small ones, and small files pack together up to it."
).bytes_conf.create_with_default(128 << 20)

HBM_POOL_FRACTION = conf("rapids.tpu.memory.hbm.allocFraction").doc(
    "Fraction of HBM the framework may fill before spilling "
    "(RMM pool fraction analogue, RapidsConf.scala)."
).double_conf.create_with_default(0.9)

HBM_RESERVE = conf("rapids.tpu.memory.hbm.reserve").doc(
    "Bytes of HBM reserved for XLA scratch/fusion temporaries."
).bytes_conf.create_with_default(1 << 30)

HOST_SPILL_STORAGE_SIZE = conf("rapids.tpu.memory.host.spillStorageSize").doc(
    "Bounded host-memory spill target before falling to disk "
    "(RapidsConf.scala:319)."
).bytes_conf.create_with_default(8 << 30)

SPILL_DIR = conf("rapids.tpu.memory.spillDir").doc(
    "Directory for disk-tier spill files."
).string_conf.create_with_default("/tmp/rapids_tpu_spill")

DEVICE_BUDGET = conf("rapids.tpu.memory.device.budget").doc(
    "Explicit device-memory budget for the spill catalog in bytes; 0 "
    "(the default) derives the budget from reported HBM "
    "(allocFraction * HBM - reserve). Setting a deliberately tiny "
    "value forces the out-of-core execution paths end to end — the "
    "chaos regression fence runs real queries with a budget a quarter "
    "of their working set."
).bytes_conf.create_with_default(0)

SPILL_ASYNC_WRITE = conf("rapids.tpu.memory.spill.asyncWrite.enabled").doc(
    "Write host->disk spills on a double-buffered background writer "
    "(compressed serialization overlaps compute; a spill storm "
    "backpressures the evicting thread at the buffer depth) instead "
    "of inline on the evicting thread."
).boolean_conf.create_with_default(True)

RETRY_MAX_SPILL_RETRIES = conf("rapids.tpu.memory.retry.maxSpillRetries").doc(
    "Spill rungs of the OOM retry ladder before splitting/giving up: "
    "rung 1 spills tracked device buffers to half, rung 2 spills "
    "everything (DeviceMemoryEventHandler escalation analogue)."
).int_conf.create_with_default(2)

RETRY_MAX_SPLIT_DEPTH = conf("rapids.tpu.memory.retry.maxSplitDepth").doc(
    "Maximum recursive input halvings after the spill rungs are "
    "exhausted at a splittable call site (2^depth sub-batches at the "
    "bound); past it the computation fails with SplitAndRetryOOM."
).int_conf.create_with_default(8)

FAULT_INJECTION_ENABLED = conf(
    "rapids.tpu.memory.faultInjection.enabled").doc(
    "Arm the deterministic device-OOM injector: guarded device "
    "computations raise synthetic RESOURCE_EXHAUSTED per the "
    "faultInjection.* trigger config, exercising the full retry "
    "ladder (spill, spill-all, split, give-up) on any backend — "
    "including CPU-only CI. Never enable in production."
).boolean_conf.create_with_default(False)

FAULT_INJECTION_AT_CALL = conf(
    "rapids.tpu.memory.faultInjection.atCall").doc(
    "Fail the Nth eligible guarded device call (counted from 1 across "
    "the process, after the sites filter); 0 disables the "
    "deterministic trigger."
).int_conf.create_with_default(0)

FAULT_INJECTION_SITES = conf(
    "rapids.tpu.memory.faultInjection.sites").doc(
    "Comma-separated call-site tags eligible for injection (prefix "
    "match: 'join' hits join.probe and join.build.concat). Empty = "
    "every guarded site."
).string_conf.create_with_default("")

FAULT_INJECTION_PROBABILITY = conf(
    "rapids.tpu.memory.faultInjection.probability").doc(
    "Per-guarded-call injection probability for seeded chaos sweeps "
    "(0.0 disables). Reproducible via faultInjection.seed."
).double_conf.create_with_default(0.0)

FAULT_INJECTION_SEED = conf(
    "rapids.tpu.memory.faultInjection.seed").doc(
    "RNG seed for probabilistic injection — the same seed replays the "
    "same failure sequence."
).int_conf.create_with_default(0)

FAULT_INJECTION_CONSECUTIVE = conf(
    "rapids.tpu.memory.faultInjection.consecutive").doc(
    "Guarded calls failed in a row per firing point. Values above "
    "maxSpillRetries push the ladder past spill-and-retry into "
    "split-and-retry, which is why the default is 3 (= the default 2 "
    "spill rungs + 1)."
).int_conf.create_with_default(3)

FAULT_INJECTION_MAX = conf(
    "rapids.tpu.memory.faultInjection.maxInjections").doc(
    "Total injections cap (0 = unlimited) so probabilistic chaos runs "
    "terminate."
).int_conf.create_with_default(0)

MEMORY_DEBUG = conf("rapids.tpu.memory.debug").doc(
    "Log every allocation/free (RMM debug-mode analogue, RapidsConf.scala:277)."
).boolean_conf.create_with_default(False)

DEBUG_LOCK_ORDER = conf("rapids.tpu.debug.lockOrder.enabled").doc(
    "Wrap every framework lock in a tracking proxy that asserts the "
    "declared hierarchy (utils/lockorder.py) on each acquire. Read at "
    "lock-CREATION time via its env spelling "
    "(RAPIDS_TPU_DEBUG_LOCKORDER_ENABLED), so it must be set before "
    "the framework imports; tests/conftest.py enables it for every "
    "tier-1 run. Static half of the same check: tpulint TPU301 "
    "(docs/static-analysis.md)."
).boolean_conf.create_with_default(False)

SHUFFLE_PARTITIONS = conf("rapids.tpu.sql.shuffle.partitions").doc(
    "Number of shuffle partitions; 0 (the default) auto-sizes to "
    "2 x attached device count. Spark's 200-partition default exists to "
    "feed many cheap CPU tasks; here every partition costs device "
    "dispatches (and, behind a remote attachment, ~100 ms round trips "
    "each), so fewer, larger partitions win until data exceeds HBM."
).int_conf.create_with_default(0)


def resolve_shuffle_partitions(conf_obj) -> int:
    """SHUFFLE_PARTITIONS with 0 = auto (2 x device count)."""
    n = conf_obj.get(SHUFFLE_PARTITIONS)
    if n and n > 0:
        return n
    try:
        import jax

        return max(2 * len(jax.devices()), 2)
    except Exception:  # pragma: no cover - no backend at plan time
        return 8

MESH_ENABLED = conf("rapids.tpu.mesh.enabled").doc(
    "Lower planned queries onto the device mesh: hash exchanges become "
    "in-program lax.all_to_all collectives and aggregation/join execs run "
    "per-chip kernels inside one shard_map program (the planner-reachable "
    "multi-chip path; GpuShuffleExchangeExec.scala:146-248 re-imagined as "
    "ICI collectives)."
).boolean_conf.create_with_default(False)

MESH_DEVICES = conf("rapids.tpu.mesh.devices").doc(
    "Device count for the mesh data axis; 0 = all visible devices. A "
    "request larger than the attached backend clamps to what exists and "
    "records a mesh-fallback reason (parallel/mesh.mesh_fallback_snapshot, "
    "surfaced in runner telemetry next to shuffle_fallbacks)."
).int_conf.create_with_default(0)

MESH_MODEL_DEVICES = conf("rapids.tpu.mesh.modelDevices").doc(
    "Width of the mesh's model axis: the session mesh becomes a 2-D "
    "data x model layout (devices = data * model) with shuffles riding "
    "the data axis and the model axis reserved for tensor-parallel "
    "operators. 1 (default) keeps the 1-D data-only mesh. Values that "
    "leave fewer than 2 data devices disable the mesh with a recorded "
    "reason."
).int_conf.create_with_default(1)

MESH_HOSTS = conf("rapids.tpu.mesh.hosts").doc(
    "Host (process) count in the logical multi-host topology: each host "
    "owns one mesh slice and runs ONE SPMD program over its own devices "
    "with in-program ICI collectives; the DCN seam between hosts is "
    "carried by the TCP exchange path (parallel/mesh.HostTopology). "
    "0 = infer: 1 + rapids.tpu.cluster.workers when cluster mode is "
    "enabled, else 1."
).int_conf.create_with_default(0)

FUSION_ENABLED = conf("rapids.tpu.sql.fusion.enabled").doc(
    "Fuse filter/project/broadcast-join-probe chains into ONE compiled "
    "XLA program per batch (and feed the surviving-row mask straight "
    "into the groupby kernel when the chain ends at an aggregate). "
    "Each fused step removes its own dispatch round trip; behind a "
    "remote device attachment a dispatch costs ~100 ms, so a "
    "scan->filter->join->agg pipeline collapses from ~8 dispatches per "
    "batch to 2. Joins whose broadcast build side has duplicate key "
    "hashes fall back to the general expansion kernel automatically."
).boolean_conf.create_with_default(True)

FUSION_SORT_TAIL = conf("rapids.tpu.sql.fusion.sortTail").doc(
    "Absorb a global ORDER BY into the post-aggregate chain program "
    "(SortStep): final projection + HAVING + project + variadic sort "
    "run as ONE dispatch over the aggregate's raw partials, and the "
    "aggregate skips its own final-project dispatch and rebucket host "
    "sync. Disable if the fused sort module misbehaves on a backend "
    "(the unfused SortExec path remains fully supported)."
).boolean_conf.create_with_default(True)

FUSION_DEFER_DECODE = conf("rapids.tpu.sql.fusion.deferScanDecode").doc(
    "Hand transfer-packed scan uploads to the consuming fused chain "
    "UNDECODED; the chain inlines the decode as its first traced steps "
    "so the scan stage pays zero decode dispatch. Disable to restore "
    "the standalone per-batch decode program."
).boolean_conf.create_with_default(True)

COMPILE_CACHE_DIR = conf("rapids.tpu.sql.compileCacheDir").doc(
    "Directory for the persistent compile cache (utils/progcache): XLA "
    "executables of jitted programs — including the stable-named fused "
    "chain programs — persist across processes, so a repeated plan "
    "over the same schema skips compilation AND the warm-up dispatch "
    "it would cost. Empty = in-process program sharing only. Behind "
    "the remote-compile tunnel a cold compile of a big fused kernel "
    "costs minutes (BASELINE.md), so long-lived deployments should "
    "always set this."
).string_conf.create_with_default("")

SCAN_PACK_TRANSFERS = conf("rapids.tpu.scan.packTransfers").doc(
    "Pack scan uploads before they cross the host->device link: string "
    "codes ship at the dictionary's width, integers offset-narrow to "
    "their footer-stat span, repeated-value doubles ship as codes plus "
    "a value table, validity masks bit-pack 8x; one jitted program per "
    "batch decodes on device, bit-exactly (verified host-side per "
    "column before each encoding is chosen). The TPU-native analogue "
    "of the reference's nvcomp-compressed transfers "
    "(GpuCompressedColumnVector) — a TPU cannot LZ4-decode, but it can "
    "widen and gather. Matters whenever the link is thin: the axon "
    "tunnel measures ~20-45 MB/s, so TPC-H q1 @ sf 1 drops from ~264 "
    "to ~70 uploaded MB. Applies to scans of >= 65536 rows."
).boolean_conf.create_with_default(True)

FUSION_DENSE_PROBE_MAX_SPAN = conf(
    "rapids.tpu.sql.fusion.denseProbe.maxSpan").doc(
    "Ceiling on the build-key value span (table slots, 4 bytes each) "
    "for the fused chain's dense inverse-table join probe: "
    "table[key - lo] = build row, ONE gather per join. Spans above it "
    "use the int64 hash + searchsorted probe (a ~17-step binary-search "
    "gather loop). Single integral keys only; 0 disables."
).int_conf.create_with_default(1 << 22)

FUSION_IN_PROGRAM_BUILD = conf(
    "rapids.tpu.sql.fusion.inProgramBuild.enabled").doc(
    "Fold the broadcast-join build-side preparation (hash sort, "
    "duplicate probe, dense inverse table) INTO the consuming fused "
    "chain program's first launch instead of running it as a separate "
    "_prep_build dispatch plus a flag-fetch device_get. The chain's "
    "first batch runs a build-inlined program variant that also emits "
    "the prepared build arrays; later batches reuse them through the "
    "probe-only variant, so stage0 sheds two dispatches. The duplicate "
    "flag rides back with the (asynchronously fetched) speculative "
    "output — a duplicate-keyed build discards that output and falls "
    "back to the unfused join, exactly like the host path. Disable to "
    "restore the standalone host-side prepare_builds launch."
).boolean_conf.create_with_default(True)

NATIVE_KERNELS_ENABLED = conf("rapids.tpu.native.kernels.enabled").doc(
    "Master switch for the native Pallas kernel layer "
    "(spark_rapids_tpu/native/kernels): hand-written device kernels "
    "replacing the jnp graphs where XLA's lowering is the measured "
    "floor — the open-addressing hash-join probe, the prefix-scan "
    "partition/segmented sort, and the dictionary-string predicate "
    "kernels. On CPU backends every kernel runs through Pallas "
    "interpret mode, so CI exercises the exact kernel code that "
    "compiles for TPU. Off by default: the jnp implementations remain "
    "the reference semantics and every kernel is differentially "
    "fenced against them."
).boolean_conf.create_with_default(False)

NATIVE_KERNELS_JOIN = conf("rapids.tpu.native.kernels.join").doc(
    "Route equi-join probes through the native open-addressing hash "
    "table kernel: the build side becomes a device-resident bucketed "
    "table (built once, probed across every stream batch) and the "
    "probe is one gather-scan kernel — replacing both the dense "
    "inverse-table and the hash+searchsorted probe dichotomy. "
    "Requires rapids.tpu.native.kernels.enabled."
).boolean_conf.create_with_default(True)

NATIVE_KERNELS_SORT = conf("rapids.tpu.native.kernels.sort").doc(
    "Route row compaction and multi-column (segmented) sorts through "
    "the native prefix-scan kernels: live-mask compaction becomes one "
    "O(n) scan+scatter instead of a stable argsort, and ORDER BY "
    "permutations run as binary-radix passes over order keys instead "
    "of the variadic sort network whose payload carry blows up past "
    "6 lanes. Requires rapids.tpu.native.kernels.enabled."
).boolean_conf.create_with_default(True)

NATIVE_KERNELS_STRINGS = conf("rapids.tpu.native.kernels.strings").doc(
    "Evaluate dictionary-string predicates (LIKE / contains / "
    "startswith / endswith / substring) with the native char-table "
    "kernels: the dictionary's code+offset char matrix is scanned on "
    "device instead of transforming every dictionary entry through a "
    "host Python loop. Patterns outside the kernel's LIKE subset "
    "(custom ESCAPE) fall back to the host path automatically. "
    "Requires rapids.tpu.native.kernels.enabled."
).boolean_conf.create_with_default(True)

GROUPBY_SINGLE_PASS = conf(
    "rapids.tpu.sql.groupby.singlePass.enabled").doc(
    "Emit wide group-bys (more than 6 aggregate columns) as ONE "
    "segmented-aggregation launch instead of the chunked two-dispatch "
    "loop. The chunk loop exists as a workaround for a libtpu v5e "
    "remote-compile segfault on >= 7-agg fused sort modules at "
    "capacity >= 32768; on backends without that defect a single pass "
    "halves the group-by's dispatch cost. Disable on v5e remote "
    "attachments if wide-aggregate compiles crash. The "
    "compact-wide pre-pass (_COMPACT_WIDE_MIN_CAP) applies to both "
    "paths unchanged."
).boolean_conf.create_with_default(True)

CLUSTER_ENABLED = conf("rapids.tpu.cluster.enabled").doc(
    "Execute shuffle exchanges through the multi-process cluster runtime: "
    "map tasks write partitioned output into per-executor shuffle catalogs "
    "(spillable, priority 0) and reduce tasks read through the transport "
    "over real sockets — the reference's shuffle manager wired into query "
    "execution (RapidsShuffleInternalManager.scala:200-305, "
    "RapidsCachingReader.scala:59-145)."
).boolean_conf.create_with_default(False)

CLUSTER_EXECUTORS = conf("rapids.tpu.cluster.executors").doc(
    "In-process executors in the cluster runtime (each owns a spill "
    "catalog + TCP-served shuffle server)."
).int_conf.create_with_default(2)

CLUSTER_WORKERS = conf("rapids.tpu.cluster.workers").doc(
    "Remote worker processes: each is a separate OS process hosting an "
    "executor (shuffle/remote_worker.py) that RUNS map tasks and serves "
    "their output over TCP — the separate-executor-JVM model."
).int_conf.create_with_default(1)

CLUSTER_MAX_STAGE_RETRIES = conf(
    "rapids.tpu.cluster.maxStageRetries").doc(
    "Lineage-recovery budget per reduce read: each ShuffleFetchFailedError "
    "invalidates the dead executor's map outputs, re-runs the lost map "
    "tasks on survivors/respawned workers, and re-reads — at most this "
    "many times (with exponential backoff, cluster.retryBackoffMs base) "
    "before the ORIGINAL fetch failure re-raises chained from its "
    "transport cause (Spark's spark.stage.maxConsecutiveAttempts role)."
).int_conf.create_with_default(3)

CLUSTER_TASK_TIMEOUT_SEC = conf(
    "rapids.tpu.cluster.taskTimeoutSec").doc(
    "Liveness ceiling for one map task on a remote worker: a worker that "
    "has not replied within this window is presumed hung, is killed, and "
    "the task re-places (locally or on a respawned worker). Without it a "
    "wedged worker blocks the driver's reader forever."
).double_conf.create_with_default(120.0)

CLUSTER_BLACKLIST_AFTER = conf(
    "rapids.tpu.cluster.blacklistAfterFailures").doc(
    "Consecutive failures (submit-time death, task-timeout kill, "
    "fetch-failure blame) after which a worker SLOT is blacklisted: it "
    "is no longer respawned and placement stops targeting it, so "
    "retries quit landing on a flapping host. A successful task resets "
    "the slot's count. 0 disables blacklisting."
).int_conf.create_with_default(3)

CLUSTER_RESPAWN_WORKERS = conf(
    "rapids.tpu.cluster.respawnWorkers").doc(
    "Respawn dead worker processes during fetch-failure recovery (a "
    "fresh process per generation, re-registered with every peer). "
    "Disable to recover onto surviving executors only."
).boolean_conf.create_with_default(True)

CLUSTER_RETRY_BACKOFF_MS = conf(
    "rapids.tpu.cluster.retryBackoffMs").doc(
    "Base backoff before a stage retry re-runs lost map tasks; doubles "
    "per attempt (attempt k sleeps base * 2^k). Small by default: the "
    "local fault injector needs no settling time, real deployments "
    "should give a flapping peer a few seconds."
).int_conf.create_with_default(50)

CLUSTER_AUTOSCALE_ENABLED = conf(
    "rapids.tpu.cluster.autoscale.enabled").doc(
    "Let the service's autoscaler add worker hosts while queries queue: "
    "each admission pump observes queue depth, and sustained pressure "
    "above autoscale.queueDepthHigh invokes ClusterRuntime.add_host — "
    "the SAME elastic-membership seam operators and the recovery ladder "
    "use, so a scale-up is a recovery event, not a special deployment "
    "path. Requires rapids.tpu.cluster.enabled."
).boolean_conf.create_with_default(False)

CLUSTER_AUTOSCALE_MAX_WORKERS = conf(
    "rapids.tpu.cluster.autoscale.maxWorkers").doc(
    "Ceiling on live worker hosts the autoscaler may grow to (counting "
    "distinct live slots); scale-ups stop at this size."
).int_conf.create_with_default(4)

CLUSTER_AUTOSCALE_QUEUE_HIGH = conf(
    "rapids.tpu.cluster.autoscale.queueDepthHigh").doc(
    "Admission queue depth at or above which the autoscaler requests a "
    "new host on the next pump."
).int_conf.create_with_default(8)

CLUSTER_AUTOSCALE_COOLDOWN_SEC = conf(
    "rapids.tpu.cluster.autoscale.cooldownSec").doc(
    "Minimum seconds between autoscaler scale events (up or down), so "
    "one burst does not spawn a host per queued query before the first "
    "new host drains anything — and a scale-down cannot immediately "
    "chase a scale-up."
).double_conf.create_with_default(30.0)

CLUSTER_AUTOSCALE_QUEUE_LOW = conf(
    "rapids.tpu.cluster.autoscale.queueDepthLow").doc(
    "Scale-DOWN watermark: with the cluster idle — admission queue "
    "depth at or below this value AND zero inflight queries — "
    "sustained for autoscale.idleSec (and past the shared cooldown), "
    "the autoscaler retires one worker host through "
    "ClusterRuntime.remove_host: the SAME planned-decommission seam "
    "operators use (slot generations killed, its map outputs "
    "invalidated, lost maps re-run through the lineage ladder), never "
    "below autoscale.minWorkers. -1 (default) disables scale-down."
).int_conf.create_with_default(-1)

CLUSTER_AUTOSCALE_MIN_WORKERS = conf(
    "rapids.tpu.cluster.autoscale.minWorkers").doc(
    "Floor on live worker hosts the autoscaler may shrink to "
    "(counting distinct live slots); scale-downs stop at this size."
).int_conf.create_with_default(1)

CLUSTER_AUTOSCALE_IDLE_SEC = conf(
    "rapids.tpu.cluster.autoscale.idleSec").doc(
    "Seconds the idle condition (queue depth <= queueDepthLow, zero "
    "inflight) must hold continuously before a scale-down fires — a "
    "gap between dashboard refreshes must not decommission a host "
    "the next refresh needs."
).double_conf.create_with_default(60.0)

SHUFFLE_FI_ENABLED = conf(
    "rapids.tpu.shuffle.faultInjection.enabled").doc(
    "Arm the deterministic transport/worker fault injector "
    "(shuffle/fault_injection.py): connection drops, truncated chunk "
    "frames, and worker kills fire at exact request/task ordinals so "
    "the whole lineage-recovery ladder (fetch failure -> invalidate -> "
    "re-run -> re-read) runs deterministically on CPU CI "
    "(scripts/dist_chaos_check.py). Never enable in production."
).boolean_conf.create_with_default(False)

SHUFFLE_FI_DROP_AT = conf(
    "rapids.tpu.shuffle.faultInjection.dropConnectionAtRequest").doc(
    "Drop the client socket (and fail the round trip with a retryable "
    "TransportError) on the Nth transport request, counted from 1 "
    "across the process; 0 disables. Exercises the connection-level "
    "reconnect+backoff path (shuffle/tcp.py _roundtrip_retrying)."
).int_conf.create_with_default(0)

SHUFFLE_FI_TRUNCATE_AT = conf(
    "rapids.tpu.shuffle.faultInjection.truncateFrameAtRequest").doc(
    "Truncate the payload of the Nth chunk request (counted from 1); "
    "0 disables. The short chunk is detected ABOVE the connection retry "
    "loop (transport.py _fetch_payload), so it deterministically "
    "escalates to a fetch failure and a stage retry."
).int_conf.create_with_default(0)

SHUFFLE_FI_KILL_BEFORE_TASK = conf(
    "rapids.tpu.shuffle.faultInjection.killWorkerBeforeTask").doc(
    "SIGKILL the target worker process immediately before the Nth "
    "worker task submission (counted from 1); 0 disables. Earlier "
    "tasks' registered outputs then produce reduce-side fetch failures "
    "— the worker-death half of the recovery ladder."
).int_conf.create_with_default(0)

SHUFFLE_FI_PROBABILITY = conf(
    "rapids.tpu.shuffle.faultInjection.probability").doc(
    "Per-transport-request connection-drop probability for seeded "
    "chaos sweeps (0.0 disables). Reproducible via faultInjection.seed."
).double_conf.create_with_default(0.0)

SHUFFLE_FI_SEED = conf(
    "rapids.tpu.shuffle.faultInjection.seed").doc(
    "RNG seed for probabilistic transport faults — the same seed "
    "replays the same drop sequence."
).int_conf.create_with_default(0)

SHUFFLE_FI_CONSECUTIVE = conf(
    "rapids.tpu.shuffle.faultInjection.consecutive").doc(
    "Requests failed in a row per firing point (applies to drops and "
    "truncations). Values past the transport's transient-retry budget "
    "escalate a drop from a reconnect into a fetch failure; a huge "
    "value with truncateFrameAtRequest=1 makes EVERY chunk short — the "
    "budget-exhaustion fence."
).int_conf.create_with_default(1)

SHUFFLE_FI_MAX = conf(
    "rapids.tpu.shuffle.faultInjection.maxInjections").doc(
    "Total injections cap across all fault kinds (0 = unlimited) so "
    "probabilistic chaos runs terminate."
).int_conf.create_with_default(0)

SHUFFLE_FI_KILL_HOST_AT_STAGE = conf(
    "rapids.tpu.shuffle.faultInjection.killHostAtStage").doc(
    "SIGKILL one live worker HOST (preferring one that owns registered "
    "map output) at the Nth driver-side stage boundary — each shuffle "
    "map stage start and each exchange's first reduce read, counted "
    "from 1 across the process; 0 disables. Unlike "
    "killWorkerBeforeTask (which intercepts one submission), this kills "
    "the whole host out from under a running query: its earlier "
    "registered map outputs fail reduce-side fetches and the full "
    "elastic-membership ladder (invalidate, respawn {slot}~{gen}, "
    "re-run lost maps, re-read) runs deterministically on CPU CI "
    "(scripts/multihost_chaos_check.py)."
).int_conf.create_with_default(0)

SHUFFLE_FI_PARTITION_DCN_AT = conf(
    "rapids.tpu.shuffle.faultInjection.partitionDcnAtRequest").doc(
    "Partition the DCN seam starting at the Nth cross-host transport "
    "round trip (counted from 1); 0 disables. Each affected request "
    "fails like a downed inter-host link (socket dropped, retryable "
    "TransportError); combine with faultInjection.consecutive past the "
    "transport retry budget to escalate the partition into a fetch "
    "failure and a stage retry. Each distinct partition event bumps the "
    "dcn_partitions recovery counter."
).int_conf.create_with_default(0)

SHUFFLE_FI_CRASH_AT_FOLD = conf(
    "rapids.tpu.shuffle.faultInjection.crashAtFold").doc(
    "SIGKILL the CURRENT process at the start of the Nth standing-"
    "query fold (counted from 1 across the process; 0 disables) — "
    "after the delta's WAL record is durable, before the running "
    "state swaps. The hard-crash half of the streaming durability "
    "fence (scripts/stream_durability_check.py): a restarted service "
    "must recover the standing query from its latest checkpoint plus "
    "the WAL suffix, bit-exact, folding the interrupted delta exactly "
    "once."
).int_conf.create_with_default(0)

SHUFFLE_FI_TORN_CHECKPOINT_AT = conf(
    "rapids.tpu.shuffle.faultInjection.tornCheckpointAt").doc(
    "Tear the Nth streaming checkpoint commit (counted from 1; 0 "
    "disables): only the first half of the checkpoint bytes reach the "
    "final file name, modeling a crash mid-write that beat the atomic "
    "rename. Recovery must reject it on CRC (torn_rejected counter), "
    "fall back to an older checkpoint or — with "
    "faultInjection.consecutive large enough to tear EVERY checkpoint "
    "— to a full WAL-only refold, still bit-exact."
).int_conf.create_with_default(0)

SHUFFLE_FI_TRUNCATE_WAL_AT = conf(
    "rapids.tpu.shuffle.faultInjection.truncateWalAt").doc(
    "Write only half of the Nth WAL record's bytes (counted from 1; 0 "
    "disables), modeling a crash mid-append. Replay must tolerate the "
    "torn TAIL record — truncate it, count it in torn_rejected, and "
    "recover every record before it; mid-log corruption (valid "
    "records AFTER a bad CRC) is a loud WalCorruptionError instead, "
    "never silent data loss."
).int_conf.create_with_default(0)

SHUFFLE_IN_PROGRAM = conf("rapids.tpu.shuffle.inProgram.enabled").doc(
    "Fold mesh-internal shuffles into the compiled program: when the "
    "session mesh is active, hash-routed exchanges lower to in-program "
    "lax.all_to_all collectives inside the enclosing stage's shard_map "
    "program (scan-decode -> hash-partition -> all_to_all -> local "
    "join/aggregate/sort as ONE pjit launch), the SPMD analogue of the "
    "reference's UCX on-device shuffle (PAPER L7). Disable to force "
    "every exchange through the host/TCP block-store path even with a "
    "mesh attached; the planner records the fallback reason either way "
    "(parallel/spmd.fallback_snapshot, surfaced in run telemetry)."
).boolean_conf.create_with_default(True)

SHUFFLE_IN_PROGRAM_MIN_ROWS = conf(
    "rapids.tpu.shuffle.inProgram.minRows").doc(
    "Estimated-row floor for the in-program shuffle: below it the "
    "exchange stays on the host block-store path (an all_to_all "
    "program over a handful of rows pays mesh staging + a fresh "
    "compile for nothing). 0 = no floor."
).int_conf.create_with_default(0)

SHUFFLE_SEAM_ICI = conf(
    "rapids.tpu.shuffle.seam.intraHostIci.enabled").doc(
    "Per-seam shuffle routing in cluster mode: keep in-program ICI "
    "collectives for exchanges whose subtree ships to one host whole "
    "(the collective spans only that process's mesh slice) and use the "
    "TCP path ONLY at the DCN seam between hosts. Disable to restore "
    "the all-or-nothing cluster gate where ANY cluster session forces "
    "every exchange onto TCP. Every seam decision is recorded either "
    "way (parallel/spmd.seam_snapshot, surfaced in run telemetry)."
).boolean_conf.create_with_default(True)

SHUFFLE_COMPRESSION_CODEC = conf("rapids.tpu.shuffle.compression.codec").doc(
    "Compression for host-path shuffle payloads: none, lz4 (native C++ "
    "codec; the nvcomp-LZ4 analogue, RapidsConf.scala:685) or zlib."
).string_conf.create_with_default("lz4")

SHUFFLE_MAX_INFLIGHT = conf(
    "rapids.tpu.shuffle.transport.maxReceiveInflightBytes").doc(
    "Inflight-bytes throttle for shuffle fetches (RapidsConf.scala:603-685)."
).bytes_conf.create_with_default(1 << 30)

SHUFFLE_RETRY_JITTER_MS = conf(
    "rapids.tpu.shuffle.retry.jitterMs").doc(
    "Uniform random jitter (0..jitterMs) added to each transport "
    "reconnect backoff sleep, so hosts that watched the same DCN blip "
    "de-synchronize instead of stampeding one survivor with "
    "simultaneous reconnects. 0 disables jitter (deterministic "
    "backoff, useful under fault injection)."
).int_conf.create_with_default(10)

SHUFFLE_RETRY_MAX_RECONNECTS = conf(
    "rapids.tpu.shuffle.retry.maxReconnects").doc(
    "Transient-fault retry budget per transport request (each retry is "
    "also the one reconnect — the failed round trip already dropped "
    "the socket). Past it the error surfaces as a fetch failure and "
    "costs a stage retry."
).int_conf.create_with_default(3)

TEST_ENABLED = conf("rapids.tpu.sql.test.enabled").doc(
    "Test mode: assert the whole plan is on the TPU "
    "(GpuTransitionOverrides.scala:270-326)."
).internal().boolean_conf.create_with_default(False)

TEST_ALLOWED_NON_TPU = conf("rapids.tpu.sql.test.allowedNonTpu").doc(
    "Comma-separated exec/expr class names allowed to fall back in test mode."
).internal().string_conf.create_with_default("")

CAST_FLOAT_TO_STRING = conf(
    "rapids.tpu.sql.castFloatToString.enabled").doc(
    "Enable float->string cast (formatting differs from Java in corner "
    "cases; GpuCast gate analogue, RapidsConf.scala:450-482)."
).boolean_conf.create_with_default(False)

CAST_STRING_TO_FLOAT = conf(
    "rapids.tpu.sql.castStringToFloat.enabled").doc(
    "Enable string->float cast."
).boolean_conf.create_with_default(False)

CAST_STRING_TO_TIMESTAMP = conf(
    "rapids.tpu.sql.castStringToTimestamp.enabled").doc(
    "Enable string->timestamp cast."
).boolean_conf.create_with_default(False)

ENABLE_REPLACE_SORT_MERGE_JOIN = conf(
    "rapids.tpu.sql.replaceSortMergeJoin.enabled").doc(
    "Replace sort-merge joins with TPU hash joins (RapidsConf.scala:439). "
    "On TPU the join itself is sort-based, so this controls removing the "
    "upstream CPU sorts."
).boolean_conf.create_with_default(True)

IMPROVED_FLOAT_OPS = conf("rapids.tpu.sql.improvedFloatOps.enabled").doc(
    "Enable float ops that use TPU transcendental approximations."
).boolean_conf.create_with_default(False)

MAX_CAPACITY_BUCKETS = conf("rapids.tpu.sql.shape.bucketWaste").doc(
    "Capacity bucketing growth factor numerator/denominator packed as "
    "percent waste allowed; buckets bound XLA recompilation (TPU-specific; "
    "the reference never needed this because cuDF allocates dynamically)."
).int_conf.create_with_default(100)

MULTIFILE_READ_THREADS = conf("rapids.tpu.sql.multiFile.numThreads").doc(
    "Thread pool size for multi-file reads "
    "(MultiFileThreadPoolFactory analogue, GpuParquetScan.scala:647)."
).int_conf.create_with_default(8)

UDF_COMPILER_ENABLED = conf("rapids.tpu.sql.udfCompiler.enabled").doc(
    "Trace Python UDFs into jittable jax expressions "
    "(udf-compiler analogue)."
).boolean_conf.create_with_default(True)

# -- file format gates (RapidsConf.scala per-format enables) ----------------

PARQUET_ENABLED = conf("rapids.tpu.sql.format.parquet.enabled").doc(
    "Enable parquet input and output on the TPU path."
).boolean_conf.create_with_default(True)

PARQUET_READ_ENABLED = conf("rapids.tpu.sql.format.parquet.read.enabled").doc(
    "Enable parquet scans."
).boolean_conf.create_with_default(True)

PARQUET_WRITE_ENABLED = conf(
    "rapids.tpu.sql.format.parquet.write.enabled").doc(
    "Enable parquet writes."
).boolean_conf.create_with_default(True)

ORC_ENABLED = conf("rapids.tpu.sql.format.orc.enabled").doc(
    "Enable ORC input and output on the TPU path."
).boolean_conf.create_with_default(True)

ORC_READ_ENABLED = conf("rapids.tpu.sql.format.orc.read.enabled").doc(
    "Enable ORC scans."
).boolean_conf.create_with_default(True)

ORC_WRITE_ENABLED = conf("rapids.tpu.sql.format.orc.write.enabled").doc(
    "Enable ORC writes."
).boolean_conf.create_with_default(True)

CSV_ENABLED = conf("rapids.tpu.sql.format.csv.enabled").doc(
    "Enable CSV input on the TPU path (the reference is read-only for CSV)."
).boolean_conf.create_with_default(True)

CSV_READ_ENABLED = conf("rapids.tpu.sql.format.csv.read.enabled").doc(
    "Enable CSV scans."
).boolean_conf.create_with_default(True)

OPTIMIZER_ENABLED = conf("rapids.tpu.sql.optimizer.enabled").doc(
    "Structural plan rules before override planning: collapse adjacent "
    "projections, combine filters, push filters through deterministic "
    "projections (each removed node is one fewer executable per batch)."
).boolean_conf.create_with_default(True)

ADAPTIVE_ENABLED = conf("rapids.tpu.sql.adaptive.enabled").doc(
    "Adaptive shuffle reads: after an exchange materializes, coalesce "
    "small reduce partitions toward the advisory size using exact map "
    "output statistics (GpuCustomShuffleReaderExec analogue, "
    "GpuOverrides.scala:1874-1887)."
).boolean_conf.create_with_default(True)

ADVISORY_PARTITION_SIZE = conf(
    "rapids.tpu.sql.adaptive.advisoryPartitionSizeBytes").doc(
    "Target bytes per coalesced shuffle partition."
).bytes_conf.create_with_default(64 << 20)

ADAPTIVE_SKEW_JOIN = conf("rapids.tpu.sql.adaptive.skewJoin.enabled").doc(
    "Replan rule 1 (OptimizeSkewedJoin analogue): shuffle partitions "
    "exceeding the skewedPartition cut are split into sub-reads on the "
    "host path, and salted across mesh devices before the in-program "
    "all_to_all, while the other join side replicates — the hot key "
    "stops setting the whole mesh's wall clock. Each split/salt is a "
    "skew replan event in the dispatch telemetry."
).boolean_conf.create_with_default(True)

ADAPTIVE_SKEW_FACTOR = conf(
    "rapids.tpu.sql.adaptive.skewJoin.skewedPartitionFactor").doc(
    "A shuffle partition is skewed when its bytes exceed this multiple "
    "of the median partition size (and the threshold below) — Spark's "
    "skewedPartitionFactor."
).double_conf.create_with_default(5.0)

ADAPTIVE_SKEW_THRESHOLD = conf(
    "rapids.tpu.sql.adaptive.skewJoin.skewedPartitionThresholdInBytes"
).doc(
    "Minimum bytes before a partition can be considered skewed, "
    "whatever the factor says — Spark's skewedPartitionThresholdInBytes."
).bytes_conf.create_with_default(256 << 20)

ADAPTIVE_SKEW_MAX_SPLITS = conf(
    "rapids.tpu.sql.adaptive.skewJoin.maxSplitsPerPartition").doc(
    "Upper bound on sub-reads one skewed partition is split into "
    "(bounds the replicated-side re-reads and the salt fan-out)."
).int_conf.create_with_default(8)

ADAPTIVE_STRATEGY_SWITCH = conf(
    "rapids.tpu.sql.adaptive.strategySwitch.enabled").doc(
    "Replan rule 2: once the build-side exchange has materialized, "
    "re-decide the join strategy from MEASURED bytes — a shuffled hash "
    "join whose build side came in under autoBroadcastJoinThreshold "
    "re-plans as a broadcast join (skipping the stream-side shuffle "
    "read restructure), and a dense key range upgrades the probe to "
    "the direct-address table. Recorded as strategy_switch replan "
    "events."
).boolean_conf.create_with_default(True)

ADAPTIVE_DENSE_JOIN = conf(
    "rapids.tpu.sql.adaptive.denseJoin.enabled").doc(
    "Allow the strategy switch to flip a shuffled hash join's probe to "
    "the dense direct-address table when the measured build key range "
    "is dense enough (minDensity/maxKeySpan below) — one gather per "
    "probe row instead of an int64 hash + binary search."
).boolean_conf.create_with_default(True)

ADAPTIVE_DENSE_MAX_SPAN = conf(
    "rapids.tpu.sql.adaptive.denseJoin.maxKeySpan").doc(
    "Largest (max-min+1) build key span eligible for the dense table; "
    "the start-offset table costs 4 bytes per slot of span."
).int_conf.create_with_default(1 << 23)

ADAPTIVE_DENSE_MIN_DENSITY = conf(
    "rapids.tpu.sql.adaptive.denseJoin.minDensity").doc(
    "Minimum build_rows / key_span ratio before the dense table is "
    "considered worth its memory."
).double_conf.create_with_default(0.125)

ADAPTIVE_DENSE_MIN_ROWS = conf(
    "rapids.tpu.sql.adaptive.denseJoin.minBuildRows").doc(
    "Skip the key-range measurement (one extra dispatch + sync per "
    "build) for builds smaller than this many rows — the hash probe is "
    "already cheap there."
).int_conf.create_with_default(1 << 16)

ADAPTIVE_REBUCKET = conf(
    "rapids.tpu.sql.adaptive.rebucket.enabled").doc(
    "Replan rule 3a: an adaptive join read serving a coalesced group "
    "of 2+ map blocks concatenates them into ONE batch bucketed at the "
    "MEASURED row count, so the progcache serves the right ladder rung "
    "instead of padding each small block to its own bucket. Recorded "
    "as rebucket replan events."
).boolean_conf.create_with_default(True)

ADAPTIVE_RUNTIME_STATS = conf(
    "rapids.tpu.sql.adaptive.runtimeStats.enabled").doc(
    "Replan rule 3b: measured exchange cardinalities feed "
    "estimate_footprint_bytes on later plans of the same shape, so "
    "out-of-core admission tightens as the workload runs instead of "
    "guessing from the static default row estimate."
).boolean_conf.create_with_default(True)

PARQUET_DEBUG_DUMP_PREFIX = conf(
    "rapids.tpu.sql.parquet.debug.dumpPrefix").doc(
    "When set, copy every parquet file a scan reads under this directory "
    "for offline repro (RapidsConf.scala:575-581 debug dump analogue)."
).string_conf.create_with_default("")

AUTO_BROADCAST_THRESHOLD = conf(
    "rapids.tpu.sql.autoBroadcastJoinThreshold").doc(
    "Equi-joins whose build side is ESTIMATED (scan statistics: parquet "
    "footer num_rows / host array lengths) at or below this many bytes "
    "broadcast instead of shuffling both sides - Spark's "
    "autoBroadcastJoinThreshold, which the reference inherits from the "
    "upstream optimizer. 0 disables (always shuffle when partitioned). "
    "Each skipped exchange pair saves partition/transfer dispatches, "
    "which dominate small-dimension joins behind the compile tunnel."
).bytes_conf.create_with_default(10 << 20)

PYTHON_WORKER_PROCESS = conf(
    "rapids.tpu.python.worker.process.enabled").doc(
    "Run pandas UDFs (mapInPandas / applyInPandas / cogroup / "
    "window-in-pandas / pandas aggregates / scalar pandas UDFs) in "
    "POOLED SEPARATE worker processes instead of in-process — the "
    "reference's worker/daemon model (python/rapids/worker.py:22-50, "
    "daemon.py:36-60): on the accelerated execs a crashing or leaking "
    "UDF can no longer take the engine with it, and workers are pinned "
    "off the TPU. (CPU-fallback pandas execs still run in-process.)"
).boolean_conf.create_with_default(False)

PYTHON_WORKER_SLOTS = conf(
    "rapids.tpu.python.worker.processes").doc(
    "Worker processes in the pandas-UDF pool (checkout blocks, the "
    "process-level PythonWorkerSemaphore)."
).int_conf.create_with_default(2)

ORC_DEBUG_DUMP_PREFIX = conf(
    "rapids.tpu.sql.orc.debug.dumpPrefix").doc(
    "When set, copy every ORC file a scan reads under this directory "
    "for offline repro (the ORC half of the reference's debug dump, "
    "RapidsConf.scala:583-589)."
).string_conf.create_with_default("")

CSV_TIMESTAMPS_ENABLED = conf(
    "rapids.tpu.sql.csv.read.timestamps.enabled").doc(
    "Enable reading TIMESTAMP columns from CSV. Off by default: CSV "
    "timestamp text admits many format/timezone spellings and only the "
    "formats listed in rapids.tpu.sql.csv.timestampFormats parse "
    "identically to Spark CPU (the reference gates cuDF's CSV "
    "timestamp parsing the same way, RapidsConf.scala:482)."
).boolean_conf.create_with_default(False)

CSV_TIMESTAMP_FORMATS = conf(
    "rapids.tpu.sql.csv.timestampFormats").doc(
    "Comma-separated strptime patterns tried in order for CSV "
    "TIMESTAMP columns when csv.read.timestamps.enabled is true. Text "
    "matching none of them fails the scan (FAILFAST semantics)."
).string_conf.create_with_default(
    "%Y-%m-%dT%H:%M:%S,%Y-%m-%d %H:%M:%S,%Y-%m-%d")

# -- concurrent query service (service/ subsystem) --------------------------

SERVICE_QUEUE_LIMIT = conf("rapids.tpu.service.queueLimit").doc(
    "Maximum queries waiting for admission (across all tenants). "
    "Submissions beyond it are shed with a structured ServiceOverloaded "
    "rejection instead of queueing unboundedly — load shedding is the "
    "service's backpressure signal to callers."
).int_conf.create_with_default(64)

SERVICE_MAX_CONCURRENT = conf("rapids.tpu.service.maxConcurrent").doc(
    "Queries admitted concurrently (each admitted query gets stage "
    "slices interleaved on the dispatch path by the stage scheduler). "
    "Within the admitted set, device entry is still bounded by "
    "rapids.tpu.sql.concurrentTpuTasks semaphore permits."
).int_conf.create_with_default(4)

SERVICE_DEFAULT_DEADLINE = conf("rapids.tpu.service.defaultDeadlineSec").doc(
    "Default per-query deadline in seconds (queue time + run time). "
    "0 disables; submit(deadline=...) overrides per query. Expired "
    "queries fail with DeadlineExceeded and release their admission, "
    "semaphore permit and catalog buffers."
).double_conf.create_with_default(0.0)

SERVICE_FAIRNESS_WEIGHTS = conf("rapids.tpu.service.fairness.weights").doc(
    "Weighted-round-robin admission weights per tenant as "
    "'tenantA:2,tenantB:1'. Unlisted tenants weigh 1. A tenant's weight "
    "is how many queries it may admit per WRR cycle while other tenants "
    "have queued work — a flood from one tenant cannot starve another."
).string_conf.create_with_default("")

SERVICE_ADMISSION_BUDGET = conf("rapids.tpu.service.admission.hbmBudget").doc(
    "Device-memory budget admission controls against, in bytes. 0 (the "
    "default) uses the runtime's HBM budget (allocFraction * HBM - "
    "reserve) when a device reports memory, else admission is bounded "
    "only by maxConcurrent. A query whose estimated peak footprint "
    "does not fit next to the in-flight queries WAITS in the queue."
).bytes_conf.create_with_default(0)

SERVICE_DEFAULT_ROW_ESTIMATE = conf(
    "rapids.tpu.service.admission.defaultRowEstimate").doc(
    "Row-count assumption for plan nodes whose cardinality the "
    "optimizer cannot estimate (no footer stats); feeds the admission "
    "footprint estimate."
).int_conf.create_with_default(1 << 20)

SERVICE_OUT_OF_CORE = conf("rapids.tpu.service.outOfCore.enabled").doc(
    "Admit a query whose estimated peak footprint exceeds the WHOLE "
    "device budget in flagged out-of-core mode — planned with a "
    "forced-splitting batch budget and eager spill priority, charged "
    "a capped share of HBM — instead of parking it in the admission "
    "queue until the device drains (or its deadline fires)."
).boolean_conf.create_with_default(True)

SERVICE_OUT_OF_CORE_POLICY = conf("rapids.tpu.service.outOfCore.policy").doc(
    "What to do with an over-budget query when outOfCore.enabled: "
    "'run' executes it out-of-core (splitting + spilling to disk); "
    "'shed' rejects it at submit with a structured OutOfCoreRejected "
    "— for deployments that prefer failing whales fast over letting "
    "them occupy the device for a long spill-bound run."
).string_conf.create_with_default("run")

SERVICE_BATCHING_ENABLED = conf("rapids.tpu.service.batching.enabled").doc(
    "Cross-tenant micro-batching: a stage-program dispatch inside a "
    "service slice holds for batching.windowMs and coalesces with "
    "compatible same-program same-bucket dispatches from OTHER queries "
    "into one physical launch (per-query row-count scalars mask each "
    "participant's padding; results split inside the same compiled "
    "program). One launch then serves K tenants — the inference-"
    "serving batching trick applied to SQL stages. The hold only "
    "engages while more than one query is in flight."
).boolean_conf.create_with_default(True)

SERVICE_BATCHING_WINDOW_MS = conf(
    "rapids.tpu.service.batching.windowMs").doc(
    "Micro-batch hold window in milliseconds: how long a stage "
    "dispatch waits for compatible peers before launching. Behind a "
    "~100 ms-per-dispatch remote attachment a few ms buys up to a "
    "K-fold dispatch reduction; keep it well under the backend RTT."
).double_conf.create_with_default(2.0)

SERVICE_BATCHING_MAX = conf("rapids.tpu.service.batching.maxBatch").doc(
    "Maximum queries coalesced into one physical stage launch (a full "
    "group launches immediately, before the window expires). Each "
    "group size K compiles its own K-way program variant once, so "
    "keep this small."
).int_conf.create_with_default(8)

SERVICE_BATCHING_BUCKET_GROWTH = conf(
    "rapids.tpu.service.batching.bucketGrowth").doc(
    "Growth factor of the geometric capacity-bucket ladder "
    "(ops/buckets), installed process-wide at service construction. "
    "2.0 = classic power-of-two buckets. Coarser (e.g. 4.0) funnels "
    "more tenants onto the same compiled executables and coalescible "
    "shapes at the cost of more padding lanes; finer (e.g. 1.5) "
    "wastes less HBM but fragments the executable space. Padding is "
    "masked by the per-batch row-count scalar either way."
).double_conf.create_with_default(2.0)

SERVICE_WARMUP_ENABLED = conf("rapids.tpu.service.warmup.enabled").doc(
    "AOT-warm the compile caches when a query template is registered "
    "(QueryService.register_template): the template runs once under a "
    "reserved '__warmup__' tenant so its stage programs trace, "
    "compile, and land in the persistent progcache BEFORE the first "
    "tenant request — which otherwise eats the cold compile (behind "
    "the remote-compile tunnel, minutes)."
).boolean_conf.create_with_default(False)

SERVICE_WARMUP_LADDER = conf("rapids.tpu.service.warmup.ladder").doc(
    "After template warmup, replay each recorded stage program over "
    "the capacity-ladder rungs at/below its observed bucket with "
    "zero-filled operands (service/batching shape-bucket registry), "
    "pre-compiling the executables smaller batches will hit. Only "
    "applies when warmup.enabled is set."
).boolean_conf.create_with_default(True)

SERVICE_CACHE_ENABLED = conf("rapids.tpu.service.cache.enabled").doc(
    "Master switch for the semantic cache (service/cache): repeat "
    "queries over unchanged table snapshots are served from the exact "
    "result cache, and matching stage subplans from the fragment "
    "cache, instead of recomputing on the device. Keys are canonical "
    "plan fingerprints (plan/fingerprint) plus table snapshot "
    "versions, so invalidation is a version comparison — a replaced "
    "view, a rewritten file, or Session.bump_table_version all miss "
    "exactly. Sources without a stable identity (in-memory data) "
    "always bypass."
).boolean_conf.create_with_default(True)

SERVICE_CACHE_RESULT = conf(
    "rapids.tpu.service.cache.resultCache.enabled").doc(
    "Serve a query whose (canonical plan fingerprint, table snapshot "
    "versions) key matches a stored result directly from the host-side "
    "result cache — zero planning, zero device dispatches. Concurrent "
    "identical misses single-flight: one leader computes, followers "
    "are served a copy when it completes."
).boolean_conf.create_with_default(True)

SERVICE_CACHE_FRAGMENT = conf(
    "rapids.tpu.service.cache.fragmentCache.enabled").doc(
    "Materialize cacheable stage subplans (aggregate/join/sort/window "
    "roots — the stage-breaker analogues of plan/optimizer.cut_stages) "
    "as spillable batches on first execution and graft them into later "
    "plans as cached-scan leaves, so subplans shared across queries "
    "and tenants compute once. Entries ride the device->host->disk "
    "spill tiers under the normal priority machinery and their "
    "device-resident bytes count against admission's HBM budget."
).boolean_conf.create_with_default(True)

SERVICE_CACHE_MAX_BYTES = conf("rapids.tpu.service.cache.maxBytes").doc(
    "Combined byte budget for cached results (host frames) and cached "
    "fragments (spillable batches, measured at device width). Above "
    "it, least-recently-used unpinned entries are evicted; an entry "
    "larger than the whole budget is never stored. See "
    "docs/tuning-guide.md for sizing against the device budget."
).bytes_conf.create_with_default(256 << 20)

STREAMING_ENABLED = conf("rapids.tpu.streaming.enabled").doc(
    "Master switch for streaming ingestion & incremental queries "
    "(service/streaming): Session.create_streaming_table registers an "
    "appendable table, QueryService.ingest lands micro-batches as "
    "versioned deltas, and standing queries registered with "
    "QueryService.register_standing fold each delta into long-lived "
    "device-resident partial-aggregate state — one update launch plus "
    "one merge launch per micro-batch, O(batch) not O(total). "
    "Disabled, register_standing raises and appends still land (batch "
    "queries over the table keep working)."
).boolean_conf.create_with_default(True)

STREAMING_WATERMARK_MS = conf("rapids.tpu.streaming.watermarkMs").doc(
    "Default allowed event-time lateness in milliseconds for standing "
    "queries registered with an event-time column. The per-query "
    "watermark advances to max(event_time_seen) - watermarkMs and "
    "never retreats; rows arriving at-or-below the watermark are LATE "
    "(see rapids.tpu.streaming.lateData.policy), and windows whose "
    "end is at-or-below it are FINAL (StandingQuery.results("
    "final_only=True)). Per-registration override: the watermark_ms "
    "argument of register_standing."
).int_conf.create_with_default(0)

STREAMING_MAX_STATE_BYTES = conf("rapids.tpu.streaming.maxStateBytes").doc(
    "Upper bound on one standing query's partial-aggregate state, "
    "measured at device width (the SpillableBatch registered size — "
    "the state itself rides the device->host->disk spill tiers and "
    "its device-resident bytes charge the admission footprint). A "
    "fold that grows the state past this bound FAILS the standing "
    "query and tears its state down (owner-tag removal), exactly like "
    "cancel — unbounded key cardinality must not silently eat the "
    "spill store. 0 disables the bound."
).bytes_conf.create_with_default(0)

STREAMING_LATE_POLICY = conf("rapids.tpu.streaming.lateData.policy").doc(
    "What a standing query does with rows that arrive at-or-below its "
    "watermark: 'merge' (default) folds them through the same "
    "merge-spec path as on-time rows — already-emitted aggregates "
    "self-correct on the next emit, counted as late-row re-merges in "
    "the streaming stats block; 'drop' discards them host-side before "
    "the update launch. Per-registration override: the late_policy "
    "argument of register_standing."
).string_conf.create_with_default("merge")

STREAMING_CHECKPOINT_DIR = conf(
    "rapids.tpu.streaming.checkpoint.dir").doc(
    "Root directory of the streaming durability layer "
    "(service/streaming/durability.py). Set, every "
    "StreamTableSource.append persists its validated delta to a "
    "CRC-framed per-table write-ahead log BEFORE any standing query "
    "folds it, and every standing query checkpoints its running "
    "(keys..., partials...) state + watermark + sequence cursor at "
    "fold boundaries into atomically-renamed, CRC'd checkpoint files "
    "under the same root. A restarted service recovers through "
    "StreamingManager.recover(): latest valid checkpoint + WAL-suffix "
    "replay past its cursor = fold-exactly-once; no valid checkpoint "
    "falls back to a full refold from the WAL. Empty (default) "
    "disables durability — streaming state is process-memory only, "
    "as before PR 19."
).string_conf.create_with_default("")

STREAMING_CHECKPOINT_INTERVAL = conf(
    "rapids.tpu.streaming.checkpoint.intervalFolds").doc(
    "Checkpoint a standing query's state every N folds (counted per "
    "query). 1 (default) checkpoints at every fold boundary — the "
    "tightest recovery point; larger values trade restart replay "
    "length (up to N-1 WAL deltas refold) for less checkpoint I/O. "
    "Values < 1 clamp to 1."
).int_conf.create_with_default(1)

STREAMING_CHECKPOINT_RETAIN = conf(
    "rapids.tpu.streaming.checkpoint.retain").doc(
    "Checkpoint files kept per standing query; older ones are pruned "
    "after each successful write. Keeping >= 2 means a checkpoint torn "
    "by a crash mid-write still leaves the previous valid one to "
    "recover from (recovery tries newest to oldest, counting rejects "
    "in the torn_rejected streaming counter). Values < 1 clamp to 1."
).int_conf.create_with_default(2)

STREAMING_CHECKPOINT_WAL_SYNC = conf(
    "rapids.tpu.streaming.checkpoint.walSyncEvery").doc(
    "fsync the ingest write-ahead log every N appended records. 1 "
    "(default) syncs every append — an acknowledged ingest is durable "
    "before any fold sees it; larger values batch the fsync cost "
    "across appends at the price of the unsynced tail being lost on "
    "power failure (process crash alone loses nothing: the bytes are "
    "already in the page cache). Unsynced WAL bytes are charged to "
    "admission via the service's extra_bytes_fn."
).int_conf.create_with_default(1)

STREAMING_CHECKPOINT_ASYNC = conf(
    "rapids.tpu.streaming.checkpoint.asyncWrite.enabled").doc(
    "Write checkpoint files on the shared async batch-writer template "
    "(memory/catalog.py AsyncBatchWriter — the PR 6 double-buffered "
    "spill writer generalized): the fold returns while the serialized "
    "snapshot commits in the background, with the bounded queue as "
    "backpressure and pending bytes charged to admission. Disabled, "
    "checkpoints commit inline at the fold boundary (deterministic — "
    "what the durability unit tests use)."
).boolean_conf.create_with_default(True)

STREAMING_CHECKPOINT_ON_SIGTERM = conf(
    "rapids.tpu.streaming.checkpoint.onSigterm").doc(
    "With durability enabled, install a SIGTERM handler (main thread "
    "only) that checkpoint-then-drains the service instead of letting "
    "the default handler kill standing queries mid-fold: every live "
    "standing query writes a final checkpoint and suspends, then the "
    "previously-installed handler (if any) runs. SIGKILL needs no "
    "handler — that is what the WAL + checkpoint recovery path is "
    "for."
).boolean_conf.create_with_default(True)

SERVICE_CACHE_TTL = conf("rapids.tpu.service.cache.ttlSec").doc(
    "Time-to-live in seconds for cache entries: an entry older than "
    "this is treated as a miss on next touch and evicted — or, while "
    "queries still pin it (serving or holding it grafted in a queued "
    "plan), marked stale and evicted on the last unpin. 0 (default) "
    "disables TTL — snapshot-version invalidation alone decides "
    "freshness, which is exact for file-backed and protocol sources."
).double_conf.create_with_default(0.0)

FILTER_PUSHDOWN_ENABLED = conf(
    "rapids.tpu.sql.format.pushDownFilters.enabled").doc(
    "Push comparison conjuncts from a Filter above a file scan into the "
    "source for row-group/stripe pruning (GpuParquetScan.scala:228-265 "
    "row-group filtering analogue; exact filtering still runs on device)."
).boolean_conf.create_with_default(True)


class RapidsConf:
    """Immutable snapshot of configuration values.

    Values resolve: explicit dict > environment (dots->underscores,
    uppercased) > registered default.
    """

    def __init__(self, settings: Optional[Dict[str, Any]] = None):
        self._settings = dict(settings or {})

    def with_overrides(self, extra: Dict[str, Any]) -> "RapidsConf":
        s = dict(self._settings)
        s.update(extra)
        return RapidsConf(s)

    def get(self, entry: ConfEntry) -> Any:
        if entry.key in self._settings:
            return entry.converter(self._settings[entry.key])
        env_key = entry.key.upper().replace(".", "_")
        if env_key in os.environ:
            return entry.converter(os.environ[env_key])
        return entry.default

    def get_key(self, key: str, default=None):
        with _REGISTRY_LOCK:
            entry = _REGISTRY.get(key)
        if entry is not None:
            return self.get(entry)
        return self._settings.get(key, default)

    def is_op_enabled(self, kind: str, name: str, default: bool = True) -> bool:
        key = f"rapids.tpu.sql.{kind}.{name}"
        with _REGISTRY_LOCK:
            entry = _REGISTRY.get(key)
        if entry is None:
            return default
        return self.get(entry)

    # Convenience accessors used widely.
    @property
    def sql_enabled(self) -> bool:
        return self.get(SQL_ENABLED)

    @property
    def explain(self) -> str:
        return str(self.get(EXPLAIN)).upper()

    @property
    def batch_size_bytes(self) -> int:
        return self.get(BATCH_SIZE_BYTES)

    @property
    def concurrent_tpu_tasks(self) -> int:
        return self.get(CONCURRENT_TPU_TASKS)

    @staticmethod
    def help() -> str:
        """Generate config docs (docs/configs.md analogue)."""
        lines = ["Name|Description|Default", "---|---|---"]
        for e in sorted(registered_entries(), key=lambda e: e.key):
            if not e.internal:
                lines.append(e.help())
        return "\n".join(lines)


DEFAULT_CONF = RapidsConf()
