"""spark_rapids_tpu: a TPU-native columnar SQL acceleration framework.

A from-scratch, TPU-first re-design of the capabilities of the RAPIDS
Accelerator for Apache Spark (reference surveyed in SURVEY.md):

- a columnar batch representation backed by JAX device arrays with
  validity masks and *bucketed static capacities* (the TPU/XLA answer to
  cuDF's dynamically-sized device buffers),
- a kernel surface (filter/sort/groupby/join/partition/concat/cast/...)
  implemented as jit-compiled XLA computations with bounded recompilation,
- an expression layer whose projections fuse into single XLA executables,
- a tiered device->host->disk spill catalog and chip admission control,
- a plan-override planner with per-op config gates, tagging reasons and
  CPU fallback (pandas engine doubles as the golden-comparison oracle),
- a device-resident shuffle whose intra-slice path rides ICI collectives
  (jax.lax.all_to_all under shard_map) instead of UCX/RDMA.

Reference architecture citations throughout use ``path:line`` into
/root/reference (vorktanamobay/spark-rapids).
"""
from __future__ import annotations

import os

# Spark SQL semantics require 64-bit longs/doubles (LongType/DoubleType are
# pervasive in TPC-* schemas). JAX defaults to 32-bit; opt into x64 before any
# array is created. Set SPARK_RAPIDS_TPU_NO_X64=1 to opt out (perf experiments).
if not os.environ.get("SPARK_RAPIDS_TPU_NO_X64"):
    import jax

    jax.config.update("jax_enable_x64", True)

# Persistent executable cache: the fused relational programs are LARGE
# (sorts + scans over x64-rewritten graphs) and tunnel-remote compiles
# run minutes; caching makes every process after the first start hot.
if not os.environ.get("SPARK_RAPIDS_TPU_NO_COMPILE_CACHE"):
    import jax

    # SEPARATE cache dirs per platform env: CPU executables compiled in
    # a TPU-attached (axon) process carry that platform's XLA target
    # features (+prefer-no-scatter etc.); a plain-CPU process loading
    # such an entry SIGSEGVs inside the AOT loader. Processes forced to
    # CPU (tests, dryrun) therefore use their own cache. The rule lives
    # in ONE place (utils/progcache, which also resolves explicit
    # compileCacheDir settings) so the two sites can never drift.
    from spark_rapids_tpu.utils.progcache import _platform_suffix

    _suffix = _platform_suffix()
    _cache_dir = os.environ.get(
        "SPARK_RAPIDS_TPU_COMPILE_CACHE",
        os.path.join(os.path.dirname(os.path.abspath(__file__)), "..",
                     f".jax_cache{_suffix}"))
    try:
        jax.config.update("jax_compilation_cache_dir",
                          os.path.abspath(_cache_dir))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 2)
    except Exception:  # older jax without the knobs
        pass

__version__ = "0.1.0"

from spark_rapids_tpu.config import RapidsConf  # noqa: E402,F401
