"""Query lifecycle types shared by the service modules."""
from __future__ import annotations

import enum
import threading
import time
from typing import List, Optional


class QueryState(enum.Enum):
    QUEUED = "QUEUED"          # accepted, waiting for admission
    ADMITTED = "ADMITTED"      # counted against budget, awaiting a slice
    RUNNING = "RUNNING"        # a scheduler worker is driving a slice
    DONE = "DONE"
    FAILED = "FAILED"          # error or deadline expiry
    CANCELLED = "CANCELLED"    # explicit cancel()
    SHED = "SHED"              # rejected at submit (queue limit)


TERMINAL_STATES = frozenset(
    {QueryState.DONE, QueryState.FAILED, QueryState.CANCELLED,
     QueryState.SHED})


class ServiceOverloaded(RuntimeError):
    """Structured load-shed rejection: the admission queue is at
    ``rapids.tpu.service.queueLimit``. Callers should back off and
    retry; the fields let a gateway turn this into a 429."""

    def __init__(self, tenant: str, queue_depth: int, queue_limit: int):
        self.tenant = tenant
        self.queue_depth = queue_depth
        self.queue_limit = queue_limit
        super().__init__(
            f"service overloaded: admission queue depth {queue_depth} "
            f"at limit {queue_limit} (tenant {tenant!r}) — retry with "
            f"backoff or raise rapids.tpu.service.queueLimit")


class DeadlineExceeded(RuntimeError):
    """The query's deadline (queue time + run time) expired before it
    completed; its admission, permit and buffers were released."""


class OutOfCoreRejected(RuntimeError):
    """The query's estimated footprint exceeds the whole device budget
    and ``rapids.tpu.service.outOfCore.policy`` is ``shed``: the
    service refuses to run it out-of-core. Recorded as a terminal SHED
    query; callers can resubmit with a smaller working set or to a
    service configured with policy ``run``."""

    def __init__(self, tenant: str, footprint: int, budget: int):
        self.tenant = tenant
        self.footprint = footprint
        self.budget = budget
        super().__init__(
            f"query footprint {footprint} bytes exceeds the device "
            f"budget {budget} and outOfCore.policy=shed (tenant "
            f"{tenant!r}) — shrink the query or set "
            f"rapids.tpu.service.outOfCore.policy=run")


class QueryCancelled(RuntimeError):
    """result() on a query whose cancel() won."""


class Query:
    """Internal per-query record. All mutable fields are guarded by the
    service-wide lock; the condition variable wakes ``result()``
    waiters on any state transition."""

    def __init__(self, query_id: int, tenant: str, plan, exec_,
                 priority: int, deadline_s: Optional[float],
                 footprint: int, stages: List[dict],
                 cv: threading.Condition):
        self.query_id = query_id
        self.tenant = tenant
        self.plan = plan
        self.exec = exec_
        self.priority = priority
        self.deadline_s = deadline_s
        self.footprint = footprint
        self.stages = stages
        self.cv = cv
        self.state = QueryState.QUEUED
        self.cancel_requested = False
        self.error: Optional[BaseException] = None
        self.result = None  # assembled pandas frame once DONE
        self.submitted_at = time.perf_counter()
        self.admitted_at: Optional[float] = None
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.slices_done = 0
        self.dispatches = 0  # filled from telemetry when installed
        #: coalesced launches this query participated in (a physical
        #: launch shared with K-1 other queries counts here once, and
        #: 1/K in ``dispatches``) — service/batching attribution
        self.coalesced = 0
        self.spill_demoted = False  # stalled-yield bias currently set
        # out-of-core mode: footprint exceeds the whole device budget;
        # planned with a forced-splitting batch budget, runs with eager
        # spill bias, and charges admission only ``charge`` bytes (a
        # capped share — the spill chain, not HBM, absorbs the rest)
        self.out_of_core = False
        self.charge = footprint
        # final per-query OOM-retry accounting (memory/retry), filled
        # at finalize so stats history keeps it after the live map is
        # popped
        self.retry: dict = {}
        # semantic cache (service/cache): a query holding a result-cache
        # key is the single-flight LEADER for it — identical concurrent
        # misses register as followers and are served when the leader
        # finalizes DONE (one is PROMOTED to a fresh leader otherwise);
        # pending_fragments are capture entries this query must publish
        # or abort; served_fragments are READY entries its serve leaves
        # reference, pinned at graft time and unpinned at finalize so
        # eviction cannot close them while the query is queued
        self.result_cache_key = None
        self.cache_followers: list = []
        self.pending_fragments: list = []
        self.served_fragments: list = []
        self.cache_hit = False
        # cooperative execution cursor: per-partition batch iterators,
        # advanced one stage-slice at a time by the scheduler. The REAL
        # partition count resolves lazily on the first slice — querying
        # it eagerly would materialize adaptive exchanges on the
        # submitter's thread (exactly the blocking submit() must avoid).
        if exec_ is None:
            # shed-at-submit record: never planned, never runs
            self.planned_partitions = 0
        else:
            from spark_rapids_tpu.execs import adaptive as adaptive_exec

            with adaptive_exec.planning_mode():
                self.planned_partitions = exec_.num_partitions
        self.num_partitions: Optional[int] = None
        self.frames: dict = {}            # partition -> [pandas frames]
        self._iters: dict = {}            # partition -> live iterator
        self._cursor = 0

    # buffer-ownership tag for catalog attribution (demotion + cleanup)
    @property
    def owner_tag(self):
        return ("svc-query", self.query_id)

    @property
    def deadline_at(self) -> Optional[float]:
        if not self.deadline_s or self.deadline_s <= 0:
            return None
        return self.submitted_at + self.deadline_s

    def deadline_expired(self) -> bool:
        d = self.deadline_at
        return d is not None and time.perf_counter() > d

    @property
    def terminal(self) -> bool:
        return self.state in TERMINAL_STATES

    def queue_time_s(self) -> Optional[float]:
        end = self.admitted_at if self.admitted_at is not None \
            else self.finished_at
        return None if end is None else end - self.submitted_at

    def run_time_s(self) -> Optional[float]:
        if self.admitted_at is None:
            return None
        end = self.finished_at if self.finished_at is not None \
            else time.perf_counter()
        return end - self.admitted_at


class QueryHandle:
    """The caller's view of a submitted query (the service front door
    hands one back from ``submit()``)."""

    def __init__(self, service, query: Query):
        self._service = service
        self._query = query

    @property
    def query_id(self) -> int:
        return self._query.query_id

    @property
    def tenant(self) -> str:
        return self._query.tenant

    def poll(self) -> QueryState:
        """Non-blocking state probe (also lazily expires the deadline
        of a still-queued query)."""
        return self._service._poll(self._query)

    @property
    def state(self) -> QueryState:
        return self.poll()

    def result(self, timeout: Optional[float] = None):
        """Block until terminal, then return the pandas DataFrame
        (DONE) or raise: the original error / DeadlineExceeded
        (FAILED), QueryCancelled (CANCELLED). ``timeout`` raises
        TimeoutError without affecting the query."""
        return self._service._result(self._query, timeout)

    def cancel(self) -> bool:
        """Request cancellation; True if the query will not (or did
        not) complete. Queued queries cancel immediately; running ones
        stop at the next stage boundary."""
        return self._service._cancel(self._query)

    def info(self) -> dict:
        q = self._query
        return {
            "query_id": q.query_id,
            "tenant": q.tenant,
            "state": self.poll().value,
            "priority": q.priority,
            "footprint_bytes": q.footprint,
            "num_partitions": q.num_partitions
            if q.num_partitions is not None else q.planned_partitions,
            "stages": len(q.stages),
            "slices_done": q.slices_done,
            "queue_time_s": q.queue_time_s(),
            "run_time_s": q.run_time_s(),
        }
