"""Micro-batcher: coalesce compatible stage dispatches across queries.

Behind a remote device attachment every program launch pays ~100 ms of
fixed round-trip overhead (BASELINE.md), so N concurrent tenants each
dispatching the SAME stage program over the SAME bucket shape pay N
round trips for work one launch could carry. This is the
inference-serving continuous-batching trick applied to SQL: a stage
dispatch entering the service holds for a bounded window
(``rapids.tpu.service.batching.windowMs``); compatible dispatches from
other queries that arrive inside the window join the group; the group
leader then executes ONE jitted program that runs every participant's
stage — each with its own operands and row-count scalar masking its
own padding — and splits the results back out inside the same compiled
program (no per-participant slicing dispatches).

Compatibility = same program key (the structural chain key from
execs/fused — shared across plan instances and tenants by
construction), same operand tree structure, and same bucketed operand
shapes. The coalesced K-way program is built from the chain program's
RAW traceable function (``prog.__wrapped__``) so the inner program
inlines instead of nesting a jit, and is cached per
(program key, signature, K) — the ladder bounds the shape space, K is
bounded by ``maxBatch``, so the variant count stays small.

Deadlock-freedom: a leader never waits on other participants — it
seals its group at the window deadline regardless — and participants
wait only on their leader, who is by construction not waiting on them.
Workers hold no service lock inside the batcher, and every thread
RELEASES its device-entry permit (TpuSemaphore) while parked in the
batcher, re-acquiring before device work resumes: the engine-wide
invariant is that nobody holds a permit while waiting on other
threads, and a leader holding one through its window would block the
very peers it is waiting for at the device door (measured: with
concurrentTpuTasks=2, two window-holders starved the third query's
compatible dispatch until both windows expired — zero coalescing).

Attribution: the physical launch counts ONCE in the global dispatch
telemetry; each participating query's ledger records a fractional
share (1/K — per-query counts sum to the physical launch count) plus
one entry in its coalesced-participation counter
(utils/dispatch.enter_coalesced).
"""
from __future__ import annotations

import threading
from spark_rapids_tpu.utils import lockorder
import time
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.utils import dispatch as _disp

#: ceiling on how long a participant waits for its leader to execute —
#: generous (the leader's wait is window-bounded; past this something
#: is genuinely wedged and failing the slice beats hanging the worker)
_PARTICIPANT_TIMEOUT_S = 120.0


class _SliceContext:
    """Thread-local marker a scheduler slice (and the task threads it
    fans out to) carries: which batcher to route stage dispatches
    through, which query to attribute them to, and whether holding for
    coalescing can possibly pay (another query is in flight)."""

    __slots__ = ("batcher", "query_id", "multi")

    def __init__(self, batcher, query_id, multi):
        self.batcher = batcher
        self.query_id = query_id
        self.multi = multi


_tls = threading.local()


def enter_slice(batcher, query_id, multi: bool):
    """Install the batching context on this thread; returns a token for
    ``exit_slice``. ``multi`` False keeps the hold window off (a solo
    query must not pay windowMs per dispatch waiting for peers that
    cannot exist)."""
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = _SliceContext(batcher, query_id, multi) \
        if batcher is not None else None
    return prev


def exit_slice(token) -> None:
    _tls.ctx = token


def current() -> Optional[_SliceContext]:
    return getattr(_tls, "ctx", None)


def _semaphore():
    try:
        from spark_rapids_tpu.memory import semaphore as sem

        return sem.get()
    except Exception:  # pragma: no cover - memory package unavailable
        return None


def _quantize_group(k: int, max_batch: int) -> int:
    """Next power of two >= k, capped at max_batch."""
    q = 1
    while q < k:
        q *= 2
    return min(q, max_batch)


class _Group:
    """One forming micro-batch: the leader's + joiners' call slots."""

    __slots__ = ("slots", "sealed", "done", "results", "error")

    def __init__(self):
        self.slots: List[Tuple[Optional[int], tuple]] = []
        self.sealed = False
        self.done = threading.Event()
        self.results: Optional[list] = None
        self.error: Optional[BaseException] = None


class MicroBatcher:
    """One per QueryService. ``call()`` is the only hot entry point."""

    def __init__(self, window_s: float = 0.002, max_batch: int = 8,
                 enabled: bool = True, registry=None,
                 inflight_fn=None):
        self.window_s = max(float(window_s), 0.0)
        # max_batch normalizes DOWN to a power of two: group sizes
        # quantize to powers of two, so a non-power cap (say 6) would
        # admit a 6-way group that warm_coalesced (which enumerates
        # 2, 4, 8, ...) never pre-compiled — reintroducing exactly the
        # mid-run cold-compile stall warmup exists to prevent
        mb = max(int(max_batch), 1)
        self.max_batch = 1 << (mb.bit_length() - 1)
        self.enabled = bool(enabled) and self.window_s > 0 and \
            self.max_batch > 1
        self.registry = registry
        #: live inflight-query-count probe (the service passes its
        #: admission ledger). Serves two holds-related decisions: the
        #: slice-start ``multi`` snapshot goes stale when a peer is
        #: admitted MID-slice (re-probe before skipping the hold), and
        #: a leader whose group already contains every inflight query
        #: can seal EARLY — nobody else can possibly join, so waiting
        #: out the window would be pure added latency
        self.inflight_fn = inflight_fn
        self._lock = lockorder.make_lock("service.batching.microbatch")
        self._cv = lockorder.make_condition("service.batching.microbatch", lock=self._lock)
        self._groups: Dict[tuple, _Group] = {}
        #: (program_key, signature, k) -> jitted K-way program
        self._coalesced: Dict[tuple, object] = {}
        # stats (under self._lock)
        self._solo_launches = 0
        self._coalesced_launches = 0
        self._coalesced_participants = 0
        self._held_s = 0.0

    # -- public ------------------------------------------------------------

    def call(self, program_key, prog, args: tuple, statics: dict,
             query_id=None, multi: bool = True):
        """Execute ``prog(*args, **statics)``, possibly coalesced with
        compatible concurrent calls. Returns exactly what the direct
        call would."""
        if not self.enabled:
            return self._direct(prog, args, statics)
        if not multi:
            # stale slice-start snapshot? re-probe live before giving
            # up the hold — a peer admitted mid-slice is coalescible
            if self.inflight_fn is None or self.inflight_fn() <= 1:
                return self._direct(prog, args, statics)
        raw = getattr(prog, "__wrapped__", None)
        if raw is None:
            # no traceable inner function: coalescing would nest jits
            return self._direct(prog, args, statics)
        key = self._group_key(program_key, args, statics)
        if key is None:
            return self._direct(prog, args, statics)

        with self._cv:
            g = self._groups.get(key)
            if g is not None and not g.sealed and \
                    len(g.slots) < self.max_batch:
                idx = len(g.slots)
                g.slots.append((query_id, args))
                if len(g.slots) >= self.max_batch:
                    g.sealed = True
                    self._groups.pop(key, None)
                # wake the leader either way: it re-evaluates the
                # early-seal condition on every join
                self._cv.notify_all()
                leader = False
            else:
                g = _Group()
                g.slots.append((query_id, args))
                idx = 0
                self._groups[key] = g
                leader = True

        # park WITHOUT the device permit: peers must pass the
        # TpuSemaphore to reach this same coalescing point, so a
        # window-holder keeping its permit would starve its own group
        sem = _semaphore()
        had_permit = sem is not None and sem.holds()
        if had_permit:
            sem.release_if_necessary()
        try:
            if leader:
                t0 = time.perf_counter()
                deadline = t0 + self.window_s
                with self._cv:
                    while not g.sealed:
                        if self.inflight_fn is not None and \
                                len(g.slots) >= min(self.max_batch,
                                                    self.inflight_fn()):
                            # every inflight query is already in the
                            # group (or it is full): nobody else can
                            # join — seal now instead of burning the
                            # rest of the window
                            break
                        remaining = deadline - time.perf_counter()
                        if remaining <= 0:
                            break
                        self._cv.wait(remaining)
                    g.sealed = True
                    if self._groups.get(key) is g:
                        self._groups.pop(key, None)
                    self._held_s += time.perf_counter() - t0
                # back on the device for the group's launch
                if had_permit:
                    sem.acquire_if_necessary()
                    had_permit = False  # re-held; skip finally's path
                self._execute(key, g, prog, raw, statics)
            else:
                if not g.done.wait(_PARTICIPANT_TIMEOUT_S):
                    raise RuntimeError(
                        "micro-batch leader never executed "
                        "(participant timed out after "
                        f"{_PARTICIPANT_TIMEOUT_S:.0f}s)")
        finally:
            if had_permit:
                # participants (and a leader that errored before
                # re-acquiring) restore the permit the surrounding
                # exec believes it holds
                sem.acquire_if_necessary()
        if g.error is not None:
            raise g.error
        return g.results[idx]

    def warm_coalesced(self) -> dict:
        """Pre-compile the quantized K-way coalesced variants (2, 4,
        ..., maxBatch) of every program the registry recorded, with
        zero-filled operands at the observed bucket — a cold group
        forming mid-run must not stall its K participants on a trace +
        compile (measured: one lazy 2-way compile put a ~0.4 s outlier
        at p99 of an otherwise ~30 ms distribution). Called from
        QueryService.warmup()."""
        if not self.enabled or self.registry is None:
            return {"programs": 0, "variants": 0, "errors": 0}
        import jax

        sizes = []
        k = 2
        while k <= self.max_batch:
            sizes.append(k)
            k *= 2
        programs = variants = errors = 0
        for pkey, prog, zargs, statics in self.registry.replay_specs():
            raw = getattr(prog, "__wrapped__", None)
            if raw is None:
                continue
            key = self._group_key(pkey, zargs, statics)
            if key is None:
                continue
            programs += 1
            for k in sizes:
                fn = self._coalesced_program(key, k, raw, statics)
                try:
                    jax.block_until_ready(fn(tuple([zargs] * k)))
                    variants += 1
                except Exception as e:
                    from spark_rapids_tpu.memory.retry import \
                        is_oom_error

                    if is_oom_error(e):
                        raise  # OOM belongs to the retry ladder, not
                        #        the advisory error count (TPU401)
                    errors += 1
        return {"programs": programs, "variants": variants,
                "errors": errors}

    def stats(self) -> dict:
        with self._lock:
            launches = self._solo_launches + self._coalesced_launches
            return {
                "enabled": self.enabled,
                "window_ms": round(self.window_s * 1e3, 3),
                "max_batch": self.max_batch,
                "launches": launches,
                "coalesced_launches": self._coalesced_launches,
                "coalesced_participants": self._coalesced_participants,
                "mean_group_size": round(
                    self._coalesced_participants /
                    self._coalesced_launches, 3)
                if self._coalesced_launches else 0.0,
                "held_s": round(self._held_s, 4),
            }

    # -- internals ---------------------------------------------------------

    def _direct(self, prog, args, statics):
        with self._lock:
            self._solo_launches += 1
        return prog(*args, **statics)

    @staticmethod
    def _group_key(program_key, args, statics):
        """Compatibility key: program identity + operand tree structure
        + bucketed array shapes/dtypes. Non-array leaves become traced
        scalar operands in the coalesced program, so their VALUES may
        differ per participant — only their positions must line up
        (the treedef covers that)."""
        import jax.tree_util as tu

        try:
            leaves, treedef = tu.tree_flatten(args)
            sig = tuple(
                (tuple(leaf.shape), str(leaf.dtype))
                if getattr(leaf, "shape", None) is not None and
                getattr(leaf, "dtype", None) is not None
                else ("scalar", type(leaf).__name__)
                for leaf in leaves)
            skey = tuple(sorted((k, repr(v))
                                for k, v in statics.items()))
            return (program_key, treedef, sig, skey)
        except Exception as e:
            from spark_rapids_tpu.memory.retry import is_oom_error

            if is_oom_error(e):
                raise  # never classify an OOM as "unbatchable" (TPU401)
            return None

    def _coalesced_program(self, key, k: int, raw, statics):
        ckey = (key, k)
        with self._lock:
            fn = self._coalesced.get(ckey)
        if fn is not None:
            return fn
        import jax

        def coalesced(parts):
            # K inner programs inline into ONE executable; each
            # participant's outputs come back as its own pytree — the
            # split happens inside the compiled program, not as
            # per-participant slicing dispatches afterwards
            return tuple(raw(*p, **statics) for p in parts)

        inner = getattr(raw, "__name__", "program")
        coalesced.__name__ = coalesced.__qualname__ = \
            f"coalesced[{k}x]{inner}"
        fn = jax.jit(coalesced)
        with self._lock:
            if len(self._coalesced) >= 512:
                self._coalesced.clear()
            self._coalesced[ckey] = fn
        return fn

    def _execute(self, key, g: _Group, prog, raw, statics) -> None:
        """Leader-side: run the sealed group (one launch) and publish
        per-participant results."""
        try:
            k = len(g.slots)
            if k == 1:
                # nobody joined inside the window: plain direct call
                # through the original jitted program (compile reuse +
                # per-program telemetry naming), only the hold paid
                g.results = [self._direct(prog, g.slots[0][1],
                                          statics)]
            else:
                # group sizes QUANTIZE to powers of two (pad with the
                # leader's operands, discard the padding results): the
                # compiled K-way variant space shrinks from maxBatch-1
                # programs to log2(maxBatch), which is what lets
                # warm_coalesced() pre-compile ALL of them at startup
                # instead of a cold group eating a mid-run trace
                kq = _quantize_group(k, self.max_batch)
                fn = self._coalesced_program(key, kq, raw, statics)
                parts = [args for _qid, args in g.slots]
                parts += [parts[0]] * (kq - k)
                qids = [qid for qid, _args in g.slots
                        if qid is not None]
                tok = _disp.enter_coalesced(qids)
                try:
                    outs = fn(tuple(parts))
                finally:
                    _disp.exit_coalesced(tok)
                g.results = list(outs[:k])
                with self._lock:
                    self._coalesced_launches += 1
                    self._coalesced_participants += k
        except BaseException as e:
            g.error = e
        finally:
            g.done.set()
