"""Shape-bucket registry: executable sharing as a measured, warmable
property.

Stage programs are jitted over BUCKETED operand shapes (ops/buckets
pads every device column to a geometric-ladder capacity and carries the
true row count as a masked device scalar), so two tenants running the
same query template compile NOTHING after the first — their operand
shapes collide on the same ladder rung and XLA's executable cache plus
the chain-key program cache (expressions/compiler) hand back the same
compiled program. This module makes that sharing:

- **observable**: every service-path stage dispatch records its
  (program key, bucket shape); ``stats()`` reports distinct programs,
  distinct (program, bucket) executables, and the observation/compile
  split — surfaced through ``utils/progcache.stats()`` next to the
  chain-key hit rate the fence asserts on;
- **warmable**: ``warm()`` replays each recorded program over the
  ladder rungs at/below its observed bucket with zero-filled operands,
  so a service that registered its query templates at startup
  (``rapids.tpu.service.warmup.enabled``) compiles the whole ladder
  before the first tenant request arrives (ROADMAP item 2's AOT-warm).
"""
from __future__ import annotations

import threading
from spark_rapids_tpu.utils import lockorder
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu.ops import buckets as _ladder


class _ProgramSpec:
    """One recorded (program, operand-shape) point: enough to replay
    the call at other ladder rungs. ``stream_cap`` is the leading dim
    of the stage's streaming operands — the axis the ladder buckets;
    only the first ``n_stream_leaves`` leaves (the caller's streaming
    args) resize on replay, so a build-side operand that merely
    COINCIDES with the stream capacity keeps its recorded shape."""

    __slots__ = ("prog", "treedef", "leaf_spec", "statics",
                 "stream_cap", "n_stream_leaves")

    def __init__(self, prog, treedef, leaf_spec, statics, stream_cap,
                 n_stream_leaves):
        self.prog = prog
        self.treedef = treedef
        self.leaf_spec = leaf_spec    # [("arr", shape, dtype) | ("val", v)]
        self.statics = statics
        self.stream_cap = stream_cap
        self.n_stream_leaves = n_stream_leaves


class ShapeBucketRegistry:
    """Thread-safe observation log + warm replayer. Bounded: a
    long-lived service must not pin one spec per program x bucket
    forever (specs hold jitted callables, which hold device constants);
    the observation COUNTS stay exact past the bound."""

    MAX_SPECS = 256

    def __init__(self):
        self._lock = lockorder.make_lock("service.batching.buckets")
        #: (program_key, bucket) -> observation count
        self._seen: Dict[Tuple, int] = {}
        #: program_key -> replayable spec at the largest observed bucket
        self._specs: Dict[Tuple, _ProgramSpec] = {}
        self._warmed: set = set()     # (program_key, bucket) replayed
        self._warm_compiles = 0

    # -- observation (hot path: one dict bump per stage dispatch) ---------

    def record(self, program_key, prog, args, statics,
               stream_args: int = 1) -> None:
        """Log a service-path stage dispatch. ``args`` is the program's
        positional operand pytree; the bucket is the leading dimension
        of its first array leaf (the stage's streaming capacity).
        ``stream_args``: how many leading positional args carry the
        STREAMING operands — only their leaves resize on warm replay."""
        import jax.tree_util as tu

        leaves, treedef = tu.tree_flatten(args)
        n_stream = len(tu.tree_flatten(tuple(args[:stream_args]))[0])
        stream_cap = None
        leaf_spec = []
        for leaf in leaves:
            shape = getattr(leaf, "shape", None)
            if shape is not None and getattr(leaf, "dtype", None) \
                    is not None:
                if stream_cap is None and len(shape) >= 1:
                    stream_cap = int(shape[0])
                leaf_spec.append(("arr", tuple(shape), leaf.dtype))
            else:
                leaf_spec.append(("val", leaf))
        if stream_cap is None:
            return
        key = (program_key, stream_cap)
        with self._lock:
            self._seen[key] = self._seen.get(key, 0) + 1
            keep = program_key not in self._specs or \
                self._specs[program_key].stream_cap < stream_cap
            if keep and len(self._specs) < self.MAX_SPECS:
                self._specs[program_key] = _ProgramSpec(
                    prog, treedef, leaf_spec, statics, stream_cap,
                    n_stream)

    # -- warm replay -------------------------------------------------------

    @staticmethod
    def _zero_args(spec: _ProgramSpec, rung: Optional[int] = None):
        """Rebuild the recorded operand pytree with zero-filled arrays;
        STREAMING array leaves (the first ``n_stream_leaves``, at the
        stream capacity) resize to ``rung`` (None keeps the observed
        bucket); build-side leaves and scalar leaves keep their
        recorded shapes/values."""
        import jax.numpy as jnp

        leaves = []
        for i, (kind, *info) in enumerate(spec.leaf_spec):
            if kind == "val":
                leaves.append(info[0])
                continue
            shape, dtype = info
            if rung is not None and i < spec.n_stream_leaves and \
                    shape and shape[0] == spec.stream_cap:
                shape = (rung,) + tuple(shape[1:])
            leaves.append(jnp.zeros(shape, dtype=dtype))
        return spec.treedef.unflatten(leaves)

    def replay_specs(self):
        """[(program_key, prog, zero_args_at_observed_bucket, statics)]
        for every recorded program — the micro-batcher pre-compiles its
        K-way coalesced variants from these at warmup."""
        with self._lock:
            specs = list(self._specs.items())
        return [(key, s.prog, self._zero_args(s), s.statics)
                for key, s in specs]

    def warm(self, max_rung: Optional[int] = None) -> dict:
        """Replay every recorded program over the ladder rungs at/below
        its observed bucket (bounded by ``max_rung``) with zero-filled
        operands: each replay forces the XLA compile for that
        (program, bucket) executable, so the compiles land at startup
        instead of under the first tenant whose batch hits the rung.
        Returns {"programs", "replays", "errors", "rungs_skipped"}
        (rungs_skipped: rungs above ``max_rung`` NOT replayed — a
        single-query bench caps the ladder at its input's bucket)."""
        with self._lock:
            specs = list(self._specs.items())
        replays = errors = skipped = 0
        for program_key, spec in specs:
            rungs = _ladder.ladder_rungs(spec.stream_cap)
            for rung in rungs:
                if max_rung is not None and rung > max_rung:
                    skipped += 1
                    continue
                mark = (program_key, rung)
                with self._lock:
                    if mark in self._warmed:
                        continue
                    if rung == spec.stream_cap and \
                            (program_key, rung) in self._seen:
                        # organically observed = already compiled
                        self._warmed.add(mark)
                        continue
                args = self._zero_args(spec, rung)
                try:
                    out = spec.prog(*args, **spec.statics)
                    # block so the compile definitely happened before
                    # warmup reports done (async dispatch would defer
                    # it to the first real request)
                    import jax

                    jax.block_until_ready(out)
                    replays += 1
                    # mark only on SUCCESS: a transiently-failed replay
                    # must stay retryable by the next warmup() call,
                    # not be silently skipped forever (worst case of a
                    # concurrent double-warm is one duplicate compile)
                    with self._lock:
                        self._warmed.add(mark)
                except Exception as e:
                    from spark_rapids_tpu.memory.retry import \
                        is_oom_error

                    if is_oom_error(e):
                        # device OOM on a ladder rung is not a bad
                        # program — it must reach the retry ladder /
                        # admission, not be counted away (tpulint
                        # TPU401)
                        raise
                    # a program whose trace depends on operand VALUES
                    # (not shapes) may reject zeros; warmup is advisory
                    errors += 1
        return {"programs": len(specs), "replays": replays,
                "errors": errors, "rungs_skipped": skipped}

    # -- stats -------------------------------------------------------------

    def stats(self) -> dict:
        """Sharing effectiveness per the bucket discipline: every
        observation past the first of a (program, bucket) pair reused a
        compiled executable instead of creating one."""
        with self._lock:
            observations = sum(self._seen.values())
            executables = len(self._seen)
            programs = len({k for k, _b in self._seen})
            warmed = len(self._warmed)
        reuses = observations - executables
        return {
            "programs": programs,
            "bucket_executables": executables,
            "observations": observations,
            "bucket_reuses": max(reuses, 0),
            "bucket_reuse_rate": round(reuses / observations, 4)
            if observations else 0.0,
            "warmed": warmed,
            "ladder_growth": _ladder.ladder_growth(),
        }

    def reset(self) -> None:
        with self._lock:
            self._seen.clear()
            self._specs.clear()
            self._warmed.clear()


#: process-global registry, mirroring the process-global program caches
#: it measures (two services in one process share executables, so they
#: share the ledger too)
_REGISTRY = ShapeBucketRegistry()


def get_registry() -> ShapeBucketRegistry:
    return _REGISTRY
