"""Sustained-load SLO harness: open-loop offered-QPS sweeps.

Closed-loop benchmarks (submit N, wait for N) measure throughput but
hide latency pathologies — a closed loop self-throttles exactly when
the service saturates. A SERVING SLO is defined the other way around:
arrivals are OPEN-LOOP (a Poisson process at an offered rate that does
not slow down because the service is busy), and the question is what
p50/p99 queue+run latency and shed rate the service sustains at that
rate. This module is the harness behind
``benchmarks/service_bench.py --open-loop`` and the
``scripts/slo_check.py`` fence (ROADMAP item 4: p99 at N=64 concurrent
q1/q6 within 3x serial single-query time — a RATIO, so the criterion
is meaningful on any backend, CPU CI included).
"""
from __future__ import annotations

import math
import time
from typing import Callable, List, Optional, Sequence


def poisson_gaps(rate_qps: float, n: int, seed: int = 7) -> List[float]:
    """Inter-arrival gaps (seconds) of a Poisson process at
    ``rate_qps``, deterministic per seed (exponential inversion —
    the harness must replay identically across runs)."""
    import numpy as np

    if rate_qps <= 0:
        return [0.0] * n
    rng = np.random.default_rng(seed)
    u = rng.random(n)
    return list(-np.log1p(-u) / rate_qps)


def percentile(values: Sequence[float], q: float) -> float:
    """Nearest-rank percentile on a copy (q in [0, 100])."""
    if not values:
        return 0.0
    vals = sorted(values)
    rank = max(int(math.ceil(q / 100.0 * len(vals))) - 1, 0)
    return float(vals[min(rank, len(vals) - 1)])


def run_open_loop(service, make_query: Callable[[int], object],
                  offered_qps: float, n_queries: int,
                  tenants: int = 4, seed: int = 7,
                  deadline_s: Optional[float] = None,
                  result_timeout_s: float = 600.0) -> dict:
    """Submit ``n_queries`` fresh query instances at Poisson arrivals
    of ``offered_qps`` (round-robin over ``tenants`` submitter keys),
    then drain. Returns the per-rate record: latency percentiles over
    queue/run/total, shed + failure counts, achieved vs offered rate.

    ``make_query(i)`` must return a FRESH plan/DataFrame per call (plan
    instances are single-use through the override planner)."""
    from spark_rapids_tpu.service.types import (OutOfCoreRejected,
                                                ServiceOverloaded)

    gaps = poisson_gaps(offered_qps, n_queries, seed)
    handles = []
    shed = 0
    t0 = time.perf_counter()
    next_at = t0
    for i, gap in enumerate(gaps):
        next_at += gap
        delay = next_at - time.perf_counter()
        if delay > 0:
            time.sleep(delay)
        try:
            handles.append(service.submit(
                make_query(i), tenant=f"tenant{i % max(tenants, 1)}",
                deadline=deadline_s))
        except (ServiceOverloaded, OutOfCoreRejected):
            # open loop: a shed arrival — queue-limit OR whale-policy
            # rejection — is a data point, not a retry; that IS the
            # backpressure signal the sweep reports
            shed += 1
    submit_wall = time.perf_counter() - t0

    queue_s: List[float] = []
    run_s: List[float] = []
    total_s: List[float] = []
    failed = 0
    for h in handles:
        try:
            h.result(timeout=result_timeout_s)
        except Exception:
            failed += 1
            continue
        info = h.info()
        qt = info["queue_time_s"] or 0.0
        rt = info["run_time_s"] or 0.0
        queue_s.append(qt)
        run_s.append(rt)
        total_s.append(qt + rt)
    wall = time.perf_counter() - t0
    done = len(total_s)
    return {
        "offered_qps": round(offered_qps, 4),
        "achieved_qps": round(done / wall, 4) if wall > 0 else 0.0,
        "queries": n_queries,
        "done": done,
        "shed": shed,
        "failed": failed,
        "shed_rate": round(shed / n_queries, 4) if n_queries else 0.0,
        "submit_wall_s": round(submit_wall, 4),
        "wall_s": round(wall, 4),
        "latency_s": _latency_block(queue_s, run_s, total_s),
    }


def _latency_block(queue_s, run_s, total_s) -> dict:
    def pcts(vals):
        return {
            "p50": round(percentile(vals, 50), 4),
            "p95": round(percentile(vals, 95), 4),
            "p99": round(percentile(vals, 99), 4),
            "max": round(max(vals), 4) if vals else 0.0,
            "mean": round(sum(vals) / len(vals), 4) if vals else 0.0,
        }
    return {"queue": pcts(queue_s), "run": pcts(run_s),
            "total": pcts(total_s)}


def slo_block(sweep: List[dict], serial_s: Optional[float],
              ratio: float = 3.0) -> dict:
    """The ``SLO_r*``-style summary the runner embeds: the sweep plus
    the ROADMAP fence criterion evaluated at the highest offered rate
    the service sustained (shed rate < 50%) — p99 total (queue+run)
    latency within ``ratio`` x the serial single-query time."""
    block = {"sweep": sweep, "serial_single_query_s": serial_s,
             "ratio_threshold": ratio}
    sustained = [e for e in sweep if e["shed_rate"] < 0.5 and e["done"]]
    if sustained and serial_s:
        at = max(sustained, key=lambda e: e["offered_qps"])
        p99 = at["latency_s"]["total"]["p99"]
        block["criterion"] = {
            "at_offered_qps": at["offered_qps"],
            "p99_total_s": p99,
            "p99_over_serial": round(p99 / serial_s, 3),
            "pass": bool(p99 <= ratio * serial_s),
        }
    return block
