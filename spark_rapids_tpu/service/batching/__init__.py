"""Cross-tenant micro-batching serving layer.

The inference-serving batching playbook applied to SQL stage programs
(ROADMAP item 4 — "make concurrency fast"):

- ``buckets.py``: the shape-bucket registry. Batch capacities already
  ride a geometric ladder (ops/buckets); the registry pins the
  consequence — concurrent queries from different tenants hit the SAME
  compiled executable by construction — by recording every
  (stage program, bucket shape) the service dispatches, exposing the
  per-bucket sharing stats through ``utils/progcache.stats()``, and
  replaying recorded programs across the ladder rungs for AOT warmup
  (``rapids.tpu.service.warmup.*``).
- ``microbatch.py``: the micro-batcher. A stage dispatch holds for a
  bounded window (``rapids.tpu.service.batching.windowMs``) and
  compatible same-bucket stage slices from different queries coalesce
  into ONE physical program launch (per-query row-count scalars mask
  each participant's padding); results split back out inside the same
  compiled program and dispatch telemetry attributes the launch once
  globally and fractionally per participant.
- ``slo.py``: the sustained-load harness. Open-loop (Poisson-arrival)
  offered-QPS sweeps with p50/p95/p99 queue+run latency and shed rate,
  feeding ``benchmarks/service_bench.py`` and the
  ``scripts/slo_check.py`` fence.
"""
from spark_rapids_tpu.service.batching.buckets import (  # noqa: F401
    ShapeBucketRegistry, get_registry)
from spark_rapids_tpu.service.batching.microbatch import (  # noqa: F401
    MicroBatcher)

__all__ = ["ShapeBucketRegistry", "get_registry", "MicroBatcher"]
