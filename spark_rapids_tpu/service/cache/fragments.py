"""Fragment cache: materialized stage outputs as spillable citizens.

A *fragment* is the output of a cacheable stage subplan (aggregate /
join / sort / window roots — the logical analogues of the stage
breakers ``plan/optimizer.cut_stages`` cuts on). The manager's graft
pass (manager.CacheManager.graft_fragments) rewrites submitted plans:

- a READY entry replaces its subplan with a **serve-mode**
  ``CachedFragmentNode`` leaf — no children, no device work; the
  planner converts it to ``FragmentServeExec`` which streams the
  stored ``SpillableBatch``es (auto-unspilling through host/disk
  tiers exactly like shuffle blocks),
- a first miss wraps the subplan in a **capture-mode** node —
  ``FragmentCaptureExec`` drains the child once under a
  materialize-once barrier (the same ``execs.cache.materialize``
  plan-barrier rank CacheHolder uses) and publishes the entry.

Safety properties the tests fence:

- a READY entry grafted as a serve leaf is **pinned at graft time**
  and unpinned only when the query finalizes, so LRU/TTL eviction can
  never close its handles while the query waits in the admission
  queue; if parts are somehow gone at execute time, ``_serve`` raises
  :class:`FragmentUnavailable` rather than yielding an empty (wrong)
  batch;
- batches register under the entry's OWN owner tag ``("svc-cache",
  id)`` — the scheduler's post-terminal owner sweep for the capturing
  query must not reap cache entries that outlive it;
- an OOM while materializing degrades to cache-off: the half-built
  entry is dropped and the child subtree re-executes streaming —
  never a wrong answer (PR 6 retry-ladder contract);
- publish revalidates the subplan fingerprint against CURRENT snapshot
  versions, so a table bumped mid-materialization aborts the entry
  instead of publishing stale data under a fresh-looking key;
- a key already PENDING in another query is NOT waited on (a worker
  slice blocking on another query's barrier could deadlock at
  maxConcurrent=1) and NOT double-captured — the second query simply
  compiles the plain subtree.
"""
from __future__ import annotations

import itertools
import time
from typing import Dict, Iterator, List, Optional

from spark_rapids_tpu.columnar.batch import ColumnarBatch, Schema
from spark_rapids_tpu.execs.base import TpuExec, timed
from spark_rapids_tpu.memory import priorities
from spark_rapids_tpu.memory.catalog import set_buffer_owner
from spark_rapids_tpu.memory.fault_injection import get_injector
from spark_rapids_tpu.memory.retry import is_oom_error
from spark_rapids_tpu.memory.spillable import SpillableBatch
from spark_rapids_tpu.plan.nodes import PlanNode
from spark_rapids_tpu.utils import lockorder

#: entry lifecycle: PENDING (registered, not yet materialized) ->
#: READY (published, servable) | ABORTED (failed/evicted/invalidated)
PENDING, READY, ABORTED = "pending", "ready", "aborted"

#: fault-injection site armed by tests to OOM a materialization
MATERIALIZE_SITE = "cache.fragment.materialize"

_ENTRY_IDS = itertools.count(1)


class FragmentUnavailable(RuntimeError):
    """A serve leaf reached execution but its entry's parts are gone
    (evicted/aborted). Grafting pins READY entries for the query's
    whole lifetime precisely so this cannot happen — raising (instead
    of yielding an empty batch) turns any future pinning bug into a
    loud failure, never a silently wrong answer."""


class FragmentEntry:
    """One cached fragment. ``state``/``bytes``/``pins``/``last_used``
    are guarded by the manager's ``service.cache.state`` lock; the
    per-entry materialize barrier only serializes capture itself."""

    def __init__(self, key, subtree: PlanNode, schema: Schema,
                 reads: tuple, est_rows: Optional[int], manager):
        self.key = key
        #: the ORIGINAL subplan (pre-graft) — publish re-fingerprints
        #: it to detect a snapshot bump that happened mid-run
        self.subtree = subtree
        self.schema = schema
        self.reads = reads
        self.est_rows = est_rows
        self.manager = manager
        self.entry_id = next(_ENTRY_IDS)
        self.state = PENDING
        self.bytes = 0
        self.pins = 0
        #: TTL expired while pinned: unservable to NEW grafts, but the
        #: handles stay open until the last unpin evicts it (closing a
        #: pinned entry under a mid-iteration server is use-after-close)
        self.stale = False
        self.hits = 0
        self.created_at = time.perf_counter()
        self.last_used = self.created_at
        self._barrier = lockorder.make_lock("execs.cache.materialize")
        self._parts: Optional[Dict[int, List[SpillableBatch]]] = None

    @property
    def owner_tag(self):
        """Catalog buffer-owner tag. NOT the capturing query's tag: the
        scheduler sweeps a terminal query's owned buffers, and a cache
        entry must outlive the query that happened to fill it."""
        return ("svc-cache", self.entry_id)

    def num_partitions(self) -> int:
        parts = self._parts
        return max(len(parts), 1) if parts else 1

    def close_parts(self) -> None:
        parts, self._parts = self._parts, None
        _close_handles(parts or {})


def _close_handles(parts: Dict[int, List[SpillableBatch]]) -> None:
    for handles in parts.values():
        for h in handles:
            h.close()


def _serve(entry: FragmentEntry, schema: Schema,
           partition: int) -> Iterator[ColumnarBatch]:
    """Yield an entry's stored batches for one partition, pinned for
    the duration so eviction cannot close handles mid-iteration. A
    serve leaf additionally holds a graft-time pin for the query's
    whole queued+running life, so the READY check below cannot fail
    for a grafted plan — it guards against pinning bugs by raising
    rather than fabricating an empty (wrong) result."""
    entry.manager.fragment_pin(entry)
    try:
        parts = entry._parts
        if entry.state != READY or parts is None:
            raise FragmentUnavailable(
                f"cached fragment {entry.entry_id} is {entry.state} "
                f"with no stored parts — entry evicted while a plan "
                f"referencing it was live (missing pin?)")
        handles = parts.get(partition, ())
        if not handles:
            # a legitimately empty stored partition (captured zero
            # batches there), NOT a closed entry
            yield ColumnarBatch.empty(schema)
            return
        for h in handles:
            with h.acquired() as batch:
                yield batch
    finally:
        entry.manager.fragment_unpin(entry)


class CachedFragmentNode(PlanNode):
    """Graft marker. Serve mode has no children (a cached-scan leaf);
    capture mode wraps the original subtree as its only child."""

    def __init__(self, entry: FragmentEntry,
                 child: Optional[PlanNode] = None):
        super().__init__([child] if child is not None else [])
        self.entry = entry

    def output_schema(self) -> Schema:
        return self.entry.schema

    def plan_row_estimate(self) -> Optional[int]:
        # the optimizer's estimate_rows hook: a serve leaf knows the
        # cardinality of the subtree it replaced (estimated at graft)
        return self.entry.est_rows

    def describe(self) -> str:
        mode = "capture" if self.children else "serve"
        return f"CachedFragment[{mode}, {self.entry.state}]"


class FragmentServeExec(TpuExec):
    """Serve a READY fragment: stream its spillable batches. Acquiring
    a handle unspills it back to device transparently (the disk-tier
    round trip the tests fence bit-exact)."""

    def __init__(self, node: CachedFragmentNode):
        super().__init__([], node.entry.schema)
        self.node = node

    @property
    def num_partitions(self) -> int:
        return self.node.entry.num_partitions()

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        return timed(self, _serve(self.node.entry, self.schema,
                                  partition))


class FragmentCaptureExec(TpuExec):
    """First execution of a missed fragment: drain the child subtree
    once into spillable batches, publish, then serve. On any failure
    the entry aborts and execution degrades to streaming the child."""

    def __init__(self, node: CachedFragmentNode, child: TpuExec):
        super().__init__([child], child.schema)
        self.node = node

    @property
    def num_partitions(self) -> int:
        entry = self.node.entry
        if entry.state == READY and entry._parts is not None:
            return entry.num_partitions()
        return self.children[0].num_partitions

    def execute(self, partition: int = 0) -> Iterator[ColumnarBatch]:
        def it():
            entry = self.node.entry
            # pin-if-ready closes the publish->serve window: unlike a
            # serve LEAF (pinned since graft), a capture node's entry
            # is evictable the instant publish makes it READY, so
            # re-check under the pin and degrade on loss
            if self._capture(entry) and \
                    entry.manager.fragment_pin_if_ready(entry):
                try:
                    yield from _serve(entry, self.schema, partition)
                finally:
                    entry.manager.fragment_unpin(entry)
            else:
                # cache-off degrade: deterministic re-execution of the
                # plain subtree — correctness never depends on capture
                for b in self.children[0].execute(partition):
                    yield b
        return timed(self, it())

    def _capture(self, entry: FragmentEntry) -> bool:
        """Materialize-once; True iff the entry is servable. Runs under
        the per-entry plan barrier: concurrent partitions of the SAME
        query serialize here, then all serve from the stored parts."""
        with entry._barrier:
            if entry.state == READY:
                return True
            if entry.state != PENDING:
                return False
            child = self.children[0]
            parts: Dict[int, List[SpillableBatch]] = {}
            prev = set_buffer_owner(entry.owner_tag)
            try:
                injector = get_injector()
                for p in range(child.num_partitions):
                    handles: List[SpillableBatch] = []
                    for b in child.execute(p):
                        injector.maybe_inject(MATERIALIZE_SITE)
                        # defer_count: counting rows here would force a
                        # host sync per batch (tpulint TPU1xx) for a
                        # number serving never needs eagerly
                        handles.append(SpillableBatch(
                            b, priorities.CACHED_FRAGMENT_PRIORITY,
                            defer_count=True))
                    parts[p] = handles
            except Exception as e:
                _close_handles(parts)
                if not is_oom_error(e):
                    entry.manager.fragment_aborted(entry, oom=False)
                    raise
                # OOM while filling the cache degrades to cache-off,
                # never to a wrong answer: drop the partial entry and
                # let the caller stream the child fresh
                entry.manager.fragment_aborted(entry, oom=True)
                return False
            except BaseException:
                # scheduler interrupts (cancel/deadline) pass through;
                # the half-built entry must not linger half-registered
                _close_handles(parts)
                entry.manager.fragment_aborted(entry, oom=False)
                raise
            finally:
                set_buffer_owner(prev)
            entry._parts = parts
            return entry.manager.publish_fragment(entry)
