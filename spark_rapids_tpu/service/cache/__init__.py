"""Semantic result & fragment cache (ROADMAP item 4).

The engine already applies identity discipline to *programs* — compiled
plans persist in progcache, shape-bucketed executables are shared across
tenants (service/batching) — but recomputed every *result* from
scratch. Production SQL traffic is dominated by repeated dashboard
queries over slowly-changing data; this package extends the same
identity discipline to data, with three tiers of reuse:

- ``result_cache``: exact repeat queries served from a host-side result
  cache keyed on (canonical plan fingerprint, table snapshot versions)
  — zero device dispatches.
- ``fragments``: materialized stage outputs keyed on (stage subplan
  fingerprint, input snapshot versions), grafted into later plans as
  cached-scan leaves so shared subplans across queries and tenants
  compute once. Entries are first-class spillable citizens — stored as
  ``SpillableBatch``es with owner tagging, evicted through the
  device→host→disk tiers by the existing priority machinery, charged
  against admission's device budget.
- ``snapshots``: table snapshot versioning — every cache entry records
  the (source identity, version) pairs it read, so invalidation is a
  version comparison, never a staleness guess.

``manager.CacheManager`` (one per ``QueryService``) ties the tiers
together: lookup/publish hooks, single-flight coordination so N
concurrent identical misses compute once, a shared LRU byte budget,
and stats. Plan fingerprinting lives in ``plan/fingerprint.py``.
"""
