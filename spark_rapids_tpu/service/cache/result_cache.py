"""Exact result cache: final assembled frames keyed on plan identity.

An entry maps ``("result", plan_fingerprint.key)`` — the canonical plan
tree key WITH embedded source snapshot versions — to the pandas frame a
prior query assembled. Because the snapshot version participates in the
key, invalidation is free: bumping a table version makes every
dependent key unreachable, and the orphaned entries age out of the LRU
(or fall to TTL) without any scan-and-invalidate pass.

This container is deliberately NOT self-locking: ``CacheManager``
serializes every access under its single ``service.cache.state`` lock,
and splitting that into a second lock here would only add a rank to the
hierarchy for zero concurrency (all operations are dict moves).
"""
from __future__ import annotations

import time
from collections import OrderedDict
from typing import Optional, Tuple


class ResultEntry:
    """One cached final result."""

    __slots__ = ("key", "frame", "bytes", "reads", "created_at",
                 "last_used", "hits")

    def __init__(self, key, frame, nbytes: int, reads: tuple):
        self.key = key
        self.frame = frame
        self.bytes = nbytes
        self.reads = reads
        self.created_at = time.perf_counter()
        self.last_used = self.created_at
        self.hits = 0


class ResultCache:
    """LRU over ``OrderedDict`` (front = coldest). Frames are stored and
    served as copies so callers can mutate what they get back."""

    def __init__(self):
        self._entries: "OrderedDict[Tuple, ResultEntry]" = OrderedDict()
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.evicted = 0

    def __len__(self) -> int:
        return len(self._entries)

    def get(self, key, now: float, ttl_s: float,
            count: bool = True) -> Optional[ResultEntry]:
        e = self._entries.get(key)
        if e is not None and ttl_s > 0 and now - e.created_at > ttl_s:
            # expired: treat as a miss and reclaim immediately
            self.pop(key)
            self.evicted += 1
            e = None
        if e is None:
            if count:
                self.misses += 1
            return None
        if count:
            self.hits += 1
            e.hits += 1
        e.last_used = now
        self._entries.move_to_end(key)
        return e

    def put(self, entry: ResultEntry) -> None:
        old = self._entries.pop(entry.key, None)
        if old is not None:
            self.bytes -= old.bytes
        self._entries[entry.key] = entry
        self.bytes += entry.bytes

    def pop(self, key) -> Optional[ResultEntry]:
        e = self._entries.pop(key, None)
        if e is not None:
            self.bytes -= e.bytes
        return e

    def coldest(self) -> Optional[ResultEntry]:
        """Peek the LRU-front entry (eviction candidate)."""
        if not self._entries:
            return None
        return next(iter(self._entries.values()))

    def clear(self) -> None:
        self._entries.clear()
        self.bytes = 0
