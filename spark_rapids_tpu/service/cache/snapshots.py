"""Table snapshot versioning: cache invalidation as version comparison.

Every cache entry (service/cache) records the ``(source identity,
snapshot version)`` pairs its plan read; a lookup recomputes them and
misses on any difference. Nothing is ever "expired by guess" — a cached
result is served iff the data it read is provably the data a fresh run
would read.

Identity and version resolve per source kind:

- **file sources** (io/filesrc.FileSourceBase): identity is the sorted
  path list + projected columns + pushed-down filters; version is the
  per-file ``(mtime_ns, size)`` stat vector — rewriting or appending to
  a file changes it with no bookkeeping required — plus the manual
  bump counter below.
- **custom sources** implementing the optional ``cache_identity()`` /
  ``cache_version()`` protocol: whatever they return (must be hashable).
- **everything else** (``InMemorySource``, test gate sources, ...):
  UNKEYABLE — ``source_identity`` returns None and every plan over the
  source bypasses the cache entirely. Ad-hoc host arrays have no stable
  name, and two submissions of the same object must stay two
  computations unless the source opts in.

Manual bumps (``bump``/``bump_plan``) increment a monotonic counter on
the source object itself — ``Session.create_temp_view`` replacing a
view and ``Session.bump_table_version`` route through here, so a
replaced view's old cached results are never served even when the
underlying files did not move.
"""
from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.utils import lockorder

#: guards the per-source manual version counter (leaf lock: bump holds
#: nothing else)
_lock = lockorder.make_lock("service.cache.snapshots")


def bump(source) -> int:
    """Increment ``source``'s manual snapshot version; returns the new
    version. Any cache entry keyed on the old version misses forever."""
    with _lock:
        v = int(getattr(source, "_snap_version", 0)) + 1
        source._snap_version = v
        return v


def bump_plan(target) -> int:
    """Bump every DataSource reachable from ``target`` (a DataSource, a
    PlanNode tree, or a DataFrame-like with ``._plan``). Returns the
    number of sources bumped."""
    from spark_rapids_tpu.plan import nodes as pn

    plan = getattr(target, "_plan", target)
    if isinstance(plan, pn.DataSource):
        bump(plan)
        return 1
    n = 0
    if isinstance(plan, pn.PlanNode):
        for node in pn.walk(plan):
            src = getattr(node, "source", None)
            if isinstance(src, pn.DataSource):
                bump(src)
                n += 1
    return n


def source_identity(source) -> Optional[tuple]:
    """Stable content-addressing identity of a DataSource, or None when
    the source is unkeyable (see module docstring)."""
    fn = getattr(source, "cache_identity", None)
    if callable(fn):
        return ("#custom", type(source).__module__,
                type(source).__qualname__, fn())
    from spark_rapids_tpu.io.filesrc import FileSourceBase

    if isinstance(source, FileSourceBase):
        filters = tuple(tuple(f) for f in (source.filters or ()))
        columns = tuple(source.columns) if source.columns else None
        return ("#file", type(source).__qualname__,
                tuple(source.paths), columns, filters)
    return None


def file_versions(paths) -> Optional[tuple]:
    """Per-file ``(mtime_ns, size)`` stat vector for an explicit path
    list, or None when any file vanished — the same no-guess contract
    as ``source_version``, reusable for sub-source keys (the scan
    cache versions individual splits with this)."""
    import os

    stats = []
    for p in paths:
        try:
            st = os.stat(p)
        except OSError:
            return None  # a vanished file: never serve cached data
        stats.append((st.st_mtime_ns, st.st_size))
    return tuple(stats)


def source_version(source) -> Optional[tuple]:
    """Snapshot version of a keyable DataSource as of NOW, or None when
    the version cannot be established (then nothing over this source is
    cached — staleness must never be a guess)."""
    manual = int(getattr(source, "_snap_version", 0))
    fn = getattr(source, "cache_version", None)
    if callable(fn):
        return ("#v", manual, fn())
    from spark_rapids_tpu.io.filesrc import FileSourceBase

    if isinstance(source, FileSourceBase):
        stats = file_versions(source.paths)
        if stats is None:
            return None
        return ("#v", manual, stats)
    return None
