"""CacheManager: one registry uniting result + fragment tiers.

Every QueryService owns one manager. ``submit()`` consults it twice:

1. **result tier** — ``result_key(plan)`` fingerprints the whole plan
   (canonical tree key + source snapshot versions); a hit serves the
   stored frame with ZERO device work, and a non-terminal leader for
   the same key absorbs concurrent identical misses as *followers*
   (single-flight: N dashboards refreshing together compute once);
2. **fragment tier** — ``graft_fragments(plan)`` rewrites the plan,
   replacing READY cacheable stage roots with serve leaves and
   wrapping first-seen ones in capture nodes (see
   :mod:`spark_rapids_tpu.service.cache.fragments`).

Both tiers share one byte budget (``rapids.tpu.service.cache.maxBytes``)
and one LRU clock, and both revalidate their fingerprint at PUBLISH
time — a table version bumped mid-run aborts the publish instead of
installing stale data under a fresh-looking key.

Locking: one ``service.cache.state`` lock (rank 76) guards the
registries and counters. Lookups arrive under the service lock (20),
fragment publishes arrive inside a materialize barrier (planBarrier,
rank <=38), and eviction closes spillable handles through the catalog
(rank 100) — the rank sits between those bands so every path nests
cleanly; see utils/lockorder.py.
"""
from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from spark_rapids_tpu import config as cfg
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.memory.retry import is_oom_error
from spark_rapids_tpu.plan import nodes as pn
from spark_rapids_tpu.plan.fingerprint import plan_fingerprint
from spark_rapids_tpu.service.cache import fragments
from spark_rapids_tpu.service.cache.result_cache import (ResultCache,
                                                         ResultEntry)
from spark_rapids_tpu.utils import lockorder

#: stage roots worth materializing: the logical analogues of the
#: pipeline breakers cut_stages cuts on — their output is small relative
#: to the work that produced it, which is exactly when caching pays
FRAGMENT_CANDIDATES = (pn.AggregateNode, pn.JoinNode, pn.SortNode,
                       pn.WindowNode)


class CacheManager:
    def __init__(self, conf: Optional[RapidsConf] = None):
        conf = conf if isinstance(conf, RapidsConf) else RapidsConf(conf)
        master = conf.get(cfg.SERVICE_CACHE_ENABLED)
        self.enabled = master
        self.result_enabled = master and conf.get(cfg.SERVICE_CACHE_RESULT)
        self.fragment_enabled = master and \
            conf.get(cfg.SERVICE_CACHE_FRAGMENT)
        self.max_bytes = conf.get(cfg.SERVICE_CACHE_MAX_BYTES)
        self.ttl_s = conf.get(cfg.SERVICE_CACHE_TTL)
        self._lock = lockorder.make_lock("service.cache.state")
        self._results = ResultCache()
        self._fragments: Dict[Tuple, fragments.FragmentEntry] = {}
        self._frag_bytes = 0
        self._frag_hits = 0
        self._frag_misses = 0
        self._frag_published = 0
        self._frag_aborted = 0
        self._frag_evicted = 0
        self._oom_degraded = 0
        self._followers = 0

    # -- result tier --------------------------------------------------

    def result_key(self, plan: pn.PlanNode) -> Optional[Tuple]:
        """Cache key for a whole plan, or None when any leaf is
        unkeyable (ad-hoc in-memory frames, gated test sources)."""
        if not self.result_enabled:
            return None
        fp = plan_fingerprint(plan)
        if fp is None:
            return None
        return ("result", fp.key)

    def lookup_result(self, key, count: bool = True):
        """The cached frame (a private copy) or None."""
        with self._lock:
            e = self._results.get(key, time.perf_counter(), self.ttl_s,
                                  count=count)
            if e is None:
                return None
            return e.frame.copy()

    def publish_result(self, key, plan: pn.PlanNode, frame) -> bool:
        """Install a completed query's frame. Recomputes the plan's
        fingerprint first: a snapshot bumped while the query ran means
        ``frame`` describes data that no longer exists — skip."""
        if not self.result_enabled or frame is None:
            return False
        fp = plan_fingerprint(plan)
        if fp is None or ("result", fp.key) != key:
            return False
        try:
            nbytes = int(frame.memory_usage(index=True, deep=True).sum())
        except Exception as e:
            if is_oom_error(e):
                raise
            nbytes = 0  # exotic frame: admit unmetered rather than drop
        if nbytes > self.max_bytes:
            return False
        with self._lock:
            self._evict_locked(nbytes)
            self._results.put(ResultEntry(key, frame.copy(), nbytes,
                                          fp.reads))
        return True

    def note_follower(self) -> None:
        with self._lock:
            self._followers += 1

    # -- fragment tier ------------------------------------------------

    def graft_fragments(self, plan: pn.PlanNode
                        ) -> Tuple[pn.PlanNode,
                                   List[fragments.FragmentEntry],
                                   List[fragments.FragmentEntry]]:
        """Rewrite ``plan`` against the fragment registry. Returns the
        (possibly identical) plan, the PENDING entries this query
        became responsible for (the service aborts them at finalize if
        the run never published them), and the READY entries its serve
        leaves reference — each **pinned here, at graft time**, so
        LRU/TTL eviction cannot close a grafted entry's handles while
        the query waits in the admission queue (a serve leaf has no
        child to recompute from). The service releases the pins at
        finalize via :meth:`release_served`."""
        if not self.fragment_enabled:
            return plan, [], []
        pending: List[fragments.FragmentEntry] = []
        served: List[fragments.FragmentEntry] = []
        memo: dict = {}
        out = self._graft(plan, True, pending, served, memo)
        return out, pending, served

    def _graft(self, node, allow_capture, pending, served, memo):
        mk = (id(node), allow_capture)
        hit = memo.get(mk)
        if hit is None:
            hit = self._graft_inner(node, allow_capture, pending,
                                    served, memo)
            memo[mk] = hit
        return hit

    def _graft_inner(self, node, allow_capture, pending, served, memo):
        if isinstance(node, FRAGMENT_CANDIDATES):
            fp = plan_fingerprint(node)
            if fp is not None:
                key = ("fragment", fp.key)
                entry = self._fragment_lookup_or_register(
                    key, node, fp, allow_capture, served)
                if entry is not None and entry.state == fragments.READY:
                    return fragments.CachedFragmentNode(entry)
                if entry is not None:
                    # fresh PENDING entry owned by this query: capture.
                    # Children still graft (a READY inner fragment
                    # feeds the capture), but no nested captures — one
                    # materialization per path keeps the plan's memory
                    # footprint shaped like a single extra stage.
                    pending.append(entry)
                    inner = self._rebuild(node, False, pending, served,
                                          memo)
                    return fragments.CachedFragmentNode(entry,
                                                        child=inner)
                # PENDING in another query (don't block on someone
                # else's barrier, don't double-capture) or aborted and
                # not recapturable here: compile the plain subtree
        return self._rebuild(node, allow_capture, pending, served, memo)

    def _rebuild(self, node, allow_capture, pending, served, memo):
        kids = [self._graft(c, allow_capture, pending, served, memo)
                for c in node.children]
        if all(k is c for k, c in zip(kids, node.children)):
            return node
        return node.with_children(kids)

    def _fragment_lookup_or_register(self, key, node, fp,
                                     allow_capture, served):
        """READY entry (hit, pinned + recorded in ``served``), a NEW
        pending entry this caller must capture, or None
        (pending/aborted elsewhere, or capture not allowed here)."""
        now = time.perf_counter()
        with self._lock:
            entry = self._fragments.get(key)
            if entry is not None and entry.state == fragments.READY \
                    and self.ttl_s > 0 \
                    and now - entry.created_at > self.ttl_s:
                # expired: a miss either way, but NEVER close a pinned
                # entry's handles — a server may be mid-iteration and a
                # queued query's graft may reference it. Mark it stale;
                # the last unpin performs the eviction.
                if entry.pins == 0:
                    self._evict_fragment_locked(entry)
                else:
                    entry.stale = True
                entry = None
            if entry is not None:
                if entry.state == fragments.READY:
                    entry.hits += 1
                    # graft-time pin: held until the query finalizes
                    # (release_served), so eviction cannot invalidate
                    # the serve leaf this hit becomes
                    entry.pins += 1
                    entry.last_used = now
                    self._frag_hits += 1
                    served.append(entry)
                    return entry
                return None
            if not allow_capture:
                return None
            self._frag_misses += 1
            est = self._estimate_rows(node)
            entry = fragments.FragmentEntry(
                key, node, node.output_schema(), fp.reads, est, self)
            self._fragments[key] = entry
            return entry

    @staticmethod
    def _estimate_rows(node) -> Optional[int]:
        from spark_rapids_tpu.plan.optimizer import estimate_rows
        try:
            return estimate_rows(node)
        except Exception as e:
            if is_oom_error(e):
                raise
            return None  # estimate is advisory; capture proceeds

    def publish_fragment(self, entry: fragments.FragmentEntry) -> bool:
        """Promote a fully materialized entry to READY. Revalidates the
        subplan fingerprint against CURRENT snapshot versions and the
        registry mapping; any mismatch drops the entry (the capture
        degrades to streaming — a correctness no-op)."""
        parts = entry._parts or {}
        size = sum(h.device_memory_size()
                   for handles in parts.values() for h in handles)
        fp = plan_fingerprint(entry.subtree)
        ok = (fp is not None and ("fragment", fp.key) == entry.key
              and size <= self.max_bytes)
        with self._lock:
            if ok and entry.state == fragments.PENDING \
                    and self._fragments.get(entry.key) is entry:
                self._evict_locked(size)
                entry.bytes = size
                entry.state = fragments.READY
                entry.last_used = time.perf_counter()
                self._frag_bytes += size
                self._frag_published += 1
                return True
            if self._fragments.get(entry.key) is entry:
                self._fragments.pop(entry.key, None)
            entry.state = fragments.ABORTED
            self._frag_aborted += 1
            entry.close_parts()
            return False

    def fragment_aborted(self, entry: fragments.FragmentEntry,
                         oom: bool) -> None:
        """Capture failed (handles already closed by the caller)."""
        with self._lock:
            if self._fragments.get(entry.key) is entry:
                self._fragments.pop(entry.key, None)
            if entry.state == fragments.PENDING:
                entry.state = fragments.ABORTED
                self._frag_aborted += 1
                if oom:
                    self._oom_degraded += 1
            entry.close_parts()

    def abort_pending(self,
                      entries: List[fragments.FragmentEntry]) -> None:
        """Finalize sweep for a query's registered-but-unpublished
        entries (shed/failed/cancelled before capture ran). Removing
        the aborted mapping lets a future query retry the capture."""
        for entry in entries:
            with self._lock:
                if entry.state == fragments.PENDING:
                    entry.state = fragments.ABORTED
                    self._frag_aborted += 1
                    entry.close_parts()
                if entry.state == fragments.ABORTED and \
                        self._fragments.get(entry.key) is entry:
                    self._fragments.pop(entry.key, None)

    def fragment_pin(self, entry: fragments.FragmentEntry) -> None:
        with self._lock:
            entry.pins += 1
            entry.last_used = time.perf_counter()

    def fragment_pin_if_ready(self, entry: fragments.FragmentEntry
                              ) -> bool:
        """Pin only if the entry is still servable — the capture path
        uses this to close the publish->serve race (a just-published
        entry is evictable until someone pins it)."""
        with self._lock:
            if entry.state != fragments.READY or entry._parts is None:
                return False
            entry.pins += 1
            entry.last_used = time.perf_counter()
            return True

    def fragment_unpin(self, entry: fragments.FragmentEntry) -> None:
        with self._lock:
            entry.pins = max(entry.pins - 1, 0)
            if entry.pins == 0 and entry.stale \
                    and entry.state == fragments.READY:
                # deferred TTL eviction: expiry observed while pinned
                # could not close the handles then — do it now that the
                # last server/graft reference is gone
                self._evict_fragment_locked(entry)

    def release_served(self,
                       entries: List[fragments.FragmentEntry]) -> None:
        """Drop the graft-time pins a query's serve leaves hold (taken
        in _fragment_lookup_or_register). Called exactly once per
        graft_fragments, at query finalize or on a failed submit."""
        for entry in entries:
            self.fragment_unpin(entry)

    # -- shared budget -------------------------------------------------

    def _evict_locked(self, need: int) -> None:
        """LRU across BOTH tiers until ``need`` more bytes fit. Pinned
        or pending fragments are not candidates; if nothing is
        evictable the new entry is admitted anyway (the spill tiers
        absorb transient overshoot — maxBytes bounds the steady state,
        not a hard ceiling mid-publish)."""
        while self._results.bytes + self._frag_bytes + need \
                > self.max_bytes:
            r = self._results.coldest()
            f = None
            for e in self._fragments.values():
                if e.state == fragments.READY and e.pins == 0:
                    if f is None or e.last_used < f.last_used:
                        f = e
            if r is not None and (f is None
                                  or r.last_used <= f.last_used):
                self._results.pop(r.key)
                self._results.evicted += 1
            elif f is not None:
                self._evict_fragment_locked(f)
            else:
                break

    def _evict_fragment_locked(self,
                               entry: fragments.FragmentEntry) -> None:
        if self._fragments.get(entry.key) is entry:
            self._fragments.pop(entry.key, None)
        self._frag_bytes -= entry.bytes
        entry.state = fragments.ABORTED
        entry.close_parts()
        self._frag_evicted += 1

    def device_resident_bytes(self) -> int:
        """Bytes of READY fragment batches currently on the DEVICE
        tier — admission charges these against the device budget (see
        AdmissionController.extra_bytes_fn) so cached data and inflight
        queries share one accounting. Spilled handles cost nothing."""
        from spark_rapids_tpu.memory.catalog import (StorageTier,
                                                     get_catalog)
        cat = get_catalog()
        total = 0
        with self._lock:
            for entry in self._fragments.values():
                if entry.state != fragments.READY:
                    continue
                for handles in (entry._parts or {}).values():
                    for h in handles:
                        try:
                            if cat.tier_of(h.buffer_id) == \
                                    StorageTier.DEVICE:
                                total += cat.size_of(h.buffer_id)
                        except KeyError:
                            continue
        return total

    # -- observability / lifecycle ------------------------------------

    def stats(self) -> dict:
        with self._lock:
            pending = sum(1 for e in self._fragments.values()
                          if e.state == fragments.PENDING)
            return {
                "enabled": self.enabled,
                "result": {
                    "hits": self._results.hits,
                    "misses": self._results.misses,
                    "entries": len(self._results),
                    "bytes": self._results.bytes,
                    "evicted": self._results.evicted,
                    "single_flight_followers": self._followers,
                },
                "fragment": {
                    "hits": self._frag_hits,
                    "misses": self._frag_misses,
                    "published": self._frag_published,
                    "aborted": self._frag_aborted,
                    "oom_degraded": self._oom_degraded,
                    "evicted": self._frag_evicted,
                    "entries": len(self._fragments),
                    "bytes": self._frag_bytes,
                    "pending": pending,
                },
            }

    def close(self) -> None:
        """Release every entry (service shutdown, workers joined)."""
        with self._lock:
            for entry in list(self._fragments.values()):
                entry.state = fragments.ABORTED
                entry.close_parts()
            self._fragments.clear()
            self._frag_bytes = 0
            self._results.clear()
