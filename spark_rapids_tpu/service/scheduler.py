"""Stage scheduler: interleave admitted queries on the dispatch path.

One query's long stage must not starve the rest: the engine executes a
query as per-stage compiled programs (plan/optimizer.cut_stages labels
them; each batch pull dispatches one stage program post-fusion), so the
natural schedulable unit is ONE batch pull — a stage slice. Workers
pull slices from a round-robin ready deque: after each slice the query
goes to the back, cancellation and deadline are checked between slices
(= between stage programs), and the slice brackets set the thread's
buffer-owner tag (memory/catalog) and dispatch query tag
(utils/dispatch) so spill demotion and per-query telemetry attribute
correctly. Device entry within a slice passes through the TpuSemaphore
exactly as in single-query mode — the execs acquire at device touch and
release per batch. The scheduler deliberately does NOT hold a permit
across a slice: a slice may materialize an exchange whose internal task
threads take permits of their own, and a slice-long hold would deadlock
against them (the engine-wide invariant is that nobody holds a permit
while waiting on other threads). Admission consults permit availability
instead (admission.py).

While a query sits in the ready deque (stalled: admitted, not on a
worker) its catalog buffers carry a large negative spill bias — under
memory pressure the catalog evicts the stalled tenant's batches first
and the running tenant keeps its working set (SpillPriorities aging,
applied cross-query).
"""
from __future__ import annotations

import threading
from collections import deque
from typing import List, Optional

from spark_rapids_tpu.memory import semaphore as sem
from spark_rapids_tpu.memory.catalog import get_catalog, set_buffer_owner
from spark_rapids_tpu.service.batching import microbatch as _mb
from spark_rapids_tpu.service.types import (DeadlineExceeded, Query,
                                            QueryState)
from spark_rapids_tpu.utils import dispatch as _disp

#: spill-priority bias applied to a stalled query's buffers: larger in
#: magnitude than every SpillPriorities band (tops out near 1 << 62),
#: so a stalled tenant's batches — even its ACTIVE on-deck ones — are
#: always preferred victims over any running query's buffers
STALLED_SPILL_BIAS = -(1 << 63)

#: eager-spill bias an out-of-core query carries WHILE RUNNING: its
#: active working batches (ACTIVE_* bands, ~1 << 62) drop below every
#: other query's actives but stay above bystanders' passive bands —
#: memory pressure evicts the whale's staged data into its spill chain
#: first, never a well-behaved tenant's working set
OUT_OF_CORE_SPILL_BIAS = -(1 << 61)


class _Interrupted(BaseException):
    """Internal slice unwind (cancel/deadline); never escapes the
    scheduler. BaseException so a careless ``except Exception`` inside
    an exec iterator cannot swallow a cancellation."""

    def __init__(self, state: QueryState,
                 error: Optional[BaseException] = None):
        self.state = state
        self.error = error


class StageScheduler:
    """Worker pool driving stage slices. All shared state is guarded by
    the service lock (``service._lock``); slice execution itself runs
    unlocked."""

    def __init__(self, service, n_workers: int):
        self._service = service
        self._n_workers = max(n_workers, 1)
        self._ready: deque = deque()
        self._workers: List[threading.Thread] = []
        self._shutdown = False

    # -- service-side hooks (called under the service lock) ---------------

    def enqueue(self, q: Query) -> None:
        self._ready.append(q)
        self._service._work_cv.notify_all()
        self._ensure_workers()

    def ready_count(self) -> int:
        return len(self._ready)

    def drop(self, q: Query) -> bool:
        """Remove a query from the ready deque (cancel while stalled)."""
        try:
            self._ready.remove(q)
            return True
        except ValueError:
            return False

    def stop(self) -> None:
        self._shutdown = True
        self._service._work_cv.notify_all()

    def join(self, timeout: float = 5.0) -> None:
        for w in self._workers:
            w.join(timeout)

    def _ensure_workers(self) -> None:
        if self._workers or self._shutdown:
            return
        for i in range(self._n_workers):
            t = threading.Thread(target=self._worker_loop,
                                 name=f"svc-worker-{i}", daemon=True)
            self._workers.append(t)
            t.start()

    # -- worker side ------------------------------------------------------

    def _worker_loop(self) -> None:
        svc = self._service
        while True:
            with svc._lock:
                while not self._ready and not self._shutdown:
                    svc._work_cv.wait()
                if self._shutdown:
                    return
                q = self._ready.popleft()
                if q.terminal:
                    continue
                q.state = QueryState.RUNNING
                if q.started_at is None:
                    import time

                    q.started_at = time.perf_counter()
            self._run_slice(q)

    def _run_slice(self, q: Query) -> None:
        """Advance one stage slice (one batch pull) of ``q``, then hand
        it back to the ready deque — or finalize it."""
        catalog = get_catalog()
        # back on the device: restore the query's RUNNING spill bias —
        # 0 normally, the eager-spill band for out-of-core queries
        # (skipped unless the last yield demoted or this is an OOC
        # query's first slice — the common single-query case never
        # touches the catalog heap)
        base_bias = OUT_OF_CORE_SPILL_BIAS if q.out_of_core else 0
        if q.spill_demoted or (q.out_of_core and q.slices_done == 0):
            catalog.set_owner_bias(q.owner_tag, base_bias)
            q.spill_demoted = False
        done = False
        outcome: Optional[_Interrupted] = None
        prev_owner = set_buffer_owner(q.owner_tag)
        qtok = _disp.enter_query(q.query_id)
        # micro-batching context: stage programs dispatched inside this
        # slice may coalesce with other queries' (service/batching).
        # ``multi`` snapshots whether a peer even exists — a solo query
        # must not pay the hold window waiting for peers that cannot
        # arrive (len() read is advisory; worst case one slice holds
        # a window for a peer that just finished)
        svc = self._service
        multi = len(svc.admission.inflight) > 1
        btok = _mb.enter_slice(getattr(svc, "batcher", None),
                               q.query_id, multi)
        try:
            self._check_interrupt(q)
            done = self._advance(q)
            q.slices_done += 1
        except _Interrupted as stop:
            outcome = stop
        except BaseException as e:  # exec failure -> query failure
            outcome = _Interrupted(QueryState.FAILED, e)
        finally:
            # execs acquire the (thread-keyed) permit inside their
            # iterators and hold it across yields; a suspended slice
            # must not pin this worker's permit while the query waits
            # in the ready deque — release whatever this thread holds.
            # Cross-thread iterator resumption makes the per-batch
            # semaphore accounting advisory across slice boundaries
            # (never a leak, never a deadlock: releases only ever free
            # permits); the strict cross-query bound is admission's.
            sem.get().release_if_necessary()
            _mb.exit_slice(btok)
            _disp.exit_query(qtok)
            set_buffer_owner(prev_owner)

        requeued = False
        if outcome is not None:
            svc._finalize(q, outcome.state, outcome.error)
        elif done:
            svc._finalize(q, QueryState.DONE)
        else:
            with svc._lock:
                if not q.terminal:   # else: cancel raced the slice
                    # cooperative yield: back of the deque, another
                    # query's stage goes next; stalled buffers become
                    # spill victims
                    q.state = QueryState.ADMITTED
                    if len(svc.admission.inflight) > 1:
                        # another admitted query can use the memory:
                        # make the stalled tenant the preferred spill
                        # victim. Solo queries skip the demote/restore
                        # churn (2 x n_buffers heap updates per slice
                        # that could never benefit anyone).
                        catalog.set_owner_bias(q.owner_tag,
                                               STALLED_SPILL_BIAS)
                        q.spill_demoted = True
                    self._ready.append(q)
                    # permits freed during the slice may unblock
                    # admission (the availability gate in
                    # admission._fits): pump here, not only at
                    # submit/finalize, or a queued query could wait a
                    # whole query's latency instead of a slice's
                    svc._pump_locked()
                    svc._work_cv.notify_all()
                    requeued = True
        if not requeued and q.terminal:
            # an outside finalize (shutdown's post-join pass, a cancel
            # racing the finish) may have swept the owner tag while
            # this slice was still registering buffers under it; the
            # slice is off the device now, so a re-sweep closes the
            # leak (idempotent when nothing raced). Resolve the catalog
            # FRESH: a runtime teardown racing this slice swaps the
            # global catalog, and late registrations landed in the new
            # one — the instance captured at slice start is stale.
            get_catalog().remove_owner(q.owner_tag)
            # same race for telemetry: dispatches this slice issued
            # after the finalize popped the query's count re-created
            # the _query_counts entry; drop it or it lives forever
            _disp.pop_query_count(q.query_id)
            _disp.pop_query_coalesced(q.query_id)

    def _check_interrupt(self, q: Query) -> None:
        if q.cancel_requested:
            raise _Interrupted(QueryState.CANCELLED)
        if q.deadline_expired():
            raise _Interrupted(
                QueryState.FAILED,
                DeadlineExceeded(
                    f"query {q.query_id} exceeded its "
                    f"{q.deadline_s:.3f}s deadline"))

    def _advance(self, q: Query) -> bool:
        """Pull the next batch of the current partition; True when the
        whole query has drained. The first pull of a partition runs any
        upstream stage materializations (exchange/broadcast builds) —
        that whole stage is one slice, which is exactly the cooperative
        granularity: yields happen at stage boundaries, never inside a
        compiled program."""
        if q.num_partitions is None:
            # first slice: resolving the count may materialize adaptive
            # exchanges — that is exactly the work a slice is for
            q.num_partitions = q.exec.num_partitions
        while q._cursor < q.num_partitions:
            p = q._cursor
            it = q._iters.get(p)
            if it is None:
                it = q._iters[p] = iter(q.exec.execute(p))
            try:
                batch = next(it)
            except StopIteration:
                q._iters.pop(p, None)
                q._cursor += 1
                continue
            frame = batch.to_pandas(q.exec.schema)
            if len(frame):
                q.frames.setdefault(p, []).append(frame)
            return False
        return True
