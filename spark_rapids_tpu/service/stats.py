"""Service observability: the ServiceStats snapshot.

The multi-tenant wins this surfaces: queue depth + shed counts show
backpressure working, queue/run-time histograms (now with p50/p95/p99)
show fairness AND feed the sustained-QPS SLO harness, the
compile-cache hit rate shows tenants sharing compiled programs — a
repeated plan shape admitted for tenant B reuses tenant A's XLA
executables (utils/progcache), which is the dominant cost behind the
remote-compile tunnel — and the batching block shows the micro-batcher
turning that sharing into coalesced physical launches
(service/batching).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

#: histogram bucket upper bounds in seconds (last bucket is +inf)
HIST_BUCKETS = (0.001, 0.01, 0.1, 1.0, 10.0, 100.0)
HIST_LABELS = tuple(f"le_{b:g}s" for b in HIST_BUCKETS) + ("inf",)


class Histogram:
    """Fixed log-bucket latency histogram plus a bounded sample set for
    percentiles (enough for a snapshot; the service is not a metrics
    pipeline).

    Percentiles need more resolution than 7 log buckets, so raw samples
    are retained up to ``SAMPLE_CAP`` and then deterministically
    THINNED: the set halves (every other sample) and the keep stride
    doubles, so memory stays bounded while the retained set remains an
    unbiased-in-time 1-in-stride systematic sample. Exact until the
    cap; an approximation with bounded memory beyond it."""

    SAMPLE_CAP = 8192

    def __init__(self):
        self.counts = [0] * (len(HIST_BUCKETS) + 1)
        self.total = 0
        self.sum_s = 0.0
        self._samples: List[float] = []
        self._stride = 1
        self._skip = 0

    def add(self, seconds: float) -> None:
        for i, b in enumerate(HIST_BUCKETS):
            if seconds <= b:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.total += 1
        self.sum_s += seconds
        if self._skip > 0:
            self._skip -= 1
            return
        self._samples.append(seconds)
        self._skip = self._stride - 1
        if len(self._samples) >= self.SAMPLE_CAP:
            self._samples = self._samples[::2]
            self._stride *= 2

    def percentile(self, q: float) -> float:
        """Nearest-rank percentile over the retained samples
        (q in [0, 100]) — one definition for the whole serving layer
        (service/batching/slo), so harness and histogram numbers can
        never diverge."""
        from spark_rapids_tpu.service.batching.slo import percentile

        return percentile(self._samples, q)

    def snapshot(self) -> dict:
        return {
            "buckets": dict(zip(HIST_LABELS, self.counts)),
            "count": self.total,
            "mean_s": round(self.sum_s / self.total, 6)
            if self.total else 0.0,
            "p50_s": round(self.percentile(50), 6),
            "p95_s": round(self.percentile(95), 6),
            "p99_s": round(self.percentile(99), 6),
        }


@dataclasses.dataclass
class ServiceStats:
    """Point-in-time service snapshot; ``to_dict()`` is what the
    benchmark runner embeds in its JSON."""

    queue_depth: int
    running: int
    admitted_inflight: int
    inflight_bytes: int
    budget_bytes: Optional[int]
    counters: Dict[str, int]           # admitted/shed/done/failed/...
    queue_time_hist: dict
    run_time_hist: dict
    per_query: List[dict]
    progcache: dict
    semaphore: dict
    #: OOM-retry ladder accounting (memory/retry.stats()): totals +
    #: per-call-site retries/splits/bytes-spilled/time-blocked
    retry: dict = dataclasses.field(default_factory=dict)
    #: micro-batcher effectiveness (service/batching): physical
    #: launches, coalesced launches/participants, mean group size
    batching: dict = dataclasses.field(default_factory=dict)
    #: semantic result & fragment cache effectiveness (service/cache):
    #: per-tier hits/misses/bytes, single-flight followers, publishes,
    #: OOM-degraded captures, evictions
    cache: dict = dataclasses.field(default_factory=dict)
    #: streaming ingestion & standing queries (service/streaming):
    #: appends/folds/late-row counters, live standing-query registry,
    #: state bytes (device-resident share), watermark lag
    streaming: dict = dataclasses.field(default_factory=dict)
    #: lineage fault recovery (runtime/recovery.snapshot()): reduce-side
    #: fetch failures, map tasks re-run, workers respawned, executor
    #: slots blacklisted, stage retries spent, SPMD degrades, hosts
    #: added/removed through elastic membership — a query that survived
    #: a worker death shows up here, never silently
    recovery: dict = dataclasses.field(default_factory=dict)
    #: queue-pressure autoscaler (service/autoscaler): scale-ups fired,
    #: thresholds, last reason/executor — pairs with counters.scale_ups
    autoscaler: dict = dataclasses.field(default_factory=dict)

    @property
    def progcache_hit_rate(self) -> float:
        hits = self.progcache.get("hits", 0)
        misses = self.progcache.get("misses", 0)
        return hits / (hits + misses) if hits + misses else 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["progcache"]["hit_rate"] = round(self.progcache_hit_rate, 4)
        # the SLO headline numbers, hoisted so harnesses need not dig
        # through the histogram blocks
        d["latency"] = {
            "queue_p99_s": self.queue_time_hist.get("p99_s", 0.0),
            "run_p99_s": self.run_time_hist.get("p99_s", 0.0),
            "queue_p50_s": self.queue_time_hist.get("p50_s", 0.0),
            "run_p50_s": self.run_time_hist.get("p50_s", 0.0),
        }
        return d
