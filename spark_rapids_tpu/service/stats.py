"""Service observability: the ServiceStats snapshot.

The multi-tenant wins this surfaces: queue depth + shed counts show
backpressure working, queue/run-time histograms show fairness, and the
compile-cache hit rate shows tenants sharing compiled programs — a
repeated plan shape admitted for tenant B reuses tenant A's XLA
executables (utils/progcache), which is the dominant cost behind the
remote-compile tunnel.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

#: histogram bucket upper bounds in seconds (last bucket is +inf)
HIST_BUCKETS = (0.001, 0.01, 0.1, 1.0, 10.0, 100.0)
HIST_LABELS = tuple(f"le_{b:g}s" for b in HIST_BUCKETS) + ("inf",)


class Histogram:
    """Fixed log-bucket latency histogram (enough for a snapshot; the
    service is not a metrics pipeline)."""

    def __init__(self):
        self.counts = [0] * (len(HIST_BUCKETS) + 1)
        self.total = 0
        self.sum_s = 0.0

    def add(self, seconds: float) -> None:
        for i, b in enumerate(HIST_BUCKETS):
            if seconds <= b:
                self.counts[i] += 1
                break
        else:
            self.counts[-1] += 1
        self.total += 1
        self.sum_s += seconds

    def snapshot(self) -> dict:
        return {
            "buckets": dict(zip(HIST_LABELS, self.counts)),
            "count": self.total,
            "mean_s": round(self.sum_s / self.total, 6)
            if self.total else 0.0,
        }


@dataclasses.dataclass
class ServiceStats:
    """Point-in-time service snapshot; ``to_dict()`` is what the
    benchmark runner embeds in its JSON."""

    queue_depth: int
    running: int
    admitted_inflight: int
    inflight_bytes: int
    budget_bytes: Optional[int]
    counters: Dict[str, int]           # admitted/shed/done/failed/...
    queue_time_hist: dict
    run_time_hist: dict
    per_query: List[dict]
    progcache: dict
    semaphore: dict
    #: OOM-retry ladder accounting (memory/retry.stats()): totals +
    #: per-call-site retries/splits/bytes-spilled/time-blocked
    retry: dict = dataclasses.field(default_factory=dict)

    @property
    def progcache_hit_rate(self) -> float:
        hits = self.progcache.get("hits", 0)
        misses = self.progcache.get("misses", 0)
        return hits / (hits + misses) if hits + misses else 0.0

    def to_dict(self) -> dict:
        d = dataclasses.asdict(self)
        d["progcache"]["hit_rate"] = round(self.progcache_hit_rate, 4)
        return d
