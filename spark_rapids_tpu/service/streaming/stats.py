"""Process-global streaming counters.

Per-service numbers (live standing-query count, per-query watermark
lag) come from StreamingManager.stats(); THESE counters are process
totals in the style of memory/retry's, so the benchmark runner can
bracket any run with ``snapshot()``/``delta()`` and emit a
``streaming`` block next to its ``memory`` block without holding a
service reference.
"""
from __future__ import annotations

from typing import Dict

from spark_rapids_tpu.utils import lockorder

_lock = lockorder.make_lock("service.streaming.stats")

_KEYS = ("standing_registered", "standing_cancelled", "standing_failed",
         "appends", "rows_appended", "folds", "rows_folded",
         "late_rows_remerged", "late_rows_dropped", "fold_dispatches",
         "emits",
         # durability layer (PR 19): WAL records persisted, checkpoint
         # files committed (final_checkpoints = the overflow/suspend
         # subset written on a terminal transition), restart recoveries
         # (checkpoint restored), WAL replays (table deltas rebuilt
         # from the log), and torn/corrupt artifacts rejected on CRC
         "wal_records", "wal_replays", "checkpoints_written",
         "final_checkpoints", "recoveries", "torn_rejected",
         "standing_suspended")

_counters: Dict[str, int] = {k: 0 for k in _KEYS}


def bump(key: str, n: int = 1) -> None:
    with _lock:
        _counters[key] += n


def snapshot() -> Dict[str, int]:
    with _lock:
        return dict(_counters)


def delta(before: Dict[str, int]) -> Dict[str, int]:
    now = snapshot()
    return {k: now[k] - before.get(k, 0) for k in _KEYS}


def reset() -> None:
    """Test isolation hook."""
    with _lock:
        for k in _KEYS:
            _counters[k] = 0
